"""Serve a TransformerLM forward behind the batching executor.

Demonstrates the full serving path (`heat_tpu.serve`): a dp-sharded
transformer forward wrapped by :func:`heat_tpu.serve.serve_transformer`,
warmed over the shape-bucket ladder, then hit with concurrent mixed-size
requests from client threads — ending with the metrics snapshot
(latency percentiles, batch occupancy, program-cache counters: zero
steady-state misses) and ``ht.runtime_stats()``.

Usage (4 virtual devices):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=4 \
  python serve_transformer.py --requests 40
"""

import argparse
import json
import os
import threading
import time

import numpy as np

try:
    import heat_tpu as ht
except ModuleNotFoundError:  # running from a source checkout without install
    import sys

    sys.path.insert(0, os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..")))
    import heat_tpu as ht


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--requests", type=int, default=40)
    p.add_argument("--threads", type=int, default=4)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--tenants", type=int, default=1, choices=(1, 2),
                   help="2 = register an 'interactive' (priority 10, "
                        "SLO) and a 'batch' (priority 0, queue quota) "
                        "tenant over the one executor and print "
                        "per-tenant runtime_stats")
    args = p.parse_args()
    if os.environ.get("HEAT_TPU_EXAMPLE_SMOKE"):  # CI ladder smoke: shrink
        args.d_model, args.layers, args.seq_len = 32, 1, 16
        args.requests = 12

    import jax

    from heat_tpu.nn.transformer import TransformerLM, TransformerLMConfig
    from heat_tpu.serve import metrics as serve_metrics
    from heat_tpu.serve import serve_transformer

    n_dev = len(jax.devices())
    grid = ht.MeshGrid((n_dev, 1, 1, 1), ("dp", "pp", "tp", "sp"))
    cfg = TransformerLMConfig(vocab=args.vocab, d_model=args.d_model,
                              n_heads=args.heads, n_layers=args.layers)
    model = TransformerLM(grid, cfg)
    params = model.init(0)
    print(f"model d={args.d_model} L={args.layers} over dp={n_dev}; "
          f"serving seq_len={args.seq_len}")

    ex = serve_transformer(model, params, seq_len=args.seq_len)
    ex.config.max_batch = args.max_batch
    ex.config.max_wait_ms = args.max_wait_ms
    tenant_of = None
    if args.tenants == 2:
        # two tenants over ONE executor: the interactive tenant outranks
        # the batch tenant in the queue and inherits an SLO deadline; the
        # batch tenant is quota-bounded so it can never fill the shared
        # queue bound (doc/serving.md "Multi-tenant admission")
        ex.register_tenant("interactive", priority=10, slo_ms=60e3)
        ex.register_tenant("batch", priority=0,
                           max_queue=ex.config.queue_limit * 3 // 4)

        def tenant_of(i):
            return "interactive" if i % 3 == 0 else "batch"

    rows_mix = (1, 2, 3, 1, 2, 1)
    t0 = time.perf_counter()
    # coalesced totals reach max_batch x max(rows_mix): warm every bucket
    # the policy can produce up to that total (NOT a hardcoded row set —
    # --max-batch changes the reachable ladder)
    ex.warmup((args.seq_len,), np.int32,
              rows=ex.config.bucket_rows.ladder(
                  args.max_batch * max(rows_mix)))
    print(f"warmup ({ex.program_cache.stats()['compiles']} programs) "
          f"in {time.perf_counter() - t0:.1f}s")
    misses0 = ex.program_cache.stats()["misses"]
    # warmup latencies are compile times — restart the window so the
    # percentiles below describe traffic, not warmup
    serve_metrics.DEFAULT.reset()

    rng = np.random.default_rng(0)
    reqs = [rng.integers(0, args.vocab,
                         (rows_mix[i % len(rows_mix)], args.seq_len)
                         ).astype(np.int32)
            for i in range(args.requests)]
    done = []

    def client(t):
        idx = list(range(t, len(reqs), args.threads))
        futs = [ex.submit(reqs[i],
                          tenant=tenant_of(i) if tenant_of else None)
                for i in idx]
        done.extend(np.asarray(f.result(600)).shape for f in futs)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(t,))
               for t in range(args.threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0
    ex.close()

    snap = ex.stats()
    assert len(done) == len(reqs)
    assert ex.program_cache.stats()["misses"] == misses0, "recompiled!"
    print(f"{len(reqs)} requests in {wall * 1e3:.0f} ms "
          f"({len(reqs) / wall:.1f} req/s), "
          f"p50={snap['latency_ms']['p50']:.1f} ms "
          f"p99={snap['latency_ms']['p99']:.1f} ms, "
          f"occupancy={snap['batch_occupancy']['mean']:.2f}, "
          f"0 steady-state recompiles")
    print("runtime_stats:", json.dumps({
        "serve": {k: ht.runtime_stats()["serve"][k]
                  for k in ("requests", "batches", "shed")},
        "resharding": ht.runtime_stats()["resharding"],
    }))
    if args.tenants == 2:
        # the per-tenant observability surface the tentpole added:
        # admission counters + breaker state per tenant, one JSON line
        for name, row in sorted(snap["tenants"].items()):
            print(f"tenant {name}: " + json.dumps(
                {k: row[k] for k in ("priority", "admitted", "completed",
                                     "shed", "breaker")}))
        assert snap["tenants"]["interactive"]["completed"] > 0
        assert snap["tenants"]["batch"]["completed"] > 0


if __name__ == "__main__":
    main()
