"""Data-parallel MLP training (reference ``examples/nn/mnist.py`` pattern).

The reference launches with ``mpirun -np N``; here the same script runs on
any mesh — the batch is sharded over the devices and gradients are psum'd by
GSPMD inside the fused train step. Uses synthetic data unless MNIST IDX
files are available under ``--data-root``.
"""

import argparse

import numpy as np

try:
    import heat_tpu as ht
except ModuleNotFoundError:  # running from a source checkout without install
    import os, sys

    sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
    import heat_tpu as ht


def get_data(root):
    if root:
        ds = ht.utils.data.MNISTDataset(root, train=True, split=0)
        return ds, 784, 10
    rng = np.random.default_rng(0)
    n, d, k = 4096, 64, 10
    w = rng.normal(size=(d, k))
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.int32)
    ds = ht.utils.data.Dataset([ht.array(X, split=0), ht.array(y, split=0)])
    return ds, d, k


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data-root", type=str, default=None)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args()

    import flax.linen as fnn

    dataset, d_in, k = get_data(args.data_root)

    class Net(fnn.Module):
        @fnn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            x = fnn.Dense(128)(x)
            x = fnn.relu(x)
            x = fnn.Dense(64)(x)
            x = fnn.relu(x)
            return fnn.Dense(k)(x)

    optimizer = ht.optim.DataParallelOptimizer(ht.optim.SGD(lr=args.lr))
    net = ht.nn.DataParallel(Net(), optimizer=optimizer)
    loader = ht.utils.data.DataLoader(dataset=dataset, batch_size=args.batch_size)

    # net.step runs the packed-collective fused train step: forward,
    # backward, ONE flattened gradient all-reduce and the optimizer update
    # in a single donated executable (HEAT_TPU_FUSION_STEP=0 restores the
    # historic GSPMD-placed step — same math, per-parameter collectives)
    for epoch in range(args.epochs):
        losses = []
        for bx, by in loader:
            losses.append(net.step(bx, by))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f}")
    fstats = ht.runtime_stats()["op_engine"]["fusion"]
    print(f"fusion step flushes: {fstats['step_flushes']} "
          f"(packed path {'on' if ht.fusion.step_enabled() else 'off'})")


if __name__ == "__main__":
    main()
