"""Data-parallel CNN training (the reference's vision path is the torch.nn
passthrough + ``DataParallel``; here it is flax.linen via ``ht.nn`` + the
mesh-sharded batch, reference ``examples/nn/mnist.py`` shape).

Synthetic 28x28 images stand in for MNIST (offline environment); swap in
``ht.utils.data.MNISTDataset`` for the real files.

Usage: python cnn_train.py [--epochs 2 --batch 256]
"""

import argparse

import numpy as np

try:
    import heat_tpu as ht
except ModuleNotFoundError:  # running from a source checkout without install
    import os, sys

    sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
    import heat_tpu as ht


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=1e-3)
    args = p.parse_args()

    import flax.linen as fnn

    class ConvNet(fnn.Module):
        @fnn.compact
        def __call__(self, x):            # (B, 28, 28, 1)
            x = fnn.Conv(16, (3, 3))(x)
            x = fnn.relu(x)
            x = fnn.avg_pool(x, (2, 2), strides=(2, 2))
            x = fnn.Conv(32, (3, 3))(x)
            x = fnn.relu(x)
            x = fnn.avg_pool(x, (2, 2), strides=(2, 2))
            x = x.reshape((x.shape[0], -1))
            x = fnn.relu(fnn.Dense(64)(x))
            return fnn.Dense(10)(x)

    # synthetic digits: class = dominant quadrant pattern, learnable
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 10, args.n).astype(np.int32)
    base = rng.normal(0.0, 0.3, (args.n, 28, 28, 1)).astype(np.float32)
    for c in range(10):
        r, col = divmod(c, 4)
        base[labels == c, 3 + 5 * r : 8 + 5 * r, 3 + 6 * col : 9 + 6 * col, :] += 1.5
    X = ht.array(base, split=0)          # batch sharded over the mesh
    y = ht.array(labels, split=0)

    opt = ht.optim.DataParallelOptimizer(ht.optim.Adam(lr=args.lr))
    net = ht.nn.DataParallel(ConvNet(), optimizer=opt)

    loader = ht.utils.data.DataLoader(data=[X, y], batch_size=args.batch)
    from heat_tpu.utils import metrics

    for epoch in range(args.epochs):
        metrics.reset()
        for bx, by in loader:
            with metrics.timer("step") as t:
                loss = net.step(bx, by)
                t.sync(loss)
            metrics.observe("loss", loss)
        snap = metrics.to_dict()["series"]
        if "loss" not in snap:
            raise SystemExit(
                f"no batches ran: --batch ({args.batch}) exceeds --n ({args.n})")
        print(f"epoch {epoch}: loss {snap['loss']['mean']:.4f} "
              f"({snap['step']['mean'] * 1e3:.1f} ms/step)")


if __name__ == "__main__":
    main()
