"""Combined-parallelism GPT training: dp x pp x tp x sp (x ep) in one step.

The flagship demonstration of the full parallelism grid
(`heat_tpu.nn.transformer.TransformerLM`): batch over dp, pipeline stages
over pp, Megatron head/feature shards over tp, ring-attention sequence
shards over sp, and (with ``--moe-experts``) Switch-MoE experts over the dp
axis — one shard_map train step, exact gradients (verified against a dense
reference in ``tests/test_transformer.py``).

The reference framework composes exactly one split axis at a time
(SURVEY.md §2.6); this is the TPU-native superset.

Usage (8 virtual devices):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python gpt_parallel.py --grid 1,2,2,2 --steps 20
  python gpt_parallel.py --grid 2,2,2,1 --moe-experts 4   # with ep
  python gpt_parallel.py --tiers dcn,ici --steps 20  # simulated 2-host
      # (2, n/2) ("dcn", "ici") tier grid: the packed train step's
      # gradient all-reduce decomposes as reduce-scatter(ici) ->
      # all-reduce(dcn) -> all-gather(ici), HEAT_TPU_HIER
  python gpt_parallel.py --serve --steps 5   # continuous-batching decode:
      # 2 tenants' mixed-length generation through the slot-based
      # DecodeEngine (heat_tpu.serve.decode), per-tenant tokens/s printed
"""

import argparse
import os

import numpy as np

try:
    import heat_tpu as ht
except ModuleNotFoundError:  # running from a source checkout without install
    import os, sys

    sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
    import heat_tpu as ht


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--grid", default="auto",
                   help="dp,pp,tp,sp sizes (product = device count); "
                        "'auto' picks 1,2,2,2 on vma-tracking jax and the "
                        "dp-only packed-step grid on older jax (whose "
                        "check_vma train path cannot trace)")
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--n-micro", type=int, default=2)
    p.add_argument("--moe-experts", type=int, default=0)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--tiers", default=os.environ.get(
        "HEAT_TPU_MESH_TIERS", ""),
        help="declare mesh tiers (default: $HEAT_TPU_MESH_TIERS): "
             "'dcn,ici' (or 'D,I' sizes) runs the dp grid 2-D — a "
             "simulated 2-host (2, n/2) ('dcn','ici') split on CPU — "
             "so the packed step's gradient all-reduce decomposes "
             "hierarchically (RS over ici, AR over dcn, AG over ici)")
    p.add_argument("--serve", action="store_true",
                   help="after training, serve generation through the "
                        "continuous-batching DecodeEngine: 2 tenants "
                        "(interactive prio 10 / batch prio 0), mixed "
                        "prompt/output lengths, per-tenant tokens/s + "
                        "slot occupancy printed")
    p.add_argument("--serve-requests", type=int, default=24)
    args = p.parse_args()

    import optax

    from heat_tpu.core import fusion
    from heat_tpu.nn.transformer import TransformerLM, TransformerLMConfig

    tiers = None
    if args.tiers:
        fusion.set_mesh_tiers(args.tiers)
        tiers = fusion.mesh_tiers()

    if tiers is not None and args.grid != "auto":
        # the tier grid is dp-only by construction — silently dropping a
        # requested pp/tp/sp layout would misreport what ran
        raise SystemExit(
            f"--tiers {args.tiers} builds its own (dcn, dp) grid and "
            f"cannot honor --grid {args.grid}; pass one or the other")
    if tiers is not None:
        import jax

        n = len(jax.devices())
        if isinstance(tiers[0], int):
            d, i = tiers
            if d * i != n:
                raise SystemExit(
                    f"--tiers {args.tiers}: {d}x{i} != {n} devices")
        else:
            # name form ('dcn,ici'): simulate 2 hosts on this mesh
            d, i = 2, n // 2
            if n < 4 or n % 2:
                raise SystemExit(
                    f"--tiers {args.tiers}: needs an even mesh of >= 4 "
                    f"devices to simulate a (2, n/2) pod, got {n}")
        # tiered dp-only grid: dcn x dp both shard the batch, the
        # packed-collective train step (PR 7) decomposes hierarchically
        shape = (d, i, 1, 1, 1)
        grid = ht.MeshGrid(shape, ("dcn",) + TransformerLM.AXES)
        print(f"tiers {args.tiers}: simulated {d}-host x {i}-device "
              f"('dcn', 'ici') grid — hierarchical packed collectives "
              f"{'ON' if fusion.hier_enabled() else 'OFF (HEAT_TPU_HIER=0)'}")
    elif args.grid == "auto":
        import jax

        n = len(jax.devices())
        # jax.typeof is deliberately the NARROW probe here (same as the
        # test suite's needs_vma gate): it asks "do check_vma grads
        # trace", not nn.parallel.vma_capable()'s broader "may the vma
        # typing system be live" (which keeps identity psums)
        if hasattr(jax, "typeof") and n % 8 == 0:
            # vma tracking + an 8-divisible mesh: the full composition
            shape = (n // 8, 2, 2, 2)
        else:
            # older jax (the check_vma train path cannot trace) or a mesh
            # the 2x2x2 layout does not divide — run the dp-only
            # packed-collective fused step instead (PR 7)
            shape = (n, 1, 1, 1)
            print(f"grid auto: dp-only packed train step on {n} devices")
    else:
        shape = tuple(int(s) for s in args.grid.split(","))
    if tiers is None:
        grid = ht.MeshGrid(shape, ("dp", "pp", "tp", "sp"))
    cfg = TransformerLMConfig(
        vocab=args.vocab, d_model=args.d_model, n_heads=args.heads,
        n_layers=args.layers, n_micro=args.n_micro,
        moe_experts=args.moe_experts)
    model = TransformerLM(grid, cfg)
    print(f"grid {dict(zip(grid.axis_names, grid.shape))}  layers/stage "
          f"{model.layers_per_stage}  heads/shard {cfg.n_heads // model.tp}")

    rng = np.random.default_rng(0)
    # round the batch up so it divides the dp world x n_micro on any grid
    unit = model.dp_world * cfg.n_micro
    batch = -(-args.batch // unit) * unit
    base = np.arange(batch * args.seq_len).reshape(batch, args.seq_len)
    tokens = ((base + rng.integers(0, 2, base.shape)) % args.vocab)
    toks = model.shard_batch(tokens)

    params = model.init(0)
    tx = optax.adam(args.lr)
    opt_state = tx.init(params)
    step = model.make_train_step(tx)

    for i in range(args.steps):
        params, opt_state, lval = step(params, opt_state, toks)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}: loss {float(lval):.4f}")

    # KV-cached greedy decode needs a token-recurrent grid (pp=sp=1, dense
    # MLP); skip the demo on pipelined / sequence-sharded / MoE configs
    decode_ok = model.pp == 1 and model.sp == 1 and not cfg.moe_experts
    if decode_ok and not args.serve:
        # exactly dp prompt rows (tile if the training batch is smaller)
        reps = -(-model.dp_world // tokens.shape[0])
        prompt = np.tile(tokens, (reps, 1))[:model.dp_world,
                                            :8].astype(np.int32)
        out = np.asarray(model.generate(params, prompt, max_new_tokens=12))
        print("prompt:   ", prompt[0].tolist())
        print("generated:", out[0, 8:].tolist())
    if decode_ok and args.serve:
        run_serve(model, params, args, rng)
    elif args.serve:
        print("--serve skipped: decode needs a pp=1, sp=1 dense grid")


def run_serve(model, params, args, rng):
    """--serve: two tenants' mixed-length generation through the
    continuous-batching DecodeEngine (heat_tpu.serve.decode) — finished
    sequences free their slot mid-flight, queued requests join between
    steps, and the ONE decode executable serves every occupancy."""
    import time

    from heat_tpu.serve import serve_transformer

    vocab = model.cfg.vocab
    eng = serve_transformer(model, params, seq_len=64, decode=True,
                            slots=2 * model.dp_world)
    eng.register_tenant("interactive", priority=10, slo_ms=120e3)
    eng.register_tenant("batch", priority=0)
    eng.warmup()

    n_req = max(4, args.serve_requests)
    reqs = []
    for i in range(n_req):
        s0 = int(rng.integers(4, 13))
        max_new = int(rng.integers(4, 17))
        tenant = "interactive" if i % 3 else "batch"
        reqs.append((rng.integers(0, vocab, (s0,)).astype(np.int32),
                     max_new, tenant))
    t0 = time.perf_counter()
    futs = [(t, p.size, eng.submit(p, m, tenant=t)) for p, m, t in reqs]
    per_tenant = {"interactive": 0, "batch": 0}
    sample = None
    for tenant, s0, f in futs:
        out = f.result(600)
        per_tenant[tenant] += int(out.size) - int(s0)  # generated only
        if sample is None:
            sample = out
    wall = time.perf_counter() - t0
    st = eng.stats()
    print(f"serve: {n_req} requests in {wall:.2f}s over {st['slots']} "
          f"slots  mean occupancy {st['occupancy']:.2f}")
    for tenant, toks in per_tenant.items():
        row = st["tenants"].get(tenant, {})
        print(f"  tenant {tenant:12s} {toks / wall:8.1f} tok/s  "
              f"completed {row.get('completed', 0)}")
    print(f"  prefills {st['prefills']}  decode steps "
          f"{st['decode_steps']}  tokens out {st['tokens_out']}  "
          f"steady compiles after warmup: "
          f"{st['program_cache']['misses']} misses total")
    print("  sample:", sample.tolist())
    eng.close()


if __name__ == "__main__":
    main()
