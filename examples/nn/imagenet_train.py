"""ImageNet-scale training with out-of-core HDF5 loading and DASO
(the reference's ``examples/nn/imagenet.py`` / ``imagenet-DASO.py`` pattern).

Feeds a convnet from a :class:`PartialH5Dataset` — chunks of the HDF5 file
are prefetched by background threads while the mesh trains on the current
chunk — and optionally syncs with the two-level DASO schedule instead of
every-step data parallelism. Falls back to a small synthetic image set when
no HDF5 file is given, so the script runs anywhere.

Usage:
    python imagenet_train.py [--file images.h5 --images-name images
                              --labels-name labels] [--daso] [--epochs N]
"""

import argparse

import numpy as np

try:
    import heat_tpu as ht
except ModuleNotFoundError:  # running from a source checkout without install
    import os, sys

    sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
    import heat_tpu as ht


def synthetic_h5(path, n=256, hw=32, classes=10):
    import h5py

    rng = np.random.default_rng(0)
    images = rng.normal(size=(n, hw, hw, 3)).astype(np.float32)
    labels = rng.integers(0, classes, size=n).astype(np.int32)
    with h5py.File(path, "w") as f:
        f.create_dataset("images", data=images)
        f.create_dataset("labels", data=labels)
    return path


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--file", type=str, default=None)
    p.add_argument("--images-name", type=str, default="images")
    p.add_argument("--labels-name", type=str, default="labels")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--daso", action="store_true", help="two-level DASO sync")
    p.add_argument("--classes", type=int, default=10)
    args = p.parse_args()

    import flax.linen as fnn

    path = args.file
    if path is None:
        import tempfile, os

        path = synthetic_h5(os.path.join(tempfile.mkdtemp(), "synth.h5"))
        print(f"no --file given; using synthetic data at {path}")

    dataset = ht.utils.data.PartialH5Dataset(
        path,
        dataset_names=[args.images_name, args.labels_name],
        initial_load=4096,
        load_length=2048,
    )

    class ConvNet(fnn.Module):
        @fnn.compact
        def __call__(self, x):
            x = fnn.Conv(32, (3, 3), strides=2)(x)
            x = fnn.relu(x)
            x = fnn.Conv(64, (3, 3), strides=2)(x)
            x = fnn.relu(x)
            x = x.reshape((x.shape[0], -1))
            x = fnn.relu(fnn.Dense(128)(x))
            return fnn.Dense(args.classes)(x)

    local_opt = ht.optim.SGD(lr=args.lr)
    if args.daso:
        daso = ht.optim.DASO(
            local_opt, total_epochs=args.epochs, warmup_epochs=1, cooldown_epochs=1
        )
        net = ht.nn.DataParallelMultiGPU(ConvNet(), optimizer=daso)
    else:
        daso = None
        net = ht.nn.DataParallel(
            ConvNet(), optimizer=ht.optim.DataParallelOptimizer(local_opt)
        )

    for epoch in range(args.epochs):
        losses = []
        it = ht.utils.data.PartialH5DataLoaderIter(
            dataset, batch_size=args.batch_size, shuffle=True, seed=epoch
        )
        # yields (images, labels) tuples — two dataset names configured
        for images, labels in it:
            loss = net.step(ht.array(np.asarray(images), split=0),
                            ht.array(np.asarray(labels), split=0))
            losses.append(loss)
        if daso is not None:
            daso.epoch_loss_logic(float(np.mean(losses)))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f}")


if __name__ == "__main__":
    main()
