#!/usr/bin/env python
"""PR 8 drive script: the fault-injection harness + failure-domain
hardening, exercised as a USER would on the 8-device CPU mesh.

Checks (each prints PASS/FAIL, exit 1 on any failure):
 1. baseline sanity: uneven split sum exact, resplit roundtrip
 2. fused-flush fault -> inline-eager fallback, tape consistent, numerics
    equal, `op_engine.fusion_flush_fallbacks` ticked, stale HLO cleared
 3. serve burst under every:3 dispatch faults -> every request answered
    correctly, worker alive, retries counted, zero client errors
 4. probabilistic seeded chaos (prob:0.3@7) over 30 resplits -> process
    survives, fire count identical across two identically-seeded runs
 5. checkpoint crash-cycle: injected write fault + real corruption ->
    save retries, restore quarantines and falls back a step
 6. run_with_recovery bounded restarts with backoff, counter ticked
 7. runtime_stats surfaces: faults section shape, fallback counters
 8. disarmed steady state: re-running the op workload fires nothing
"""

import os
import sys
import time

import numpy as np

import heat_tpu as ht
from heat_tpu.core import fusion, resharding
from heat_tpu.serve import Pow2Buckets, ServeConfig, ServeMetrics, \
    ServingExecutor
from heat_tpu.utils import faults, metrics
from heat_tpu.utils.checkpointing import CheckpointManager, \
    run_with_recovery

FAILED = []


def check(name, ok, detail=""):
    print(f"[{'PASS' if ok else 'FAIL'}] {name} {detail}")
    if not ok:
        FAILED.append(name)


def counters():
    return metrics.counters()


# 1 ------------------------------------------------------------------ #
comm = ht.get_comm()
x = ht.arange(10, dtype=ht.int32, split=0)          # uneven over 8 devs
check("uneven split sum exact", int(x.sum()) == 45)
y = ht.arange(26, dtype=ht.float32, split=0).reshape((13, 2))
rt = y.resplit(1).resplit(0)
check("resplit roundtrip", np.array_equal(rt.numpy(), y.numpy()))

# 2 ------------------------------------------------------------------ #
fusion.reset()
fusion.capture_hlo(True)
a = ht.arange(40, dtype=ht.float32, split=0).reshape((10, 4))
ref = (ht.exp(a * 0.05) + a * 0.5 - 1.0).resplit(1)
ref_np = ref.numpy()
check("baseline capture", fusion.last_hlo() is not None)
before = int(counters().get("op_engine.fusion_flush_fallbacks", 0))
with faults.inject("fusion.flush.compile=nth:1"):
    b = ht.arange(48, dtype=ht.float32, split=0).reshape((12, 4))
    out = (ht.exp(b * 0.05) + b * 0.5 - 1.0).resplit(1)
    got = out.numpy()
fusion.capture_hlo(False)
eager_b = np.exp(np.arange(48, dtype=np.float32).reshape(12, 4) * 0.05) \
    + np.arange(48, dtype=np.float32).reshape(12, 4) * 0.5 - 1.0
check("flush fault -> fallback numerics",
      np.allclose(got, eager_b, rtol=1e-6))
check("flush fallback counter",
      int(counters().get("op_engine.fusion_flush_fallbacks", 0))
      == before + 1)
check("stale HLO cleared on error", fusion.last_hlo() is None)
check("tape consistent after fallback", np.array_equal(out.numpy(), got))
del ref_np

# 3 ------------------------------------------------------------------ #
sm = ServeMetrics()
cfg = ServeConfig(max_batch=4, max_wait_ms=10.0,
                  bucket_rows=Pow2Buckets(min_rows=comm.size,
                                          multiple_of=comm.size))
retr0 = int(counters().get("serve.batch_retries", 0))
with ServingExecutor(lambda v: v * np.float32(3.0) - np.float32(1.0),
                     cfg, metrics=sm, cache_token=comm.cache_key) as ex:
    with faults.inject("serve.batch.dispatch=every:3"):
        futs = [ex.submit(np.full((comm.size, 4), i, np.float32))
                for i in range(24)]
        results = [np.asarray(f.result(60)) for f in futs]
    ok = all(np.array_equal(r, np.full((comm.size, 4), 3.0 * i - 1.0,
                                       np.float32))
             for i, r in enumerate(results))
    check("serve burst under every:3 faults", ok)
    check("worker alive", ex._worker.is_alive())
check("retries counted, zero client errors",
      int(counters().get("serve.batch_retries", 0)) > retr0
      and sm.snapshot()["errors"] == 0,
      f"retries +{int(counters().get('serve.batch_retries', 0)) - retr0}")

# 4 ------------------------------------------------------------------ #
def stochastic_leg():
    resharding.reset_plan_cache()
    fires0 = int(counters().get("faults.reshard.dispatch.fires", 0))
    with faults.inject("reshard.dispatch=prob:0.3@7"):
        with fusion.override(False):
            for i in range(30):
                v = ht.arange(16 + 2 * i, dtype=ht.float32,
                              split=0).reshape((8 + i, 2)).resplit(1)
                assert np.array_equal(
                    v.numpy(),
                    np.arange(16 + 2 * i,
                              dtype=np.float32).reshape(8 + i, 2))
    return int(counters().get("faults.reshard.dispatch.fires", 0)) - fires0


f1 = stochastic_leg()
f2 = stochastic_leg()
check("prob chaos survives + seeded-deterministic",
      f1 == f2 and 0 < f1 < 30, f"fires {f1} vs {f2}")

# 5 ------------------------------------------------------------------ #
import tempfile
import warnings

d = tempfile.mkdtemp()
mgr = CheckpointManager(os.path.join(d, "run"), every_steps=1, keep=3)
w = ht.arange(10, dtype=ht.float32, split=0)
with faults.inject("checkpoint.leaf.write=nth:1"):
    mgr.save(1, {"w": w, "n": 1}, force=True)     # write retried
mgr.save(2, {"w": w * 2.0, "n": 2}, force=True)
with open(os.path.join(mgr._path(2), "manifest.json"), "w") as f:
    f.write("garbage")
with warnings.catch_warnings():
    warnings.simplefilter("ignore")
    step, state = mgr.restore()
check("corrupt newest -> older restores",
      step == 1 and np.array_equal(state["w"].numpy(),
                                   np.arange(10, dtype=np.float32)))
check("corrupt dir quarantined",
      os.path.isdir(mgr._path(2) + ".corrupt"))

# 6 ------------------------------------------------------------------ #
r0 = int(counters().get("checkpoint.recovery_restarts", 0))
crash = {"left": 2}


def train(state, start, save):
    s = dict(state)
    for stp in range(start, 4):
        s = {"v": s.get("v", 0) + 1}
        save(stp + 1, s)
        if crash["left"] > 0:
            crash["left"] -= 1
            raise RuntimeError("preempted")
    return s


t0 = time.monotonic()
out = run_with_recovery(train, CheckpointManager(os.path.join(d, "rec"),
                                                 every_steps=1, keep=2),
                        {"v": 0}, max_restarts=3, backoff_s=0.02)
check("run_with_recovery converges", out["v"] == 4)
check("restarts counted + backoff paced",
      int(counters().get("checkpoint.recovery_restarts", 0)) == r0 + 2
      and time.monotonic() - t0 >= 0.06)

# 7 ------------------------------------------------------------------ #
rt = ht.runtime_stats()
check("runtime_stats faults shape",
      set(rt["faults"]) == {"armed", "plan", "sites", "arms",
                            "total_fires", "fires"}
      and rt["faults"]["armed"] is False
      and rt["faults"]["sites"] == len(faults.SITES))
check("fusion stats exposes flush_fallbacks",
      "flush_fallbacks" in rt["op_engine"]["fusion"])

# 8 ------------------------------------------------------------------ #
fires_total = int(counters().get("faults.fires", 0))
c2 = ht.arange(40, dtype=ht.float32, split=0).reshape((10, 4))
(ht.exp(c2 * 0.05) + c2 * 0.5 - 1.0).resplit(1).numpy()
check("disarmed steady state fires nothing",
      int(counters().get("faults.fires", 0)) == fires_total)

print(f"\n{len(FAILED)} failures" + (f": {FAILED}" if FAILED else ""))
sys.exit(1 if FAILED else 0)
