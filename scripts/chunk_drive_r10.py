"""User-style drive for ISSUE 11: chunked, double-buffered packed
collectives + async train-step dispatch.

Run (8-device virtual CPU mesh):

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/chunk_drive_r10.py

Checks (each prints PASS/FAIL; exit 1 on any FAIL):

 1. chunked flush: chain -> split-axis sum under CHUNKS=4 lowers to 4
    all-reduce legs moving EXACTLY the unchunked wire bytes, values
    bitwise the CHUNKS=1 leg;
 2. int8 codec chunked: a2a/gather legs multiply by the chunk count,
    wire bytes equal, values bitwise the unchunked int8 leg;
 3. transformer packed train step: chunked-vs-unchunked loss bitwise,
    wire parity, steady-state cache misses 0 across chunk toggling;
 4. async trace_step: 13-row linear regression converges with ZERO
    post-warmup cache misses, async leg bitwise the sync leg, donated
    inputs invalidated, fusion.sync() drains;
 5. fault fallback: fusion.chunk.dispatch degrades to the unchunked
    packed collective, values equal, chunk_fallbacks ticks;
 6. runtime_stats surfaces chunk_count/chunk_collectives/chunk_fallbacks.
"""

import sys

import numpy as np

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.core import fusion
from heat_tpu.utils import faults, hlo_audit, metrics

FAILS = []


def check(name, ok, info=""):
    print(f"{'PASS' if ok else 'FAIL'}  {name}  {info}")
    if not ok:
        FAILS.append(name)


def flush_chain(m=96):
    x = ht.arange(13 * m, dtype=ht.float32, split=None).reshape((13, m))
    x = x.resplit(0)
    y = ht.exp(x * 1e-5) + x * 1e-4 - 1.25
    y = y * y + 0.25
    return y.sum(axis=0)


def flush_hlo(codec, chunks, m=96):
    with fusion.quant_override(codec, min_numel=8), \
            fusion.chunk_override(chunks, min_numel=8):
        fusion.reset()
        fusion.capture_hlo(True)
        try:
            out = flush_chain(m).numpy()
            hlo = fusion.last_hlo()
        finally:
            fusion.capture_hlo(False)
    return out, hlo


def main():
    comm = ht.get_comm()
    world = comm.size
    print(f"mesh: {world} devices")

    # -- 1. exact chunked flush ------------------------------------- #
    out1, h1 = flush_hlo(None, 1)
    out4, h4 = flush_hlo(None, 4)
    s1 = hlo_audit.communicating_collective_stats(h1)
    s4 = hlo_audit.communicating_collective_stats(h4)
    b1 = hlo_audit.collective_bytes(h1, world)["total_wire_bytes"]
    b4 = hlo_audit.collective_bytes(h4, world)["total_wire_bytes"]
    check("exact: 1 -> 4 all-reduce legs",
          s1.get("all-reduce", {}).get("count") == 1
          and s4.get("all-reduce", {}).get("count") == 4,
          f"{s1} -> {s4}")
    check("exact: wire bytes equal", b1 == b4, f"{b1} == {b4}")
    check("exact: values bitwise", bool((out1 == out4).all()))

    # -- 2. int8 codec chunked -------------------------------------- #
    m8 = 4 * world * 128
    q1, qh1 = flush_hlo("int8", 1, m=m8)
    q4, qh4 = flush_hlo("int8", 4, m=m8)
    qs1 = hlo_audit.communicating_collective_stats(qh1)
    qs4 = hlo_audit.communicating_collective_stats(qh4)
    qb1 = hlo_audit.collective_bytes(qh1, world)["total_wire_bytes"]
    qb4 = hlo_audit.collective_bytes(qh4, world)["total_wire_bytes"]
    check("int8: a2a legs x4",
          qs4["all-to-all"]["count"] == 4 * qs1["all-to-all"]["count"]
          and qs4["all-gather"]["count"] == 4 * qs1["all-gather"]["count"],
          f"{qs1} -> {qs4}")
    check("int8: wire bytes equal", qb1 == qb4, f"{qb1} == {qb4}")
    check("int8: values bitwise", bool((q1 == q4).all()))

    # -- 3. transformer packed step --------------------------------- #
    from heat_tpu.nn.transformer import TransformerLM, TransformerLMConfig

    grid = ht.MeshGrid((world, 1, 1, 1), ("dp", "pp", "tp", "sp"))
    cfg = TransformerLMConfig(vocab=64, d_model=32, n_heads=4,
                              n_layers=2, d_ff=64)
    model = TransformerLM(grid, cfg)
    toks = model.shard_batch(np.random.default_rng(0).integers(
        0, cfg.vocab, (2 * world, 8)).astype(np.int32))
    params = model.init(0)
    step_hlo, losses = {}, {}
    for n in (1, 4):
        with fusion.quant_override(None), \
                fusion.chunk_override(n, min_numel=8):
            lg = model.loss_and_grad_fn()
            step_hlo[n] = lg.lower(params, toks).compile().as_text()
            losses[n] = float(lg(params, toks)[0])
    tb1 = hlo_audit.collective_bytes(step_hlo[1], world)["total_wire_bytes"]
    tb4 = hlo_audit.collective_bytes(step_hlo[4], world)["total_wire_bytes"]
    check("transformer: loss bitwise chunked-vs-unchunked",
          losses[1] == losses[4], f"{losses[1]} == {losses[4]}")
    check("transformer: wire bytes equal", tb1 == tb4, f"{tb1} == {tb4}")
    with fusion.quant_override(None), fusion.chunk_override(1):
        fn1 = model.loss_and_grad_fn()
    with fusion.quant_override(None), fusion.chunk_override(4, min_numel=8):
        fn4 = model.loss_and_grad_fn()
    with fusion.quant_override(None), fusion.chunk_override(1):
        check("transformer: toggle-back re-hits cached step",
              model.loss_and_grad_fn() is fn1 and fn4 is not fn1)

    # -- 4. async trace_step: convergence + donation ---------------- #
    rng = np.random.default_rng(1)
    Xh = rng.standard_normal((13, 4)).astype(np.float32)
    wtrue = np.array([0.5, -1.0, 2.0, 0.25], np.float32)
    yh = Xh @ wtrue
    X = ht.array(Xh, split=0)
    Y = ht.array(yh, split=0)

    def step(p, a, b):
        def loss_fn(q, xa, yb):
            d = ht.matmul(xa, q["w"].reshape((4, 1))).reshape((13,)) - yb
            return ht.mean(d * d)

        lval, g = fusion.value_and_grad(loss_fn)(p, a, b)
        return {"w": p["w"] - 0.2 * g["w"]}, lval

    def run(block):
        ts = fusion.trace_step(step, donate_argnums=(0,), block=block)
        p = {"w": ht.zeros(4, dtype=ht.float32)}
        p, l = ts(p, X, Y)  # warmup/compile
        fusion.sync()
        m0 = fusion.program_cache().stats()["misses"]
        for _ in range(60):
            p, l = ts(p, X, Y)
        fusion.sync()
        return p["w"].numpy(), float(l.numpy()), \
            fusion.program_cache().stats()["misses"] - m0

    ws, ls, miss_s = run(True)
    wa, la, miss_a = run(False)
    check("async: converges to closed form",
          np.allclose(wa, wtrue, atol=1e-3), f"w={wa}")
    check("async: bitwise the sync leg",
          bool((ws == wa).all()) and ls == la)
    check("async: zero post-warmup misses (both legs)",
          miss_s == 0 and miss_a == 0, f"{miss_s}/{miss_a}")
    ts = fusion.trace_step(step, donate_argnums=(0,), block=False)
    p0 = {"w": ht.zeros(4, dtype=ht.float32)}
    _ = ts(p0, X, Y)
    fusion.sync()
    died = False
    try:
        p0["w"].numpy()
    except RuntimeError:
        died = True
    check("async: donated input invalidated", died)

    # -- 5. fault fallback ------------------------------------------ #
    base = flush_chain().numpy()
    c0 = int(metrics.counters().get("op_engine.chunk_fallbacks", 0))
    with fusion.chunk_override(4, min_numel=8):
        with faults.inject("fusion.chunk.dispatch=nth:1"):
            faulted = flush_chain().numpy()
    c1 = int(metrics.counters().get("op_engine.chunk_fallbacks", 0))
    check("fault: degrades to unchunked, values equal",
          bool((faulted == base).all()))
    check("fault: chunk_fallbacks ticked", c1 - c0 == 1, f"+{c1 - c0}")

    # -- 6. runtime_stats surface ----------------------------------- #
    st = ht.runtime_stats()["op_engine"]["fusion"]
    check("stats: chunk keys present and sane",
          st["chunk_count"] >= 1 and st["chunk_collectives"] >= 1
          and st["chunk_fallbacks"] >= 1,
          {k: st[k] for k in ("chunk_count", "chunk_collectives",
                              "chunk_fallbacks")})

    print(f"\n{len(FAILS)} failures" if FAILS
          else f"\nALL PASS ({world} devices)")
    sys.exit(1 if FAILS else 0)


if __name__ == "__main__":
    main()
