#!/usr/bin/env python
"""Static collective audit of the compiled programs across device counts.

Round-4 verdict #4: timing a virtual CPU mesh on a 1-core host cannot
evidence scaling behavior (all devices share the silicon, noise swamps
signal). What CAN be evidenced without a pod is the *communication
structure* of the compiled programs: for each device count d, lower +
compile the hot programs on a d-device virtual CPU mesh and count the
collective instructions and their per-device payload bytes in the
optimized HLO. The programs' scaling claims are then checked analytically:

- KMeans Lloyd step: O(1) all-reduce instructions whose payload is
  O(k*feats) — independent of both n and d (the only cross-device traffic
  is the centroid sums/counts). No all-gather, no collective-permute.
- Ring manipulations (roll / reshape): O(1) collective-permute rounds
  (scheduled window fetch, NOT a p-step rotation ring), payload O(n/p).
- cdist systolic ring: exactly d-1 collective-permute steps by design
  (every device must see every Y tile), payload O(m/p * feats) per step.
- Ring attention: 2*(d-1) collective-permutes (K and V circulate),
  payload O(S/p * heads * head_dim) per step.

Bytes are read from the HLO result shapes of the collective instructions,
so the numbers are the partitioned per-device payloads XLA actually
emits, not a model. Instructions inside a `while` body appear once
statically (the Lloyd loop executes its all-reduce once per iteration —
the audit counts program structure, which is what scales with d).

Usage (writes one JSON line per (program, d) plus a summary):
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python scripts/collective_audit.py --devices 1,4,16,64,256
"""

import argparse
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _audit_one(ndev: int, programs: list) -> list:
    """Child process: build each requested program on an ndev-device mesh,
    compile, and emit its collective stats."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, _REPO)
    import heat_tpu as ht
    from heat_tpu.core.communication import TPUCommunication

    from heat_tpu.utils import hlo_audit

    comm = TPUCommunication(jax.devices()[:ndev])
    out = []

    def emit(name, fn, args, expect):
        try:
            compiled = fn.lower(*args).compile()
            hlo = compiled.as_text()
        except Exception as exc:
            out.append({"program": name, "devices": ndev,
                        "error": str(exc)[-200:]})
            return
        # hlo_audit parses per line with comment stripping, so long
        # tuple-shaped results (``/*index=5*/`` markers) are counted fully;
        # the previous in-script regex undercounted 8-way tiled all-to-alls
        out.append({"program": name, "devices": ndev,
                    "stats": hlo_audit.collective_stats(hlo),
                    "memory": hlo_audit.memory_stats(compiled),
                    "expect": expect})

    n_per = 128  # rows per device: payloads scale as O(n/p) by construction
    feats, k = 64, 8

    if "kmeans" in programs:
        from heat_tpu.cluster.kmeans import _lloyd_fori_fn

        n = n_per * ndev
        x = ht.random.rand(n, feats, dtype=ht.float32, split=0, comm=comm)
        cents = jnp.asarray(
            np.random.default_rng(0).random((k, feats), dtype=np.float32))
        fn = _lloyd_fori_fn(x.larray.shape, jnp.dtype(jnp.float32), k, n, comm)
        emit("kmeans_lloyd_step", fn,
             (x.larray, cents, jnp.int32(2)),
             "O(1) all-reduce instrs, payload O(k*feats) indep of n and d; "
             "no all-gather / collective-permute")

    if "roll" in programs and ndev > 1:
        from heat_tpu.core import _manips

        n = n_per * ndev
        x = ht.random.rand(n, dtype=ht.float32, split=0, comm=comm)
        fn = _manips.ring_roll_fn(x.larray.shape, jnp.dtype(jnp.float32),
                                  0, n, 5, comm)
        emit("ring_roll", fn, (x.larray,),
             "O(1) collective-permute rounds (window fetch), payload O(n/p)")

    if "reshape" in programs and ndev > 1:
        from heat_tpu.core import _manips

        n = n_per * ndev
        x = ht.random.rand(n, dtype=ht.float32, split=0, comm=comm)
        fn = _manips.ring_reshape_fn(x.larray.shape, jnp.dtype(jnp.float32),
                                     (n // 2, 2), comm.chunk_size(n // 2),
                                     comm)
        emit("ring_reshape", fn, (x.larray,),
             "O(1) collective-permute rounds, payload O(n/p)")

    if "cdist" in programs and ndev > 1:
        n = n_per * ndev
        x = ht.random.rand(n, 18, dtype=ht.float32, split=0, comm=comm)
        from heat_tpu.spatial import distance as _dist_mod

        fn = _dist_mod._ring_kernel(
            x, x, _dist_mod._euclidean_tile, False, jnp.dtype(jnp.float32),
            comm, ("euclidean",))
        emit("cdist_ring", fn, (x.larray, x.larray),
             "exactly d-1 collective-permutes (systolic ring), payload "
             "O(m/p * feats) each")

    def _transformer_step(grid_shape, cfg_kw, seq):
        """Build a TransformerLM train step + inputs on the given grid."""
        import optax
        from heat_tpu.nn.transformer import TransformerLM, TransformerLMConfig

        grid = ht.MeshGrid(grid_shape, ("dp", "pp", "tp", "sp"),
                           devices=jax.devices()[:ndev])
        model = TransformerLM(grid, TransformerLMConfig(vocab=32, **cfg_kw))
        params = model.init(0)
        tx = optax.sgd(0.05)
        step = model.make_train_step(tx)
        toks = model.shard_batch(np.zeros((2, seq), dtype=np.int32))
        return step, (params, tx.init(params), toks)

    if "transformer_tp" in programs and ndev > 1:
        # Megatron tensor parallelism: the all-reduce COUNT is set by the
        # layer structure (row-parallel projections fwd + column-parallel
        # input grads bwd, + grad syncs of replicated params), NOT by the
        # tp width. NB the model width scales with tp here (head/feature
        # divisibility), so the recorded payload grows with the model —
        # count constancy is the claim this config tests.
        step, args_ = _transformer_step(
            (1, 1, ndev, 1),
            dict(d_model=8 * ndev, n_heads=2 * ndev, n_layers=2,
                 d_ff=8 * ndev), seq=8)
        emit("transformer_tp_step", step, args_,
             "all-reduce count set by layer structure - constant in tp for "
             "fixed layers (model width scales with tp in this config, so "
             "payloads scale with the model, not the partitioning)")

    if "transformer_sp" in programs and ndev > 1:
        step, args_ = _transformer_step(
            (1, 1, 1, ndev),
            dict(d_model=8, n_heads=2, n_layers=2, d_ff=8), seq=8 * ndev)
        emit("transformer_sp_step", step, args_,
             "ring attention: collective-permute rounds O(d) per layer "
             "(fwd + bwd recompute), payload O(S/p * H * D) each; "
             "all-reduces for replicated-param grad sync only")

    if "resplit" in programs and ndev > 1:
        # The explicit reshard planner vs the GSPMD-blind baseline (the
        # pre-planner ``out_shardings`` program, kept for exactly this
        # audit), at a FIXED global size so the ladder shows the O(N/p)
        # per-device payload and temp-buffer scaling. "even" divides at
        # every audited d; "uneven" exercises the padded canonical layout,
        # where the baseline re-lays-out through a larger temp buffer.
        from heat_tpu.core import resharding

        for label, gshape in (("even", (1024, 640)), ("uneven", (1000, 636))):
            x = ht.random.rand(*gshape, dtype=ht.float32, split=0, comm=comm)
            phys, jdt = x.larray.shape, x.larray.dtype
            emit(f"resplit_planned_{label}",
                 resharding.planned_reshard_fn(phys, jdt, gshape, 0, 1, comm),
                 (x.larray,),
                 "split0->split1: exactly ONE all-to-all, ZERO all-gather, "
                 "payload and temp O(N/p)")
            emit(f"resplit_gspmd_{label}",
                 resharding.gspmd_reshard_fn(phys, jdt, gshape, 0, 1, comm),
                 (x.larray,),
                 "GSPMD-blind baseline for the same reshard: whatever XLA "
                 "chooses (audited, not trusted)")
        x = ht.random.rand(1024, 640, dtype=ht.float32, split=None, comm=comm)
        emit("resplit_place",
             resharding.planned_reshard_fn(
                 x.larray.shape, x.larray.dtype, (1024, 640), None, 0, comm),
             (x.larray,),
             "None->split0: local slice per device, ZERO collectives")
        xs = ht.random.rand(1024, 640, dtype=ht.float32, split=0, comm=comm)
        emit("resplit_gather",
             resharding.planned_reshard_fn(
                 xs.larray.shape, xs.larray.dtype, (1024, 640), 0, None,
                 comm),
             (xs.larray,),
             "split0->None: the ONE legitimate all-gather case")

    if "attention" in programs and ndev > 1:
        from heat_tpu.nn.attention import ring_attention

        S_per, H, D = 8, 2, 4
        q = ht.random.rand(1, S_per * ndev, H, D, dtype=ht.float32, split=1,
                           comm=comm)
        o = ring_attention(q, q, q)  # builds + caches the jitted shard_map
        from heat_tpu.nn.attention import _ATTN_CACHE

        fn = next(iter(_ATTN_CACHE.values()))
        emit("ring_attention", fn, (q.larray, q.larray, q.larray),
             "2*(d-1) collective-permutes (K and V circulate), payload "
             "O(S/p * H * D) each")

    print(json.dumps(out))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default=None,
                    help="device-count ladder (default 1,4,16,64,256; "
                         "4,8 under --resplit)")
    ap.add_argument("--programs",
                    default="kmeans,roll,reshape,cdist,attention,resplit")
    ap.add_argument("--resplit", action="store_true",
                    help="audit ONLY the resplit planner vs the GSPMD "
                         "baseline (standalone mode; also run from "
                         "scripts/run_suite_ladder.py every round)")
    ap.add_argument("--timeout", type=float, default=1800.0,
                    help="per-device-count compile budget (s)")
    ap.add_argument("--out", default=None, help="also write summary JSON here")
    ap.add_argument("--measure-devices", type=int, default=0,
                    help="(internal) run the audit in THIS process")
    args = ap.parse_args()

    programs = ["resplit"] if args.resplit else args.programs.split(",")
    if args.devices is None:
        args.devices = "4,8" if args.resplit else "1,4,16,64,256"
    if args.measure_devices:
        _audit_one(args.measure_devices, programs)
        return

    # unrolled rings make compile time itself O(d) for cdist/attention and
    # the sequence-parallel transformer; cap those at 64 devices and say
    # so rather than time out silently
    ring_cap = 64
    capped = ("cdist", "attention", "transformer_sp")
    all_results = []
    for d in (int(x) for x in args.devices.split(",")):
        progs = [p for p in programs if d <= ring_cap or p not in capped]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={d}")
        env["XLA_FLAGS"] = " ".join(flags).strip()
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--measure-devices", str(d), "--programs", ",".join(progs)],
                env=env, capture_output=True, text=True,
                timeout=args.timeout, cwd=_REPO)
        except subprocess.TimeoutExpired:
            rec = [{"devices": d, "error": f"compile audit exceeded "
                                           f"{args.timeout:.0f}s"}]
            all_results.extend(rec)
            print(json.dumps(rec))
            continue
        line = next((l for l in reversed(out.stdout.splitlines())
                     if l.startswith("[")), None)
        if line is None:
            rec = [{"devices": d,
                    "error": (out.stderr or "no output").strip()[-300:]}]
            all_results.extend(rec)
            print(json.dumps(rec))
            continue
        recs = json.loads(line)
        all_results.extend(recs)
        for r in recs:
            print(json.dumps(r))

    verdicts = audit_verdicts(all_results)
    print(json.dumps({"summary": verdicts}))
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"results": all_results, "verdict": verdicts}, f,
                      indent=1)
    if args.resplit:
        # standalone/CI mode: the collective bounds are the contract — and a
        # compile failure must FAIL the gate, not skip it. Error records
        # carry no 'stats', so audit_verdicts never sees them; require every
        # resplit program to have a full >=2-rung ladder and every planned
        # rung to carry its baseline comparison (cf. the transformer checks
        # above: a single surviving record must not pass).
        bad = [p for p, rec in verdicts.items() if not rec.get("all_ok")]
        required = ("resplit_planned_even", "resplit_planned_uneven",
                    "resplit_gspmd_even", "resplit_gspmd_uneven",
                    "resplit_place", "resplit_gather")
        for p in required:
            if len(verdicts.get(p, {}).get("ladder", [])) < 2:
                bad.append(f"{p}: missing ladder records (compile failure?)")
        for label in ("even", "uneven"):
            for c in verdicts.get(f"resplit_planned_{label}",
                                  {}).get("ladder", []):
                if "bytes_vs_gspmd" not in c:
                    bad.append(f"resplit_planned_{label}@d={c['devices']}: "
                               "no GSPMD baseline to compare against")
        if bad:
            print(json.dumps({"resplit_audit_failed": bad}))
            sys.exit(1)


def audit_verdicts(results: list) -> dict:
    """Check each program's measured collective structure against its
    analytic claim, across the device ladder."""
    by_prog = {}
    for r in results:
        if "stats" in r:
            by_prog.setdefault(r["program"], []).append(r)
    v = {}
    for prog, recs in sorted(by_prog.items()):
        recs.sort(key=lambda r: r["devices"])
        checks = []
        for r in recs:
            d, st = r["devices"], r["stats"]
            cp = st.get("collective-permute", {"count": 0, "bytes": 0})
            ar = st.get("all-reduce", {"count": 0, "bytes": 0})
            ag = st.get("all-gather", {"count": 0})
            a2a = st.get("all-to-all", {"count": 0, "bytes": 0})
            if prog == "kmeans_lloyd_step":
                ok = (ag["count"] == 0 and cp["count"] == 0
                      and ar["count"] <= 4)
            elif prog in ("ring_roll", "ring_reshape"):
                ok = ag["count"] == 0 and cp["count"] <= 4
            elif prog == "cdist_ring":
                ok = ag["count"] == 0 and cp["count"] == d - 1
            elif prog == "ring_attention":
                ok = ag["count"] == 0 and cp["count"] == 2 * (d - 1)
            elif prog.startswith("resplit_planned"):
                # the tentpole invariant: zero all-gather, ONE all-to-all
                ok = ag["count"] == 0 and a2a["count"] == 1
            elif prog == "resplit_place":
                ok = not st  # None->split: ZERO collectives of any kind
            elif prog == "resplit_gather":
                ok = ag["count"] == 1 and a2a["count"] == 0
            else:
                ok = True
            entry = {"devices": d, "ok": ok, **st}
            if r.get("memory"):
                entry["memory"] = r["memory"]
            checks.append(entry)
        # cross-record structure checks for the transformer train step;
        # these NEED a ladder — a single surviving record (others failed to
        # compile) or a missing collective kind must FAIL, not pass
        if prog == "transformer_tp_step":
            # Megatron TP: the all-reduce count is a property of the layer
            # structure, identical (and nonzero) at every width
            counts = {c.get("all-reduce", {}).get("count") for c in checks}
            if len(checks) < 2 or len(counts) != 1 or None in counts:
                for c in checks:
                    c["ok"] = False
        if prog == "transformer_sp_step":
            # ring attention: permute count linear in d -> (cp - base) /
            # (d - 1) is the same per-layer ring constant at every d
            ratios = set()
            for c in checks:
                cpc = c.get("collective-permute", {}).get("count")
                ratios.add(None if cpc is None
                           else (cpc - 1) / (c["devices"] - 1))
            if len(checks) < 2 or len(ratios) != 1 or None in ratios:
                for c in checks:
                    c["ok"] = False
        v[prog] = {"all_ok": all(c["ok"] for c in checks), "ladder": checks}

    # cross-program resplit bounds: at every device count the planned path
    # must move no more collective bytes than the GSPMD-blind baseline and
    # peak no higher in temp buffers; across the ladder the per-device
    # payload must scale ~1/p (fixed global size by construction above)
    for label in ("even", "uneven"):
        planned = v.get(f"resplit_planned_{label}")
        baseline = v.get(f"resplit_gspmd_{label}")
        if planned is None:
            continue
        base_by_d = {c["devices"]: c
                     for c in (baseline or {"ladder": []})["ladder"]}
        for c in planned["ladder"]:
            b = base_by_d.get(c["devices"])
            if b is None:
                continue
            kinds = ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective-permute")
            pb = sum(c.get(k, {}).get("bytes", 0) for k in kinds)
            bb = sum(b.get(k, {}).get("bytes", 0) for k in kinds)
            c["bytes_vs_gspmd"] = {"planned": pb, "gspmd": bb,
                                   "ok": pb <= bb}
            pt = c.get("memory", {}).get("temp_size_in_bytes")
            bt = b.get("memory", {}).get("temp_size_in_bytes")
            if pt is not None and bt is not None:
                c["temp_vs_gspmd"] = {"planned": pt, "gspmd": bt,
                                      "ok": pt <= bt}
            c["ok"] = (c["ok"] and c["bytes_vs_gspmd"]["ok"]
                       and c.get("temp_vs_gspmd", {}).get("ok", True))
        lad = sorted(planned["ladder"], key=lambda c: c["devices"])
        for lo, hi in zip(lad, lad[1:]):
            blo = lo.get("all-to-all", {}).get("bytes")
            bhi = hi.get("all-to-all", {}).get("bytes")
            if blo and bhi:
                # recorded bytes are the per-device payload = N/p at fixed
                # global N, so bytes·p is constant across the ladder
                # (±25% for padding granularity on the uneven shape)
                ratio = (blo * lo["devices"]) / (bhi * hi["devices"])
                hi["payload_scaling_1_over_p"] = {
                    "vs_devices": lo["devices"],
                    "ratio": round(ratio, 3),
                    "ok": 0.75 <= ratio <= 1.34,
                }
                hi["ok"] = hi["ok"] and hi["payload_scaling_1_over_p"]["ok"]
        planned["all_ok"] = all(c["ok"] for c in planned["ladder"])
    return v


if __name__ == "__main__":
    main()
