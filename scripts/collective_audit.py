#!/usr/bin/env python
"""Static collective audit of the compiled programs across device counts.

Round-4 verdict #4: timing a virtual CPU mesh on a 1-core host cannot
evidence scaling behavior (all devices share the silicon, noise swamps
signal). What CAN be evidenced without a pod is the *communication
structure* of the compiled programs: for each device count d, lower +
compile the hot programs on a d-device virtual CPU mesh and count the
collective instructions and their per-device payload bytes in the
optimized HLO. The programs' scaling claims are then checked analytically:

- KMeans Lloyd step: O(1) all-reduce instructions whose payload is
  O(k*feats) — independent of both n and d (the only cross-device traffic
  is the centroid sums/counts). No all-gather, no collective-permute.
- Ring manipulations (roll / reshape): O(1) collective-permute rounds
  (scheduled window fetch, NOT a p-step rotation ring), payload O(n/p).
- cdist systolic ring: exactly d-1 collective-permute steps by design
  (every device must see every Y tile), payload O(m/p * feats) per step.
- Ring attention: 2*(d-1) collective-permutes (K and V circulate),
  payload O(S/p * heads * head_dim) per step.

Bytes are read from the HLO result shapes of the collective instructions,
so the numbers are the partitioned per-device payloads XLA actually
emits, not a model. Instructions inside a `while` body appear once
statically (the Lloyd loop executes its all-reduce once per iteration —
the audit counts program structure, which is what scales with d).

Usage (writes one JSON line per (program, d) plus a summary):
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python scripts/collective_audit.py --devices 1,4,16,64,256
"""

import argparse
import json
import os
import re
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8, "u64": 8, "c64": 8,
    "c128": 16,
}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
                "collective-permute")

# matches the result portion of a collective instruction, e.g.
# ``%all-reduce.9 = (f32[8,64]{1,0}, f32[8]{0}, f32[]) all-reduce(`` —
# XLA fuses independent psums into ONE tuple-shaped all-reduce, so the
# result may be a tuple of shapes; the payload is their sum.
_INSTR_RE = re.compile(
    r"= ([^=]*?)\s(" + "|".join(_COLLECTIVES) + r")\(")
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def hlo_collective_stats(hlo: str) -> dict:
    """{kind: {"count": int, "bytes": int}} over an optimized-HLO dump.
    ``bytes`` sums each instruction's result-shape payload once (all
    elements of a tuple-shaped result)."""
    stats = {k: {"count": 0, "bytes": 0} for k in _COLLECTIVES}
    for m in _INSTR_RE.finditer(hlo):
        result, kind = m.groups()
        total = 0
        for dt, dims in _SHAPE_RE.findall(result):
            n = 1
            for piece in dims.split(","):
                if piece:
                    n *= int(piece)
            total += n * _DTYPE_BYTES.get(dt, 4)
        stats[kind]["count"] += 1
        stats[kind]["bytes"] += total
    return {k: v for k, v in stats.items() if v["count"]}


def _audit_one(ndev: int, programs: list) -> list:
    """Child process: build each requested program on an ndev-device mesh,
    compile, and emit its collective stats."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, _REPO)
    import heat_tpu as ht
    from heat_tpu.core.communication import TPUCommunication

    comm = TPUCommunication(jax.devices()[:ndev])
    out = []

    def emit(name, fn, args, expect):
        try:
            hlo = fn.lower(*args).compile().as_text()
        except Exception as exc:
            out.append({"program": name, "devices": ndev,
                        "error": str(exc)[-200:]})
            return
        out.append({"program": name, "devices": ndev,
                    "stats": hlo_collective_stats(hlo), "expect": expect})

    n_per = 128  # rows per device: payloads scale as O(n/p) by construction
    feats, k = 64, 8

    if "kmeans" in programs:
        from heat_tpu.cluster.kmeans import _lloyd_fori_fn

        n = n_per * ndev
        x = ht.random.rand(n, feats, dtype=ht.float32, split=0, comm=comm)
        cents = jnp.asarray(
            np.random.default_rng(0).random((k, feats), dtype=np.float32))
        fn = _lloyd_fori_fn(x.larray.shape, jnp.dtype(jnp.float32), k, n, comm)
        emit("kmeans_lloyd_step", fn,
             (x.larray, cents, jnp.int32(2)),
             "O(1) all-reduce instrs, payload O(k*feats) indep of n and d; "
             "no all-gather / collective-permute")

    if "roll" in programs and ndev > 1:
        from heat_tpu.core import _manips

        n = n_per * ndev
        x = ht.random.rand(n, dtype=ht.float32, split=0, comm=comm)
        fn = _manips.ring_roll_fn(x.larray.shape, jnp.dtype(jnp.float32),
                                  0, n, 5, comm)
        emit("ring_roll", fn, (x.larray,),
             "O(1) collective-permute rounds (window fetch), payload O(n/p)")

    if "reshape" in programs and ndev > 1:
        from heat_tpu.core import _manips

        n = n_per * ndev
        x = ht.random.rand(n, dtype=ht.float32, split=0, comm=comm)
        fn = _manips.ring_reshape_fn(x.larray.shape, jnp.dtype(jnp.float32),
                                     (n // 2, 2), comm.chunk_size(n // 2),
                                     comm)
        emit("ring_reshape", fn, (x.larray,),
             "O(1) collective-permute rounds, payload O(n/p)")

    if "cdist" in programs and ndev > 1:
        n = n_per * ndev
        x = ht.random.rand(n, 18, dtype=ht.float32, split=0, comm=comm)
        from heat_tpu.spatial import distance as _dist_mod

        fn = _dist_mod._ring_kernel(
            x, x, _dist_mod._euclidean_tile, False, jnp.dtype(jnp.float32),
            comm, ("euclidean",))
        emit("cdist_ring", fn, (x.larray, x.larray),
             "exactly d-1 collective-permutes (systolic ring), payload "
             "O(m/p * feats) each")

    def _transformer_step(grid_shape, cfg_kw, seq):
        """Build a TransformerLM train step + inputs on the given grid."""
        import optax
        from heat_tpu.nn.transformer import TransformerLM, TransformerLMConfig

        grid = ht.MeshGrid(grid_shape, ("dp", "pp", "tp", "sp"),
                           devices=jax.devices()[:ndev])
        model = TransformerLM(grid, TransformerLMConfig(vocab=32, **cfg_kw))
        params = model.init(0)
        tx = optax.sgd(0.05)
        step = model.make_train_step(tx)
        toks = model.shard_batch(np.zeros((2, seq), dtype=np.int32))
        return step, (params, tx.init(params), toks)

    if "transformer_tp" in programs and ndev > 1:
        # Megatron tensor parallelism: the all-reduce COUNT is set by the
        # layer structure (row-parallel projections fwd + column-parallel
        # input grads bwd, + grad syncs of replicated params), NOT by the
        # tp width. NB the model width scales with tp here (head/feature
        # divisibility), so the recorded payload grows with the model —
        # count constancy is the claim this config tests.
        step, args_ = _transformer_step(
            (1, 1, ndev, 1),
            dict(d_model=8 * ndev, n_heads=2 * ndev, n_layers=2,
                 d_ff=8 * ndev), seq=8)
        emit("transformer_tp_step", step, args_,
             "all-reduce count set by layer structure - constant in tp for "
             "fixed layers (model width scales with tp in this config, so "
             "payloads scale with the model, not the partitioning)")

    if "transformer_sp" in programs and ndev > 1:
        step, args_ = _transformer_step(
            (1, 1, 1, ndev),
            dict(d_model=8, n_heads=2, n_layers=2, d_ff=8), seq=8 * ndev)
        emit("transformer_sp_step", step, args_,
             "ring attention: collective-permute rounds O(d) per layer "
             "(fwd + bwd recompute), payload O(S/p * H * D) each; "
             "all-reduces for replicated-param grad sync only")

    if "attention" in programs and ndev > 1:
        from heat_tpu.nn.attention import ring_attention

        S_per, H, D = 8, 2, 4
        q = ht.random.rand(1, S_per * ndev, H, D, dtype=ht.float32, split=1,
                           comm=comm)
        o = ring_attention(q, q, q)  # builds + caches the jitted shard_map
        from heat_tpu.nn.attention import _ATTN_CACHE

        fn = next(iter(_ATTN_CACHE.values()))
        emit("ring_attention", fn, (q.larray, q.larray, q.larray),
             "2*(d-1) collective-permutes (K and V circulate), payload "
             "O(S/p * H * D) each")

    print(json.dumps(out))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,4,16,64,256")
    ap.add_argument("--programs",
                    default="kmeans,roll,reshape,cdist,attention")
    ap.add_argument("--timeout", type=float, default=1800.0,
                    help="per-device-count compile budget (s)")
    ap.add_argument("--out", default=None, help="also write summary JSON here")
    ap.add_argument("--measure-devices", type=int, default=0,
                    help="(internal) run the audit in THIS process")
    args = ap.parse_args()

    programs = args.programs.split(",")
    if args.measure_devices:
        _audit_one(args.measure_devices, programs)
        return

    # unrolled rings make compile time itself O(d) for cdist/attention and
    # the sequence-parallel transformer; cap those at 64 devices and say
    # so rather than time out silently
    ring_cap = 64
    capped = ("cdist", "attention", "transformer_sp")
    all_results = []
    for d in (int(x) for x in args.devices.split(",")):
        progs = [p for p in programs if d <= ring_cap or p not in capped]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={d}")
        env["XLA_FLAGS"] = " ".join(flags).strip()
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--measure-devices", str(d), "--programs", ",".join(progs)],
                env=env, capture_output=True, text=True,
                timeout=args.timeout, cwd=_REPO)
        except subprocess.TimeoutExpired:
            rec = [{"devices": d, "error": f"compile audit exceeded "
                                           f"{args.timeout:.0f}s"}]
            all_results.extend(rec)
            print(json.dumps(rec))
            continue
        line = next((l for l in reversed(out.stdout.splitlines())
                     if l.startswith("[")), None)
        if line is None:
            rec = [{"devices": d,
                    "error": (out.stderr or "no output").strip()[-300:]}]
            all_results.extend(rec)
            print(json.dumps(rec))
            continue
        recs = json.loads(line)
        all_results.extend(recs)
        for r in recs:
            print(json.dumps(r))

    verdicts = audit_verdicts(all_results)
    print(json.dumps({"summary": verdicts}))
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"results": all_results, "verdict": verdicts}, f,
                      indent=1)


def audit_verdicts(results: list) -> dict:
    """Check each program's measured collective structure against its
    analytic claim, across the device ladder."""
    by_prog = {}
    for r in results:
        if "stats" in r:
            by_prog.setdefault(r["program"], []).append(r)
    v = {}
    for prog, recs in sorted(by_prog.items()):
        recs.sort(key=lambda r: r["devices"])
        checks = []
        for r in recs:
            d, st = r["devices"], r["stats"]
            cp = st.get("collective-permute", {"count": 0, "bytes": 0})
            ar = st.get("all-reduce", {"count": 0, "bytes": 0})
            ag = st.get("all-gather", {"count": 0})
            if prog == "kmeans_lloyd_step":
                ok = (ag["count"] == 0 and cp["count"] == 0
                      and ar["count"] <= 4)
            elif prog in ("ring_roll", "ring_reshape"):
                ok = ag["count"] == 0 and cp["count"] <= 4
            elif prog == "cdist_ring":
                ok = ag["count"] == 0 and cp["count"] == d - 1
            elif prog == "ring_attention":
                ok = ag["count"] == 0 and cp["count"] == 2 * (d - 1)
            else:
                ok = True
            checks.append({"devices": d, "ok": ok, **st})
        # cross-record structure checks for the transformer train step;
        # these NEED a ladder — a single surviving record (others failed to
        # compile) or a missing collective kind must FAIL, not pass
        if prog == "transformer_tp_step":
            # Megatron TP: the all-reduce count is a property of the layer
            # structure, identical (and nonzero) at every width
            counts = {c.get("all-reduce", {}).get("count") for c in checks}
            if len(checks) < 2 or len(counts) != 1 or None in counts:
                for c in checks:
                    c["ok"] = False
        if prog == "transformer_sp_step":
            # ring attention: permute count linear in d -> (cp - base) /
            # (d - 1) is the same per-layer ring constant at every d
            ratios = set()
            for c in checks:
                cpc = c.get("collective-permute", {}).get("count")
                ratios.add(None if cpc is None
                           else (cpc - 1) / (c["devices"] - 1))
            if len(checks) < 2 or len(ratios) != 1 or None in ratios:
                for c in checks:
                    c["ok"] = False
        v[prog] = {"all_ok": all(c["ok"] for c in checks), "ladder": checks}
    return v


if __name__ == "__main__":
    main()
