#!/usr/bin/env python
"""Bisect + A/B harness for the fused KMeans Pallas kernel on a real TPU.

The kernel compiles and validates in interpreter mode (CPU test mesh), but
on v5e Mosaic reported a scoped-VMEM stack OOM (~66M against a 16M limit)
once the one-hot update GEMM (contraction over the row-block dim) is
included; the XLA Lloyd path then serves the benchmark. This script, run on
the real chip, isolates which kernel stage triggers the allocation and
times kernel-vs-XLA at bench size.

Usage (repo root, real TPU):
    python scripts/tpu_kernel_probe.py bisect       # per-stage compile check
    python scripts/tpu_kernel_probe.py ab           # XLA vs Pallas iter/s

Per the verify notes: first TPU run after a tunnel incident must be tiny —
`bisect` uses n=64k and 2-minute timeouts per stage.
"""

import functools
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")
import heat_tpu as ht  # noqa: E402  (x64 + matmul-precision config)


def _i32(v):
    return jnp.asarray(v, jnp.int32)


def bisect(n=1 << 16, d=64, kp=128, bm=1024):
    acc = jnp.float32
    # Must match the kernel under diagnosis (pallas_kernels._MM_PRECISION).
    # Explicit (not None): an omitted precision resolves to the package-level
    # jax_default_matmul_precision=HIGH, which Mosaic rejects.
    PREC = jax.lax.Precision.DEFAULT

    def kern(x_ref, c_ref, m_ref, s_ref, a_s, *, sub):
        step = pl.program_id(0)
        nsteps = pl.num_programs(0)

        @pl.when(step == 0)
        def _():
            a_s[...] = jnp.zeros_like(a_s)

        x = x_ref[...].astype(acc)
        c = c_ref[...].astype(acc)
        valid = m_ref[...].astype(acc)
        c2 = jnp.sum(c * c, axis=1)[None, :]
        xc = jax.lax.dot_general(
            x, c, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=acc, precision=PREC)
        scores = c2 - 2.0 * xc
        if sub == "scores":
            a_s[...] += jnp.zeros_like(a_s) + jnp.sum(scores)
        else:
            labels = jax.lax.argmin(scores, 1, jnp.int32)
            if sub == "argmin":
                a_s[...] += jnp.zeros_like(a_s) + jnp.sum(labels.astype(acc))
            else:
                onehot = (labels[:, None] == jax.lax.broadcasted_iota(
                    jnp.int32, (bm, kp), 1)).astype(acc) * valid
                if sub == "onehot":
                    a_s[...] += jnp.zeros_like(a_s) + jnp.sum(onehot)
                elif sub == "counts":
                    a_s[...] += jnp.broadcast_to(
                        jnp.sum(onehot, axis=0)[:, None], a_s.shape)
                elif sub == "dot_rev":
                    a_s[...] += jax.lax.dot_general(
                        onehot, x, dimension_numbers=(((0,), (0,)), ((), ())),
                        preferred_element_type=acc, precision=PREC)
                elif sub == "dot_via_transpose":
                    a_s[...] += jax.lax.dot_general(
                        onehot.T, x, dimension_numbers=(((1,), (0,)), ((), ())),
                        preferred_element_type=acc, precision=PREC)

        @pl.when(step == nsteps - 1)
        def _():
            s_ref[...] = a_s[...].astype(s_ref.dtype)

    x = jnp.ones((n, d), jnp.float32)
    c = jnp.ones((kp, d), jnp.float32)
    m = jnp.ones((n, 1), jnp.float32)
    for sub in ("scores", "argmin", "onehot", "counts", "dot_rev",
                "dot_via_transpose"):
        try:
            out = pl.pallas_call(
                functools.partial(kern, sub=sub),
                grid=(n // bm,),
                in_specs=[
                    pl.BlockSpec((bm, d), lambda i: (_i32(i), _i32(0))),
                    pl.BlockSpec((kp, d), lambda i: (_i32(0), _i32(0))),
                    pl.BlockSpec((bm, 1), lambda i: (_i32(i), _i32(0))),
                ],
                out_specs=[pl.BlockSpec((kp, d), lambda i: (_i32(0), _i32(0)))],
                out_shape=[jax.ShapeDtypeStruct((kp, d), acc)],
                scratch_shapes=[pltpu.VMEM((kp, d), acc)],
            )(x, c, m)
            jax.block_until_ready(out)
            print(sub, "OK", flush=True)
        except Exception as e:  # noqa: BLE001 — report and continue bisecting
            print(sub, "FAIL:", str(e)[:160].replace("\n", " "), flush=True)


def ab(n=1 << 23, d=64, k=8, iters=50):
    import os

    from heat_tpu.cluster.kmeans import _lloyd_fori_fn
    from heat_tpu.core import pallas_kernels as pk

    ht.random.seed(0)
    x = ht.random.rand(n, d, dtype=ht.float32, split=0)
    xp = x.larray

    def run(pallas, sums_mode=None, block_rows=None):
        pk.set_pallas(pallas)
        # always set explicitly so no mode leaks from a previous variant
        os.environ["HEAT_TPU_KMEANS_SUMS"] = sums_mode or "dot_t"
        if block_rows is None:
            os.environ.pop("HEAT_TPU_KMEANS_BLOCK_ROWS", None)
        else:
            os.environ["HEAT_TPU_KMEANS_BLOCK_ROWS"] = str(block_rows)
        fn = _lloyd_fori_fn(xp.shape, xp.dtype, k, n, x.comm)
        c0 = xp[:k]
        fn(xp, c0, 2)[1].item()
        t0 = time.perf_counter()
        fn(xp, c0, 2)[1].item()
        t1 = time.perf_counter()
        fn(xp, c0, 2 + iters)[1].item()
        t2 = time.perf_counter()
        return iters / ((t2 - t1) - (t1 - t0))

    # XLA baseline first; then each kernel sums-mode candidate (NEXT.md #1);
    # then smaller X tiles (the scoped-VMEM lever: every per-step temporary
    # scales with block_rows); then XLA again to bracket drift
    variants = [(False, None, None), (True, "dot_t", None),
                (True, "loop", None), (True, "dot_rev", None),
                (True, "dot_t", 512), (True, "dot_t", 256),
                (True, "loop", 256), (False, None, None)]
    for pallas, mode, bm in variants:
        tag = (f"pallas={pallas}" + (f" sums={mode}" if mode else "")
               + (f" bm={bm}" if bm else ""))
        try:
            print(tag, "iter/s:", round(run(pallas, mode, bm), 1), flush=True)
        except Exception as e:  # noqa: BLE001
            print(tag, "FAILED:", str(e)[:160].replace("\n", " "), flush=True)


def _timeit(fn, warmup=2, iters=10):
    for _ in range(warmup):
        jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def cdist_ab(n=40_000, d=18):
    """Pallas fused tile (Precision.HIGHEST GEMM) vs the XLA expansion path
    (package-default HIGH GEMM) at the distance_matrix bench shape
    (NEXT.md #2)."""
    from heat_tpu.core import pallas_kernels as pk

    ht.random.seed(0)
    x = ht.random.rand(n, d, dtype=ht.float32, split=0)
    for pallas in (False, True, False):
        pk.set_pallas(pallas)
        try:
            dt = _timeit(
                lambda: ht.spatial.cdist(x, quadratic_expansion=True).larray,
                warmup=2, iters=5)
            gbs = n * n * 4 / dt / 1e9
            print(f"cdist pallas={pallas}: {dt*1e3:.1f} ms  {gbs:.1f} GB/s",
                  flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"cdist pallas={pallas} FAILED:",
                  str(e)[:160].replace("\n", " "), flush=True)
    pk.set_pallas(None)


def flash_ab(B=4, H=8, S=2048, D=64):
    """Pallas flash attention vs the dense jnp softmax path, causal and full,
    fwd only and fwd+bwd (NEXT.md #2)."""
    from heat_tpu.core import pallas_kernels as pk
    from heat_tpu.nn import attention as attn

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, H, S, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, H, S, D), jnp.bfloat16)

    def loss(q_, k_, v_, causal):
        return attn.local_attention(q_, k_, v_, causal=causal)\
            .astype(jnp.float32).sum()

    for causal in (False, True):
        for pallas in (False, True, False):
            pk.set_pallas(pallas)
            tag = f"flash causal={causal} pallas={pallas}"
            try:
                fwd = jax.jit(functools.partial(loss, causal=causal))
                dt_f = _timeit(lambda: fwd(q, k, v))
                # grads wrt ALL of q/k/v: q-only would let XLA prune the
                # dK/dV backward kernel and under-report the bwd cost
                grad = jax.jit(jax.grad(
                    functools.partial(loss, causal=causal), argnums=(0, 1, 2)))
                dt_b = _timeit(lambda: grad(q, k, v))
                print(f"{tag}: fwd {dt_f*1e3:.2f} ms  fwd+bwd {dt_b*1e3:.2f} ms",
                      flush=True)
            except Exception as e:  # noqa: BLE001
                print(tag, "FAILED:", str(e)[:160].replace("\n", " "),
                      flush=True)
    pk.set_pallas(None)


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "bisect"
    {"bisect": bisect, "ab": ab, "cdist_ab": cdist_ab,
     "flash_ab": flash_ab}[mode]()
