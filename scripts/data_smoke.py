#!/usr/bin/env python
"""Data-engine smoke for the CI ladder (ISSUE 17).

Drives the tape-compiled data engine end to end over the launch mesh
(the ladder runs it at 4 virtual CPU devices) and checks the engine
contract:

* groupby-aggregate equals the numpy reference; top-k values AND
  indices bitwise-equal to the gathered argsort; the engine-routed
  ``ht.percentile`` equals both the merge-split sort path (exactly) and
  numpy;
* the streaming folds (groupby / top-k / multi-pass quantile) over a
  chunked out-of-core pass agree with the in-memory results;
* ZERO steady-state program-cache misses on the second pass at the same
  structural signatures, and ZERO eager fallbacks anywhere;
* ``ht.runtime_stats()["data_engine"]`` present with the pinned shape.

Prints ONE JSON line; exit 1 on any violation (the ladder fails the
round).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python scripts/data_smoke.py
"""

import json
import sys

import numpy as np


def main() -> int:
    import heat_tpu as ht
    from heat_tpu import data

    n_dev = ht.get_comm().size
    rng = np.random.default_rng(0)
    N, G, K = 100_000, 32, 8
    keys = rng.integers(0, G, N).astype(np.int64)
    vals = rng.standard_normal(N)

    k = ht.array(keys, split=0)
    v = ht.array(vals, split=0)

    def burst():
        g = data.groupby(k, G).sum(v).numpy()
        tv, ti = data.topk(v, K)
        p = ht.percentile(v, [7.0, 50.0, 93.0]).numpy()
        return g, tv.numpy(), ti.numpy(), p

    gsum, tvn, tin, pct = burst()  # warm pass compiles everything once
    misses0 = data.engine.program_cache().stats()["misses"]
    g2, tv2, ti2, p2 = burst()
    steady_misses = data.engine.program_cache().stats()["misses"] - misses0

    gref = np.bincount(keys, weights=vals, minlength=G)
    order = np.argsort(-vals, kind="stable")[:K]
    with data.override(False):
        pct_sort = ht.percentile(v, [7.0, 50.0, 93.0]).numpy()

    # streaming pass: the same table chunked out-of-core
    tab = np.stack([keys.astype(np.float64), vals], axis=1)
    rows = 1 << 14

    def chunks():
        return iter(ht.array(tab[i:i + rows], split=0)
                    for i in range(0, N, rows))

    sg = data.stream_groupby(chunks, G, "sum").numpy()
    sv, sp = data.stream_topk(lambda: iter(
        ht.array(vals[i:i + rows], split=0) for i in range(0, N, rows)), K)
    sq = data.stream_quantile(lambda: iter(
        ht.array(vals[i:i + rows], split=0) for i in range(0, N, rows)),
        [0.07, 0.5, 0.93])

    st = data.stats()
    rt = ht.runtime_stats()

    verdicts = {
        "groupby_matches_numpy": bool(
            np.allclose(gsum, gref, rtol=1e-10, atol=1e-8)),
        "topk_bitwise": bool(np.array_equal(tin, order)
                             and np.array_equal(tvn, vals[order])),
        "percentile_equals_sort_path": bool(
            np.array_equal(pct, pct_sort)
            and np.allclose(pct, np.percentile(vals, [7.0, 50.0, 93.0]),
                            rtol=1e-9)),
        "second_pass_deterministic": bool(
            np.array_equal(gsum, g2) and np.array_equal(tvn, tv2)
            and np.array_equal(tin, ti2) and np.array_equal(pct, p2)),
        "zero_steady_misses": steady_misses == 0,
        "stream_groupby_matches": bool(
            np.allclose(sg, gref, rtol=1e-10, atol=1e-8)),
        "stream_topk_bitwise": bool(
            np.array_equal(sp.numpy(), order)
            and np.array_equal(sv.numpy(), vals[order])),
        "stream_quantile_matches": bool(
            np.allclose(sq, np.percentile(vals, [7.0, 50.0, 93.0]),
                        rtol=1e-9)),
        "no_fallbacks": (st["exchange_fallbacks"] == 0
                         and st["stream_fallbacks"] == 0),
        "stats_shape": (set(rt["data_engine"]) == {
            "enabled", "dispatches", "exchange_fallbacks", "stream_chunks",
            "stream_fallbacks", "groupby_calls", "topk_calls",
            "quantile_calls", "join_calls", "program_cache"}
            and st["dispatches"] > 0 and st["stream_chunks"] > 0),
    }
    record = {
        "devices": n_dev,
        "rows": N,
        "groups": G,
        "k": K,
        "steady_misses": steady_misses,
        "dispatches": st["dispatches"],
        "stream_chunks": st["stream_chunks"],
        "program_cache": st["program_cache"],
        "verdicts": verdicts,
        "ok": all(verdicts.values()),
    }
    print(json.dumps(record), flush=True)
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
