"""Round-11 user-style drive: tier-aware hierarchical packed collectives.

Runs ~15 end-to-end checks of the ISSUE 12 surface on the 8-device CPU
mesh simulated as a (2, 4) ("dcn", "ici") two-host pod:

  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python scripts/hier_drive_r11.py
"""

import numpy as np

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.core import fusion
from heat_tpu.core._compat import shard_map
from heat_tpu.utils import faults, hlo_audit, metrics

from jax.sharding import Mesh, PartitionSpec as P

PASS = []
FAIL = []


def check(name, ok, detail=""):
    (PASS if ok else FAIL).append(name)
    print(f"[{'PASS' if ok else 'FAIL'}] {name}" + (f"  {detail}" if detail else ""))


def main():
    n = len(jax.devices())
    assert n >= 4 and n % 2 == 0, f"need an even mesh >= 4, got {n}"
    d, i = 2, n // 2
    mesh2 = Mesh(np.array(jax.devices()).reshape(d, i), ("dcn", "ici"))
    rng = np.random.default_rng(0)

    # ---- 1-4: packed_psum named-grid forms --------------------------- #
    vals = [rng.standard_normal(4096).astype(np.float32),
            rng.standard_normal(300).astype(np.float32)]

    def psum_named(hier_on, codec=None, ici=None):
        with fusion.hier_override(hier_on, tiers="dcn,ici",
                                  ici_codec=ici), \
                fusion.quant_override(codec, min_numel=64):
            def body(a, b):
                return tuple(fusion.packed_psum([a, b], ("dcn", "ici")))
            fn = jax.jit(shard_map(body, mesh=mesh2, in_specs=(P(), P()),
                                   out_specs=(P(), P()), check_vma=False))
            args = [jnp.asarray(v) for v in vals]
            out = [np.asarray(o) for o in fn(*args)]
            hlo = fn.lower(*args).compile().as_text()
        return out, hlo

    flat, hlo_flat = psum_named(False)
    hier, hlo_hier = psum_named(True)
    err = max(np.abs(a - b).max() / (np.abs(b).max() + 1e-30)
              for a, b in zip(hier, flat))
    cs = hlo_audit.collective_stats(hlo_hier)
    t = hlo_audit.collective_bytes(hlo_hier, world=n, tiers=(d, i))
    check("packed_psum hier==flat (few-ulp)", err < 1e-5, f"rel={err:.2e}")
    check("packed_psum decomposition RS+AR+AG, no full AR",
          "reduce-scatter" in cs and "all-gather" in cs
          and "full" not in t["by_tier"])

    ivals_ref = None
    with fusion.hier_override(False):
        def ibody(a):
            return fusion.packed_psum([a], ("dcn", "ici"))[0]
        ifn = jax.jit(shard_map(ibody, mesh=mesh2, in_specs=(P(),),
                                out_specs=P(), check_vma=False))
        ivals_ref = np.asarray(ifn(jnp.arange(500, dtype=jnp.int32)))
    with fusion.hier_override(True, tiers="dcn,ici"):
        ivals = np.asarray(ifn(jnp.arange(500, dtype=jnp.int32)))
    check("int payloads bitwise", np.array_equal(ivals, ivals_ref))

    q8, hlo_q8 = psum_named(True, codec="int8")
    rel = np.linalg.norm(q8[0] - flat[0]) / np.linalg.norm(flat[0])
    t8 = hlo_audit.collective_bytes(hlo_q8, world=n, tiers=(d, i))
    check("int8-over-DCN within 1e-2", rel <= 1e-2, f"rel={rel:.2e}")
    check("int8 DCN a2a legs classified dcn, no full collective",
          "full" not in t8["by_tier"] and t8["by_tier"]["dcn"]["count"] >= 2)

    qb, _ = psum_named(True, ici="bf16")
    rel = np.linalg.norm(qb[0] - flat[0]) / np.linalg.norm(flat[0])
    check("ici bf16 codec within 4e-3", rel <= 4e-3, f"rel={rel:.2e}")

    # ---- 5: DASO replicated-fast form -------------------------------- #
    def psum_rep(hier_on):
        with fusion.hier_override(hier_on, tiers="dcn,ici"):
            def body(a):
                return fusion.packed_psum([a], ("dcn",),
                                          replicated=("ici",))[0]
            fn = jax.jit(shard_map(body, mesh=mesh2, in_specs=(P(),),
                                   out_specs=P(), check_vma=False))
            v = jnp.asarray(vals[0])
            return np.asarray(fn(v)), fn.lower(v).compile().as_text()

    rf, _ = psum_rep(False)
    rh, rhlo = psum_rep(True)
    check("replicated-fast form bitwise",
          np.array_equal(rf, rh)
          and "reduce-scatter" not in hlo_audit.collective_stats(rhlo))

    # ---- 6-8: flush path over flat factored mesh --------------------- #
    def chain():
        x = ht.arange(13 * 40, dtype=ht.float32).reshape((13, 40)).resplit(0)
        y = ht.exp(x * 0.001) + x * 0.5 - 1.25
        y = y * y + 0.25
        return y.sum(axis=0)

    fusion.reset()
    with fusion.hier_override(False):
        base = chain().numpy()
    with fusion.hier_override(True, tiers=(d, i)):
        fusion.capture_hlo(True)
        got = chain().numpy()
        fh = fusion.last_hlo()
        fusion.capture_hlo(False)
    tf = hlo_audit.collective_bytes(fh, world=n, tiers=(d, i))
    check("flush hier parity + decomposition",
          np.allclose(got, base, rtol=1e-5)
          and "full" not in tf["by_tier"]
          and {"ici", "dcn"} <= set(tf["by_tier"]))
    with fusion.hier_override(False, tiers=(d, i)):
        off = chain().numpy()
    check("HEAT_TPU_HIER=0 bitwise today's flat", np.array_equal(off, base))
    s0 = fusion.program_cache().stats()
    with fusion.hier_override(True, tiers=(d, i)):
        chain().numpy()
    with fusion.hier_override(False):
        chain().numpy()
    s1 = fusion.program_cache().stats()
    check("steady-state toggle-back 0 recompiles",
          s1["compiles"] == s0["compiles"], f"{s0} -> {s1}")

    # ---- 9-10: transformer acceptance on the (2, n/2) tier grid ------ #
    import optax

    from heat_tpu.nn.transformer import TransformerLM, TransformerLMConfig

    cfg = TransformerLMConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                              d_ff=64)
    grid = ht.MeshGrid((d, i, 1, 1, 1), ("dcn", "dp", "pp", "tp", "sp"))
    model = TransformerLM(grid, cfg)
    toks = model.shard_batch(rng.integers(0, 64, (2 * n, 16)).astype(np.int32))
    tx = optax.adam(1e-2)

    def step_hlo(hier_on, codec):
        with fusion.hier_override(hier_on, tiers=None), \
                fusion.quant_override(codec), fusion.chunk_override(1):
            step = model.make_train_step(tx)
            p, o = model.init(0), tx.init(model.init(0))
            hlo = step.lower(p, o, toks).compile().as_text()
            losses = []
            for _ in range(8):
                p, o, l = step(p, o, toks)
                losses.append(float(l))
        return hlo, losses

    h_flat, _ = step_hlo(False, None)
    h_hier, losses = step_hlo(True, None)
    h_int8, _ = step_hlo(True, "int8")
    a_flat = hlo_audit.collective_bytes(h_flat, world=n, tiers=(d, i))
    a_hier = hlo_audit.collective_bytes(h_hier, world=n, tiers=(d, i))
    a_int8 = hlo_audit.collective_bytes(h_int8, world=n, tiers=(d, i))
    red = a_flat["total_dcn_wire_bytes"] / max(
        a_hier["total_dcn_wire_bytes"], 1)
    red8 = a_hier["total_dcn_wire_bytes"] / max(
        a_int8["total_dcn_wire_bytes"], 1)
    check("transformer DCN bytes reduced >= p_ici x", red >= i * 0.99,
          f"{red:.2f}x (p_ici={i})")
    check("int8-over-DCN >= 2x further", red8 >= 2.0, f"{red8:.2f}x")
    check("tiered train step converges",
          losses[-1] < losses[0], f"{losses[0]:.3f} -> {losses[-1]:.3f}")

    # ---- 11: int8 overflow hardening --------------------------------- #
    comm = ht.get_comm()
    big = np.stack([np.full(256, 3.4e38 / comm.size, np.float32)] *
                   comm.size).reshape(-1)

    def int8_rt(v):
        def body(x):
            return fusion._quant_int8_allreduce(
                x, comm.axis_name, comm.size, (), 128)
        fn = jax.jit(shard_map(body, mesh=comm.mesh,
                               in_specs=P(comm.axis_name), out_specs=P(),
                               check_vma=False))
        return np.asarray(fn(jnp.asarray(v)))

    out = int8_rt(big)
    check("int8 sum>bf16max saturates (no inf)", np.isfinite(out).all(),
          f"max={out.max():.3e}")
    bad = np.ones(256 * comm.size, np.float32)
    bad[7] = np.inf
    check("int8 inf payload never NaNs", not np.isnan(int8_rt(bad)).any())

    # ---- 12: fault site degrades to flat ----------------------------- #
    fusion.reset()
    c0 = int(metrics.counters().get("op_engine.hier_fallbacks", 0))
    with fusion.hier_override(True, tiers=(d, i)), \
            faults.inject("fusion.hier.exchange=nth:1"):
        faulted = chain().numpy()
    c1 = int(metrics.counters().get("op_engine.hier_fallbacks", 0))
    check("fault site degrades to flat + counter",
          c1 - c0 == 1 and np.allclose(faulted, base, rtol=1e-5))

    # ---- 13: stats surface ------------------------------------------- #
    st = ht.runtime_stats()["op_engine"]["fusion"]
    check("runtime_stats hier keys",
          all(k in st for k in ("hier_enabled", "mesh_tiers",
                                "hier_ici_codec", "hier_collectives",
                                "hier_fallbacks"))
          and st["hier_collectives"] > 0)

    # ---- 14: DataParallel 2-D tier grid ------------------------------ #
    try:
        import flax.linen as fnn

        class MLP(fnn.Module):
            @fnn.compact
            def __call__(self, x):
                return fnn.Dense(4)(fnn.relu(fnn.Dense(16)(x)))

        X = rng.standard_normal((4 * n, 8)).astype(np.float32)
        Y = rng.integers(0, 4, (4 * n,)).astype(np.int32)

        def run_dp(hier_on):
            import heat_tpu.optim as optim

            net = ht.nn.DataParallel(MLP(), optimizer=(
                optim.DataParallelOptimizer(optim.SGD(lr=0.05))))
            ctx = fusion.hier_override(hier_on,
                                       tiers=(d, i) if hier_on else None)
            with ctx:
                return [net.step(X, Y) for _ in range(3)]

        lf, lh = run_dp(False), run_dp(True)
        check("DataParallel tiered step parity",
              np.allclose(lf, lh, rtol=1e-5), f"{lf[-1]:.4f}/{lh[-1]:.4f}")
    except ImportError:
        check("DataParallel tiered step parity", True, "flax absent, skip")

    print(f"\n{len(PASS)}/{len(PASS) + len(FAIL)} PASS"
          + (f"; FAILED: {FAIL}" if FAIL else " — ALL PASS"))
    raise SystemExit(1 if FAIL else 0)


if __name__ == "__main__":
    main()
