#!/usr/bin/env python
"""CI ladder with an auditable skip inventory (round-4 verdict #5).

Runs the full suite at each device count (reference ``Jenkinsfile:24-33``
runs its suite under ``mpirun -n 1..8``; a virtual CPU mesh is the TPU
analog), captures ``pytest -rs`` output, and writes a JSON artifact where
EVERY skip names its reason — so "74 skips at 1 device" decomposes into
named device-count guards instead of unexplained coverage loss.

Optionally (``--examples``) smoke-runs every script in ``examples/`` on
the largest mesh of the ladder.

    python scripts/run_suite_ladder.py --devices 1,2,4,8 \
        --out LADDER_r05.json
"""

import argparse
import json
import os
import re
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# "SKIPPED [8] tests/test_foo.py:123: needs a multi-device mesh"
_SKIP_RE = re.compile(r"^SKIPPED \[(\d+)\] ([^:]+:\d+): (.*)$")
_SUMMARY_RE = re.compile(
    r"(?:(\d+) failed, )?(\d+) passed(?:, (\d+) skipped)?"
    r"(?:, \d+ deselected)?(?:, (\d+) error)?.* in ([\d.]+)s")


def _env(n: int) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["HEAT_TPU_TEST_DEVICES"] = str(n)
    env["HEAT_TPU_RUN_SLOW"] = "1"  # the ladder runs the soak tests too
    return env


def run_suite(n: int, timeout: float) -> dict:
    t0 = time.time()
    # per-test executable/counter log (conftest appends one JSON line per
    # test): on the rare 4-device SIGABRT (NEXT.md §2b) the last line names
    # the accumulated jit-executable count right before the abort, so the
    # flakiness can be correlated with cache growth
    stats_path = os.path.join(_REPO, f".ladder_teststats_{n}.jsonl")
    try:
        os.unlink(stats_path)
    except OSError:
        pass
    env = _env(n)
    env["HEAT_TPU_LADDER_STATS"] = stats_path
    try:
        # -X faulthandler: the rare 4-device XLA:CPU SIGABRT (NEXT.md §2b)
        # kills the interpreter below pytest — only a faulthandler dump on
        # stderr survives it, and it is persisted into the ladder JSON
        out = subprocess.run(
            [sys.executable, "-X", "faulthandler", "-m", "pytest", "tests/",
             "-x", "-q", "-rs"],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=_REPO)
    except subprocess.TimeoutExpired:
        return {"devices": n, "error": f"suite exceeded {timeout:.0f}s"}
    skips = {}
    for line in out.stdout.splitlines():
        m = _SKIP_RE.match(line.strip())
        if m:
            count, _loc, reason = m.groups()
            skips[reason] = skips.get(reason, 0) + int(count)
    rec = {"devices": n, "rc": out.returncode,
           "wall_s": round(time.time() - t0, 1),
           "skip_reasons": dict(sorted(skips.items(),
                                       key=lambda kv: -kv[1]))}
    m = _SUMMARY_RE.search(out.stdout)
    if m:
        failed, passed, skipped, errors, dur = m.groups()
        rec.update(passed=int(passed), skipped=int(skipped or 0),
                   failed=int(failed or 0), errors=int(errors or 0),
                   pytest_s=float(dur))
    else:
        rec["tail"] = out.stdout.strip().splitlines()[-3:]
    if out.returncode != 0:
        # surface what broke in the CI log and the artifact — the summary
        # line alone names no test and shows no traceback
        tail = out.stdout.strip().splitlines()[-40:]
        rec["failure_tail"] = tail
        print("\n".join(tail), file=sys.stderr, flush=True)
    stderr = out.stderr or ""
    if out.returncode < 0 or "Fatal Python error" in stderr:
        # interpreter abort (SIGABRT/SIGSEGV): pytest never reported — the
        # faulthandler dump on stderr is the only trace; keep it
        rec["abort_signal"] = -out.returncode if out.returncode < 0 else None
        rec["abort_traceback"] = stderr.strip().splitlines()[-120:]
        print("\n".join(rec["abort_traceback"][-40:]), file=sys.stderr,
              flush=True)
    # the last per-test counter line = state right before exit/abort
    # (NEXT.md §2b: correlate the SIGABRT with executable-cache growth)
    try:
        with open(stats_path) as f:
            lines = [l for l in f.read().splitlines() if l.strip()]
        if lines:
            rec["executable_counters"] = json.loads(lines[-1])
            rec["executable_counters"]["tests_logged"] = len(lines)
    except OSError:
        pass
    except Exception as exc:
        rec["executable_counters"] = {"error": repr(exc)}
    finally:
        try:
            os.unlink(stats_path)
        except OSError:
            pass
    return rec


# fast, numerically-loaded subset for the fusion on/off A/B: the op-engine
# surface where deferred evaluation could drift from eager semantics.
# The reduction-heavy slice (statistics + nan-reductions + the distributed
# statistics module) exercises the PR 4 reduction-fused tapes; the
# linalg-heavy slice (linalg + transformer) the PR 5 contraction-fused
# tapes; the manipulations-heavy slice the PR 6 resplit-fused tapes (the
# alignment/pre-alignment resplit surface: concatenate/reshape/stack over
# mixed splits) — the per-test HEAT_TPU_LADDER_STATS log carries
# fusion_reduce_flushes / fusion_contract_flushes / fusion_resplit_nodes /
# fusion_step_flushes next to the executable counters so the A/B shows
# which tests actually took the collective-fused paths
_FUSION_AB_TESTS = [
    "tests/test_operations.py", "tests/test_arithmetics.py",
    "tests/test_fuzz_chains.py", "tests/test_rounding_exp_trig.py",
    "tests/test_fusion.py",
    # reduction-heavy slice
    "tests/test_statistics.py", "tests/test_nan_reductions.py",
    "tests/test_statistics_distributed.py",
    # linalg-heavy slice (contraction-fused tapes: GEMM/einsum/tensordot
    # record_contract paths + the transformer forward that inherits them)
    "tests/test_linalg.py", "tests/test_linalg_more.py",
    "tests/test_linalg_gauss.py", "tests/test_transformer.py",
    # manipulations-heavy slice (resplit-fused tapes: record_resplit plus
    # the concatenate/reshape/stack alignment resplits that now record)
    "tests/test_manipulations.py", "tests/test_manips_distributed.py",
    # training-heavy slice (differentiable tapes: trace_step train steps,
    # packed-gradient transformer/DataParallel steps, batched optimizer
    # updates — fusion_step_flushes logged per test)
    "tests/test_trace_step.py", "tests/test_nn_optim_data.py",
]


# training-heavy subset for the quantized-collective A/B: the packed
# train-step surfaces (trace_step, the TransformerLM/DataParallel packed
# steps) plus the quant property/acceptance suite itself — the per-test
# HEAT_TPU_LADDER_STATS log carries quant_collectives/quant_bytes_saved
# so the A/B shows which tests actually moved quantized bytes
_QUANT_AB_TESTS = [
    "tests/test_trace_step.py", "tests/test_transformer.py",
    "tests/test_nn_optim_data.py", "tests/test_quant_collectives.py",
]


def _run_env_ab(env_key: str, legs_spec, tests, n: int,
                timeout: float, extra_env=None) -> dict:
    """Shared A/B mechanics for the env-flag gates: run ``tests`` once
    per ``(label, env value)`` leg, both legs must pass (``agree``).
    ``legs_spec`` is ``((label, value), (label, value))``;
    ``extra_env`` rides on BOTH legs (the hier A/B declares the tier
    factorization on both sides and toggles only the gate)."""
    result = {}
    for label, value in legs_spec:
        env = _env(n)
        env[env_key] = value
        if extra_env:
            env.update(extra_env)
        t0 = time.time()
        try:
            out = subprocess.run(
                [sys.executable, "-m", "pytest", *tests, "-q"],
                env=env, capture_output=True, text=True, timeout=timeout,
                cwd=_REPO)
        except subprocess.TimeoutExpired:
            result[label] = {"error": f"exceeded {timeout:.0f}s"}
            continue
        rec = {"rc": out.returncode, "wall_s": round(time.time() - t0, 1)}
        m = _SUMMARY_RE.search(out.stdout)
        if m:
            failed, passed, skipped, errors, dur = m.groups()
            rec.update(passed=int(passed), failed=int(failed or 0),
                       skipped=int(skipped or 0), errors=int(errors or 0))
        if out.returncode != 0:
            rec["tail"] = out.stdout.strip().splitlines()[-15:]
        result[label] = rec
    result["agree"] = all(
        result.get(label, {}).get("rc") == 0 for label, _ in legs_spec)
    return result


def run_quant_ab(n: int, timeout: float) -> dict:
    """``HEAT_TPU_QUANT_COLLECTIVES=0`` vs ``int8`` on the training-heavy
    subset: the quant leg must keep every packed-step test green (the
    codec may never change WHICH path runs, only its wire format, within
    the documented error contract), and the exact leg proves the escape
    hatch restores today's behavior — exit-gating, like the fusion A/B."""
    return _run_env_ab("HEAT_TPU_QUANT_COLLECTIVES",
                       (("exact", "0"), ("quant", "int8")),
                       _QUANT_AB_TESTS, n, timeout)


def run_fusion_ab(n: int, timeout: float) -> dict:
    """One suite leg with ``HEAT_TPU_FUSION=0`` vs ``1`` on a fast subset:
    any test that passes eager but fails fused (or vice versa) is semantic
    drift the fused engine introduced — exit-gating, like the serve smoke."""
    return _run_env_ab("HEAT_TPU_FUSION",
                       (("eager", "0"), ("fused", "1")),
                       _FUSION_AB_TESTS, n, timeout)


# chunk-pipelined collectives gate: the training-heavy subset (the paths
# whose packed collectives chunk) + the chunk contract module itself; the
# HEAT_TPU_LADDER_STATS log carries chunk_collectives/chunk_fallbacks so
# the A/B shows which tests actually dispatched chunked legs
_CHUNK_AB_TESTS = [
    "tests/test_trace_step.py", "tests/test_transformer.py",
    "tests/test_nn_optim_data.py", "tests/test_chunk_collectives.py",
]


def run_chunk_ab(n: int, timeout: float) -> dict:
    """``HEAT_TPU_FUSION_CHUNKS=1`` vs ``4`` on the training-heavy
    subset: the chunked leg must keep every packed-step test green
    (chunking may never change WHICH path runs or its values — the
    N-chunk emission is value-bitwise the unchunked plan per codec), and
    the CHUNKS=1 leg proves the default is bitwise today's behavior —
    exit-gating, like the fusion/quant A/Bs."""
    return _run_env_ab("HEAT_TPU_FUSION_CHUNKS",
                       (("unchunked", "1"), ("chunked", "4")),
                       _CHUNK_AB_TESTS, n, timeout)


# training-heavy subset for the hierarchical-collective A/B: the packed
# train-step surfaces plus the hier contract module itself — the
# per-test HEAT_TPU_LADDER_STATS log carries hier_collectives /
# hier_fallbacks so the A/B shows which tests actually decomposed
_HIER_AB_TESTS = [
    "tests/test_trace_step.py", "tests/test_transformer.py",
    "tests/test_nn_optim_data.py", "tests/test_hier_collectives.py",
]


def run_hier_ab(n: int, timeout: float) -> dict:
    """``HEAT_TPU_HIER=0`` vs ``1`` with the tier factorization
    ``(2, n/2)`` declared on BOTH legs: the hier leg must keep every
    packed-step test green (the decomposition may never change WHICH
    path runs — only reassociate its psums within the documented few-ulp
    freedom, with per-tier codecs carrying their own contract), and the
    HIER=0 leg proves the escape hatch restores today's flat behavior
    bitwise — exit-gating, like the fusion/quant/chunk A/Bs."""
    return _run_env_ab("HEAT_TPU_HIER",
                       (("flat", "0"), ("hier", "1")),
                       _HIER_AB_TESTS, n, timeout,
                       extra_env={"HEAT_TPU_MESH_TIERS": f"2,{n // 2}"})


# analytics slice for the fit-step A/B: the estimator surfaces whose
# fit()/predict hot loops now dispatch through fusion.fit_step_call
# (cluster family Lloyd iterations, Lasso coordinate sweeps, the Lanczos
# inner loop behind spectral, the KNN/GaussianNB assign programs) plus
# the fit contract module itself — the per-test HEAT_TPU_LADDER_STATS
# log carries fit_step_flushes/fit_step_fallbacks so the A/B shows which
# tests actually dispatched compiled iterations
_FIT_AB_TESTS = [
    "tests/test_analytics_fit.py", "tests/test_estimators.py",
    "tests/test_estimators_distributed.py", "tests/test_spatial_cluster.py",
    "tests/test_cluster_distributed.py", "tests/test_linalg.py",
]


def run_fit_ab(n: int, timeout: float) -> dict:
    """``HEAT_TPU_FUSION_FIT=0`` vs ``1`` on the analytics slice: the
    fused leg must keep every estimator test green (the tape-compiled
    step may never change WHICH mathematics runs — only pack its psums
    and donate its carries, within the documented numerics contract),
    and the FIT=0 leg proves the escape hatch restores the legacy step
    programs — exit-gating, like the fusion/quant/chunk/hier A/Bs."""
    return _run_env_ab("HEAT_TPU_FUSION_FIT",
                       (("legacy", "0"), ("fused", "1")),
                       _FIT_AB_TESTS, n, timeout)


_CHAOS_SITE_RE = re.compile(
    r"test_chaos_site\[([^\]]+)\]\s+(PASSED|FAILED|ERROR|SKIPPED)")


def run_chaos(n: int, timeout: float) -> dict:
    """The fault-injection chaos matrix (tests/test_faults.py) as a
    ladder stage: every registered site fired one-at-a-time (seeded)
    inside its designated workload, plus the fault-free counter-silence
    leg. Per-site verdicts land in the artifact next to the executable
    counters, so a regression names its failure DOMAIN, not just a test."""
    env = _env(n)
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, "-m", "pytest", "tests/test_faults.py",
             "-v", "--no-header"],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=_REPO)
    except subprocess.TimeoutExpired:
        return {"error": f"chaos matrix exceeded {timeout:.0f}s"}
    sites = {}
    for m in _CHAOS_SITE_RE.finditer(out.stdout):
        sites[m.group(1)] = m.group(2).lower()
    silence = None
    m = re.search(r"test_no_faults_armed_is_silent\s+"
                  r"(PASSED|FAILED|ERROR)", out.stdout)
    if m:
        silence = m.group(1).lower()
    rec = {"rc": out.returncode, "wall_s": round(time.time() - t0, 1),
           "sites": dict(sorted(sites.items())),
           "counter_silence": silence}
    m = _SUMMARY_RE.search(out.stdout)
    if m:
        failed, passed, skipped, errors, _dur = m.groups()
        rec.update(passed=int(passed), failed=int(failed or 0),
                   skipped=int(skipped or 0), errors=int(errors or 0))
    if out.returncode != 0:
        rec["tail"] = out.stdout.strip().splitlines()[-20:]
    return rec


def run_examples(n: int, timeout: float) -> list:
    """Smoke-run every examples/ script end-to-end on an n-device mesh."""
    results = []
    ex_dir = os.path.join(_REPO, "examples")
    for root, _dirs, files in os.walk(ex_dir):
        for f in sorted(files):
            if not f.endswith(".py") or f.startswith("_"):
                continue
            path = os.path.join(root, f)
            rel = os.path.relpath(path, _REPO)
            env = _env(n)
            env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
            env["PYTHONPATH"] = _REPO
            env["MPLBACKEND"] = "Agg"  # no display in CI
            env["HEAT_TPU_EXAMPLE_SMOKE"] = "1"  # examples shrink workloads
            t0 = time.time()
            try:
                out = subprocess.run(
                    [sys.executable, path], env=env, capture_output=True,
                    text=True, timeout=timeout, cwd=_REPO)
                rec = {"example": rel, "rc": out.returncode,
                       "wall_s": round(time.time() - t0, 1)}
                if out.returncode != 0:
                    rec["tail"] = (out.stderr or out.stdout).strip().splitlines()[-5:]
            except subprocess.TimeoutExpired:
                rec = {"example": rel, "rc": -1,
                       "error": f"exceeded {timeout:.0f}s"}
            results.append(rec)
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,2,4,8")
    ap.add_argument("--out", default="LADDER_r05.json")
    ap.add_argument("--timeout", type=float, default=3600.0,
                    help="per-device-count suite budget (s)")
    ap.add_argument("--examples", action="store_true",
                    help="also smoke-run examples/ on the largest mesh")
    ap.add_argument("--examples-only", action="store_true",
                    help="skip the suite; run only the examples smoke")
    ap.add_argument("--examples-timeout", type=float, default=600.0)
    ap.add_argument("--no-resplit-audit", action="store_true",
                    help="skip the collective_audit --resplit bounds check")
    ap.add_argument("--fusion-ab", dest="fusion_ab", action="store_true",
                    default=True,
                    help="run the HEAT_TPU_FUSION=0 vs 1 A/B subset "
                         "(default on)")
    ap.add_argument("--no-fusion-ab", dest="fusion_ab", action="store_false",
                    help="skip the fusion on/off semantic-drift A/B")
    ap.add_argument("--fusion-ab-timeout", type=float, default=900.0)
    ap.add_argument("--quant-ab", dest="quant_ab", action="store_true",
                    default=True,
                    help="run the HEAT_TPU_QUANT_COLLECTIVES=0 vs int8 "
                         "A/B on the training-heavy subset (default on)")
    ap.add_argument("--no-quant-ab", dest="quant_ab", action="store_false",
                    help="skip the quantized-collective A/B")
    ap.add_argument("--quant-ab-timeout", type=float, default=900.0)
    ap.add_argument("--chunk-ab", dest="chunk_ab", action="store_true",
                    default=True,
                    help="run the HEAT_TPU_FUSION_CHUNKS=1 vs 4 A/B on "
                         "the training-heavy subset (default on)")
    ap.add_argument("--no-chunk-ab", dest="chunk_ab", action="store_false",
                    help="skip the chunked-collective A/B")
    ap.add_argument("--chunk-ab-timeout", type=float, default=900.0)
    ap.add_argument("--hier-ab", dest="hier_ab", action="store_true",
                    default=True,
                    help="run the HEAT_TPU_HIER=0/1 A/B (tiers declared "
                         "on both legs) on the training-heavy subset "
                         "(default on)")
    ap.add_argument("--no-hier-ab", dest="hier_ab", action="store_false",
                    help="skip the hierarchical-collective A/B")
    ap.add_argument("--hier-ab-timeout", type=float, default=900.0)
    ap.add_argument("--fit-ab", dest="fit_ab", action="store_true",
                    default=True,
                    help="run the HEAT_TPU_FUSION_FIT=0 vs 1 A/B on the "
                         "cluster/lasso/lanczos analytics slice "
                         "(default on)")
    ap.add_argument("--no-fit-ab", dest="fit_ab", action="store_false",
                    help="skip the tape-compiled fit-step A/B")
    ap.add_argument("--fit-ab-timeout", type=float, default=900.0)
    ap.add_argument("--serve-smoke", dest="serve_smoke", action="store_true",
                    default=True, help="run the serving smoke (default on)")
    ap.add_argument("--no-serve-smoke", dest="serve_smoke",
                    action="store_false",
                    help="skip the serving executor smoke step")
    ap.add_argument("--decode-smoke", dest="decode_smoke",
                    action="store_true", default=True,
                    help="run the continuous-batching decode smoke "
                         "(default on)")
    ap.add_argument("--no-decode-smoke", dest="decode_smoke",
                    action="store_false",
                    help="skip the decode engine smoke step")
    ap.add_argument("--data-smoke", dest="data_smoke", action="store_true",
                    default=True,
                    help="run the tape-compiled data-engine smoke "
                         "(default on)")
    ap.add_argument("--no-data-smoke", dest="data_smoke",
                    action="store_false",
                    help="skip the data engine smoke step")
    ap.add_argument("--serve-soak", dest="serve_soak", action="store_true",
                    default=True,
                    help="run the open-loop overload soak with "
                         "p99-under-load verdicts (default on)")
    ap.add_argument("--no-serve-soak", dest="serve_soak",
                    action="store_false",
                    help="skip the serve soak stage")
    ap.add_argument("--serve-soak-timeout", type=float, default=600.0)
    ap.add_argument("--chaos", dest="chaos", action="store_true",
                    default=True,
                    help="run the fault-injection chaos matrix + "
                         "counter-silence check (default on)")
    ap.add_argument("--no-chaos", dest="chaos", action="store_false",
                    help="skip the chaos matrix stage")
    ap.add_argument("--chaos-timeout", type=float, default=600.0)
    args = ap.parse_args()

    ladder = []
    devices = [int(d) for d in args.devices.split(",")]
    if not args.examples_only:
        for n in devices:
            print(f"=== suite at {n} device(s) ===", flush=True)
            rec = run_suite(n, args.timeout)
            print(json.dumps(rec), flush=True)
            ladder.append(rec)

    artifact = {
        "date": time.strftime("%Y-%m-%d"),
        "command": f"python scripts/run_suite_ladder.py "
                   f"--devices {args.devices}",
        "note": "full suite per device count on a virtual CPU mesh "
                "(reference Jenkinsfile:24-33 analog). skip_reasons maps "
                "every pytest -rs skip reason to its occurrence count - "
                "the auditable skip inventory.",
        "ladder": ladder,
    }
    ex = []
    if args.examples or args.examples_only:
        n = max(devices)
        print(f"=== examples smoke at {n} device(s) ===", flush=True)
        ex = run_examples(n, args.examples_timeout)
        for r in ex:
            print(json.dumps(r), flush=True)
        artifact["examples"] = ex

    serve_bad = False
    if args.serve_smoke and not args.examples_only:
        # serving smoke: executor up -> 50 mixed-shape requests -> metrics
        # snapshot sanity, on the 4-device CPU mesh (scripts/serve_smoke.py)
        print("=== serve smoke (4 devices) ===", flush=True)
        env = _env(4)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["PYTHONPATH"] = _REPO
        try:
            out = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "scripts", "serve_smoke.py")],
                env=env, capture_output=True, text=True, timeout=600.0,
                cwd=_REPO)
            line = next((l for l in reversed(out.stdout.splitlines())
                         if l.startswith("{")), None)
            artifact["serve_smoke"] = (
                json.loads(line) if line
                else {"error": (out.stderr or "no output").strip()[-300:]})
            serve_bad = out.returncode != 0
        except subprocess.TimeoutExpired:
            artifact["serve_smoke"] = {"error": "serve smoke exceeded 600s"}
            serve_bad = True
        print(json.dumps({"serve_smoke_ok": not serve_bad}), flush=True)

    decode_bad = False
    if args.decode_smoke and not args.examples_only:
        # continuous-batching gate (ISSUE 15): mixed-length two-tenant
        # decode through the slot engine — parity vs generate(), zero
        # steady-state misses, pinned stats shape (scripts/decode_smoke.py)
        print("=== decode smoke (4 devices) ===", flush=True)
        env = _env(4)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["PYTHONPATH"] = _REPO
        try:
            out = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "scripts", "decode_smoke.py")],
                env=env, capture_output=True, text=True, timeout=600.0,
                cwd=_REPO)
            line = next((l for l in reversed(out.stdout.splitlines())
                         if l.startswith("{")), None)
            artifact["decode_smoke"] = (
                json.loads(line) if line
                else {"error": (out.stderr or "no output").strip()[-300:]})
            decode_bad = out.returncode != 0
        except subprocess.TimeoutExpired:
            artifact["decode_smoke"] = {"error": "decode smoke exceeded 600s"}
            decode_bad = True
        print(json.dumps({"decode_smoke_ok": not decode_bad}), flush=True)

    data_bad = False
    if args.data_smoke and not args.examples_only:
        # data-engine gate (ISSUE 17): groupby/top-k/percentile + the
        # streaming folds — numpy parity, percentile == sort path, zero
        # steady-state misses, zero fallbacks (scripts/data_smoke.py)
        print("=== data engine smoke (4 devices) ===", flush=True)
        env = _env(4)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["PYTHONPATH"] = _REPO
        try:
            out = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "scripts", "data_smoke.py")],
                env=env, capture_output=True, text=True, timeout=600.0,
                cwd=_REPO)
            line = next((l for l in reversed(out.stdout.splitlines())
                         if l.startswith("{")), None)
            artifact["data_smoke"] = (
                json.loads(line) if line
                else {"error": (out.stderr or "no output").strip()[-300:]})
            data_bad = out.returncode != 0
        except subprocess.TimeoutExpired:
            artifact["data_smoke"] = {"error": "data smoke exceeded 600s"}
            data_bad = True
        print(json.dumps({"data_smoke_ok": not data_bad}), flush=True)

    soak_bad = False
    if args.serve_soak and not args.examples_only:
        # overload-robustness gate (ISSUE 14): short deterministic
        # open-loop soak at 1x/2x estimated capacity with
        # serve.batch.dispatch=every:5 armed mid-soak — per-tenant
        # p50/p95/p99 + shed/breaker verdicts land in the artifact next
        # to chaos; any failed verdict fails the round
        print("=== serve soak (4 devices) ===", flush=True)
        env = _env(4)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["PYTHONPATH"] = _REPO
        try:
            out = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "scripts", "soak_serve.py"),
                 "--quick"],
                env=env, capture_output=True, text=True,
                timeout=args.serve_soak_timeout, cwd=_REPO)
            line = next((l for l in reversed(out.stdout.splitlines())
                         if l.startswith("{")), None)
            artifact["serve_soak"] = (
                json.loads(line) if line
                else {"error": (out.stderr or "no output").strip()[-300:]})
            soak_bad = out.returncode != 0
        except subprocess.TimeoutExpired:
            artifact["serve_soak"] = {
                "error": f"serve soak exceeded {args.serve_soak_timeout:.0f}s"}
            soak_bad = True
        print(json.dumps({
            "serve_soak_ok": not soak_bad,
            "verdicts": artifact["serve_soak"].get("verdicts", {})}),
            flush=True)

    chaos_bad = False
    if args.chaos and not args.examples_only:
        # failure-domain gate: every injection site must degrade
        # gracefully (seeded, one-at-a-time) and a fault-free pass must
        # tick zero faults.* counters (4-device mesh, like serve smoke)
        print("=== chaos matrix (4 devices) ===", flush=True)
        chaos = run_chaos(4, args.chaos_timeout)
        artifact["chaos"] = chaos
        chaos_bad = chaos.get("rc") != 0
        print(json.dumps({"chaos_ok": not chaos_bad,
                          "sites": chaos.get("sites", {})}), flush=True)

    fusion_bad = False
    if args.fusion_ab and not args.examples_only:
        # semantic-drift gate: the same fast, numerically-loaded subset
        # must pass with the fused engine ON and OFF (4-device mesh)
        print("=== fusion on/off A/B (4 devices) ===", flush=True)
        ab = run_fusion_ab(4, args.fusion_ab_timeout)
        artifact["fusion_ab"] = ab
        fusion_bad = not ab.get("agree", False)
        print(json.dumps({"fusion_ab_ok": not fusion_bad}), flush=True)

    quant_bad = False
    if args.quant_ab and not args.examples_only:
        # codec gate: the training-heavy subset must pass exact AND int8
        # (4-device mesh — with the ladder's 8-dev full suites this
        # covers the 4/8-dev acceptance pair)
        print("=== quant collectives A/B (4 devices) ===", flush=True)
        qab = run_quant_ab(4, args.quant_ab_timeout)
        artifact["quant_ab"] = qab
        quant_bad = not qab.get("agree", False)
        print(json.dumps({"quant_ab_ok": not quant_bad}), flush=True)

    hier_bad = False
    if args.hier_ab and not args.examples_only:
        # tier gate: the training-heavy subset must pass flat AND
        # hierarchically decomposed on the simulated (2, 2) two-host
        # factorization of the 4-device mesh
        print("=== hierarchical collectives A/B (4 devices) ===",
              flush=True)
        hab = run_hier_ab(4, args.hier_ab_timeout)
        artifact["hier_ab"] = hab
        hier_bad = not hab.get("agree", False)
        print(json.dumps({"hier_ab_ok": not hier_bad}), flush=True)

    fit_bad = False
    if args.fit_ab and not args.examples_only:
        # fit gate: the analytics slice must pass with the tape-compiled
        # fit steps ON and OFF (4-device mesh) — any leg disagreement is
        # semantic drift the compiled estimator iteration introduced
        print("=== fit-step (analytics) A/B (4 devices) ===", flush=True)
        fab = run_fit_ab(4, args.fit_ab_timeout)
        artifact["fit_ab"] = fab
        fit_bad = not fab.get("agree", False)
        print(json.dumps({"fit_ab_ok": not fit_bad}), flush=True)

    chunk_bad = False
    if args.chunk_ab and not args.examples_only:
        # chunk gate: the training-heavy subset must pass unchunked AND
        # 4-chunked (4-device mesh) — chunking is value-exact per codec,
        # so ANY leg disagreement is a leg-structure bug
        print("=== chunk collectives A/B (4 devices) ===", flush=True)
        cab = run_chunk_ab(4, args.chunk_ab_timeout)
        artifact["chunk_ab"] = cab
        chunk_bad = not cab.get("agree", False)
        print(json.dumps({"chunk_ab_ok": not chunk_bad}), flush=True)

    audit_bad = False
    if not (args.no_resplit_audit or args.examples_only):
        # re-check the reshard planner's collective bounds every round:
        # zero all-gather on split->split, bytes/temp <= the GSPMD
        # baseline, O(N/p) payload scaling (collective_audit --resplit)
        print("=== resplit collective audit (4,8 devices) ===", flush=True)
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        try:
            out = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "scripts", "collective_audit.py"),
                 "--resplit"],
                env=env, capture_output=True, text=True, timeout=900.0,
                cwd=_REPO)
            line = next((l for l in reversed(out.stdout.splitlines())
                         if l.startswith("{\"summary\"")), None)
            artifact["resplit_audit"] = (
                json.loads(line)["summary"] if line
                else {"error": (out.stderr or "no output").strip()[-300:]})
            audit_bad = out.returncode != 0
        except subprocess.TimeoutExpired:
            artifact["resplit_audit"] = {"error": "audit exceeded 900s"}
            audit_bad = True
        print(json.dumps({"resplit_audit_ok": not audit_bad}), flush=True)

    with open(os.path.join(_REPO, args.out), "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {args.out}")
    bad = ([r for r in ladder if r.get("rc") != 0]
           + [r for r in ex if r.get("rc") != 0])
    sys.exit(1 if bad or audit_bad or serve_bad or decode_bad or data_bad
             or soak_bad or fusion_bad or quant_bad or chunk_bad or hier_bad
             or fit_bad or chaos_bad else 0)


if __name__ == "__main__":
    main()
