#!/bin/sh
# Reference-style CI ladder (reference Jenkinsfile:24-33 runs the suite
# under mpirun -n 1..8): run the whole suite at 1, 2, 4 and 8 virtual
# devices. The suite is device-count-agnostic by construction; this proves
# it the way the reference proves MPI-size-agnosticism.
set -e
cd "$(dirname "$0")/.."
for n in 1 2 4 8; do
  echo "=== suite at $n device(s) ==="
  env -u PALLAS_AXON_POOL_IPS -u XLA_FLAGS JAX_PLATFORMS=cpu \
    HEAT_TPU_TEST_DEVICES=$n python -m pytest tests/ -x -q
done
