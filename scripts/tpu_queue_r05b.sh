#!/bin/bash
# Late-window bench-only queue: covers tunnel recoveries between the main
# queue's retirement and the driver's end-of-round bench. Only runs
# bench.py (persists BENCH_TPU_BEST.json for the driver's run to use) and
# stops LAUNCHING well before the driver window so nothing contends.
cd /root/repo || exit 1
LOG=/tmp/tpu_queue_r05b.log
OUT=/root/repo/tpu_queue_r05
mkdir -p "$OUT"
LAUNCH_DEADLINE=$(( $(date +%s) + 95 * 60 ))  # stop launching ~07:45 UTC

log() { echo "$(date -u +%H:%M:%S) $*" >> "$LOG"; }

probe_ok() {
  timeout 60 python -c "import jax; assert jax.default_backend() != 'cpu'" \
    >/dev/null 2>&1
}

log "late-window bench queue armed; launch deadline $(date -u -d @$LAUNCH_DEADLINE +%H:%M:%S) UTC"
while [ "$(date +%s)" -lt "$LAUNCH_DEADLINE" ]; do
  if [ -f "$OUT/bench.ok" ]; then
    log "bench already captured — retiring"; exit 0
  fi
  if probe_ok; then
    log "tunnel UP — running bench"
    timeout 2700 env HEAT_TPU_BENCH_REPLAY_MAX_AGE_H=0 \
      HEAT_TPU_BENCH_PROBE_BUDGET_S=120 python bench.py \
      > "$OUT/bench_late.out" 2> "$OUT/bench_late.err"
    rc=$?
    if [ $rc -eq 0 ] && grep -q '"backend": "tpu"' "$OUT/bench_late.out"; then
      touch "$OUT/bench.ok"; log "bench captured (TPU) — retiring"; exit 0
    fi
    log "bench rc=$rc without a TPU record; retrying after sleep"
    sleep 120
  else
    sleep 280
  fi
done
log "launch deadline reached — retiring clean of the driver window"
