#!/usr/bin/env python
"""Open-loop serve soak with p99-under-load acceptance (ISSUE 14).

Brings up a multi-tenant :class:`heat_tpu.serve.ServingExecutor` over the
launch mesh (the ladder/bench run it at 4 virtual CPU devices), registers
two tenants —

* ``hi``: priority 10, an SLO-derived deadline, a small share of traffic
  (the interactive tenant the acceptance bar protects), and
* ``lo``: priority 0, a queue quota + its own (looser) SLO (the bulk
  tenant overload is allowed to land on)

— estimates capacity closed-loop, then drives seeded open-loop Poisson
phases at 1× and 2× (optionally 4×) of it. The ≥2× phases run with a
fault plan armed (default ``serve.batch.dispatch=every:5`` — the bounded
dispatch-retry path absorbs every fire) and a mid-phase worker stall
that deterministically pushes the queue past its bound. A final breaker
phase opens the ``lo`` circuit under a persistent dispatch fault and
measures fast-fail latency against the dispatch-retry failure path.

Verdicts (exit 1 if any fails — the ladder/bench gate on this):

* ``worker_alive``   — the dispatch worker survived every phase;
* ``zero_untyped``   — every rejected request carried a *typed* serve
  error (no raw exception ever reached a client);
* ``hi_p99_le_slo``  — the high-priority tenant's p99 stayed within its
  SLO at 2× offered load;
* ``shed_skew``      — ≥90% of shed volume landed on the low-priority
  tenant (and sheds actually happened — an overload harness that never
  overloads is lying);
* ``breaker_fast``   — breaker-open fast-fail latency < 1/10 of the
  dispatch-retry failure path's;
* ``breaker_recovered`` — after cool-down, a half-open probe closed the
  breaker and the tenant serves again.

Prints ONE JSON line (phase reports + per-phase serve.* counter deltas +
verdicts).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python scripts/soak_serve.py --quick
"""

import argparse
import json
import sys
import time


def _counter_delta(before: dict, after: dict) -> dict:
    keys = set(before) | set(after)
    return {k: int(after.get(k, 0)) - int(before.get(k, 0))
            for k in sorted(keys)
            if k.startswith("serve.")
            and int(after.get(k, 0)) != int(before.get(k, 0))}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=4.0,
                    help="seconds per load phase")
    ap.add_argument("--loads", default="1,2",
                    help="offered-load multipliers over estimated capacity")
    ap.add_argument("--fault", default="serve.batch.dispatch=every:5",
                    help="fault plan armed during the >=2x phases "
                         "('' disarms)")
    ap.add_argument("--quick", action="store_true",
                    help="short deterministic form for the CI ladder / "
                         "bench stage (~10 s of phases)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-rps", type=float, default=2000.0,
                    help="offered-rate clamp (a python generator thread "
                         "cannot emit much past this)")
    args = ap.parse_args()
    if args.quick:
        args.duration = min(args.duration, 2.0)

    import numpy as np

    import heat_tpu as ht
    from heat_tpu.serve import (Pow2Buckets, ServeCircuitOpen, ServeConfig,
                                ServeMetrics, ServingExecutor, TenantLoad,
                                estimate_capacity, run_open_loop)
    from heat_tpu.serve.adapters import _centroid_assign_fn
    from heat_tpu.utils import faults
    from heat_tpu.utils import metrics as _pm

    comm = ht.get_comm()
    # a deliberately heavy-ish model (nearest-centroid over 8192 centers)
    # keeps capacity in the hundreds-to-low-thousands req/s band a python
    # open-loop generator can genuinely exceed (and below --max-rps, so
    # the 1x/2x multipliers scale for real instead of clamping)
    d, k = 256, 8192
    rng = np.random.default_rng(args.seed)
    fn = _centroid_assign_fn(
        rng.standard_normal((k, d)).astype(np.float32), comm)
    policy = Pow2Buckets(min_rows=comm.size, multiple_of=comm.size)
    cfg = ServeConfig(max_batch=16, max_wait_ms=2.0, queue_limit=128,
                      bucket_rows=policy)
    metrics = ServeMetrics()
    ex = ServingExecutor(fn, cfg, name="soak", cache_token=comm.cache_key,
                         metrics=metrics)
    record = {"devices": comm.size, "quick": bool(args.quick),
              "model": {"d": d, "k": k}, "phases": []}
    verdicts = {}
    try:
        ex.warmup((d,), np.float32, rows=(1, 2, 5, 9, 17, 33, 65))
        # n stays under queue_limit so the estimate itself never sheds
        cap = estimate_capacity(ex, (d,), rows=1, n=96, seed=args.seed)
        metrics.reset()
        # SLOs on the same monotonic clock everything else uses: hi gets
        # a bound generous against box noise (~30 batch service times,
        # and 3x the injected stall) but far below what sitting behind
        # the low-priority backlog would cost a FIFO executor
        stall_s = 0.35 if args.quick else 0.5
        batch_ms = 1e3 * cfg.max_batch / max(cap, 1e-9)
        slo_hi_ms = max(1000.0, 30.0 * batch_ms, 3e3 * stall_s)
        slo_lo_ms = 4.0 * slo_hi_ms
        ex.register_tenant("hi", priority=10, slo_ms=slo_hi_ms)
        ex.register_tenant("lo", priority=0,
                           max_queue=int(cfg.queue_limit * 3 // 4),
                           slo_ms=slo_lo_ms,
                           breaker_cooldown_s=0.25 if args.quick else 1.0)
        record["capacity_rps"] = round(cap, 1)
        record["slo_hi_ms"] = round(slo_hi_ms, 1)
        record["slo_lo_ms"] = round(slo_lo_ms, 1)

        hi_p99 = {}
        shed_hi = shed_lo = 0
        untyped = 0
        for mult_s in args.loads.split(","):
            mult = float(mult_s)
            total = min(mult * cap, args.max_rps)
            # hi rides a small absolute share so a stall backlog of hi
            # requests never overflows the whole queue bound
            hi_rate = min(0.25 * total, 60.0)
            lo_rate = max(total - hi_rate, 1.0)
            loads = [
                TenantLoad("hi", hi_rate, rows_mix=(1, 2)),
                TenantLoad("lo", lo_rate, rows_mix=(1, 2, 3)),
            ]
            overload = mult >= 2.0
            fault_plan = args.fault if (overload and args.fault) else None
            stall = ((0.3 * args.duration, stall_s) if overload else None)
            before = dict(_pm.counters())
            if fault_plan:
                with faults.inject(fault_plan):
                    rep = run_open_loop(
                        ex, loads, args.duration, (d,), seed=args.seed,
                        stall=stall)
            else:
                rep = run_open_loop(ex, loads, args.duration, (d,),
                                    seed=args.seed, stall=stall)
            rep["load_x"] = mult
            rep["fault"] = fault_plan
            rep["counters_delta"] = _counter_delta(before,
                                                   dict(_pm.counters()))
            record["phases"].append(rep)
            hi_p99[mult] = rep["tenants"]["hi"]["latency_ms"].get("p99")
            if overload:
                shed_hi += rep["tenants"]["hi"]["shed"]
                shed_lo += rep["tenants"]["lo"]["shed"]
            untyped += rep["totals"]["untyped"]

        # ---- breaker phase: open lo's circuit under a persistent fault,
        # measure fast-fail vs the dispatch-retry failure path ---------- #
        breaker = {}
        retry_lat = []
        x1 = rng.standard_normal((1, d)).astype(np.float32)
        with faults.inject("serve.batch.dispatch=every:1"):
            trips = ex.admission.DEFAULT_BREAKER_FAILURES
            for _ in range(trips):
                t0 = time.monotonic()
                try:
                    ex.submit(x1, tenant="lo").result(60)
                except Exception:
                    pass
                retry_lat.append(time.monotonic() - t0)
        fast_lat = []
        opened = False
        for _ in range(20):
            t0 = time.monotonic()
            try:
                ex.submit(x1, tenant="lo")
            except ServeCircuitOpen:
                opened = True
            fast_lat.append(time.monotonic() - t0)
        breaker["opened"] = opened
        breaker["retry_fail_ms"] = round(
            1e3 * sum(retry_lat) / max(len(retry_lat), 1), 3)
        fast_lat.sort()
        breaker["fast_fail_ms"] = round(
            1e3 * fast_lat[len(fast_lat) // 2], 4)
        breaker["ratio"] = round(
            breaker["fast_fail_ms"] / max(breaker["retry_fail_ms"], 1e-9),
            5)
        # recovery: cool-down elapses, the half-open probe dispatches
        # clean (faults disarmed) and closes the breaker
        time.sleep((ex.admission.get("lo").breaker_cooldown_s
                    or ex.admission.DEFAULT_BREAKER_COOLDOWN_S) + 0.05)
        try:
            ex.submit(x1, tenant="lo").result(60)
            breaker["recovered"] = (
                ex.admission.breaker_state("lo") == "closed")
        except Exception as exc:
            breaker["recovered"] = False
            breaker["recover_error"] = repr(exc)[:200]
        record["breaker"] = breaker

        two_x = next((m for m in hi_p99 if m >= 2.0), None)
        total_shed = shed_hi + shed_lo
        verdicts = {
            "worker_alive": ex.worker_alive,
            "zero_untyped": untyped == 0,
            "hi_p99_le_slo": (two_x is not None
                              and hi_p99[two_x] is not None
                              and hi_p99[two_x] <= slo_hi_ms),
            "shed_skew": (total_shed > 0
                          and shed_lo / total_shed >= 0.90),
            "breaker_fast": (breaker["opened"]
                             and breaker["ratio"] < 0.1),
            "breaker_recovered": bool(breaker.get("recovered")),
        }
        record["shed_hi_2x"] = shed_hi
        record["shed_lo_2x"] = shed_lo
    except Exception as exc:  # the harness itself broke: loud, typed
        record["error"] = repr(exc)[:400]
        verdicts = {"harness": False}
    finally:
        try:
            ex.close(drain=False, timeout=30)
        except Exception:
            pass
    record["verdicts"] = verdicts
    record["ok"] = bool(verdicts) and all(verdicts.values())
    print(json.dumps(record), flush=True)
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
