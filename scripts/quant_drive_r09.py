"""User-style drive of the quantized packed collectives (PR 9 / ISSUE 10).

Run on the 8-device virtual CPU mesh:

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/quant_drive_r09.py

Checks (each prints PASS/FAIL; exit 1 on any FAIL):
 1. baseline sanity: uneven arange sum exact (10 elems over 8 devs);
 2. quant flush: chain -> split-axis sum under bf16/int8 within the
    documented bounds, escape hatch bitwise, counters tick per dispatch;
 3. quant flush HLO: int8 leg lowers to a2a(s8)+a2a(u16 scales)+ag(u16),
    NO f32 all-reduce of the payload; wire bytes < exact;
 4. steady state: repeat chains per codec = zero new program-cache misses;
 5. transformer packed step: int8 wire-byte reduction >= 2x at 8 AND 4
    devices, grads within 1e-2, loss close; counters tick per step;
 6. DataParallel: quant step descends, losses track exact within 2e-2;
 7. DASO: packed capture bitwise vs legacy; int8 blend within 1e-2 and
    the sub-floor leaf exact;
 8. runtime_stats carries the quant keys and json-serializes.
"""

import json
import sys

import numpy as np

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.core import fusion
from heat_tpu.utils import hlo_audit, metrics

FAILS = []


def check(name, ok, detail=""):
    print(f"[{'PASS' if ok else 'FAIL'}] {name}  {detail}")
    if not ok:
        FAILS.append(name)


def rel(a, b):
    a = np.asarray(a).astype(np.float64)
    b = np.asarray(b).astype(np.float64)
    return np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-30)


n_dev = ht.MESH_WORLD.size
print(f"mesh: {n_dev} devices")

# 1. baseline sanity -------------------------------------------------- #
check("uneven arange sum exact",
      int(ht.arange(10, split=0).sum()) == 45)

# 2/3/4. quant flush path --------------------------------------------- #
rng = np.random.default_rng(0)
x = ht.array(rng.standard_normal((7, 1501)).astype(np.float32), split=0)


def chain(v):
    t = (v - 0.5) * 0.25
    t = ht.tanh(t) + 1.0
    t = t * t + t
    return t.sum(axis=0)


with fusion.quant_override(None):
    base = chain(x).numpy()
for codec, bound in (("bf16", 4e-3), ("int8", 1e-2)):
    with fusion.quant_override(codec):
        got = chain(x).numpy()
    check(f"flush {codec} within {bound}", rel(got, base) <= bound,
          f"rel={rel(got, base):.2e}")
with fusion.quant_override(None):
    again = chain(x).numpy()
check("escape hatch bitwise", np.array_equal(again, base))

c0 = int(metrics.counters().get("op_engine.quant_collectives", 0))
with fusion.quant_override("int8"):
    chain(x).numpy()
    chain(x).numpy()
c1 = int(metrics.counters().get("op_engine.quant_collectives", 0))
check("counters tick per dispatch (incl. cache hits)", c1 - c0 == 2)

fusion.reset()
with fusion.quant_override("int8"):
    fusion.capture_hlo(True)
    chain(x).numpy()
    hlo_q = fusion.last_hlo()
    fusion.capture_hlo(False)
cb = hlo_audit.collective_bytes(hlo_q, world=n_dev)["by_kind"]
check("int8 flush HLO: a2a + gather, no float payload all-reduce",
      cb.get("all-to-all", {}).get("count") == 2
      and cb.get("all-gather", {}).get("count") == 1
      and "all-reduce" not in cb, json.dumps(cb))

with fusion.quant_override("int8"):
    s0 = fusion.program_cache().stats()
    for _ in range(3):
        chain(x).numpy()
    s1 = fusion.program_cache().stats()
check("steady-state zero recompiles", s1["misses"] == s0["misses"])

# 5. transformer packed step ------------------------------------------ #
import optax

from heat_tpu.nn.transformer import TransformerLM, TransformerLMConfig

for ndev in ([n_dev, n_dev // 2] if n_dev >= 4 else [n_dev]):
    grid = ht.MeshGrid((ndev, 1, 1, 1), ("dp", "pp", "tp", "sp"),
                       devices=jax.devices()[:ndev])
    cfg = TransformerLMConfig(vocab=64, d_model=32, n_heads=4,
                              n_layers=2, d_ff=64)
    model = TransformerLM(grid, cfg)
    params = model.init(0)
    toks = model.shard_batch(rng.integers(0, cfg.vocab, (2 * ndev, 8))
                             .astype(np.int32))
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)
    with fusion.quant_override(None):
        hlo_e = model.make_train_step(tx).lower(
            params, opt_state, toks).compile().as_text()
        loss_e, grads_e = model.loss_and_grad_fn()(params, toks)
    with fusion.quant_override("int8"):
        step_q = model.make_train_step(tx)
        hlo_i = step_q.lower(params, opt_state, toks).compile().as_text()
        loss_q, grads_q = model.loss_and_grad_fn()(params, toks)
    be = hlo_audit.collective_bytes(hlo_e, world=ndev)["total_wire_bytes"]
    bq = hlo_audit.collective_bytes(hlo_i, world=ndev)["total_wire_bytes"]
    ge = np.concatenate([np.asarray(g).ravel() for g in
                         jax.tree_util.tree_leaves(grads_e)])
    gq = np.concatenate([np.asarray(g).ravel() for g in
                         jax.tree_util.tree_leaves(grads_q)])
    check(f"{ndev}-dev step wire reduction >= 2x", be / bq >= 2.0,
          f"{be}/{bq} = {be / bq:.2f}x")
    check(f"{ndev}-dev grads within 1e-2", rel(gq, ge) <= 1e-2,
          f"rel={rel(gq, ge):.2e}")
    check(f"{ndev}-dev loss close",
          abs(float(loss_q) - float(loss_e)) / abs(float(loss_e)) < 1e-2)
    c0 = int(metrics.counters().get("op_engine.quant_collectives", 0))
    with fusion.quant_override("int8"):
        params2, opt2, lval = step_q(params, opt_state, toks)
    c1 = int(metrics.counters().get("op_engine.quant_collectives", 0))
    check(f"{ndev}-dev step dispatch ticks quant counter", c1 - c0 == 1,
          f"loss={float(lval):.4f}")

# 6. DataParallel ------------------------------------------------------ #
try:
    import flax.linen as fnn

    from heat_tpu.nn.data_parallel import DataParallel
    from heat_tpu.optim import Adam, DataParallelOptimizer

    class MLP(fnn.Module):
        @fnn.compact
        def __call__(self, v):
            v = fnn.Dense(64)(v)
            v = fnn.tanh(v)
            return fnn.Dense(10)(v)

    X = rng.standard_normal((8 * n_dev, 32)).astype(np.float32)
    Y = rng.integers(0, 10, len(X)).astype(np.int32)

    def run(codec):
        net = DataParallel(MLP(), optimizer=DataParallelOptimizer(
            Adam(1e-3)))
        with fusion.quant_override(codec):
            return [net.step(X, Y) for _ in range(5)]

    le, lq = run(None), run("int8")
    check("DataParallel quant descends", lq[-1] < lq[0])
    check("DataParallel quant tracks exact",
          all(abs(a - b) / abs(a) <= 2e-2 for a, b in zip(le, lq)),
          f"exact={le[-1]:.4f} quant={lq[-1]:.4f}")
except ImportError:
    print("[skip] flax not available")

# 7. DASO -------------------------------------------------------------- #
if n_dev >= 4 and n_dev % 2 == 0:
    from heat_tpu.optim.dp_optimizer import DASO, Adam as DAdam

    def mkdaso():
        return DASO(DAdam(1e-3), total_epochs=4, local_size=n_dev // 2)

    p0 = {"w": np.linspace(-1, 1, 4096, dtype=np.float32).reshape(64, 64),
          "b": np.arange(64, dtype=np.float32)}
    d = mkdaso()
    repl = d.replicate(p0)
    repl = jax.tree_util.tree_map(
        lambda p: p * (1 + jnp.arange(d.slow_size).reshape(
            (-1,) + (1,) * (p.ndim - 1)) * 0.125), repl)
    with fusion.quant_override(None):
        packed = d._global_sync(repl)
    with fusion.step_override(False):
        legacy = mkdaso()._global_sync(repl)
    check("DASO packed capture bitwise vs legacy",
          all(np.array_equal(np.asarray(packed[k]), np.asarray(legacy[k]))
              for k in p0))
    with fusion.quant_override("int8"):
        q = mkdaso()._global_sync(repl)
    check("DASO int8 blend within 1e-2", rel(q["w"], packed["w"]) <= 1e-2)
    check("DASO sub-floor leaf exact",
          np.array_equal(np.asarray(q["b"]), np.asarray(packed["b"])))

# 8. runtime_stats ----------------------------------------------------- #
st = ht.runtime_stats()
fu = st["op_engine"]["fusion"]
check("runtime_stats quant keys",
      all(k in fu for k in ("quant_codec", "quant_min_numel",
                            "quant_collectives", "quant_bytes_saved",
                            "quant_fallbacks"))
      and fu["quant_collectives"] > 0 and fu["quant_bytes_saved"] > 0)
json.dumps(st)
check("runtime_stats json-serializable", True)

print(f"\n{'ALL PASS' if not FAILS else 'FAILURES: ' + ', '.join(FAILS)}")
sys.exit(1 if FAILS else 0)
