#!/usr/bin/env python
"""Two-process ``distributed_init`` drive (round-4 verdict #7).

Launches 2 REAL OS processes x 2 virtual CPU devices each, joined via
``jax.distributed.initialize`` (gRPC coordinator on localhost) into one
4-device world — the same code path a multi-host TPU pod takes over DCN,
scaled down to one machine. Each process then runs, SPMD-style, the
dryrun body's core on the global mesh:

  1. ``ht.distributed_init`` -> world communicator over 4 devices
  2. a sharded array op with a cross-process reduction (global sum)
  3. a 2x2 MeshGrid ("dcn" x "ici") and the DASO two-tier slow sync:
     bf16 blend over the "dcn" (cross-process) axis with real bytes
  4. a DP train-step shape: per-device grads psum'd across the world

Writes one JSON line per process; the parent asserts both agree and
emits MULTIPROC_r05.json.

Usage:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
            python scripts/multiprocess_drive.py
"""

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PORT = 18765


def worker(pid: int, nprocs: int) -> None:
    import numpy as np
    import jax

    sys.path.insert(0, _REPO)
    import heat_tpu as ht

    comm = ht.distributed_init(
        coordinator_address=f"localhost:{_PORT}",
        num_processes=nprocs, process_id=pid)
    world = {
        "process": pid,
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "comm_size": comm.size,
    }

    # ---- sharded op with a cross-process reduction --------------------
    n = 10  # uneven over 4 devices: exercises the padded canonical layout
    x = ht.arange(n, dtype=ht.float32, split=0)
    world["arange_sum"] = float(x.sum())

    # ---- two-tier grid: dcn (cross-process) x ici (intra-process) -----
    import jax.numpy as jnp

    grid = ht.MeshGrid((2, 2), ("dcn", "ici"))
    from jax.sharding import NamedSharding, PartitionSpec as P

    # per-device distinct payload, blended over the dcn axis (the DASO
    # slow tier's global sync direction) in bf16 — real cross-host bytes
    k = 256
    w = jnp.arange(4 * k, dtype=jnp.float32).reshape(4, k)
    w = jax.device_put(w, NamedSharding(grid.mesh, P(("dcn", "ici"))))

    from heat_tpu.core._compat import shard_map

    def blend(wblk):
        # bf16 on the wire, f32 math — DASO's global-sync recipe
        return jax.lax.pmean(wblk.astype(jnp.bfloat16), "dcn").astype(
            jnp.float32)

    out = jax.jit(shard_map(
        blend, mesh=grid.mesh, in_specs=P(("dcn", "ici")),
        out_specs=P(("dcn", "ici"))))(w)
    # a cross-process global array is not fetchable whole — verify this
    # process's ADDRESSABLE shards against the analytic bf16 dcn-mean
    wg = np.arange(4 * k, dtype=np.float32).reshape(4, k)
    expect = np.tile((wg[:2] + wg[2:]) / 2.0, (2, 1))
    ok = True
    for shard in out.addressable_shards:
        got = np.asarray(shard.data).reshape(-1, k)
        want = expect[shard.index[0]].reshape(-1, k)
        ok = ok and np.allclose(got, want, atol=4.0)  # bf16 wire precision
    world["daso_dcn_blend_ok"] = bool(ok and len(out.addressable_shards) > 0)

    # ---- DP train-step shape: grads psum'd across the world -----------
    def loss(p, xb):
        return jnp.sum((xb @ p) ** 2) / xb.shape[0]

    xb = ht.random.rand(8, 4, dtype=ht.float32, split=0)
    p0 = jnp.ones((4,), jnp.float32)
    g = jax.jit(jax.grad(loss))(p0, xb.larray)
    world["dp_grad_norm"] = round(float(jnp.linalg.norm(g)), 4)

    print("RESULT " + json.dumps(world), flush=True)


def main() -> None:
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        worker(int(sys.argv[2]), int(sys.argv[3]))
        return

    nprocs = 2
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=2")
    env["XLA_FLAGS"] = " ".join(flags).strip()

    procs = []
    for pid in range(nprocs):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             str(pid), str(nprocs)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=_REPO))
    results, errs = [], []
    deadline = time.time() + 600
    for p in procs:
        try:
            out, err = p.communicate(timeout=max(10, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            errs.append("timeout")
        line = next((l for l in out.splitlines()
                     if l.startswith("RESULT ")), None)
        if p.returncode == 0 and line:
            results.append(json.loads(line[len("RESULT "):]))
        else:
            errs.append(f"rc={p.returncode}: " +
                        (err or out).strip()[-300:])

    ok = (len(results) == nprocs
          and all(r["process_count"] == nprocs for r in results)
          and all(r["global_devices"] == 4 for r in results)
          and all(r["comm_size"] == 4 for r in results)
          and all(r["arange_sum"] == 45.0 for r in results)
          and all(r["daso_dcn_blend_ok"] for r in results)
          and len({r["dp_grad_norm"] for r in results}) == 1)
    artifact = {
        "note": "ht.distributed_init across 2 REAL processes x 2 virtual "
                "CPU devices (gRPC coordinator), running sharded ops, a "
                "2x2 dcn-x-ici MeshGrid with the DASO bf16 blend over the "
                "cross-process axis, and a DP gradient on the 4-device "
                "world mesh. SPMD: both processes execute the same program "
                "and must agree on every figure.",
        "date": time.strftime("%Y-%m-%d"),
        "command": "PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python "
                   "scripts/multiprocess_drive.py",
        "ok": ok,
        "results": results,
        "errors": errs,
    }
    print(json.dumps(artifact, indent=1))
    with open(os.path.join(_REPO, "MULTIPROC_r05.json"), "w") as f:
        json.dump(artifact, f, indent=1)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
