#!/usr/bin/env python
"""Serving smoke for the CI ladder: executor up → 50 requests → snapshot.

Brings up a :class:`heat_tpu.serve.ServingExecutor` over the launch mesh
(the ladder runs it at 4 virtual CPU devices), warms the bucket ladder,
fires 50 mixed-shape requests from 4 client threads, and sanity-checks the
metrics snapshot: everything answered, nothing shed, ZERO steady-state
program-cache misses, latency percentiles present, and
``ht.runtime_stats()`` carrying all three sections. Prints ONE JSON line;
exit 1 on any violation (the ladder fails the round).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python scripts/serve_smoke.py
"""

import json
import sys
import threading

import numpy as np


def main() -> int:
    import jax.numpy as jnp

    import heat_tpu as ht
    from heat_tpu.core._compat import shard_map
    from heat_tpu.serve import (Pow2Buckets, ServeConfig, ServeMetrics,
                                ServingExecutor)

    comm = ht.get_comm()
    d = 16
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((d, 8)).astype(np.float32))

    def local(x):
        return x @ w

    fn = (local if comm.size == 1 else shard_map(
        local, mesh=comm.mesh, in_specs=comm.spec(2, 0),
        out_specs=comm.spec(2, 0), check_vma=False))
    metrics = ServeMetrics()
    ex = ServingExecutor(
        fn, ServeConfig(max_batch=8, max_wait_ms=2.0, queue_limit=256,
                        bucket_rows=Pow2Buckets(min_rows=comm.size,
                                                multiple_of=comm.size)),
        name="smoke", cache_token=comm.cache_key, metrics=metrics)
    ex.warmup((d,), np.float32, rows=(1, 2, 5, 9, 17, 33, 65))
    misses0 = ex.program_cache.stats()["misses"]
    metrics.reset()  # percentiles describe traffic, not warmup compiles

    mix = (1, 2, 3, 5, 8, 13, 16, 4, 7, 9)
    reqs = [rng.standard_normal((r, d)).astype(np.float32)
            for r in mix * 5]  # 50 requests
    errors = []

    def client(t):
        try:
            futs = [ex.submit(x) for x in reqs[t::4]]
            for x, f in zip(reqs[t::4], futs):
                out = np.asarray(f.result(120))
                np.testing.assert_allclose(
                    out, x @ np.asarray(w), rtol=1e-5, atol=1e-6)
        except Exception as exc:
            errors.append(repr(exc))

    threads = [threading.Thread(target=client, args=(t,)) for t in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(300)
    ex.close()

    snap = metrics.snapshot(program_cache=ex.program_cache.stats())
    rt = ht.runtime_stats()
    checks = {
        "all_answered": snap["requests"] >= len(reqs),
        "no_errors": not errors and snap["errors"] == 0,
        "nothing_shed": snap["shed"] == 0,
        "zero_steady_misses":
            ex.program_cache.stats()["misses"] == misses0,
        "latency_present": snap["latency_ms"].get("p99") is not None,
        "runtime_stats_sections":
            all(k in rt for k in ("serve", "resharding", "op_engine")),
    }
    record = {
        "devices": comm.size,
        "requests": snap["requests"],
        "batches": snap["batches"],
        "p50_ms": round(snap["latency_ms"].get("p50", -1), 2),
        "p99_ms": round(snap["latency_ms"].get("p99", -1), 2),
        "batch_occupancy": round(
            snap["batch_occupancy"].get("mean", 0.0), 3),
        "program_cache": ex.program_cache.stats(),
        "checks": checks,
        "errors": errors[:3],
    }
    print(json.dumps(record), flush=True)
    return 0 if all(checks.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
