#!/usr/bin/env python
"""API parity audit against the reference Heat source tree.

Statically enumerates every name exported through ``__all__`` in the
reference (``/root/reference/heat`` by default, or ``--reference PATH``) and
checks it resolves in heat_tpu — flat namespace, linalg, spatial, random,
estimator subpackages, and ``heat_tpu.utils.data``. Also diffs the public
method surface of ``DNDarray``.

Run on an 8-device CPU mesh:

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/api_parity_check.py
"""

import argparse
import ast
import importlib
import os
import re
import sys


def reference_exports(ref_root: str):
    """name -> defining file, for every __all__ entry outside tests."""
    names = {}
    for root, _dirs, files in os.walk(ref_root):
        if "tests" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            try:
                tree = ast.parse(open(path).read())
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign):
                    for target in node.targets:
                        if isinstance(target, ast.Name) and target.id == "__all__":
                            try:
                                for v in ast.literal_eval(node.value):
                                    names.setdefault(v, os.path.relpath(path, ref_root))
                            except (ValueError, SyntaxError):
                                pass
    return names


def reference_dndarray_methods(ref_root: str):
    """DNDarray methods: class body + monkey-patched assignments."""
    methods = set()
    dnd = os.path.join(ref_root, "core", "dndarray.py")
    for node in ast.walk(ast.parse(open(dnd).read())):
        if isinstance(node, ast.ClassDef) and node.name == "DNDarray":
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods.add(item.name)
    core = os.path.join(ref_root, "core")
    for root, _dirs, files in os.walk(core):
        if "tests" in root:
            continue
        for fname in files:
            if fname.endswith(".py"):
                src = open(os.path.join(root, fname)).read()
                # plain and type-annotated assignments, including multi-line
                # annotations: DNDarray.x = ... / DNDarray.x: Callable[ ...
                for m in re.finditer(r"^DNDarray\.(\w+)\s*[:=]", src, re.M):
                    methods.add(m.group(1))
    return methods


def reference_signatures(ref_root: str, names):
    """name -> ordered parameter-name list, statically parsed. Only records
    defs found in the file that *exports* the name via ``__all__`` (the
    ``names`` map from :func:`reference_exports`), so same-named private
    helpers in other files cannot shadow the public signature."""
    sigs = {}
    for name, rel in names.items():
        path = os.path.join(ref_root, rel)
        try:
            tree = ast.parse(open(path).read())
        except (OSError, SyntaxError):
            continue
        for node in tree.body:
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == name
            ):
                a = node.args
                params = [p.arg for p in a.posonlyargs + a.args]
                if a.vararg:
                    params.append("*" + a.vararg.arg)
                params += [p.arg for p in a.kwonlyargs]
                if a.kwarg:
                    params.append("**" + a.kwarg.arg)
                sigs[name] = params
    return sigs


def signature_drift(names, ref_sigs, search_modules):
    """Compare reference parameter names against ours for shared callables.

    Only reports DROPPED reference parameters (we may add TPU-specific
    keywords freely; a missing reference kwarg breaks migrating user code).
    """
    import inspect

    drift = []
    for name in sorted(names):
        if name not in ref_sigs:
            continue
        obj = None
        for m in search_modules:
            obj = getattr(m, name, None)
            if callable(obj):
                break
        if obj is None or not callable(obj):
            continue
        try:
            mine = [
                ("*" if p.kind is inspect.Parameter.VAR_POSITIONAL else
                 "**" if p.kind is inspect.Parameter.VAR_KEYWORD else "") + p.name
                for p in inspect.signature(obj).parameters.values()
            ]
        except (ValueError, TypeError):
            continue
        mine_clean = {p.lstrip("*") for p in mine}
        has_kwargs = any(p.startswith("**") for p in mine)
        dropped = [
            p for p in ref_sigs[name]
            if not p.startswith("*")
            and p not in mine_clean
            and not has_kwargs
            and p != "self"
        ]
        if dropped:
            drift.append((name, dropped, ref_sigs[name], mine))
    return drift


def _load_or_build_manifest(ref_root: str, manifest_path: str, refresh: bool):
    """(names, methods, sigs), cached as JSON so the parity claim re-verifies
    in seconds without re-walking the reference tree (round-2 verdict weak
    #6). The cache keys on the reference version file's mtime+size."""
    import json

    ver = os.path.join(ref_root, "core", "version.py")
    try:
        st = os.stat(ver)
        stamp = [st.st_mtime, st.st_size]
    except OSError:
        stamp = None
    if not refresh and os.path.exists(manifest_path):
        try:
            blob = json.load(open(manifest_path))
            if blob.get("stamp") == stamp:
                return blob["names"], set(blob["methods"]), blob["sigs"]
        except (ValueError, KeyError, OSError):
            pass
    names = reference_exports(ref_root)
    methods = reference_dndarray_methods(ref_root)
    sigs = reference_signatures(ref_root, names)
    try:
        json.dump(
            {"stamp": stamp, "names": names, "methods": sorted(methods),
             "sigs": sigs},
            open(manifest_path, "w"), indent=1)
    except OSError:
        pass
    return names, methods, sigs


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--reference", default="/root/reference/heat")
    parser.add_argument(
        "--manifest",
        default=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "parity_manifest.json"))
    parser.add_argument("--refresh-manifest", action="store_true")
    args = parser.parse_args()

    # API introspection only — force the CPU backend before jax can touch a
    # (possibly wedged) accelerator tunnel; this was the >5-minute stall the
    # round-2 judge hit, not the reference walk
    if "jax" not in sys.modules:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    # invoked as a script: the repo root is not on sys.path
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)

    import heat_tpu as ht

    search_modules = [ht, ht.linalg, ht.spatial, ht.random]
    for sub in ("cluster", "classification", "naive_bayes", "regression", "graph"):
        search_modules.append(importlib.import_module(f"heat_tpu.{sub}"))
    search_modules.append(importlib.import_module("heat_tpu.utils.data"))
    search_modules.append(importlib.import_module("heat_tpu.nn"))
    search_modules.append(importlib.import_module("heat_tpu.optim"))

    names, ref_methods, ref_sigs = _load_or_build_manifest(
        args.reference, args.manifest, args.refresh_manifest)
    missing = {
        name: src
        for name, src in names.items()
        if not any(hasattr(m, name) for m in search_modules)
    }
    mine = set(dir(ht.DNDarray)) | set(vars(ht.arange(1)))
    # private helpers (mangled __name without trailing dunder) are reference
    # internals, not API; __torch_proxy__ is torch-backend-specific
    backend_specific = {"__torch_proxy__"}
    missing_methods = sorted(
        m
        for m in ref_methods
        if m not in mine
        and not (m.startswith("__") and not m.endswith("__"))
        and m not in backend_specific
    )

    print(f"reference __all__ exports: {len(names)}; unresolved: {len(missing)}")
    for name, src in sorted(missing.items(), key=lambda kv: kv[1]):
        print(f"  MISSING  {src:45s} {name}")
    print(f"reference DNDarray methods: {len(ref_methods)}; missing: {len(missing_methods)}")
    for m in missing_methods:
        print(f"  MISSING METHOD  DNDarray.{m}")

    drift = signature_drift(names, ref_sigs, search_modules)
    print(f"signature drift (dropped reference params): {len(drift)}")
    for name, dropped, ref_p, my_p in drift:
        print(f"  DRIFT  {name}: dropped {dropped}  (ref {ref_p} -> ours {my_p})")
    return 1 if (missing or missing_methods or drift) else 0


if __name__ == "__main__":
    sys.exit(main())
