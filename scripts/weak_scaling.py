#!/usr/bin/env python
"""Weak-scaling harness for the headline KMeans benchmark (BASELINE.json
north star: >=90% weak-scaling efficiency 1 -> 256 chips on v5e).

Per device count d in the ladder, a subprocess builds a d-device mesh —
the first d devices of the real backend, or a forced d-device virtual CPU
mesh — and measures the fused KMeans Lloyd step at n = BASE_N * d points
(weak scaling: constant work per device). Under perfect weak scaling
iter/s stays CONSTANT as devices and points grow together, so
efficiency(d) = iter_per_s(d) / iter_per_s(1).

On real TPU hardware run WITHOUT the CPU forcing (the ladder slices the
first d chips of the pod):

    python scripts/weak_scaling.py --devices 1,4,16,64,256

On the virtual CPU mesh (methodology check; numbers are NOT hardware
results — all virtual devices share the host's cores, so efficiency
reflects scheduler overhead, not ICI):

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/weak_scaling.py

Prints one JSON line per ladder step plus a final summary line.
"""

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def measure(n_points: int, d_feats: int, k: int, ndev: int,
            reps: int = 3) -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, _REPO)
    import heat_tpu as ht
    from heat_tpu.core.communication import TPUCommunication

    from heat_tpu.cluster.kmeans import _lloyd_fori_fn

    have = len(jax.devices())
    if ndev > have:
        return {"devices": ndev, "error": f"only {have} devices available"}
    comm = TPUCommunication(jax.devices()[:ndev])
    ht.random.seed(0)
    x = ht.random.rand(n_points, d_feats, dtype=ht.float32, split=0,
                       comm=comm)
    cents = jnp.asarray(
        np.random.default_rng(0).random((k, d_feats), dtype=np.float32))
    run = _lloyd_fori_fn(x.larray.shape, jnp.dtype(jnp.float32), k, n_points,
                         comm)

    def timed(iters):
        t0 = time.perf_counter()
        _, inertia, _ = run(x.larray, cents, iters)
        float(np.asarray(inertia))
        return time.perf_counter() - t0

    timed(1)
    lo, hi = 2, 12
    # >=3 independent repetitions of the full differenced measurement
    # (round-4 verdict #4: single-run ladder numbers on a shared-core host
    # carry no variance information and cannot support scaling claims)
    rates = []
    for _ in range(max(1, reps)):
        t_lo = min(timed(lo) for _ in range(3))
        t_hi = min(timed(hi) for _ in range(3))
        per = (t_hi - t_lo) / (hi - lo)
        if per <= 0:
            per = t_hi / hi
        rates.append(1.0 / per)
    mean = sum(rates) / len(rates)
    var = sum((r - mean) ** 2 for r in rates) / max(1, len(rates) - 1)
    return {"devices": comm.size, "n": n_points,
            "kmeans_iter_per_s": round(mean, 3),
            "kmeans_iter_per_s_reps": [round(r, 3) for r in rates],
            "kmeans_iter_per_s_std": round(var ** 0.5, 3)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,2,4,8",
                    help="comma-separated mesh-size ladder")
    ap.add_argument("--base-n", type=int, default=1 << 18,
                    help="points per device (weak scaling)")
    ap.add_argument("--feats", type=int, default=64)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--reps", type=int, default=3,
                    help="independent measurement repetitions per step")
    ap.add_argument("--measure", type=int, default=0,
                    help="(internal) run one measurement at this point count")
    ap.add_argument("--measure-devices", type=int, default=0,
                    help="(internal) mesh size for the measurement")
    args = ap.parse_args()

    if args.measure:
        print(json.dumps(measure(args.measure, args.feats, args.k,
                                 args.measure_devices, args.reps)))
        return

    ladder = [int(d) for d in args.devices.split(",")]
    results = []
    for d in ladder:
        env = dict(os.environ)
        if env.get("JAX_PLATFORMS") == "cpu" or not env.get(
                "PALLAS_AXON_POOL_IPS"):
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if "host_platform_device_count" not in f]
            flags.append(f"--xla_force_host_platform_device_count={d}")
            env["XLA_FLAGS"] = " ".join(flags).strip()
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--measure", str(args.base_n * d),
                 "--measure-devices", str(d),
                 "--feats", str(args.feats), "--k", str(args.k),
                 "--reps", str(args.reps)],
                env=env, capture_output=True, text=True, timeout=1800,
                cwd=_REPO)
        except subprocess.TimeoutExpired:
            print(json.dumps({"devices": d, "error": "timeout after 1800s"}))
            continue
        line = next((l for l in reversed(out.stdout.splitlines())
                     if l.startswith("{")), None)
        if line is None:
            print(json.dumps({"devices": d, "error":
                              (out.stderr or "no output").strip()[-300:]}))
            continue
        rec = json.loads(line)
        results.append(rec)
        print(json.dumps(rec))

    if results and results[0].get("kmeans_iter_per_s"):
        base = results[0]["kmeans_iter_per_s"]
        print(json.dumps({
            "summary": "weak_scaling_efficiency_vs_1dev",
            "base_iter_per_s": base,
            "efficiency": {
                str(r["devices"]):
                    round(r["kmeans_iter_per_s"] / base, 3)
                for r in results
            },
            "efficiency_std": {
                str(r["devices"]):
                    round(r.get("kmeans_iter_per_s_std", 0.0) / base, 3)
                for r in results
            },
            "note": "perfect weak scaling keeps iter/s constant as devices "
                    "and points grow together; efficiency = iter/s(d) / "
                    "iter/s(1); efficiency_std propagates each step's "
                    "repetition std against the 1-device mean",
        }))


if __name__ == "__main__":
    main()
