#!/usr/bin/env python
"""Single-chip large-n KMeans probe toward BASELINE.json's 100M x 64 config.

With round-4's half-precision storage (bf16 HBM reads, f32 accumulation)
100M x 64 is 12.8 GB — inside one v5e's 16 GB HBM, where the f32 path
(25.6 GB) never fit. Stages up through n = 2^26 (67M) before attempting
the full 100M so an OOM at the target size still leaves a recorded figure.
Run on the real chip from the repo root:

    python scripts/kmeans_100m_probe.py

Prints one JSON line per stage ({n, dtype, kmeans_iter_per_s} or an error).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from heat_tpu.cluster.kmeans import _lloyd_fori_fn
from heat_tpu.core.communication import get_comm


def measure(n: int, d: int = 64, k: int = 8) -> float:
    comm = get_comm()
    pad = (-n) % comm.size
    gen = jax.jit(
        lambda key: jax.random.uniform(key, (n + pad, d), jnp.bfloat16),
        out_shardings=comm.sharding(2, 0))
    xp = gen(jax.random.PRNGKey(0))
    jax.block_until_ready(xp)
    cents = jnp.asarray(
        np.random.default_rng(0).random((k, d), dtype=np.float32))
    run = _lloyd_fori_fn(xp.shape, jnp.dtype(xp.dtype), k, n, comm)

    def timed(iters: int) -> float:
        t0 = time.perf_counter()
        _, inertia, _ = run(xp, cents, iters)
        float(np.asarray(inertia))
        return time.perf_counter() - t0

    timed(1)
    lo, hi = 2, 12
    t_lo = min(timed(lo) for _ in range(3))
    t_hi = min(timed(hi) for _ in range(3))
    per = (t_hi - t_lo) / (hi - lo)
    if per <= 0:
        per = t_hi / hi
    return 1.0 / per


def main() -> None:
    for n in (1 << 24, 1 << 26, 100_000_000):
        try:
            ips = measure(n)
            print(json.dumps({"n": n, "dtype": "bfloat16",
                              "kmeans_iter_per_s": round(ips, 3)}),
                  flush=True)
        except Exception as exc:  # keep earlier stage results on OOM
            print(json.dumps({"n": n, "dtype": "bfloat16",
                              "error": str(exc)[:200]}), flush=True)
            break


if __name__ == "__main__":
    main()
