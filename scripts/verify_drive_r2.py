"""User-style end-to-end drive of the round-2 surfaces (verify skill)."""
import numpy as np
import jax
import jax.numpy as jnp

import heat_tpu as ht

rng = np.random.default_rng(0)

# minimum slice + uneven shapes
assert int(ht.arange(10, split=0).sum().item()) == 45
assert int(ht.arange(8 * 6 + 3, split=0).sum().item()) == sum(range(51))

# sort: both directions, uneven, ties, 2-D
for n in (10, 29, 101):
    d = rng.integers(0, 7, n).astype(np.float32)
    v, i = ht.sort(ht.array(d, split=0))
    assert np.array_equal(np.asarray(v.numpy()), np.sort(d))
    assert np.array_equal(np.sort(np.asarray(i.numpy())), np.arange(n))
    vd, _ = ht.sort(ht.array(d, split=0), descending=True)
    assert np.array_equal(np.asarray(vd.numpy()), np.sort(d)[::-1])
m = rng.normal(size=(13, 9)).astype(np.float32)
v2, _ = ht.sort(ht.array(m, split=1), axis=1)
assert np.allclose(np.asarray(v2.numpy()), np.sort(m, axis=1))

# unique + inverse + counts round trip
d = rng.integers(0, 11, 83).astype(np.int64)
u, inv, cnt = ht.unique(ht.array(d, split=0), return_inverse=True, return_counts=True)
nu, ninv, ncnt = np.unique(d, return_inverse=True, return_counts=True)
assert np.array_equal(np.asarray(u.numpy()), nu)
assert np.array_equal(nu[np.asarray(inv.numpy())], d)
assert np.array_equal(np.asarray(cnt.numpy()), ncnt)

# NaN/inf discipline (round-2 review): sort keeps NaNs, unique keeps each
# NaN, percentile propagates NaN
nd = np.array([1.0, np.nan, 2.0, np.inf, -np.inf, 3.0], np.float32)
nv, nidx = ht.sort(ht.array(nd, split=0))
assert np.array_equal(np.asarray(nv.numpy()), np.sort(nd), equal_nan=True)
assert np.array_equal(np.sort(np.asarray(nidx.numpy())), np.arange(6))
nu = np.asarray(ht.unique(ht.array(nd, split=0)).numpy())
assert nu.shape == (6,) and np.isnan(nu[-1])
assert np.isnan(float(ht.median(ht.array(nd, split=0)).item()))

# percentile / median crossing the split axis
d = rng.normal(size=97).astype(np.float32)
x = ht.array(d, split=0)
assert abs(float(ht.median(x).item()) - float(np.median(d))) < 1e-5
assert np.allclose(np.asarray(ht.percentile(x, [10, 50, 90]).numpy()),
                   np.percentile(d, [10, 50, 90]), rtol=1e-5)
m = rng.normal(size=(19, 11)).astype(np.float32)
assert np.allclose(np.asarray(ht.percentile(ht.array(m, split=0), 40, axis=0).numpy()),
                   np.percentile(m, 40, axis=0), rtol=1e-4, atol=1e-6)

# DASO two-tier: diverged replicas reconcile
comm = ht.get_comm()
daso = ht.optim.DASO(ht.optim.SGD(0.1), total_epochs=2, comm=comm,
                     local_size=max(1, comm.size // 4))
if daso.slow_size > 1:
    base = {"w": jnp.ones((4, 3), jnp.float32)}
    rep = daso.replicate(base)
    offs = jnp.arange(daso.slow_size, dtype=jnp.float32).reshape(-1, 1, 1)
    rep = jax.tree_util.tree_map(lambda p: p + offs * 0.5, rep)
    synced = daso._global_sync(rep)
    spread0 = float(jnp.max(rep["w"][-1] - rep["w"][0]))
    spread1 = float(jnp.max(synced["w"][-1] - synced["w"][0]))
    assert 0.4 * spread0 < spread1 < 0.6 * spread0, (spread0, spread1)

# DataParallelMultiGPU end-to-end training
if comm.size >= 4 and comm.size % 2 == 0:
    import flax.linen as fnn

    class MLP(fnn.Module):
        @fnn.compact
        def __call__(self, x):
            return fnn.Dense(4)(fnn.relu(fnn.Dense(16)(x)))

    daso2 = ht.optim.DASO(ht.optim.SGD(0.05), total_epochs=3, comm=comm,
                          local_size=comm.size // 2)
    net = ht.nn.DataParallelMultiGPU(MLP(), daso2, comm=comm)
    X = rng.normal(size=(8 * comm.size, 8)).astype(np.float32)
    Y = rng.integers(0, 4, 8 * comm.size).astype(np.int32)
    losses = [net.step(X, Y) for _ in range(6)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses

# resplit roundtrip + matmul + TSQR still healthy after the refactor
a = rng.normal(size=(12, 7)).astype(np.float32)
xa = ht.array(a, split=0)
assert np.allclose(np.asarray(xa.resplit(1).resplit(0).numpy()), a, atol=1e-6)
b = rng.normal(size=(7, 5)).astype(np.float32)
assert np.allclose(np.asarray((xa @ ht.array(b, split=0)).numpy()), a @ b, atol=1e-4)
tall = rng.normal(size=(64, 8)).astype(np.float32)
q, r = ht.linalg.qr(ht.array(tall, split=0))
assert np.abs(np.asarray(q.numpy()) @ np.asarray(r.numpy()) - tall).max() < 1e-4
print("verify drive r2: ALL OK")
