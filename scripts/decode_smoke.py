#!/usr/bin/env python
"""Continuous-batching decode smoke for the CI ladder (ISSUE 15).

Brings up a :class:`heat_tpu.serve.DecodeEngine` over the launch mesh
(the ladder runs it at 4 virtual CPU devices), warms the prefill ladder +
the one decode-step executable, drives a seeded mixed-length two-tenant
workload through it, and checks the engine contract end to end:

* every request answered, worker alive, engine ends empty;
* greedy tokens bitwise-equal to ``TransformerLM.generate()`` for a
  sampled subset of requests;
* ZERO steady-state program-cache misses after warmup;
* ``ht.runtime_stats()["serve"]["decode"]`` present with the pinned
  shape and non-zero steps/tokens.

Prints ONE JSON line; exit 1 on any violation (the ladder fails the
round).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python scripts/decode_smoke.py
"""

import json
import sys

import numpy as np


def main() -> int:
    import heat_tpu as ht
    from heat_tpu.nn.transformer import TransformerLM, TransformerLMConfig
    from heat_tpu.serve import DecodeConfig, DecodeEngine

    n = ht.get_comm().size
    tp = 2 if n % 2 == 0 else 1
    grid = ht.MeshGrid((n // tp, 1, tp, 1), ("dp", "pp", "tp", "sp"))
    cfg = TransformerLMConfig(vocab=61, d_model=32, n_heads=4, n_layers=2,
                              d_ff=64)
    model = TransformerLM(grid, cfg)
    params = model.init(2)
    rng = np.random.default_rng(0)

    eng = DecodeEngine(model, params,
                       DecodeConfig(slots=2 * model.dp_world,
                                    max_seq_len=64),
                       name="decode-smoke")
    eng.register_tenant("interactive", priority=10)
    eng.register_tenant("batch", priority=0)
    eng.warmup()
    misses0 = eng.program_cache.stats()["misses"]

    n_req = 24
    reqs = []
    for i in range(n_req):
        s0 = int(rng.integers(3, 13))
        mn = int(rng.integers(2, 12))
        prompt = rng.integers(0, cfg.vocab, (s0,)).astype(np.int32)
        tenant = "interactive" if i % 3 else "batch"
        reqs.append((prompt, mn, tenant))
    futs = [eng.submit(p, m, tenant=t) for p, m, t in reqs]
    outs = []
    errors = []
    for f in futs:
        try:
            outs.append(f.result(300))
        except Exception as exc:
            errors.append(repr(exc))
            outs.append(None)

    # parity spot-check: every 5th request vs the monolithic generate()
    parity_ok = True
    for i in range(0, n_req, 5):
        prompt, mn, _t = reqs[i]
        if outs[i] is None:
            parity_ok = False
            continue
        B = model.dp_world
        want = np.asarray(model.generate(params, np.tile(prompt, (B, 1)),
                                         mn))[0]
        if not np.array_equal(outs[i], want):
            parity_ok = False

    st = eng.stats()
    steady_misses = eng.program_cache.stats()["misses"] - misses0
    rt = ht.runtime_stats()["serve"]["decode"]
    eng.close()

    verdicts = {
        "all_answered": not errors and all(o is not None for o in outs),
        "parity": parity_ok,
        "zero_steady_misses": steady_misses == 0,
        "worker_survived": st["live"] == 0 and st["queue_depth"] == 0,
        "stats_shape": (set(rt) == {"slots", "occupancy", "prefills",
                                    "decode_steps", "tokens_out",
                                    "decode_fallbacks"}
                        and rt["decode_steps"] > 0
                        and rt["tokens_out"] > 0),
        "no_fallbacks": st["decode_fallbacks"] == 0,
    }
    record = {
        "devices": n,
        "grid": {"dp": n // tp, "tp": tp},
        "requests": n_req,
        "steady_misses": steady_misses,
        "prefills": st["prefills"],
        "decode_steps": st["decode_steps"],
        "tokens_out": st["tokens_out"],
        "mean_occupancy": round(st["occupancy"], 3),
        "errors": errors[:3],
        "verdicts": verdicts,
        "ok": all(verdicts.values()),
    }
    print(json.dumps(record), flush=True)
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
