#!/bin/bash
# Round-17 TPU recovery queue (re-armed from tpu_queue_r15.sh — the tunnel
# stayed down through round 16). Probes every ~5 min and on recovery runs
# the round's owed TPU work, one job at a time, never killed mid-compile
# (generous timeouts only — a >1h hang means the tunnel died again
# anyway). NEW this round: bench.py now includes the data_* stage — the
# tape-compiled data engine (groupby 10M rows through the one-packed-
# all-reduce program, top-64 via the k-sized exchange, and the EXACT
# streaming quantile over a 100M-element HDF5 stream) gets REAL-chip
# numbers automatically on any tunnel-up window: on TPU the bisection
# rounds' (m,) count psums ride the ICI instead of the host-loopback
# mesh, and the segment-scatter partials hit real HBM bandwidth.
#
# Queue (first post-incident run must be tiny):
#   1. tpu_kernel_probe.py bisect   (tiny, validates the chip end-to-end)
#   2. bench.py                     (TPU record -> BENCH_TPU_BEST.json:
#                                    m=8192 matmul, bf16 kmeans, transformer
#                                    MFU — now including the data_* stage's
#                                    groupby/top-k rows/s and streaming-
#                                    quantile throughput alongside the
#                                    decode/analytics/fusion/serve stages)
#   3. kmeans_100m_probe.py         (single-chip 100M x 64 Lloyd staging)
#   4. tpu_kernel_probe.py ab       (fused KMeans kernel vs XLA, bench size)
#   5. tpu_kernel_probe.py cdist_ab (fused distance tile vs XLA ring step)
#   6. tpu_kernel_probe.py flash_ab (flash attention fwd+bwd vs XLA)
#
# Retires itself at the deadline (driver's end-of-round bench must not be
# contended) or once the full queue has succeeded.

cd /root/repo || exit 1
LOG=/tmp/tpu_queue_r17.log
OUT=/root/repo/tpu_queue_r17
mkdir -p "$OUT"
DEADLINE=$(( $(date +%s) + 9 * 3600 ))

log() { echo "$(date -u +%H:%M:%S) $*" >> "$LOG"; }

probe_ok() {
  timeout 60 python -c "import jax; assert jax.default_backend() != 'cpu'" \
    >/dev/null 2>&1
}

run_job() {  # $1 marker name, $2 budget seconds, rest: command
  local name=$1 budget=$2; shift 2
  [ -f "$OUT/$name.ok" ] && return 0
  log "job $name starting (budget ${budget}s): $*"
  timeout "$budget" "$@" > "$OUT/$name.out" 2> "$OUT/$name.err"
  local rc=$?
  if [ $rc -eq 0 ]; then
    touch "$OUT/$name.ok"; log "job $name OK"
  else
    log "job $name rc=$rc (tail): $(tail -c 300 "$OUT/$name.err" | tr '\n' ' ')"
  fi
  return $rc
}

log "queue armed; deadline $(date -u -d @$DEADLINE +%H:%M:%S) UTC"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe_ok; then
    log "tunnel UP — running queue"
    run_job bisect 600 python scripts/tpu_kernel_probe.py bisect || { sleep 120; continue; }
    # bench: replay disabled (a stale-record replay or CPU fallback must not
    # satisfy the queue's "fresh TPU capture" job); short probe budget (the
    # tunnel was just probed up); timeout > bench's own worst case so the
    # outer timeout never kills a live measurement mid-compile.
    if [ ! -f "$OUT/bench.ok" ]; then
      run_job bench 5400 env HEAT_TPU_BENCH_REPLAY_MAX_AGE_H=0 \
        HEAT_TPU_BENCH_PROBE_BUDGET_S=120 python bench.py
      if [ -f "$OUT/bench.ok" ] && ! grep -q '"backend": "tpu"' "$OUT/bench.out"; then
        rm "$OUT/bench.ok"; log "bench produced no TPU-backed record — will retry"
      fi
    fi
    run_job kmeans100m 2700 python scripts/kmeans_100m_probe.py
    run_job ab 2700 python scripts/tpu_kernel_probe.py ab
    run_job cdist_ab 2700 python scripts/tpu_kernel_probe.py cdist_ab
    run_job flash_ab 2700 python scripts/tpu_kernel_probe.py flash_ab
    if ls "$OUT"/bench.ok "$OUT"/kmeans100m.ok "$OUT"/ab.ok \
        "$OUT"/cdist_ab.ok "$OUT"/flash_ab.ok >/dev/null 2>&1; then
      log "queue complete — retiring"; exit 0
    fi
    sleep 120
  else
    sleep 290
  fi
done
log "deadline reached — retiring so the driver's bench is uncontended"
