#!/usr/bin/env python
"""Interpret-mode traffic proof for the Pallas kernels (round-4 verdict #1
fallback deliverable: the tunnel-independent half of the Pallas story).

For each kernel this script emits:

1. **Numerics**: the kernel (interpret mode — same kernel code Mosaic
   compiles) matches its jnp/XLA reference implementation.
2. **HBM traffic accounting**: bytes each grid step DMAs in/out, derived
   from the kernels' OWN BlockSpecs and grids (the same shapes the
   wrappers pass to ``pallas_call``), vs the bytes the multi-pass XLA path
   moves for the same result. This is the measurable basis of the
   projected speedups for the bandwidth-bound workloads:

   - KMeans Lloyd step: the fused kernel streams X once per iteration;
     the XLA path's separate fusions read it twice (PERF_r04.md roofline:
     65.6% HBM utilization at bench size -> a 1-pass kernel is worth up
     to ~2x, bounded by the non-X terms).
   - cdist: the fused tile writes each distance block once; the XLA
     expansion materializes the squared-distance matrix, re-reads it for
     the sqrt, and writes again — 3x the output-matrix traffic.
   - flash attention: O(S*D + S) per-block intermediates instead of the
     dense path's O(Sq*Sk) probability matrix in HBM.

Block revisits with constant index maps (centroids, the resident Q tile)
are counted at both bounds: ``*_hbm_worst`` assumes every grid step
re-DMAs them, ``*_hbm_best`` assumes Mosaic keeps them VMEM-resident.
X-pass claims hold at either bound.

Writes PALLAS_TRAFFIC_r05.json. Run:
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python scripts/pallas_traffic_proof.py
"""

import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import heat_tpu as ht  # noqa: E402  (configures x64 + matmul precision)
from heat_tpu.core import pallas_kernels as pk  # noqa: E402


def _bytes(shape, dtype) -> int:
    return int(np.prod(shape)) * np.dtype(dtype).itemsize


def kmeans_proof(n=4096, d=64, k=8, block_rows=1024) -> dict:
    x = np.random.default_rng(0).random((n, d), np.float32)
    c = np.random.default_rng(1).random((k, d), np.float32)
    mask = np.ones((n, 1), np.float32)

    # numerics: kernel (interpret) vs the jnp Lloyd partials
    sums, counts, inertia = pk.kmeans_step_tile(
        jnp.asarray(x), jnp.asarray(c), jnp.asarray(mask),
        block_rows=block_rows)
    d2 = ((x[:, None, :] - c[None, :, :]) ** 2).sum(-1)
    labels = d2.argmin(1)
    ref_sums = np.zeros((k, d), np.float32)
    np.add.at(ref_sums, labels, x)
    ref_counts = np.bincount(labels, minlength=k).astype(np.float32)
    ok = (np.allclose(np.asarray(sums), ref_sums, rtol=2e-2, atol=2e-2)
          and np.allclose(np.asarray(counts), ref_counts)
          and np.isclose(float(inertia), float(d2.min(1).sum()), rtol=2e-2))

    # traffic: from the kernel's grid/BlockSpecs (mirrors _kmeans_step_tile)
    kp = 128  # k rounded up to the lane width
    bm = block_rows
    steps = (n + bm - 1) // bm
    f32 = np.dtype(np.float32).itemsize
    in_x = steps * bm * d * f32          # X tile: fresh block every step
    in_c = steps * kp * d * f32          # centroids: constant index map
    in_m = steps * bm * 1 * f32          # mask
    out = (kp * d + 8 * kp + 8 * 128) * f32  # flushed once, last step
    kernel_worst = in_x + in_c + in_m + out
    kernel_best = in_x + kp * d * f32 + in_m + out
    # XLA Lloyd step (optimized HLO at bench shape): X feeds two separate
    # fusions (assignment GEMM+argmin; one-hot update GEMM) -> 2 passes,
    # plus the same small centroid/score traffic
    xla = 2 * n * d * f32 + in_m + out
    return {
        "kernel": "kmeans_step_tile",
        "numerics_ok": bool(ok),
        "shape": f"n{n}_d{d}_k{k}_bm{block_rows}",
        "x_passes_kernel": 1,
        "x_passes_xla": 2,
        "kernel_hbm_best": kernel_best,
        "kernel_hbm_worst": kernel_worst,
        "xla_hbm": xla,
        "traffic_ratio_best": round(xla / kernel_best, 3),
        "traffic_ratio_worst": round(xla / kernel_worst, 3),
    }


def cdist_proof(n=1024, m=1024, d=18, bm=256, bn=256) -> dict:
    x = np.random.default_rng(0).random((n, d), np.float32)
    y = np.random.default_rng(1).random((m, d), np.float32)
    got = pk.cdist_tile(jnp.asarray(x), jnp.asarray(y), block_m=bm,
                        block_n=bn)
    ref = np.sqrt(np.maximum(
        (x * x).sum(1)[:, None] + (y * y).sum(1)[None] - 2 * x @ y.T, 0))
    ok = np.allclose(np.asarray(got), ref, atol=2e-3)

    f32 = 4
    gi, gj = (n + bm - 1) // bm, (m + bn - 1) // bn
    in_x = gi * gj * bm * d * f32        # X tile re-read per column step
    in_y = gi * gj * bn * d * f32
    out = n * m * f32                    # each distance block written ONCE
    kernel_worst = in_x + in_y + out
    kernel_best = n * d * f32 + m * d * f32 + out
    # XLA expansion: inputs once + write d^2 matrix, re-read it for the
    # sqrt, write the result -> 3 passes over the (n, m) output
    xla = (n * d + m * d) * f32 + 3 * n * m * f32
    return {
        "kernel": "cdist_tile",
        "numerics_ok": bool(ok),
        "shape": f"n{n}_m{m}_d{d}_bm{bm}_bn{bn}",
        "output_passes_kernel": 1,
        "output_passes_xla": 3,
        "kernel_hbm_best": kernel_best,
        "kernel_hbm_worst": kernel_worst,
        "xla_hbm": xla,
        "traffic_ratio_best": round(xla / kernel_best, 3),
        "traffic_ratio_worst": round(xla / kernel_worst, 3),
        "note": "ratios at the proof shape; at the bench shape (40k x 18) "
                "the output matrix dominates and the ratio approaches the "
                "3x output-pass bound",
    }


def flash_proof(B=2, H=4, S=512, D=64, bq=256, bk=256) -> dict:
    rng = np.random.default_rng(0)
    q = rng.standard_normal((B, H, S, D)).astype(np.float32)
    k = rng.standard_normal((B, H, S, D)).astype(np.float32)
    v = rng.standard_normal((B, H, S, D)).astype(np.float32)
    scale = 1.0 / np.sqrt(D)
    out, lse = pk.flash_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), scale=scale,
                                  return_lse=True)
    # dense reference ((B, H, S, D) layout, the kernel's native one)
    s = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, v)
    ok = np.allclose(np.asarray(out), ref, atol=2e-3)

    f32 = 4
    gq, gk = (S + bq - 1) // bq, (S + bk - 1) // bk
    per_head = (gq * gk * (bk * D) * 2 * f32      # K,V tiles (inner axis)
                + gq * bq * D * f32               # Q tile per outer step
                + S * D * f32 + S * f32)          # out + lse written once
    kernel = B * H * per_head
    # dense path: the (S, S) probability matrix hits HBM twice per head
    # (softmax write + read for the PV GEMM) when S*S exceeds cache
    dense = B * H * ((3 * S * D) * f32 + 2 * S * S * f32 + S * D * f32)
    return {
        "kernel": "flash_attention",
        "numerics_ok": bool(ok),
        "shape": f"B{B}_H{H}_S{S}_D{D}_bq{bq}_bk{bk}",
        "intermediate_kernel": "O(S*D + S) per block",
        "intermediate_dense": "O(S^2) probability matrix",
        "kernel_hbm": kernel,
        "dense_hbm": dense,
        "traffic_ratio": round(dense / kernel, 3),
        "scaling_note": "kernel traffic grows as S*(S/bk)*D (K/V restream) "
                        "vs the dense path's S^2 matrix: ratio ~ "
                        "2*bk/(2*D)=4x at these blocks and grows with S",
    }


def main() -> None:
    pk.set_pallas(True)  # interpret mode on CPU exercises the kernel code
    results = [kmeans_proof(), kmeans_proof(block_rows=256),
               cdist_proof(), flash_proof()]
    artifact = {
        "note": "Interpret-mode numerics + BlockSpec-derived HBM traffic "
                "accounting for the three Pallas kernels (fallback "
                "deliverable while the TPU tunnel is down; the on-silicon "
                "A/Bs are queued in scripts/tpu_queue_r05.sh). Traffic "
                "numbers are computed from the kernels' own grids and "
                "block shapes, not asserted.",
        "date": time.strftime("%Y-%m-%d"),
        "command": "PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python "
                   "scripts/pallas_traffic_proof.py",
        "all_numerics_ok": all(r["numerics_ok"] for r in results),
        "kernels": results,
    }
    print(json.dumps(artifact, indent=1))
    with open(os.path.join(_REPO, "PALLAS_TRAFFIC_r05.json"), "w") as f:
        json.dump(artifact, f, indent=1)
    sys.exit(0 if artifact["all_numerics_ok"] else 1)


if __name__ == "__main__":
    main()
