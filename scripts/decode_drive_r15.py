#!/usr/bin/env python
"""User-style drive for the continuous-batching decode engine (ISSUE 15).

Exercises the package surface the way a serving deployment would —
engine up, mixed-length two-tenant traffic, fault injection, observability
— and checks every contract the PR claims. ~16 checks, ~1 min.

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/decode_drive_r15.py
"""

import sys
import time

import numpy as np

import jax

import heat_tpu as ht
from heat_tpu.core import fusion
from heat_tpu.nn.transformer import TransformerLM, TransformerLMConfig
from heat_tpu.serve import DecodeConfig, DecodeEngine, ServeOverloaded
from heat_tpu.serve import serve_transformer
from heat_tpu.utils import faults, metrics as _pm

PASS = []


def check(name, ok):
    PASS.append(bool(ok))
    print(f"  [{'PASS' if ok else 'FAIL'}] {name}", flush=True)


def main() -> int:
    n = ht.get_comm().size
    tp = 2 if n % 2 == 0 else 1
    grid = ht.MeshGrid((n // tp, 1, tp, 1), ("dp", "pp", "tp", "sp"))
    cfg = TransformerLMConfig(vocab=47, d_model=32, n_heads=4, n_layers=2,
                              d_ff=64)
    model = TransformerLM(grid, cfg)
    params = model.init(9)
    rng = np.random.default_rng(1)
    B = model.dp_world

    def ref(prompt, mn):
        return np.asarray(model.generate(
            params, np.tile(prompt, (B, 1)), mn))[0]

    print(f"decode drive: {n} devices, dp={n // tp} tp={tp}")

    # 1-3: engine via the adapter, warmup, steady-state misses
    eng = serve_transformer(model, params, seq_len=64, decode=True,
                            slots=2 * B)
    eng.register_tenant("hi", priority=10)
    eng.register_tenant("lo", priority=0)
    st0 = eng.warmup()
    check("adapter returns a DecodeEngine", isinstance(eng, DecodeEngine))
    mix = [(rng.integers(0, 47, (int(rng.integers(3, 14)),))
            .astype(np.int32), int(rng.integers(2, 11)),
            "hi" if i % 2 else "lo") for i in range(16)]
    futs = [eng.submit(p, m, tenant=t) for p, m, t in mix]
    outs = [f.result(300) for f in futs]
    check("steady-state misses 0 after warmup",
          eng.program_cache.stats()["misses"] == st0["misses"])
    check("greedy tokens bitwise-equal generate() per request",
          all(np.array_equal(o, ref(p, m))
              for (p, m, _t), o in zip(mix, outs)))

    # 4: slot reuse (16 requests over 2B slots) + engine empty
    st = eng.stats()
    check("slot reuse: 16 prefills, engine drained",
          st["prefills"] >= 16 and st["live"] == 0
          and st["queue_depth"] == 0)

    # 5: tenant counters folded
    t = st["tenants"]
    check("per-tenant admitted/completed counters",
          t["hi"]["completed"] == 8 and t["lo"]["completed"] == 8)

    # 6: donation — old cache buffers invalid
    ck0 = eng._ck
    eng.generate(mix[0][0], 3, timeout=120)
    check("decode-step carry donated (old cache deleted)",
          ck0.is_deleted())

    # 7: device-residency audit — d2h disallowed around live decode
    eng.pause()
    f2 = [eng.submit(p, m) for p, m, _t in mix[:4]]
    with jax.transfer_guard_device_to_host("disallow"):
        eng.resume()
        audited = [f.result(300) for f in f2]
    check("per-step host fetch is only the token vector (guard audit)",
          all(np.array_equal(o, ref(p, m))
              for (p, m, _t), o in zip(mix[:4], audited)))

    # 8: EOS early-leave with exact prefix
    p0, m0 = mix[2][0], 8
    full = ref(p0, m0)
    eos = int(full[p0.size + 1])
    out = eng.generate(p0, m0, eos_id=eos, timeout=120)
    cut = int(np.nonzero(full[p0.size:] == eos)[0][0]) + 1
    check("EOS frees the slot with the exact token prefix",
          np.array_equal(out, full[:p0.size + cut]))

    # 9-10: codec toggles compile siblings, toggle-back re-hits
    m_before = eng.program_cache.stats()["misses"]
    with fusion.quant_override("int8"):
        q_out = eng.generate(p0, 4, timeout=120)
    sib = eng.program_cache.stats()["misses"] - m_before
    eng.generate(p0, 4, timeout=120)
    back = eng.program_cache.stats()["misses"] - m_before - sib
    check("codec toggle compiles siblings (keys carry quant_key)",
          sib > 0 and np.array_equal(q_out, ref(p0, 4)))
    check("toggle-back re-hits the exact programs", back == 0)

    # 11: queue bound sheds typed
    eng.pause()
    small = DecodeEngine(model, params,
                         DecodeConfig(slots=B, max_seq_len=64,
                                      queue_limit=2))
    small.pause()
    small.submit(p0, 2)
    small.submit(p0, 2)
    try:
        small.submit(p0, 2)
        check("queue bound sheds ServeOverloaded", False)
    except ServeOverloaded:
        check("queue bound sheds ServeOverloaded", True)
    small.resume()
    small.flush(120)
    small.close()
    eng.resume()

    # 12-13: chaos — faulted step degrades eager, tokens equal, counter 1
    fb0 = int(_pm.counters().get("serve.decode_fallbacks", 0))
    with faults.inject("serve.decode.step=nth:1"):
        f_out = eng.generate(p0, m0, timeout=300)
    fb = int(_pm.counters().get("serve.decode_fallbacks", 0)) - fb0
    check("faulted step degrades to eager per-slot, tokens equal",
          np.array_equal(f_out, full) and eng.worker_alive)
    check("exactly one serve.decode_fallbacks tick", fb == 1)

    # 14: runtime_stats decode shape
    rt = ht.runtime_stats()["serve"]["decode"]
    check("runtime_stats decode shape pinned",
          set(rt) == {"slots", "occupancy", "prefills", "decode_steps",
                      "tokens_out", "decode_fallbacks"}
          and rt["decode_steps"] > 0)

    # 15: generate() prompt-bucket hygiene
    n_prog0 = len(model._step_cache)
    for s0 in (5, 6, 8):
        model.generate(params, np.tile(mix[0][0][:s0], (B, 1))[:, :s0], 7)
    grew = len(model._step_cache) - n_prog0
    check("generate() shares one program per prompt bucket", grew == 1)

    # 16: throughput sanity — continuous batching beats sequential waits
    t0 = time.perf_counter()
    futs = [eng.submit(p, m) for p, m, _t in mix]
    for f in futs:
        f.result(300)
    wall = time.perf_counter() - t0
    toks = sum(p.size + m for p, m, _t in mix)
    check("mixed stream completes with sane throughput",
          wall < 30 and toks / wall > 50)
    eng.close()

    print(f"{sum(PASS)}/{len(PASS)} checks passed")
    return 0 if all(PASS) else 1


if __name__ == "__main__":
    sys.exit(main())
