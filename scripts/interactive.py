#!/usr/bin/env python
"""Interactive distributed console (reference ``scripts/interactive.py``).

The reference needs ``mpirun -stdin all`` plus a rank-aware InteractiveConsole
so every MPI process replays the typed line. Under the single-controller SPMD
model there is nothing to synchronize — one Python process drives the whole
mesh — so this is a plain REPL with heat_tpu preloaded and a mesh banner:

    python scripts/interactive.py
"""

import code
import sys


def main() -> None:
    import heat_tpu as ht

    comm = ht.get_comm()
    banner = (
        f"heat_tpu {ht.__version__} interactive console\n"
        f"mesh: {comm.size} device(s) — "
        f"{', '.join(str(d) for d in comm.devices[:4])}"
        f"{' …' if comm.size > 4 else ''}\n"
        f"`ht` is heat_tpu; try: ht.arange(10, split=0).sum()"
    )
    console = code.InteractiveConsole(locals={"ht": ht})
    console.interact(banner=banner, exitmsg="")


if __name__ == "__main__":
    sys.exit(main())
