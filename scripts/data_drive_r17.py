#!/usr/bin/env python
"""User-style drive for the tape-compiled data engine (ISSUE 17).

Exercises `heat_tpu.data` the way an analytics user would — uneven
shards, every aggregate, special floats, exact quantiles against the
sort path, joins, out-of-core streaming, the escape hatch, fault
injection, observability — and checks every contract the PR claims.
~18 checks, ~1 min.

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/data_drive_r17.py
"""

import sys

import numpy as np

import heat_tpu as ht
from heat_tpu import data
from heat_tpu.utils import faults

PASS = []


def check(name, ok):
    PASS.append(bool(ok))
    print(f"  [{'PASS' if ok else 'FAIL'}] {name}", flush=True)


def main() -> int:
    p = ht.get_comm().size
    print(f"data drive: {p} devices")
    rng = np.random.default_rng(17)

    # 1: minimum slice — exact integer reduction over the mesh
    check("arange(1000).sum() exact",
          int(ht.arange(1000, split=0).sum().numpy()) == 499500)

    # 2-4: groupby, UNEVEN rows (10007 % 8 != 0), every aggregate
    N, G = 10_007, 23
    keys = rng.integers(0, G, N).astype(np.int64)
    vals = rng.standard_normal(N)
    k, v = ht.array(keys, split=0), ht.array(vals, split=0)
    gb = data.groupby(k, G)
    ok = np.allclose(gb.sum(v).numpy(),
                     np.bincount(keys, weights=vals, minlength=G),
                     rtol=1e-12, atol=1e-12)
    ok &= np.array_equal(gb.count().numpy(),
                         np.bincount(keys, minlength=G))
    check("groupby sum+count on uneven 10007 rows", ok)
    mref = np.full(G, np.inf)
    np.minimum.at(mref, keys, vals)
    check("groupby min bitwise",
          np.array_equal(gb.min(v).numpy(), mref))
    cnt = np.bincount(keys, minlength=G)
    check("groupby mean", np.allclose(
        gb.mean(v).numpy(),
        np.bincount(keys, weights=vals, minlength=G) / cnt, rtol=1e-12))

    # 5-6: top-k bitwise incl. special floats (NaN above inf, like sort)
    order = np.argsort(-vals, kind="stable")[:16]
    tv, ti = data.topk(v, 16)
    check("topk values+indices bitwise the stable argsort",
          np.array_equal(tv.numpy(), vals[order])
          and np.array_equal(ti.numpy(), order))
    sp = vals.copy()
    sp[7], sp[4999], sp[10_000] = np.inf, -np.inf, np.nan
    tvs, tis = data.topk(ht.array(sp, split=0), 3)
    check("topk special floats: NaN > inf ordering",
          np.isnan(tvs.numpy()[0]) and tvs.numpy()[1] == np.inf
          and int(tis.numpy()[1]) == 7)

    # 7-9: exact order statistics vs the sort path, every interpolation
    q = [0.0, 12.5, 37.3, 50.0, 99.1, 100.0]
    ok = True
    for interp in ("linear", "lower", "higher", "nearest", "midpoint"):
        eng = ht.percentile(v, q, interpolation=interp).numpy()
        with data.override(False):
            srt = ht.percentile(v, q, interpolation=interp).numpy()
        ok &= np.array_equal(eng, srt)
    check("percentile == sort path EXACTLY, all 5 interpolations", ok)
    check("median matches numpy", np.allclose(
        float(np.asarray(ht.median(v).numpy())), np.median(vals),
        rtol=1e-12))
    nan_in = vals.copy()
    nan_in[123] = np.nan
    check("NaN input poisons the percentile (numpy semantics)",
          np.isnan(float(np.asarray(
              ht.percentile(ht.array(nan_in, split=0), 50.0).numpy()))))

    # 10: inner join vs a dict reference, uneven left, unique build keys
    # (the right side is the build side — its keys must be unique)
    lk = rng.integers(0, 40, 1003).astype(np.int64)
    rk = rng.permutation(40)[:29].astype(np.int64)
    lv = rng.standard_normal(1003)
    rv = rng.standard_normal(29)
    jk, jl, jr = (x.numpy() for x in data.join(
        ht.array(lk, split=0), ht.array(lv, split=0),
        ht.array(rk, split=0), ht.array(rv, split=0)))
    rdict = dict(zip(rk.tolist(), rv.tolist()))
    want = sorted((int(a), float(lv[i]), rdict[int(a)])
                  for i, a in enumerate(lk) if int(a) in rdict)
    got = sorted(zip(jk.tolist(), jl.tolist(), jr.tolist()))
    check("join == dict reference (1003 probe x 29 unique build)",
          got == want)

    # 11-12: steady state — a repeat burst with DIFFERENT quantiles
    # compiles NOTHING; zero fallbacks anywhere
    def burst(qq):
        data.groupby(k, G).sum(v).numpy()
        data.topk(v, 16)[0].numpy()
        ht.percentile(v, qq).numpy()

    burst([5.0, 95.0])
    m0 = data.engine.program_cache().stats()["misses"]
    burst([33.0, 66.0])
    st = data.stats()
    check("repeat burst at new q: ZERO cache misses",
          data.engine.program_cache().stats()["misses"] == m0)
    check("zero fallbacks across the whole drive so far",
          st["exchange_fallbacks"] == 0 and st["stream_fallbacks"] == 0)

    # 13-15: out-of-core streaming over a chunked source
    tab = np.stack([keys.astype(np.float64), vals], axis=1)

    def chunks():
        return iter(ht.array(tab[i:i + 1024], split=0)
                    for i in range(0, N, 1024))

    check("stream_groupby == in-memory groupby", np.allclose(
        data.stream_groupby(chunks, G, "sum").numpy(),
        np.bincount(keys, weights=vals, minlength=G), rtol=1e-12))
    sv, si = data.stream_topk(
        lambda: iter(ht.array(vals[i:i + 1024], split=0)
                     for i in range(0, N, 1024)), 16)
    check("stream_topk BITWISE the in-memory topk",
          np.array_equal(sv.numpy(), tv.numpy())
          and np.array_equal(si.numpy(), ti.numpy()))
    sq = np.asarray(data.stream_quantile(
        lambda: iter(ht.array(vals[i:i + 1024], split=0)
                     for i in range(0, N, 1024)),
        [0.25, 0.5, 0.75], interpolation="nearest"))
    check("stream_quantile bit-equal ht.percentile (nearest)",
          np.array_equal(sq, ht.percentile(
              v, [25.0, 50.0, 75.0], interpolation="nearest").numpy()))

    # 16: escape hatch — override(False) gives identical results and
    # routes nothing through the engine
    d0 = data.stats()["dispatches"]
    with data.override(False):
        g_eager = data.groupby(k, G).sum(v).numpy()
    check("override(False): identical result, zero engine dispatches",
          np.allclose(g_eager, gb.sum(v).numpy(), rtol=1e-15)
          and data.stats()["dispatches"] == d0 + 1)  # the re-run above

    # 17: chaos — one injected dispatch fault degrades to eager with
    # the SAME result and exactly one fallback counter tick
    f0 = data.stats()["exchange_fallbacks"]
    faults.arm(faults.parse_spec("data.exchange.dispatch=nth:1"))
    try:
        g_faulted = data.groupby(k, G).sum(v).numpy()
    finally:
        faults.disarm()
    st = data.stats()
    check("injected fault: eager fallback, equal payload, counter +1",
          np.allclose(g_faulted, g_eager, rtol=1e-15)
          and st["exchange_fallbacks"] == f0 + 1)

    # 18: observability — the pinned runtime_stats surface
    rt = ht.runtime_stats()["data_engine"]
    check("runtime_stats()['data_engine'] pinned shape + live counters",
          set(rt) == {"enabled", "dispatches", "exchange_fallbacks",
                      "stream_chunks", "stream_fallbacks", "groupby_calls",
                      "topk_calls", "quantile_calls", "join_calls",
                      "program_cache"}
          and rt["dispatches"] > 0 and rt["stream_chunks"] > 0
          and rt["join_calls"] >= 1)

    n_ok = sum(PASS)
    print(f"{n_ok}/{len(PASS)} checks passed"
          + ("  ALL PASS" if all(PASS) else "  FAILURES"))
    return 0 if all(PASS) else 1


if __name__ == "__main__":
    sys.exit(main())
