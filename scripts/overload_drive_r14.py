#!/usr/bin/env python
"""User-style drive for the ISSUE 14 overload-serving surface (r14).

Run on the 8-device virtual CPU mesh:

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/overload_drive_r14.py

18 checks, each printed PASS/FAIL; exit 1 on any FAIL. Exercises the
package boundary exactly as a serving user would: a real sharded model
behind `ServingExecutor`, tenants registered over it, open-loop soak,
breaker cycle, the untouched legacy path, and `ht.runtime_stats()`.
"""

import json
import sys
import time

import numpy as np

import heat_tpu as ht
from heat_tpu.serve import (Pow2Buckets, ServeCircuitOpen, ServeConfig,
                            ServeDeadlineExceeded, ServeMetrics,
                            ServeOverloaded, ServeRateLimited,
                            ServingExecutor, TenantLoad, estimate_capacity,
                            run_open_loop, serve_estimator)
from heat_tpu.utils import faults
from heat_tpu.utils import metrics as _pm

CHECKS = []


def check(name, ok, detail=""):
    CHECKS.append(bool(ok))
    print(f"[{'PASS' if ok else 'FAIL'}] {name}" +
          (f"  ({detail})" if detail else ""), flush=True)


def main():
    comm = ht.get_comm()
    print(f"mesh: {comm.size} devices", flush=True)
    rng = np.random.default_rng(0)

    # ---- a REAL model behind the executor: fitted KMeans via the
    # production adapter (the serving path a data-analytics user gets) --
    d = 16
    xtr = rng.standard_normal((256, d)).astype(np.float32)
    km = ht.cluster.KMeans(n_clusters=8, max_iter=10, random_state=0)
    km.fit(ht.array(xtr, split=0))
    ex = serve_estimator(km, comm=comm, metrics=ServeMetrics())
    ex.warmup((d,), np.float32, rows=(1, 2, 5, 9, 17, 33))

    # 1. legacy path first: untouched single-FIFO semantics
    q = rng.standard_normal((5, d)).astype(np.float32)
    want = km.predict(ht.array(q, split=0)).numpy()
    got = np.asarray(ex.predict(q, timeout=60))
    check("legacy predict == estimator.predict",
          np.array_equal(got.astype(np.int64), np.asarray(want, np.int64)))
    check("legacy path has no tenant rows",
          ex.tenant_stats() == {} and ex.admission is None)

    # 2. tenants over the SAME executor
    ex.register_tenant("interactive", priority=10, slo_ms=30e3)
    ex.register_tenant("batch", priority=0, max_queue=8, rate_limit=1e4)
    got2 = np.asarray(ex.predict(q, tenant="interactive", timeout=60))
    check("tenant-tagged predict bitwise-equal",
          np.array_equal(got2, got))
    st = ex.stats()["tenants"]
    check("per-tenant counters in stats()",
          st["interactive"]["admitted"] >= 1
          and st["interactive"]["completed"] >= 1
          and st["interactive"]["breaker"] == "closed")

    # 3. priority: paused queue, batch flood + one interactive -> the
    # interactive request completes first (served from the queue head)
    order = []
    ex.pause()
    futs = []
    for i in range(4):
        f = ex.submit(q, tenant="batch")
        f.add_done_callback(lambda _f, t="batch": order.append(t))
        futs.append(f)
    fi = ex.submit(q, tenant="interactive")
    fi.add_done_callback(lambda _f: order.append("interactive"))
    ex.resume()
    for f in futs + [fi]:
        f.result(60)
    check("priority head-of-queue", order[0] == "interactive", str(order))

    # 4. quota: batch capped at 8 queued
    ex.pause()
    futs = [ex.submit(q, tenant="batch") for _ in range(8)]
    try:
        ex.submit(q, tenant="batch")
        check("quota sheds typed", False)
    except ServeOverloaded as e:
        check("quota sheds typed", "quota" in str(e))
    ex.resume()
    for f in futs:
        f.result(60)

    # 5. rate limit (fresh tenant with a 1-token bucket)
    ex.register_tenant("freebie", rate_limit=1e-3, burst=1.0)
    ex.predict(q, tenant="freebie", timeout=60)
    try:
        ex.submit(q, tenant="freebie")
        check("rate limit sheds typed", False)
    except ServeRateLimited:
        check("rate limit sheds typed", True)

    # 6. SLO as deadline + never dispatched when expired
    m2 = ServeMetrics()
    ex2 = ServingExecutor(
        lambda x: x * np.float32(2.0),
        ServeConfig(bucket_rows=Pow2Buckets(min_rows=comm.size,
                                            multiple_of=comm.size)),
        metrics=m2, cache_token=comm.cache_key)
    ex2.register_tenant("t", slo_ms=1.0)
    ex2.pause()
    f = ex2.submit(np.ones((comm.size, 4), np.float32), tenant="t")
    time.sleep(0.05)
    ex2.resume()
    try:
        f.result(30)
        check("SLO deadline expiry typed", False)
    except ServeDeadlineExceeded:
        snap = m2.snapshot()
        check("SLO deadline expiry typed",
              snap["deadline_expired"] == 1 and snap["batches"] == 0,
              f"batches={snap['batches']}")

    # 7. early shed: primed 10s estimate, 500ms deadline -> shed unrun
    ex2.admission.observe_service(((4,), np.dtype(np.float32).str),
                                  comm.size, 10.0)
    ex2.pause()
    f = ex2.submit(np.ones((comm.size, 4), np.float32), deadline_ms=500.0,
                   tenant="t")
    ex2.resume()
    try:
        f.result(30)
        check("early shed before dispatch", False)
    except ServeDeadlineExceeded as e:
        snap = m2.snapshot()
        check("early shed before dispatch",
              "early shed" in str(e) and snap["batches"] == 0
              and snap["early_shed"] == 1)
    ex2.close()

    # 8. breaker: K=2 failures -> open -> fast fail -> healthy tenant
    # unaffected -> half-open probe closes; fast-fail < 1/10 retry path
    m3 = ServeMetrics()
    ex3 = ServingExecutor(
        lambda x: x + np.float32(1.0),
        ServeConfig(max_batch=2, bucket_rows=Pow2Buckets(
            min_rows=comm.size, multiple_of=comm.size)),
        metrics=m3, cache_token=comm.cache_key)
    ex3.register_tenant("hi", priority=10)
    ex3.register_tenant("bk", priority=0, breaker_failures=2,
                        breaker_cooldown_s=0.25)
    xb = np.ones((comm.size, 4), np.float32)
    ex3.predict(xb, tenant="hi", timeout=60)  # warm the program
    retry_lat = []
    with faults.inject("serve.batch.dispatch=every:1"):
        for _ in range(2):
            t0 = time.monotonic()
            try:
                ex3.submit(xb, tenant="bk").result(60)
            except faults.FaultInjected:
                pass
            retry_lat.append(time.monotonic() - t0)
    check("breaker opens after K post-retry failures",
          ex3.admission.breaker_state("bk") == "open")
    fast = []
    for _ in range(10):
        t0 = time.monotonic()
        try:
            ex3.submit(xb, tenant="bk")
        except ServeCircuitOpen:
            pass
        fast.append(time.monotonic() - t0)
    ratio = sorted(fast)[5] / (sum(retry_lat) / len(retry_lat))
    check("breaker fast-fail < 1/10 retry path", ratio < 0.1,
          f"ratio={ratio:.4f}")
    out = np.asarray(ex3.predict(xb, tenant="hi", timeout=60))
    check("healthy tenant unaffected while breaker open",
          np.array_equal(out, xb + 1.0) and m3.snapshot()["errors"] == 2)
    time.sleep(0.3)
    ex3.submit(xb, tenant="bk").result(60)
    check("half-open probe closes breaker",
          ex3.admission.breaker_state("bk") == "closed")
    check("worker alive through the whole breaker cycle", ex3.worker_alive)
    ex3.close()

    # 9. the open-loop soak short form (2-tenant, stall + every:5 fault)
    m4 = ServeMetrics()
    ex4 = ServingExecutor(
        lambda x: x * np.float32(3.0),
        ServeConfig(max_batch=8, max_wait_ms=2.0, queue_limit=32,
                    bucket_rows=Pow2Buckets(min_rows=comm.size,
                                            multiple_of=comm.size)),
        metrics=m4, cache_token=comm.cache_key)
    ex4.register_tenant("hi", priority=10, slo_ms=1500.0)
    ex4.register_tenant("lo", priority=0, max_queue=24, slo_ms=6000.0)
    ex4.warmup((4,), np.float32, rows=(1, 2, 3, 5, 9, 17))
    cap = estimate_capacity(ex4, (4,), n=24)
    total = min(2.0 * cap, 500.0)
    retries0 = int(_pm.counters().get("serve.batch_retries", 0))
    with faults.inject("serve.batch.dispatch=every:5"):
        rep = run_open_loop(
            ex4, [TenantLoad("hi", min(0.2 * total, 50.0), rows_mix=(1, 2)),
                  TenantLoad("lo", max(total * 0.8, 100.0), rows_mix=(1, 2))],
            1.2, (4,), seed=3, stall=(0.3, 0.4))
    hi, lo = rep["tenants"]["hi"], rep["tenants"]["lo"]
    shed = hi["shed"] + lo["shed"]
    check("soak: worker alive", ex4.worker_alive)
    check("soak: zero untyped client errors",
          rep["totals"]["untyped"] == 0)
    check("soak: overload materialized and >=90% shed on lo",
          shed > 0 and lo["shed"] / max(shed, 1) >= 0.9,
          f"hi={hi['shed']} lo={lo['shed']}")
    check("soak: hi p99 within SLO",
          hi["outcomes"]["ok"] > 0 and hi["latency_ms"]["p99"] <= 1500.0,
          f"p99={hi['latency_ms'].get('p99')}ms")
    check("soak: bounded dispatch retry exercised",
          int(_pm.counters().get("serve.batch_retries", 0)) > retries0)
    ex4.close()

    # 10. one observability surface: runtime_stats carries the tenant map
    rt = ht.runtime_stats()
    row = rt["serve"]["tenants"].get("interactive", {})
    check("runtime_stats tenants folded + json-serializable",
          row.get("admitted", 0) >= 1
          and json.dumps(rt) is not None)
    ex.close()

    n_fail = CHECKS.count(False)
    print(f"\n{len(CHECKS) - n_fail}/{len(CHECKS)} checks passed", flush=True)
    return 1 if n_fail else 0


if __name__ == "__main__":
    sys.exit(main())
