"""Explicit reshard planner: split-layout changes as planned collectives.

``DNDarray.resplit``/``resplit_`` used to hand every layout change to GSPMD
as a blind ``out_shardings`` constraint (the old ``_reshard_physical`` in
``dndarray.py``), which XLA is free to lower as an all-gather — materializing
the full global array on every device: O(N) peak memory and bandwidth per
device. "Memory-efficient array redistribution through portable collective
communication" (arXiv:2112.01075) shows the same reshard decomposes into a
single all-to-all plus local slicing at O(N/p) peak. This module plans each
``(from_split, to_split)`` case explicitly inside ``shard_map``:

=================  =====================================================
case               program (collectives emitted)
=================  =====================================================
split j → split k  local pad of axis k → ONE ``all_to_all``
                   (split_axis=k, concat_axis=j) → local slice of axis j.
                   Zero all-gathers; payload is the O(N/p) local block.
None → split k     local dynamic-slice of the replicated array per device.
                   ZERO collectives.
split j → None     ``all_gather`` along j + local slice — the only case
                   where gathering is the semantics, not an accident.
=================  =====================================================

Why the split→split decomposition is correct: device ``i`` owns the
canonical (ceil-chunked, tail-padded) rows ``i*c_j..(i+1)*c_j`` of axis
``j``; the target wants device ``e`` to own columns ``e*c_k..(e+1)*c_k`` of
axis ``k``. A tiled ``all_to_all`` with ``split_axis=k, concat_axis=j``
sends exactly sub-block (my j-rows × your k-cols) to each peer and
concatenates received pieces in sender order — which IS ascending global
j-order, so the result is each device's full-j / own-k canonical block, up
to the tail padding of axis j (sliced off locally) and of axis k (zero-
padded locally before the exchange so the tile split divides evenly).

Plans compile once per ``(physical shape, dtype, gshape, from, to, mesh)``
and are cached; hit/miss counts feed :mod:`heat_tpu.utils.metrics`
(counters ``resharding.plan_hits`` / ``resharding.plan_misses``) and
:func:`plan_cache_stats`. The GSPMD-blind program is kept as
:func:`gspmd_reshard_fn` — the audited baseline
(``scripts/collective_audit.py --resplit``) and the fallback for degenerate
layouts (single device, zero-size arrays, non-canonical physicals).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ._compat import shard_map

__all__ = [
    "reshard",
    "planned_reshard_fn",
    "gspmd_reshard_fn",
    "plan_kind",
    "plan_cache_stats",
    "reset_plan_cache",
]

# compiled plans keyed by (phys_shape, dtype, gshape, from, to, mesh)
_PLAN_CACHE: dict = {}
# GSPMD-blind baseline programs, same key shape (kept for audit + fallback)
_GSPMD_CACHE: dict = {}
_HITS = 0
_MISSES = 0

_FAULTS = None  # lazy module handle (utils imports back into core)


def _faults():
    global _FAULTS
    if _FAULTS is None:
        from ..utils import faults

        _FAULTS = faults
    return _FAULTS


def plan_cache_stats() -> dict:
    """Plan-cache observability: hits/misses since process start (also
    mirrored into the default metrics registry) and live entry count."""
    return {"hits": _HITS, "misses": _MISSES, "entries": len(_PLAN_CACHE)}


def reset_plan_cache() -> None:
    global _HITS, _MISSES
    _PLAN_CACHE.clear()
    _GSPMD_CACHE.clear()
    _HITS = 0
    _MISSES = 0


def plan_kind(gshape, from_split: Optional[int], to_split: Optional[int],
              comm) -> str:
    """Which program :func:`reshard` would run for this layout change:
    ``"noop"`` / ``"all_to_all"`` / ``"local_slice"`` / ``"all_gather"`` /
    ``"gspmd"`` (degenerate fallback)."""
    if from_split == to_split:
        return "noop"
    if not _plannable(gshape, from_split, to_split, comm):
        return "gspmd"
    if from_split is None:
        return "local_slice"
    if to_split is None:
        return "all_gather"
    return "all_to_all"


def _plannable(gshape, from_split, to_split, comm) -> bool:
    """The explicit programs assume a multi-device mesh and a non-empty
    canonical layout; everything else (p==1, zero-size arrays, 0-d) is
    local-only anyway and keeps the simple slice→pad→constrain program."""
    if comm.size <= 1 or len(gshape) == 0:
        return False
    if any(int(s) <= 0 for s in gshape):
        return False
    return True


def _slice_logical(x, gshape):
    """Physical → logical: cut tail padding (static shapes)."""
    if tuple(x.shape) != tuple(gshape):
        x = jax.lax.slice(x, (0,) * x.ndim, tuple(gshape))
    return x


def _pad_axis(x, axis: int, target: int):
    """Zero-pad ``axis`` up to ``target`` rows (padding is don't-care)."""
    pad = target - x.shape[axis]
    if pad <= 0:
        return x
    cfg = [(0, pad if i == axis else 0, 0) for i in range(x.ndim)]
    return jax.lax.pad(x, jnp.zeros((), x.dtype), cfg)


def gspmd_reshard_fn(phys_shape, jdt, gshape, from_split, to_split, comm):
    """The pre-planner program: slice-off-old-padding → pad-new-axis →
    ``out_shardings`` constraint, one jitted XLA program with GSPMD choosing
    the collectives. Kept as the audited baseline and the degenerate-layout
    fallback."""
    gshape = tuple(int(s) for s in gshape)
    key = (tuple(phys_shape), str(jdt), gshape, from_split, to_split,
           comm.cache_key)
    fn = _GSPMD_CACHE.get(key)
    if fn is not None:
        return fn
    out_sharding = comm.sharding(len(gshape), to_split)

    def _go(x):
        x = _slice_logical(x, gshape)
        if to_split is not None:
            x = _pad_axis(x, to_split, comm.padded_size(gshape[to_split]))
        return x

    fn = jax.jit(_go, out_shardings=out_sharding)
    _GSPMD_CACHE[key] = fn
    return fn


def _build_plan(phys_shape, jdt, gshape, from_split, to_split, comm):
    """Compile the explicit shard_map program for one layout change."""
    p = comm.size
    ndim = len(gshape)

    if from_split is None:
        # None → k: every device slices its own canonical chunk out of the
        # replicated array. ZERO collectives.
        k = to_split
        c = comm.chunk_size(gshape[k])

        def body_slice(x):
            me = jax.lax.axis_index(comm.axis_name)
            x = _pad_axis(x, k, c * p)
            return jax.lax.dynamic_slice_in_dim(x, me * c, c, axis=k)

        return jax.jit(shard_map(
            body_slice, mesh=comm.mesh, in_specs=comm.spec(ndim, None),
            out_specs=comm.spec(ndim, k), check_vma=False))

    if to_split is None:
        # j → None: the only case where gathering IS the semantics.
        j = from_split

        def body_gather(x):
            full = jax.lax.all_gather(x, comm.axis_name, axis=j, tiled=True)
            return _slice_logical(full, gshape)

        return jax.jit(shard_map(
            body_gather, mesh=comm.mesh, in_specs=comm.spec(ndim, j),
            out_specs=comm.spec(ndim, None), check_vma=False))

    # j → k: the 2112.01075 decomposition — one all_to_all + local reslice.
    j, k = from_split, to_split
    c_k = comm.chunk_size(gshape[k])

    def body_a2a(x):
        # local zero-pad of axis k so the tile split divides evenly
        x = _pad_axis(x, k, c_k * p)
        # ONE all_to_all: my j-rows × peer e's k-cols go to e; received
        # pieces concatenate along j in sender (= global j) order
        x = jax.lax.all_to_all(x, comm.axis_name, split_axis=k,
                               concat_axis=j, tiled=True)
        # axis j is now the full padded extent locally: cut its tail padding
        if x.shape[j] != gshape[j]:
            x = jax.lax.slice_in_dim(x, 0, gshape[j], axis=j)
        return x

    return jax.jit(shard_map(
        body_a2a, mesh=comm.mesh, in_specs=comm.spec(ndim, j),
        out_specs=comm.spec(ndim, k), check_vma=False))


def planned_reshard_fn(phys_shape, jdt, gshape, from_split, to_split, comm):
    """Cached compiled reshard program ``physical(from) -> physical(to)``.

    Falls back to :func:`gspmd_reshard_fn` for degenerate layouts (see
    :func:`_plannable`); otherwise builds the explicit program for the
    ``(from, to)`` case. Counters ``resharding.plan_hits`` /
    ``resharding.plan_misses`` track cache behavior.
    """
    global _HITS, _MISSES
    # lazy: utils.checkpointing imports back into core — a module-level
    # import here would cycle during package init
    from ..utils import metrics

    gshape = tuple(int(s) for s in gshape)
    key = (tuple(phys_shape), str(jdt), gshape, from_split, to_split,
           comm.cache_key)
    fn = _PLAN_CACHE.get(key)
    if fn is not None:
        _HITS += 1
        metrics.inc("resharding.plan_hits")
        return fn
    _MISSES += 1
    metrics.inc("resharding.plan_misses")
    if not _plannable(gshape, from_split, to_split, comm):
        fn = gspmd_reshard_fn(phys_shape, jdt, gshape, from_split, to_split,
                              comm)
    else:
        try:
            _faults().check("reshard.plan.build")
            fn = _build_plan(phys_shape, jdt, gshape, from_split, to_split,
                             comm)
        except Exception:
            # HARDENED FAILURE DOMAIN (doc/robustness.md): the explicit
            # plan is an optimization — a failed plan build degrades to
            # the audited GSPMD baseline program (value-identical layout
            # move, XLA-placed collectives) instead of failing the
            # resplit. The fallback is cached under the same key so a
            # hot loop pays the failed build once.
            metrics.inc("resharding.plan_build_fallbacks")
            fn = gspmd_reshard_fn(phys_shape, jdt, gshape, from_split,
                                  to_split, comm)
    _PLAN_CACHE[key] = fn
    return fn


def reshard(parray, gshape, from_split: Optional[int],
            to_split: Optional[int], comm):
    """Move a canonical physical array between split layouts, on device.

    The planner entry point used by ``DNDarray.resplit``/``resplit_``, the
    op-engine split alignment and the manipulations reshape path. Returns
    the physical array of the target layout (tail-padded along
    ``to_split``).
    """
    if from_split == to_split:
        return parray
    gshape = tuple(int(s) for s in gshape)
    # a physical that does not match the canonical from-layout (e.g. a
    # zero-size axis placed replicated by ``from_logical``) cannot feed the
    # shard_map programs — the GSPMD constraint program handles any input
    expected = list(gshape)
    if from_split is not None and gshape and all(s > 0 for s in gshape):
        expected[from_split] = comm.padded_size(gshape[from_split])
    if tuple(parray.shape) != tuple(expected):
        fn = gspmd_reshard_fn(parray.shape, parray.dtype, gshape, from_split,
                              to_split, comm)
    else:
        fn = planned_reshard_fn(parray.shape, parray.dtype, gshape,
                                from_split, to_split, comm)
    try:
        _faults().check("reshard.dispatch")
        return fn(parray)
    except Exception:
        # HARDENED FAILURE DOMAIN (doc/robustness.md): a failed collective
        # dispatch gets ONE retry through the GSPMD baseline program (a
        # distinct executable — if the planned program itself is the
        # problem, the retry does not re-run it). A second failure is a
        # real device/runtime error and surfaces.
        from ..utils import metrics

        metrics.inc("resharding.dispatch_fallbacks")
        return gspmd_reshard_fn(parray.shape, parray.dtype, gshape,
                                from_split, to_split, comm)(parray)
