"""Shape/axis sanitation helpers (reference ``heat/core/stride_tricks.py``)."""

from __future__ import annotations

import itertools
from typing import Tuple, Union

import numpy as np

__all__ = ["broadcast_shape", "broadcast_shapes", "sanitize_axis", "sanitize_shape", "sanitize_slice"]


def broadcast_shape(shape_a, shape_b) -> Tuple[int, ...]:
    """NumPy broadcast of two shapes (reference ``stride_tricks.py:12``)."""
    try:
        return tuple(np.broadcast_shapes(tuple(shape_a), tuple(shape_b)))
    except ValueError as exc:
        raise ValueError(
            f"operands could not be broadcast, input shapes {tuple(shape_a)} {tuple(shape_b)}"
        ) from exc


def broadcast_shapes(*shapes) -> Tuple[int, ...]:
    try:
        return tuple(np.broadcast_shapes(*[tuple(s) for s in shapes]))
    except ValueError as exc:
        raise ValueError(f"operands could not be broadcast, input shapes {shapes}") from exc


def sanitize_axis(shape, axis):
    """Normalize (possibly negative / tuple) axis against ``shape``
    (reference ``stride_tricks.py:72``)."""
    ndim = len(shape)
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        axis = tuple(sanitize_axis(shape, ax) for ax in axis)
        if len(set(axis)) != len(axis):
            raise ValueError("repeated axis")
        return axis
    if not isinstance(axis, (int, np.integer)):
        raise TypeError(f"axis must be None or int or tuple of ints, got {type(axis)}")
    axis = int(axis)
    if ndim == 0:
        if axis in (0, -1):
            return 0
        raise ValueError(f"axis {axis} out of bounds for 0-dimensional array")
    if axis < -ndim or axis >= ndim:
        raise ValueError(f"axis {axis} out of bounds for {ndim}-dimensional array")
    return axis % ndim


def sanitize_shape(shape, lval: int = 0) -> Tuple[int, ...]:
    """Normalize a shape argument (reference ``stride_tricks.py:135``)."""
    if isinstance(shape, (int, np.integer)):
        shape = (int(shape),)
    shape = tuple(shape)
    out = []
    for dim in shape:
        if not isinstance(dim, (int, np.integer)):
            raise TypeError(f"expected shape of ints, got {type(dim)}")
        dim = int(dim)
        if dim < lval:
            raise ValueError(f"negative dimensions are not allowed, got {dim}")
        out.append(dim)
    return tuple(out)


def sanitize_slice(sl: slice, max_dim: int) -> slice:
    """Resolve a slice to explicit non-negative bounds (reference ``stride_tricks.py:180``)."""
    if not isinstance(sl, slice):
        raise TypeError("can only be applied to slice objects")
    return slice(*sl.indices(max_dim))
