"""Distributed unique over the device mesh (reference
``heat/core/manipulations.py:3051``).

The reference Allgatherv-merges per-rank local uniques. That shape is
dynamic twice over (local unique counts, global unique count), which XLA
cannot compile, so the TPU-native pipeline is built from the static-shape
block merge-split network (:mod:`heat_tpu.core._sort`) in three jitted
phases — none of which ever gathers the full array:

A. distributed sort of the values (carrying original positions), then a
   one-element ``ppermute`` halo compare marks each first occurrence, and a
   ``psum`` counts the global number of uniques ``U`` (the ONE scalar that
   must be concretized on the host, exactly like the reference's dynamic
   result size).
B. compaction, compiled per ``U``: marked elements get their output rank as
   a sort key (everything else MAX), one more network pass moves the ``U``
   uniques to the front of the global layout in order; counts come from
   differencing neighbouring first-occurrence positions (one more
   single-element ``ppermute``).
C. inverse, on demand: each sorted element's unique rank is a prefix count
   of the marks; network-sorting ranks keyed by the original positions is a
   distributed scatter back to the input layout.

NaN semantics follow elementwise ``!=`` (each NaN is its own unique), like
torch's ``unique`` that the reference wraps.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from ._compat import shard_map

from ._sort import (
    _float_key_dtype,
    _float_sort_key,
    _index_dtype,
    _merge_split_network,
    _network_sort,
    _role_tables,
    _sentinel,
    batcher_rounds,
)

__all__ = ["distributed_unique", "distributed_unique_rows"]

_UNIQUE_CACHE: dict = {}


def _network_row_sort(key_rows, payloads, rounds, role_tables, c, axis_name):
    """Merge-split network over blocks of ROWS, ordered lexicographically.

    ``key_rows``: (c, K) integer sort-key columns, column 0 most significant
    (callers fold the padding flag in as column 0 and encode float columns
    with :func:`_float_sort_key`). ``payloads``: tuple of (c, ...) arrays
    co-moved with the rows (``jnp.take`` on axis 0). The shared
    :func:`_sort._merge_split_network` round loop with the scalar comparator
    replaced by ``jnp.lexsort`` over the key columns.
    """
    K = key_rows.shape[1]

    def _merge(kr, pls):
        # lexsort: last key is primary → feed columns least-significant first
        order = jnp.lexsort([kr[:, j] for j in range(K - 1, -1, -1)])
        return (jnp.take(kr, order, axis=0),
                tuple(jnp.take(pl, order, axis=0) for pl in pls))

    return _merge_split_network(
        key_rows, payloads, rounds, role_tables, c, axis_name, _merge,
        block_axis=0)


def _row_keys(rows, gpos, n):
    """(c, 1+w) lexsort keys for a (c, w) row block: padding flag (most
    significant, 0 = real row) then each column in a NaN-safe monotone
    integer encoding, all in the encoding's dtype (the 0/1 flag fits any)."""
    if jnp.issubdtype(rows.dtype, jnp.floating):
        enc = _float_sort_key(rows)
    elif rows.dtype == jnp.bool_:
        enc = rows.astype(jnp.int8)
    else:
        enc = rows
    flag = (gpos >= n).astype(enc.dtype)[:, None]
    return jnp.concatenate([flag, enc], axis=1)


def _rows_phase_a_fn(c, w, jdt, n, comm):
    """rows -> (sorted rows, original positions, first-occurrence mask,
    global unique-row count). Row analogue of :func:`_phase_a_fn`."""
    key = ("uniqRA", c, w, str(jdt), n, comm.cache_key)
    fn = _UNIQUE_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    rounds = batcher_rounds(p)
    roles = _role_tables(rounds, p)
    idt = _index_dtype()
    spec2 = comm.spec(2, 0)
    spec1 = comm.spec(1, 0)

    def body(x):
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c, dtype=idt)
        keys = _row_keys(x, gpos, n)
        _, (xl, gi) = _network_row_sort(
            keys, (x, gpos), rounds, roles, c, comm.axis_name)
        spos = me * c + jnp.arange(c, dtype=idt)
        # left halo: previous device's last row (device 0's first row is
        # forced "first" below). Compare the RAW rows, not the encoded keys:
        # the key encoding canonicalizes NaNs for ordering, but uniqueness
        # follows elementwise ``!=`` — NaN != NaN, so each NaN-containing
        # row is its own unique (torch semantics, like the scalar pipeline)
        prev_last = jax.lax.ppermute(
            xl[-1:], comm.axis_name,
            perm=[(i, i + 1) for i in range(p - 1)])
        prev = jnp.concatenate([prev_last, xl[:-1]], axis=0)
        differs = jnp.any(xl != prev, axis=1)
        mask = (spos < n) & ((spos == 0) | differs)
        total = jax.lax.psum(jnp.sum(mask.astype(idt)), comm.axis_name)
        return xl, gi, mask, total

    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=spec2,
                  out_specs=(spec2, spec1, spec1, comm.spec(0, None)),
                  check_vma=False)
    )
    _UNIQUE_CACHE[key] = fn
    return fn


def _rows_phase_b_fn(c, w, jdt, n, n_unique, comm, with_counts):
    """(sorted rows, mask) -> compacted unique rows (+counts), front-aligned
    in the c-chunk layout. Row analogue of :func:`_phase_b_fn`."""
    key = ("uniqRB", c, w, str(jdt), n, n_unique, with_counts, comm.cache_key)
    fn = _UNIQUE_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    rounds = batcher_rounds(p)
    roles = _role_tables(rounds, p)
    idt = _index_dtype()
    kmax = jnp.iinfo(idt).max
    spec2 = comm.spec(2, 0)
    spec1 = comm.spec(1, 0)

    def body(xl, mask):
        me = jax.lax.axis_index(comm.axis_name)
        cnt = jnp.sum(mask.astype(idt))
        offs = comm.exscan(cnt)
        out_pos = jnp.where(mask, offs + jnp.cumsum(mask.astype(idt)) - 1,
                            kmax)
        spos = me * c + jnp.arange(c, dtype=idt)
        _, (vals_s, spos_s) = _network_row_sort(
            out_pos[:, None], (xl, spos), rounds, roles, c, comm.axis_name)
        if not with_counts:
            return (vals_s,)
        nxt_first = jax.lax.ppermute(
            spos_s[:1], comm.axis_name,
            perm=[(i + 1, i) for i in range(p - 1)])
        nxt = jnp.concatenate([spos_s[1:], nxt_first])
        gout = me * c + jnp.arange(c, dtype=idt)
        counts = jnp.where(
            gout < n_unique - 1, nxt - spos_s,
            jnp.where(gout == n_unique - 1, n - spos_s, 0))
        return vals_s, counts

    n_out = 2 if with_counts else 1
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=(spec2, spec1),
                  out_specs=(spec2,) + (spec1,) * (n_out - 1),
                  check_vma=False)
    )
    _UNIQUE_CACHE[key] = fn
    return fn


def distributed_unique_rows(a, return_inverse: bool, return_counts: bool):
    """Distributed unique ROWS of a 2-D split=0 DNDarray (the engine behind
    ``unique(axis=k)``, reference ``manipulations.py:3051``): network
    lexicographic row sort → halo row compare → psum count → network
    compaction. Returns ``(uniques[, inverse][, counts])``; uniques/counts
    split at 0 in the canonical layout for the unique count ``U``, inverse
    split like ``a``."""
    from .dndarray import DNDarray
    from . import types

    comm = a.comm
    n, w = a.shape
    c = comm.chunk_size(n)
    jdt = jnp.dtype(a.larray.dtype)

    sorted_phys, gi, mask, total = _rows_phase_a_fn(c, w, jdt, n, comm)(
        a.filled(0) if a.pad else a.larray)
    n_unique = int(total)  # the one host sync — the result size is dynamic

    fb = _rows_phase_b_fn(c, w, jdt, n, n_unique, comm, return_counts)
    compacted = fb(sorted_phys, mask)
    uniques = DNDarray.from_logical(
        compacted[0][:n_unique], 0, a.device, comm, dtype=a.dtype)
    out = [uniques]
    if return_inverse:
        rank_s = _phase_c_fn(c, comm)(gi, mask)
        out.append(DNDarray(
            rank_s, (n,), types.canonical_heat_type(rank_s.dtype), 0,
            a.device, comm))
    if return_counts:
        out.append(DNDarray.from_logical(
            compacted[1][:n_unique], 0, a.device, comm))
    return tuple(out)


def _phase_a_fn(c, jdt, n, comm):
    """values -> (sorted values, original positions, first-occurrence mask,
    global unique count)."""
    key = ("uniqA", c, str(jdt), n, comm.cache_key)
    fn = _UNIQUE_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    rounds = batcher_rounds(p)
    roles = _role_tables(rounds, p)
    idt = _index_dtype()
    floating = jnp.issubdtype(jdt, jnp.floating)
    spec = comm.spec(1, 0)

    def body(x):
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c, dtype=idt)
        if floating:
            # NaN-safe key order (see _sort._float_sort_key); the value
            # payload keeps the raw floats for the != neighbour compare, so
            # every NaN still counts as its own unique
            kdt = _float_key_dtype(jnp.float32 if jnp.dtype(jdt).itemsize < 4
                                   else jdt)
            pad_key = jnp.asarray(jnp.iinfo(kdt).max, kdt)
            keys = jnp.where(gpos < n, _float_sort_key(x), pad_key)
            _, (xl, gi) = _network_sort(keys, (x, gpos), rounds, roles, c,
                                        False, comm.axis_name)
        else:
            xl = jnp.where(gpos < n, x, _sentinel(jdt, False))
            xl, (gi,) = _network_sort(xl, (gpos,), rounds, roles, c, False,
                                      comm.axis_name)
        spos = me * c + jnp.arange(c, dtype=idt)  # sorted coordinates
        # left halo: previous device's last element (device 0 receives zeros,
        # but its position 0 is forced to "first" below)
        prev_last = jax.lax.ppermute(
            xl[-1:], comm.axis_name, perm=[(i, i + 1) for i in range(p - 1)])
        prev = jnp.concatenate([prev_last, xl[:-1]])
        mask = (spos < n) & ((spos == 0) | (xl != prev))
        total = jax.lax.psum(jnp.sum(mask.astype(idt)), comm.axis_name)
        return xl, gi, mask, total

    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=spec,
                  out_specs=(spec, spec, spec, comm.spec(0, None)),
                  check_vma=False)
    )
    _UNIQUE_CACHE[key] = fn
    return fn


def _phase_b_fn(c, jdt, n, n_unique, comm, with_counts):
    """(sorted values, mask) -> compacted uniques (+counts), front-aligned in
    the c-chunk layout; positions beyond ``n_unique`` are garbage."""
    key = ("uniqB", c, str(jdt), n, n_unique, with_counts, comm.cache_key)
    fn = _UNIQUE_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    rounds = batcher_rounds(p)
    roles = _role_tables(rounds, p)
    idt = _index_dtype()
    kmax = jnp.iinfo(idt).max
    spec = comm.spec(1, 0)

    def body(xl, mask):
        me = jax.lax.axis_index(comm.axis_name)
        cnt = jnp.sum(mask.astype(idt))
        offs = comm.exscan(cnt)
        out_pos = jnp.where(mask, offs + jnp.cumsum(mask.astype(idt)) - 1,
                            kmax)
        spos = me * c + jnp.arange(c, dtype=idt)
        _, (vals_s, spos_s) = _network_sort(
            out_pos, (xl, spos), rounds, roles, c, False, comm.axis_name)
        if not with_counts:
            return (vals_s,)
        # counts[r] = first_pos[r+1] - first_pos[r]; last closes at n
        nxt_first = jax.lax.ppermute(
            spos_s[:1], comm.axis_name,
            perm=[(i + 1, i) for i in range(p - 1)])
        nxt = jnp.concatenate([spos_s[1:], nxt_first])
        gout = me * c + jnp.arange(c, dtype=idt)
        counts = jnp.where(
            gout < n_unique - 1, nxt - spos_s,
            jnp.where(gout == n_unique - 1, n - spos_s, 0))
        return vals_s, counts

    n_out = 2 if with_counts else 1
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=(spec, spec),
                  out_specs=(spec,) * n_out, check_vma=False)
    )
    _UNIQUE_CACHE[key] = fn
    return fn


def _phase_c_fn(c, comm):
    """(original positions, mask) -> inverse indices in the input layout."""
    key = ("uniqC", c, comm.cache_key)
    fn = _UNIQUE_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    rounds = batcher_rounds(p)
    roles = _role_tables(rounds, p)
    idt = _index_dtype()
    spec = comm.spec(1, 0)

    def body(gi, mask):
        cnt = jnp.sum(mask.astype(idt))
        offs = comm.exscan(cnt)
        # rank of the unique each sorted element belongs to (duplicates
        # inherit the rank of their first occurrence via the prefix count)
        rank = offs + jnp.cumsum(mask.astype(idt)) - 1
        # distributed scatter back to input order: gi is a permutation of the
        # physical positions (padding entries carry gi >= n and sink to the
        # trailing padding again)
        _, (rank_s,) = _network_sort(gi, (rank,), rounds, roles, c, False,
                                     comm.axis_name)
        return rank_s

    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=(spec, spec),
                  out_specs=spec, check_vma=False)
    )
    _UNIQUE_CACHE[key] = fn
    return fn


def distributed_unique(a, return_inverse: bool, return_counts: bool):
    """Distributed unique of a 1-D split DNDarray. Returns DNDarrays
    ``(uniques[, inverse][, counts])``; uniques/counts are split at 0 in the
    canonical layout for their length ``U``, inverse is split like ``a``."""
    from .dndarray import DNDarray
    from . import types

    comm = a.comm
    n = a.shape[0]
    c = comm.chunk_size(n)
    jdt = jnp.dtype(a.larray.dtype)

    sorted_phys, gi, mask, total = _phase_a_fn(c, jdt, n, comm)(a.larray)
    n_unique = int(total)  # the one host sync — the result size is dynamic

    fb = _phase_b_fn(c, jdt, n, n_unique, comm, return_counts)
    compacted = fb(sorted_phys, mask)
    uniques = DNDarray.from_logical(
        compacted[0][:n_unique], 0, a.device, comm, dtype=a.dtype)
    out = [uniques]
    if return_inverse:
        rank_s = _phase_c_fn(c, comm)(gi, mask)
        out.append(DNDarray(
            rank_s, (n,), types.canonical_heat_type(rank_s.dtype), 0,
            a.device, comm))
    if return_counts:
        out.append(DNDarray.from_logical(
            compacted[1][:n_unique], 0, a.device, comm))
    return tuple(out) if len(out) > 1 else out[0]
