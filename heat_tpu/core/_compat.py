"""JAX version compatibility shims.

The library targets the current ``jax.shard_map`` (with its ``check_vma``
kwarg), but must also run on jax releases where shard_map still lives at
``jax.experimental.shard_map.shard_map`` and the same kwarg is spelled
``check_rep``. Every module imports :func:`shard_map` from here instead of
from ``jax`` directly.
"""

from __future__ import annotations

__all__ = ["shard_map"]

try:  # current jax: top-level export, kwarg ``check_vma``
    from jax import shard_map as shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: experimental home, kwarg ``check_rep``
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
        if check_vma is not None and "check_rep" not in kwargs:
            kwargs["check_rep"] = check_vma
        return _exp_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
