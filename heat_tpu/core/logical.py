"""Logical operations (reference ``heat/core/logical.py:38-531``)."""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = [
    "all",
    "allclose",
    "any",
    "count_nonzero",
    "in1d",
    "isclose",
    "isfinite",
    "isin",
    "isinf",
    "isnan",
    "isneginf",
    "isposinf",
    "logical_and",
    "logical_not",
    "logical_or",
    "logical_xor",
    "signbit",
]


def _all_op(a, axis=None, keepdims=False):
    """Module-level partial reducer (stable identity: it must be
    recordable onto the fusion tape, where a per-call lambda would compile
    one pinned executable per invocation)."""
    return jnp.all(a != 0, axis=axis, keepdims=keepdims)


def _any_op(a, axis=None, keepdims=False):
    return jnp.any(a != 0, axis=axis, keepdims=keepdims)


def _register_collectives() -> None:
    # shard-local all/any partials combine with pmin/pmax over bool — the
    # reference's Allreduce(LAND/LOR) as one grouped mesh collective
    from . import fusion

    fusion.register_reduce_collective(_all_op, "pmin")
    fusion.register_reduce_collective(_any_op, "pmax")


_register_collectives()


def all(x: DNDarray, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:  # noqa: A001
    """Test whether all elements evaluate True (reference ``logical.py:38``):
    local reduce + ``Allreduce(LAND)`` in the reference, one fused reduce
    here."""
    if keepdim is not None:  # reference/torch keyword name
        keepdims = keepdim
    return _operations._reduce_op(
        x, _all_op, 1, axis=axis, out=out, keepdims=keepdims,
    )


def allclose(x: DNDarray, y: DNDarray, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> bool:
    """Global closeness test (reference ``:130``)."""
    close = isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)
    return bool(all(close).item())


def any(x: DNDarray, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:  # noqa: A001
    """Test whether any element evaluates True (reference ``:190``)."""
    if keepdim is not None:  # reference/torch keyword name
        keepdims = keepdim
    return _operations._reduce_op(
        x, _any_op, 0, axis=axis, out=out, keepdims=keepdims,
    )


def isclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> DNDarray:
    """Element-wise closeness (reference ``:250``)."""
    return _operations._binary_op(
        jnp.isclose, x, y, fn_kwargs={"rtol": rtol, "atol": atol, "equal_nan": equal_nan}
    )


def isfinite(x: DNDarray) -> DNDarray:
    """Element-wise finiteness test (reference ``:310``)."""
    return _operations._local_op(jnp.isfinite, x)


def isinf(x: DNDarray) -> DNDarray:
    """Element-wise infinity test (reference ``:340``)."""
    return _operations._local_op(jnp.isinf, x)


def count_nonzero(x: DNDarray, axis=None, keepdims: bool = False) -> DNDarray:
    """Number of nonzero elements (``numpy.count_nonzero``): one masked
    distributed sum."""
    from . import arithmetics, types as _t

    return arithmetics.sum((x != 0).astype(_t.int64), axis=axis,
                           keepdims=keepdims)


def isin(element, test_elements, assume_unique: bool = False,
         invert: bool = False) -> DNDarray:
    """Membership test (``numpy.isin``): ``test_elements`` replicates (it
    is the lookup set); ``element`` stays split."""
    from . import _operations, factories

    t = (test_elements._logical() if isinstance(test_elements, DNDarray)
         else jnp.asarray(test_elements))
    if not isinstance(element, DNDarray):
        element = factories.array(element)
    return _operations._local_op(
        lambda a: jnp.isin(a, t, assume_unique=assume_unique,
                           invert=invert), element)


def in1d(ar1, ar2, assume_unique: bool = False,
         invert: bool = False) -> DNDarray:
    """1-D membership (``numpy.in1d``): :func:`isin` on the raveled input."""
    from . import manipulations, factories

    if not isinstance(ar1, DNDarray):
        ar1 = factories.array(ar1)
    return isin(manipulations.flatten(ar1), ar2,
                assume_unique=assume_unique, invert=invert)


def isnan(x: DNDarray) -> DNDarray:
    """Element-wise NaN test (reference ``:370``)."""
    return _operations._local_op(jnp.isnan, x)


def isneginf(x: DNDarray, out=None) -> DNDarray:
    """Element-wise -inf test (reference ``:400``)."""
    return _operations._local_op(jnp.isneginf, x, out)


def isposinf(x: DNDarray, out=None) -> DNDarray:
    """Element-wise +inf test (reference ``:420``)."""
    return _operations._local_op(jnp.isposinf, x, out)


def logical_and(x, y) -> DNDarray:
    """Element-wise logical AND (reference ``:440``)."""
    return _operations._binary_op(jnp.logical_and, x, y)


def logical_not(x: DNDarray, out=None) -> DNDarray:
    """Element-wise logical NOT (reference ``:460``)."""
    return _operations._local_op(jnp.logical_not, x, out)


def logical_or(x, y) -> DNDarray:
    """Element-wise logical OR (reference ``:480``)."""
    return _operations._binary_op(jnp.logical_or, x, y)


def logical_xor(x, y) -> DNDarray:
    """Element-wise logical XOR (reference ``:500``)."""
    return _operations._binary_op(jnp.logical_xor, x, y)


def signbit(x: DNDarray, out=None) -> DNDarray:
    """Element-wise signbit test (reference ``:520``)."""
    return _operations._local_op(jnp.signbit, x, out)
