"""Pallas (Mosaic) TPU kernels for the hot ops.

The reference gets all local-compute performance from ATen's CUDA kernels
(SURVEY.md §2: ``_operations.py:172``, ``spatial/distance.py:28``). The
TPU-native equivalents here are hand-tiled Pallas kernels for the two
GB/s-critical tiles the framework runs in its hot loops:

* :func:`cdist_tile` — one fused pairwise-L2 block: the norm terms, the
  ``-2·x·yᵀ`` GEMM on the MXU, the clamp and the sqrt all execute inside a
  single VMEM-resident tile, so the ``(bm, bn)`` distance block is produced
  in one pass with no HBM round-trip for intermediates. This is the tile
  under the ``ppermute`` ring of :mod:`heat_tpu.spatial.distance` (the
  reference's systolic loop, ``distance.py:280-362``).
* :func:`flash_attention` — blockwise attention with online-softmax
  statistics (flash style). Returns the normalized block output together
  with the log-sum-exp per query row, which is exactly the merge state ring
  attention needs: per ring step each device runs this kernel on its
  resident K/V block and folds the result with the running ``(out, lse)``
  pair. The backward is blockwise too (``_flash_bwd_impl``: dK/dV and dQ
  grid kernels recomputing probabilities from the saved lse) — O(S·D)
  memory instead of the dense fallback's O(Sq·Sk), so long-context
  *training* fits in HBM, not just inference.

On non-TPU backends every wrapper falls back to the interpreter
(``interpret=True``), so the CPU test mesh exercises the same kernel code
path; the jnp reference implementations remain available for equivalence
checks. Enablement: by default the cdist/attention kernels are used iff the
active backend is TPU; override with :func:`set_pallas` or
``HEAT_TPU_PALLAS=0/1``. The fused KMeans kernel is the exception — it is
OPT-IN only (:func:`kmeans_pallas_enabled`) until its large-shape scoped-VMEM
issue is resolved (NEXT.md).
"""

from __future__ import annotations

import functools
import math
import os
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = [
    "pallas_enabled",
    "kmeans_pallas_enabled",
    "set_pallas",
    "cdist_tile",
    "flash_attention",
    "kmeans_step_tile",
]

_NEG_BIG = -1e30  # finite stand-in for -inf so exp() of masked rows is safe

# KMeans-kernel GEMM precision. DEFAULT (1-pass bf16 on the MXU) matches the
# XLA Lloyd path, which calls `xp @ centroids.T` without a precision override;
# HIGHEST would emulate f32 in multiple passes and dominates the kernel cost.
_MM_PRECISION = jax.lax.Precision.DEFAULT

_override: Optional[bool] = None
_mosaic_ok: Optional[bool] = None


def set_pallas(enabled: Optional[bool]) -> None:
    """Force Pallas kernels on/off; ``None`` restores backend autodetection."""
    global _override
    _override = enabled


def _mosaic_available() -> bool:
    """One-time probe: can this TPU runtime actually compile a Mosaic kernel?

    Remote-compile TPU runtimes (tunneled dev chips) can serve plain XLA
    programs while their Mosaic kernel-compile path is down (observed: every
    ``pallas_call`` fails with an HTTP 500 from the compile helper while jnp
    programs run fine). Auto-selecting Pallas there would turn every hot op —
    and the driver's flagship-model compile check — into a compile error, so
    backend autodetection compiles one trivial 8x128 kernel first and falls
    back to the XLA paths (with a warning) if that fails. Explicit opt-in
    (``set_pallas(True)`` / ``HEAT_TPU_PALLAS=1``) bypasses the probe."""
    global _mosaic_ok
    if _mosaic_ok is None:
        def _probe(x_ref, o_ref):
            o_ref[...] = x_ref[...] + 1.0

        try:
            # ensure_compile_time_eval: pallas_enabled() is consulted at
            # trace time inside jitted wrappers; the probe must execute
            # eagerly there, not be staged into the caller's trace
            with jax.ensure_compile_time_eval():
                out = pl.pallas_call(
                    _probe,
                    out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                )(jnp.zeros((8, 128), jnp.float32))
                jax.block_until_ready(out)
            _mosaic_ok = True
        except Exception as e:  # noqa: BLE001 — any compile/runtime failure
            warnings.warn(
                "Pallas/Mosaic kernel compilation is unavailable on this TPU "
                f"runtime ({str(e)[:160]}); falling back to XLA implementations "
                "of the hot ops. Set HEAT_TPU_PALLAS=1 to force kernels on.",
                RuntimeWarning,
                stacklevel=3,
            )
            _mosaic_ok = False
    return _mosaic_ok


def pallas_enabled() -> bool:
    """True when the hot ops should route through the Pallas kernels."""
    if _override is not None:
        return _override
    env = os.environ.get("HEAT_TPU_PALLAS")
    if env in ("0", "false", "False"):
        return False
    if env in ("1", "true", "True"):
        return True
    return jax.default_backend() == "tpu" and _mosaic_available()


def kmeans_pallas_enabled() -> bool:
    """The fused KMeans kernel is OPT-IN (explicit ``set_pallas(True)`` or
    ``HEAT_TPU_PALLAS=1``) rather than backend-autoselected: its large-shape
    Mosaic compile currently exceeds the scoped-VMEM budget on v5e (NEXT.md),
    and auto-selection would turn a working fit into a compile error. The
    cdist/attention kernels keep the backend-default behavior."""
    if _override is not None:
        return _override
    return os.environ.get("HEAT_TPU_PALLAS") in ("1", "true", "True")


def _interpret() -> bool:
    # off-TPU the Mosaic compiler is unavailable; run the kernels interpreted
    return jax.default_backend() != "tpu"


def interpret_vma_hazard(*ts) -> bool:
    """True when the kernels would run INTERPRETED (off-TPU) on operands
    carrying a nonempty varying-across-mesh-axes type: the Pallas HLO
    interpreter's dynamic_slice rejects mixed-vma operands inside a
    ``check_vma=True`` shard_map (the flagship transformer's train step), so
    call sites with a jnp fallback should take it. Real Mosaic lowering on
    TPU is unaffected — this never fires there."""
    return _interpret() and bool(_vma(*ts))


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _i32(v):
    # index maps must return int32: with jax_enable_x64 (which the package
    # turns on) they otherwise trace to int64 and Mosaic fails to legalize
    # the kernel ('func.return' lowering error)
    return jnp.asarray(v, jnp.int32)


def _pad_axis(x, axis: int, target: int):
    pad = target - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# --------------------------------------------------------------------------- #
# cdist tile                                                                  #
# --------------------------------------------------------------------------- #


def _cdist_kernel(x_ref, y_ref, o_ref, *, sqrt: bool, acc_dtype):
    x = x_ref[...].astype(acc_dtype)
    y = y_ref[...].astype(acc_dtype)
    x2 = jnp.sum(x * x, axis=1, keepdims=True)  # (bm, 1)
    y2 = jnp.sum(y * y, axis=1)[None, :]  # (1, bn)
    xy = jax.lax.dot_general(
        x, y, dimension_numbers=(((1,), (1,)), ((), ())), preferred_element_type=acc_dtype,
        precision=jax.lax.Precision.HIGHEST,  # Mosaic rejects HIGH; DEFAULT is 1-pass bf16
    )
    d2 = jnp.maximum(x2 + y2 - 2.0 * xy, 0.0)
    o_ref[...] = (jnp.sqrt(d2) if sqrt else d2).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("sqrt", "block_m", "block_n", "out_dtype"))
def cdist_tile(x, y, sqrt: bool = True, block_m: int = 256,
               block_n: int = 256, out_dtype=None):
    """Fused pairwise L2 distance block ``(m, d) × (n, d) → (m, n)``.

    One Pallas grid pass: each ``(block_m, block_n)`` output tile computes
    its norm terms and MXU GEMM entirely in VMEM. ``sqrt=False`` returns
    squared distances (the KMeans assignment form). ``out_dtype`` overrides
    the output dtype (the kernel accumulates in f32/f64 regardless — rbf
    passes f32 here so the exp sees unrounded distances)."""
    m, d = x.shape
    n = y.shape[0]
    if out_dtype is None:
        # preserve the callers' (promoted) floating dtype — a bf16 input
        # must yield a bf16 distance block, not silently upcast to f32
        out_dtype = jnp.promote_types(x.dtype, y.dtype)
    out_dtype = jnp.dtype(out_dtype)
    if not jnp.issubdtype(out_dtype, jnp.floating):
        out_dtype = jnp.dtype(jnp.float32)
    acc_dtype = jnp.float64 if out_dtype == jnp.float64 else jnp.float32
    # Mosaic tiling: sublane block multiple of 8, lane block multiple of 128
    bm = min(_round_up(block_m, 8), _round_up(m, 8))
    bn = min(_round_up(block_n, 128), _round_up(n, 128))
    mp, np_, dp = _round_up(m, bm), _round_up(n, bn), _round_up(d, 128)
    xp = _pad_axis(_pad_axis(x, 0, mp), 1, dp)
    yp = _pad_axis(_pad_axis(y, 0, np_), 1, dp)

    out = pl.pallas_call(
        functools.partial(_cdist_kernel, sqrt=sqrt, acc_dtype=acc_dtype),
        grid=(mp // bm, np_ // bn),
        in_specs=[
            pl.BlockSpec((bm, dp), lambda i, j: (_i32(i), _i32(0))),
            pl.BlockSpec((bn, dp), lambda i, j: (_i32(j), _i32(0))),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (_i32(i), _i32(j))),
        out_shape=_sds((mp, np_), out_dtype, vma=_vma(xp, yp)),
        interpret=_interpret(),
    )(xp, yp)
    return out[:m, :n]


# --------------------------------------------------------------------------- #
# flash attention                                                             #
# --------------------------------------------------------------------------- #


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    scale: float,
    block_q: int,
    block_k: int,
    kv_valid: int,
    causal_offset: Optional[int],
    acc_dtype,
):
    """One (q-block, k-block) grid cell of blockwise attention.

    The K/V grid axis is innermost, so the VMEM scratch accumulators persist
    across its sequential iterations; only one ``(block_k, d)`` K and V tile
    is VMEM-resident at a time — long key sequences never have to fit
    on-chip. ``causal_offset`` is ``Sk - Sq`` (end-aligned diagonal, matching
    the dense fallback) or ``None`` for full attention.
    """
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    num_kb = pl.num_programs(2)
    bq = q_ref.shape[1]

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[...] = jnp.zeros_like(l_ref)

    def step():
        row = jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0) + qi * block_q
        col = jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1) + kb * block_k
        q = q_ref[0].astype(acc_dtype) * scale
        k = k_ref[0].astype(acc_dtype)
        v = v_ref[0].astype(acc_dtype)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())), preferred_element_type=acc_dtype,
            precision=jax.lax.Precision.HIGHEST,
        )  # (bq, block_k)
        mask = col < kv_valid
        if causal_offset is not None:
            mask = jnp.logical_and(mask, col <= row + causal_offset)
        s = jnp.where(mask, s, jnp.asarray(_NEG_BIG, s.dtype))
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # mask p explicitly: on a fully-masked row m_new is still _NEG_BIG and
        # exp(s - m_new) would be 1 at masked positions, silently yielding
        # mean(V) instead of the dense path's NaN
        p = jnp.where(mask, jnp.exp(s - m_new), jnp.zeros((), acc_dtype))
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, dimension_numbers=(((1,), (0,)), ((), ())), preferred_element_type=acc_dtype,
            precision=jax.lax.Precision.HIGHEST,
        )
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new

    if causal_offset is None:
        step()
    else:
        # skip blocks wholly above the (end-aligned) diagonal
        live = kb * block_k <= (qi + 1) * block_q - 1 + causal_offset
        pl.when(live)(step)

    @pl.when(kb == num_kb - 1)
    def _finalize():
        # rows with no unmasked keys (l == 0) produce NaN output and -inf
        # lse, matching softmax-over-all--inf in the dense fallback
        l = l_ref[...]
        empty = l == 0
        l_safe = jnp.where(empty, jnp.ones((), l.dtype), l)
        o = acc_ref[...] / l_safe
        o = jnp.where(empty, jnp.asarray(jnp.nan, o.dtype), o)
        o_ref[0] = o.astype(o_ref.dtype)
        # lse block is (1, bq, 8): the 8-lane tail exists only to satisfy the
        # Mosaic block-shape constraint; callers read lane 0
        lse = jnp.where(empty, jnp.asarray(-jnp.inf, l.dtype), m_ref[...] + jnp.log(l_safe))
        lse = lse.astype(lse_ref.dtype)  # (bq, 1)
        lse_ref[0] = jnp.broadcast_to(lse, (lse.shape[0], 8))


def _vma(*ts):
    """Union of the operands' varying-across-mesh-axes type, so pallas_call
    outputs typecheck inside a ``check_vma=True`` shard_map (e.g. the
    flagship transformer's train step)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:  # older jax: no vma tracking — nothing varies
        return frozenset()
    out = frozenset()
    for t in ts:
        out = out | frozenset(getattr(typeof(t), "vma", ()) or ())
    return out


def _sds(shape, dtype, vma=frozenset()):
    """``jax.ShapeDtypeStruct`` with the ``vma`` type annotation when this
    jax supports it (older releases have neither the kwarg nor the
    tracking, so dropping it is exact)."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "causal", "block_q", "block_k")
)
def _flash_impl(
    q,
    k,
    v,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
):
    """Raw blockwise (flash) attention forward; returns ``(out, lse)``."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    acc_dtype = jnp.float64 if jnp.promote_types(q.dtype, jnp.float32) == jnp.float64 else jnp.float32
    # bq must be a multiple of 128 (the (1, bq) lse output block's lane dim),
    # and bk is the lane dim of the (bq, bk) score block — round user-supplied
    # block sizes up rather than trusting them
    bq = min(_round_up(block_q, 128), _round_up(Sq, 128))
    bk = min(_round_up(block_k, 128), _round_up(Sk, 128))
    sqp, skp, dp = _round_up(Sq, bq), _round_up(Sk, bk), _round_up(D, 128)

    qf = _pad_axis(_pad_axis(q.reshape(B * H, Sq, D), 1, sqp), 2, dp)
    kf = _pad_axis(_pad_axis(k.reshape(B * H, Sk, D), 1, skp), 2, dp)
    vf = _pad_axis(_pad_axis(v.reshape(B * H, Sk, D), 1, skp), 2, dp)

    from jax.experimental.pallas import tpu as pltpu

    out, lse = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            scale=float(scale),
            block_q=bq,
            block_k=bk,
            kv_valid=Sk,
            causal_offset=(Sk - Sq) if causal else None,
            acc_dtype=acc_dtype,
        ),
        # K/V axis innermost: scratch accumulators persist across its
        # sequential steps; only one (bk, dp) K and V tile in VMEM at a time
        grid=(B * H, sqp // bq, skp // bk),
        in_specs=[
            pl.BlockSpec((1, bq, dp), lambda b, i, j: (_i32(b), _i32(i), _i32(0))),
            pl.BlockSpec((1, bk, dp), lambda b, i, j: (_i32(b), _i32(j), _i32(0))),
            pl.BlockSpec((1, bk, dp), lambda b, i, j: (_i32(b), _i32(j), _i32(0))),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, dp), lambda b, i, j: (_i32(b), _i32(i), _i32(0))),
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (_i32(b), _i32(i), _i32(0))),
        ],
        out_shape=[
            _sds((B * H, sqp, dp), q.dtype, vma=_vma(q, k, v)),
            _sds((B * H, sqp, 8), jnp.float32, vma=_vma(q, k, v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, dp), acc_dtype),
            pltpu.VMEM((bq, 1), acc_dtype),
            pltpu.VMEM((bq, 1), acc_dtype),
        ],
        interpret=_interpret(),
    )(qf, kf, vf)

    out = out[:, :Sq, :D].reshape(B, H, Sq, D)
    return out, lse[:, :Sq, 0].reshape(B, H, Sq)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dmb_ref,
                          dk_ref, dv_ref, acc_dk, acc_dv, *, scale: float,
                          block_q: int, block_k: int, kv_valid: int,
                          causal_offset: Optional[int], acc_dtype):
    """dK/dV for one K/V block, accumulated over the (innermost) Q-block
    axis. Everything is computed in the TRANSPOSED (bk, bq) orientation so
    every GEMM is a dim-1×dim-1 or dim-1×dim-0 contraction — no dim-0
    contractions for Mosaic to build transpose temporaries for (the KMeans
    kernel's scoped-VMEM failure mode, NEXT.md #1).

    ``lse_ref``/``dmb_ref`` blocks are (1, 8, bq): the per-row statistics
    pre-transposed host-side into an 8-sublane layout (lane dim = bq, a
    128-multiple); the kernel reads sublane 0. ``dmb = dlse - delta`` is the
    combined additive score-cotangent term (delta = rowsum(dout·out); dlse
    is the lse cotangent ring attention feeds back)."""
    kb = pl.program_id(1)
    qi = pl.program_id(2)
    num_qb = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        acc_dk[...] = jnp.zeros_like(acc_dk)
        acc_dv[...] = jnp.zeros_like(acc_dv)

    def step():
        q = q_ref[0].astype(acc_dtype)
        k = k_ref[0].astype(acc_dtype)
        v = v_ref[0].astype(acc_dtype)
        do = do_ref[0].astype(acc_dtype)
        lse_row = lse_ref[0][:1, :]          # (1, bq)
        dmb_row = dmb_ref[0][:1, :]          # (1, bq)
        s_t = jax.lax.dot_general(
            k, q * scale, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=jax.lax.Precision.HIGHEST,
        )                                     # (bk, bq)
        col = jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0) + kb * block_k
        row = jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1) + qi * block_q
        mask = col < kv_valid
        if causal_offset is not None:
            mask = jnp.logical_and(mask, col <= row + causal_offset)
        # lse = +inf on padded query rows (p -> 0); -inf on fully-masked real
        # rows would blow exp() up, so gate on finiteness like the dense path
        p_t = jnp.where(
            jnp.logical_and(mask, jnp.isfinite(lse_row)),
            jnp.exp(s_t - lse_row), jnp.zeros((), acc_dtype))
        dp_t = jax.lax.dot_general(
            v, do, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=jax.lax.Precision.HIGHEST,
        )                                     # (bk, bq)
        ds_t = p_t * (dp_t + dmb_row)
        acc_dv[...] += jax.lax.dot_general(
            p_t, do, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=jax.lax.Precision.HIGHEST,
        )
        acc_dk[...] += jax.lax.dot_general(
            ds_t, q, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=jax.lax.Precision.HIGHEST,
        )

    if causal_offset is None:
        step()
    else:
        # skip Q blocks wholly above the diagonal for this K block
        live = kb * block_k <= (qi + 1) * block_q - 1 + causal_offset
        pl.when(live)(step)

    @pl.when(qi == num_qb - 1)
    def _flush():
        dk_ref[0] = (acc_dk[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = acc_dv[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dmb_ref,
                         dq_ref, acc_dq, *, scale: float, block_q: int,
                         block_k: int, kv_valid: int,
                         causal_offset: Optional[int], acc_dtype):
    """dQ for one Q block, accumulated over the (innermost) K-block axis.
    ``lse_ref``/``dmb_ref`` blocks are (1, bq, 8) (the forward's lse output
    layout); the kernel reads lane 0."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    num_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_dq[...] = jnp.zeros_like(acc_dq)

    def step():
        q = q_ref[0].astype(acc_dtype)
        k = k_ref[0].astype(acc_dtype)
        v = v_ref[0].astype(acc_dtype)
        do = do_ref[0].astype(acc_dtype)
        lse_col = lse_ref[0][:, :1]          # (bq, 1)
        dmb_col = dmb_ref[0][:, :1]          # (bq, 1)
        s = jax.lax.dot_general(
            q * scale, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=jax.lax.Precision.HIGHEST,
        )                                     # (bq, bk)
        row = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + qi * block_q
        col = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + kb * block_k
        mask = col < kv_valid
        if causal_offset is not None:
            mask = jnp.logical_and(mask, col <= row + causal_offset)
        p = jnp.where(
            jnp.logical_and(mask, jnp.isfinite(lse_col)),
            jnp.exp(s - lse_col), jnp.zeros((), acc_dtype))
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=jax.lax.Precision.HIGHEST,
        )                                     # (bq, bk)
        ds = p * (dp + dmb_col)
        acc_dq[...] += jax.lax.dot_general(
            ds, k, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=jax.lax.Precision.HIGHEST,
        )

    if causal_offset is None:
        step()
    else:
        live = kb * block_k <= (qi + 1) * block_q - 1 + causal_offset
        pl.when(live)(step)

    @pl.when(kb == num_kb - 1)
    def _flush():
        dq_ref[0] = (acc_dq[...] * scale).astype(dq_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "causal", "block_q", "block_k")
)
def _flash_bwd_impl(q, k, v, out, lse, dout, dlse, scale: float, causal: bool,
                    block_q: int, block_k: int):
    """Blockwise (flash) attention backward: O(S·D) memory per (batch, head)
    instead of the dense fallback's O(Sq·Sk) probability matrix — the memory
    profile long-context training needs. Two grid passes: dK/dV (Q-axis
    innermost) and dQ (K-axis innermost), both recomputing probabilities
    from the forward's saved lse."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    acc_dtype = jnp.float64 if jnp.promote_types(q.dtype, jnp.float32) == jnp.float64 else jnp.float32
    bq = min(_round_up(block_q, 128), _round_up(Sq, 128))
    bk = min(_round_up(block_k, 128), _round_up(Sk, 128))
    sqp, skp, dp = _round_up(Sq, bq), _round_up(Sk, bk), _round_up(D, 128)
    BH = B * H

    qf = _pad_axis(_pad_axis(q.reshape(BH, Sq, D), 1, sqp), 2, dp)
    kf = _pad_axis(_pad_axis(k.reshape(BH, Sk, D), 1, skp), 2, dp)
    vf = _pad_axis(_pad_axis(v.reshape(BH, Sk, D), 1, skp), 2, dp)
    dof = _pad_axis(_pad_axis(dout.reshape(BH, Sq, D), 1, sqp), 2, dp)

    # per-row statistics: lse (padded +inf so padded rows give p = 0) and the
    # combined additive term dmb = dlse - delta, delta = rowsum(dout·out)
    delta = jnp.sum(dout.astype(acc_dtype) * out.astype(acc_dtype), axis=-1)
    dmb = (dlse.astype(acc_dtype) - delta).reshape(BH, Sq)
    lse_f = lse.astype(acc_dtype).reshape(BH, Sq)
    pad = sqp - Sq
    lse_f = jnp.pad(lse_f, ((0, 0), (0, pad)), constant_values=jnp.inf)
    dmb = jnp.pad(dmb, ((0, 0), (0, pad)))
    # both layouts: (BH, sqp, 8) for the dQ kernel (column reads), and the
    # transposed (BH, 8, sqp) for the dK/dV kernel (row reads)
    lse_c = jnp.broadcast_to(lse_f[:, :, None], (BH, sqp, 8))
    dmb_c = jnp.broadcast_to(dmb[:, :, None], (BH, sqp, 8))
    lse_r = jnp.broadcast_to(lse_f[:, None, :], (BH, 8, sqp))
    dmb_r = jnp.broadcast_to(dmb[:, None, :], (BH, 8, sqp))

    from jax.experimental.pallas import tpu as pltpu

    common = dict(
        scale=float(scale), block_q=bq, block_k=bk, kv_valid=Sk,
        causal_offset=(Sk - Sq) if causal else None, acc_dtype=acc_dtype,
    )
    vma = _vma(q, k, v, dout, dlse)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        grid=(BH, skp // bk, sqp // bq),
        in_specs=[
            pl.BlockSpec((1, bq, dp), lambda b, kb, qi: (_i32(b), _i32(qi), _i32(0))),
            pl.BlockSpec((1, bk, dp), lambda b, kb, qi: (_i32(b), _i32(kb), _i32(0))),
            pl.BlockSpec((1, bk, dp), lambda b, kb, qi: (_i32(b), _i32(kb), _i32(0))),
            pl.BlockSpec((1, bq, dp), lambda b, kb, qi: (_i32(b), _i32(qi), _i32(0))),
            pl.BlockSpec((1, 8, bq), lambda b, kb, qi: (_i32(b), _i32(0), _i32(qi))),
            pl.BlockSpec((1, 8, bq), lambda b, kb, qi: (_i32(b), _i32(0), _i32(qi))),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, dp), lambda b, kb, qi: (_i32(b), _i32(kb), _i32(0))),
            pl.BlockSpec((1, bk, dp), lambda b, kb, qi: (_i32(b), _i32(kb), _i32(0))),
        ],
        out_shape=[
            _sds((BH, skp, dp), k.dtype, vma=vma),
            _sds((BH, skp, dp), v.dtype, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, dp), acc_dtype),
            pltpu.VMEM((bk, dp), acc_dtype),
        ],
        interpret=_interpret(),
    )(qf, kf, vf, dof, lse_r, dmb_r)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(BH, sqp // bq, skp // bk),
        in_specs=[
            pl.BlockSpec((1, bq, dp), lambda b, qi, kb: (_i32(b), _i32(qi), _i32(0))),
            pl.BlockSpec((1, bk, dp), lambda b, qi, kb: (_i32(b), _i32(kb), _i32(0))),
            pl.BlockSpec((1, bk, dp), lambda b, qi, kb: (_i32(b), _i32(kb), _i32(0))),
            pl.BlockSpec((1, bq, dp), lambda b, qi, kb: (_i32(b), _i32(qi), _i32(0))),
            pl.BlockSpec((1, bq, 8), lambda b, qi, kb: (_i32(b), _i32(qi), _i32(0))),
            pl.BlockSpec((1, bq, 8), lambda b, qi, kb: (_i32(b), _i32(qi), _i32(0))),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, dp), lambda b, qi, kb: (_i32(b), _i32(qi), _i32(0))),
        ],
        out_shape=[_sds((BH, sqp, dp), q.dtype, vma=vma)],
        scratch_shapes=[pltpu.VMEM((bq, dp), acc_dtype)],
        interpret=_interpret(),
    )(qf, kf, vf, dof, lse_c, dmb_c)[0]

    dq = dq[:, :Sq, :D].reshape(B, H, Sq, D)
    dk = dk[:, :Sk, :D].reshape(B, H, Sk, D)
    dv = dv[:, :Sk, :D].reshape(B, H, Sk, D)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff(q, k, v, scale, causal, block_q, block_k):
    return _flash_impl(q, k, v, scale, causal, block_q, block_k)


def _flash_diff_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _flash_impl(q, k, v, scale, causal, block_q, block_k)
    return (out, lse), (q, k, v, out, lse)


def _flash_diff_bwd(scale, causal, block_q, block_k, residuals, cotangents):
    """Flash-attention backward. Default: the blockwise Pallas kernels
    (``_flash_bwd_impl``) — O(S·D) memory, recompute-from-lse, including the
    ``dlse`` cotangent ring attention folds with (``∂lse/∂S = P`` adds
    ``dlse·P`` to the score cotangent). When Pallas is unavailable, a dense
    jnp fallback with the same math: O(Sq·Sk) memory per (batch, head),
    correct on every backend."""
    q, k, v, out, lse = residuals
    dout, dlse = cotangents
    # hazard-check the cotangents too: replicated q/k/v pass the forward's
    # guard, but a loss that mixes the output with mesh-varying data hands
    # this bwd a vma-carrying dout the interpreter would reject
    if pallas_enabled() and not interpret_vma_hazard(q, k, v, dout, dlse):
        return _flash_bwd_impl(q, k, v, out, lse, dout, dlse, scale, causal,
                               block_q, block_k)
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    doutf, outf = dout.astype(jnp.float32), out.astype(jnp.float32)

    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    Sq, Sk = s.shape[-2], s.shape[-1]
    if causal:
        row = jnp.arange(Sq)[:, None]
        col = jnp.arange(Sk)[None, :]
        s = jnp.where(col <= row + (Sk - Sq), s, -jnp.inf)
    p = jnp.exp(s - lse[..., None].astype(jnp.float32))
    p = jnp.where(jnp.isfinite(s), p, 0.0)  # fully-masked rows have lse=-inf

    d_rows = jnp.sum(doutf * outf, axis=-1)  # (B, H, Sq)
    dp = jnp.einsum("bhqd,bhkd->bhqk", doutf, vf)
    ds = p * (dp - d_rows[..., None] + dlse.astype(jnp.float32)[..., None])
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, doutf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def flash_attention(
    q,
    k,
    v,
    scale: Optional[float] = None,
    causal: bool = False,
    return_lse: bool = False,
    block_q: int = 256,
    block_k: int = 256,
):
    """Blockwise (flash) attention with online softmax.

    ``q``: ``(B, H, Sq, D)``; ``k``/``v``: ``(B, H, Sk, D)``. Returns the
    attention output, plus per-row log-sum-exp when ``return_lse`` — the
    merge statistic ring attention folds across ``ppermute`` steps.
    Differentiable: the Pallas forward pairs with a recompute-from-lse
    backward (``_flash_diff_bwd``), so training paths (ring attention, the
    transformer example) work on TPU.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out, lse = _flash_diff(q, k, v, float(scale), bool(causal), int(block_q), int(block_k))
    if return_lse:
        return out, lse
    return out


# --------------------------------------------------------------------------- #
# fused KMeans Lloyd tile                                                     #
# --------------------------------------------------------------------------- #


def _kmeans_kernel(x_ref, c_ref, mask_ref, sums_ref, counts_ref, stats_ref,
                   acc_sums, acc_counts, acc_inertia, *, block_rows: int,
                   acc_dtype, sums_mode: str, k: int):
    """One X row-block of the fused Lloyd step.

    The assignment GEMM, argmin, one-hot update GEMM and the inertia terms
    all consume the SAME VMEM-resident ``(block_rows, d)`` X tile, so each
    Lloyd iteration streams X from HBM exactly once (the jnp path reads it
    three times: the x^2 pass and both GEMMs). Scratch accumulators persist
    across the sequential 1-D grid; outputs are written on the last step.

    ``sums_mode`` selects how the centroid-sum update is computed (the stage
    whose Mosaic compile blew the scoped-VMEM budget at bench shapes,
    NEXT.md #1):

    * ``"dot_rev"`` — ``onehotᵀ·x`` expressed as a dim-0 contraction of the
      ``(bm, kp)`` one-hot (the original formulation; Mosaic materializes
      transpose temporaries for it).
    * ``"dot_t"`` — build the transposed one-hot ``(kp, bm)`` directly from
      the label vector and run a standard dim-1×dim-0 GEMM; no transpose
      temporaries.
    * ``"loop"`` — ``k`` masked VPU reductions of the resident tile
      (no update GEMM at all; attractive because k is tiny for Lloyd
      benchmarks, k=8).
    """
    step = pl.program_id(0)
    nsteps = pl.num_programs(0)

    @pl.when(step == 0)
    def _init():
        acc_sums[...] = jnp.zeros_like(acc_sums)
        acc_counts[...] = jnp.zeros_like(acc_counts)
        acc_inertia[...] = jnp.zeros_like(acc_inertia)

    x = x_ref[...].astype(acc_dtype)              # (bm, d)
    c = c_ref[...].astype(acc_dtype)              # (kp, d), pad rows = +big
    valid = mask_ref[...].astype(acc_dtype)       # (bm, 1)

    c2 = jnp.sum(c * c, axis=1)[None, :]          # (1, kp)
    xc = jax.lax.dot_general(
        x, c, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=acc_dtype,
        precision=_MM_PRECISION,
    )                                             # (bm, kp)
    scores = c2 - 2.0 * xc                        # d^2 minus the x^2 term
    # explicit int32 index dtype: under jax_enable_x64 jnp.argmin asks for
    # int64 indices, which Mosaic's reduce-index lowering rejects
    labels = jax.lax.argmin(scores, 1, jnp.int32)  # (bm,)
    kp = scores.shape[1]

    # Each mode is fully self-contained — sums AND counts come from its own
    # representation, so the VMEM A/B on real TPU isolates the formulation
    # (a shared (bm, kp) one-hot would keep the dot_rev operand live in every
    # mode). acc_counts is (1, kp) for dot_rev, (kp, 1) otherwise.
    if sums_mode == "dot_rev":
        onehot = (labels[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (block_rows, kp), 1)).astype(acc_dtype) * valid
        acc_sums[...] += jax.lax.dot_general(
            onehot, x, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=_MM_PRECISION,
        )                                         # (kp, d)
        acc_counts[...] += jnp.sum(onehot, axis=0, keepdims=True)  # (1, kp)
    elif sums_mode == "dot_t":
        # invalid (padding) rows get the out-of-range label kp so the row
        # iota never matches them — masking without a (1, bm) transpose of
        # the valid column
        labels_m = jnp.where(mask_ref[...][:, 0] > 0, labels, kp)
        onehot_t = (labels_m[None, :] == jax.lax.broadcasted_iota(
            jnp.int32, (kp, block_rows), 0)).astype(acc_dtype)  # (kp, bm)
        acc_sums[...] += jax.lax.dot_general(
            onehot_t, x, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=_MM_PRECISION,
        )                                         # (kp, d)
        acc_counts[...] += jnp.sum(onehot_t, axis=1, keepdims=True)  # (kp, 1)
    elif sums_mode == "loop":
        for j in range(k):
            w = jnp.where(labels[:, None] == j, valid, 0.0)      # (bm, 1)
            acc_sums[j:j + 1, :] += jnp.sum(w * x, axis=0, keepdims=True)
            acc_counts[j:j + 1, :] += jnp.sum(w, axis=0, keepdims=True)
    else:  # pragma: no cover — guarded by kmeans_step_tile
        raise ValueError(f"unknown sums_mode {sums_mode!r}")
    # inertia: min d^2 = min(scores) + x^2, both from the resident tile.
    # Mosaic forbids scalar stores to VMEM, so the scalar partial is
    # broadcast-accumulated into every lane of a vector-shaped scratch; the
    # flush reads one lane's worth (all lanes hold the same running sum).
    # all 2-D with keepdims: Mosaic rejects 1-D offset-changing slices
    x2 = jnp.sum(x * x, axis=1, keepdims=True)        # (bm, 1)
    min_s = jnp.min(scores, axis=1, keepdims=True)    # (bm, 1)
    partial = jnp.sum((min_s + x2) * valid)
    acc_inertia[...] += jnp.broadcast_to(partial, acc_inertia.shape)

    @pl.when(step == nsteps - 1)
    def _flush():
        sums_ref[...] = acc_sums[...].astype(sums_ref.dtype)
        cnt = acc_counts[...]
        if sums_mode != "dot_rev":
            cnt = cnt.T  # (kp, 1) accumulator -> (1, kp); one tiny transpose
        counts_ref[...] = jnp.broadcast_to(
            cnt, counts_ref.shape).astype(counts_ref.dtype)
        stats_ref[...] = jnp.broadcast_to(
            acc_inertia[...], stats_ref.shape).astype(stats_ref.dtype)


def _kmeans_block_rows() -> int:
    """X-tile rows for the KMeans kernel; A/B on real TPU via
    ``HEAT_TPU_KMEANS_BLOCK_ROWS`` (default 1024 — the scoped-VMEM lever:
    every per-step temporary scales with the tile). Resolved by the CALLER
    like :func:`_kmeans_sums_mode`, so step-cache keys and traced kernels
    can never disagree."""
    raw = os.environ.get("HEAT_TPU_KMEANS_BLOCK_ROWS", "1024")
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(
            f"HEAT_TPU_KMEANS_BLOCK_ROWS={raw!r}: expected a positive int")
    if val < 1:
        raise ValueError(
            f"HEAT_TPU_KMEANS_BLOCK_ROWS={val}: expected a positive int")
    return val


def _kmeans_sums_mode() -> str:
    """Centroid-sum formulation inside the KMeans kernel; A/B on real TPU via
    ``HEAT_TPU_KMEANS_SUMS=dot_rev|dot_t|loop`` (default: transposed GEMM —
    the candidate that avoids Mosaic's dim-0-contraction temporaries)."""
    mode = os.environ.get("HEAT_TPU_KMEANS_SUMS", "dot_t")
    if mode not in ("dot_rev", "dot_t", "loop"):
        raise ValueError(
            f"HEAT_TPU_KMEANS_SUMS={mode!r}: expected dot_rev|dot_t|loop")
    return mode


def kmeans_step_tile(x, centroids, valid_mask, block_rows: Optional[int] = None,
                     sums_mode: Optional[str] = None):
    """Fused Lloyd iteration over a local X shard: ONE HBM pass.

    ``x``: ``(N_pad, d)``; ``centroids``: ``(k, d)``; ``valid_mask``:
    ``(N_pad, 1)`` 1.0 for real rows (the canonical-padding mask, constant
    across iterations). Returns ``(sums (k, d), counts (k,), inertia)`` —
    the per-shard partials the caller psums over the mesh. Labels are not
    produced here; the fit computes them once after convergence (a single
    extra assignment pass) instead of writing N int32s every iteration.
    ``sums_mode`` (default ``HEAT_TPU_KMEANS_SUMS``) picks the centroid-sum
    formulation, see :func:`_kmeans_kernel`.
    """
    # resolve the env-selected knobs OUTSIDE the jit so they are part of the
    # cache key (a None default baked in at trace time would go stale if the
    # env var changes between calls)
    if sums_mode is None:
        sums_mode = _kmeans_sums_mode()
    if block_rows is None:
        block_rows = _kmeans_block_rows()
    return _kmeans_step_tile(x, centroids, valid_mask, block_rows, sums_mode)


@functools.partial(jax.jit, static_argnames=("block_rows", "sums_mode"))
def _kmeans_step_tile(x, centroids, valid_mask, block_rows: int,
                      sums_mode: str):
    n, d = x.shape
    k = centroids.shape[0]
    acc_dtype = jnp.float64 if jnp.promote_types(x.dtype, jnp.float32) == jnp.float64 else jnp.float32
    kp = _round_up(k, 128)
    bm = min(_round_up(block_rows, 8), _round_up(n, 8))
    npad = _round_up(n, bm)
    xp = _pad_axis(x, 0, npad)
    maskp = _pad_axis(valid_mask.astype(x.dtype), 0, npad)
    # pad centroid rows with a huge coordinate: their c^2 term dominates so
    # argmin never selects a padding cluster
    cp = jnp.full((kp, d), 1e15, x.dtype).at[:k].set(centroids)

    from jax.experimental.pallas import tpu as pltpu

    sums, counts, stats = pl.pallas_call(
        functools.partial(_kmeans_kernel, block_rows=bm, acc_dtype=acc_dtype,
                          sums_mode=sums_mode, k=k),
        grid=(npad // bm,),
        in_specs=[
            pl.BlockSpec((bm, d), lambda i: (_i32(i), _i32(0))),
            pl.BlockSpec((kp, d), lambda i: (_i32(0), _i32(0))),
            pl.BlockSpec((bm, 1), lambda i: (_i32(i), _i32(0))),
        ],
        out_specs=[
            pl.BlockSpec((kp, d), lambda i: (_i32(0), _i32(0))),
            pl.BlockSpec((8, kp), lambda i: (_i32(0), _i32(0))),
            pl.BlockSpec((8, 128), lambda i: (_i32(0), _i32(0))),
        ],
        out_shape=[
            _sds((kp, d), acc_dtype, vma=_vma(x, centroids)),
            _sds((8, kp), acc_dtype, vma=_vma(x, centroids)),
            _sds((8, 128), acc_dtype, vma=_vma(x, centroids)),
        ],
        scratch_shapes=[
            pltpu.VMEM((kp, d), acc_dtype),
            pltpu.VMEM((1, kp) if sums_mode == "dot_rev" else (kp, 1),
                       acc_dtype),
            pltpu.VMEM((8, 128), acc_dtype),  # scalar held in every lane (native tile)
        ],
        interpret=_interpret(),
    )(xp, cp, maskp)
    return (sums[:k].astype(x.dtype), counts[0, :k].astype(x.dtype),
            stats[0, 0].astype(x.dtype))
