"""The op engine: four generic wrappers every ``ht.*`` op funnels through.

Re-design of the reference's ``heat/core/_operations.py`` (``__binary_op``
``:24``, ``__cum_op`` ``:185``, ``__local_op`` ``:282``, ``__reduce_op``
``:356``). The reference versions orchestrate type promotion, broadcasting,
redistribution, and MPI collectives by hand; here the same four entry points
reduce to dtype/split bookkeeping around ``jnp`` calls, because GSPMD inserts
the collectives: a reduction over the split axis lowers to a local reduce +
``psum`` over ICI exactly like the reference's local-reduce + ``Allreduce``
(``_operations.py:440-445``), but scheduled by XLA.

Padding discipline: reductions/scans that read across the split axis first
overwrite the padding with the op's neutral element (``DNDarray.filled``);
ops that do not cross the split axis leave padding as garbage, which stays in
the padding region of the result.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import devices, sanitation, types
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import broadcast_shape, sanitize_axis

__all__ = []


def _count_align_resplit() -> None:
    """Metrics tick for an op-engine distribution-alignment reshard (lazy
    import: utils imports back into core)."""
    from ..utils import metrics

    metrics.inc("op_engine.align_resplits")


def _count_zero_fill() -> None:
    """Metrics tick for an eager zero-fill masking pass a contraction paid
    (``op_engine.zero_fills``): GEMM operands whose buffers are already
    canonically zero-padded (``DNDarray.pad_is_zero``) never tick this —
    the ladder stats line shows how often GEMMs pay the masking pass."""
    from ..utils import metrics

    metrics.inc("op_engine.zero_fills")


def _split_in_output(split: Optional[int], ndim_in: int, ndim_out: int) -> Optional[int]:
    """Map an input split axis to output coordinates after broadcasting
    (leading dimensions are prepended)."""
    if split is None:
        return None
    return split + (ndim_out - ndim_in)


def __binary_op(
    operation: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    where=None,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Generic binary operation (reference ``_operations.py:24-182``).

    Promotes scalars, broadcasts shapes, aligns distributions (resplit of the
    non-dominant operand — the reference's ``sanitize_distribution`` redisti-
    bution trigger), and applies the ``jnp`` operation on physical arrays.
    Alignment resplits run through the explicit reshard planner
    (:mod:`.resharding`: split→split is ONE planned all_to_all, never an
    all-gather) and are counted in the metrics registry
    (``op_engine.align_resplits``) — resplit sits on the hot path of every
    cross-split op alignment, so its volume is worth watching.
    """
    fn_kwargs = fn_kwargs or {}

    if isinstance(t1, DNDarray):
        device, comm = t1.device, t1.comm
    elif isinstance(t2, DNDarray):
        device, comm = t2.device, t2.comm
    else:
        raise TypeError(f"at least one operand must be a DNDarray, got {type(t1)}, {type(t2)}")

    def prep(t):
        if isinstance(t, DNDarray):
            return t
        if isinstance(t, (int, float, bool, complex, np.generic)):
            return t  # keep weak-typed scalar for NumPy-style promotion
        if isinstance(t, (list, tuple, np.ndarray, jnp.ndarray)):
            return DNDarray.from_logical(jnp.asarray(t), None, device, comm)
        raise TypeError(f"operand type not supported: {type(t)}")

    t1 = prep(t1)
    t2 = prep(t2)

    # scalar fast path -------------------------------------------------- #
    if not isinstance(t1, DNDarray) or not isinstance(t2, DNDarray):
        x = t1 if isinstance(t1, DNDarray) else t2
        other = t2 if isinstance(t1, DNDarray) else t1
        if out is None and where is None:
            from . import fusion

            lazy = fusion.record_binary(operation, t1, t2, fn_kwargs,
                                        None, None, x.gshape, x.split,
                                        device, comm)
            if lazy is not None:
                return lazy
        res = operation(t1.larray if isinstance(t1, DNDarray) else t1,
                        t2.larray if isinstance(t2, DNDarray) else t2, **fn_kwargs)
        result = DNDarray(
            res, x.gshape, types.canonical_heat_type(res.dtype), x.split, device, comm
        )
        return _finalize(result, out, where)

    # both DNDarray ----------------------------------------------------- #
    out_shape = broadcast_shape(t1.shape, t2.shape)
    ndim_out = len(out_shape)

    s1 = _split_in_output(t1.split, t1.ndim, ndim_out)
    s2 = _split_in_output(t2.split, t2.ndim, ndim_out)

    # an operand split along an axis it broadcasts over (size 1) must be
    # replicated first — its padded physical layout cannot broadcast
    if s1 is not None and t1.shape[t1.split] == 1 and out_shape[s1] != 1:
        _count_align_resplit()
        t1 = t1.resplit(None)
        s1 = None
    if s2 is not None and t2.shape[t2.split] == 1 and out_shape[s2] != 1:
        _count_align_resplit()
        t2 = t2.resplit(None)
        s2 = None

    # dominant-operand split precedence (reference ``:140-161``); never
    # resplit an operand onto an axis it broadcasts over (size 1) — its
    # padded physical layout could not broadcast
    if s1 is not None:
        out_split = s1
        if s2 is not None and s2 != s1:
            _count_align_resplit()
            ax2 = s1 - (ndim_out - t2.ndim)
            if ax2 >= 0 and t2.shape[ax2] == out_shape[s1]:
                t2 = t2.resplit(ax2)
            else:
                t2 = t2.resplit(None)
    elif s2 is not None:
        out_split = s2
        ax1 = s2 - (ndim_out - t1.ndim)
        if t1.ndim > 0 and t1.shape and ax1 >= 0 and t1.shape[ax1] == out_shape[s2]:
            _count_align_resplit()
            t1 = t1.resplit(ax1)
    else:
        out_split = None

    # physical alignment: a replicated operand whose axis matches the split
    # axis length must be padded to the physical length (computed from
    # metadata first, so the deferred path can record the pad as a node)
    pad1 = pad2 = None
    if out_split is not None:
        phys_len = comm.padded_size(out_shape[out_split])
        logical_len = out_shape[out_split]
        if phys_len != logical_len:
            for name, t in (("1", t1), ("2", t2)):
                ax = out_split - (ndim_out - t.ndim)
                if ax >= 0 and t.shape[ax] == logical_len \
                        and t._phys_shape()[ax] == logical_len:
                    cfg = tuple(
                        (0, phys_len - logical_len if i == ax else 0)
                        for i in range(t.ndim))
                    if name == "1":
                        pad1 = cfg
                    else:
                        pad2 = cfg

    if out is None and where is None:
        from . import fusion

        lazy = fusion.record_binary(operation, t1, t2, fn_kwargs, pad1, pad2,
                                    out_shape, out_split, device, comm)
        if lazy is not None:
            return lazy

    p1, p2 = t1.larray, t2.larray
    if pad1 is not None:
        p1 = jnp.pad(p1, list(pad1))
    if pad2 is not None:
        p2 = jnp.pad(p2, list(pad2))

    res = operation(p1, p2, **fn_kwargs)
    result = DNDarray(
        res, out_shape, types.canonical_heat_type(res.dtype), out_split, device, comm
    )
    return _finalize(result, out, where)


def _finalize(result: DNDarray, out: Optional[DNDarray], where=None) -> DNDarray:
    """Apply ``where=``/``out=`` semantics and return.

    Every distribution alignment here rides the explicit reshard planner
    and is counted in ``op_engine.align_resplits`` — the ``out=``/``where=``
    sites were the op engine's only uncounted resplits.
    """
    if where is not None:
        if out is None:
            raise ValueError("'where' requires 'out' to be specified")
        w = _align_where_mask(where, out)
        if result.split != out.split:
            _count_align_resplit()
            aligned = result.resplit(out.split)
        else:
            aligned = result
        out.larray = jnp.where(w, aligned.larray.astype(out.dtype.jax_type()), out.larray)
        return out
    if out is not None:
        if out.split != result.split:
            _count_align_resplit()  # sanitize_out resplits out in place
        sanitation.sanitize_out(out, result.shape, result.split, result.device)
        aligned = result.resplit(out.split) if result.split != out.split else result
        out.larray = aligned.larray.astype(out.dtype.jax_type())
        return out
    return result


def _align_where_mask(where, out: DNDarray):
    """The ``where=`` mask as a physical array aligned with ``out``'s
    layout. A DNDarray mask whose split differs from ``out.split`` is
    resplit first (it was previously consumed in ITS OWN layout — wrong
    selections on uneven shapes and hidden XLA reshards otherwise); raw
    array masks spanning a padded split axis are padded with False so
    ``out`` keeps its own (don't-care) padding content."""
    if isinstance(where, DNDarray):
        if where.gshape == tuple(out.gshape):
            if where.split != out.split:
                _count_align_resplit()
                where = where.resplit(out.split)
            return where.larray
        w = where._logical()  # broadcast-shaped mask: replicate it
    else:
        w = jnp.asarray(where)
    if out.split is not None and out.pad:
        ax = out.split - (out.ndim - w.ndim)
        if ax >= 0 and w.shape[ax] == out.gshape[out.split]:
            cfg = [(0, out.pad if i == ax else 0) for i in range(w.ndim)]
            w = jnp.pad(w, cfg)  # False: padding keeps out's values
    return w


def __local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    **kwargs,
) -> DNDarray:
    """Pure elementwise operation (reference ``_operations.py:282-353``).

    Zero communication; runs on the physical array (padding computes garbage
    that stays in padding). Without an ``out=`` buffer the op is *recorded*
    instead of dispatched (:mod:`.fusion`): the whole chain compiles as one
    program at the next materialization point.
    """
    sanitation.sanitize_in(x)
    if out is None:
        from . import fusion

        lazy = fusion.record_unary(operation, x, kwargs)
        if lazy is not None:
            return lazy
    res = operation(x.larray, **kwargs)
    result = DNDarray(
        res, x.gshape, types.canonical_heat_type(res.dtype), x.split, x.device, x.comm
    )
    return _finalize(result, out)


def __reduce_op(
    x: DNDarray,
    partial_op: Callable,
    neutral,
    axis=None,
    out: Optional[DNDarray] = None,
    keepdims: bool = False,
    **kwargs,
) -> DNDarray:
    """Generic reduction (reference ``_operations.py:356-482``).

    The reference computes a local partial reduce then ``Allreduce`` when the
    split axis is reduced (``:440-445``); here the same happens inside XLA:
    ``jnp``'s reduce over a sharded axis lowers to shard-local reduce +
    ``psum`` over the mesh. The only extra step is neutral-element masking of
    the canonical padding (the reference's empty-shard neutral fill,
    ``:402-411``, plays the same role).

    Without an ``out=`` buffer the reduction is *recorded* onto the fusion
    tape (:func:`heat_tpu.core.fusion.record_reduce`): the whole
    elementwise chain feeding it — mask, shard-local reduce and the one
    collective included — compiles as a single program at the next
    materialization point, and the full-size elementwise intermediate
    never reaches HBM.
    """
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    axes = tuple(range(x.ndim)) if axis is None else ((axis,) if isinstance(axis, int) else axis)

    touches_split = x.split is not None and (axis is None or x.split in axes)
    if x.split is None or touches_split:
        out_split = None
    elif keepdims:
        out_split = x.split
    else:
        out_split = x.split - sum(1 for a in axes if a < x.split)
    gshape = _reduced_shape(x.shape, axes if axis is not None else None, keepdims)

    if out is None:
        from . import fusion

        lazy = fusion.record_reduce(x, partial_op, neutral, axis, axes,
                                    keepdims, touches_split, gshape,
                                    out_split, kwargs)
        if lazy is not None:
            return lazy

    physical = x.filled(neutral) if touches_split and x.pad else x.larray
    res = partial_op(physical, axis=(None if axis is None else axes), keepdims=keepdims, **kwargs)
    result = DNDarray(
        res, gshape, types.canonical_heat_type(res.dtype), out_split, x.device, x.comm
    )
    return _finalize(result, out)


def _reduced_shape(shape, axes, keepdims):
    if axes is None:
        return (1,) * len(shape) if keepdims else ()
    if keepdims:
        return tuple(1 if i in axes else s for i, s in enumerate(shape))
    return tuple(s for i, s in enumerate(shape) if i not in axes)


def __cum_op(
    x: DNDarray,
    partial_op: Callable,
    axis: int,
    neutral,
    out: Optional[DNDarray] = None,
    dtype=None,
) -> DNDarray:
    """Generic cumulative operation (reference ``_operations.py:185-279``).

    The reference's local-cum + ``Exscan`` + combine collapses into one
    ``jnp`` scan over the (possibly sharded) axis — XLA partitions it.
    """
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative over flattened array: call flatten() first")
    if out is None:
        from . import fusion

        # split-preserving scans (axis != split) record into the tape; a
        # scan across the split axis materializes first so the neutral-
        # element padding fill stays exactly the eager program
        lazy = fusion.record_cum(x, partial_op, axis, dtype)
        if lazy is not None:
            return lazy
    physical = x.filled(neutral) if (x.split == axis and x.pad) else x.larray
    res = partial_op(physical, axis=axis)
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        res = res.astype(dtype.jax_type())
    result = DNDarray(
        res, x.gshape, types.canonical_heat_type(res.dtype), x.split, x.device, x.comm
    )
    return _finalize(result, out)


# public-ish aliases used by the ops namespaces (mirrors the reference's
# name-mangled imports of the form ``_operations.__binary_op``)
_binary_op = __binary_op
_local_op = __local_op
_reduce_op = __reduce_op
_cum_op = __cum_op
