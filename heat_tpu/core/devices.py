"""Device abstraction (reference ``heat/core/devices.py``).

The reference pins each MPI rank to a CPU or a round-robin CUDA device
(``devices.py:79-100``). Under single-controller JAX the platform is chosen at
backend init; a :class:`Device` here names a *platform* ("tpu" or "cpu") whose
actual device placement is governed by the mesh in
:class:`~heat_tpu.core.communication.TPUCommunication`.
"""

from __future__ import annotations

from typing import Optional, Union

import jax

__all__ = ["Device", "cpu", "get_device", "sanitize_device", "use_device"]


class Device:
    """Platform identity of a DNDarray (reference ``devices.py:17``)."""

    def __init__(self, device_type: str, device_id: int = 0):
        self.__device_type = str(device_type)
        self.__device_id = int(device_id)

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def device_id(self) -> int:
        return self.__device_id

    def __repr__(self) -> str:
        return f"device({str(self)!r})"

    def __str__(self) -> str:
        return f"{self.device_type}:{self.device_id}"

    def __eq__(self, other) -> bool:
        if isinstance(other, Device):
            return self.device_type == other.device_type and self.device_id == other.device_id
        if isinstance(other, str):
            try:
                return self == sanitize_device(other)
            except (ValueError, TypeError):
                return False
        return NotImplemented

    def __hash__(self):
        return hash(str(self))


cpu = Device("cpu", 0)
"""The host-CPU platform singleton (reference ``devices.py:79``)."""

# Platform detection is LAZY: importing heat_tpu must not initialize the
# XLA backend, or ``distributed_init()`` (which must run before any backend
# touch) could never be called after the import. The accelerator singleton
# and default device materialize on first use; ``tpu`` resolves via module
# ``__getattr__``.
_platform: Optional[str] = None
_accel: Optional[Device] = None
_default_device: Optional[Device] = None


def _detect() -> None:
    global _platform, _accel, _default_device
    if _platform is None:
        _platform = jax.default_backend()
        if _platform != "cpu":
            _accel = Device(_platform, 0)
        if _default_device is None:
            _default_device = _accel if _accel is not None else cpu


def __getattr__(name: str):
    if name == "tpu":
        _detect()
        return _accel if _accel is not None and _accel.device_type == "tpu" else None
    if name in ("gpu", "axon"):
        _detect()
        return _accel if _accel is not None and _accel.device_type == name else None
    raise AttributeError(f"module 'heat_tpu.core.devices' has no attribute {name!r}")


def get_device() -> Device:
    """Default device for new arrays (reference ``get_device``, ``devices.py:113``)."""
    _detect()
    return _default_device


def sanitize_device(device: Union[str, Device, None]) -> Device:
    """Normalize a device argument (reference ``sanitize_device``, ``devices.py:126``)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    name = str(device).split(":")[0].strip().lower()
    if name == "cpu":
        return cpu
    _detect()
    if _accel is not None and name == _accel.device_type:
        return _accel
    raise ValueError(f"Unknown device, must be 'cpu' or '{_platform}', got {device!r}")


def use_device(device: Union[str, Device, None] = None) -> None:
    """Set the default device (reference ``use_device``, ``devices.py:157``)."""
    global _default_device
    _default_device = sanitize_device(device)
