"""Device abstraction (reference ``heat/core/devices.py``).

The reference pins each MPI rank to a CPU or a round-robin CUDA device
(``devices.py:79-100``). Under single-controller JAX the platform is chosen at
backend init; a :class:`Device` here names a *platform* ("tpu" or "cpu") whose
actual device placement is governed by the mesh in
:class:`~heat_tpu.core.communication.TPUCommunication`.
"""

from __future__ import annotations

from typing import Optional, Union

import jax

__all__ = ["Device", "cpu", "get_device", "sanitize_device", "use_device"]


class Device:
    """Platform identity of a DNDarray (reference ``devices.py:17``)."""

    def __init__(self, device_type: str, device_id: int = 0):
        self.__device_type = str(device_type)
        self.__device_id = int(device_id)

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def device_id(self) -> int:
        return self.__device_id

    def __repr__(self) -> str:
        return f"device({str(self)!r})"

    def __str__(self) -> str:
        return f"{self.device_type}:{self.device_id}"

    def __eq__(self, other) -> bool:
        if isinstance(other, Device):
            return self.device_type == other.device_type and self.device_id == other.device_id
        if isinstance(other, str):
            try:
                return self == sanitize_device(other)
            except (ValueError, TypeError):
                return False
        return NotImplemented

    def __hash__(self):
        return hash(str(self))


cpu = Device("cpu", 0)
"""The host-CPU platform singleton (reference ``devices.py:79``)."""

# accelerator singleton: present when the JAX backend is TPU (or GPU)
_platform = jax.default_backend()
if _platform not in ("cpu",):
    globals()[_platform] = Device(_platform, 0)
    __default_device = globals()[_platform]
else:
    __default_device = cpu

# convenience: expose `tpu` if a TPU backend exists
tpu: Optional[Device] = globals().get("tpu")


def get_device() -> Device:
    """Default device for new arrays (reference ``get_device``, ``devices.py:113``)."""
    return __default_device


def sanitize_device(device: Union[str, Device, None]) -> Device:
    """Normalize a device argument (reference ``sanitize_device``, ``devices.py:126``)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    name = str(device).split(":")[0].strip().lower()
    if name == "cpu":
        return cpu
    known = globals().get(name)
    if isinstance(known, Device):
        return known
    raise ValueError(f"Unknown device, must be 'cpu' or '{_platform}', got {device!r}")


def use_device(device: Union[str, Device, None] = None) -> None:
    """Set the default device (reference ``use_device``, ``devices.py:157``)."""
    global __default_device
    __default_device = sanitize_device(device)
