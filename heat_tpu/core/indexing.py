"""Index-producing operations (reference ``heat/core/indexing.py``)."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = ["nonzero", "where"]


def _nonzero_distributed(x: DNDarray) -> DNDarray:
    """Distributed nonzero (reference keeps the result split,
    ``indexing.py:16``): a prefix-count compress over the global *flat*
    index space — the same three-piece machinery as ``x[mask]``
    (:mod:`heat_tpu.core._indexing`). The only host sync is the nonzero
    count ``m`` (dynamic output shape — unavoidable under XLA, SURVEY.md §7
    hard part 4); the coordinates themselves never leave the devices.
    """
    from . import _indexing
    from ._sort import _index_dtype

    comm = x.comm
    src = x if x.split == 0 else x.resplit(0)
    phys = src.larray
    total_flat = int(np.prod(phys.shape))
    local = total_flat // comm.size
    idt = _index_dtype()
    n_valid = x.size  # logical flat extent: rows beyond are padding

    sharding1 = comm.sharding(1, 0)
    flat_iota = jax.jit(
        lambda: jnp.arange(total_flat, dtype=idt), out_shardings=sharding1)()
    flat_vals = jax.jit(
        lambda a: a.reshape(-1), out_shardings=sharding1)(phys)
    mask = jax.jit(
        lambda v, f: (v != 0) & (f < n_valid), out_shardings=sharding1
    )(flat_vals, flat_iota)
    pos, total = _indexing.mask_positions_fn(local, comm)(mask)
    m = int(total)
    if m == 0:
        return DNDarray.from_logical(
            jnp.zeros((0, x.ndim), idt), None, x.device, comm)
    c_out = comm.chunk_size(m)
    fn = _indexing.ring_compress_fn(
        (total_flat,), jnp.dtype(idt), 0, m, c_out, comm)
    flat_kept = fn(flat_iota, pos)
    strides = []
    s = 1
    for dim in reversed(x.gshape):
        strides.append(s)
        s *= dim
    strides = strides[::-1]

    def unravel(fk):
        return jnp.stack(
            [(fk // int(strides[j])) % int(x.gshape[j])
             for j in range(x.ndim)], axis=1)

    coords = jax.jit(unravel, out_shardings=comm.sharding(2, 0))(flat_kept)
    return DNDarray(
        coords, (m, x.ndim), types.canonical_heat_type(idt), 0, x.device, comm)


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of nonzero elements as an (nnz, ndim) array (reference
    ``indexing.py:16``).

    Split arrays run the distributed prefix-count compress (result stays
    split along axis 0, matching the reference); only the nonzero *count*
    syncs to host — a dynamic output shape needs a concrete size under XLA.
    """
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    if x.split is not None and x.comm.size > 1 and x.size > 0 and x.ndim > 0:
        return _nonzero_distributed(x)
    logical = x._logical()
    idx = jnp.nonzero(logical)
    stacked = jnp.stack(idx, axis=1) if x.ndim > 0 else jnp.zeros((0, 0), jnp.int64)
    split = 0 if x.split is not None else None
    return DNDarray.from_logical(stacked, split, x.device, x.comm)


def where(cond, x=None, y=None) -> DNDarray:
    """Ternary select / nonzero (reference ``indexing.py:91``)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")
    if not isinstance(cond, DNDarray):
        raise TypeError(f"expected cond to be a DNDarray, but was {type(cond)}")

    from . import arithmetics

    # cond*x + (1-cond)*y with proper promotion, via the binary op engine
    c = cond.astype(types.canonical_heat_type(jnp.bool_))
    picked_x = _operations._binary_op(lambda c_, x_: jnp.where(c_, x_, 0), c, x)
    picked_y = _operations._binary_op(lambda c_, y_: jnp.where(c_, 0, y_), c, y)
    return arithmetics.add(picked_x, picked_y)
