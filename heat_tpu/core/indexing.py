"""Index-producing operations (reference ``heat/core/indexing.py``)."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = [
    "compress",
    "extract",
    "indices",
    "nonzero",
    "put_along_axis",
    "ravel_multi_index",
    "take",
    "take_along_axis",
    "trim_zeros",
    "unravel_index",
    "where",
]


def _nonzero_distributed(x: DNDarray) -> DNDarray:
    """Distributed nonzero (reference keeps the result split,
    ``indexing.py:16``): a prefix-count compress over the global *flat*
    index space — the same three-piece machinery as ``x[mask]``
    (:mod:`heat_tpu.core._indexing`). The only host sync is the nonzero
    count ``m`` (dynamic output shape — unavoidable under XLA, SURVEY.md §7
    hard part 4); the coordinates themselves never leave the devices.
    """
    from . import _indexing
    from ._sort import _index_dtype

    comm = x.comm
    src = x if x.split == 0 else x.resplit(0)
    phys = src.larray
    total_flat = int(np.prod(phys.shape))
    local = total_flat // comm.size
    idt = _index_dtype()
    n_valid = x.size  # logical flat extent: rows beyond are padding

    sharding1 = comm.sharding(1, 0)
    flat_iota = jax.jit(
        lambda: jnp.arange(total_flat, dtype=idt), out_shardings=sharding1)()
    flat_vals = jax.jit(
        lambda a: a.reshape(-1), out_shardings=sharding1)(phys)
    mask = jax.jit(
        lambda v, f: (v != 0) & (f < n_valid), out_shardings=sharding1
    )(flat_vals, flat_iota)
    pos, total = _indexing.mask_positions_fn(local, comm)(mask)
    m = int(total)
    if m == 0:
        return DNDarray.from_logical(
            jnp.zeros((0, x.ndim), idt), None, x.device, comm)
    c_out = comm.chunk_size(m)
    fn = _indexing.ring_compress_fn(
        (total_flat,), jnp.dtype(idt), 0, m, c_out, comm)
    flat_kept = fn(flat_iota, pos)
    strides = []
    s = 1
    for dim in reversed(x.gshape):
        strides.append(s)
        s *= dim
    strides = strides[::-1]

    def unravel(fk):
        return jnp.stack(
            [(fk // int(strides[j])) % int(x.gshape[j])
             for j in range(x.ndim)], axis=1)

    coords = jax.jit(unravel, out_shardings=comm.sharding(2, 0))(flat_kept)
    return DNDarray(
        coords, (m, x.ndim), types.canonical_heat_type(idt), 0, x.device, comm)


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of nonzero elements as an (nnz, ndim) array (reference
    ``indexing.py:16``).

    Split arrays run the distributed prefix-count compress (result stays
    split along axis 0, matching the reference); only the nonzero *count*
    syncs to host — a dynamic output shape needs a concrete size under XLA.
    """
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    if x.split is not None and x.comm.size > 1 and x.size > 0 and x.ndim > 0:
        return _nonzero_distributed(x)
    logical = x._logical()
    idx = jnp.nonzero(logical)
    stacked = jnp.stack(idx, axis=1) if x.ndim > 0 else jnp.zeros((0, 0), jnp.int64)
    split = 0 if x.split is not None else None
    return DNDarray.from_logical(stacked, split, x.device, x.comm)


def _pick_true(c_, x_):
    """``cond ? x : 0`` — module-level so the fusion engine can key it."""
    return jnp.where(c_, x_, 0)


def _pick_false(c_, y_):
    """``cond ? 0 : y`` — module-level so the fusion engine can key it."""
    return jnp.where(c_, 0, y_)


def where(cond, x=None, y=None) -> DNDarray:
    """Ternary select / nonzero (reference ``indexing.py:91``)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")
    if not isinstance(cond, DNDarray):
        raise TypeError(f"expected cond to be a DNDarray, but was {type(cond)}")

    from . import arithmetics

    # cond*x + (1-cond)*y with proper promotion, via the binary op engine
    c = cond.astype(types.canonical_heat_type(jnp.bool_))
    picked_x = _operations._binary_op(_pick_true, c, x)
    picked_y = _operations._binary_op(_pick_false, c, y)
    return arithmetics.add(picked_x, picked_y)


def take(a: DNDarray, indices, axis=None, out=None) -> DNDarray:
    """Elements at the given indices (``numpy.take``): routed through the
    distributed fancy getitem, which keeps the result split."""
    from . import factories, manipulations, _operations
    from .stride_tricks import sanitize_axis

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    idx = (indices.astype("int64") if isinstance(indices, DNDarray)
           else np.asarray(indices))
    if axis is None:
        flat = manipulations.flatten(a)
        result = flat[idx]
    else:
        axis = sanitize_axis(a.shape, axis)
        key = tuple(slice(None) for _ in range(axis)) + (idx,)
        result = a[key]
    return _operations._finalize(result, out)


def compress(condition, a: DNDarray, axis=None, out=None) -> DNDarray:
    """Selection by a 1-D boolean (``numpy.compress``): the condition is
    host-small by numpy's contract (it is truncated to its own length);
    data selection runs through :func:`take`."""
    cond = np.asarray(condition, dtype=bool).ravel()
    (idx,) = np.nonzero(cond)
    return take(a, idx, axis=axis, out=out)


def extract(condition, arr: DNDarray) -> DNDarray:
    """Flat elements where ``condition`` is nonzero (``numpy.extract``):
    the distributed boolean selection (stays split)."""
    from . import factories, manipulations

    if not isinstance(arr, DNDarray):
        arr = factories.array(arr)
    if not isinstance(condition, DNDarray):
        condition = factories.array(np.asarray(condition), comm=arr.comm,
                                    split=arr.split)
    flat = manipulations.flatten(arr)
    mask = manipulations.flatten(condition) != 0
    if mask.split != flat.split:
        mask = mask.resplit(flat.split)
    return flat[mask]


def trim_zeros(filt: DNDarray, trim: str = "fb") -> DNDarray:
    """Trim leading/trailing zeros of a 1-D array (``numpy.trim_zeros``).
    Only the two boundary positions sync to host (scalar fetches)."""
    from . import factories

    if not isinstance(filt, DNDarray):
        filt = factories.array(filt)
    if filt.ndim != 1:
        raise ValueError("trim_zeros expects a 1-D array")
    trim = trim.lower()
    nz = nonzero(filt != 0)
    nz = nz[0] if isinstance(nz, tuple) else nz
    if nz.size == 0:
        return filt[0:0]
    start = int(nz[0].item()) if "f" in trim else 0
    stop = int(nz[-1].item()) + 1 if "b" in trim else filt.shape[0]
    return filt[start:stop]


def unravel_index(indices, shape):
    """Flat indices -> coordinate tuple (``numpy.unravel_index``), as
    elementwise arithmetic on the (possibly split) index array."""
    from . import factories

    if not isinstance(indices, DNDarray):
        indices = factories.array(np.asarray(indices))
    total = int(np.prod(shape))
    # numpy raises for out-of-bounds flat indices; one scalar sync each
    hi = int(indices.max().item()) if indices.size else 0
    lo = int(indices.min().item()) if indices.size else 0
    if indices.size and (hi >= total or lo < 0):
        raise ValueError(
            f"index {hi if hi >= total else lo} is out of bounds for array "
            f"with size {total}")
    out = []
    stride = total
    for dim in shape:
        stride //= int(dim)
        out.append((indices // stride) % int(dim))
    return tuple(out)


def ravel_multi_index(multi_index, dims) -> DNDarray:
    """Coordinate tuple -> flat indices (``numpy.ravel_multi_index``)."""
    from . import factories

    arrs = [a if isinstance(a, DNDarray) else factories.array(np.asarray(a))
            for a in multi_index]
    if len(arrs) != len(dims):
        raise ValueError("multi_index length must match dims")
    flat = None
    stride = int(np.prod(dims))
    for a, dim in zip(arrs, dims):
        # numpy raises for out-of-range coordinates (one scalar sync each)
        if a.size and (int(a.max().item()) >= int(dim)
                       or int(a.min().item()) < 0):
            raise ValueError(
                f"invalid entry in coordinates array for dimension of "
                f"size {dim}")
        stride //= int(dim)
        term = a * stride
        flat = term if flat is None else flat + term
    return flat


def indices(dimensions, dtype=None, split=None) -> DNDarray:
    """Index grids (``numpy.indices``): shape ``(len(dims), *dims)``; pass
    ``split`` to shard the result (split counts the leading grid axis)."""
    from . import factories, types

    grids = np.indices(tuple(int(d) for d in dimensions))
    return factories.array(grids, dtype=dtype or types.int64, split=split)


def _align_indices(arr, indices, axis):
    """Indices as a DNDarray sharded like ``arr`` (same split; shapes may
    differ only along ``axis``), with numpy's out-of-bounds error."""
    from . import factories

    # broadcast dims (size 1 where arr is larger) must stay replicated —
    # sharding a length-1 dim across ranks is meaningless
    def _can_shard(idx):
        return (arr.split is not None and idx.ndim > arr.split
                and idx.shape[arr.split] == arr.shape[arr.split])

    if not isinstance(indices, DNDarray):
        ind_np = np.asarray(indices)
        split = (arr.split if (arr.split is not None
                               and ind_np.ndim > arr.split
                               and ind_np.shape[arr.split]
                               == arr.shape[arr.split]) else None)
        indices = factories.array(ind_np, split=split, comm=arr.comm)
    elif indices.split != arr.split:
        indices = indices.resplit(arr.split if _can_shard(indices) else None)
    if indices.size:
        hi = int(indices.max().item())
        lo = int(indices.min().item())
        if hi >= arr.shape[axis] or lo < -arr.shape[axis]:
            raise IndexError(
                f"index {hi if hi >= arr.shape[axis] else lo} is out of "
                f"bounds for axis {axis} with size {arr.shape[axis]}")
    return indices


def take_along_axis(arr: DNDarray, indices, axis) -> DNDarray:
    """Match-shaped gather (``numpy.take_along_axis``): per-shard
    ``jnp.take_along_axis`` once the split is off the gather axis (at most
    one reshard, no material gather)."""
    from . import factories, manipulations
    from .stride_tricks import sanitize_axis

    if not isinstance(arr, DNDarray):
        arr = factories.array(arr)
    if axis is None:
        return take_along_axis(manipulations.flatten(arr), indices, 0)
    axis = sanitize_axis(arr.shape, axis)
    if arr.split == axis and arr.comm.size > 1:
        arr = (arr.resplit((axis + 1) % arr.ndim) if arr.ndim > 1
               else arr.resplit(None))
    indices = _align_indices(arr, indices, axis)
    res = jnp.take_along_axis(arr.larray, indices.larray, axis=axis)
    # numpy broadcasts the non-gather dims of arr and indices
    gshape = tuple(np.broadcast_shapes(
        tuple(1 if i == axis else s for i, s in enumerate(arr.shape)),
        indices.gshape))
    return DNDarray(res, gshape, arr.dtype, arr.split, arr.device, arr.comm)


def put_along_axis(arr: DNDarray, indices, values, axis) -> None:
    """Match-shaped scatter (``numpy.put_along_axis``): updates ``arr`` in
    place (numpy semantics) via a per-shard functional scatter."""
    from . import factories, types
    from .stride_tricks import sanitize_axis

    if not isinstance(arr, DNDarray):
        raise TypeError("put_along_axis updates in place and requires a "
                        "DNDarray")
    if axis is None:
        raise NotImplementedError(
            "put_along_axis with axis=None (flattened in-place update) is "
            "not supported on the canonical layout; reshape explicitly")
    axis = sanitize_axis(arr.shape, axis)
    original_split = arr.split
    work = arr
    if work.split == axis and work.comm.size > 1:
        work = (work.resplit((axis + 1) % work.ndim) if work.ndim > 1
                else work.resplit(None))
    indices = _align_indices(work, indices, axis)
    if isinstance(values, DNDarray):
        # aligned same-shape values keep their shards; anything else
        # (scalars, broadcastable shapes) goes through the logical view
        if values.gshape == indices.gshape and values.split == work.split:
            vals = values.larray.astype(work.larray.dtype)
        else:
            vals = values._logical().astype(work.larray.dtype)
    else:
        vals = jnp.asarray(np.asarray(values), dtype=work.larray.dtype)
    res = jnp.put_along_axis(work.larray, indices.larray,
                             vals, axis=axis, inplace=False)
    updated = DNDarray(res, work.gshape, work.dtype, work.split, work.device,
                       work.comm)
    if updated.split != original_split:
        updated = updated.resplit(original_split)
    arr.larray = updated.larray
