"""Index-producing operations (reference ``heat/core/indexing.py``)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = ["nonzero", "where"]


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of nonzero elements as an (nnz, ndim) array (reference
    ``indexing.py:16``).

    Dynamic-shape op: the result is materialized replicated (host-synced
    count), the documented semantic for shape-data-dependent ops on the XLA
    backend (SURVEY.md §7, hard part 4).
    """
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    logical = x._logical()
    idx = jnp.nonzero(logical)
    stacked = jnp.stack(idx, axis=1) if x.ndim > 0 else jnp.zeros((0, 0), jnp.int64)
    split = 0 if x.split is not None else None
    return DNDarray.from_logical(stacked, split, x.device, x.comm)


def where(cond, x=None, y=None) -> DNDarray:
    """Ternary select / nonzero (reference ``indexing.py:91``)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")
    if not isinstance(cond, DNDarray):
        raise TypeError(f"expected cond to be a DNDarray, but was {type(cond)}")

    from . import arithmetics

    # cond*x + (1-cond)*y with proper promotion, via the binary op engine
    c = cond.astype(types.canonical_heat_type(jnp.bool_))
    picked_x = _operations._binary_op(lambda c_, x_: jnp.where(c_, x_, 0), c, x)
    picked_y = _operations._binary_op(lambda c_, y_: jnp.where(c_, 0, y_), c, y)
    return arithmetics.add(picked_x, picked_y)
