"""Distributed split-axis manipulations: destination-scatter ring programs.

TPU-native counterparts of the reference's point-to-point/Alltoallv
manipulations (``heat/core/manipulations.py``: concatenate ``:188``, reshape
``:1817``, roll ``:1985``, flip ``:1343``). Each op is a *static* global-row
permutation (or injection) along the split axis, so the XLA rendering is one
jitted shard_map program: the data blocks rotate around the mesh in ``p``
``ppermute`` steps and every device scatters the rows whose destination
falls in its output range — O(chunk) memory per device, no materialization
of the logical array, and no all-gather anywhere in the HLO (the round-2
VERDICT #4 done-criterion).

The canonical layout invariant (valid rows occupy global positions
``0..n-1``, padding at the tail) holds for inputs and outputs alike;
destinations are computed from *global* row positions, so padded and
non-block-aligned shapes need no special cases.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map

from ._sort import _index_dtype

__all__ = [
    "ring_roll_fn",
    "ring_flip_fn",
    "ring_concat_fn",
    "ring_reshape_fn",
    "ring_repeat_fn",
]

_MANIP_CACHE: dict = {}


def _scatter_ring(buf, out, me, owner0, c_in, c_out, dest_of, comm):
    """Scatter ``buf``'s rows (rotating around the ring) into ``out`` by the
    static destination map ``dest_of(global_row) -> global_row | -1``."""
    p = comm.size
    idt = _index_dtype()
    for k in range(p):
        owner = (owner0 - k) % p
        gpos = owner * c_in + jnp.arange(c_in, dtype=idt)
        dest = dest_of(gpos)
        rel = dest - me * c_out
        tgt = jnp.where((rel >= 0) & (rel < c_out) & (dest >= 0), rel, c_out)
        out = out.at[tgt].set(buf, mode="drop")
        if k < p - 1:
            buf = comm.ring_shift(buf, 1)
    return out


def _ring_permute_factory(key, phys_shape, axis, c_out, make_dest, comm):
    """Build & cache a jitted ``x_physical -> out_physical`` program whose
    output block ``d`` holds rows ``[d*c_out, (d+1)*c_out)`` of the permuted
    global sequence."""
    fn = _MANIP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c_in = phys_shape[axis] // p

    def body(xb):
        buf = jnp.moveaxis(xb, axis, 0)  # (c_in, rest...)
        me = jax.lax.axis_index(comm.axis_name)
        out = jnp.zeros((c_out,) + buf.shape[1:], buf.dtype)
        out = _scatter_ring(buf, out, me, me, c_in, c_out, make_dest, comm)
        return jnp.moveaxis(out, 0, axis)

    spec = comm.spec(len(phys_shape), axis)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=spec, out_specs=spec,
                  check_vma=False)
    )
    _MANIP_CACHE[key] = fn
    return fn


def ring_roll_fn(phys_shape, jdt, axis: int, n: int, shift: int, comm):
    """``out[(g + shift) % n] = in[g]`` along the split axis (reference
    ``roll``, ``manipulations.py:1985``)."""
    shift = int(shift) % n if n else 0
    idt = _index_dtype()

    def dest(gpos):
        return jnp.where(gpos < n, (gpos + shift) % n, jnp.asarray(-1, idt))

    key = ("rroll", tuple(phys_shape), str(jdt), axis, n, shift, comm.cache_key)
    c_out = phys_shape[axis] // comm.size
    return _ring_permute_factory(key, phys_shape, axis, c_out, dest, comm)


def ring_flip_fn(phys_shape, jdt, axis: int, n: int, comm):
    """``out[n - 1 - g] = in[g]`` along the split axis (reference ``flip``,
    ``manipulations.py:1343``)."""
    idt = _index_dtype()

    def dest(gpos):
        return jnp.where(gpos < n, n - 1 - gpos, jnp.asarray(-1, idt))

    key = ("rflip", tuple(phys_shape), str(jdt), axis, n, comm.cache_key)
    c_out = phys_shape[axis] // comm.size
    return _ring_permute_factory(key, phys_shape, axis, c_out, dest, comm)


def ring_concat_fn(phys_shapes, jdt, axis: int, ns, c_out: int, comm):
    """Jitted ``(*x_physicals) -> out_physical``: concatenation of ``k``
    split arrays along their shared split axis (reference ``concatenate``,
    ``manipulations.py:188``). Array ``i``'s valid rows shift by
    ``sum(ns[:i])``; every input streams through its own ring into the
    shared output block."""
    key = ("rconcat", tuple(map(tuple, phys_shapes)), str(jdt), axis,
           tuple(ns), c_out, comm.cache_key)
    fn = _MANIP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    idt = _index_dtype()
    offsets = np.concatenate([[0], np.cumsum(ns)]).astype(np.int64)

    def body(*xbs):
        me = jax.lax.axis_index(comm.axis_name)
        first = jnp.moveaxis(xbs[0], axis, 0)
        out = jnp.zeros((c_out,) + first.shape[1:], first.dtype)
        for i, xb in enumerate(xbs):
            buf = jnp.moveaxis(xb, axis, 0)
            n_i, off = int(ns[i]), int(offsets[i])
            c_in = buf.shape[0]

            def dest(gpos, n_i=n_i, off=off):
                return jnp.where(gpos < n_i, gpos + off, jnp.asarray(-1, idt))

            out = _scatter_ring(buf, out, me, me, c_in, c_out, dest, comm)
        return jnp.moveaxis(out, 0, axis)

    specs = tuple(comm.spec(len(s), axis) for s in phys_shapes)
    out_spec = comm.spec(len(phys_shapes[0]), axis)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=specs, out_specs=out_spec,
                  check_vma=False)
    )
    _MANIP_CACHE[key] = fn
    return fn


def ring_repeat_fn(phys_shape, jdt, axis: int, n: int, rep: int, c_out: int,
                   comm):
    """Jitted ``x_physical -> out_physical``: each valid row ``g`` fans out
    to output rows ``g*rep .. g*rep+rep-1`` along the split axis (reference
    ``repeat``, ``manipulations.py:1770``, scalar repeats). One ring pass
    with ``rep`` scatter sub-steps per rotation."""
    key = ("rrepeat", tuple(phys_shape), str(jdt), axis, n, rep, c_out,
           comm.cache_key)
    fn = _MANIP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c_in = phys_shape[axis] // p
    idt = _index_dtype()

    def body(xb):
        buf = jnp.moveaxis(xb, axis, 0)
        me = jax.lax.axis_index(comm.axis_name)
        out = jnp.zeros((c_out,) + buf.shape[1:], buf.dtype)
        for k in range(p):
            owner = (me - k) % p
            gpos = owner * c_in + jnp.arange(c_in, dtype=idt)
            for jj in range(rep):
                dest = jnp.where(gpos < n, gpos * rep + jj,
                                 jnp.asarray(-1, idt))
                rel = dest - me * c_out
                tgt = jnp.where((rel >= 0) & (rel < c_out) & (dest >= 0),
                                rel, c_out)
                out = out.at[tgt].set(buf, mode="drop")
            if k < p - 1:
                buf = comm.ring_shift(buf, 1)
        return jnp.moveaxis(out, 0, axis)

    spec = comm.spec(len(phys_shape), axis)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=spec, out_specs=spec,
                  check_vma=False)
    )
    _MANIP_CACHE[key] = fn
    return fn


def split_topk_fn(phys_shape, jdt, axis: int, n: int, k: int, largest: bool,
                  comm):
    """Jitted ``x_physical -> (values, global_indices)``, replicated, shapes
    ``(..., k)`` on the moved-to-last split axis.

    The reference's ``mpi_topk`` (``manipulations.py:3971``) is an Allreduce
    whose custom op merges per-rank top-k lists; the XLA rendering is the
    same tournament: local ``top_k`` over the shard (padding masked with the
    sentinel), an all-gather of the ``p * min(k, c)`` candidates — O(p k),
    never the data — and a final local ``top_k``."""
    key = ("stopk", tuple(phys_shape), str(jdt), axis, n, k, largest,
           comm.cache_key)
    fn = _MANIP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c = phys_shape[axis] // p
    kk = min(k, c)
    idt = _index_dtype()
    floating = jnp.issubdtype(jdt, jnp.floating)
    unsigned = jnp.issubdtype(jdt, jnp.unsignedinteger)
    if floating:
        sentinel = -jnp.inf if largest else jnp.inf
    elif jdt == jnp.dtype(jnp.bool_):
        sentinel = not largest
    else:
        info = jnp.iinfo(jdt)
        sentinel = info.min if largest else info.max

    def keyed(v):
        """Monotone selection key: top_k picks largest, so negate for
        smallest (on a signed view — unsigned negation wraps)."""
        if jdt == jnp.dtype(jnp.bool_):
            v = v.astype(jnp.int32)
        elif unsigned:
            v = v.astype(jnp.int64 if jnp.dtype(jdt).itemsize >= 4
                         else jnp.int32)
        return v if largest else -v

    def body(xb):
        buf = jnp.moveaxis(xb, axis, -1)  # (..., c)
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c, dtype=idt)
        vals = jnp.where(gpos < n, buf, jnp.asarray(sentinel, buf.dtype))
        _, li = jax.lax.top_k(keyed(vals), kk)
        lv = jnp.take_along_axis(vals, li, axis=-1)
        gi = jnp.broadcast_to(gpos, vals.shape)
        gi = jnp.take_along_axis(gi, li, axis=-1)
        cand_v = jax.lax.all_gather(lv, comm.axis_name, axis=-1, tiled=True)
        cand_i = jax.lax.all_gather(gi, comm.axis_name, axis=-1, tiled=True)
        _, fi = jax.lax.top_k(keyed(cand_v), k)
        out_v = jnp.take_along_axis(cand_v, fi, axis=-1)
        out_i = jnp.take_along_axis(cand_i, fi, axis=-1)
        return out_v, out_i

    spec_in = comm.spec(len(phys_shape), axis)
    spec_out = comm.spec(len(phys_shape), None)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=spec_in,
                  out_specs=(spec_out, spec_out), check_vma=False)
    )
    _MANIP_CACHE[key] = fn
    return fn


def ring_reshape_fn(in_phys_shape, jdt, out_gshape, c_out: int, comm):
    """Jitted ``x_physical(split=0) -> out_physical(split=0)`` reshape.

    Row-major order is preserved by reshape, so the global flat element
    sequence is identical before and after — reshape is a *re-chunking* of
    that sequence (the reference's Alltoallv formulation,
    ``manipulations.py:1817``). Each device's input shard is one contiguous
    flat range; the rings rotate those ranges and every device takes the
    elements landing in its output flat range. Callers resplit to axis 0 on
    both sides (one reshard program each) for other splits.
    """
    key = ("rreshape", tuple(in_phys_shape), str(jdt), tuple(out_gshape),
           c_out, comm.cache_key)
    fn = _MANIP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    idt = _index_dtype()
    c1 = in_phys_shape[0] // p
    r1 = int(np.prod(in_phys_shape[1:], dtype=np.int64))
    r2 = int(np.prod(out_gshape[1:], dtype=np.int64))
    total = int(np.prod(out_gshape, dtype=np.int64))
    local_in = c1 * r1
    local_out = c_out * r2

    def body(xb):
        flat = xb.reshape(-1)  # this device's contiguous flat range
        me = jax.lax.axis_index(comm.axis_name)
        f = me * local_out + jnp.arange(local_out, dtype=idt)  # my out slots
        out = jnp.zeros((local_out,), flat.dtype)
        q = f // r1  # source global row
        col = f % r1
        for k in range(p):
            o = (me - k) % p
            rel = (q - o * c1) * r1 + col
            hit = (q >= o * c1) & (q < (o + 1) * c1) & (f < total)
            take = flat[jnp.clip(rel, 0, local_in - 1)]
            out = jnp.where(hit, take, out)
            if k < p - 1:
                flat = comm.ring_shift(flat, 1)
        return out.reshape((c_out,) + tuple(out_gshape[1:]))

    fn = jax.jit(
        shard_map(body, mesh=comm.mesh,
                  in_specs=comm.spec(len(in_phys_shape), 0),
                  out_specs=comm.spec(len(out_gshape), 0), check_vma=False)
    )
    _MANIP_CACHE[key] = fn
    return fn
