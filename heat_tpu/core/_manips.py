"""Distributed split-axis manipulations: scheduled block-window fetches.

TPU-native counterparts of the reference's point-to-point/Alltoallv
manipulations (``heat/core/manipulations.py``: concatenate ``:188``, reshape
``:1817``, roll ``:1985``, flip ``:1343``). Each op is a *static* monotone
source map along the split axis, so each output device needs a CONTIGUOUS
range of source rows spanning only ~``c_out/c_in + 1`` source blocks. The
(sender block -> receiver device) demand graph is computed in Python at
trace time and greedily edge-colored into rounds where every round is a
partial permutation — one ``ppermute`` each. Result: O(1) collective rounds
and O(n) total traffic (vs O(p) rounds / O(p n) for a naive rotation ring),
O(chunk) memory per device, no materialization of the logical array, and no
all-gather anywhere in the HLO (the round-2 VERDICT #4 done-criterion).

The canonical layout invariant (valid rows occupy global positions
``0..n-1``, padding at the tail) holds for inputs and outputs alike; source
positions are *global*, so padded and non-block-aligned shapes need no
special cases — a receiver unserved in a round has owner -1 in its table
entry, which the hit mask rejects (ppermute delivers zeros there).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from ._compat import shard_map

from ._sort import _index_dtype

__all__ = [
    "ring_roll_fn",
    "ring_flip_fn",
    "ring_concat_fn",
    "ring_reshape_fn",
    "ring_repeat_fn",
]

_MANIP_CACHE: dict = {}


def _row_mask(hit, row_ndim):
    return hit.reshape(hit.shape + (1,) * row_ndim)


def _demand_blocks(src_at, glo: int, ghi: int, p: int, c_out: int,
                   c_in: int):
    """Per-output-device lists of source blocks needed, computed statically.

    ``src_at(go) -> int`` is the (python) source-position map, monotone over
    the valid output interval ``[glo, ghi)``; each device's needed source
    rows therefore form a contiguous range, read off the clamped endpoints.
    """
    demands = []
    for e in range(p):
        lo = max(e * c_out, glo)
        hi = min((e + 1) * c_out, ghi) - 1
        if lo > hi:
            demands.append([])
            continue
        s0, s1 = src_at(lo), src_at(hi)
        b0, b1 = sorted((s0 // c_in, s1 // c_in))
        b0, b1 = max(b0, 0), min(b1, p - 1)
        demands.append(list(range(b0, b1 + 1)))
    return demands


def _schedule_block_fetch(demands, p: int):
    """Greedy edge-coloring of the (sender block -> receiver device) demand
    graph into rounds where every round is a partial permutation — one
    ``ppermute`` each. Shift-like maps need only ~(c_out/c_in + 1) rounds
    instead of the p rotations of a full ring. Returns
    ``[(perm_pairs, owner_table)]`` with ``owner_table[e]`` = the block
    device ``e`` receives that round (-1: none)."""
    remaining = [list(s) for s in demands]
    rounds = []
    while any(remaining):
        used = set()
        perm = []
        table = np.full(p, -1, np.int64)
        progressed = False
        for e in range(p):
            for s in remaining[e]:
                if s not in used:
                    used.add(s)
                    perm.append((s, e))
                    table[e] = s
                    remaining[e].remove(s)
                    progressed = True
                    break
        if not progressed:  # cannot happen, but never loop forever
            break
        rounds.append((perm, table))
    return rounds


def _window_gather(buf, me, src, rounds, c_in, comm, out):
    """Apply scheduled block fetches: ``out[i] = buf_global[src[i]]``.

    ``src`` carries global source positions (-1 = no source). Receivers not
    served in a round see owner -1 in their table entry and keep ``out``
    unchanged (ppermute delivers zeros there, which the hit mask ignores)."""
    for perm, table in rounds:
        blk = jax.lax.ppermute(buf, comm.axis_name, perm=perm)
        owner = jnp.asarray(table)[me]
        rel = src - owner * c_in
        hit = (owner >= 0) & (src >= 0) & (rel >= 0) & (rel < c_in)
        take = jnp.take(blk, jnp.clip(rel, 0, c_in - 1), axis=0)
        out = jnp.where(_row_mask(hit, buf.ndim - 1), take, out)
    return out


def _window_factory(key, phys_shape, axis, c_in, c_out, rounds, make_src,
                    comm):
    """Cache + compile the common single-input window program:
    ``out[go] = in_global[make_src(go)]`` along ``axis`` with ``c_out`` rows
    per device (roll/flip/repeat share this; concat and reshape have their
    own bodies)."""
    fn = _MANIP_CACHE.get(key)
    if fn is not None:
        return fn
    idt = _index_dtype()

    def body(xb):
        buf = jnp.moveaxis(xb, axis, 0)
        me = jax.lax.axis_index(comm.axis_name)
        go = me * c_out + jnp.arange(c_out, dtype=idt)
        out = jnp.zeros((c_out,) + buf.shape[1:], buf.dtype)
        out = _window_gather(buf, me, make_src(go), rounds, c_in, comm, out)
        return jnp.moveaxis(out, 0, axis)

    spec = comm.spec(len(phys_shape), axis)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=spec, out_specs=spec,
                  check_vma=False)
    )
    _MANIP_CACHE[key] = fn
    return fn


def ring_roll_fn(phys_shape, jdt, axis: int, n: int, shift: int, comm):
    """``out[(g + shift) % n] = in[g]`` along the split axis (reference
    ``roll``, ``manipulations.py:1985``). Two affine fetch segments (the
    wrap), scheduled into O(1) ppermute rounds."""
    shift = int(shift) % n if n else 0
    key = ("rroll", tuple(phys_shape), str(jdt), axis, n, shift, comm.cache_key)
    if key in _MANIP_CACHE:
        return _MANIP_CACHE[key]
    p = comm.size
    c = phys_shape[axis] // p
    idt = _index_dtype()
    s = shift
    seg1 = _demand_blocks(lambda go: go - s + n, 0, min(s, n), p, c, c)
    seg2 = _demand_blocks(lambda go: go - s, s, n, p, c, c)
    rounds = _schedule_block_fetch(
        [sorted(set(a) | set(b)) for a, b in zip(seg1, seg2)], p)

    def src(go):
        return jnp.where(go < n,
                         jnp.where(go < s, go - s + n, go - s),
                         jnp.asarray(-1, idt))

    return _window_factory(key, phys_shape, axis, c, c, rounds, src, comm)


def ring_flip_fn(phys_shape, jdt, axis: int, n: int, comm):
    """``out[n - 1 - g] = in[g]`` along the split axis (reference ``flip``,
    ``manipulations.py:1343``): the block-reversal permutation plus its
    neighbor, two ppermute rounds."""
    key = ("rflip", tuple(phys_shape), str(jdt), axis, n, comm.cache_key)
    if key in _MANIP_CACHE:
        return _MANIP_CACHE[key]
    p = comm.size
    c = phys_shape[axis] // p
    idt = _index_dtype()
    rounds = _schedule_block_fetch(
        _demand_blocks(lambda go: n - 1 - go, 0, n, p, c, c), p)

    def src(go):
        return jnp.where(go < n, n - 1 - go, jnp.asarray(-1, idt))

    return _window_factory(key, phys_shape, axis, c, c, rounds, src, comm)


def ring_concat_fn(phys_shapes, jdt, axis: int, ns, c_out: int, comm):
    """Jitted ``(*x_physicals) -> out_physical``: concatenation of ``k``
    split arrays along their shared split axis (reference ``concatenate``,
    ``manipulations.py:188``). Array ``i``'s rows shift by ``sum(ns[:i])``;
    each input's boundary blocks move in O(c_out/c_in) scheduled ppermute
    rounds (the reference's point-to-point boundary exchange)."""
    key = ("rconcat", tuple(map(tuple, phys_shapes)), str(jdt), axis,
           tuple(ns), c_out, comm.cache_key)
    fn = _MANIP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    idt = _index_dtype()
    offsets = np.concatenate([[0], np.cumsum(ns)]).astype(np.int64)
    cs = [int(s[axis]) // p for s in phys_shapes]
    all_rounds = []
    for i, n_i in enumerate(ns):
        off = int(offsets[i])
        dem = _demand_blocks(lambda go, off=off: go - off,
                             off, off + int(n_i), p, c_out, cs[i])
        all_rounds.append(_schedule_block_fetch(dem, p))

    def body(*xbs):
        me = jax.lax.axis_index(comm.axis_name)
        go = me * c_out + jnp.arange(c_out, dtype=idt)
        first = jnp.moveaxis(xbs[0], axis, 0)
        out = jnp.zeros((c_out,) + first.shape[1:], first.dtype)
        for i, xb in enumerate(xbs):
            buf = jnp.moveaxis(xb, axis, 0)
            n_i, off = int(ns[i]), int(offsets[i])
            src = jnp.where((go >= off) & (go < off + n_i), go - off,
                            jnp.asarray(-1, idt))
            out = _window_gather(buf, me, src, all_rounds[i], cs[i], comm,
                                 out)
        return jnp.moveaxis(out, 0, axis)

    specs = tuple(comm.spec(len(s), axis) for s in phys_shapes)
    out_spec = comm.spec(len(phys_shapes[0]), axis)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=specs, out_specs=out_spec,
                  check_vma=False)
    )
    _MANIP_CACHE[key] = fn
    return fn


def ring_repeat_fn(phys_shape, jdt, axis: int, n: int, rep: int, c_out: int,
                   comm):
    """Jitted ``x_physical -> out_physical``: each valid row ``g`` fans out
    to output rows ``g*rep .. g*rep+rep-1`` along the split axis (reference
    ``repeat``, ``manipulations.py:1770``, scalar repeats). Receiver-side
    map ``src(go) = go // rep`` through the scheduled window fetch."""
    key = ("rrepeat", tuple(phys_shape), str(jdt), axis, n, rep, c_out,
           comm.cache_key)
    if key in _MANIP_CACHE:
        return _MANIP_CACHE[key]
    p = comm.size
    c_in = phys_shape[axis] // p
    idt = _index_dtype()
    rounds = _schedule_block_fetch(
        _demand_blocks(lambda go: go // rep, 0, n * rep, p, c_out, c_in), p)

    def src(go):
        return jnp.where(go < n * rep, go // rep, jnp.asarray(-1, idt))

    return _window_factory(key, phys_shape, axis, c_in, c_out, rounds, src,
                           comm)


def ring_slice_fn(phys_shape, jdt, axis: int, start: int, step: int, L: int,
                  c_out: int, comm):
    """Jitted contiguous/strided slice along the split axis: ``out[go] =
    in[start + go*step]`` for ``go < L`` (reference basic ``__getitem__``
    slicing, ``dndarray.py:656-912``). An affine map — one scheduled window
    fetch re-chunks the selection into canonical layout."""
    key = ("rslice", tuple(phys_shape), str(jdt), axis, start, step, L,
           c_out, comm.cache_key)
    if key in _MANIP_CACHE:
        return _MANIP_CACHE[key]
    p = comm.size
    c_in = phys_shape[axis] // p
    idt = _index_dtype()
    rounds = _schedule_block_fetch(
        _demand_blocks(lambda go: start + go * step, 0, L, p, c_out, c_in), p)

    def src(go):
        return jnp.where(go < L, start + go * step, jnp.asarray(-1, idt))

    return _window_factory(key, phys_shape, axis, c_in, c_out, rounds, src,
                           comm)


def ring_pad_fn(phys_shape, jdt, axis: int, n: int, before: int, after: int,
                mode: str, comm):
    """Jitted split-axis pad for the boundary-sourcing modes (reference
    ``pad``, ``manipulations.py:1128``): ``reflect``/``symmetric``/``edge``/
    ``wrap``. Each pad region is a static (piecewise-monotone) source map
    into the valid rows, so the scheduled window fetch applies: the body
    copies through, the margins fetch their mirror/edge/wrap sources."""
    key = ("rpad", tuple(phys_shape), str(jdt), axis, n, before, after, mode,
           comm.cache_key)
    fn = _MANIP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c_in = phys_shape[axis] // p
    n_out = n + before + after
    c_out = comm.chunk_size(n_out)
    idt = _index_dtype()

    def src_py(go):
        """Python mirror of the traced map (for demand computation)."""
        rel = go - before
        if 0 <= rel < n:
            return rel
        if mode == "edge":
            return 0 if rel < 0 else n - 1
        if mode == "wrap":
            return rel % n
        if mode == "symmetric":
            period = 2 * n
            r = rel % period if rel >= 0 else (rel % period + period) % period
            return r if r < n else period - 1 - r
        # reflect: period 2n-2 (no repeated edge)
        period = max(2 * n - 2, 1)
        r = rel % period if rel >= 0 else (rel % period + period) % period
        return r if r < n else period - r

    # demands: evaluate per region (each monotone); union per receiver
    regions = [(0, before), (before, before + n), (before + n, n_out)]
    demands = [set() for _ in range(p)]
    for glo, ghi in regions:
        for e in range(p):
            lo = max(e * c_out, glo)
            hi = min((e + 1) * c_out, ghi) - 1
            if lo > hi:
                continue
            # piecewise-monotone: sample the endpoints AND the interior
            # extrema candidates (period fold points); small intervals are
            # sampled exhaustively so a missed extremum cannot drop a block
            if hi - lo < 4096:
                cand = set(range(lo, hi + 1))
            else:
                cand = {lo, hi}
                if mode in ("reflect", "symmetric", "wrap"):
                    period = {"reflect": max(2 * n - 2, 1),
                              "symmetric": 2 * n, "wrap": n}[mode]
                    k0 = (lo - before) // period
                    k1 = (hi - before) // period + 1
                    for k in range(k0, k1 + 1):
                        for boundary in (before + k * period,
                                         before + k * period + n - 1,
                                         before + k * period + n):
                            if lo <= boundary <= hi:
                                cand.add(boundary)
            srcs = [src_py(g) for g in cand]
            b0, b1 = max(min(srcs) // c_in, 0), min(max(srcs) // c_in, p - 1)
            demands[e].update(range(b0, b1 + 1))
    rounds = _schedule_block_fetch([sorted(d) for d in demands], p)

    def src_traced(go):
        rel = go - before
        if mode == "edge":
            src = jnp.clip(rel, 0, n - 1)
        elif mode == "wrap":
            src = rel % n
        elif mode == "symmetric":
            r = rel % (2 * n)
            src = jnp.where(r < n, r, 2 * n - 1 - r)
        else:  # reflect
            period = max(2 * n - 2, 1)
            r = rel % period
            src = jnp.where(r < n, r, period - r)
        return jnp.where(go < n_out, src, jnp.asarray(-1, idt)).astype(idt)

    return _window_factory(key, phys_shape, axis, c_in, c_out, rounds,
                           src_traced, comm)


def split_diff_fn(phys_shape, jdt, axis: int, n: int, comm):
    """Jitted first-order ``diff`` along the split axis (reference ``diff``,
    ``arithmetics.py:563``): ``out[g] = in[g+1] - in[g]`` for ``g < n-1``
    (bool: xor, numpy semantics). One scheduled window pass serves both
    source maps; output re-chunks to length ``n - 1``."""
    key = ("sdiff", tuple(phys_shape), str(jdt), axis, n, comm.cache_key)
    fn = _MANIP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c_in = phys_shape[axis] // p
    c_out = comm.chunk_size(n - 1)
    idt = _index_dtype()
    d1 = _demand_blocks(lambda go: go, 0, n - 1, p, c_out, c_in)
    d2 = _demand_blocks(lambda go: go + 1, 0, n - 1, p, c_out, c_in)
    rounds = _schedule_block_fetch(
        [sorted(set(a) | set(b)) for a, b in zip(d1, d2)], p)
    is_bool = jnp.dtype(jdt) == jnp.bool_

    def body(xb):
        buf = jnp.moveaxis(xb, axis, 0)
        me = jax.lax.axis_index(comm.axis_name)
        go = me * c_out + jnp.arange(c_out, dtype=idt)
        valid = go < n - 1
        srcs = (jnp.where(valid, go, jnp.asarray(-1, idt)),
                jnp.where(valid, go + 1, jnp.asarray(-1, idt)))
        outs = [jnp.zeros((c_out,) + buf.shape[1:], buf.dtype)
                for _ in srcs]
        for perm, table in rounds:
            blk = jax.lax.ppermute(buf, comm.axis_name, perm=perm)
            owner = jnp.asarray(table)[me]
            for j, src in enumerate(srcs):
                rel = src - owner * c_in
                hit = (owner >= 0) & (src >= 0) & (rel >= 0) & (rel < c_in)
                take = jnp.take(blk, jnp.clip(rel, 0, c_in - 1), axis=0)
                outs[j] = jnp.where(_row_mask(hit, buf.ndim - 1), take,
                                    outs[j])
        res = (outs[1] != outs[0]) if is_bool else (outs[1] - outs[0])
        return jnp.moveaxis(res, 0, axis)

    spec = comm.spec(len(phys_shape), axis)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=spec, out_specs=spec,
                  check_vma=False)
    )
    _MANIP_CACHE[key] = fn
    return fn


def split_topk_fn(phys_shape, jdt, axis: int, n: int, k: int, largest: bool,
                  comm):
    """Jitted ``x_physical -> (values, global_indices)``, replicated, shapes
    ``(..., k)`` on the moved-to-last split axis.

    The reference's ``mpi_topk`` (``manipulations.py:3971``) is an Allreduce
    whose custom op merges per-rank top-k lists; the XLA rendering is the
    same tournament: local ``top_k`` over the shard (padding masked with the
    sentinel), an all-gather of the ``p * min(k, c)`` candidates — O(p k),
    never the data — and a final local ``top_k``."""
    key = ("stopk", tuple(phys_shape), str(jdt), axis, n, k, largest,
           comm.cache_key)
    fn = _MANIP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c = phys_shape[axis] // p
    kk = min(k, c)
    idt = _index_dtype()
    floating = jnp.issubdtype(jdt, jnp.floating)
    unsigned = jnp.issubdtype(jdt, jnp.unsignedinteger)
    if floating:
        sentinel = -jnp.inf if largest else jnp.inf
    elif jdt == jnp.dtype(jnp.bool_):
        sentinel = not largest
    else:
        info = jnp.iinfo(jdt)
        sentinel = info.min if largest else info.max

    def keyed(v):
        """Monotone selection key: top_k picks largest, so negate for
        smallest (on a signed view — unsigned negation wraps)."""
        if jdt == jnp.dtype(jnp.bool_):
            v = v.astype(jnp.int32)
        elif unsigned:
            v = v.astype(jnp.int64 if jnp.dtype(jdt).itemsize >= 4
                         else jnp.int32)
        return v if largest else -v

    def body(xb):
        buf = jnp.moveaxis(xb, axis, -1)  # (..., c)
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c, dtype=idt)
        vals = jnp.where(gpos < n, buf, jnp.asarray(sentinel, buf.dtype))
        _, li = jax.lax.top_k(keyed(vals), kk)
        lv = jnp.take_along_axis(vals, li, axis=-1)
        gi = jnp.broadcast_to(gpos, vals.shape)
        gi = jnp.take_along_axis(gi, li, axis=-1)
        cand_v = jax.lax.all_gather(lv, comm.axis_name, axis=-1, tiled=True)
        cand_i = jax.lax.all_gather(gi, comm.axis_name, axis=-1, tiled=True)
        _, fi = jax.lax.top_k(keyed(cand_v), k)
        out_v = jnp.take_along_axis(cand_v, fi, axis=-1)
        out_i = jnp.take_along_axis(cand_i, fi, axis=-1)
        return out_v, out_i

    spec_in = comm.spec(len(phys_shape), axis)
    spec_out = comm.spec(len(phys_shape), None)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=spec_in,
                  out_specs=(spec_out, spec_out), check_vma=False)
    )
    _MANIP_CACHE[key] = fn
    return fn


def ring_reshape_fn(in_phys_shape, jdt, out_gshape, c_out: int, comm):
    """Jitted ``x_physical(split=0) -> out_physical(split=0)`` reshape.

    Row-major order is preserved by reshape, so the global flat element
    sequence is identical before and after — reshape is a *re-chunking* of
    that sequence (the reference's Alltoallv formulation,
    ``manipulations.py:1817``). Each device's input shard is one contiguous
    flat range; the rings rotate those ranges and every device takes the
    elements landing in its output flat range. Callers resplit to axis 0 on
    both sides (one reshard program each) for other splits.
    """
    key = ("rreshape", tuple(in_phys_shape), str(jdt), tuple(out_gshape),
           c_out, comm.cache_key)
    fn = _MANIP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    idt = _index_dtype()
    c1 = in_phys_shape[0] // p
    r1 = int(np.prod(in_phys_shape[1:], dtype=np.int64))
    r2 = int(np.prod(out_gshape[1:], dtype=np.int64))
    total = int(np.prod(out_gshape, dtype=np.int64))
    local_in = c1 * r1
    local_out = c_out * r2
    # the flat sequence is preserved: re-chunking is the identity map over
    # flat positions, so each device needs ~local_out/local_in + 1 windows
    rounds = _schedule_block_fetch(
        _demand_blocks(lambda f: f, 0, total, p, local_out, local_in), p)

    def body(xb):
        flat = xb.reshape(-1)  # this device's contiguous flat range
        me = jax.lax.axis_index(comm.axis_name)
        f = me * local_out + jnp.arange(local_out, dtype=idt)  # my out slots
        src = jnp.where(f < total, f, jnp.asarray(-1, idt))
        out = jnp.zeros((local_out,), flat.dtype)
        out = _window_gather(flat, me, src, rounds, local_in, comm, out)
        return out.reshape((c_out,) + tuple(out_gshape[1:]))

    fn = jax.jit(
        shard_map(body, mesh=comm.mesh,
                  in_specs=comm.spec(len(in_phys_shape), 0),
                  out_specs=comm.spec(len(out_gshape), 0), check_vma=False)
    )
    _MANIP_CACHE[key] = fn
    return fn
