"""Input checking and distribution alignment (reference ``heat/core/sanitation.py``).

``sanitize_distribution`` (reference ``:31-157``) is where the reference
triggers redistribution so binary operands share an lshape map. Under the
canonical even layout the only alignment needed is a *split-axis match* —
the physical shards of equal-gshape operands are automatically congruent, so
alignment reduces to ``resplit`` (an XLA reshard) instead of a point-to-point
shuffle.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .communication import sanitize_comm
from .dndarray import DNDarray

__all__ = [
    "sanitize_in",
    "sanitize_in_tensor",
    "sanitize_infinity",
    "sanitize_lshape",
    "sanitize_sequence",
    "sanitize_out",
    "sanitize_distribution",
    "scalar_to_1d",
]


def sanitize_in(x) -> None:
    """Verify ``x`` is a DNDarray (reference ``sanitation.py:14``)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input must be a DNDarray, got {type(x)}")


def sanitize_in_tensor(x) -> None:
    """Verify ``x`` is a backend array (reference checks torch.Tensor,
    ``sanitation.py:200``; the backend tensor here is ``jax.Array``)."""
    import jax

    if not isinstance(x, (jax.Array,)):
        raise TypeError(f"input must be a jax.Array, got {type(x)}")


def sanitize_lshape(array, tensor) -> None:
    """Verify a local tensor fits the array's shard layout
    (reference ``sanitation.py:220``)."""
    import numpy as np_

    tshape = tuple(tensor.shape)
    gshape = tuple(array.gshape)
    if array.split is None:
        if tshape != gshape:
            raise ValueError(f"local tensor shape {tshape} does not match global shape {gshape}")
        return
    expected = list(gshape)
    expected[array.split] = array.larray.shape[array.split]
    if tshape != tuple(expected):
        raise ValueError(
            f"local tensor shape {tshape} inconsistent with canonical physical shape {tuple(expected)}"
        )


def sanitize_infinity(x):
    """Largest representable value for ``x``'s dtype (reference ``:220``)."""
    from . import types

    dt = x.dtype if isinstance(x, DNDarray) else types.canonical_heat_type(x.dtype)
    if types.heat_type_is_exact(dt):
        return types.iinfo(dt).max
    return float("inf")


def sanitize_sequence(seq):
    """Normalize a sequence argument to a list (reference ``:240``)."""
    if isinstance(seq, list):
        return seq
    if isinstance(seq, tuple):
        return list(seq)
    if isinstance(seq, DNDarray):
        return seq.numpy().tolist()
    if isinstance(seq, np.ndarray):
        return seq.tolist()
    raise TypeError(f"seq must be a list, tuple, DNDarray or ndarray, got {type(seq)}")


def sanitize_out(out, output_shape, output_split, output_device, output_comm=None) -> None:
    """Verify an ``out=`` buffer matches the result (reference ``:259``)."""
    if not isinstance(out, DNDarray):
        raise TypeError(f"expected out to be None or a DNDarray, but was {type(out)}")
    if tuple(out.shape) != tuple(output_shape):
        raise ValueError(f"Expecting output buffer of shape {tuple(output_shape)}, got {out.shape}")
    if out.split != output_split:
        # align distribution of the out buffer to the result
        out.resplit_(output_split)


def sanitize_distribution(*args: DNDarray, target: DNDarray, diff_map=None):
    """Align every operand's split to ``target``'s split (reference ``:31``).

    Returns the re-aligned operands (out-of-place resplit where needed).
    """
    out = []
    for a in args:
        sanitize_in(a)
        if a.split != target.split:
            out.append(a.resplit(target.split))
        else:
            out.append(a)
    return tuple(out) if len(out) != 1 else out[0]


def scalar_to_1d(x: DNDarray) -> DNDarray:
    """Reshape a scalar DNDarray to shape (1,) (reference ``:350``)."""
    if x.ndim == 0:
        return x.reshape((1,))
    return x
