"""NumPy-style dtype hierarchy backed by JAX dtypes.

TPU-native re-design of the reference's type system
(``heat/core/types.py:64-413`` class lattice, ``canonical_heat_type`` at
``:495``, ``promote_types`` at ``:836``, ``result_type`` at ``:868``,
``can_cast`` at ``:671``, ``finfo``/``iinfo`` at ``:950,1007``).

Differences by design:

* the backing scalar types are JAX/numpy dtypes, not torch dtypes;
* ``bfloat16`` is a **native first-class dtype** (the MXU's preferred input
  format) — the reference can only move it over MPI by bit-casting to int16
  (``communication.py:137-138``);
* promotion follows the **JAX lattice** via ``jnp.promote_types`` — notably
  int + float32 stays float32 instead of NumPy's widening to float64, the
  deliberate TPU-first choice (f64 is emulated on TPU).
"""

from __future__ import annotations

import builtins
import math
from typing import Tuple, Union

import numpy as np

import jax.numpy as jnp
from jax._src import dtypes as _jax_dtypes

__all__ = [
    "datatype",
    "generic",
    "number",
    "integer",
    "signedinteger",
    "unsignedinteger",
    "floating",
    "inexact",
    "complexfloating",
    "flexible",
    "bool",
    "bool_",
    "uint8",
    "ubyte",
    "int8",
    "byte",
    "int16",
    "short",
    "int32",
    "int",
    "int64",
    "long",
    "bfloat16",
    "float16",
    "half",
    "float32",
    "float",
    "float_",
    "float64",
    "double",
    "complex64",
    "cfloat",
    "csingle",
    "complex128",
    "cdouble",
    "canonical_heat_type",
    "heat_type_of",
    "heat_type_is_exact",
    "heat_type_is_inexact",
    "heat_type_is_complexfloating",
    "issubdtype",
    "iscomplex",
    "isreal",
    "promote_types",
    "result_type",
    "can_cast",
    "finfo",
    "iinfo",
]


class datatype:
    """Abstract base of all heat types (reference ``types.py:64``)."""

    _np_type = None  # numpy/ml_dtypes scalar dtype
    _char = None

    def __new__(cls, *value, device=None, comm=None):
        # Calling a type casts to it, producing a DNDarray (reference
        # ``types.py:86-130``). Imported lazily to avoid a module cycle.
        from . import factories

        if cls._np_type is None:
            raise TypeError(f"cannot instantiate abstract type {cls.__name__}")
        if len(value) == 0:
            value = ((0,),)
        elif len(value) == 1:
            value = (value[0],)
        else:
            value = (value,)
        return factories.array(value[0], dtype=cls, device=device, comm=comm)

    @classmethod
    def np_type(cls):
        return cls._np_type

    @classmethod
    def jax_type(cls):
        return jnp.dtype(cls._np_type)

    # reference spells this ``torch_type`` — kept as an alias so ported
    # call-sites read the same; it returns the JAX dtype here.
    torch_type = jax_type

    @classmethod
    def char(cls):
        return cls._char


class generic(datatype):
    pass


class bool(generic):  # noqa: A001 — parity with the reference namespace
    _np_type = np.bool_
    _char = "u1"


bool_ = bool


class number(generic):
    pass


class integer(number):
    pass


class signedinteger(integer):
    pass


class unsignedinteger(integer):
    pass


class inexact(number):
    pass


class floating(inexact):
    pass


class complexfloating(inexact):
    pass


class flexible(generic):
    pass


class uint8(unsignedinteger):
    _np_type = np.uint8
    _char = "B"


class int8(signedinteger):
    _np_type = np.int8
    _char = "b"


class int16(signedinteger):
    _np_type = np.int16
    _char = "h"


class int32(signedinteger):
    _np_type = np.int32
    _char = "i"


class int64(signedinteger):
    _np_type = np.int64
    _char = "l"


class bfloat16(floating):
    _np_type = _jax_dtypes.bfloat16
    _char = "E"


class float16(floating):
    _np_type = np.float16
    _char = "e"


class float32(floating):
    _np_type = np.float32
    _char = "f"


class float64(floating):
    _np_type = np.float64
    _char = "d"


class complex64(complexfloating):
    _np_type = np.complex64
    _char = "F"


class complex128(complexfloating):
    _np_type = np.complex128
    _char = "D"


# aliases (reference ``types.py:415-440``)
ubyte = uint8
byte = int8
short = int16
int = int32  # noqa: A001
long = int64
half = float16
float = float32  # noqa: A001
float_ = float32
double = float64
cfloat = complex64
csingle = complex64
cdouble = complex128


_JAX_TO_HEAT = {
    jnp.dtype(np.bool_): bool,
    jnp.dtype(np.uint8): uint8,
    jnp.dtype(np.int8): int8,
    jnp.dtype(np.int16): int16,
    jnp.dtype(np.int32): int32,
    jnp.dtype(np.int64): int64,
    jnp.dtype(_jax_dtypes.bfloat16): bfloat16,
    jnp.dtype(np.float16): float16,
    jnp.dtype(np.float32): float32,
    jnp.dtype(np.float64): float64,
    jnp.dtype(np.complex64): complex64,
    jnp.dtype(np.complex128): complex128,
}

_PY_TO_HEAT = {
    builtins.bool: bool,
    builtins.int: int64,
    builtins.float: float32,
    builtins.complex: complex64,
}

_CHAR_TO_HEAT = {
    "?": bool,
    "B": uint8,
    "b": int8,
    "h": int16,
    "i": int32,
    "i4": int32,
    "l": int64,
    "i8": int64,
    "E": bfloat16,
    "e": float16,
    "f": float32,
    "f4": float32,
    "d": float64,
    "f8": float64,
    "F": complex64,
    "D": complex128,
    "u1": uint8,
}


def canonical_heat_type(a_type) -> type:
    """Normalize any dtype-like to a heat type class (reference ``types.py:495``)."""
    if isinstance(a_type, type) and issubclass(a_type, datatype):
        if a_type._np_type is None:
            raise TypeError(f"data type {a_type!r} is abstract")
        return a_type
    if a_type in _PY_TO_HEAT:
        return _PY_TO_HEAT[a_type]
    if isinstance(a_type, str) and a_type in _CHAR_TO_HEAT:
        return _CHAR_TO_HEAT[a_type]
    try:
        return _JAX_TO_HEAT[jnp.dtype(a_type)]
    except (TypeError, KeyError) as exc:
        raise TypeError(f"data type {a_type!r} not understood") from exc


def heat_type_of(obj) -> type:
    """Heat type of an object's elements (reference ``types.py:541``)."""
    from .dndarray import DNDarray

    if isinstance(obj, DNDarray):
        return obj.dtype
    if hasattr(obj, "dtype"):
        return canonical_heat_type(obj.dtype)
    if isinstance(obj, (builtins.bool, builtins.int, builtins.float, builtins.complex)):
        return _PY_TO_HEAT[type(obj)]
    if isinstance(obj, (list, tuple)):
        return canonical_heat_type(np.asarray(obj).dtype)
    raise TypeError(f"cannot determine heat type of {type(obj)}")


def heat_type_is_exact(ht_dtype) -> builtins.bool:
    """True for integer/bool types (reference ``types.py:590``)."""
    dt = canonical_heat_type(ht_dtype)
    return issubclass(dt, integer) or dt is bool


def heat_type_is_inexact(ht_dtype) -> builtins.bool:
    """True for floating/complex types (reference ``types.py:610``)."""
    return issubclass(canonical_heat_type(ht_dtype), inexact)


def heat_type_is_complexfloating(ht_dtype) -> builtins.bool:
    return issubclass(canonical_heat_type(ht_dtype), complexfloating)


def issubdtype(arg1, arg2) -> builtins.bool:
    """NumPy-style abstract dtype test (reference ``types.py:632``)."""
    abstract = {
        generic,
        number,
        integer,
        signedinteger,
        unsignedinteger,
        inexact,
        floating,
        complexfloating,
        flexible,
    }
    if isinstance(arg2, type) and arg2 in abstract:
        try:
            dt1 = canonical_heat_type(arg1)
        except TypeError:
            return False
        return issubclass(dt1, arg2)
    try:
        return canonical_heat_type(arg1) is canonical_heat_type(arg2)
    except TypeError:
        return False


def iscomplex(x):
    """Elementwise test for nonzero imaginary part (reference ``types.py:700``)."""
    from . import _operations, factories

    if heat_type_is_complexfloating(x.dtype):
        return _operations.__dict__["_local_op"](jnp.imag, x) != 0
    return factories.zeros(x.shape, dtype=bool, split=x.split, device=x.device, comm=x.comm)


def isreal(x):
    """Elementwise test for zero imaginary part (reference ``types.py:730``)."""
    from . import logical

    return logical.logical_not(iscomplex(x))


# The reference's promotion ladder (``types.py:754-761``): the FIRST type in
# this order both operands can "intuitively" cast to. This is neither NumPy
# (int32+f32→f64 there) nor torch (int64+f32→f32 there): same-bit-length
# int→float casts are allowed (int32→f32) but int64 only fits f64.
_PROMOTION_ORDER = None  # filled lazily below (after all classes exist)


def _promotion_order():
    global _PROMOTION_ORDER
    if _PROMOTION_ORDER is None:
        _PROMOTION_ORDER = [
            bool, uint8, int8, int16, int32, int64,
            bfloat16, float16, float32, float64, complex64, complex128,
        ]
    return _PROMOTION_ORDER


def promote_types(type1, type2) -> type:
    """Smallest common intuitively-castable type (reference ``types.py:836``,
    derived from the same intuitive-cast table + ladder walk ``:754-761``)."""
    t1 = canonical_heat_type(type1)
    t2 = canonical_heat_type(type2)
    if {t1, t2} == {bfloat16, float16}:
        return float32  # neither holds the other's values (JAX convention)
    for target in _promotion_order():
        if can_cast(t1, target, "intuitive") and can_cast(t2, target, "intuitive"):
            return target
    return float64


def accumulation_dtype(jdt):
    """jnp accumulation dtype for a storage dtype: half-precision inputs
    (bf16/f16 — MXU-native, half the HBM traffic) accumulate reductions
    and GEMMs in float32 via ``preferred_element_type``; everything else
    accumulates in its own dtype. Shared by the KMeans Lloyd step and the
    distance tiles so the mixed-precision policy cannot drift."""
    jdt = jnp.dtype(jdt)
    if jdt in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        return jnp.dtype(jnp.float32)
    return jdt


def _kind_rank(t) -> builtins.int:
    if issubclass(t, complexfloating):
        return 3
    if issubclass(t, floating):
        return 2
    if issubclass(t, integer):
        return 1
    return 0


def result_type(*arrays_and_types) -> type:
    """Promotion over arrays, dtypes and scalars (reference ``types.py:868``:
    precedence array(0) > type(1) > 0-d array(2) > python scalar(3); same
    kind → higher precedence wins, different kind → higher kind wins)."""
    from .dndarray import DNDarray

    def classify(arg):
        if isinstance(arg, DNDarray):
            return arg.dtype, (0 if arg.ndim > 0 else 2)
        if isinstance(arg, np.ndarray) or hasattr(arg, "dtype"):
            t = canonical_heat_type(arg.dtype)
            return t, (0 if len(getattr(arg, "shape", (1,))) > 0 else 2)
        try:
            return canonical_heat_type(arg), 1
        except TypeError:
            return canonical_heat_type(type(arg)), 3

    t1, p1 = classify(arrays_and_types[0])
    for arg in arrays_and_types[1:]:
        t2, p2 = classify(arg)
        if t1 == t2:
            p1 = min(p1, p2)
            continue
        if p1 == p2:
            t1 = promote_types(t1, t2)
            continue
        k1, k2 = _kind_rank(t1), _kind_rank(t2)
        if k1 == k2:
            t1 = t1 if p1 < p2 else t2
        else:
            t1 = t1 if k1 > k2 else t2
        p1 = min(p1, p2)
    return t1


# --------------------------------------------------------------------------- #
# cast tables — the reference's explicit tables (``types.py:621-664``),
# extended with bfloat16 and float16 rows/columns. Encoded as per-source-type
# sets of permitted targets. "safe" preserves values exactly (mantissa rule
# for floats: int16 fits f32's 24-bit mantissa but not bf16's 8-bit one;
# int64→f64 follows the reference, which permits it). "intuitive" adds the
# reference's same-bit-length int→float casts (int32→f32, int16→f16/bf16).
# --------------------------------------------------------------------------- #


def _cast_tables():
    order = [bool, uint8, int8, int16, int32, int64,
             bfloat16, float16, float32, float64, complex64, complex128]
    floats_up = {float32, float64, complex64, complex128}
    safe = {
        bool: set(order),
        uint8: {uint8, int16, int32, int64, bfloat16, float16} | floats_up,
        int8: {int8, int16, int32, int64, bfloat16, float16} | floats_up,
        int16: {int16, int32, int64} | floats_up,
        int32: {int32, int64, float64, complex128},
        int64: {int64, float64, complex128},
        bfloat16: {bfloat16} | floats_up,
        float16: {float16} | floats_up,
        float32: floats_up,
        float64: {float64, complex128},
        complex64: {complex64, complex128},
        complex128: {complex128},
    }
    intuitive = {k: set(v) for k, v in safe.items()}
    intuitive[int16] |= {bfloat16, float16}
    intuitive[int32] |= {float32, complex64}
    kinds = {bool: 0}
    for t in (uint8, int8, int16, int32, int64):
        kinds[t] = 1
    for t in (bfloat16, float16, float32, float64):
        kinds[t] = 2
    for t in (complex64, complex128):
        kinds[t] = 3
    return order, safe, intuitive, kinds


_CAST_TABLES = None


def _get_cast_tables():
    global _CAST_TABLES
    if _CAST_TABLES is None:
        _CAST_TABLES = _cast_tables()
    return _CAST_TABLES


def _scalar_fits(value, to_t) -> builtins.bool:
    """Value-based scalar cast check (reference/legacy-NumPy semantics:
    ``can_cast(1024, int8) is False`` because the value overflows)."""
    if isinstance(value, builtins.bool):
        return True
    jt = np.dtype(to_t.np_type()) if to_t is not bfloat16 else None
    if isinstance(value, builtins.int):
        if jt is not None and jt.kind in "iu":
            info = np.iinfo(jt)
            return info.min <= value <= info.max
        return jt is None or jt.kind in "fc"  # any int fits a float's range
    if isinstance(value, builtins.float):
        if to_t is bfloat16:
            return True  # bf16 range ≈ f32 range
        if jt.kind == "f":
            return math.isinf(value) or math.isnan(value) or abs(value) <= np.finfo(jt).max
        return jt.kind == "c"
    if isinstance(value, builtins.complex):
        if jt is None or jt.kind != "c":
            return False
        comp = np.finfo(np.float32 if jt.itemsize == 8 else np.float64)
        return abs(value.real) <= comp.max and abs(value.imag) <= comp.max
    return False


def can_cast(from_, to, casting: str = "intuitive") -> builtins.bool:
    """Cast-safety test (reference ``types.py:671``): casting kinds
    ``no``/``safe``/``same_kind``/``unsafe`` plus the reference's
    ``intuitive``, which adds same-bit-length int→float casts (int32→f32
    yes; int64→f32 no — f32's mantissa cannot hold it).
    Python scalars are checked by VALUE (``can_cast(1024, int8) → False``).
    """
    if casting not in ("no", "safe", "same_kind", "unsafe", "intuitive"):
        raise ValueError(f"unknown casting kind {casting!r}")
    to_t = canonical_heat_type(to)
    if hasattr(from_, "dtype"):
        from_ = from_.dtype
    if isinstance(from_, (builtins.bool, builtins.int, builtins.float, builtins.complex)) and not isinstance(from_, type):
        if casting == "unsafe":
            return True
        return _scalar_fits(from_, to_t)
    from_t = canonical_heat_type(from_)
    if casting == "unsafe":
        return True
    if casting == "no":
        return from_t is to_t
    _order, safe, intuitive, kinds = _get_cast_tables()
    if casting == "safe":
        return to_t in safe[from_t]
    if casting == "intuitive":
        return to_t in intuitive[from_t]
    # same_kind: safe casts plus any cast within the same kind family
    return to_t in safe[from_t] or kinds[from_t] == kinds[to_t]


class finfo:
    """Machine limits for floating types (reference ``types.py:950``)."""

    def __new__(cls, ht_dtype):
        dt = canonical_heat_type(ht_dtype)
        if not issubclass(dt, (floating, complexfloating)):
            raise TypeError(f"data type {dt!r} not inexact")
        return super().__new__(cls)

    def __init__(self, ht_dtype):
        dt = canonical_heat_type(ht_dtype)
        info = jnp.finfo(dt.jax_type())
        self.bits = info.bits
        self.eps = builtins.float(info.eps)
        self.max = builtins.float(info.max)
        self.min = builtins.float(info.min)
        self.tiny = builtins.float(info.tiny)
        self.resolution = getattr(info, "resolution", self.eps)
        self.dtype = dt

    def __repr__(self):
        return f"finfo(resolution={self.resolution}, min={self.min}, max={self.max}, dtype={self.dtype.__name__})"


class iinfo:
    """Machine limits for integer types (reference ``types.py:1007``)."""

    def __new__(cls, ht_dtype):
        dt = canonical_heat_type(ht_dtype)
        if not (issubclass(dt, integer) or dt is bool):
            raise TypeError(f"data type {dt!r} not an integer type")
        return super().__new__(cls)

    def __init__(self, ht_dtype):
        dt = canonical_heat_type(ht_dtype)
        if dt is bool:
            self.bits, self.max, self.min = 8, 1, 0
        else:
            info = jnp.iinfo(dt.jax_type())
            self.bits = info.bits
            self.max = builtins.int(info.max)
            self.min = builtins.int(info.min)
        self.dtype = dt

    def __repr__(self):
        return f"iinfo(min={self.min}, max={self.max}, dtype={self.dtype.__name__})"
