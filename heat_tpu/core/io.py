"""Parallel I/O (reference ``heat/core/io.py``).

The reference reads per-rank chunk slices of HDF5/NetCDF/CSV files
(``load_hdf5`` ``io.py:55``, ``load_csv`` ``:710``) and writes with
rank-ordered/mpio access (``save_hdf5`` ``:147``). Under a single controller
the host reads chunk-by-chunk and assembles the sharded global array device
shard by device shard (``jax.device_put`` per shard), so no full copy is
required beyond one chunk at a time per device. NetCDF support is gated on
the optional ``netCDF4`` package exactly like the reference.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterable, List, Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import devices, factories, types
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis
from ..utils import metrics as _metrics

__all__ = [
    "DataStream",
    "load",
    "load_csv",
    "load_hdf5",
    "load_netcdf",
    "load_npy_from_path",
    "save",
    "save_csv",
    "save_hdf5",
    "save_netcdf",
    "supports_hdf5",
    "supports_netcdf",
]


class DataStream:
    """Re-iterable out-of-core chunk source — the ``stream=True`` mode of
    :func:`load_hdf5` / :func:`load_netcdf`.

    :meth:`iter_chunks` re-opens the dataset and yields consecutive
    row-blocks as split-0 ``DNDarray`` chunks: per chunk the host reads
    one device-block slice at a time (the :func:`_shard_and_wrap`
    discipline), so the peak HOST footprint is one device block and the
    peak DEVICE footprint is one chunk — the full dataset is never
    materialized, and a new ``iter_chunks`` call streams the same data
    again (the epoch re-read an out-of-core ``fit_stream`` needs).

    Chunk accounting (the out-of-core acceptance evidence):
    ``chunks_read`` / ``bytes_read`` accumulate over the stream's
    lifetime and ``peak_chunk_bytes`` is the largest single chunk's
    physical payload — asserting it under a configured in-memory cap
    proves the resident set stayed below full materialization. The
    process-wide counters ``io.stream_chunks`` / ``io.stream_bytes``
    mirror the totals into ``heat_tpu.utils.metrics``.
    """

    def __init__(self, open_fn, gshape, dtype, device, comm, name=""):
        self._open = open_fn
        self.shape = tuple(int(s) for s in gshape)
        self.dtype = dtype
        self.device = device
        self.comm = comm
        self.name = name
        self.chunks_read = 0
        self.bytes_read = 0
        self.peak_chunk_bytes = 0

    def __repr__(self) -> str:
        return (f"DataStream({self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype}, chunks_read={self.chunks_read})")

    def iter_chunks(self, rows_per_chunk: int):
        """Yield the dataset as consecutive split-0 chunks of at most
        ``rows_per_chunk`` logical rows (the tail chunk is smaller)."""
        rows = int(rows_per_chunk)
        if rows <= 0:
            raise ValueError(
                f"rows_per_chunk must be positive, got {rows_per_chunk!r}")
        n = self.shape[0]
        jdt = self.dtype.jax_type()

        def gen():
            with self._open() as read:
                for lo in range(0, n, rows):
                    hi = min(lo + rows, n)
                    gshape = (hi - lo,) + self.shape[1:]

                    def load(slices, _lo=lo):
                        # _shard_and_wrap clamps the split axis to
                        # concrete logical bounds — shift them into the
                        # file's row space
                        shifted = (slice(slices[0].start + _lo,
                                         slices[0].stop + _lo),) \
                            + tuple(slices[1:])
                        return read(shifted)

                    chunk = _shard_and_wrap(
                        load, gshape, jdt, 0, self.device, self.comm)
                    nbytes = (int(np.prod(chunk.larray.shape))
                              * jnp.dtype(chunk.larray.dtype).itemsize)
                    self.chunks_read += 1
                    self.bytes_read += nbytes
                    self.peak_chunk_bytes = max(self.peak_chunk_bytes,
                                                nbytes)
                    _metrics.inc("io.stream_chunks")
                    _metrics.inc("io.stream_bytes", nbytes)
                    yield chunk

        return gen()

try:
    import h5py

    __HDF5 = True
except ImportError:
    __HDF5 = False

try:
    import netCDF4 as nc  # noqa: F401

    __NETCDF = "netCDF4"
except ImportError:
    # classic NetCDF-3 fallback: scipy ships a pure-python reader/writer,
    # so NetCDF I/O works (for classic-format files) even without the
    # optional netCDF4 package the reference gates on
    try:
        from scipy.io import netcdf_file as _scipy_nc  # noqa: F401

        __NETCDF = "scipy"
    except ImportError:
        __NETCDF = None


def supports_hdf5() -> bool:
    """True if HDF5 I/O is available (reference ``io.py:40``)."""
    return __HDF5


def supports_netcdf() -> bool:
    """True if NetCDF I/O is available (reference ``io.py:47``; here also
    true with only scipy's classic NetCDF-3 backend)."""
    return __NETCDF is not None


def _shard_and_wrap(load_chunk, gshape, jdtype, split, device, comm) -> DNDarray:
    """Assemble a sharded DNDarray by reading per-device chunks.

    ``load_chunk(slices) -> np.ndarray`` reads one device's slice; chunks are
    placed on their devices one at a time (the reference's per-rank
    ``comm.chunk`` read, ``io.py:122``).
    """
    from jax.sharding import NamedSharding

    gshape = tuple(int(s) for s in gshape)
    if split is None:
        data = load_chunk(tuple(slice(0, s) for s in gshape))
        return factories.array(np.asarray(data), dtype=types.canonical_heat_type(jdtype), comm=comm, device=device)
    split = sanitize_axis(gshape, split)
    c = comm.chunk_size(gshape[split])
    sharding = comm.sharding(len(gshape), split)
    phys_shape = list(gshape)
    phys_shape[split] = c * comm.size
    np_dtype = np.dtype(jdtype) if jdtype != jnp.bfloat16 else np.float32
    cache: dict = {}

    def read_block(index):
        # index: per-device slice tuple into the PHYSICAL shape; clamp to the
        # logical extent, read, and pad back to the physical block. Works for
        # any sharding (1-D mesh or a grid axis view, where devices on other
        # grid axes receive replicated copies of the same block).
        key = tuple((s.start, s.stop) for s in index)
        if key in cache:
            return cache[key]
        req = list(index)
        lo = index[split].start or 0
        hi = min(index[split].stop or phys_shape[split], gshape[split])
        req[split] = slice(lo, max(hi, lo))
        chunk = np.asarray(load_chunk(tuple(req)), dtype=np_dtype)
        want_rows = (index[split].stop or phys_shape[split]) - lo
        if chunk.shape[split] < want_rows:
            cfg = [
                (0, want_rows - chunk.shape[split] if i == split else 0)
                for i in range(len(gshape))
            ]
            chunk = np.pad(chunk, cfg)
        out = jnp.asarray(chunk, jdtype)
        cache[key] = out
        return out

    parray = jax.make_array_from_callback(tuple(phys_shape), sharding, read_block)
    return DNDarray(
        parray, gshape, types.canonical_heat_type(jdtype), split, device, comm
    )


def load_hdf5(
    path: str,
    dataset: str,
    dtype=types.float32,
    load_fraction: float = 1.0,
    split=None,
    device=None,
    comm=None,
    stream: bool = False,
):
    """Load an HDF5 dataset chunk-parallel (reference ``io.py:55``).

    ``stream=True`` returns a :class:`DataStream` instead of loading:
    the out-of-core mode — ``stream.iter_chunks(rows_per_chunk)`` feeds
    consecutive split-0 row chunks (re-opened per pass, so each
    ``fit_stream`` epoch re-reads from disk and datasets larger than
    host RAM never materialize). Streaming requires ``split`` in
    ``(None, 0)`` — chunks are always row-split."""
    if not supports_hdf5():
        raise RuntimeError("hdf5 is required for HDF5 operations, but h5py is not available")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    if not isinstance(dataset, str):
        raise TypeError(f"dataset must be str, not {type(dataset)}")
    comm = sanitize_comm(comm)
    device = devices.sanitize_device(device)
    dtype = types.canonical_heat_type(dtype)
    with h5py.File(path, "r") as handle:
        data = handle[dataset]
        gshape = tuple(data.shape)
        if load_fraction < 1.0:
            ax = split if split is not None else 0
            gshape = tuple(
                int(s * load_fraction) if i == ax else s for i, s in enumerate(gshape)
            )
        if not stream:
            return _shard_and_wrap(
                lambda slices: data[slices], gshape, dtype.jax_type(), split, device, comm
            )
    if split not in (None, 0):
        raise ValueError(
            f"stream=True yields row-split chunks; split={split!r} is not "
            "supported")

    @contextlib.contextmanager
    def _open():
        with h5py.File(path, "r") as handle:
            yield lambda slices: handle[dataset][slices]

    return DataStream(_open, gshape, dtype, device, comm,
                      name=f"{path}:{dataset}")


def _np_save_dtype(data: DNDarray):
    """NumPy storage dtype for a DNDarray (bf16 widens to f32: neither h5py
    nor netCDF4 stores bfloat16)."""
    jdt = jnp.dtype(data.larray.dtype)
    return np.dtype(np.float32) if jdt == jnp.bfloat16 else np.dtype(jdt)


def _iter_shard_blocks(data: DNDarray, order: bool = False):
    """Yield ``(logical_slices, np_block)`` once per distinct shard of the
    physical array, trimmed to the logical extent (padding removed).

    This is the write-side analog of the chunked loads: peak host memory is
    one shard, never the gathered global array — the reference's
    rank-ordered/mpio parallel writes (``heat/core/io.py:147-233,487``).
    ``order=True`` yields shards sorted by their split-axis offset (the
    rank-ordered CSV stream)."""
    np_dtype = _np_save_dtype(data)
    split = data.split
    if split is None or data.comm.size == 1:
        block = np.asarray(data.larray.addressable_shards[0].data
                           if data.larray.is_fully_addressable and split is None
                           else data._logical(), np_dtype)
        yield tuple(slice(0, s) for s in data.gshape), block
        return
    n = data.gshape[split]
    phys = data.larray.shape[split]
    shards = data.larray.addressable_shards
    if order:
        shards = sorted(shards, key=lambda s: s.index[split].start or 0)
    seen = set()
    for sh in shards:
        lo = sh.index[split].start or 0
        if lo in seen:  # replicated copies on other grid axes
            continue
        seen.add(lo)
        hi = min(sh.index[split].stop or phys, n)
        if hi <= lo:
            continue  # pure-padding shard
        block = np.asarray(sh.data, np_dtype)
        take = hi - lo
        if block.shape[split] > take:
            trim = [slice(None)] * data.ndim
            trim[split] = slice(0, take)
            block = block[tuple(trim)]
        slices = tuple(
            slice(lo, hi) if i == split else slice(0, s)
            for i, s in enumerate(data.gshape)
        )
        yield slices, block


def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
    """Save to HDF5 shard-by-shard (reference rank-ordered/mpio writes,
    ``io.py:147-233``): the dataset is created at the global shape and each
    device shard's valid slice streams in — O(shard) host memory, never a
    full gather (round-1/round-2 finding)."""
    if not supports_hdf5():
        raise RuntimeError("hdf5 is required for HDF5 operations, but h5py is not available")
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, not {type(data)}")
    with h5py.File(path, mode) as handle:
        dset = handle.create_dataset(
            dataset, shape=data.gshape, dtype=_np_save_dtype(data), **kwargs
        )
        for slices, block in _iter_shard_blocks(data):
            if data.ndim == 0:
                dset[()] = block
            else:
                dset[slices] = block


def load_netcdf(path: str, variable: str, dtype=types.float32, split=None,
                device=None, comm=None, stream: bool = False):
    """Load a NetCDF variable (reference ``io.py:265``).

    ``stream=True`` returns a :class:`DataStream` (chunked out-of-core
    ingestion, same contract as :func:`load_hdf5`'s streaming mode —
    masked/missing-value semantics are applied per chunk exactly as the
    in-memory load applies them)."""
    if not supports_netcdf():
        raise RuntimeError(
            "netcdf is required for NetCDF operations — install netCDF4, "
            "or scipy for classic NetCDF-3 files")
    comm = sanitize_comm(comm)
    device = devices.sanitize_device(device)
    dtype = types.canonical_heat_type(dtype)
    def _read_chunk(data):
        # masked (missing/_FillValue) cells are NaN for float data on BOTH
        # backends (np.asarray on a MaskedArray would silently expose raw
        # fill values); integer data has no NaN, so masked cells fill with
        # the variable's declared fill value on both backends
        def read(slices):
            block = data[slices]
            if isinstance(block, np.ma.MaskedArray):
                block = (block.filled(np.nan)
                         if np.issubdtype(block.dtype, np.floating)
                         else block.filled())
            return np.asarray(block)

        return read

    @contextlib.contextmanager
    def _open_var():
        if __NETCDF == "netCDF4":
            with nc.Dataset(path, "r") as handle:
                yield handle.variables[variable]
        else:
            # maskandscale matches netCDF4's default semantics (CF
            # scale_factor / add_offset applied, missing values masked)
            # so both backends return the same physical values for
            # packed variables
            with _scipy_nc(path, "r", mmap=False,
                           maskandscale=True) as handle:
                yield handle.variables[variable]

    if stream:
        if split not in (None, 0):
            raise ValueError(
                f"stream=True yields row-split chunks; split={split!r} "
                "is not supported")
        with _open_var() as data:
            gshape = tuple(data.shape)

        @contextlib.contextmanager
        def _open():
            with _open_var() as data:
                yield _read_chunk(data)

        return DataStream(_open, gshape, dtype, device, comm,
                          name=f"{path}:{variable}")

    with _open_var() as data:
        gshape = tuple(data.shape)
        return _shard_and_wrap(
            _read_chunk(data), gshape, dtype.jax_type(), split, device, comm
        )


def save_netcdf(data: DNDarray, path: str, variable: str, mode: str = "w", **kwargs) -> None:
    """Save to NetCDF shard-by-shard (reference merged-slice parallel writes,
    ``io.py:348,487``): the variable is created at the global shape and each
    device shard's valid slice streams in — O(shard) host memory."""
    if not supports_netcdf():
        raise RuntimeError(
            "netcdf is required for NetCDF operations — install netCDF4, "
            "or scipy for classic NetCDF-3 files")
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, not {type(data)}")

    def _dim_names(handle, dims_sizes):
        """Positional ``dim_{i}`` names, creating missing dimensions. An
        existing same-position dimension of a different size — or the
        unlimited/record dimension (size ``None`` in scipy, unbounded in
        netCDF4), which must never be rebound — gets a size-suffixed name
        instead of silently binding the wrong extent."""
        names = []
        for i, s in enumerate(dims_sizes):
            name = f"dim_{i}"
            if name not in handle.dimensions:
                handle.createDimension(name, s)
            else:
                d = handle.dimensions[name]
                # scipy: name -> size (None = unlimited); netCDF4:
                # name -> Dimension (len(); isunlimited())
                size = len(d) if hasattr(d, "__len__") else d
                unlimited = (size is None
                             or (hasattr(d, "isunlimited") and d.isunlimited()))
                if unlimited or size != s:
                    name = f"dim_{i}_{s}"
                    if name not in handle.dimensions:
                        handle.createDimension(name, s)
            names.append(name)
        return tuple(names)

    def _stream_shards(var):
        """Write each device shard's valid slice into the variable —
        O(shard) host memory, no global gather."""
        for slices, block in _iter_shard_blocks(data):
            if data.ndim == 0:
                var[()] = block
            else:
                var[slices] = block

    if __NETCDF == "netCDF4":
        with nc.Dataset(path, mode) as handle:
            _stream_shards(handle.createVariable(
                variable, _np_save_dtype(data),
                _dim_names(handle, data.gshape)))
        return
    # scipy classic NetCDF-3 writer; "a"/"r+" append like netCDF4
    if mode in ("a", "r+"):
        scipy_mode = "a"
    elif mode == "w":
        scipy_mode = "w"
    else:
        raise ValueError(
            f"mode {mode!r} is not supported by the classic NetCDF-3 "
            "(scipy) backend; use 'w', 'a' or 'r+'")
    np_dt = np.dtype(_np_save_dtype(data))
    if np_dt not in (np.dtype(t) for t in
                     ("int8", "int16", "int32", "float32", "float64")):
        raise ValueError(
            f"dtype {np_dt} cannot be stored in a classic NetCDF-3 file "
            "(scipy backend; NetCDF-3 has no 8-byte or unsigned integers) "
            "— cast the array first, e.g. to int32 or float64")
    with _scipy_nc(path, scipy_mode) as handle:
        _stream_shards(handle.createVariable(
            variable, np_dt, _dim_names(handle, data.gshape)))


def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split=None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load a CSV file (reference ``load_csv``, ``io.py:710``; the reference's
    byte-offset chunked parse becomes a host read + sharded placement).

    The parse runs through the native multithreaded C++ parser
    (``heat_tpu/native/fastcsv.cpp``) when a compiler is available — the
    reference's Python byte-range convention at native speed — and falls
    back to ``numpy.genfromtxt`` otherwise (identical NaN semantics)."""
    comm = sanitize_comm(comm)
    device = devices.sanitize_device(device)
    dtype = types.canonical_heat_type(dtype)
    data = None
    from .. import native

    # the C++ parser reads raw bytes — only valid for ASCII-superset
    # encodings (a UTF-16 file would NaN out silently, not fall back)
    ascii_superset = encoding.lower().replace("-", "").replace("_", "") in (
        "utf8", "ascii", "latin1", "iso88591")
    if ascii_superset and native.available():
        try:
            start = 0
            if header_lines:
                with open(path, "rb") as handle:
                    for _ in range(header_lines):
                        handle.readline()
                    start = handle.tell()
            data = native.parse_csv_chunk(path, start=start, sep=sep)
            if data.shape == (1, 1):
                data = data.reshape(())  # single cell: 0-d (genfromtxt parity)
            elif data.shape[0] == 1 and data.shape[1] > 1:
                pass  # single data row stays (1, c)
            elif data.shape[1] == 1:
                data = data[:, 0]  # single column flattens (genfromtxt parity)
        except (OSError, RuntimeError):
            data = None
        # ValueError (ragged) propagates: genfromtxt would raise too
    if data is None:
        data = np.genfromtxt(
            path, delimiter=sep, skip_header=header_lines, encoding=encoding
        )
        if data.ndim == 1:
            # disambiguate a single data row (→ (1, c)) from a single column
            # (→ (r,)) by counting data lines
            with open(path, encoding=encoding) as handle:
                n_lines = sum(1 for line in handle if line.strip()) - header_lines
            if n_lines == 1 and data.size > 1:
                data = data.reshape(1, -1)
    return factories.array(data, dtype=dtype, split=split, device=device, comm=comm)


def save_csv(
    data: DNDarray,
    path: str,
    header_lines: Optional[Iterable[str]] = None,
    sep: str = ",",
    decimals: int = -1,
    trunc: bool = False,
    **kwargs,
) -> None:
    """Save to CSV with a rank-ordered shard stream (reference ``io.py:860``):
    rows are written shard by shard in global row order — O(shard) host
    memory. Column-split arrays resplit to rows on-device first (one
    all_to_all program, no host gather)."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, not {type(data)}")
    if data.ndim > 1 and data.split not in (None, 0):
        data = data.resplit(0)
    with open(path, "w", encoding="utf-8") as handle:
        if header_lines:
            handle.write("\n".join(header_lines) + "\n")
        for _, block in _iter_shard_blocks(data, order=True):
            if decimals >= 0:
                block = np.round(block, decimals)
            np.savetxt(handle, np.atleast_1d(block), delimiter=sep)


def load_npy_from_path(path: str, dtype=types.float32, split=0, device=None, comm=None) -> DNDarray:
    """Load and concatenate all .npy files in a directory (reference ``io.py:1040``)."""
    files = sorted(f for f in os.listdir(path) if f.endswith(".npy"))
    if not files:
        raise ValueError(f"no .npy files under {path}")
    arrays = [np.load(os.path.join(path, f)) for f in files]
    data = np.concatenate(arrays, axis=0)
    return factories.array(data, dtype=dtype, split=split, device=device, comm=comm)


def load(path: str, *args, **kwargs) -> DNDarray:
    """Extension-dispatched load (reference ``io.py:659``)."""
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    ext = os.path.splitext(path)[-1].lower()
    if ext in (".h5", ".hdf5"):
        return load_hdf5(path, *args, **kwargs)
    if ext in (".nc", ".nc4", ".netcdf"):
        return load_netcdf(path, *args, **kwargs)
    if ext == ".csv":
        return load_csv(path, *args, **kwargs)
    raise ValueError(f"Unsupported file extension {ext}")


def save(data: DNDarray, path: str, *args, **kwargs) -> None:
    """Extension-dispatched save (reference ``io.py:923``)."""
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    ext = os.path.splitext(path)[-1].lower()
    if ext in (".h5", ".hdf5"):
        return save_hdf5(data, path, *args, **kwargs)
    if ext in (".nc", ".nc4", ".netcdf"):
        return save_netcdf(data, path, *args, **kwargs)
    if ext == ".csv":
        return save_csv(data, path, *args, **kwargs)
    raise ValueError(f"Unsupported file extension {ext}")
