"""Communication substrate: a device-mesh collective facade for TPU.

This is the TPU-native re-design of the reference's MPI wrapper
(``heat/core/communication.py``: ``Communication`` ABC at ``:88``,
``MPICommunication`` at ``:120``, ``chunk`` at ``:161``). Instead of wrapping
an MPI communicator over processes, a :class:`TPUCommunication` wraps a 1-D
``jax.sharding.Mesh`` over TPU (or CPU) devices. Cross-device data movement is
expressed as XLA collectives (``psum`` / ``all_gather`` / ``all_to_all`` /
``ppermute``) that ride the ICI/DCN interconnect, either implicitly via GSPMD
sharding propagation under ``jit`` or explicitly inside ``shard_map`` bodies.

Key differences from the reference, chosen deliberately for XLA:

* There is **one controller process**; ``rank``/SPMD-per-process semantics of
  MPI are replaced by a single global view of sharded ``jax.Array`` values.
  ``chunk()`` still answers "which slice of the global array lives on device
  *i*" — the canonical layout is **even (ceil) chunking with tail padding**,
  because XLA named shardings require the sharded dimension to be divisible
  by the mesh axis size (see ``DNDarray`` for the padding discipline).
* Collectives are not eager library calls on buffers; they are traced
  operations. The methods on this class are thin, composable wrappers meant
  to be used inside ``shard_map``-decorated functions (explicit tier) or are
  realized implicitly by GSPMD (default tier).
* bf16 is a first-class dtype — no int16 bit-cast shuffle is needed (the
  reference bit-casts bf16 to int16 to move it over MPI,
  ``communication.py:137-138``).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "Communication",
    "Request",
    "TPUCommunication",
    "MeshAxisComm",
    "MeshGrid",
    "get_comm",
    "use_comm",
    "sanitize_comm",
    "distributed_init",
]


class Request:
    """Completed-request handle returned by the ``I*`` collective aliases
    (reference ``MPIRequest``, ``communication.py:29-85``).

    Under XLA every collective is a traced op whose overlap with compute is
    scheduled by the compiler, so the request is complete by construction;
    ``Wait``/``Test`` exist for drop-in parity with reference call sites.
    """

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def Wait(self):
        return self._value

    wait = Wait

    def Test(self) -> bool:
        return True

    @property
    def value(self):
        return self._value


class Communication:
    """Base class for communication backends (reference ``communication.py:88``)."""

    @staticmethod
    def is_distributed() -> bool:
        raise NotImplementedError()

    def __init__(self) -> None:
        raise NotImplementedError()

    def chunk(self, shape, split, rank=None):
        raise NotImplementedError()


class TPUCommunication(Communication):
    """A 1-D device mesh plus the collective facade over it.

    Parameters
    ----------
    devices : sequence of jax.Device, optional
        Devices forming the mesh; defaults to all of ``jax.devices()``.
    axis_name : str
        Mesh axis name used by explicit collectives (default ``"proc"``).
    """

    def __init__(self, devices: Optional[Sequence] = None, axis_name: str = "proc"):
        if devices is None:
            devices = tuple(jax.devices())
        else:
            devices = tuple(devices)
        self._devices = devices
        self.axis_name = axis_name
        self.mesh = Mesh(np.asarray(devices), (axis_name,))

    # ------------------------------------------------------------------ #
    # identity / topology                                                #
    # ------------------------------------------------------------------ #
    @property
    def size(self) -> int:
        """Number of devices in the mesh (reference: number of MPI ranks)."""
        return len(self._devices)

    @property
    def rank(self) -> int:
        """Controller process index. Single-controller JAX: the host is rank 0.

        Unlike MPI-SPMD, algorithm code here does not branch on ``rank`` —
        per-device identity lives inside ``shard_map`` bodies via
        ``jax.lax.axis_index``.
        """
        return jax.process_index()

    @property
    def devices(self) -> Tuple:
        return self._devices

    @property
    def cache_key(self) -> Tuple:
        """Stable identity for jit-cache keys (device ids + axis name).

        ``id(mesh)`` is unsafe: a garbage-collected mesh's address can be
        recycled by a different mesh, aliasing compiled kernels across
        communicators.
        """
        return (self.axis_name, tuple(d.id for d in self._devices))

    @staticmethod
    def is_distributed() -> bool:
        return len(jax.devices()) > 1

    def __repr__(self) -> str:
        plat = self._devices[0].platform if self._devices else "?"
        return f"TPUCommunication(size={self.size}, axis='{self.axis_name}', platform={plat})"

    # ------------------------------------------------------------------ #
    # chunking / layout                                                  #
    # ------------------------------------------------------------------ #
    def chunk_size(self, n: int) -> int:
        """Per-device chunk length for a split axis of global length ``n``.

        Canonical layout is ceil-division: every device owns ``ceil(n/size)``
        physical rows; trailing devices may own fewer *logical* rows (or
        none). This replaces the reference's balanced ``n//size (+1)`` layout
        (``communication.py:193-209``) because XLA shards must be equal-sized.
        """
        if self.size == 0:
            return n
        return -(-n // self.size) if n > 0 else 0

    def padded_size(self, n: int) -> int:
        """Physical (padded) length of a split axis of logical length ``n``."""
        return self.chunk_size(n) * self.size if n > 0 else 0

    def chunk(self, shape, split, rank: Optional[int] = None):
        """Compute the logical chunk of device ``rank`` for ``shape``/``split``.

        Returns ``(offset, local_shape, slices)`` exactly like the reference's
        ``MPICommunication.chunk`` (``communication.py:161-209``), but for the
        canonical ceil-chunk layout.
        """
        if rank is None:
            rank = 0
        shape = tuple(int(s) for s in shape)
        if split is None:
            return 0, shape, tuple(slice(0, s) for s in shape)
        split = split % len(shape) if shape else 0
        n = shape[split]
        c = self.chunk_size(n)
        start = min(rank * c, n)
        stop = min((rank + 1) * c, n)
        lshape = list(shape)
        lshape[split] = stop - start
        slices = tuple(
            slice(start, stop) if i == split else slice(0, s) for i, s in enumerate(shape)
        )
        return start, tuple(lshape), slices

    def counts_displs(self, n: int):
        """Per-device (counts, displacements) along a split axis of length ``n``.

        Analogue of the reference's ``counts_displs_shape``
        (``communication.py:211-239``).
        """
        c = self.chunk_size(n)
        counts = [max(0, min((r + 1) * c, n) - min(r * c, n)) for r in range(self.size)]
        displs = [min(r * c, n) for r in range(self.size)]
        return tuple(counts), tuple(displs)

    def lshape_map(self, shape, split) -> np.ndarray:
        """(size, ndim) array of per-device logical shard shapes."""
        shape = tuple(int(s) for s in shape)
        out = np.tile(np.asarray(shape, dtype=np.int64), (self.size, 1))
        if split is not None and len(shape) > 0:
            split = split % len(shape)
            counts, _ = self.counts_displs(shape[split])
            out[:, split] = counts
        return out

    def spec(self, ndim: int, split: Optional[int]) -> PartitionSpec:
        """PartitionSpec placing the mesh axis at dimension ``split``."""
        if split is None or ndim == 0:
            return PartitionSpec()
        split = split % ndim
        return PartitionSpec(*(self.axis_name if i == split else None for i in range(ndim)))

    def sharding(self, ndim: int, split: Optional[int]) -> NamedSharding:
        """NamedSharding for an ``ndim``-dim array split along ``split``."""
        return NamedSharding(self.mesh, self.spec(ndim, split))

    # ------------------------------------------------------------------ #
    # explicit collectives — for use inside shard_map bodies             #
    # ------------------------------------------------------------------ #
    # These mirror the reference's collective surface
    # (``communication.py:458-1872``) but as traced XLA ops. GSPMD covers the
    # common cases implicitly; these exist for algorithms where the
    # communication pattern *is* the algorithm (cdist ring, TSQR, sample
    # sort, halo exchange).

    def axis_index(self):
        """Device's own index along the mesh axis (inside shard_map)."""
        return jax.lax.axis_index(self.axis_name)

    def psum(self, x):
        """Allreduce(SUM) → ``lax.psum`` (reference ``Allreduce``, ``:749``)."""
        return jax.lax.psum(x, self.axis_name)

    def pmax(self, x):
        return jax.lax.pmax(x, self.axis_name)

    def pmin(self, x):
        return jax.lax.pmin(x, self.axis_name)

    def pmean(self, x):
        return jax.lax.pmean(x, self.axis_name)

    def exscan(self, x):
        """Exclusive prefix sum over devices (reference ``Exscan``, ``:872``).

        Hillis-Steele doubling: ``ceil(log2 size)`` ``ppermute`` rounds of
        O(n) bytes each — O(n log p) total, vs the O(n·p) of an all-gather
        formulation (round-1 VERDICT weak #6). Unlisted ``ppermute``
        receivers get zeros, the scan's neutral element."""
        import jax.numpy as jnp

        n = self.size
        acc = x
        shift = 1
        while shift < n:
            acc = acc + jax.lax.ppermute(
                acc, self.axis_name,
                perm=[(i, i + shift) for i in range(n - shift)])
            shift *= 2
        return acc - x

    def all_gather(self, x, axis: int = 0):
        """Allgather → ``lax.all_gather`` concatenated along ``axis``
        (reference ``Allgather``/``Allgatherv``, ``:1002``)."""
        return jax.lax.all_gather(x, self.axis_name, axis=axis, tiled=True)

    def all_to_all(self, x, split_axis: int, concat_axis: int):
        """Alltoall with axis change → ``lax.all_to_all``
        (reference ``Alltoall(v/w)``, ``:1199-1341``)."""
        return jax.lax.all_to_all(
            x, self.axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
        )

    def ppermute(self, x, perm):
        """Point-to-point permutation (reference ``Send``/``Recv`` rings)."""
        return jax.lax.ppermute(x, self.axis_name, perm=perm)

    def ring_shift(self, x, shift: int = 1):
        """Systolic ring step: device i sends to (i+shift) % size.

        The communication skeleton of the reference's cdist ring
        (``heat/spatial/distance.py:280-362``) and of ring attention.
        """
        n = self.size
        perm = [(i, (i + shift) % n) for i in range(n)]
        return jax.lax.ppermute(x, self.axis_name, perm=perm)

    def broadcast_from(self, x, root: int = 0):
        """Bcast from device ``root`` (reference ``Bcast``, ``:668``).

        Masked psum (log-depth all-reduce, O(n) per device) instead of
        gathering all shards to pick one (round-1 VERDICT weak #6)."""
        import jax.numpy as jnp

        me = jax.lax.axis_index(self.axis_name)
        xa = jnp.asarray(x)
        contrib = jnp.where(me == root, xa, jnp.zeros_like(xa))
        if xa.dtype == jnp.bool_:
            return jax.lax.psum(contrib.astype(jnp.int32), self.axis_name) > 0
        return jax.lax.psum(contrib, self.axis_name)

    def scan(self, x):
        """Inclusive prefix sum over devices (reference ``Scan``, ``:845``)."""
        return self.exscan(x) + x

    # ------------------------------------------------------------------ #
    # reference-named aliases (migration surface)                        #
    # ------------------------------------------------------------------ #
    # The reference exposes MPI names in blocking + nonblocking pairs
    # (``communication.py:458-1872``). The blocking names map 1:1 onto the
    # collectives above; the I-variants return an immediately-complete
    # :class:`Request` — under XLA the *compiler* owns comm/compute overlap
    # (the very thing the reference builds wait-handle machinery for), so
    # "nonblocking" is the default execution model, not an API mode.

    def Allreduce(self, x):
        return self.psum(x)

    def Allgather(self, x, axis: int = 0):
        return self.all_gather(x, axis)

    Allgatherv = Allgather

    def Alltoall(self, x, split_axis: int = 0, concat_axis: int = 0):
        return self.all_to_all(x, split_axis, concat_axis)

    Alltoallv = Alltoall
    Alltoallw = Alltoall

    def Bcast(self, x, root: int = 0):
        return self.broadcast_from(x, root)

    def Exscan(self, x):
        return self.exscan(x)

    def Scan(self, x):
        return self.scan(x)

    def Iallreduce(self, x):
        return Request(self.psum(x))

    def Iallgather(self, x, axis: int = 0):
        return Request(self.all_gather(x, axis))

    Iallgatherv = Iallgather

    def Ialltoall(self, x, split_axis: int = 0, concat_axis: int = 0):
        return Request(self.all_to_all(x, split_axis, concat_axis))

    Ialltoallv = Ialltoall
    Ialltoallw = Ialltoall

    def Ibcast(self, x, root: int = 0):
        return Request(self.broadcast_from(x, root))

    def Iexscan(self, x):
        return Request(self.exscan(x))

    def Iscan(self, x):
        return Request(self.scan(x))

    # ------------------------------------------------------------------ #
    # sub-communicators                                                  #
    # ------------------------------------------------------------------ #
    def Split(self, devices: Optional[Sequence[int]] = None,
              axis_name: Optional[str] = None, *, color=None, key=None):
        """New communicator over a subset of devices (reference ``Split``,
        ``:445``). MPI's per-rank ``Split(color, key)`` has no "this rank"
        under the single-controller SPMD model — pass the subgroup's device
        indices instead (one call per group)."""
        if (color is not None or key is not None
                or isinstance(devices, int)  # positional mpi4py color
                or not (axis_name is None or isinstance(axis_name, str))):
            # catches Split(color), Split(color, key) and Split(devs, key):
            # mpi4py's convention is positional, so an int in either slot is
            # migrating MPI code, not a device list / axis name
            raise TypeError(
                "MPI-style Split(color, key) is per-rank; under the "
                "single-controller model pass the subgroup's device indices: "
                "comm.Split(devices=[...]) — one call per group (see "
                "doc/migrating_from_heat.md)")
        if devices is None:
            raise TypeError("Split requires the subgroup's device indices")
        sub = [self._devices[i] for i in devices]
        return TPUCommunication(sub, axis_name or self.axis_name)


class MeshAxisComm(TPUCommunication):
    """A single named axis of a :class:`MeshGrid`, exposed as a communicator.

    Shares the grid's N-D ``jax.sharding.Mesh``; every inherited collective
    (``psum``/``all_gather``/``all_to_all``/``ppermute``/``ring_shift``/…)
    runs over THIS axis only, and ``sharding``/``spec`` place this axis at
    the split dimension (replicated across the grid's other axes). A
    DNDarray created with ``comm=grid.axis("dp")`` is therefore sharded over
    the dp rows of the grid and replicated over the other axes — the
    building block for combined dp×sp programs.
    """

    def __init__(self, grid: "MeshGrid", axis_name: str):
        self._grid = grid
        self._devices = tuple(grid.mesh.devices.flatten())
        self.axis_name = axis_name
        self.mesh = grid.mesh

    @property
    def size(self) -> int:
        return self.mesh.shape[self.axis_name]

    @property
    def grid(self) -> "MeshGrid":
        return self._grid

    @property
    def cache_key(self) -> Tuple:
        return (
            self.axis_name,
            tuple(self.mesh.shape.items()),
            tuple(d.id for d in self._devices),
        )

    def __repr__(self) -> str:
        return (
            f"MeshAxisComm(axis='{self.axis_name}', size={self.size}, "
            f"grid={dict(self.mesh.shape)})"
        )


class MeshGrid:
    """A named N-D device mesh for combined parallelism (e.g. dp × sp).

    The reference's single-axis ``split`` model composes one strategy at a
    time; a grid composes several — the batch sharded over one axis while
    the sequence (ring attention) is sharded over another, in the same
    compiled program. Multi-host pods: the leading axis is typically the DCN
    (slow) axis, trailing axes ride ICI.

    >>> grid = MeshGrid((2, 4), ("dp", "sp"))
    >>> xb = ht.random.rand(64, 16, split=0, comm=grid.axis("dp"))   # batch
    >>> qs = ht.random.rand(1, 256, 8, 16, split=1, comm=grid.axis("sp"))
    """

    def __init__(self, shape: Sequence[int], axis_names: Sequence[str] = ("dp", "sp"),
                 devices: Optional[Sequence] = None):
        shape = tuple(int(s) for s in shape)
        axis_names = tuple(axis_names)
        if len(shape) != len(axis_names):
            raise ValueError(f"shape {shape} and axis_names {axis_names} length mismatch")
        if devices is None:
            devices = tuple(jax.devices())
        else:
            devices = tuple(devices)
        want = int(np.prod(shape))
        if want != len(devices):
            raise ValueError(f"grid shape {shape} needs {want} devices, got {len(devices)}")
        self.shape = shape
        self.axis_names = axis_names
        self.mesh = Mesh(np.asarray(devices).reshape(shape), axis_names)
        self._axes = {name: MeshAxisComm(self, name) for name in axis_names}

    def axis(self, name: str) -> MeshAxisComm:
        """The communicator view of one grid axis."""
        return self._axes[name]

    def spec(self, ndim: int, **axis_to_dim: int) -> PartitionSpec:
        """PartitionSpec placing each named grid axis at the given dimension,
        e.g. ``grid.spec(4, dp=0, sp=1)`` for a (batch✂dp, seq✂sp, …) array."""
        placement = [None] * ndim
        for name, dim in axis_to_dim.items():
            if name not in self._axes:
                raise ValueError(f"unknown grid axis {name!r}; have {self.axis_names}")
            if not -ndim <= dim < ndim:
                raise ValueError(f"dimension {dim} out of range for ndim {ndim}")
            dim %= ndim
            if placement[dim] is not None:
                raise ValueError(
                    f"grid axes {placement[dim]!r} and {name!r} both map to dimension {dim}"
                )
            placement[dim] = name
        return PartitionSpec(*placement)

    def sharding(self, ndim: int, **axis_to_dim: int) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec(ndim, **axis_to_dim))

    def __repr__(self) -> str:
        return f"MeshGrid({dict(zip(self.axis_names, self.shape))})"


# ---------------------------------------------------------------------- #
# module globals (reference ``communication.py:1886-1933``)              #
# ---------------------------------------------------------------------- #
# World/self communicators are LAZY: importing heat_tpu must not touch the
# XLA backend, or ``distributed_init`` (which must run before any backend
# use) could never be called after the import. They materialize on first
# attribute access via module ``__getattr__`` (``MPI_WORLD``/``MPI_SELF``
# mirror the reference's aliases) and ``distributed_init`` rebuilds them.
_mesh_world: Optional[TPUCommunication] = None
_mesh_self: Optional[TPUCommunication] = None
__default_comm: Optional[TPUCommunication] = None


def _world() -> TPUCommunication:
    global _mesh_world
    if _mesh_world is None:
        _mesh_world = TPUCommunication()
    return _mesh_world


def __getattr__(name: str):
    global _mesh_self
    if name in ("MESH_WORLD", "MPI_WORLD"):
        return _world()
    if name in ("MESH_SELF", "MPI_SELF"):
        if _mesh_self is None:
            _mesh_self = TPUCommunication(jax.devices()[:1])
        return _mesh_self
    raise AttributeError(
        f"module 'heat_tpu.core.communication' has no attribute {name!r}")


def get_comm() -> TPUCommunication:
    """Return the default communicator (reference ``get_comm``, ``:1893``)."""
    global __default_comm
    if __default_comm is None:
        __default_comm = _world()
    return __default_comm


def use_comm(comm: TPUCommunication) -> None:
    """Set the default communicator (reference ``use_comm``, ``:1923``)."""
    global __default_comm
    if not isinstance(comm, Communication):
        raise TypeError(f"comm must be a Communication, got {type(comm)}")
    __default_comm = comm


def sanitize_comm(comm) -> TPUCommunication:
    """Validate-or-default a communicator (reference ``sanitize_comm``, ``:1902``)."""
    if comm is None:
        return get_comm()
    if not isinstance(comm, Communication):
        raise TypeError(f"comm must be a Communication, got {type(comm)}")
    return comm


def distributed_init(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     max_retries: Optional[int] = None,
                     backoff_s: Optional[float] = None,
                     **kwargs) -> TPUCommunication:
    """Join a multi-host pod and rebuild the world communicator.

    The reference's runtime bring-up is ``MPI.COMM_WORLD`` at import time
    (``communication.py:1886``); the TPU-native equivalent is explicit:
    ``jax.distributed.initialize`` (topology auto-detected on TPU pods —
    all arguments optional there) followed by a world communicator over
    the now-global device set. Host-local shards feed in through
    ``factories.array(..., is_split=...)`` / per-host chunked I/O exactly
    as single-host; collectives ride ICI within a slice and DCN across
    hosts via the mesh.

    HARDENED FAILURE DOMAIN (doc/robustness.md): on a multi-host pod the
    coordinator is typically another freshly-booting host, so the first
    connect attempt failing is the COMMON case, not the exceptional one.
    A failed ``jax.distributed.initialize`` is retried with bounded
    exponential backoff plus deterministic per-process jitter (seeded
    from ``process_id`` and the attempt number — hosts desynchronize
    without losing reproducibility). ``max_retries`` (default 4, env
    ``HEAT_TPU_INIT_MAX_RETRIES``) bounds the retries; ``backoff_s``
    (default 0.5, env ``HEAT_TPU_INIT_BACKOFF_S``) is the base delay,
    doubling per attempt and capped at 30 s. Each retry counts
    ``init.connect_retries`` in :mod:`heat_tpu.utils.metrics`; the final
    failure re-raises the connect error.

    Returns the new default communicator (also installed via
    :func:`use_comm` and as ``MESH_WORLD``).
    """
    import os
    import random
    import time

    from ..utils import faults as _faults
    from ..utils import metrics as _metrics

    if max_retries is None:
        max_retries = int(os.environ.get("HEAT_TPU_INIT_MAX_RETRIES", "4"))
    if backoff_s is None:
        backoff_s = float(os.environ.get("HEAT_TPU_INIT_BACKOFF_S", "0.5"))
    attempt = 0
    while True:
        try:
            _faults.check("init.coordinator.connect")
            # None arguments mean auto-detect (the TPU-pod default)
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id, **kwargs)
            break
        except Exception:
            attempt += 1
            if attempt > max_retries:
                raise
            # a failed connect leaves jax.distributed's global client/
            # service state SET on this jax (State.initialize assigns
            # them before client.connect()), and a second initialize()
            # would then refuse with "should only be called once" —
            # tear the half-initialized state down before retrying
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            _metrics.inc("init.connect_retries")
            delay = min(30.0, backoff_s * (2.0 ** (attempt - 1)))
            # deterministic jitter in [0.5, 1.0) x delay: same process +
            # same attempt -> same sleep, different processes spread out
            rng = random.Random((process_id or 0) * 7919 + attempt)
            time.sleep(delay * (0.5 + 0.5 * rng.random()))
    global _mesh_world
    _mesh_world = TPUCommunication(jax.devices())
    use_comm(_mesh_world)
    return _mesh_world
