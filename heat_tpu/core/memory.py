"""Memory layout helpers (reference ``heat/core/memory.py``)."""

from __future__ import annotations

import jax.numpy as jnp

from .dndarray import DNDarray

__all__ = ["copy", "sanitize_memory_layout", "sanitize_memory_order"]


def sanitize_memory_order(order: str) -> str:
    """Validate an ``order=`` keyword without an array (factory signatures
    carry it for reference parity, ``factories.py:488-1322``). ``C``/``K``/
    ``A`` all mean the row-major layout XLA owns; ``F`` is rejected."""
    if order not in ("C", "F", "K", "A"):
        raise ValueError(f"order must be one of 'C', 'F', 'K', 'A', got {order!r}")
    if order == "F":
        raise NotImplementedError("column-major layout is not supported on the XLA backend")
    return order


def copy(x: DNDarray) -> DNDarray:
    """Physical copy of a DNDarray (reference ``memory.py:13``)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
    return DNDarray(jnp.copy(x.larray), x.gshape, x.dtype, x.split, x.device, x.comm)


def sanitize_memory_layout(x, order: str = "C"):
    """Memory-order enforcement (reference ``memory.py:42``).

    XLA owns physical layout on TPU; only the default row-major view is
    meaningful, so ``order='F'`` is rejected rather than silently ignored.
    """
    sanitize_memory_order(order)
    return x
