"""Printing controls (reference ``heat/core/printing.py``).

The reference distinguishes *global* printing (gather to rank 0, summarize)
from *local* printing (each rank prints its shard). Under a single controller
the global array is always addressable; "local" mode prints per-device shard
shapes and the addressable shards instead.
"""

from __future__ import annotations

import numpy as np

__all__ = ["get_printoptions", "global_printing", "local_printing", "print0", "set_printoptions"]

# summarization threshold mirrors the reference's default behavior
__PRINT_LOCAL = False


def get_printoptions() -> dict:
    """Current NumPy print options (reference ``printing.py:23``)."""
    return dict(np.get_printoptions())


def global_printing() -> None:
    """Print the global array (default; reference ``printing.py:62``)."""
    global __PRINT_LOCAL
    __PRINT_LOCAL = False


def local_printing() -> None:
    """Print per-device shards (reference ``printing.py:30``)."""
    global __PRINT_LOCAL
    __PRINT_LOCAL = True


def print0(*args, **kwargs) -> None:
    """Print once from the controller (reference ``printing.py:100``).

    Single-controller JAX has exactly one printing process, so this is
    plain ``print`` — kept for script parity with ``mpirun`` jobs.
    """
    print(*args, **kwargs)


def set_printoptions(precision=None, threshold=None, edgeitems=None, linewidth=None, profile=None, sci_mode=None):
    """Configure summarization (reference ``printing.py:150``)."""
    if profile is not None:
        profiles = {
            "default": dict(precision=4, threshold=1000, edgeitems=3, linewidth=80),
            "short": dict(precision=2, threshold=1000, edgeitems=2, linewidth=80),
            "full": dict(precision=4, threshold=int(1e9), edgeitems=3, linewidth=80),
        }
        if profile not in profiles:
            raise ValueError(f"unknown profile {profile!r}")
        np.set_printoptions(**profiles[profile])
    opts = {}
    if precision is not None:
        opts["precision"] = precision
    if threshold is not None:
        opts["threshold"] = threshold
    if edgeitems is not None:
        opts["edgeitems"] = edgeitems
    if linewidth is not None:
        opts["linewidth"] = linewidth
    if sci_mode is not None:
        opts["suppress"] = not sci_mode
    if opts:
        np.set_printoptions(**opts)


def __str__(x) -> str:
    """Render a DNDarray (used by ``DNDarray.__repr__``)."""
    if __PRINT_LOCAL:
        shards = [
            f"device {i}: shape {tuple(s.data.shape)}" for i, s in enumerate(x.larray.addressable_shards)
        ]
        return f"DNDarray(split={x.split}, local shards: " + "; ".join(shards) + ")"
    try:
        values = np.asarray(x._logical())
        body = np.array2string(values, separator=", ")
    except Exception as exc:  # un-materializable (e.g., inside tracing)
        body = f"<unrealized: {exc}>"
    return f"DNDarray({body}, dtype=ht.{x.dtype.__name__}, device={x.device}, split={x.split})"
