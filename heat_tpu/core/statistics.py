"""Statistical operations (reference ``heat/core/statistics.py``).

The reference's distributed statistics machinery — custom MPI argmin/argmax
ops carrying value+index payloads (``statistics.py:1185-1255``) and pairwise
moment merging for mean/var (``mean`` ``:741``, ``__merge_moments`` ``:893``)
— disappears on the XLA backend: value-index reductions and numerically
stable moments are single fused programs over the sharded array, with
``psum``-style collectives inserted by GSPMD. The only extra step is the
canonical-padding neutral fill (``DNDarray.filled``).
"""

from __future__ import annotations

from builtins import range as builtins_range

from typing import Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp
from ._compat import shard_map

from . import _operations, arithmetics, types
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis

__all__ = [
    "argmax",
    "argmin",
    "average",
    "bincount",
    "bucketize",
    "corrcoef",
    "cov",
    "digitize",
    "gradient",
    "histc",
    "histogram",
    "histogram2d",
    "histogramdd",
    "interp",
    "kurtosis",
    "max",
    "maximum",
    "mean",
    "median",
    "min",
    "minimum",
    "nanargmax",
    "nanargmin",
    "nanmax",
    "nanmean",
    "nanmedian",
    "nanmin",
    "nanpercentile",
    "nanprod",
    "nanquantile",
    "nanstd",
    "nansum",
    "nanvar",
    "percentile",
    "ptp",
    "quantile",
    "searchsorted",
    "skew",
    "std",
    "trapz",
    "var",
]


def _max_neutral(x: DNDarray):
    """Neutral element for max-reductions (smallest representable)."""
    if types.heat_type_is_exact(x.dtype):
        return types.iinfo(x.dtype).min if x.dtype is not types.bool else 0
    return -float("inf")


def _min_neutral(x: DNDarray):
    if types.heat_type_is_exact(x.dtype):
        return types.iinfo(x.dtype).max if x.dtype is not types.bool else 1
    return float("inf")


def argmax(x: DNDarray, axis=None, out=None, **kwargs) -> DNDarray:
    """Index of the maximum (reference ``statistics.py:115``; the custom
    MPI_ARGMAX value-index reduction ``:1185-1255`` is an XLA variadic
    reduce here)."""
    return _arg_reduce(x, jnp.argmax, _max_neutral(x), axis, out)


def argmin(x: DNDarray, axis=None, out=None, **kwargs) -> DNDarray:
    """Index of the minimum (reference ``statistics.py:178``)."""
    return _arg_reduce(x, jnp.argmin, _min_neutral(x), axis, out)


def _arg_reduce(x, op, neutral, axis, out):
    from . import sanitation

    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        # flat reduce: physical flat order equals logical order only when
        # there is no padding; otherwise use the logical view
        src = x.larray if x.pad == 0 else x._logical()
        res = op(src.reshape(-1))
        result = DNDarray.from_logical(res, None, x.device, x.comm)
        return _operations._finalize(result, out)
    touches_split = x.split == axis
    physical = x.filled(neutral) if (touches_split and x.pad) else x.larray
    res = op(physical, axis=axis)
    gshape = tuple(s for i, s in enumerate(x.shape) if i != axis)
    if x.split is None:
        out_split = None
    elif touches_split:
        out_split = None
    else:
        out_split = x.split - (1 if axis < x.split else 0)
    result = DNDarray(res, gshape, types.canonical_heat_type(res.dtype), out_split, x.device, x.comm)
    return _operations._finalize(result, out)


def average(x: DNDarray, axis=None, weights=None, returned=False):
    """Weighted average (reference ``statistics.py:236``)."""
    if weights is None:
        result = mean(x, axis)
        if returned:
            n = x.size if axis is None else np.prod([x.shape[a] for a in _axes(x, axis)])
            from . import factories

            # count inherits the result dtype (reference keeps the element
            # count in result.dtype, ``statistics.py:261-263``); full_like's
            # reference-parity float32 default would truncate counts > 2**24
            return result, factories.full_like(result, float(n), dtype=result.dtype)
        return result
    if not isinstance(weights, DNDarray):
        from . import factories

        weights = factories.array(weights, comm=x.comm)
    if axis is None:
        if weights.shape != x.shape:
            raise TypeError("Axis must be specified when shapes of x and weights differ.")
        num = arithmetics.sum(arithmetics.mul(x, weights))
        den = arithmetics.sum(weights)
    else:
        axis = sanitize_axis(x.shape, axis)
        if not isinstance(axis, int):
            raise NotImplementedError("weighted average over multiple axes is not supported")
        if weights.shape == x.shape:
            w = weights
        elif weights.ndim != 1 or weights.shape[0] != x.shape[axis]:
            # numpy's exact contract (2.x wording): unequal shapes are
            # legal ONLY for 1-D weights along the reduced axis
            raise ValueError(
                "Shape of weights must be consistent with shape of x "
                "along specified axis.")
        else:
            # classic 1-D weights along the reduced axis
            shape = [1] * x.ndim
            shape[axis] = x.shape[axis]
            w = weights.reshape(tuple(shape))
        num = arithmetics.sum(arithmetics.mul(x, w), axis=axis)
        # denominator: the aligned ``w`` summed along ``axis`` (numpy's
        # scl). Same elements as the old raw-``weights`` fallback, but the
        # axis-shaped form keeps ``returned=True`` broadcasting uniform
        # and records onto the SAME fusion tape as ``num`` — one flush,
        # one packed all-reduce for the pair
        den = arithmetics.sum(w, axis=axis)
    zero = bool((den == 0).any().item()) if isinstance(den, DNDarray) else den == 0
    if zero:
        raise ZeroDivisionError("Weights sum to zero, can't be normalized")
    result = arithmetics.div(num, den)
    if returned:
        if isinstance(den, DNDarray) and den.shape != result.shape:
            from . import manipulations

            den = manipulations.broadcast_to(den, result.shape)
        return result, den
    return result


def _axes(x, axis):
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        return tuple(range(x.ndim))
    return (axis,) if isinstance(axis, int) else axis


_COUNT_CACHE: dict = {}


def _aligned_weight_phys(x: DNDarray, weights):
    """Weights as a physical array aligned with ``x``'s shards (same split,
    same chunks — same-shape weights on a different layout re-chunk through
    one reshard program), or None when the alignment needs a fallback."""
    if weights is None:
        return jnp.ones(x.larray.shape, jnp.float64 if jax.config.jax_enable_x64
                        else jnp.float32)
    if isinstance(weights, DNDarray):
        if weights.gshape == x.gshape and weights.split != x.split:
            weights = weights.resplit(x.split)
        if weights.split == x.split and weights.larray.shape == x.larray.shape:
            return weights.larray
        return None
    w = jnp.asarray(weights)
    if x.split is None or w.shape != x.gshape:
        return w if w.shape == x.larray.shape else None
    pad = x.larray.shape[x.split] - x.gshape[x.split]
    if pad:
        cfg = [(0, pad if i == x.split else 0) for i in range(x.ndim)]
        w = jnp.pad(w, cfg)
    return jax.device_put(w, x.comm.sharding(x.ndim, x.split))


def bincount(x: DNDarray, weights=None, minlength: int = 0) -> DNDarray:
    """Count occurrences of non-negative ints (reference ``statistics.py:389``).

    Split arrays count shard-locally and merge with one psum (the
    reference's Allreduce of per-rank counts); only the global max (the
    output length — a dynamic shape) syncs to host."""
    if not types.heat_type_is_exact(x.dtype):
        raise TypeError("bincount requires an integer array")
    if isinstance(weights, DNDarray) and weights.gshape != x.gshape:
        raise ValueError("weights and x don't have the same shape")
    if x.split is not None and x.comm.size > 1 and x.ndim == 1 and x.size > 0:
        comm = x.comm
        lo = int(jnp.min(x.filled(0)))
        if lo < 0:
            raise ValueError("bincount requires non-negative entries")
        # NB: plain ``max`` is this module's reduction, not the builtin
        length = int(np.maximum(int(minlength), int(jnp.max(x.filled(0))) + 1))
        w_phys = _aligned_weight_phys(x, weights)
        if w_phys is not None:
            valid = x.valid_mask()
            wdt = (jnp.int64 if jax.config.jax_enable_x64 else jnp.int32) \
                if weights is None else w_phys.dtype
            cache_key = ("bincount", x.larray.shape, str(x.larray.dtype),
                         length, str(jnp.dtype(wdt)), comm.cache_key)
            fn = _COUNT_CACHE.get(cache_key)
            if fn is None:
                def body(xb, wb, vb):
                    wv = jnp.where(vb, wb.astype(wdt), 0)
                    counts = jnp.bincount(
                        jnp.clip(xb, 0, length - 1), weights=wv,
                        length=length)
                    return jax.lax.psum(counts, comm.axis_name)

                fn = jax.jit(shard_map(
                    body, mesh=comm.mesh,
                    in_specs=(comm.spec(1, 0),) * 3,
                    out_specs=comm.spec(1, None), check_vma=False))
                _COUNT_CACHE[cache_key] = fn
            res = fn(x.larray, w_phys, valid)
            return DNDarray.from_logical(res, None, x.device, comm)
    logical = x._logical()
    w = None
    if weights is not None:
        w = weights._logical() if isinstance(weights, DNDarray) else jnp.asarray(weights)
    length = int(jnp.maximum(minlength, (logical.max() + 1) if logical.size else 0))
    res = jnp.bincount(logical.reshape(-1), weights=None if w is None else w.reshape(-1), length=length)
    return DNDarray.from_logical(res, None, x.device, x.comm)


def bucketize(input: DNDarray, boundaries, right: bool = False, out=None) -> DNDarray:
    """Bucket indices by boundaries (reference ``statistics.py:440``)."""
    b = boundaries._logical() if isinstance(boundaries, DNDarray) else jnp.asarray(boundaries)
    side = "left" if right else "right"
    return _operations._local_op(lambda a: jnp.searchsorted(b, a, side=side).astype(jnp.int64), input, out)


def digitize(x: DNDarray, bins, right: bool = False) -> DNDarray:
    """NumPy-style bin indices."""
    b = bins._logical() if isinstance(bins, DNDarray) else jnp.asarray(bins)
    return _operations._local_op(lambda a: jnp.digitize(a, b, right=right), x)


def cov(m: DNDarray, y=None, rowvar: bool = True, bias: bool = False, ddof=None) -> DNDarray:
    """Covariance matrix estimate (reference ``statistics.py:544``): centered
    Gram matrix via distributed matmul (MXU) + psum."""
    if ddof is not None and not isinstance(ddof, int):
        raise TypeError("ddof must be integer")
    if m.ndim > 2:
        raise ValueError("m has more than 2 dimensions")
    from . import manipulations
    from .linalg import matmul, transpose

    x = m
    if x.ndim == 1:
        x = x.reshape((1, x.shape[0]))
    if not rowvar and x.shape[0] != 1:
        x = transpose(x)
    if y is not None:
        if y.ndim > 2:
            raise ValueError("y has more than 2 dimensions")
        yy = y
        if yy.ndim == 1:
            yy = yy.reshape((1, yy.shape[0]))
        if not rowvar and yy.shape[0] != 1:
            yy = transpose(yy)
        x = manipulations.concatenate([x, yy], axis=0)
    if ddof is None:
        ddof = 0 if bias else 1
    n = x.shape[1]
    mu = mean(x, axis=1)
    centered = arithmetics.sub(x, mu.reshape((x.shape[0], 1)))
    norm = n - ddof
    c = matmul(centered, transpose(centered))
    return arithmetics.div(c, float(norm))


def _hist_counts_distributed(x: DNDarray, edges, weights):
    """psum of per-shard histograms against fixed ``edges`` (the
    reference's Allreduce of local torch.histc counts), or None when the
    weights cannot be chunk-aligned."""
    comm = x.comm
    w_phys = _aligned_weight_phys(x, weights)
    if w_phys is None:
        return None
    wdt = (jnp.int64 if jax.config.jax_enable_x64 else jnp.int32) \
        if weights is None else w_phys.dtype
    edges = np.asarray(edges, dtype=np.float64)
    cache_key = ("hist", x.larray.shape, str(x.larray.dtype), x.split,
                 edges.tobytes(), str(jnp.dtype(wdt)), comm.cache_key)
    fn = _COUNT_CACHE.get(cache_key)
    if fn is None:
        edges_j = jnp.asarray(edges)

        def body(xb, wb, vb):
            wv = jnp.where(vb, wb.astype(wdt), 0).reshape(-1)
            h, _ = jnp.histogram(xb.reshape(-1), bins=edges_j, weights=wv)
            return jax.lax.psum(h, comm.axis_name)

        fn = jax.jit(shard_map(
            body, mesh=comm.mesh,
            in_specs=(comm.spec(x.ndim, x.split),) * 3,
            out_specs=comm.spec(1, None), check_vma=False))
        _COUNT_CACHE[cache_key] = fn
    return fn(x.larray, w_phys, x.valid_mask())


def _minmax_scalars(x: DNDarray):
    """Global (min, max) with padding neutralized — two scalar fetches."""
    jdt = x.larray.dtype
    if jdt == jnp.bool_:
        hi_fill, lo_fill = False, True
    elif jnp.issubdtype(jdt, jnp.inexact):
        hi_fill, lo_fill = -jnp.inf, jnp.inf
    else:
        info = jnp.iinfo(jdt)
        hi_fill, lo_fill = info.min, info.max
    lo = float(jnp.min(x.filled(lo_fill)))
    hi = float(jnp.max(x.filled(hi_fill)))
    return lo, hi


def histc(input: DNDarray, bins: int = 100, min=0, max=0, out=None) -> DNDarray:
    """Histogram with uniform bins (reference ``statistics.py:660``): split
    arrays histogram shard-locally against the shared edges and merge with
    one psum."""
    lo, hi = float(min), float(max)
    if input.split is not None and input.comm.size > 1 and input.size > 0:
        if lo == 0 and hi == 0:
            lo, hi = _minmax_scalars(input)
        if lo == hi:  # degenerate range expands like jnp.histogram's
            lo, hi = lo - 0.5, hi + 0.5
        edges = np.linspace(lo, hi, int(bins) + 1)
        res = _hist_counts_distributed(input, edges, None)
        if res is not None:
            result = DNDarray.from_logical(
                res.astype(input.dtype.jax_type()), None, input.device,
                input.comm)
            return _operations._finalize(result, out)
    logical = input._logical().reshape(-1)
    if lo == 0 and hi == 0:
        lo = float(logical.min()) if logical.size else 0.0
        hi = float(logical.max()) if logical.size else 1.0
    res, _ = jnp.histogram(logical, bins=int(bins), range=(lo, hi))
    result = DNDarray.from_logical(res.astype(input.dtype.jax_type()), None, input.device, input.comm)
    return _operations._finalize(result, out)


def histogram(a: DNDarray, bins=10, range=None, normed=None, weights=None, density=None):
    """NumPy-style histogram (reference ``statistics.py:700``): split arrays
    histogram shard-locally against shared edges and merge with one psum;
    density normalizes after the merge."""
    if (
        a.split is not None
        and a.comm.size > 1
        and a.size > 0
        and not isinstance(bins, DNDarray)
    ):
        if np.ndim(bins) == 0:
            if range is not None:
                lo, hi = float(range[0]), float(range[1])
            else:
                lo, hi = _minmax_scalars(a)
            if lo == hi:  # numpy expands degenerate ranges, explicit or not
                lo, hi = lo - 0.5, hi + 0.5
            edges = np.linspace(lo, hi, int(bins) + 1)
        else:
            edges = np.asarray(bins, dtype=np.float64)
        res = _hist_counts_distributed(a, edges, weights)
        if res is not None:
            if density:
                total = float(jnp.sum(res))
                res = res / (total * jnp.asarray(np.diff(edges)))
            return (
                DNDarray.from_logical(res, None, a.device, a.comm),
                DNDarray.from_logical(jnp.asarray(edges), None, a.device,
                                      a.comm),
            )
    logical = a._logical().reshape(-1)
    w = weights._logical().reshape(-1) if isinstance(weights, DNDarray) else weights
    hist, edges = jnp.histogram(logical, bins=bins, range=range, weights=w, density=density)
    return (
        DNDarray.from_logical(hist, None, a.device, a.comm),
        DNDarray.from_logical(edges, None, a.device, a.comm),
    )


def histogramdd(sample, bins=10, range=None, weights=None,
                density: bool = False):
    """D-dimensional histogram (``numpy.histogramdd``): per-dimension bin
    indices (elementwise on the split sample) collapse to one flat index
    and ONE distributed bincount psum — out-of-range samples route to a
    dropped overflow bin, so nothing gathers.

    ``sample`` is an ``(N, D)`` DNDarray or a sequence of ``(N,)`` arrays.
    Returns ``(H, edges)`` with ``H`` replicated like :func:`histogram`'s
    counts."""
    from . import factories, logical, indexing

    if isinstance(sample, DNDarray):
        if sample.ndim == 1:
            sample = sample.reshape((sample.shape[0], 1))
        cols = [sample[:, d] for d in builtins_range(sample.shape[1])]
    else:
        cols = [c if isinstance(c, DNDarray) else factories.array(np.asarray(c))
                for c in sample]
    nbins, edges_list = [], []
    for d, col in enumerate(cols):
        b = bins[d] if isinstance(bins, (list, tuple)) else bins
        if np.ndim(b) == 0:
            if range is not None and range[d] is not None:
                lo, hi = float(range[d][0]), float(range[d][1])
            elif col.size == 0:
                lo, hi = 0.0, 1.0  # numpy's empty-sample default edges
            else:
                lo, hi = _minmax_scalars(col)
            if lo == hi:
                lo, hi = lo - 0.5, hi + 0.5
            edges = np.linspace(lo, hi, int(b) + 1)
        else:
            edges = np.asarray(b, dtype=np.float64)
        nbins.append(len(edges) - 1)
        edges_list.append(edges)

    total = int(np.prod(nbins))
    flat = None
    valid = None
    stride = total
    for col, edges, nb in zip(cols, edges_list, nbins):
        stride //= nb
        idx = _searchsorted_minus1(col, edges)
        # the rightmost edge is closed (numpy): fold it into the last bin
        idx = indexing.where(col == float(edges[-1]),
                             factories.full_like(idx, nb - 1,
                                                 dtype=idx.dtype), idx)
        ok = logical.logical_and(col >= float(edges[0]),
                                 col <= float(edges[-1]))
        valid = ok if valid is None else logical.logical_and(valid, ok)
        term = idx.clip(0, nb - 1) * stride
        flat = term if flat is None else flat + term
    # invalid samples -> overflow bin (dropped after the count)
    flat = indexing.where(valid, flat,
                          factories.full_like(flat, total, dtype=flat.dtype))
    counts = bincount(flat.astype(types.int64), weights=weights,
                      minlength=total + 1)
    H = counts[:total].reshape(tuple(nbins))
    if density:
        vol = edges_list[0][1:] - edges_list[0][:-1]
        for e in edges_list[1:]:
            vol = np.multiply.outer(vol, e[1:] - e[:-1])
        tot = float(H.sum())
        H = H / (factories.array(vol, dtype=types.float64, comm=H.comm)
                 * (tot if tot else 1.0))
    return H, [factories.array(e, comm=H.comm) for e in edges_list]


def _searchsorted_minus1(col, edges):
    """``searchsorted(edges, col, 'right') - 1`` as a split-preserving
    elementwise op (the bin index before edge handling)."""
    ev = jnp.asarray(edges)
    return _operations._local_op(
        lambda t: (jnp.searchsorted(ev, t, side="right") - 1).astype(
            jnp.int64), col)


def histogram2d(x: DNDarray, y: DNDarray, bins=10, range=None, weights=None,
                density: bool = False):
    """2-D histogram (``numpy.histogram2d``): :func:`histogramdd` over the
    coordinate pair."""
    # numpy's bins forms: scalar -> both dims; length-2 sequence -> one
    # spec per dim; any other 1-D array_like -> SHARED edges for both dims
    # numpy's forms: scalar -> both dims; length-2 sequence -> one spec
    # per dim (counts/edges, possibly mixed); any other 1-D array_like ->
    # SHARED edges for both dims (np.ndim would choke on mixed tuples)
    if not np.isscalar(bins) and not isinstance(bins, DNDarray):
        try:
            length = len(bins)
        except TypeError:
            length = None
        if length is not None and length != 2 and all(
                np.isscalar(b) for b in bins):
            shared = np.asarray(bins)
            bins = [shared, shared]
        else:
            bins = list(bins)
    H, edges = histogramdd((x, y), bins=bins, range=range, weights=weights,
                           density=density)
    return H, edges[0], edges[1]


def kurtosis(x: DNDarray, axis=None, unbiased: bool = True, Fischer: bool = True) -> DNDarray:
    """Fourth standardized moment (reference ``statistics.py:720``)."""
    m4 = _central_moment(x, 4, axis)
    v = var(x, axis, ddof=0)
    k = arithmetics.div(m4, arithmetics.mul(v, v))
    if unbiased:
        n = float(x.size if axis is None else x.shape[sanitize_axis(x.shape, axis)])
        k = _operations._local_op(
            lambda g: ((n - 1) / ((n - 2) * (n - 3))) * ((n + 1) * g - 3 * (n - 1)) + 3, k
        )
    if Fischer:
        k = arithmetics.sub(k, 3.0)
    return k


def skew(x: DNDarray, axis=None, unbiased: bool = True) -> DNDarray:
    """Third standardized moment (reference ``statistics.py:1700``)."""
    m3 = _central_moment(x, 3, axis)
    s = std(x, axis, ddof=0)
    g = arithmetics.div(m3, _operations._local_op(lambda a: a**3, s))
    if unbiased:
        n = float(x.size if axis is None else x.shape[sanitize_axis(x.shape, axis)])
        g = _operations._local_op(lambda v: v * np.sqrt(n * (n - 1)) / (n - 2), g)
    return g


def _ipow_op(a, k):
    return a ** k


def _central_moment(x: DNDarray, k: int, axis):
    mu = _mean_keepdims(x, axis)
    centered = arithmetics.sub(x, mu)
    powed = _operations._local_op(_ipow_op, centered, k=k)
    return mean(powed, axis)


def max(x: DNDarray, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:  # noqa: A001
    """Maximum reduction (reference ``statistics.py:900``)."""
    if keepdim is not None:  # reference/torch keyword name
        keepdims = keepdim
    return _operations._reduce_op(x, jnp.max, _max_neutral(x), axis=axis, out=out, keepdims=keepdims)


def maximum(x1, x2, out=None) -> DNDarray:
    """Element-wise maximum (reference ``statistics.py:1000``)."""
    return _operations._binary_op(jnp.maximum, x1, x2, out)


def mean(x: DNDarray, axis=None) -> DNDarray:
    """Arithmetic mean (reference ``statistics.py:741``).

    The reference merges per-rank (μ, n) pairs with the Chan et al. update
    (``__merge_moments`` ``:893``); here the masked global sum divided by the
    logical count is a single XLA reduction."""
    s = arithmetics.sum(x, axis=axis)
    n = x.size if axis is None else int(np.prod([x.shape[a] for a in _axes(x, axis)]))
    return arithmetics.div(s, float(n) if n else 1.0)


def median(x: DNDarray, axis=None, keepdims: bool = False, keepdim=None) -> DNDarray:
    """Median (reference ``statistics.py:867``) — 50th percentile."""
    if keepdim is not None:  # reference/torch keyword name
        keepdims = keepdim
    return percentile(x, 50.0, axis=axis, keepdims=keepdims)


def min(x: DNDarray, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:  # noqa: A001
    """Minimum reduction (reference ``statistics.py:1050``)."""
    if keepdim is not None:  # reference/torch keyword name
        keepdims = keepdim
    return _operations._reduce_op(x, jnp.min, _min_neutral(x), axis=axis, out=out, keepdims=keepdims)


def minimum(x1, x2, out=None) -> DNDarray:
    """Element-wise minimum (reference ``statistics.py:1150``)."""
    return _operations._binary_op(jnp.minimum, x1, x2, out)


# --------------------------------------------------------------------------- #
# NaN-ignoring reductions (beyond the reference — heat has none; NumPy       #
# users expect them). Each is the corresponding masked reduction over the    #
# sharded array: NaNs are replaced with the op's neutral element in-register #
# and the existing distributed reduction runs unchanged.                     #
# --------------------------------------------------------------------------- #


def _nan_filled(x: DNDarray, fill) -> DNDarray:
    """``x`` with NaNs replaced by ``fill`` (lazy DNDarray expression)."""
    from . import logical, indexing, factories

    bad = logical.isnan(x)
    return indexing.where(bad, factories.full_like(x, fill, dtype=x.dtype), x)


def _nan_count(x: DNDarray, axis, keepdims: bool = False) -> DNDarray:
    """Count of non-NaN elements along ``axis``."""
    from . import logical

    return arithmetics.sum(
        logical.logical_not(logical.isnan(x)).astype(types.int64),
        axis=axis, keepdims=keepdims)


def nansum(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Sum ignoring NaNs (``numpy.nansum``; all-NaN slices sum to 0)."""
    if not types.heat_type_is_inexact(x.dtype):
        return arithmetics.sum(x, axis=axis, out=out, keepdims=keepdims)
    return arithmetics.sum(_nan_filled(x, 0.0), axis=axis, out=out,
                           keepdims=keepdims)


def nanprod(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Product ignoring NaNs (``numpy.nanprod``; all-NaN slices give 1)."""
    if not types.heat_type_is_inexact(x.dtype):
        return arithmetics.prod(x, axis=axis, out=out, keepdims=keepdims)
    return arithmetics.prod(_nan_filled(x, 1.0), axis=axis, out=out,
                            keepdims=keepdims)


def _nan_extremum(x, axis, keepdims, fill, reducer):
    from . import indexing, factories

    if not types.heat_type_is_inexact(x.dtype):
        return reducer(x, axis=axis, keepdims=keepdims)
    red = reducer(_nan_filled(x, fill), axis=axis, keepdims=keepdims)
    cnt = _nan_count(x, axis, keepdims=keepdims)
    # all-NaN slices: NumPy yields NaN (with a RuntimeWarning we skip)
    return indexing.where(cnt == 0, factories.full_like(red, float("nan"), dtype=red.dtype), red)


def nanmax(x: DNDarray, axis=None, keepdims: bool = False) -> DNDarray:
    """Maximum ignoring NaNs (``numpy.nanmax``; all-NaN slices give NaN)."""
    return _nan_extremum(x, axis, keepdims, float("-inf"), max)


def nanmin(x: DNDarray, axis=None, keepdims: bool = False) -> DNDarray:
    """Minimum ignoring NaNs (``numpy.nanmin``; all-NaN slices give NaN)."""
    return _nan_extremum(x, axis, keepdims, float("inf"), min)


def nanmean(x: DNDarray, axis=None, keepdims: bool = False) -> DNDarray:
    """Mean ignoring NaNs (``numpy.nanmean``; all-NaN slices give NaN)."""
    from . import indexing, factories

    if not types.heat_type_is_inexact(x.dtype):
        # no NaN exists in integral data; mean() matches the reference
        # signature (no keepdims), so reshape after
        m = mean(x, axis=axis)
        if keepdims:
            ax = _axes(x, axis)
            m = m.reshape(tuple(1 if i in ax else s
                                for i, s in enumerate(x.shape)))
        return m
    s = arithmetics.sum(_nan_filled(x, 0.0), axis=axis, keepdims=keepdims)
    cnt = _nan_count(x, axis, keepdims=keepdims)
    safe = indexing.where(cnt == 0, factories.ones_like(cnt, dtype=cnt.dtype), cnt)
    out = arithmetics.div(s, safe.astype(s.dtype))
    return indexing.where(cnt == 0, factories.full_like(out, float("nan"), dtype=out.dtype), out)


def nanvar(x: DNDarray, axis=None, ddof: int = 0, keepdims: bool = False) -> DNDarray:
    """Variance ignoring NaNs (``numpy.nanvar``; slices with fewer than
    ``ddof + 1`` non-NaN values give NaN)."""
    from . import indexing, factories, logical

    if not types.heat_type_is_inexact(x.dtype):
        v = var(x, axis=axis, ddof=ddof)
        if keepdims and axis is not None:  # var() has no keepdims (parity)
            ax = _axes(x, axis)
            v = v.reshape(tuple(1 if i in ax else s
                                for i, s in enumerate(x.shape)))
        elif keepdims:
            v = v.reshape((1,) * x.ndim)
        return v
    mu = nanmean(x, axis=axis, keepdims=True)
    dev2 = (x - mu) * (x - mu)
    bad = logical.isnan(x)
    dev2 = indexing.where(bad, factories.full_like(dev2, 0.0, dtype=dev2.dtype), dev2)
    s = arithmetics.sum(dev2, axis=axis, keepdims=keepdims)
    cnt = arithmetics.sum(logical.logical_not(bad).astype(types.int64),
                          axis=axis, keepdims=keepdims)
    denom = cnt - ddof
    safe = indexing.where(denom <= 0, factories.ones_like(denom, dtype=denom.dtype), denom)
    out = arithmetics.div(s, safe.astype(s.dtype))
    return indexing.where(denom <= 0, factories.full_like(out, float("nan"), dtype=out.dtype), out)


def nanstd(x: DNDarray, axis=None, ddof: int = 0, keepdims: bool = False) -> DNDarray:
    """Standard deviation ignoring NaNs (``numpy.nanstd``)."""
    from . import exponential

    return exponential.sqrt(nanvar(x, axis=axis, ddof=ddof, keepdims=keepdims))


def _nan_arg_extremum(x, axis, fill, arg_reducer):
    if not types.heat_type_is_inexact(x.dtype):
        return arg_reducer(x, axis=axis)
    # NumPy raises on any all-NaN slice; checking costs one fetch, which
    # these convenience APIs accept (parity with numpy's error contract)
    size = (x.size if axis is None
            else int(np.prod([x.shape[a] for a in _axes(x, axis)])))
    n_bad = size - _nan_count(x, axis)
    if bool(np.any(np.asarray(n_bad.resplit(None).larray) >= size)):
        raise ValueError("All-NaN slice encountered")
    return arg_reducer(_nan_filled(x, fill), axis=axis)


def nanargmax(x: DNDarray, axis=None) -> DNDarray:
    """Index of the maximum ignoring NaNs (``numpy.nanargmax``; raises
    ``ValueError`` on an all-NaN slice like NumPy)."""
    return _nan_arg_extremum(x, axis, float("-inf"), argmax)


def nanargmin(x: DNDarray, axis=None) -> DNDarray:
    """Index of the minimum ignoring NaNs (``numpy.nanargmin``)."""
    return _nan_arg_extremum(x, axis, float("inf"), argmin)


def nanpercentile(x: DNDarray, q, axis=None, out=None,
                  interpolation: str = "linear",
                  keepdims: bool = False) -> DNDarray:
    """q-th percentile ignoring NaNs (``numpy.nanpercentile``).

    ``axis=None`` compresses the NaNs out (distributed boolean selection,
    stays split) and runs the exact distributed percentile; an ``axis``
    reduction first reshards so the reduced axis is device-local (one
    all-to-all, no gather), then applies the per-slice NaN-aware order
    statistic locally."""
    if not types.heat_type_is_inexact(x.dtype):
        return percentile(x, q, axis=axis, out=out,
                          interpolation=interpolation, keepdims=keepdims)
    from . import logical, manipulations

    if axis is None:
        flat = manipulations.flatten(x)
        kept = flat[logical.logical_not(logical.isnan(flat))]
        res = percentile(kept, q, axis=None, interpolation=interpolation)
        if keepdims:
            res = res.reshape(tuple(np.shape(q)) + (1,) * x.ndim)
        return _operations._finalize(res, out)
    axis_s = sanitize_axis(x.shape, axis)
    ftype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    qa = jnp.asarray(q, dtype=ftype)
    distributed = x.split is not None and x.comm.size > 1
    if distributed and x.split == axis_s:
        # move the split off the reduced axis (one reshard, gather-free)
        x = x.resplit((axis_s + 1) % x.ndim) if x.ndim > 1 else x.resplit(None)
        distributed = x.split is not None

    def _nanpct(arr):
        # jnp.nanpercentile rejects q of rank > 1; flatten and restore
        r = jnp.nanpercentile(arr.astype(ftype), qa.reshape(-1),
                              axis=axis_s, method=interpolation,
                              keepdims=keepdims)
        if qa.ndim != 1:
            r = r.reshape(tuple(qa.shape) + r.shape[1:])
        return r

    q_ndim = np.ndim(q)
    if keepdims:
        gshape = tuple(np.shape(q)) + tuple(
            1 if i == axis_s else s for i, s in enumerate(x.shape))
    else:
        gshape = tuple(np.shape(q)) + tuple(
            s for i, s in enumerate(x.shape) if i != axis_s)
    if not distributed:
        # single shard / replicated: operate on the logical view and keep
        # the result replicated (percentile's local route, split=None)
        res = _nanpct(x._logical())
        result = DNDarray.from_logical(res, None, x.device, x.comm)
        return _operations._finalize(result, out)
    # per-shard local reduction along a non-split axis
    res = _nanpct(x.larray)
    out_split = (x.split + q_ndim if keepdims
                 else (x.split - (1 if axis_s < x.split else 0)) + q_ndim)
    result = DNDarray(res, gshape, types.canonical_heat_type(res.dtype),
                      out_split, x.device, x.comm)
    return _operations._finalize(result, out)


def nanmedian(x: DNDarray, axis=None, keepdims: bool = False) -> DNDarray:
    """Median ignoring NaNs (``numpy.nanmedian``)."""
    return nanpercentile(x, 50.0, axis=axis, keepdims=keepdims)


def _q01_to_percent(q):
    """Validate quantile inputs on [0, 1] (NaN fails) and rescale to the
    percentile range."""
    qn = np.asarray(q, dtype=np.float64)
    if qn.size and not bool((qn >= 0).all() and (qn <= 1).all()):
        raise ValueError("Quantiles must be in the range [0, 1]")
    return qn * 100.0


def nanquantile(x: DNDarray, q, axis=None, out=None,
                interpolation: str = "linear",
                keepdims: bool = False) -> DNDarray:
    """q-th quantile (``q`` in [0, 1]) ignoring NaNs (``numpy.nanquantile``)."""
    return nanpercentile(x, _q01_to_percent(q), axis=axis, out=out,
                         interpolation=interpolation, keepdims=keepdims)


def quantile(x: DNDarray, q, axis=None, out=None,
             interpolation: str = "linear", keepdims: bool = False) -> DNDarray:
    """q-th quantile, ``q`` in [0, 1] (``numpy.quantile``) — the [0, 100]
    scale of :func:`percentile`."""
    return percentile(x, _q01_to_percent(q), axis=axis, out=out,
                      interpolation=interpolation, keepdims=keepdims)


def ptp(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Peak-to-peak range, ``max - min`` (``numpy.ptp``)."""
    result = arithmetics.sub(max(x, axis=axis, keepdims=keepdims),
                             min(x, axis=axis, keepdims=keepdims))
    return _operations._finalize(result, out)


def corrcoef(m: DNDarray, y=None, rowvar: bool = True) -> DNDarray:
    """Pearson correlation coefficients (``numpy.corrcoef``) from
    :func:`cov`: ``C[i,j] / sqrt(C[i,i] * C[j,j])``, clipped to [-1, 1]."""
    from . import exponential, manipulations

    c = cov(m, y, rowvar=rowvar)
    if c.ndim == 0:
        from . import factories

        return factories.array(1.0, dtype=c.dtype, comm=m.comm)
    d = exponential.sqrt(manipulations.diag(c))
    outer_d = arithmetics.mul(d.reshape((d.shape[0], 1)),
                              d.reshape((1, d.shape[0])))
    return arithmetics.div(c, outer_d).clip(-1.0, 1.0)


def searchsorted(a: DNDarray, v, side: str = "left", sorter=None) -> DNDarray:
    """Insertion indices into a sorted 1-D array (``numpy.searchsorted``).
    The sorted ``a`` replicates (it is the boundary set, like
    :func:`bucketize`'s boundaries); ``v`` may stay split."""
    if side not in ("left", "right"):
        raise ValueError(f"side must be 'left' or 'right', got {side!r}")
    if sorter is not None:
        raise NotImplementedError("searchsorted: sorter is not supported")
    from . import factories

    av = a._logical() if isinstance(a, DNDarray) else jnp.asarray(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v, comm=a.comm if isinstance(a, DNDarray) else None)
    return _operations._local_op(
        lambda t: jnp.searchsorted(av, t, side=side).astype(jnp.int64), v)


def trapz(y: DNDarray, x=None, dx: float = 1.0, axis: int = -1) -> DNDarray:
    """Trapezoidal integration (``numpy.trapz``): built from distributed
    slicing + one reduction, gather-free on split arrays."""
    axis = sanitize_axis(y.shape, axis)
    n = y.shape[axis]
    if n < 2:
        # numpy integrates a single sample to 0 (nothing to accumulate)
        from . import factories

        gshape = tuple(sz for i, sz in enumerate(y.shape) if i != axis)
        return factories.zeros(gshape, dtype=y.dtype, comm=y.comm)
    sl_lo = tuple(slice(None, -1) if i == axis else slice(None)
                  for i in range(y.ndim))
    sl_hi = tuple(slice(1, None) if i == axis else slice(None)
                  for i in range(y.ndim))
    pair_sum = arithmetics.add(y[sl_hi], y[sl_lo])
    if x is None:
        return arithmetics.mul(arithmetics.sum(pair_sum, axis=axis),
                               0.5 * float(dx))
    xs = x if isinstance(x, DNDarray) else None
    xv = xs._logical() if xs is not None else jnp.asarray(x)
    if xv.ndim == 1:
        d = jnp.diff(xv)
        shape = [1] * y.ndim
        shape[axis] = d.shape[0]
        d = d.reshape(shape)
    else:
        raise NotImplementedError("trapz: only 1-D sample positions")
    from . import factories

    dd = factories.array(np.asarray(d), comm=y.comm)
    return arithmetics.mul(arithmetics.sum(
        arithmetics.mul(pair_sum, dd), axis=axis), 0.5)


def gradient(f: DNDarray, *varargs, axis=None, edge_order: int = 1):
    """Numerical gradient (``numpy.gradient``): central differences in the
    interior, one-sided at the edges — distributed slicing + concatenate
    (the split-axis case rides the O(1) ppermute window fetch).

    Unit or scalar spacing only; returns a list for multiple axes like
    NumPy."""
    if edge_order != 1:
        raise NotImplementedError("gradient: only edge_order=1")
    if len(varargs) > 1:
        raise NotImplementedError("gradient: per-axis spacing arrays are "
                                  "not supported (scalar spacing only)")
    h = float(varargs[0]) if varargs else 1.0
    axes = (tuple(range(f.ndim)) if axis is None
            else ((axis,) if isinstance(axis, int) else tuple(axis)))
    axes = tuple(sanitize_axis(f.shape, a) for a in axes)
    from . import manipulations

    outs = []
    for ax in axes:
        if f.shape[ax] < 2:
            raise ValueError("gradient requires at least 2 points per axis")

        def sl(a, b):
            return tuple(slice(a, b) if i == ax else slice(None)
                         for i in range(f.ndim))

        interior = arithmetics.div(
            arithmetics.sub(f[sl(2, None)], f[sl(None, -2)]), 2.0 * h)
        first = arithmetics.div(
            arithmetics.sub(f[sl(1, 2)], f[sl(0, 1)]), h)
        last = arithmetics.div(
            arithmetics.sub(f[sl(-1, None)], f[sl(-2, -1)]), h)
        outs.append(manipulations.concatenate([first, interior, last],
                                              axis=ax))
    return outs[0] if len(axes) == 1 else outs


def interp(x: DNDarray, xp, fp, left=None, right=None) -> DNDarray:
    """1-D linear interpolation (``numpy.interp``): the sample table
    ``(xp, fp)`` replicates (it is a lookup table); ``x`` stays split."""
    xpv = xp._logical() if isinstance(xp, DNDarray) else jnp.asarray(xp)
    fpv = fp._logical() if isinstance(fp, DNDarray) else jnp.asarray(fp)
    from . import factories

    if not isinstance(x, DNDarray):
        x = factories.array(x)
    return _operations._local_op(
        lambda t: jnp.interp(t, xpv, fpv,
                             left=left, right=right), x)


def percentile(x: DNDarray, q, axis=None, out=None, interpolation: str = "linear", keepdims: bool = False, keepdim=None) -> DNDarray:
    """q-th percentile (reference ``statistics.py:1256``).

    Order statistics by sort-then-select: when the reduction crosses the
    split axis, the distributed block merge-split sort
    (:mod:`heat_tpu.core._sort`) orders the data over the mesh — no
    full-array gather, matching the reference's distributed percentile —
    and the (static) order-statistic positions are then sliced out and
    interpolated. A reduction along a non-split axis stays local on the
    physical shards. NaN lanes propagate to NaN results (numpy parity).
    """
    if keepdim is not None:  # reference/torch keyword name
        keepdims = keepdim
    q_np = np.asarray(q, dtype=np.float64)
    if q_np.size and not bool((q_np >= 0).all() and (q_np <= 100).all()):
        # NaN q fails both comparisons -> raises, matching numpy
        raise ValueError("Percentiles must be in the range [0, 100]")
    if interpolation not in ("linear", "lower", "higher", "nearest", "midpoint"):
        raise ValueError(f"unknown interpolation method {interpolation!r}")
    axis_s = sanitize_axis(x.shape, axis)
    ftype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    distributed = x.split is not None and x.comm.size > 1
    if distributed and (axis_s is None or axis_s == x.split):
        return _percentile_distributed(x, q, axis_s, out, interpolation,
                                       keepdims, ftype)
    if distributed:
        # reduction axis is not the split axis: purely local per shard;
        # padding rows produce garbage that stays in the invalid region
        qa = jnp.asarray(q, dtype=ftype)
        res = jnp.percentile(x.larray.astype(ftype), qa, axis=axis_s,
                             method=interpolation, keepdims=keepdims)
        q_ndim = np.ndim(q)
        split = x.split + q_ndim if keepdims else (
            x.split - (1 if axis_s < x.split else 0) + q_ndim)
        gshape = list(x.shape)
        if keepdims:
            gshape[axis_s] = 1
        else:
            del gshape[axis_s]
        gshape = tuple(np.shape(q)) + tuple(gshape)
        result = DNDarray(res, gshape, types.canonical_heat_type(res.dtype),
                          split, x.device, x.comm)
        return _operations._finalize(result, out)
    logical = x._logical()
    qa = jnp.asarray(q, dtype=ftype)
    # jnp.percentile rejects q with rank > 1; flatten and restore (the
    # distributed path supports N-D q natively)
    res = jnp.percentile(logical.astype(ftype), qa.reshape(-1),
                         axis=axis_s, method=interpolation, keepdims=keepdims)
    if qa.ndim != 1:
        res = res.reshape(tuple(qa.shape) + res.shape[1:])
    result = DNDarray.from_logical(res, None, x.device, x.comm)
    return _operations._finalize(result, out)


def _percentile_distributed(x: DNDarray, q, axis_s, out, interpolation,
                            keepdims, ftype) -> DNDarray:
    """Sort-then-select percentile crossing the split axis."""
    from ._sort import distributed_flat_sort_fn, distributed_sort_fn

    comm = x.comm
    jdt = jnp.dtype(x.larray.dtype)
    floating = jnp.issubdtype(jdt, jnp.floating)
    if axis_s is None:
        n = int(np.prod(x.shape, dtype=np.int64))
        # data-engine route: ONE bisection-count program returns exactly
        # the order statistics the picks below need (zero all-gather) —
        # same elements the sort path would select, bit-exact; None under
        # the HEAT_TPU_DATA_ENGINE=0 escape hatch or a non-translatable
        # dtype/layout, which keeps the merge-split sort path
        take = None
        if n > 0:
            from ..data import ops as _data_ops

            take = _data_ops.order_stat_take(
                x, n, np.asarray(q, dtype=np.float64).reshape(-1),
                interpolation, floating)
        if take is None:
            # floats: NaN-fill the padding — NaNs (data and padding
            # alike) sort last, so the first n sorted positions are
            # exactly the data multiset even when it contains NaN or +inf
            sent = jnp.asarray(jnp.nan, jdt) if floating else \
                _min_neutral(x)
            fn = distributed_flat_sort_fn(
                x.larray.shape, jdt, x.split, comm)
            sorted_phys = fn(x.filled(sent))

            def take(i):
                return sorted_phys[i]
    else:
        n = x.shape[axis_s]
        fn = distributed_sort_fn(
            x.larray.shape, jdt, axis_s, n, False, comm)
        sorted_phys, _ = fn(x.larray)

        def take(i):
            return jnp.take(sorted_phys, i, axis=axis_s)

    q_arr = np.asarray(q, dtype=np.float64).reshape(-1)
    picks = []
    for qv in q_arr:
        f = (n - 1) * float(qv) / 100.0
        lo, hi = int(np.floor(f)), int(np.ceil(f))
        w = f - lo
        if interpolation == "lower":
            r = take(lo).astype(ftype)
        elif interpolation == "higher":
            r = take(hi).astype(ftype)
        elif interpolation == "nearest":
            r = take(int(np.round(f))).astype(ftype)
        elif interpolation == "midpoint":
            r = (take(lo).astype(ftype) + take(hi).astype(ftype)) / 2
        else:  # linear
            a = take(lo).astype(ftype)
            r = a if hi == lo else a + (take(hi).astype(ftype) - a) * ftype(w)
        picks.append(r)
    if floating:
        # numpy parity: any NaN in a lane poisons that lane's percentile.
        # NaNs sort to the end of the valid region, so the last valid
        # element is NaN iff the lane contains one.
        last = take(n - 1)
        picks = [jnp.where(jnp.isnan(last), jnp.asarray(jnp.nan, ftype), r)
                 for r in picks]
    res = picks[0] if np.ndim(q) == 0 else jnp.stack(picks)
    if np.ndim(q) > 1:
        res = res.reshape(tuple(np.shape(q)) + res.shape[1:])
    if keepdims and axis_s is not None:
        res = jnp.expand_dims(res, axis_s + np.ndim(q))
    elif keepdims:
        res = res.reshape(tuple(np.shape(q)) + (1,) * x.ndim)
    result = DNDarray.from_logical(res, None, x.device, x.comm)
    return _operations._finalize(result, out)


def std(x: DNDarray, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    """Standard deviation (reference ``statistics.py:1850``)."""
    from . import exponential

    return exponential.sqrt(var(x, axis, ddof=ddof, **kwargs))


def _mean_keepdims(x: DNDarray, axis) -> DNDarray:
    """Mean with the reduced axes kept as size-1 — a *recorded* reduction
    (keepdims sum + scalar div) instead of the eager sum → ``reshape``
    round-trip, so var/std/skew/kurtosis stay on ONE fusion tape and both
    of their reductions compile into a single program with a grouped
    collective. Values are identical to ``mean(x, axis).reshape(...)``
    (same sum, same divisor, no data motion)."""
    if axis is None:
        return mean(x, None)
    n = int(np.prod([x.shape[a] for a in _axes(x, axis)]))
    s = arithmetics.sum(x, axis=axis, keepdims=True)
    return arithmetics.div(s, float(n) if n else 1.0)


def var(x: DNDarray, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    """Variance (reference ``statistics.py:1979``): two-pass masked global
    moments instead of per-rank moment merging. Both passes record onto
    the fusion tape, so ``ht.var(x)`` materializes as one program."""
    if not isinstance(ddof, int):
        raise ValueError(f"ddof must be integer, is {type(ddof)}")
    if ddof < 0:
        raise ValueError("Expected ddof >= 0")
    # heat compatibility: bessel kwarg
    if kwargs.get("bessel") is True:
        ddof = 1
    mu = _mean_keepdims(x, axis)
    centered = arithmetics.sub(x, mu)
    sq = _operations._local_op(jnp.square, centered)
    s = arithmetics.sum(sq, axis=axis)
    n = x.size if axis is None else int(np.prod([x.shape[a] for a in _axes(x, axis)]))
    denom = n - ddof
    if denom <= 0:
        # NumPy semantics: degrees of freedom <= 0 yields NaN, not 0
        return _operations._local_op(lambda v: v * jnp.asarray(float("nan"), v.dtype), s)
    return arithmetics.div(s, float(denom))
