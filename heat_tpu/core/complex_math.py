"""Complex-number operations (reference ``heat/core/complex_math.py:18-110``)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = ["angle", "conj", "conjugate", "imag", "real"]


def angle(x: DNDarray, deg: bool = False, out=None) -> DNDarray:
    """Element-wise argument of a complex number (reference ``complex_math.py:18``)."""
    return _operations._local_op(jnp.angle, x, out, deg=deg)


def conjugate(x: DNDarray, out=None) -> DNDarray:
    """Element-wise complex conjugate (reference ``:50``)."""
    return _operations._local_op(jnp.conjugate, x, out)


conj = conjugate


def imag(x: DNDarray) -> DNDarray:
    """Imaginary part (reference ``:78``)."""
    return _operations._local_op(jnp.imag, x)


def real(x: DNDarray) -> DNDarray:
    """Real part (reference ``:94``)."""
    if types.heat_type_is_complexfloating(x.dtype):
        return _operations._local_op(jnp.real, x)
    return x
