"""Distributed Gauss-Jordan inverse and determinant.

TPU-native counterpart of the reference's distributed Gauss-Jordan
(``heat/core/linalg/basics.py:312`` inv, ``:160`` det, both row-wise loops
of Bcast + local elimination). One jitted shard_map program over the
row-split augmented matrix ``[A | I]``: a ``lax.fori_loop`` over the ``n``
pivot columns where each step

1. finds the global partial pivot with two scalar ``pmax`` reductions,
2. broadcasts the pivot row and row ``k`` with two masked ``psum``s
   (O(n) floats each — the reference's ``Bcast`` of the pivot row),
3. swaps, normalizes, and eliminates locally (VPU row ops).

O(n^2 / p) memory per device — a matrix larger than one device's HBM
inverts without ever being materialized — and O(n^2) total communication,
matching the reference's algorithm. Determinant falls out of the same
elimination as ``sign * prod(pivots)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from .._compat import shard_map

from .._sort import _index_dtype

__all__ = ["gauss_jordan_fn"]

_GJ_CACHE: dict = {}


def gauss_jordan_fn(phys_shape, jdt, n: int, comm):
    """Jitted ``A_physical(split=0) -> (inv_physical(split=0), det,
    logabsdet, sign)`` — the last two are the slogdet pair.

    Singular inputs: the INVERSE carries inf/nan (the IEEE outcome of a
    zero pivot, mirroring ``jnp.linalg.inv``'s non-raising semantics under
    jit), while det/logabsdet/sign latch to numpy's ``0 / -inf / 0`` at
    the first zero pivot instead of riding the poisoned elimination tail.
    """
    key = ("gj", tuple(phys_shape), str(jdt), n, comm.cache_key)
    fn = _GJ_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c = phys_shape[0] // p
    idt = _index_dtype()
    rdt = jnp.finfo(jdt).dtype if jnp.issubdtype(jdt, jnp.complexfloating) \
        else jdt

    def body(ab):
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c, dtype=idt)
        eye = (gpos[:, None] == jnp.arange(n, dtype=idt)[None, :]).astype(jdt)
        mat = jnp.concatenate([ab, eye], axis=1)  # (c, 2n)

        def step(k, carry):
            mat, det, sign, logabs, sgn, singular = carry
            col = jax.lax.dynamic_slice_in_dim(mat, k, 1, axis=1)[:, 0]
            valid = (gpos >= k) & (gpos < n)
            cand = jnp.where(valid, jnp.abs(col).astype(rdt),
                             jnp.asarray(-jnp.inf, rdt))
            loc_i = jnp.argmax(cand)
            loc_v = cand[loc_i]
            loc_g = gpos[loc_i]
            gmax = jax.lax.pmax(loc_v, comm.axis_name)
            piv_g = jax.lax.pmax(
                jnp.where(loc_v == gmax, loc_g, jnp.asarray(-1, idt)),
                comm.axis_name)
            prow = jax.lax.psum(
                jnp.where((gpos == piv_g)[:, None], mat, 0).sum(0),
                comm.axis_name)
            krow = jax.lax.psum(
                jnp.where((gpos == k)[:, None], mat, 0).sum(0),
                comm.axis_name)
            # swap rows k and piv_g (no-op when they coincide)
            mat = jnp.where((gpos == k)[:, None], prow[None, :], mat)
            mat = jnp.where((gpos == piv_g)[:, None] & (piv_g != k),
                            krow[None, :], mat)
            piv = prow[k]
            det = det * piv
            sign = jnp.where(piv_g != k, -sign, sign)
            # stable log-determinant accumulators (slogdet): log|piv| sums
            # where the raw product would over/underflow; unit-modulus
            # pivot signs multiply (complex-safe). A zero pivot means the
            # matrix is singular — latch the flag and stop accumulating,
            # because the elimination continues into inf/NaN territory
            # (the documented IEEE outcome for inv) which would otherwise
            # poison the log-space figures numpy defines as (0, -inf)
            apiv = jnp.abs(piv).astype(rdt)
            singular = singular | ~(apiv > 0)  # catches 0 AND NaN pivots
            logabs = jnp.where(singular, logabs,
                               logabs + jnp.log(apiv))
            sgn = jnp.where(singular, sgn,
                            sgn * piv / jnp.where(
                                apiv > 0, apiv, jnp.ones((), rdt)
                            ).astype(jdt))
            prow_n = prow / piv
            colk = jax.lax.dynamic_slice_in_dim(mat, k, 1, axis=1)[:, 0]
            is_k = (gpos == k)[:, None]
            mat = jnp.where(is_k, prow_n[None, :],
                            mat - colk[:, None] * prow_n[None, :])
            return mat, det, sign, logabs, sgn, singular

        mat, det, sign, logabs, sgn, singular = jax.lax.fori_loop(
            0, n, step,
            (mat, jnp.ones((), jdt), jnp.ones((), jdt),
             jnp.zeros((), rdt), jnp.ones((), jdt),
             jnp.zeros((), jnp.bool_)))
        det_out = jnp.where(singular, jnp.zeros((), jdt), det * sign)
        logabs_out = jnp.where(singular, jnp.asarray(-jnp.inf, rdt), logabs)
        sgn_out = jnp.where(singular, jnp.zeros((), jdt), sgn * sign)
        return mat[:, n:], det_out, logabs_out, sgn_out

    spec = comm.spec(2, 0)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=spec,
                  out_specs=(spec, comm.spec(0, None), comm.spec(0, None),
                             comm.spec(0, None)), check_vma=False)
    )
    _GJ_CACHE[key] = fn
    return fn
