"""Core linear algebra (reference ``heat/core/linalg/basics.py``).

``matmul`` is the flagship: the reference implements a ~670-line block-cyclic
distributed GEMM with hand-scheduled Bcasts for every (split, split)
combination (``basics.py:424-1095``). On TPU the same cases collapse to a
zero-filled ``jnp.matmul`` on the canonical physical arrays — GSPMD
partitions the contraction onto the MXU and inserts the collective schedule
(all-gather / psum over ICI). The padding rules per case are documented
inline; correctness relies on zero-filled padding contributing nothing to
contractions.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import arithmetics, statistics, types
from ..dndarray import DNDarray
from ..stride_tricks import broadcast_shape, sanitize_axis

__all__ = [
    "cross",
    "cond",
    "det",
    "slogdet",
    "kron",
    "tensordot",
    "dot",
    "inv",
    "matmul",
    "matrix_norm",
    "norm",
    "outer",
    "projection",
    "trace",
    "transpose",
    "tril",
    "triu",
    "vdot",
    "vecdot",
    "vector_norm",
    "einsum",
]


def _filled0(x: DNDarray):
    """Physical array with zero-filled padding (safe for contractions).

    Fast path: a buffer already canonically zero-padded
    (``DNDarray.pad_is_zero`` — factory, ``from_logical`` and planner
    outputs all guarantee it) skips the re-zero entirely. Otherwise the
    select runs ONCE per buffer: the zero-filled result is written back,
    so repeat GEMMs on the same array stop paying the masking pass.
    ``op_engine.zero_fills`` counts the payers."""
    if not x.pad or x.pad_is_zero:
        return x.larray
    return x._write_back_zero_fill()


def matmul(a: DNDarray, b: DNDarray, allow_resplit: bool = False) -> DNDarray:
    """Distributed matrix product (reference ``basics.py:424``).

    Split-combination handling (reference's case tower ``:513-1094``):

    * ``a.split=0``  → output rows sharded (``split=0``); padded rows are
      zero-filled and land in the output padding.
    * ``b.split=1``  → output cols sharded (``split=1``).
    * ``a.split=1`` with ``b.split=0`` → the *contracted* dimension is
      sharded on both sides; zero-filled padding makes the shard-local
      partial products exact, and XLA reduces them with a ``psum``
      (the reference's block-cyclic Bcast loop).
    * replicated cases are plain local GEMMs.
    """
    if not isinstance(a, DNDarray) or not isinstance(b, DNDarray):
        raise TypeError("both operands must be DNDarrays")
    if a.ndim == 1 and b.ndim == 1:
        return dot(a, b)
    if a.ndim == 1:
        # NumPy semantics: prepend a 1-axis, contract, drop axis -2 (works
        # for batched b too, where the result keeps b's batch dims)
        from .. import manipulations

        res = matmul(a.reshape((1, a.shape[0])), b)
        return manipulations.squeeze(res, axis=-2)
    if b.ndim == 1:
        from .. import manipulations

        res = matmul(a, b.reshape((b.shape[0], 1)))
        return manipulations.squeeze(res, axis=-1)
    if a.ndim != 2 or b.ndim != 2:
        return _matmul_batched(a, b)
    n, ka = a.shape
    kb, m = b.shape
    if ka != kb:
        raise ValueError(f"matmul shape mismatch: {a.shape} @ {b.shape}")

    # record a CONTRACT node instead of dispatching: the zero-fill masks,
    # the GEMM and its epilogue fuse into ONE program at the next
    # materialization point, with the per-split-case collective plan
    # explicit in the shard_map translation (core/fusion.py)
    from .. import fusion

    lazy = fusion.record_contract(a, b)
    if lazy is not None:
        return lazy

    f_a = _filled0(a)
    f_b = _filled0(b)
    # align the contracted dimension physically (pad the unsharded side with
    # zero rows/cols to match the sharded side's padded extent)
    if f_a.shape[1] != f_b.shape[0]:
        if f_a.shape[1] < f_b.shape[0]:
            f_a = jnp.pad(f_a, ((0, 0), (0, f_b.shape[0] - f_a.shape[1])))
        else:
            f_b = jnp.pad(f_b, ((0, f_a.shape[1] - f_b.shape[0]), (0, 0)))

    res = jnp.matmul(f_a, f_b)

    if a.split == 0:
        out_split = 0
        if b.split == 1 and res.shape[1] != m:
            res = res[:, :m]  # only one axis may carry canonical padding
    elif b.split == 1:
        out_split = 1
        if res.shape[0] != n:
            res = res[:n, :]
    else:
        out_split = None
        if res.shape != (n, m):
            res = res[:n, :m]

    dtype = types.canonical_heat_type(res.dtype)
    # the output's padding is NOT claimed zero: padded rows/cols are the
    # zero-filled operand's padding pushed through the contraction, and
    # 0 * inf = NaN — a non-finite operand value poisons the padding even
    # though the logical result is exact. Later consumers pay at most one
    # ``filled(0)`` select per buffer (the _filled0 write-back).
    return DNDarray(res, (n, m), dtype, out_split, a.device, a.comm)


def _matmul_batched(a: DNDarray, b: DNDarray) -> DNDarray:
    """Batched matmul (beyond the reference's 2-D-only ``basics.py:424``):
    contract the last two dims with NumPy broadcasting over batch dims.

    A batch-axis split that maps onto the output runs on shard-local
    physical blocks: the previous path all-gathered BOTH operands to full
    logical size (``_logical``) on every call even when the batch split
    survived verbatim — a replication leak proportional to the model size
    per GEMM. Batch padding never enters the contraction (matmul reads
    only the last two dims), so garbage padding stays in output padding.
    Non-mappable layouts still gather (GSPMD shards the contraction from
    the operands' shardings); every unavoidable gather of a sharded
    operand is counted in ``op_engine.align_resplits``.
    """
    from .._operations import _count_align_resplit

    out_batch = broadcast_shape(a.shape[:-2], b.shape[:-2])
    out_shape = tuple(out_batch) + (a.shape[-2], b.shape[-1])
    ndim_out = len(out_shape)
    split = None
    primary = None
    for op in (a, b):
        if op.split is not None and op.split < op.ndim - 2:
            mapped = op.split + (ndim_out - op.ndim)
            if op.shape[op.split] == out_shape[mapped]:
                split, primary = mapped, op
                break
    if primary is None or 0 in out_shape:
        # no batch split survives (gathering IS the semantics here), or
        # the result is empty — block math degenerates but the mapped
        # split, when one exists, stays on the metadata
        if primary is None:
            for op in (a, b):
                if op.split is not None and op.size > 0:
                    _count_align_resplit()
        res = jnp.matmul(a._logical(), b._logical())
        return DNDarray.from_logical(res, split, a.device, a.comm)

    comm = a.comm
    phys = []
    for op in (a, b):
        if op is primary:
            phys.append(op.larray)
            continue
        ax = split - (ndim_out - op.ndim)
        if op.split is not None:
            if op.split == ax and op.shape[op.split] == out_shape[split]:
                phys.append(op.larray)  # same canonical batch layout
                continue
            _count_align_resplit()
            op = op.resplit(None)
        p = op.larray
        if ax >= 0 and op.shape[ax] == out_shape[split]:
            # align the replicated operand's batch extent onto the padded
            # physical extent (content is don't-care, zeros are cheapest)
            padn = comm.padded_size(out_shape[split]) - p.shape[ax]
            if padn > 0:
                cfg = [(0, padn if i == ax else 0) for i in range(p.ndim)]
                p = jnp.pad(p, cfg)
        phys.append(p)
    res = jnp.matmul(phys[0], phys[1])
    dtype = types.canonical_heat_type(res.dtype)
    return DNDarray(res, out_shape, dtype, split, a.device, comm)


def cross(a: DNDarray, b: DNDarray, axisa: int = -1, axisb: int = -1, axisc: int = -1, axis: int = -1) -> DNDarray:
    """Vector cross product (reference ``basics.py:60``).

    ``axis`` overrides ``axisa``/``axisb``/``axisc`` exactly as in the
    reference (``basics.py:97-100``). The product is elementwise across the
    batch dims, so matching split operands with the vector axis unsharded
    compute shard-locally (where the reference *raises* for split == axisa,
    ``basics.py:105``); mismatched layouts fall back to the logical path."""
    if axis != -1:
        # explicit axis overrides the per-operand axes (reference
        # ``basics.py:97-100``); keep it RELATIVE — jnp.cross resolves it
        # against each operand, so different-ndim operands still broadcast
        # (review findings, twice)
        axisa = axisb = axisc = axis
    va = sanitize_axis(a.shape, axisa)
    if (
        a.split is not None
        and a.split == b.split
        and a.gshape == b.gshape
        and a.larray.shape == b.larray.shape
        and va == sanitize_axis(b.shape, axisb) == sanitize_axis(a.shape, axisc)
        and a.split != va
        and a.shape[va] == 3  # 3-vectors keep the axis: shape is preserved
    ):
        res = jnp.cross(a.larray, b.larray, axisa=axisa, axisb=axisb,
                        axisc=axisc)
        return DNDarray(
            res, a.gshape, types.canonical_heat_type(res.dtype),
            a.split, a.device, a.comm)
    res = jnp.cross(a._logical(), b._logical(), axisa=axisa, axisb=axisb, axisc=axisc)
    return DNDarray.from_logical(res, a.split, a.device, a.comm)


def _gauss_jordan_path(a: DNDarray):
    """The distributed Gauss-Jordan program for ``a`` when applicable
    (2-D float/complex split matrix on a real mesh), else None. ``split=1``
    routes through the transpose identities ``inv(A) = inv(A^T)^T`` and
    ``det(A) = det(A^T)`` — transpose is a local permute + split remap."""
    if (
        a.ndim != 2
        or a.split is None
        or a.comm.size == 1
        or a.shape[0] == 0
        or not jnp.issubdtype(a.larray.dtype, jnp.inexact)
    ):
        return None
    from ._gauss import gauss_jordan_fn

    src = transpose(a) if a.split == 1 else a
    return gauss_jordan_fn(
        src.larray.shape, jnp.dtype(src.larray.dtype), src.shape[0], src.comm
    ), src


def det(a: DNDarray) -> DNDarray:
    """Determinant (reference ``basics.py:160``): split matrices run the
    distributed Gauss-Jordan elimination (``sign * prod(pivots)`` of the
    same one-program loop as :func:`inv`, :mod:`._gauss`); replicated ones
    use XLA's fused LU."""
    _square_check(a)
    gj = _gauss_jordan_path(a)
    if gj is not None:
        fn, src = gj
        _, d, _, _ = fn(src.larray)
        return DNDarray.from_logical(d, None, a.device, a.comm, dtype=a.dtype)
    res = jnp.linalg.det(a._logical())
    return DNDarray.from_logical(res, None, a.device, a.comm)


def _square_check(a):
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise ValueError(f"expected square matrix, got {a.shape}")


def slogdet(a: DNDarray):
    """``(sign, logabsdet)`` — the overflow-stable determinant (beyond the
    reference's linalg set; numpy-parity semantics). Split matrices reuse
    the distributed Gauss-Jordan loop, which accumulates ``log|pivot|``
    sums and unit-modulus pivot signs alongside the raw product."""
    _square_check(a)
    gj = _gauss_jordan_path(a)
    if gj is not None:
        fn, src = gj
        _, _, logabs, sgn = fn(src.larray)
        return (DNDarray.from_logical(sgn, None, a.device, a.comm),
                DNDarray.from_logical(logabs, None, a.device, a.comm))
    sign, logabs = jnp.linalg.slogdet(a._logical())
    return (DNDarray.from_logical(sign, None, a.device, a.comm),
            DNDarray.from_logical(logabs, None, a.device, a.comm))


def dot(a: DNDarray, b: DNDarray, out=None) -> DNDarray:
    """Dot product (reference ``basics.py:270``)."""
    if a.ndim == 1 and b.ndim == 1:
        prod = arithmetics.mul(a, b)
        result = arithmetics.sum(prod)
        if out is not None:
            out.larray = result.larray
            return out
        return result
    if a.ndim == 2 and b.ndim == 2:
        result = matmul(a, b)
        if out is not None:
            out.larray = result.larray
            return out
        return result
    raise NotImplementedError("ht.dot supports 1-D · 1-D and 2-D @ 2-D")


def inv(a: DNDarray) -> DNDarray:
    """Matrix inverse (reference ``basics.py:312``): split matrices run the
    distributed Gauss-Jordan over the row-split augmented ``[A | I]``
    (:mod:`._gauss`) — O(n^2/p) memory per device, the matrix is never
    materialized on one device. Replicated matrices use XLA's fused LU."""
    _square_check(a)
    gj = _gauss_jordan_path(a)
    if gj is not None:
        fn, src = gj
        invp, _, _, _ = fn(src.larray)
        out = DNDarray(invp, src.gshape, src.dtype, 0, a.device, a.comm)
        return transpose(out) if a.split == 1 else out
    res = jnp.linalg.inv(a._logical())
    return DNDarray.from_logical(res, a.split, a.device, a.comm)


def matrix_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Matrix norm (reference ``basics.py:1095``)."""
    a = x
    if a.ndim < 2:
        raise ValueError("matrix_norm requires at least a 2-D array")
    if axis is None:
        if a.ndim == 2:
            axis = (0, 1)
        else:
            raise ValueError("axis must be given for >2-D arrays")
    row_axis, col_axis = (sanitize_axis(a.shape, ax) for ax in axis)
    if ord is None or ord == "fro":
        absd = a.abs()  # |x|^2, not x^2 — complex parity
        sq = arithmetics.mul(absd, absd)
        s = arithmetics.sum(sq, axis=(row_axis, col_axis), keepdims=keepdims)
        from .. import exponential

        return exponential.sqrt(s)
    def _abs_sum_then(statfn, sum_ax, red_ax):
        # sum |x| over one of the matrix axes, then max/min over the other
        # — only the two matrix axes reduce (batch dims survive for
        # ndim>2) and keepdims yields numpy's (…, 1, 1) shape
        sums = arithmetics.sum(a.abs(), axis=sum_ax, keepdims=keepdims)
        if not keepdims and red_ax > sum_ax:
            red_ax -= 1
        return statfn(sums, axis=red_ax, keepdims=keepdims)

    if ord == 1:
        return _abs_sum_then(statistics.max, row_axis, col_axis)
    if ord == np.inf:
        return _abs_sum_then(statistics.max, col_axis, row_axis)
    if ord == -1:
        return _abs_sum_then(statistics.min, row_axis, col_axis)
    if ord == -np.inf:
        return _abs_sum_then(statistics.min, col_axis, row_axis)
    if ord in (2, -2, "nuc"):
        # singular-value norms — the reference raises NotImplementedError
        # for all three (``basics.py:1193-1218``); the gather-free SVD
        # makes them one reduction over the replicated spectrum
        if a.ndim != 2:
            raise ValueError("singular-value norms require a 2-D matrix")
        from .svd import svd

        s = svd(a, compute_uv=False)._logical()  # descending
        if ord == "nuc":
            val = jnp.sum(s)
        else:
            val = s[0] if ord == 2 else s[-1]
        if keepdims:
            val = val.reshape((1, 1))
        return DNDarray.from_logical(jnp.asarray(val), None, a.device,
                                     a.comm)
    raise ValueError(f"unsupported matrix norm order {ord}")


def norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector/matrix norm dispatch (reference ``basics.py:1235``)."""
    a = x
    if axis is None and a.ndim <= 1:
        return vector_norm(a, axis=None, keepdims=keepdims, ord=ord)
    if axis is None and ord is None:
        # frobenius over all axes
        absd = a.abs()  # |x|^2, not x^2 — complex parity
        sq = arithmetics.mul(absd, absd)
        from .. import exponential

        return exponential.sqrt(arithmetics.sum(sq))
    if isinstance(axis, (int, np.integer)) or (axis is None and a.ndim == 1):
        return vector_norm(a, axis=axis, keepdims=keepdims, ord=ord)
    return matrix_norm(a, axis=axis, keepdims=keepdims, ord=ord)


def vector_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector norm (reference ``basics.py:1372``)."""
    a = x
    from .. import exponential, logical

    if ord is None or ord == 2:
        absd = a.abs()  # |x|^2, not x^2 — complex parity
        sq = arithmetics.mul(absd, absd)
        return exponential.sqrt(arithmetics.sum(sq, axis=axis, keepdims=keepdims))
    if ord == np.inf:
        return statistics.max(a.abs(), axis=axis, keepdims=keepdims)
    if ord == -np.inf:
        return statistics.min(a.abs(), axis=axis, keepdims=keepdims)
    if ord == 0:
        from .. import _operations

        nz = _operations._local_op(lambda x: (x != 0).astype(x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32), a)
        return arithmetics.sum(nz, axis=axis, keepdims=keepdims)
    if isinstance(ord, (int, float)):
        p = float(ord)
        from .. import _operations

        powed = _operations._local_op(lambda x: jnp.abs(x) ** p, a)
        s = arithmetics.sum(powed, axis=axis, keepdims=keepdims)
        return _operations._local_op(lambda x: x ** (1.0 / p), s)
    raise ValueError(f"unsupported vector norm order {ord}")


def outer(a: DNDarray, b: DNDarray, out=None, split=None) -> DNDarray:
    """Outer product (reference ``basics.py:1372``; ring-shifted there, a
    rank-1 GEMM on the MXU here)."""
    a1 = a.reshape((a.size, 1)) if a.ndim == 1 else a.flatten().reshape((a.size, 1))
    b1 = b.reshape((1, b.size)) if b.ndim == 1 else b.flatten().reshape((1, b.size))
    if split == 1:
        a1 = a1.resplit(None)
        b1 = b1.resplit(1)
    result = matmul(a1, b1)
    if split is not None and result.split != split:
        result = result.resplit(split)
    if out is not None:
        out.larray = result.larray
        return out
    return result


def projection(a: DNDarray, b: DNDarray) -> DNDarray:
    """Projection of ``a`` onto ``b`` (reference ``basics.py:1560``)."""
    if a.ndim != 1 or b.ndim != 1:
        raise RuntimeError(f"projection requires 1-D vectors, got {a.shape}, {b.shape}")
    scale = arithmetics.div(dot(a, b), dot(b, b))
    return arithmetics.mul(scale, b)


def trace(a: DNDarray, offset: int = 0, axis1: int = 0, axis2: int = 1, dtype=None, out=None) -> DNDarray:
    """Sum along diagonals (reference ``basics.py:1629``)."""
    from .. import manipulations

    d = manipulations.diagonal(a, offset=offset, dim1=axis1, dim2=axis2)
    result = arithmetics.sum(d, axis=d.ndim - 1 if d.ndim > 1 else None)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype))
    if a.ndim == 2:
        # scalar result for matrices
        pass
    if out is not None:
        out.larray = result.larray
        return out
    return result


def transpose(a: DNDarray, axes=None) -> DNDarray:
    """Axis permutation (reference ``basics.py:2051``): a local permute of the
    physical array plus split remapping — zero communication, exactly like
    the reference."""
    if not isinstance(a, DNDarray):
        raise TypeError(f"a must be a DNDarray, got {type(a)}")
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    else:
        axes = tuple(sanitize_axis(a.shape, ax) for ax in axes)
        if sorted(axes) != list(range(a.ndim)):
            raise ValueError(f"axes must be a permutation of dimensions, got {axes}")
    res = jnp.transpose(a.larray, axes)
    gshape = tuple(a.shape[ax] for ax in axes)
    out_split = None if a.split is None else axes.index(a.split)
    return DNDarray(res, gshape, a.dtype, out_split, a.device, a.comm)


def _tri_op(a: DNDarray, k: int, op) -> DNDarray:
    """Shared tril/triu machinery (reference ``__tri_op``, ``basics.py:2121``).

    Runs on the physical array: the global (row, col) coordinates of valid
    elements coincide with physical coordinates (padding is trailing), so
    the mask is correct without communication.
    """
    if a.ndim == 1:
        res = op(jnp.broadcast_to(a._logical(), (a.shape[0], a.shape[0])), k=k)
        return DNDarray.from_logical(res, 0 if a.split is not None else None, a.device, a.comm)
    res = op(a.larray, k=k)
    return DNDarray(res, a.gshape, a.dtype, a.split, a.device, a.comm)


def tril(m: DNDarray, k: int = 0) -> DNDarray:
    """Lower-triangular part (reference ``basics.py:2213``)."""
    return _tri_op(m, k, jnp.tril)


def triu(m: DNDarray, k: int = 0) -> DNDarray:
    """Upper-triangular part (reference ``basics.py:2250``)."""
    return _tri_op(m, k, jnp.triu)


def vdot(x1: DNDarray, x2: DNDarray) -> DNDarray:
    """Conjugated dot product (reference ``basics.py:2290``)."""
    from .. import complex_math

    return dot(complex_math.conj(x1).flatten(), x2.flatten())


def vecdot(x1: DNDarray, x2: DNDarray, axis=None, keepdims: bool = False, keepdim=None) -> DNDarray:
    """Vector dot along an axis (reference ``basics.py:2340``)."""
    if keepdim is not None:  # reference/torch keyword name
        keepdims = keepdim
    from .. import complex_math

    m = arithmetics.mul(complex_math.conj(x1), x2)
    if axis is None:
        axis = m.ndim - 1
    return arithmetics.sum(m, axis=axis, keepdims=keepdims)


_LETTERS = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"


def tensordot(a: DNDarray, b: DNDarray, axes=2) -> DNDarray:
    """Tensor contraction over the given axes (beyond the reference's op
    surface): builds the einsum expression and rides the distributed
    :func:`einsum`, so sharded operands stay sharded and contracted split
    axes psum."""
    if not isinstance(a, DNDarray) or not isinstance(b, DNDarray):
        raise TypeError("both operands must be DNDarrays")
    if isinstance(axes, (int, np.integer)):
        k = int(axes)
        ax_a = list(range(a.ndim - k, a.ndim))
        ax_b = list(range(k))
    else:
        ax_a, ax_b = axes
        ax_a = [ax_a] if isinstance(ax_a, (int, np.integer)) else list(ax_a)
        ax_b = [ax_b] if isinstance(ax_b, (int, np.integer)) else list(ax_b)
    ax_a = [sanitize_axis(a.shape, ax) for ax in ax_a]
    ax_b = [sanitize_axis(b.shape, ax) for ax in ax_b]
    if len(ax_a) != len(ax_b):
        raise ValueError("axes lists must have matching lengths")
    if len(set(ax_a)) != len(ax_a) or len(set(ax_b)) != len(ax_b):
        raise ValueError("duplicate contracted axes")  # numpy raises too
    if a.ndim + b.ndim - len(ax_a) > len(_LETTERS):
        raise ValueError("too many dimensions for tensordot")
    it = iter(_LETTERS)
    sa = [""] * a.ndim
    sb = [""] * b.ndim
    for i, j in zip(ax_a, ax_b):
        sa[i] = sb[j] = next(it)
    for i in range(a.ndim):
        if not sa[i]:
            sa[i] = next(it)
    for j in range(b.ndim):
        if not sb[j]:
            sb[j] = next(it)
    out_sub = "".join(sa[i] for i in range(a.ndim) if i not in ax_a) + \
        "".join(sb[j] for j in range(b.ndim) if j not in ax_b)
    return einsum(f"{''.join(sa)},{''.join(sb)}->{out_sub}", a, b)


def kron(a: DNDarray, b: DNDarray) -> DNDarray:
    """Kronecker product for 1-D/2-D operands (beyond the reference's op
    surface): the block structure is one distributed einsum plus one
    distributed reshape, so split operands never materialize."""
    if not isinstance(a, DNDarray) or not isinstance(b, DNDarray):
        raise TypeError("both operands must be DNDarrays")
    if a.ndim > 2 or b.ndim > 2 or a.ndim == 0 or b.ndim == 0:
        res = jnp.kron(a._logical(), b._logical())
        return DNDarray.from_logical(res, None, a.device, a.comm)
    from .. import manipulations

    if a.ndim == 1 and b.ndim == 1:
        prod = einsum("i,j->ij", a, b)
        return manipulations.reshape(prod, (a.shape[0] * b.shape[0],))
    # numpy pads the smaller operand's shape with leading 1s
    a2 = a if a.ndim == 2 else a.reshape((1, a.shape[0]))
    b2 = b if b.ndim == 2 else b.reshape((1, b.shape[0]))
    prod = einsum("ij,kl->ikjl", a2, b2)
    return manipulations.reshape(
        prod, (a2.shape[0] * b2.shape[0], a2.shape[1] * b2.shape[1]))


def cond(x: DNDarray, p=None) -> DNDarray:
    """Condition number (beyond the reference's linalg set). ``p`` of
    None/2/-2 reads the (gather-free) SVD spectrum; other orders compose
    ``norm(x, p) * norm(inv(x), p)`` from the distributed norm and
    Gauss-Jordan inverse."""
    if x.ndim != 2:
        raise ValueError("cond requires a 2-D matrix")
    if p in (None, 2, -2):
        from .svd import svd

        s = svd(x, compute_uv=False)._logical()
        val = s[-1] / s[0] if p == -2 else s[0] / s[-1]
        return DNDarray.from_logical(val, None, x.device, x.comm)
    _square_check(x)
    n1 = matrix_norm(x, ord=p)
    n2 = matrix_norm(inv(x), ord=p)
    return arithmetics.mul(n1, n2)


def einsum(subscripts: str, *operands: DNDarray, out=None) -> DNDarray:
    """Distributed Einstein summation (beyond the reference's op surface;
    the reference composes matmul/transpose/trace by hand,
    ``basics.py:424-2120``).

    Runs ``jnp.einsum`` on the zero-filled physical arrays — padding is
    algebraically safe for sum-of-products expressions (padded positions
    contribute zero to contractions; padded output positions are sliced
    away) — so sharded operands stay sharded and XLA/GSPMD schedules the
    collectives exactly as for :func:`matmul`. The output keeps the split
    of the first output dimension that derives from a split operand
    dimension (contracted-split inputs psum into a replicated output).

    Restrictions: explicit subscripts only (no ``...``), no repeated output
    labels.
    """
    from ..dndarray import DNDarray as _D

    if "..." in subscripts:
        raise NotImplementedError("einsum with ellipsis is not supported")
    if not operands:
        raise ValueError("einsum needs at least one operand")
    if any(not isinstance(op, _D) for op in operands):
        raise TypeError("all operands must be DNDarrays")

    expr = subscripts.replace(" ", "")
    if "->" in expr:
        in_part, out_part = expr.split("->")
    else:
        in_part = expr
        # implicit mode: alphabetically sorted labels that appear exactly once
        from collections import Counter

        counts = Counter(c for c in in_part if c.isalpha())
        out_part = "".join(sorted(c for c, n in counts.items() if n == 1))
    in_specs = in_part.split(",")
    if len(in_specs) != len(operands):
        raise ValueError(
            f"{len(in_specs)} subscript groups for {len(operands)} operands")
    if len(set(out_part)) != len(out_part):
        raise ValueError("repeated output labels are not supported")

    comm = operands[0].comm
    # user shape errors must raise (numpy semantics), not vanish into the
    # split-padding normalization below: validate LOGICAL extents per label
    logical_sizes: dict = {}
    for op, spec in zip(operands, in_specs):
        if len(spec) != op.ndim:
            raise ValueError(
                f"subscript {spec!r} does not match operand ndim {op.ndim}")
        for ax, label in enumerate(spec):
            prev = logical_sizes.setdefault(label, op.gshape[ax])
            if prev != op.gshape[ax]:
                raise ValueError(
                    f"size of label {label!r} does not match between operands "
                    f"({prev} vs {op.gshape[ax]})")

    # output split: first output label whose source operand dimension is split
    out_split = None
    for pos, label in enumerate(out_part):
        for op, spec in zip(operands, in_specs):
            if op.split is not None and op.split < len(spec) and spec[op.split] == label:
                out_split = pos
                break
        if out_split is not None:
            break

    # 2-operand expressions record onto the fusion tape (epilogue fusion,
    # and the filled(0) materialization barrier disappears); ``out=`` and
    # other operand counts stay eager
    if out is None and len(operands) == 2:
        from .. import fusion

        lazy = fusion.record_contract_einsum(
            in_specs, out_part, operands[0], operands[1], out_split)
        if lazy is not None:
            return lazy

    # normalize every label to one physical extent: a label can pair a
    # padded (split) dim with an unpadded one across operands; zero-pad the
    # shorter dims — zeros contribute nothing to sum-of-products terms and
    # padded output positions are sliced away below
    filled = [_filled0(op) for op in operands]
    sizes: dict = {}
    for arr, spec in zip(filled, in_specs):
        for ax, label in enumerate(spec):
            sizes[label] = max(sizes.get(label, 0), arr.shape[ax])
    normed = []
    for arr, spec in zip(filled, in_specs):
        widths = [(0, sizes[l] - arr.shape[ax]) for ax, l in enumerate(spec)]
        normed.append(jnp.pad(arr, widths) if any(w for _, w in widths) else arr)

    res = jnp.einsum(in_part + "->" + out_part, *normed)
    # slice padded output dims back to their logical extents
    logical_shape = []
    for label in out_part:
        for op, spec in zip(operands, in_specs):
            if label in spec:
                logical_shape.append(op.gshape[spec.index(label)])
                break
    res = res[tuple(slice(0, s) for s in logical_shape)]
    result = DNDarray.from_logical(res, out_split, operands[0].device, comm)
    if out is not None:
        from .. import sanitation

        sanitation.sanitize_out(out, tuple(logical_shape), result.split, result.device)
        out.larray = result.resplit(out.split).larray
        return out
    return result
