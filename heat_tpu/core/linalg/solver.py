"""Iterative solvers (reference ``heat/core/linalg/solver.py``).

The Lanczos inner loop — spectral embedding's hot path — rides the
tape-compiled analytics fit-step engine (``fusion.fit_step_call``,
``doc/analytics.md``): for a row-split matrix each iteration is ONE
compiled shard_map executable (matvec → all_gather → Rayleigh coefficient
→ twice-applied classical re-orthogonalization → next norm) with the
Krylov basis, the residual vector and the alpha/beta coefficient buffers
all DONATED, and the iteration index a TRACED scalar so every iteration
shares one program. The legacy per-op DNDarray loop remains the
``HEAT_TPU_FUSION_FIT=0`` escape hatch and the replicated-matrix path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import arithmetics, factories, fusion
from .._compat import shard_map
from ..dndarray import DNDarray
from .basics import matmul, dot, transpose, _square_check


def _square_2d_check(a) -> None:
    """Strictly 2-D square (these solvers document a 2-D contract; the
    batched-last-two-dims _square_check would silently widen it)."""
    if a.ndim != 2:
        raise ValueError(f"expected a 2-D matrix, got {a.ndim}-D")
    _square_check(a)

__all__ = ["cg", "lanczos", "solve", "cholesky", "eigh", "lstsq"]


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients on DNDarray ops (reference ``solver.py:13-67``).

    Every iteration is two distributed matvecs plus psum'd inner products —
    all fused by XLA.
    """
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError("A, b and x0 need to be of type ht.DNDarray")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("c needs to be a 1D vector")

    r = arithmetics.sub(b, matmul(A, x0.reshape((x0.size, 1))).reshape((b.size,)))
    p = r
    rsold = dot(r, r)
    x = x0

    for _ in range(len(b)):
        Ap = matmul(A, p.reshape((p.size, 1))).reshape((b.size,))
        alpha = arithmetics.div(rsold, dot(p, Ap))
        x = arithmetics.add(x, arithmetics.mul(alpha, p))
        r = arithmetics.sub(r, arithmetics.mul(alpha, Ap))
        rsnew = dot(r, r)
        if float(rsnew.item()) ** 0.5 < 1e-10:
            return x
        p = arithmetics.add(r, arithmetics.mul(arithmetics.div(rsnew, rsold), p))
        rsold = rsnew
    return x


_LANCZOS_CACHE: dict = {}


def _lanczos_step_fn(phys_shape, jdt, m, comm, qk, ck, hk):
    """ONE donated executable per Lanczos iteration for a row-split
    matrix: ``(ab, vbuf, w, abuf, bbuf, beta_in, i) -> (vbuf, w, abuf,
    bbuf, beta_next)``.

    ``ab`` is the (N_pad, n) operator — rows zero-padded to the
    canonical layout, columns UNPADDED (no full operator copy: the
    matvec contracts against ``vi[:n]``, identical since vectors carry
    exact zeros beyond ``n``); all vectors live replicated in the padded
    coordinate space, and the zero pad rows of ``ab`` preserve the
    invariant. The body: normalize the residual into ``v_i``, row-local
    matvec + ONE tiled all_gather (the iteration's only collective),
    classical Gram-Schmidt against the whole masked basis applied TWICE
    (columns ≥ i are zero, so they project to nothing — CGS2 matches
    the legacy sequential reorthogonalization to the documented
    tolerance), and the next residual norm. ``i`` is traced, so ONE
    program serves every iteration; vbuf/w/abuf/bbuf are donated."""
    key = ("lanc", phys_shape, str(jdt), m, comm.cache_key, qk, ck, hk)
    fn = _LANCZOS_CACHE.get(key)
    if fn is not None:
        return fn
    axis = comm.axis_name

    def body(ab, vbuf, w, abuf, bbuf, beta_in, i):
        vi = w / beta_in
        vbuf = jax.lax.dynamic_update_slice(
            vbuf, vi[:, None], (jnp.int32(0), i))
        wl = ab @ vi[:ab.shape[1]]  # (c,) local rows; pad rows stay zero
        w1 = jax.lax.all_gather(wl, axis, axis=0, tiled=True)
        proj = vbuf.T @ w1  # (m,) — proj[i] is the Rayleigh alpha
        alpha = proj[i]
        w2 = w1 - vbuf @ proj
        proj2 = vbuf.T @ w2  # second CGS pass ("twice is enough")
        w2 = w2 - vbuf @ proj2
        abuf = jax.lax.dynamic_update_slice(abuf, alpha[None], (i,))
        bbuf = jax.lax.dynamic_update_slice(bbuf, beta_in[None], (i,))
        beta_next = jnp.sqrt(jnp.sum(w2 * w2))
        return vbuf, w2, abuf, bbuf, beta_next

    fn = jax.jit(
        shard_map(body, mesh=comm.mesh,
                  in_specs=(comm.spec(2, 0), P(), P(), P(), P(), P(), P()),
                  out_specs=(P(), P(), P(), P(), P()), check_vma=False),
        donate_argnums=(1, 2, 3, 4))
    _LANCZOS_CACHE[key] = fn
    return fn


def _lanczos_iter_eager(ab, vbuf, w, abuf, bbuf, beta, i, vi=None):
    """One Lanczos iteration dispatched op-by-op (unjitted jnp, GSPMD
    collectives): the ``fit.step.dispatch`` degrade path; with an
    explicit ``vi`` it is also the tiny-beta RESTART branch (the
    regenerated vector replaces ``w / beta``)."""
    if vi is None:
        vi = w / beta
    idx0 = jnp.asarray(0, i.dtype) if hasattr(i, "dtype") else 0
    vbuf = jax.lax.dynamic_update_slice(vbuf, vi[:, None], (idx0, i))
    w1 = ab @ vi[:ab.shape[1]]
    proj = vbuf.T @ w1
    alpha = proj[i]
    w2 = w1 - vbuf @ proj
    proj2 = vbuf.T @ w2
    w2 = w2 - vbuf @ proj2
    abuf = jax.lax.dynamic_update_slice(abuf, alpha[None], (i,))
    bbuf = jax.lax.dynamic_update_slice(
        bbuf, jnp.asarray(beta, bbuf.dtype)[None], (i,))
    beta_next = jnp.sqrt(jnp.sum(w2 * w2))
    return vbuf, w2, abuf, bbuf, beta_next


def _lanczos_fused(A: DNDarray, m: int, v0, V_out, T_out):
    """Tape-compiled Lanczos for a row-split operator: the whole inner
    loop is key-lookup + one donated dispatch per iteration plus a
    single ``float(beta)`` host read (the restart guard)."""
    from .. import random as ht_random

    comm = A.comm
    n = A.shape[0]
    phys = A.filled(0) if A.pad else A.larray
    if not jnp.issubdtype(phys.dtype, jnp.inexact):
        phys = phys.astype(
            jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    jdt = phys.dtype
    npad = phys.shape[0]
    # columns stay UNPADDED (the matvec slices vi[:n]) — padding them
    # would materialize a second full operator copy on the large-n path
    ab = phys
    w = jnp.pad(jnp.asarray(v0.resplit(None)._logical(), jdt),
                (0, npad - n))
    vbuf = jnp.zeros((npad, m), jdt)
    abuf = jnp.zeros((m,), jdt)
    bbuf = jnp.zeros((m,), jdt)
    beta = 1.0  # i=0 sentinel: v_0 = w / 1 = the (caller-normalized) v0
    for i in range(m):
        ii = jnp.asarray(i, jnp.int32)
        bb = jnp.asarray(beta, jdt)
        if i > 0 and beta < 1e-10:
            # restart with a random orthogonal vector (reference branch;
            # eager — the regenerated vi replaces the w/beta normalize)
            vr = jnp.pad(jnp.asarray(
                ht_random.rand(n, comm=comm).resplit(None)._logical(),
                jdt), (0, npad - n))
            vr = vr - vbuf @ (vbuf.T @ vr)
            vi = vr / jnp.sqrt(jnp.sum(vr * vr))
            vbuf, w, abuf, bbuf, bnext = _lanczos_iter_eager(
                ab, vbuf, w, abuf, bbuf, bb, ii, vi=vi)
        else:
            vbuf, w, abuf, bbuf, bnext = fusion.fit_step_call(
                ("lanczos.step", tuple(ab.shape), str(jdt), m,
                 comm.cache_key),
                lambda qk, ck, hk: _lanczos_step_fn(
                    ab.shape, jdt, m, comm, qk, ck, hk),
                (ab, vbuf, w, abuf, bbuf, bb, ii), _lanczos_iter_eager)
        beta = float(bnext)

    T_np = jnp.diag(abuf)
    if m > 1:
        off = bbuf[1:]
        T_np = T_np + jnp.diag(off, k=1) + jnp.diag(off, k=-1)
    T = DNDarray.from_logical(T_np, None, A.device, A.comm)
    V = DNDarray.from_logical(vbuf[:n], 0, A.device, A.comm)
    if V_out is not None:
        V_out.larray = V.resplit(V_out.split).larray
        if T_out is not None:
            T_out.larray = T.larray
            return V_out, T_out
        return V_out, T
    return V, T


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
):
    """Lanczos tridiagonalization (reference ``solver.py:68-184``).

    Returns ``(V, T)`` with ``A ≈ V @ T @ V.T``; used by spectral clustering
    exactly like the reference (``cluster/spectral.py:127``). For a
    row-split matrix the inner loop dispatches ONE donated compiled
    executable per iteration (:func:`_lanczos_step_fn`); the numerics
    contract of its twice-applied classical re-orthogonalization vs the
    legacy sequential form is documented in ``doc/analytics.md``.
    """
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be of type ht.DNDarray, but was {type(A)}")
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise RuntimeError("A needs to be a square matrix")

    n = A.shape[0]
    m = int(m)
    from .. import random as ht_random
    from .. import exponential

    if v0 is None:
        vr = ht_random.rand(n, split=A.split and 0, comm=A.comm)
        norm0 = exponential.sqrt(dot(vr, vr))
        v0 = arithmetics.div(vr, norm0)

    if A.split == 0 and n > 0 and m >= 1 and fusion.fit_enabled():
        # tape-compiled inner loop: one donated dispatch per iteration
        return _lanczos_fused(A, m, v0, V_out, T_out)

    alphas = []
    betas = [0.0]
    vs = [v0]
    w = matmul(A, v0.reshape((n, 1))).reshape((n,))
    alpha = dot(w, v0)
    w = arithmetics.sub(w, arithmetics.mul(alpha, v0))
    alphas.append(float(alpha.item()))

    for i in range(1, m):
        beta = float(exponential.sqrt(dot(w, w)).item())
        if beta < 1e-10:
            # restart with a random orthogonal vector
            vr = ht_random.rand(n, split=v0.split, comm=A.comm)
            # orthogonalize against previous vectors
            for v in vs:
                proj = dot(vr, v)
                vr = arithmetics.sub(vr, arithmetics.mul(proj, v))
            nrm = exponential.sqrt(dot(vr, vr))
            vi = arithmetics.div(vr, nrm)
        else:
            vi = arithmetics.div(w, beta)
        w = matmul(A, vi.reshape((n, 1))).reshape((n,))
        alpha = dot(w, vi)
        w = arithmetics.sub(w, arithmetics.mul(alpha, vi))
        w = arithmetics.sub(w, arithmetics.mul(beta, vs[-1]))
        # full reorthogonalization: plain Lanczos loses orthogonality in
        # float32; m is small so the extra matvec-free projections are cheap
        for v in vs:
            proj = dot(w, v)
            w = arithmetics.sub(w, arithmetics.mul(proj, v))
        alphas.append(float(alpha.item()))
        betas.append(beta)
        vs.append(vi)

    from .. import manipulations

    V = manipulations.stack(vs, axis=1)  # (n, m)
    T_np = jnp.diag(jnp.asarray(alphas))
    if m > 1:
        off = jnp.asarray(betas[1:])
        T_np = T_np + jnp.diag(off, k=1) + jnp.diag(off, k=-1)
    T = DNDarray.from_logical(T_np, None, A.device, A.comm)
    if V_out is not None:
        V_out.larray = V.resplit(V_out.split).larray
        if T_out is not None:
            T_out.larray = T.larray
            return V_out, T_out
        return V_out, T
    return V, T


def solve(A: DNDarray, b: DNDarray) -> DNDarray:
    """Solve the square dense system ``A x = b`` (beyond the reference,
    whose solver module stops at cg/lanczos — ``solver.py:13-184``).

    Split inexact ``A`` routes through the distributed Gauss-Jordan inverse
    + distributed matmul (``A`` is never gathered; the result comes back
    split 0). Note the usual accuracy caveat of inverse-multiply vs a
    direct LU solve — for ill-conditioned systems prefer :func:`cg` (SPD)
    or replicate ``A`` first for XLA's LU. Replicated/integer inputs run
    XLA's LU on the logical arrays with a replicated result; for tall
    least-squares systems use :func:`lstsq`, which stays distributed.
    """
    _square_2d_check(A)
    if A.split is not None and A.comm.size > 1 and \
            jnp.issubdtype(A.larray.dtype, jnp.inexact):
        # distributed route: Gauss-Jordan inverse (O(n^2/p) memory per
        # device, linalg/_gauss.py) + distributed matmul — A is never
        # gathered (round-2 verdict #7: "route solve/inv for split operands
        # through distributed paths")
        from .basics import inv, matmul

        bx = b if b.ndim == 2 else b.expand_dims(1)
        x = matmul(inv(A), bx)
        return x.reshape((A.shape[0],)) if b.ndim == 1 else x
    x = jnp.linalg.solve(A._logical(), b._logical())
    return DNDarray.from_logical(x, None, A.device, A.comm)


_CHOL_CACHE: dict = {}


def _cholesky_split0(A: DNDarray) -> DNDarray:
    """Distributed right-looking blocked Cholesky for a row-sharded SPD
    matrix (beyond the reference's solver set, which has no cholesky at
    all — same panel discipline as ``qr._split1_qr``).

    ``p`` rounds over device-aligned diagonal blocks: the owner factors its
    ``c×c`` diagonal block and broadcasts it with a masked psum (O(c²));
    every device triangular-solves its own panel block locally, the full
    panel column is assembled with one O(n·c) psum, and the trailing
    matrix updates shard-locally. Total traffic O(n²) over ``p`` rounds —
    the logical array is never materialized.
    """
    import jax
    from .._compat import shard_map
    from jax.scipy.linalg import solve_triangular

    from .. import types

    comm = A.comm
    p = comm.size
    n = A.shape[0]
    phys = A.filled(0) if A.pad else A.larray
    if not jnp.issubdtype(phys.dtype, jnp.inexact):
        phys = phys.astype(
            jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    jdt = phys.dtype
    c = phys.shape[0] // p
    n_pad = c * p
    axis = comm.axis_name

    cache_key = ("chol0", phys.shape, str(jdt), n, comm.cache_key)
    fn = _CHOL_CACHE.get(cache_key)
    if fn is None:
        def body(ab):
            me = jax.lax.axis_index(axis)
            ab = jnp.pad(ab, ((0, 0), (0, n_pad - n)))
            grow = me * c + jnp.arange(c)
            cols = jnp.arange(n_pad)
            # padded rows become identity rows: keeps every diagonal
            # block SPD without touching the logical n×n values
            eye_rows = (grow[:, None] == cols[None, :]).astype(jdt)
            ab = jnp.where((grow >= n)[:, None], eye_rows, ab)
            l_acc = jnp.zeros((c, n_pad), jdt)

            def step(j, carry):
                ab, l_acc = carry
                cand = jax.lax.dynamic_slice(
                    ab, (jnp.int32(0), (j * c).astype(jnp.int32)), (c, c))
                ljj = jnp.linalg.cholesky(cand)
                ljj = jax.lax.psum(
                    jnp.where(jnp.equal(me, j), ljj, jnp.zeros((), jdt)),
                    axis)
                # my panel block A_ij · L_jj^{-T}; the owner's solve yields
                # exactly L_jj (A_jj = L_jj L_jjᵀ), rows above the panel
                # are zeroed
                li = solve_triangular(ljj, cand.T, lower=True).T
                li = jnp.where(jnp.less(me, j), jnp.zeros((), jdt), li)
                # exact lower-triangularity: the owner's solve leaves
                # float fuzz above the block diagonal
                pancols = j * c + jnp.arange(c)
                li = jnp.where(grow[:, None] < pancols[None, :],
                               jnp.zeros((), jdt), li)
                panel = jax.lax.psum(
                    jax.lax.dynamic_update_slice(
                        jnp.zeros((n_pad, c), jdt), li,
                        ((me * c).astype(jnp.int32), jnp.int32(0))),
                    axis)
                upd = li @ panel.T
                trailing = (cols >= (j + 1) * c)[None, :]
                ab = ab - jnp.where(trailing, upd, jnp.zeros((), jdt))
                l_acc = jax.lax.dynamic_update_slice(
                    l_acc, li, (jnp.int32(0), (j * c).astype(jnp.int32)))
                return ab, l_acc

            _, l_acc = jax.lax.fori_loop(0, p, step, (ab, l_acc))
            return l_acc

        fn = jax.jit(shard_map(
            body, mesh=comm.mesh,
            in_specs=(comm.spec(2, 0),),
            out_specs=comm.spec(2, 0), check_vma=False))
        _CHOL_CACHE[cache_key] = fn
    l_phys = fn(phys)[:, :n]
    return DNDarray(l_phys, (n, n), types.canonical_heat_type(jdt), 0,
                    A.device, A.comm)


def cholesky(A: DNDarray) -> DNDarray:
    """Lower Cholesky factor of a symmetric positive-definite matrix.

    Split matrices run the distributed blocked factorization
    (:func:`_cholesky_split0`; split=1 re-chunks onto rows first — the
    matrix is symmetric, so the layout change is one reshard program);
    replicated matrices use XLA's cholesky directly.
    """
    _square_2d_check(A)
    if (A.split is not None and A.comm.size > 1 and A.size > 0
            and not jnp.issubdtype(A.larray.dtype, jnp.complexfloating)):
        return _cholesky_split0(A if A.split == 0 else A.resplit(0))
    L = jnp.linalg.cholesky(A._logical())
    return DNDarray.from_logical(L, None, A.device, A.comm)


def eigh(A: DNDarray):
    """Eigendecomposition of a symmetric matrix: ``(w, v)`` ascending.

    Split matrices run the DISTRIBUTED path (round 4, beyond the
    reference's cg/lanczos-only solver set): the input is symmetrized and
    shifted SPD by a Gershgorin bound ``c`` (one distributed row-sum +
    scalar max), then ``A + cI = U S Uᵀ`` via the gather-free SVD (CAQR +
    small-R SVD, `svd.py`) — eigenvalues are ``S - c``, eigenvectors the
    (split) left singular vectors; both flipped to ascending order. The
    shift costs ~eps·c of absolute accuracy, far inside f64 test
    tolerances. Replicated/complex inputs use XLA's eigh directly.
    """
    _square_2d_check(A)
    if (A.split is not None and A.comm.size > 1 and A.size > 0
            and not jnp.issubdtype(A.larray.dtype, jnp.complexfloating)):
        import jax

        from .. import types
        from .svd import svd

        x = A
        if not jnp.issubdtype(x.larray.dtype, jnp.inexact):
            x = x.astype(types.canonical_heat_type(
                jnp.float64 if jax.config.jax_enable_x64 else jnp.float32))
        # symmetrize (cheap next to the SVD) + Gershgorin shift to SPD.
        # The shift is RELATIVE (1.1x the row-sum bound on the spectral
        # radius) so the ~eps*c absolute error it costs scales with the
        # matrix norm — a small-norm matrix keeps full relative accuracy
        x = arithmetics.div(arithmetics.add(x, transpose(x)), 2.0)
        c = 1.1 * float(x.abs().sum(axis=1).max())
        if c == 0.0:  # zero matrix: w = 0, v = I via the SVD below
            c = 1.0
        shifted = arithmetics.add(
            x, arithmetics.mul(factories.eye(
                x.shape[0], dtype=x.dtype, split=x.split, device=x.device,
                comm=x.comm), c))
        from .. import manipulations

        if shifted.split != 0:
            # symmetric: one reshard onto rows keeps the SVD in the tall
            # split-0 branch, whose U (the eigenvectors) comes back split
            shifted = shifted.resplit(0)
        res = svd(shifted)
        w = res.S[::-1] - c            # ascending eigenvalues (replicated)
        # matching columns; flip is shard-local off the split axis, so the
        # eigenvector matrix keeps the SVD's split
        v = manipulations.flip(res.U, axis=1)
        return w, v
    w, v = jnp.linalg.eigh(A._logical())
    return (DNDarray.from_logical(w, None, A.device, A.comm),
            DNDarray.from_logical(v, None, A.device, A.comm))


def lstsq(A: DNDarray, b: DNDarray) -> DNDarray:
    """Least-squares solution of an (overdetermined) system ``A x ≈ b``.

    Distributed paths: a tall ``split=0`` matrix runs TSQR —
    ``x = R^{-1} (Q^T b)`` where Q/R come from the blockwise QR
    (:func:`heat_tpu.core.linalg.qr.qr`), so the tall dimension never
    gathers; a wide split matrix takes the min-norm solution through the
    gather-free SVD (small-side factors replicated, one distributed GEMM
    with the split V — round 4). Replicated inputs use XLA's lstsq.
    """
    if A.ndim != 2:
        raise ValueError(f"'A' must be 2-D, got {A.ndim}-D")
    m, n = A.shape
    if A.split == 0 and m >= n:
        from .qr import qr

        dec = qr(A, calc_q=True)
        qtb = matmul(transpose(dec.Q), b if b.ndim == 2 else b.expand_dims(1))
        r = dec.R._logical()
        # lstsq (not a triangular solve) on the small R system: for a
        # rank-deficient A this returns the min-norm solution, matching the
        # replicated path, instead of inf/NaN from a singular solve
        x, *_ = jnp.linalg.lstsq(r[:n, :n], qtb._logical()[:n])
        if b.ndim == 1:
            x = x[:, 0]
        return DNDarray.from_logical(x, None, A.device, A.comm)
    if (A.split is not None and A.comm.size > 1 and m < n and A.size > 0
            and not jnp.issubdtype(A.larray.dtype, jnp.complexfloating)):
        # wide system, min-norm solution through the gather-free SVD
        # (round 4): the long axis n stays split end to end — U and S are
        # (m x m)/(m,) small-side factors, x = V diag(S)^+ U^T b is one
        # distributed GEMM with the split V
        from .svd import _sv_cutoff, svd

        res = svd(A)  # svd itself reshards wide split-0 onto columns
        s = res.S._logical()
        u_l = res.U._logical()  # (m, m) small side, replicated by design
        cutoff = _sv_cutoff(s, m, n)
        b_l = b._logical()
        ub = u_l.T @ (b_l if b.ndim == 2 else b_l[:, None])
        w = ub * jnp.where(s > cutoff, 1.0 / s, 0.0)[:, None]
        x = matmul(res.V, DNDarray.from_logical(w, None, A.device, A.comm))
        from .. import manipulations

        return manipulations.reshape(x, (n,)) if b.ndim == 1 else x
    x, *_ = jnp.linalg.lstsq(A._logical(), b._logical())
    return DNDarray.from_logical(x, None, A.device, A.comm)
