"""QR decomposition (reference ``heat/core/linalg/qr.py:17-1042``).

The reference implements a tiled CAQR over ``SquareDiagTiles`` with explicit
Householder-merge sends between ranks (``__split0_r_calc`` ``:319``,
``__split0_merge_tile_rows`` ``:490``, ``__split0_send_q_to_diag_pr``
``:609``). Re-derived here as **blockwise TSQR** — the communication-optimal
tall-skinny QR that maps directly onto the mesh (SURVEY.md §7, M5):

1. each device QR-factors its local row block             (MXU)
2. the stacked small R factors are QR-factored once       (replicated)
3. local Qs are combined with the merge Q's row blocks    (MXU)

For ``split=1`` the reference runs a column-block Bcast loop
(``__split1_qr_loop`` ``:866-1042``): the owner factors the current panel,
broadcasts its Q, everyone updates their trailing columns. Re-derived here
as one jitted shard_map program (``_split1_qr``): a ``fori_loop`` over the
device-aligned column panels where each step (1) broadcasts the owner's
block with a masked ``psum`` (O(n·c) traffic — never the logical array),
(2) QR-factors the panel replicated on every device (MXU), and (3) applies
the block-Gram-Schmidt update ``A_i -= Q_j (Q_jᵀ A_i)`` locally. Replicated
operands use a single XLA ``qr``.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax
import jax.numpy as jnp

from ..dndarray import DNDarray
from .. import types

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")

# jitted factorization programs keyed by (path, shape, dtype, comm key) —
# rebuilding the shard_map closure per call would defeat jax's jit cache
_QR_CACHE: dict = {}


def qr(a: DNDarray, tiles_per_proc: int = 1, calc_q: bool = True, overwrite_a: bool = False) -> QR:
    """Reduced QR factorization ``a = Q @ R`` (reference ``qr.py:17``).

    ``tiles_per_proc`` is accepted for API parity; TSQR's block size is the
    canonical shard, so it has no effect.
    """
    if not isinstance(a, DNDarray):
        raise TypeError(f"'a' must be a DNDarray, got {type(a)}")
    # kwarg type validation matches the reference (``qr.py:100-110``): bool
    # passes the int check there (int subclass, treated as 1) and no range
    # check is applied — tiles_per_proc has no effect here anyway (TSQR's
    # block size is the canonical shard)
    if not isinstance(tiles_per_proc, int):
        raise TypeError(
            f"tiles_per_proc must be an int, got {type(tiles_per_proc)}")
    if not isinstance(calc_q, bool):
        raise TypeError(f"calc_q must be a bool, got {type(calc_q)}")
    if not isinstance(overwrite_a, bool):
        raise TypeError(f"overwrite_a must be a bool, got {type(overwrite_a)}")
    if a.ndim != 2:
        raise ValueError(f"qr requires a 2-D array, got {a.ndim}-D")

    n, m = a.shape
    if a.split == 0 and a.comm.size > 1 and n > 0 and m > 0:
        if n >= m * a.comm.size:
            return _tsqr(a, calc_q)
        return _caqr(a, calc_q)
    if a.split == 1 and a.comm.size > 1 and n > 0 and m > 0:
        return _split1_qr(a, calc_q)

    logical = a._logical()
    q, r = jnp.linalg.qr(logical, mode="reduced")
    q_d = DNDarray.from_logical(q, a.split, a.device, a.comm) if calc_q else None
    r_split = None if a.split is None else (1 if a.split == 1 else None)
    r_d = DNDarray.from_logical(r, r_split, a.device, a.comm)
    return QR(q_d, r_d)


def _caqr(a: DNDarray, calc_q: bool) -> QR:
    """General split=0 QR: right-looking panel CAQR built from TSQR
    (reference's tiled CAQR, ``qr.py:319-1042``, re-derived block-wise).

    One jitted shard_map program: a ``fori_loop`` over column panels where
    each step (1) TSQR-factors the ``b``-wide panel (local QR on the MXU +
    an all-gather of the p small ``b x b`` R factors — O(p b^2), never the
    data), (2) forms the panel's R rows with one psum GEMM, and (3) applies
    the rank-``b`` update to the trailing columns locally. Fixed shapes
    throughout — the panel index is the only dynamic value — so all panels
    share one compilation. Covers the square/wide split=0 shapes TSQR
    cannot (``n < m * p``) without materializing the logical array
    (round-2 VERDICT #6).
    """
    from .._compat import shard_map

    comm = a.comm
    p = comm.size
    n, m = a.shape
    k = min(n, m)
    c = a.larray.shape[0] // p
    b = min(c, k, 128)
    npan = -(-k // b)
    kpad = npan * b
    mpad = max(m, kpad)
    physical = a.filled(0) if a.pad else a.larray
    if mpad > m:
        physical = jnp.pad(physical, ((0, 0), (0, mpad - m)))
    jdt = physical.dtype

    def body(ab):
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c)
        rowvalid = (gpos < n)[:, None]
        qb = jnp.zeros((c, kpad), jdt)
        r_acc = jnp.zeros((kpad, mpad), jdt)
        colid = jnp.arange(mpad)

        def step(j, carry):
            ab, qb, r_acc = carry
            start = j * b
            pan = jax.lax.dynamic_slice(ab, (0, start), (c, b))
            q1, r1 = jnp.linalg.qr(pan, mode="reduced")
            rstack = jax.lax.all_gather(r1, comm.axis_name, axis=0, tiled=True)
            q2, _ = jnp.linalg.qr(rstack, mode="reduced")
            off = me * b
            myq2 = jax.lax.dynamic_slice(
                q2, (off, jnp.zeros((), off.dtype)), (b, b))
            qj = (q1 @ myq2) * rowvalid  # padding rows stay exactly zero
            rowsid = start + jnp.arange(b)
            rmask = (rowsid < k)[:, None]  # ragged last panel: junk rows off
            s = jax.lax.psum(qj.conj().T @ ab, comm.axis_name)
            s = jnp.where(rmask & (colid[None, :] >= start), s, 0)
            trail = jnp.where(colid[None, :] >= start + b, s, 0)
            ab = ab - qj @ trail
            qb = jax.lax.dynamic_update_slice(qb, qj, (0, start))
            r_acc = jax.lax.dynamic_update_slice(r_acc, s, (start, 0))
            return ab, qb, r_acc

        _, qb, r_acc = jax.lax.fori_loop(0, npan, step, (ab, qb, r_acc))
        return qb, r_acc

    cache_key = ("caqr", physical.shape, str(jdt), n, m, comm.cache_key)
    fn = _QR_CACHE.get(cache_key)
    if fn is None:
        fn = jax.jit(
            shard_map(
                body, mesh=comm.mesh, in_specs=comm.spec(2, 0),
                out_specs=(comm.spec(2, 0), comm.spec(2, None)),
                check_vma=False)
        )
        _QR_CACHE[cache_key] = fn
    q_phys, r_rep = fn(physical)
    q_d = None
    if calc_q:
        if kpad > k:
            q_phys = q_phys[:, :k]
        q_d = DNDarray(
            q_phys, (n, k), types.canonical_heat_type(q_phys.dtype), 0,
            a.device, a.comm)
    r_log = jnp.triu(r_rep[:k, :m])
    r_d = DNDarray.from_logical(r_log, None, a.device, a.comm)
    return QR(q_d, r_d)


def _split1_qr(a: DNDarray, calc_q: bool) -> QR:
    """Distributed split=1 QR: device-aligned column-panel block
    Gram-Schmidt (reference ``__split1_qr_loop``, ``qr.py:866-1042``).

    One jitted shard_map program. For each of the ``ceil(k/c)`` panels
    (``c`` = canonical column chunk, ``k = min(n, m)``): the owner's block
    is broadcast with a masked ``psum`` (O(n·c) per round — the logical
    array is never materialized), every device QR-factors the panel
    replicated, computes its R rows ``Q_jᵀ A_i`` and subtracts the rank-c
    update from its own columns. Q lands split=1 in A's exact column
    layout (``k == m``); for wide inputs (``k = n < m``) the panel-layout
    Q is re-chunked to the canonical (n, k) layout through the round-3
    distributed slicing machinery.
    """
    from .._compat import shard_map

    comm = a.comm
    p = comm.size
    n, m = a.shape
    k = min(n, m)
    c = a.larray.shape[1] // p
    physical = a.filled(0) if a.pad else a.larray
    if not jnp.issubdtype(physical.dtype, jnp.inexact):
        # integer input: the logical-path jnp.linalg.qr promotes to the
        # default inexact dtype (float64 under x64); match it so Q/R dtype
        # does not depend on the split layout
        physical = physical.astype(
            jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    jdt = physical.dtype
    npan = -(-k // c)  # only panels that intersect the first k columns
    axis = comm.axis_name

    def body(ab):
        me = jax.lax.axis_index(axis)
        q_acc = jnp.zeros((n, c), jdt)
        r_acc = jnp.zeros((npan * c, c), jdt)

        def step(j, carry):
            ab, q_acc, r_acc = carry
            # broadcast the owner's current block: masked psum, O(n*c)
            panel = jax.lax.psum(
                jnp.where(jnp.equal(me, j), ab, jnp.zeros((), jdt)), axis)
            qj, _ = jnp.linalg.qr(panel, mode="reduced")
            if qj.shape[1] < c:  # wide corner n < c: reduced Q is (n, n)
                qj = jnp.pad(qj, ((0, 0), (0, c - qj.shape[1])))
            # Q columns beyond k (ragged last panel / padded columns) come
            # from QR of zero columns — arbitrary orthonormal junk that
            # would pollute the trailing update; zero them.
            panvalid = (j * c + jnp.arange(c)) < k
            qj = qj * panvalid[None, :].astype(jdt)
            rji = qj.conj().T @ ab  # my R rows for panel j: (c, c_local)
            # block-upper-triangular structure: panel j only contributes
            # to blocks at or right of j; exactly triangular on-diagonal
            rji = jnp.where(jnp.equal(me, j), jnp.triu(rji), rji)
            rji = jnp.where(jnp.less_equal(j, me), rji, jnp.zeros((), jdt))
            ab = ab - qj @ rji
            q_acc = jnp.where(jnp.equal(me, j), qj, q_acc)
            r_acc = jax.lax.dynamic_update_slice(r_acc, rji, (j * c, 0))
            return ab, q_acc, r_acc

        _, q_acc, r_acc = jax.lax.fori_loop(
            0, npan, step, (ab, q_acc, r_acc))
        return q_acc, r_acc[:k, :]

    cache_key = ("split1", physical.shape, str(jdt), n, m, comm.cache_key)
    fn = _QR_CACHE.get(cache_key)
    if fn is None:
        spec = comm.spec(2, 1)
        fn = jax.jit(
            shard_map(
                body, mesh=comm.mesh, in_specs=spec,
                out_specs=(spec, spec), check_vma=False)
        )
        _QR_CACHE[cache_key] = fn
    q_phys, r_phys = fn(physical)
    ht_dt = types.canonical_heat_type(jdt)
    r_d = DNDarray(r_phys, (k, m), ht_dt, 1, a.device, comm)
    q_d = None
    if calc_q:
        if k == m:
            q_d = DNDarray(q_phys, (n, m), ht_dt, 1, a.device, comm)
        else:
            # wide input: Q's k columns sit in A's panel layout; re-chunk
            # to the canonical (n, k) split=1 layout (distributed slice)
            q_full = DNDarray(q_phys, (n, m), ht_dt, 1, a.device, comm)
            q_d = q_full[:, :k]
    return QR(q_d, r_d)


def _tsqr(a: DNDarray, calc_q: bool) -> QR:
    """Two-level TSQR over the mesh via shard_map."""
    from .._compat import shard_map

    comm = a.comm
    nprocs = comm.size
    n, m = a.shape
    physical = a.filled(0) if a.pad else a.larray
    spec_split0 = comm.spec(2, 0)
    spec_rep = comm.spec(2, None)

    def local_qr(x):
        # x: (chunk, m) local block → q (chunk, m), r (m, m)
        q, r = jnp.linalg.qr(x, mode="reduced")
        return q, r

    def body(x):
        q1, r1 = local_qr(x)
        # gather all local R factors: (nprocs * m, m), replicated
        r_stack = jax.lax.all_gather(r1, comm.axis_name, axis=0, tiled=True)
        q2, r2 = jnp.linalg.qr(r_stack, mode="reduced")
        # my row block of q2
        idx = jax.lax.axis_index(comm.axis_name)
        my_q2 = jax.lax.dynamic_slice_in_dim(q2, idx * m, m, axis=0)
        q_final = q1 @ my_q2
        return q_final, r2

    cache_key = ("tsqr", physical.shape, str(physical.dtype), n, m,
                 comm.cache_key)
    fn = _QR_CACHE.get(cache_key)
    if fn is None:
        fn = jax.jit(shard_map(
            body,
            mesh=comm.mesh,
            in_specs=spec_split0,
            out_specs=(spec_split0, spec_rep),
            check_vma=False,
        ))
        _QR_CACHE[cache_key] = fn
    q_phys, r_rep = fn(physical)
    # r_rep is replicated per device then stacked by shard_map on axis 0 of
    # the *global* result; out_specs=P() replication gives global (m, m)
    q_d = None
    if calc_q:
        q_d = DNDarray(q_phys, (n, m), types.canonical_heat_type(q_phys.dtype), 0, a.device, a.comm)
    r_d = DNDarray.from_logical(r_rep, None, a.device, a.comm)
    return QR(q_d, r_d)
