"""Singular value decomposition.

The reference ships only an empty stub (``heat/core/linalg/svd.py:1-5``,
"Future file for SVD functions"); this implementation therefore *exceeds*
reference parity: tall-skinny split-0 matrices are decomposed via TSQR
(QR on the mesh, then SVD of the small R), everything else by XLA's fused
SVD on the logical array.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax.numpy as jnp

from ..dndarray import DNDarray

__all__ = ["svd"]

SVD = collections.namedtuple("SVD", "U, S, V")


def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """Reduced SVD ``a = U @ diag(S) @ V.T``."""
    if not isinstance(a, DNDarray):
        raise TypeError(f"'a' must be a DNDarray, got {type(a)}")
    if a.ndim != 2:
        raise ValueError("svd requires a 2-D array")
    if full_matrices:
        raise NotImplementedError("only reduced SVD (full_matrices=False) is supported")

    n, m = a.shape
    if a.split == 0 and a.comm.size > 1 and n >= m * a.comm.size:
        from .qr import qr
        from .basics import matmul

        q, r = qr(a)
        u_r, s, vt = jnp.linalg.svd(r._logical(), full_matrices=False)
        if not compute_uv:
            return DNDarray.from_logical(s, None, a.device, a.comm)
        u_r_d = DNDarray.from_logical(u_r, None, a.device, a.comm)
        U = matmul(q, u_r_d)
        S = DNDarray.from_logical(s, None, a.device, a.comm)
        V = DNDarray.from_logical(vt.T, None, a.device, a.comm)
        return SVD(U, S, V)

    u, s, vt = jnp.linalg.svd(a._logical(), full_matrices=False)
    if not compute_uv:
        return DNDarray.from_logical(s, None, a.device, a.comm)
    return SVD(
        DNDarray.from_logical(u, a.split if a.split == 0 else None, a.device, a.comm),
        DNDarray.from_logical(s, None, a.device, a.comm),
        DNDarray.from_logical(vt.T, None, a.device, a.comm),
    )
