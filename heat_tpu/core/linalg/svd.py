"""Singular value decomposition.

The reference ships only an empty stub (``heat/core/linalg/svd.py:1-5``,
"Future file for SVD functions"); this implementation therefore *exceeds*
reference parity, and every distributed quadrant is gather-free: tall and
square split-0 matrices decompose via the distributed QR (TSQR / panel
CAQR) followed by an SVD of the small R; wide split-1 uses the transpose
identity (A^T = V S U^T, a local split remap); the remaining quadrants
reshard once to put the long axis on the mesh. Only replicated inputs use
XLA's fused SVD directly.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax.numpy as jnp

from ..dndarray import DNDarray

__all__ = ["svd", "pinv", "matrix_rank"]

SVD = collections.namedtuple("SVD", "U, S, V")


def _sv_cutoff(s, m: int, n: int, rcond=None):
    """numpy's default singular-value cutoff: ``rcond * s_max`` with
    ``rcond = max(m, n) * eps`` when unspecified."""
    if rcond is None:
        rcond = max(m, n) * jnp.finfo(s.dtype).eps
    smax = s[0] if s.size else jnp.asarray(0, s.dtype)
    return rcond * smax


def pinv(a: DNDarray, rcond=None) -> DNDarray:
    """Moore–Penrose pseudo-inverse via the (gather-free) SVD: ``V diag(S⁺)
    Uᵀ`` with numpy's default cutoff (beyond the reference's linalg set).
    The long axis stays split end to end — the small-side factors are
    replicated by the SVD's design, and the one large GEMM runs
    distributed, so the result comes back split for split inputs. Complex
    inputs use XLA's pinv on the logical array (the distributed factor
    algebra here is real-valued; conjugation is not applied)."""
    from .basics import matmul, transpose

    if jnp.issubdtype(a.larray.dtype, jnp.complexfloating):
        res = jnp.linalg.pinv(
            a._logical(), rtol=None if rcond is None else rcond)
        return DNDarray.from_logical(res, None, a.device, a.comm)
    res = svd(a)
    s = res.S._logical()
    cutoff = _sv_cutoff(s, *a.shape, rcond=rcond)
    sinv = jnp.where(s > cutoff, 1.0 / s, 0.0)
    # (n, k) * (k,) — scale V's columns shard-locally, then one GEMM
    v_scaled = res.V * DNDarray.from_logical(
        sinv[None, :], None, a.device, a.comm)
    return matmul(v_scaled, transpose(res.U))


def matrix_rank(a: DNDarray, rcond=None) -> int:
    """Rank by counting singular values above numpy's default cutoff
    (beyond the reference's linalg set; the SVD never gathers the long
    axis)."""
    if jnp.issubdtype(a.larray.dtype, jnp.complexfloating):
        return int(jnp.linalg.matrix_rank(
            a._logical(), rtol=None if rcond is None else rcond))
    s_d = svd(a, compute_uv=False)
    s = s_d._logical()
    return int(jnp.sum(s > _sv_cutoff(s, *a.shape, rcond=rcond)))


def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """Reduced SVD ``a = U @ diag(S) @ V.T``."""
    if not isinstance(a, DNDarray):
        raise TypeError(f"'a' must be a DNDarray, got {type(a)}")
    if a.ndim != 2:
        raise ValueError("svd requires a 2-D array")
    if full_matrices:
        raise NotImplementedError("only reduced SVD (full_matrices=False) is supported")

    n, m = a.shape
    if a.comm.size > 1 and a.size > 0 and a.split is not None:
        if a.split == 0 and n >= m:
            # QR (TSQR for tall, panel CAQR for square — both gather-free)
            # then SVD of the small m x m R
            from .qr import qr
            from .basics import matmul

            q, r = qr(a)
            u_r, s, vt = jnp.linalg.svd(r._logical(), full_matrices=False)
            if not compute_uv:
                return DNDarray.from_logical(s, None, a.device, a.comm)
            u_r_d = DNDarray.from_logical(u_r, None, a.device, a.comm)
            U = matmul(q, u_r_d)
            S = DNDarray.from_logical(s, None, a.device, a.comm)
            V = DNDarray.from_logical(vt.T, None, a.device, a.comm)
            return SVD(U, S, V)
        if a.split == 1 and m >= n:
            # A = U S V^T  <=>  A^T = V S U^T; transpose is a local permute
            # + split remap, landing in the tall split-0 branch above
            from .basics import transpose

            res = svd(transpose(a), compute_uv=compute_uv)
            if not compute_uv:
                return res
            return SVD(res.V, res.S, res.U)
        # remaining quadrants (tall split-1, wide split-0): one reshard puts
        # the long axis on the mesh, then the branches above terminate
        return svd(a.resplit(0 if n >= m else 1), compute_uv=compute_uv)

    u, s, vt = jnp.linalg.svd(a._logical(), full_matrices=False)
    if not compute_uv:
        return DNDarray.from_logical(s, None, a.device, a.comm)
    return SVD(
        DNDarray.from_logical(u, a.split if a.split == 0 else None, a.device, a.comm),
        DNDarray.from_logical(s, None, a.device, a.comm),
        DNDarray.from_logical(vt.T, None, a.device, a.comm),
    )
