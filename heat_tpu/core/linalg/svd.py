"""Singular value decomposition.

The reference ships only an empty stub (``heat/core/linalg/svd.py:1-5``,
"Future file for SVD functions"); this implementation therefore *exceeds*
reference parity, and every distributed quadrant is gather-free: tall and
square split-0 matrices decompose via the distributed QR (TSQR / panel
CAQR) followed by an SVD of the small R; wide split-1 uses the transpose
identity (A^T = V S U^T, a local split remap); the remaining quadrants
reshard once to put the long axis on the mesh. Only replicated inputs use
XLA's fused SVD directly.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax.numpy as jnp

from ..dndarray import DNDarray

__all__ = ["svd"]

SVD = collections.namedtuple("SVD", "U, S, V")


def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """Reduced SVD ``a = U @ diag(S) @ V.T``."""
    if not isinstance(a, DNDarray):
        raise TypeError(f"'a' must be a DNDarray, got {type(a)}")
    if a.ndim != 2:
        raise ValueError("svd requires a 2-D array")
    if full_matrices:
        raise NotImplementedError("only reduced SVD (full_matrices=False) is supported")

    n, m = a.shape
    if a.comm.size > 1 and a.size > 0 and a.split is not None:
        if a.split == 0 and n >= m:
            # QR (TSQR for tall, panel CAQR for square — both gather-free)
            # then SVD of the small m x m R
            from .qr import qr
            from .basics import matmul

            q, r = qr(a)
            u_r, s, vt = jnp.linalg.svd(r._logical(), full_matrices=False)
            if not compute_uv:
                return DNDarray.from_logical(s, None, a.device, a.comm)
            u_r_d = DNDarray.from_logical(u_r, None, a.device, a.comm)
            U = matmul(q, u_r_d)
            S = DNDarray.from_logical(s, None, a.device, a.comm)
            V = DNDarray.from_logical(vt.T, None, a.device, a.comm)
            return SVD(U, S, V)
        if a.split == 1 and m >= n:
            # A = U S V^T  <=>  A^T = V S U^T; transpose is a local permute
            # + split remap, landing in the tall split-0 branch above
            from .basics import transpose

            res = svd(transpose(a), compute_uv=compute_uv)
            if not compute_uv:
                return res
            return SVD(res.V, res.S, res.U)
        # remaining quadrants (tall split-1, wide split-0): one reshard puts
        # the long axis on the mesh, then the branches above terminate
        return svd(a.resplit(0 if n >= m else 1), compute_uv=compute_uv)

    u, s, vt = jnp.linalg.svd(a._logical(), full_matrices=False)
    if not compute_uv:
        return DNDarray.from_logical(s, None, a.device, a.comm)
    return SVD(
        DNDarray.from_logical(u, a.split if a.split == 0 else None, a.device, a.comm),
        DNDarray.from_logical(s, None, a.device, a.comm),
        DNDarray.from_logical(vt.T, None, a.device, a.comm),
    )
