"""Version identity for heat_tpu.

Mirrors the role of the reference's ``heat/core/version.py:3-8`` (major/minor/
micro components assembled into ``__version__``).
"""

major: int = 0
"""Major version component."""
minor: int = 1
"""Minor version component."""
micro: int = 0
"""Micro (patch) version component."""
extension: str = None
"""Optional pre-release tag."""

if not extension:
    __version__ = f"{major}.{minor}.{micro}"
else:
    __version__ = f"{major}.{minor}.{micro}-{extension}"
