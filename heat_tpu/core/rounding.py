"""Rounding operations (reference ``heat/core/rounding.py:30-454``)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = [
    "abs",
    "absolute",
    "ceil",
    "clip",
    "copysign",
    "fabs",
    "fix",
    "floor",
    "modf",
    "nan_to_num",
    "round",
    "round_",
    "sgn",
    "sign",
    "trunc",
]


def abs(x, out=None, dtype=None) -> DNDarray:  # noqa: A001
    """Element-wise absolute value (reference ``rounding.py:30``)."""
    if dtype is not None and not issubclass(types.canonical_heat_type(dtype), types.generic):
        raise TypeError("dtype must be a heat data type")
    res = _operations._local_op(jnp.abs, x, out)
    if dtype is not None:
        res = res.astype(types.canonical_heat_type(dtype), copy=False)
    return res


absolute = abs


def ceil(x: DNDarray, out=None) -> DNDarray:
    """Element-wise ceiling (reference ``:100``)."""
    return _operations._local_op(jnp.ceil, x, out)


def clip(x: DNDarray, min=None, max=None, out=None) -> DNDarray:
    """Clamp values to an interval (reference ``:140``)."""
    if min is None and max is None:
        raise ValueError("either min or max must be set")
    mn = min.larray if isinstance(min, DNDarray) else min
    mx = max.larray if isinstance(max, DNDarray) else max
    # static kwargs on the module-level op keep scalar-bound clips
    # recordable by the fusion engine (a per-call lambda never could:
    # fresh identity per call = one compiled program per invocation);
    # array bounds make the kwargs unhashable and dispatch eagerly
    return _operations._local_op(jnp.clip, x, out, min=mn, max=mx)


def copysign(t1, t2) -> DNDarray:
    """Magnitude of ``t1`` with the sign of ``t2``, element-wise (NumPy-parity
    extra; the reference has no copysign)."""
    return _operations._binary_op(jnp.copysign, t1, t2)


def fabs(x: DNDarray, out=None) -> DNDarray:
    """Float absolute value (reference ``:200``)."""
    return abs(x, out, dtype=None).astype(
        types.promote_types(x.dtype if isinstance(x, DNDarray) else types.float32, types.float32),
        copy=False,
    )


def floor(x: DNDarray, out=None) -> DNDarray:
    """Element-wise floor (reference ``:240``)."""
    return _operations._local_op(jnp.floor, x, out)


def modf(x: DNDarray, out=None) -> tuple:
    """Split into fractional and integral parts (reference ``:280``)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    frac = _operations._local_op(lambda a: jnp.modf(a)[0], x)
    integ = _operations._local_op(lambda a: jnp.modf(a)[1], x)
    if out is not None:
        if not isinstance(out, tuple) or len(out) != 2:
            raise TypeError("expected out to be None or a tuple of two DNDarrays")
        out[0].larray = frac.larray
        out[1].larray = integ.larray
        return out
    return (frac, integ)


def round(x: DNDarray, decimals: int = 0, out=None, dtype=None) -> DNDarray:  # noqa: A001
    """Round to ``decimals`` (reference ``:340``)."""
    res = _operations._local_op(jnp.round, x, out, decimals=decimals)
    if dtype is not None:
        res = res.astype(types.canonical_heat_type(dtype), copy=False)
    return res


def sgn(x: DNDarray, out=None) -> DNDarray:
    """Sign (complex-aware) (reference ``:400``)."""
    return _operations._local_op(jnp.sign, x, out)


def sign(x: DNDarray, out=None) -> DNDarray:
    """Sign of real arrays (reference ``:420``)."""
    return _operations._local_op(jnp.sign, x, out)


def trunc(x: DNDarray, out=None) -> DNDarray:
    """Truncate toward zero (reference ``:440``)."""
    return _operations._local_op(jnp.trunc, x, out)


def fix(x: DNDarray, out=None) -> DNDarray:
    """Round toward zero, result floating (``numpy.fix``)."""
    from . import types

    res = trunc(x if types.heat_type_is_inexact(x.dtype)
                else x.astype(types.float32), out=None)
    return _operations._finalize(res, out)


def round_(x: DNDarray, decimals: int = 0, out=None) -> DNDarray:
    """Alias of :func:`round` (``numpy.round_``)."""
    return round(x, decimals=decimals, out=out)


def nan_to_num(x: DNDarray, nan: float = 0.0, posinf=None, neginf=None,
               out=None) -> DNDarray:
    """Replace NaN/inf with finite numbers (``numpy.nan_to_num``)."""
    return _operations._local_op(
        lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
        x, out)
