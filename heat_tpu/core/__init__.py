"""heat_tpu core: flat re-export of the full ops namespace
(reference ``heat/core/__init__.py``)."""

from . import version
from .version import __version__

from .communication import *
from . import communication
from .devices import *
from . import devices
from .types import *
from . import types
from .constants import *
from . import constants
from .stride_tricks import *
from . import stride_tricks
from .dndarray import *
from . import dndarray
from .memory import *
from . import memory
from .factories import *
from . import factories
from .sanitation import *
from . import sanitation
from .arithmetics import *
from . import arithmetics
from .relational import *
from . import relational
from .logical import *
from . import logical
from .rounding import *
from . import rounding
from .exponential import *
from . import exponential
from .trigonometrics import *
from . import trigonometrics
from .complex_math import *
from . import complex_math
from .indexing import *
from . import indexing
from .statistics import *
from . import statistics
from .manipulations import *
from . import manipulations
from . import random
from .io import *
from . import io
from .printing import *
from . import printing
from .tiling import *
from . import tiling
from .base import *
from . import base
from .linalg import *
from . import linalg
from .pallas_kernels import pallas_enabled, set_pallas
from . import pallas_kernels
from . import fusion
from .fusion import enabled as fusion_enabled, set_enabled as set_fusion
# tier declaration for hierarchical packed collectives (ht.mesh_tiers):
# a flat mesh's (dcn, ici) factorization or a named grid's slow axis
from .fusion import mesh_tiers, set_mesh_tiers


def __getattr__(name):
    if name in ("MESH_WORLD", "MESH_SELF"):
        return getattr(communication, name)
    raise AttributeError(f"module 'heat_tpu.core' has no attribute {name!r}")
