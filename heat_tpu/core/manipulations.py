"""Shape and data manipulation operations (reference ``heat/core/manipulations.py``).

Strategy on the XLA backend: ops that do not touch the split axis run on the
physical (padded) array with zero communication. Ops that cross or transform
the split axis are GATHER-FREE compiled collective programs: static
monotone source maps (concatenate/reshape/roll/flip/repeat/tile/pad/diag)
run scheduled block-window fetches (:mod:`._manips` — O(1) ppermute rounds,
the counterpart of the reference's Alltoallv ``:1817`` / point-to-point
``:188`` machinery), ``sort`` runs the Batcher merge-split network
(:mod:`._sort`, vs the reference's sample-sort ``:2263``), ``unique`` the
three-phase pipeline (:mod:`._setops`, vs Allgatherv ``:3051``), and
``topk`` the tournament reduction (vs ``mpi_topk`` ``:3971``).
Array-valued ``repeat`` builds a source map from the cumulative counts and
rides the distributed fancy-indexing rings; ``unique(axis=k)`` runs the
lexicographic row pipeline (:mod:`._setops`); ``return_inverse`` for
flattened ndim>1 inputs rides the 1-D pipeline with a distributed reshape
of the inverse back to the input shape.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import factories, types
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "array_split",
    "balance",
    "broadcast_arrays",
    "broadcast_to",
    "column_stack",
    "concatenate",
    "diag",
    "diagonal",
    "dsplit",
    "dstack",
    "expand_dims",
    "flatten",
    "flip",
    "fliplr",
    "flipud",
    "hsplit",
    "hstack",
    "intersect1d",
    "moveaxis",
    "pad",
    "ravel",
    "redistribute",
    "repeat",
    "reshape",
    "resplit",
    "roll",
    "rot90",
    "row_stack",
    "setdiff1d",
    "setxor1d",
    "shape",
    "sort",
    "split",
    "squeeze",
    "stack",
    "swapaxes",
    "tile",
    "topk",
    "union1d",
    "unique",
    "vsplit",
    "vstack",
]


def _wrap_logical(arr, split, like: DNDarray, dtype=None) -> DNDarray:
    return DNDarray.from_logical(arr, split, like.device, like.comm, dtype=dtype)


def balance(array: DNDarray, copy: bool = False) -> DNDarray:
    """Balanced copy (reference ``manipulations.py:73``): canonical layout is
    always balanced, so this is identity/copy."""
    if copy:
        from . import memory

        return memory.copy(array)
    return array


def broadcast_arrays(*arrays: DNDarray) -> List[DNDarray]:
    """Broadcast arrays against each other (reference ``:100``)."""
    from .stride_tricks import broadcast_shapes

    target = broadcast_shapes(*[a.shape for a in arrays])
    return [broadcast_to(a, target) for a in arrays]


def broadcast_to(x: DNDarray, shape) -> DNDarray:
    """Broadcast to a new shape (reference ``:140``): the split axis keeps
    its extent (a size-1 split axis resplits first), so the broadcast is
    shard-local on the physical array."""
    shape = sanitize_shape(shape)
    out_split = None
    if x.split is not None:
        out_split = x.split + (len(shape) - x.ndim)
        if x.shape[x.split] == 1 and shape[out_split] != 1:
            x = x.resplit(None)
            out_split = None
    if out_split is not None and x.comm.size > 1:
        if shape[out_split] != x.shape[x.split]:
            # the fast path substitutes the physical extent below, so it
            # must enforce what jnp.broadcast_to would have (review finding:
            # a mismatched split-axis target silently mislabeled the result)
            raise ValueError(
                f"cannot broadcast shape {x.shape} to {shape}: the split "
                f"axis extent must match (got {shape[out_split]} vs "
                f"{x.shape[x.split]})")
        phys_target = tuple(
            x.larray.shape[x.split] if i == out_split else s
            for i, s in enumerate(shape))
        res = jnp.broadcast_to(x.larray, phys_target)
        return DNDarray(res, shape, x.dtype, out_split, x.device, x.comm)
    res = jnp.broadcast_to(x._logical(), shape)
    return _wrap_logical(res, out_split, x)


def column_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack 1-D/2-D arrays as columns (reference ``:188`` family)."""
    prepped = [a.reshape((a.shape[0], 1)) if a.ndim == 1 else a for a in arrays]
    return concatenate(prepped, axis=1)


def concatenate(arrays: Sequence[DNDarray], axis: int = 0) -> DNDarray:
    """Join arrays along an existing axis (reference ``:188``)."""
    arrays = list(arrays)
    if len(arrays) < 1:
        raise ValueError("need at least one array to concatenate")
    for a in arrays:
        if not isinstance(a, DNDarray):
            raise TypeError(f"inputs must be DNDarrays, found {type(a)}")
    axis = sanitize_axis(arrays[0].shape, axis)
    lead = arrays[0].shape
    for a in arrays[1:]:
        if a.ndim != len(lead) or any(
            a.shape[i] != lead[i] for i in range(a.ndim) if i != axis
        ):
            raise ValueError(
                "all input array dimensions except the concatenation axis "
                f"must match exactly: {lead} vs {tuple(a.shape)} on axis {axis}"
            )
    out_split = arrays[0].split
    for a in arrays[1:]:
        if a.split != out_split:
            a_splits = {x.split for x in arrays}
            non_none = [s for s in a_splits if s is not None]
            out_split = non_none[0] if non_none else None
            break
    dtype = types.result_type(*arrays)
    comm = arrays[0].comm
    # zero-extent operands contribute no data and are dropped so they don't
    # force the materializing fallback out of the distributed paths below
    if out_split is not None and comm.size > 1:
        nonempty = [a for a in arrays if a.shape[axis] > 0]
        if nonempty:
            arrays = nonempty
    # mixed splits (e.g. appending a replicated row block to a split array):
    # re-chunk each minority operand onto the majority layout with one
    # reshard program (replicated→split is a local slice; split→split is the
    # one-program resplit) so the distributed paths below apply — the
    # reference resplits to a common layout the same way (``:271-310``).
    if out_split is not None and comm.size > 1 and any(
        a.split != out_split for a in arrays
    ):
        arrays = [
            a if a.split == out_split else a.resplit(out_split) for a in arrays
        ]
    # distributed path: all inputs split along the concatenation axis — each
    # input streams through a destination-scatter ring (no all-gather;
    # reference ``:188`` moves boundary chunks point-to-point)
    if (
        out_split == axis
        and comm.size > 1
        and all(a.split == axis and a.shape[axis] > 0 for a in arrays)
    ):
        from . import _manips

        ns = [a.shape[axis] for a in arrays]
        n_out = sum(ns)
        gshape = tuple(
            n_out if i == axis else s for i, s in enumerate(arrays[0].gshape)
        )
        phys = [a.larray.astype(dtype.jax_type()) for a in arrays]
        c_out = comm.chunk_size(n_out)
        fn = _manips.ring_concat_fn(
            [p.shape for p in phys], jnp.dtype(dtype.jax_type()), axis, ns,
            c_out, comm)
        out = fn(*phys)
        return DNDarray(out, gshape, dtype, axis, arrays[0].device, comm)
    # all inputs share a split on some OTHER axis: the concat axis is
    # unsharded, so the join is purely shard-local (their split-axis physical
    # extents coincide — same logical size, same padding)
    if (
        out_split is not None
        and out_split != axis
        and all(a.split == out_split for a in arrays)
    ):
        phys = [a.larray.astype(dtype.jax_type()) for a in arrays]
        res = jnp.concatenate(phys, axis=axis)
        gshape = tuple(
            sum(a.shape[axis] for a in arrays) if i == axis else s
            for i, s in enumerate(arrays[0].gshape)
        )
        return DNDarray(res, gshape, dtype, out_split, arrays[0].device,
                        arrays[0].comm)
    logicals = [a._logical().astype(dtype.jax_type()) for a in arrays]
    res = jnp.concatenate(logicals, axis=axis)
    return _wrap_logical(res, out_split, arrays[0], dtype=dtype)


def _diag_construct_distributed(a: DNDarray, offset: int):
    """diag(1-D split vector) -> (L, L) row-split matrix, built shard-locally
    after one ring shift of the vector into the output row chunking
    (reference ``:512``). Row ``j`` holds ``w[j]`` at column ``j + offset``
    where ``w`` is the vector zero-extended to length ``L``."""
    import jax
    from ._compat import shard_map
    from . import factories

    comm = a.comm
    n = a.shape[0]
    L = n + abs(offset)
    if offset > 0:
        w = concatenate(
            [a, factories.zeros(offset, dtype=a.dtype, split=0, comm=comm)], 0)
    elif offset < 0:
        w = concatenate(
            [factories.zeros(-offset, dtype=a.dtype, split=0, comm=comm), a], 0)
    else:
        w = a
    c = w.larray.shape[0] // comm.size
    jdt = w.larray.dtype

    def body(wb):
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c)
        col = gpos + offset
        ok = (gpos < L) & (col >= 0) & (col < L)
        block = jnp.zeros((c, L), jdt)
        block = block.at[jnp.arange(c), jnp.clip(col, 0, L - 1)].set(
            jnp.where(ok, wb, 0))
        return block

    fn = jax.jit(shard_map(body, mesh=comm.mesh, in_specs=comm.spec(1, 0),
                           out_specs=comm.spec(2, 0), check_vma=False))
    return DNDarray(fn(w.larray), (L, L), a.dtype, 0, a.device, comm)


def diag(a: DNDarray, offset: int = 0) -> DNDarray:
    """Extract or construct a diagonal (reference ``:512``)."""
    if a.ndim == 1:
        if a.split == 0 and a.comm.size > 1 and a.shape[0] > 0:
            return _diag_construct_distributed(a, int(offset))
        res = jnp.diag(a._logical(), k=offset)
        return _wrap_logical(res, 0 if a.split is not None else None, a)
    return diagonal(a, offset=offset)


def _diagonal_extract_distributed(a: DNDarray, offset: int):
    """diagonal of a row-split 2-D matrix: each row's diagonal element is
    shard-local; the length-``L`` prefix re-chunks through the mask ring."""
    import jax
    from ._compat import shard_map

    comm = a.comm
    n, m = a.shape
    L = max(0, min(n, m - offset) if offset >= 0 else min(n + offset, m))
    if L == 0:
        return DNDarray.from_logical(
            jnp.zeros((0,), a.larray.dtype), None, a.device, comm)
    c = a.larray.shape[0] // comm.size

    def body(ab):
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c)
        col = gpos + offset
        ok = (gpos < n) & (col >= 0) & (col < m)
        vals = jnp.take_along_axis(
            ab, jnp.clip(col, 0, m - 1)[:, None], axis=1)[:, 0]
        return jnp.where(ok, vals, 0)

    fn = jax.jit(shard_map(body, mesh=comm.mesh, in_specs=comm.spec(2, 0),
                           out_specs=comm.spec(1, 0), check_vma=False))
    w = DNDarray(fn(a.larray), (n,), a.dtype, 0, a.device, comm)
    # the diagonal occupies rows [lo, lo + L); re-chunk it into canonical
    # length-L layout with the mask ring (order preserved)
    lo = max(0, -offset)
    if lo == 0 and L == n:
        return w
    rows = np.arange(n)
    return w[(rows >= lo) & (rows < lo + L)]


def diagonal(a: DNDarray, offset: int = 0, dim1: int = 0, dim2: int = 1) -> DNDarray:
    """Extract a diagonal (reference ``:587``)."""
    if (
        a.ndim == 2
        and {dim1, dim2} == {0, 1}
        and a.split is not None
        and a.comm.size > 1
        and a.size > 0
    ):
        if (dim1, dim2) == (1, 0):
            from .linalg import transpose

            return diagonal(transpose(a), offset=offset, dim1=0, dim2=1)
        if a.split == 1:
            from .linalg import transpose

            return _diagonal_extract_distributed(transpose(a), -int(offset))
        return _diagonal_extract_distributed(a, int(offset))
    res = jnp.diagonal(a._logical(), offset=offset, axis1=dim1, axis2=dim2)
    out_split = None
    if a.split is not None:
        out_split = res.ndim - 1 if a.split in (dim1, dim2) else 0
    return _wrap_logical(res, out_split, a)


def dsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along axis 2 (reference ``:700`` family)."""
    return split(x, indices_or_sections, axis=2)


def dstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack along the third axis (reference ``:760``)."""
    prepped = []
    for a in arrays:
        if a.ndim == 1:
            a = a.reshape((1, a.shape[0], 1))
        elif a.ndim == 2:
            a = a.reshape((a.shape[0], a.shape[1], 1))
        prepped.append(a)
    return concatenate(prepped, axis=2)


def expand_dims(a: DNDarray, axis: int) -> DNDarray:
    """Insert a size-1 dimension (reference ``:840``). Zero communication:
    operates on the physical array; the split index shifts."""
    axis = sanitize_axis(tuple(list(a.shape) + [1]), axis)
    res = jnp.expand_dims(a.larray, axis)
    out_split = a.split if a.split is None or a.split < axis else a.split + 1
    gshape = list(a.shape)
    gshape.insert(axis, 1)
    return DNDarray(res, tuple(gshape), a.dtype, out_split, a.device, a.comm)


def flatten(a: DNDarray) -> DNDarray:
    """Collapse to 1-D (reference ``:900``). Distributed arrays go through
    the ring re-chunking reshape (no gather)."""
    if a.split is not None and a.comm.size > 1 and a.size > 0:
        return reshape(a, (a.size,), new_split=0)
    res = a._logical().reshape(-1)
    return _wrap_logical(res, 0 if a.split is not None else None, a)


def flip(a: DNDarray, axis=None) -> DNDarray:
    """Reverse element order along axes (reference ``:960``).

    Non-split axes flip shard-locally; the split axis flips through the
    destination-scatter ring (:mod:`heat_tpu.core._manips`) — pairwise
    ``ppermute`` only, no all-gather (reference moves whole shards
    point-to-point)."""
    if axis is None:
        axes = tuple(range(a.ndim))
    else:
        axes = (sanitize_axis(a.shape, axis),) if isinstance(axis, int) else tuple(
            sanitize_axis(a.shape, ax) for ax in axis
        )
    if a.split is not None and a.split in axes:
        if a.comm.size > 1 and a.shape[a.split] > 0:
            from . import _manips

            other = tuple(ax for ax in axes if ax != a.split)
            phys = jnp.flip(a.larray, axis=other) if other else a.larray
            fn = _manips.ring_flip_fn(
                phys.shape, jnp.dtype(phys.dtype), a.split,
                a.shape[a.split], a.comm)
            return DNDarray(fn(phys), a.gshape, a.dtype, a.split, a.device,
                            a.comm)
        res = jnp.flip(a._logical(), axis=axes)
        return _wrap_logical(res, a.split, a)
    res = jnp.flip(a.larray, axis=axes)
    return DNDarray(res, a.gshape, a.dtype, a.split, a.device, a.comm)


def fliplr(a: DNDarray) -> DNDarray:
    """Flip along axis 1 (reference ``:1020``)."""
    if a.ndim < 2:
        raise IndexError("expected array with at least 2 dimensions")
    return flip(a, 1)


def flipud(a: DNDarray) -> DNDarray:
    """Flip along axis 0 (reference ``:1040``)."""
    return flip(a, 0)


def hsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along axis 1 (axis 0 for 1-D) (reference family)."""
    if x.ndim < 2:
        return split(x, indices_or_sections, axis=0)
    return split(x, indices_or_sections, axis=1)


def hstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack horizontally (reference ``:1100``)."""
    if all(a.ndim == 1 for a in arrays):
        return concatenate(arrays, axis=0)
    return concatenate(arrays, axis=1)


def moveaxis(x: DNDarray, source, destination) -> DNDarray:
    """Move axes to new positions (reference ``:1108``)."""
    if isinstance(source, int):
        source = (source,)
    if isinstance(destination, int):
        destination = (destination,)
    source = tuple(sanitize_axis(x.shape, s) for s in source)
    destination = tuple(sanitize_axis(x.shape, d) for d in destination)
    if len(source) != len(destination):
        raise ValueError("source and destination arguments must have the same number of elements")
    order = [n for n in range(x.ndim) if n not in source]
    for dest, src in sorted(zip(destination, source)):
        order.insert(dest, src)
    from .linalg import transpose

    return transpose(x, order)


def _normalize_pad_width(pad_width, ndim):
    """NumPy pad_width forms -> ((before, after), ...) per axis, or None."""
    try:
        pw = np.asarray(pad_width, dtype=np.int64)
    except (ValueError, TypeError):
        return None
    if pw.ndim == 0:
        return ((int(pw), int(pw)),) * ndim
    if pw.shape == (2,):
        return ((int(pw[0]), int(pw[1])),) * ndim
    if pw.shape == (1,):
        return ((int(pw[0]), int(pw[0])),) * ndim
    if pw.shape == (ndim, 2):
        return tuple((int(a), int(b)) for a, b in pw)
    if pw.shape == (1, 2):
        return ((int(pw[0, 0]), int(pw[0, 1])),) * ndim
    return None


def pad(array: DNDarray, pad_width, mode: str = "constant", constant_values=0) -> DNDarray:
    """Pad an array (reference ``:1128``).

    Pads that leave the split axis untouched apply shard-locally; a padded
    split axis grows through a ring concatenation with constant blocks
    (constant mode) — no logical materialization either way."""
    kw = {"constant_values": constant_values} if mode == "constant" else {}
    pw = _normalize_pad_width(pad_width, array.ndim)
    if (
        pw is not None
        and array.split is not None
        and array.comm.size > 1
        and array.size > 0
        and (mode != "constant" or np.ndim(constant_values) == 0)
    ):
        split = array.split
        before, after = pw[split]
        other = tuple((0, 0) if i == split else p for i, p in enumerate(pw))
        phys = array.larray
        if any(p != (0, 0) for p in other):
            phys = jnp.pad(phys, other, mode=mode, **kw)
        gshape = tuple(
            s + (0 if i == split else pw[i][0] + pw[i][1])
            for i, s in enumerate(array.gshape)
        )
        out = DNDarray(phys, gshape, array.dtype, split, array.device,
                       array.comm)
        if before == 0 and after == 0:
            return out
        if mode == "constant":
            from . import factories

            parts = []
            if before:
                shp = tuple(before if i == split else s
                            for i, s in enumerate(gshape))
                parts.append(factories.full(
                    shp, constant_values, dtype=array.dtype, split=split,
                    comm=array.comm))
            parts.append(out)
            if after:
                shp = tuple(after if i == split else s
                            for i, s in enumerate(gshape))
                parts.append(factories.full(
                    shp, constant_values, dtype=array.dtype, split=split,
                    comm=array.comm))
            return concatenate(parts, axis=split)
        if mode in ("reflect", "symmetric", "edge", "wrap") and \
                array.shape[split] > (1 if mode == "reflect" else 0):
            from . import _manips

            n = array.shape[split]
            fn = _manips.ring_pad_fn(
                out.larray.shape, jnp.dtype(out.larray.dtype), split, n,
                before, after, mode, array.comm)
            g2 = tuple(s + (before + after if i == split else 0)
                       for i, s in enumerate(out.gshape))
            return DNDarray(fn(out.larray), g2, array.dtype, split,
                            array.device, array.comm)
        # other modes on the split axis: fall back
    res = jnp.pad(array._logical(), pad_width, mode=mode, **kw)
    return _wrap_logical(res, array.split, array)


def ravel(a: DNDarray) -> DNDarray:
    """Flattened view (reference ``:1680``)."""
    return flatten(a)


def redistribute(arr: DNDarray, lshape_map=None, target_map=None) -> DNDarray:
    """Out-of-place redistribute (reference ``:1740``): canonical layout is
    XLA-managed; this validates and returns a copy."""
    from . import memory

    out = memory.copy(arr)
    out.redistribute_(lshape_map, target_map)
    return out


def repeat(a: DNDarray, repeats, axis: Optional[int] = None) -> DNDarray:
    """Repeat elements (reference ``:1770``).

    Scalar repeats on a distributed array stay gather-free: along the split
    axis every row fans out through a destination-scatter ring
    (:mod:`heat_tpu.core._manips`); along other axes the repeat is
    shard-local; ``axis=None`` flattens first (ring reshape). Array-valued
    ``repeats`` produce data-dependent shapes and use the logical path."""
    # normalize + validate repeats ONCE for every path below (numpy-parity
    # checks jnp.repeat skips: non-negativity, 1-D counts, length matching
    # the repeat target; size-1 arrays broadcast like scalars)
    if isinstance(repeats, DNDarray):
        repeats = np.asarray(repeats._logical())
    if not isinstance(repeats, (int, np.integer)) or isinstance(repeats, bool):
        arr = np.asarray(repeats)
        if arr.ndim == 0 or (arr.ndim == 1 and arr.size == 1):
            repeats = int(arr.reshape(-1)[0]) if arr.size else arr
        else:
            if arr.size and (arr < 0).any():
                raise ValueError("repeats must be non-negative")
            target = (a.size if axis is None
                      else a.shape[sanitize_axis(a.shape, axis)])
            if arr.ndim != 1 or arr.size != target:
                raise ValueError(
                    f"repeats shape {arr.shape} does not match the repeat "
                    f"target length {target}")
            repeats = arr
    scalar_rep = isinstance(repeats, (int, np.integer))
    if scalar_rep and repeats < 0:
        raise ValueError("repeats must be non-negative")
    if scalar_rep and repeats > 0 and a.split is not None \
            and a.comm.size > 1 and a.size > 0:
        if axis is None:
            flat = a if a.ndim == 1 and a.split == 0 else flatten(a)
            return repeat(flat, repeats, 0)
        axis = sanitize_axis(a.shape, axis)
        if axis != a.split:
            res = jnp.repeat(a.larray, repeats, axis=axis)
            gshape = tuple(
                s * repeats if i == axis else s for i, s in enumerate(a.gshape)
            )
            return DNDarray(res, gshape, a.dtype, a.split, a.device, a.comm)
        from . import _manips

        n = a.shape[axis]
        comm = a.comm
        c_out = comm.chunk_size(n * repeats)
        fn = _manips.ring_repeat_fn(
            a.larray.shape, jnp.dtype(a.larray.dtype), axis, n, int(repeats),
            c_out, comm)
        gshape = tuple(
            s * repeats if i == axis else s for i, s in enumerate(a.gshape))
        return DNDarray(fn(a.larray), gshape, a.dtype, axis, a.device, comm)
    if not scalar_rep and a.split is not None and a.comm.size > 1 \
            and a.size > 0:
        # array-valued repeats: the counts are axis-length METADATA (the
        # reference keeps them host-side too, ``:1770``), already
        # validated above; the data itself stays distributed. Along the
        # split axis the output is a gather-free fancy index by the
        # cumulative-count source map; other axes are shard-local with a
        # static total length.
        reps = repeats
        if axis is None:
            flat = a if a.ndim == 1 and a.split == 0 else flatten(a)
            return repeat(flat, reps, 0)
        axis = sanitize_axis(a.shape, axis)
        total = int(reps.sum())
        if axis != a.split:
            res = jnp.repeat(
                a.larray, jnp.asarray(reps), axis=axis,
                total_repeat_length=total)
            gshape = tuple(
                total if i == axis else s for i, s in enumerate(a.gshape))
            return DNDarray(res, gshape, a.dtype, a.split, a.device, a.comm)
        if total == 0:  # empty result — no data movement needed
            gshape = tuple(
                0 if i == axis else s for i, s in enumerate(a.gshape))
            return factories.empty(
                gshape, dtype=a.dtype, split=a.split, device=a.device,
                comm=a.comm)
        # source map computed ON DEVICE, split over the mesh (O(total/p)
        # per device): output position i reads source row
        # searchsorted(cumsum(reps), i, 'right'). Only the axis-length
        # counts ever live host-side; a host np.repeat here would
        # materialize the full output-length index.
        pos = factories.arange(total, split=0, device=a.device, comm=a.comm)
        cum = jnp.cumsum(jnp.asarray(reps, pos.larray.dtype))
        src_phys = jnp.searchsorted(cum, pos.larray, side="right").astype(
            pos.larray.dtype)
        src = DNDarray(src_phys, (total,),
                       types.canonical_heat_type(src_phys.dtype), 0,
                       a.device, a.comm)
        key = (slice(None),) * axis + (src,)
        return a[key]
    res = jnp.repeat(a._logical(), repeats, axis=axis)
    if axis is None:
        out_split = 0 if a.split is not None else None
    else:
        out_split = a.split
    return _wrap_logical(res, out_split, a)


def reshape(a: DNDarray, *shape, new_split=None, **kwargs) -> DNDarray:
    """Reshape to a new global shape (reference ``:1817``).

    The reference implements this with an Alltoallv over row-block
    boundaries; here the logical array is reshaped and re-sharded by XLA
    (the all-to-all is generated by the partitioner when needed).
    """
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    shape = tuple(int(s) for s in shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape = tuple(a.size // known if s == -1 else s for s in shape)
    if int(np.prod(shape)) != a.size:
        raise ValueError(f"cannot reshape array of size {a.size} into shape {shape}")
    if new_split is None:
        new_split = a.split if a.split is None else builtins_min(a.split, len(shape) - 1)
    if (
        a.split is not None
        and a.comm.size > 1
        and a.size > 0
        and len(shape) > 0
        and a.ndim > 0
    ):
        # distributed re-chunking of the row-major flat sequence (reference's
        # Alltoallv formulation): resplit to rows, ring-exchange flat ranges,
        # resplit to the target split — never materializes the logical array.
        # Both resplits run through the explicit reshard planner
        # (core/resharding.py): each is ONE all_to_all + local reslice, so
        # the whole reshape path stays all-gather-free end to end
        from . import _manips

        src = a if a.split == 0 else a.resplit(0)
        c_out = a.comm.chunk_size(shape[0])
        r_in = int(np.prod(src.larray.shape[1:], dtype=np.int64))
        r_out = int(np.prod(shape[1:], dtype=np.int64))
        c_in = src.larray.shape[0] // a.comm.size
        if c_in * r_in == c_out * r_out:
            # per-device flat ranges coincide (e.g. flatten of split=0, or
            # folding trailing dims): the reshape is purely shard-local — no
            # ring needed (review finding: the ring wasted (p-1)x shard
            # traffic here). Pin the output sharding so XLA keeps it local.
            new_phys = (c_out * a.comm.size,) + tuple(shape[1:])
            phys = jax.jit(
                lambda t: t.reshape(new_phys),
                out_shardings=a.comm.sharding(len(shape), 0))(src.larray)
            res = DNDarray(phys, shape, a.dtype, 0, a.device, a.comm)
        else:
            fn = _manips.ring_reshape_fn(
                src.larray.shape, jnp.dtype(src.larray.dtype), shape, c_out,
                a.comm)
            res = DNDarray(fn(src.larray), shape, a.dtype, 0, a.device, a.comm)
        if new_split != 0:
            res = res.resplit(new_split)
        return res
    res = a._logical().reshape(shape)
    return _wrap_logical(res, new_split, a)


def builtins_min(a, b):
    return a if a < b else b


def resplit(arr: DNDarray, axis=None) -> DNDarray:
    """Out-of-place split change (reference ``:3325``)."""
    return arr.resplit(axis)


def roll(x: DNDarray, shift, axis=None) -> DNDarray:
    """Circular shift (reference ``:1985``).

    Non-split axes roll shard-locally; the split axis rolls through the
    destination-scatter ring (:mod:`heat_tpu.core._manips`) — the
    static-shape rendering of the reference's rank-to-rank shard rotation.
    """
    if axis is None:
        if x.ndim == 1 and x.split == 0:
            total = sum(shift) if isinstance(shift, (tuple, list)) else shift
            return roll(x, total, 0)
        res = jnp.roll(x._logical().reshape(-1), shift).reshape(x.shape)
        return _wrap_logical(res, x.split, x)
    axes = ((int(axis),) if isinstance(axis, (int, np.integer))
            else tuple(int(ax) for ax in axis))
    shifts = ((int(shift),) * len(axes) if isinstance(shift, (int, np.integer))
              else tuple(int(s) for s in shift))
    if len(shifts) != len(axes):
        raise ValueError("shift and axis must have the same length")
    axes = tuple(sanitize_axis(x.shape, ax) for ax in axes)
    if x.split is not None and x.split in axes:
        if x.comm.size > 1 and x.shape[x.split] > 0:
            from . import _manips

            split_shift = sum(s for s, ax in zip(shifts, axes) if ax == x.split)
            other = [(s, ax) for s, ax in zip(shifts, axes) if ax != x.split]
            phys = x.larray
            if other:
                phys = jnp.roll(phys, [s for s, _ in other],
                                [ax for _, ax in other])
            fn = _manips.ring_roll_fn(
                phys.shape, jnp.dtype(phys.dtype), x.split,
                x.shape[x.split], split_shift, x.comm)
            return DNDarray(fn(phys), x.gshape, x.dtype, x.split, x.device,
                            x.comm)
        res = jnp.roll(x._logical(), shifts, axes)
        return _wrap_logical(res, x.split, x)
    res = jnp.roll(x.larray, shifts, axes)
    return DNDarray(res, x.gshape, x.dtype, x.split, x.device, x.comm)


def rot90(m: DNDarray, k: int = 1, axes: Sequence[int] = (0, 1)) -> DNDarray:
    """Rotate in a plane (reference ``:2100``): composed from the
    distributed flip (window fetch) and transpose (local split remap) —
    numpy's own decomposition — so split arrays never materialize."""
    axes = tuple(sanitize_axis(m.shape, ax) for ax in axes)
    if len(axes) != 2 or axes[0] == axes[1]:
        raise ValueError("len(axes) must be 2 and they must differ")
    k = k % 4
    if m.split is not None and m.comm.size > 1 and m.size > 0:
        from .linalg import transpose

        if k == 0:
            from . import memory

            return memory.copy(m)
        if k == 2:
            # one flip call: the non-split axis flips shard-locally and the
            # split axis does a single window pass
            return flip(m, axes)
        order = list(range(m.ndim))
        order[axes[0]], order[axes[1]] = order[axes[1]], order[axes[0]]
        if k == 1:
            return transpose(flip(m, axes[1]), order)
        return flip(transpose(m, order), axes[1])  # k == 3
    res = jnp.rot90(m._logical(), k=k, axes=axes)
    out_split = m.split
    if out_split in axes and k % 4 != 0:
        out_split = axes[0] if m.split == axes[1] else axes[1]
        if k % 2 == 0:
            out_split = m.split
    return _wrap_logical(res, out_split, m)


def row_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack arrays as rows (reference family)."""
    prepped = [a.reshape((1, a.shape[0])) if a.ndim == 1 else a for a in arrays]
    return concatenate(prepped, axis=0)


def shape(a: DNDarray) -> Tuple[int, ...]:
    """Global shape (reference ``:2240``)."""
    return a.shape


def sort(a: DNDarray, axis: int = -1, descending: bool = False, out=None):
    """Sort along an axis (reference ``:2263``).

    Along a split axis this runs the distributed block merge-split network
    (:mod:`heat_tpu.core._sort`) — the static-shape XLA equivalent of the
    reference's parallel sample-sort: local sort, then ``O(log^2 p)``
    pairwise ``ppermute`` merge-split rounds; no all-gather of the sort
    axis, O(chunk) memory per device. Sentinels in the padding sort to the
    trailing global positions, so the result lands back in canonical
    layout. Returns ``(values, indices)`` like the reference; ``indices``
    are global positions along ``axis`` into the original array.
    """
    axis = sanitize_axis(a.shape, axis)
    if a.split == axis and a.comm.size > 1 and a.shape[axis] > 0:
        from ._sort import distributed_sort_fn

        fn = distributed_sort_fn(
            a.larray.shape, jnp.dtype(a.larray.dtype), axis, a.shape[axis],
            descending, a.comm)
        values, idx = fn(a.larray)
    else:
        if a.split == axis and a.pad:
            sentinel = _sort_sentinel(a, descending)
            physical = a.filled(sentinel)
        else:
            physical = a.larray
        idx = jnp.argsort(physical, axis=axis, descending=descending)
        values = jnp.take_along_axis(physical, idx, axis=axis)
    vals = DNDarray(values, a.gshape, a.dtype, a.split, a.device, a.comm)
    indices = DNDarray(idx, a.gshape, types.canonical_heat_type(idx.dtype), a.split, a.device, a.comm)
    if out is not None:
        out.larray = vals.larray
        return out, indices
    return vals, indices


def _sort_sentinel(a: DNDarray, descending: bool):
    from . import statistics

    if descending:
        return statistics._max_neutral(a)
    return statistics._min_neutral(a)


def array_split(x: DNDarray, indices_or_sections, axis: int = 0) -> List[DNDarray]:
    """Split into sub-arrays, allowing unequal sections (NumPy-parity extra;
    the reference ships only the exact-division ``split`` family)."""
    axis = sanitize_axis(x.shape, axis)
    if isinstance(indices_or_sections, DNDarray):
        indices_or_sections = indices_or_sections.numpy().tolist()
    elif isinstance(indices_or_sections, (np.ndarray, jnp.ndarray)):
        indices_or_sections = np.asarray(indices_or_sections).tolist()
    if isinstance(indices_or_sections, (int, np.integer)):
        n, k = x.shape[axis], int(indices_or_sections)
        if k <= 0:
            raise ValueError("number sections must be larger than 0")
        sizes = [n // k + 1] * (n % k) + [n // k] * (k - n % k)
        indices_or_sections = list(np.cumsum(sizes[:-1]))
    return split(x, indices_or_sections, axis=axis)


def split(x: DNDarray, indices_or_sections, axis: int = 0) -> List[DNDarray]:
    """Split into sub-arrays (reference ``:2450``)."""
    axis = sanitize_axis(x.shape, axis)
    if isinstance(indices_or_sections, DNDarray):
        indices_or_sections = indices_or_sections.numpy().tolist()
    elif isinstance(indices_or_sections, (np.ndarray, jnp.ndarray)):
        indices_or_sections = np.asarray(indices_or_sections).tolist()
    logical = x._logical()
    parts = jnp.split(logical, indices_or_sections, axis=axis)
    return [_wrap_logical(p, x.split, x) for p in parts]


def squeeze(x: DNDarray, axis=None) -> DNDarray:
    """Remove size-1 dimensions (reference ``:2620``)."""
    if axis is not None:
        axes = (sanitize_axis(x.shape, axis),) if isinstance(axis, int) else tuple(
            sanitize_axis(x.shape, ax) for ax in axis
        )
        for ax in axes:
            if x.shape[ax] != 1:
                raise ValueError(f"cannot select an axis to squeeze out which has size not equal to one, got axis {ax}")
    else:
        axes = tuple(i for i, s in enumerate(x.shape) if s == 1)
    if x.split is not None and x.split in axes:
        x = x.resplit(None)
    res = jnp.squeeze(x.larray, axis=axes if axes else None)
    out_split = x.split
    if out_split is not None:
        out_split -= sum(1 for ax in axes if ax < out_split)
    gshape = tuple(s for i, s in enumerate(x.shape) if i not in axes)
    return DNDarray(res, gshape, x.dtype, out_split, x.device, x.comm)


def stack(arrays: Sequence[DNDarray], axis: int = 0, out=None) -> DNDarray:
    """Join along a new axis (reference ``:2720``): expand_dims (local) +
    concatenate — the new axis is unsharded, so matching-split inputs join
    shard-locally."""
    arrays = list(arrays)
    shapes = {a.shape for a in arrays}
    if len(shapes) != 1:
        raise ValueError(f"all input arrays must have the same shape, got {shapes}")
    axis = sanitize_axis(tuple([len(arrays)] + list(arrays[0].shape)), axis)
    base_split = arrays[0].split
    if (
        base_split is not None
        and arrays[0].comm.size > 1
        and all(a.split == base_split for a in arrays)
        and arrays[0].size > 0
    ):
        result = concatenate([expand_dims(a, axis) for a in arrays], axis)
    else:
        logicals = [a._logical() for a in arrays]
        res = jnp.stack(logicals, axis=axis)
        out_split = None
        if base_split is not None:
            out_split = base_split + (1 if axis <= base_split else 0)
        result = _wrap_logical(res, out_split, arrays[0])
    if out is not None:
        from . import _operations

        # the op engine's counted alignment helper: out-buffer sanitation,
        # a recorded/counted resplit (op_engine.align_resplits) and the
        # dtype cast — the raw ``result.resplit(out.split).larray`` here
        # bypassed both the counter and the shape check
        return _operations._finalize(result, out)
    return result


def swapaxes(x: DNDarray, axis1: int, axis2: int) -> DNDarray:
    """Interchange two axes (reference ``:2850``)."""
    from .linalg import transpose

    axes = list(range(x.ndim))
    axis1 = sanitize_axis(x.shape, axis1)
    axis2 = sanitize_axis(x.shape, axis2)
    axes[axis1], axes[axis2] = axes[axis2], axes[axis1]
    return transpose(x, axes)


def _tile_distributed(x: DNDarray, reps) -> Optional[DNDarray]:
    """Gather-free tile when no new leading dims appear: non-split axes tile
    shard-locally, the split axis tiles as a ring concatenation of ``r``
    copies (reference ``tile``, ``manipulations.py:3574``)."""
    reps = (reps,) if isinstance(reps, (int, np.integer)) else tuple(reps)
    if x.split is None or x.comm.size <= 1 or x.size == 0 or \
            len(reps) > x.ndim or any(int(r) <= 0 for r in reps):
        return None
    reps = (1,) * (x.ndim - len(reps)) + tuple(int(r) for r in reps)
    split = x.split
    r_split = reps[split]
    other = tuple(1 if i == split else r for i, r in enumerate(reps))
    phys = jnp.tile(x.larray, other) if any(r != 1 for r in other) else x.larray
    gshape = tuple(s * other[i] for i, s in enumerate(x.gshape))
    base = DNDarray(phys, gshape, x.dtype, split, x.device, x.comm)
    if r_split == 1:
        return base
    return concatenate([base] * r_split, axis=split)


def tile(x: DNDarray, reps) -> DNDarray:
    """Tile an array (reference ``:3574``). Same-rank tilings of distributed
    arrays run shard-local + ring concat (:func:`_tile_distributed`);
    rank-raising tilings (new leading dims) use the logical path."""
    if isinstance(reps, DNDarray):
        reps = reps.numpy().tolist()
    dist = _tile_distributed(x, reps)
    if dist is not None:
        return dist
    res = jnp.tile(x._logical(), reps)
    out_split = x.split
    if out_split is not None:
        out_split = out_split + (res.ndim - x.ndim)
    return _wrap_logical(res, out_split, x)


def topk(a: DNDarray, k: int, dim: int = -1, largest: bool = True, sorted: bool = True, out=None):
    """Top-k values and indices (reference ``:3830``; custom MPI op
    ``mpi_topk`` ``:3971``).

    Along a split axis this is the reference's tournament, XLA-style: local
    ``lax.top_k`` per shard, an all-gather of the ``p*k`` candidates (O(p k)
    bytes, never the data), and a final ``top_k``
    (:func:`heat_tpu.core._manips.split_topk_fn`)."""
    dim = sanitize_axis(a.shape, dim)
    if a.split == dim and a.comm.size > 1 and 0 < k <= a.shape[dim]:
        from . import _manips

        fn = _manips.split_topk_fn(
            a.larray.shape, jnp.dtype(a.larray.dtype), dim, a.shape[dim],
            int(k), bool(largest), a.comm)
        vals_rep, idx_rep = fn(a.larray)
        vals = jnp.moveaxis(vals_rep, -1, dim)
        idx = jnp.moveaxis(idx_rep, -1, dim)
        vals_d = _wrap_logical(vals, a.split, a)
        idx_d = _wrap_logical(idx, a.split, a)
        if out is not None:
            out[0].larray = vals_d.larray
            out[1].larray = idx_d.larray
            return out
        return vals_d, idx_d
    if a.split == dim:
        logical = a._logical()
    else:
        logical = a.larray
    moved = jnp.moveaxis(logical, dim, -1)
    if largest:
        vals, idx = jax.lax.top_k(moved, k)
    else:
        # negation is not order-reversing for unsigned ints (modular wrap at
        # 0); select indices on a signed/float view, gather original values
        neg_src = moved.astype(jnp.int64) if jnp.issubdtype(moved.dtype, jnp.unsignedinteger) else moved
        _, idx = jax.lax.top_k(-neg_src, k)
        vals = jnp.take_along_axis(moved, idx, axis=-1)
    vals = jnp.moveaxis(vals, -1, dim)
    idx = jnp.moveaxis(idx, -1, dim).astype(jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)
    gshape = tuple(k if i == dim else s for i, s in enumerate(a.shape))
    if a.split == dim:
        vals_d = _wrap_logical(vals, a.split, a)
        idx_d = _wrap_logical(idx, a.split, a)
    else:
        vals_d = DNDarray(vals, gshape, a.dtype, a.split, a.device, a.comm)
        idx_d = DNDarray(idx, gshape, types.canonical_heat_type(idx.dtype), a.split, a.device, a.comm)
    if out is not None:
        out[0].larray = vals_d.larray
        out[1].larray = idx_d.larray
        return out
    return vals_d, idx_d


def union1d(ar1: DNDarray, ar2: DNDarray) -> DNDarray:
    """Sorted union of two arrays (``numpy.union1d``): one distributed
    unique over the concatenated (flattened) inputs."""
    from . import factories

    if not isinstance(ar1, DNDarray):
        ar1 = factories.array(ar1)
    if not isinstance(ar2, DNDarray):
        ar2 = factories.array(ar2, comm=ar1.comm)
    return unique(concatenate([flatten(ar1), flatten(ar2)], axis=0),
                  sorted=True)


def intersect1d(ar1: DNDarray, ar2, assume_unique: bool = False) -> DNDarray:
    """Sorted intersection (``numpy.intersect1d``): distributed unique +
    membership selection (stays split). ``assume_unique=True`` skips the
    unique pass; the result is sorted either way, like numpy."""
    from . import logical, factories

    if not isinstance(ar1, DNDarray):
        ar1 = factories.array(ar1)
    if assume_unique:
        sel = flatten(ar1)
        return sort(sel[logical.isin(sel, ar2)], axis=0)[0]
    u = unique(flatten(ar1), sorted=True)
    return u[logical.isin(u, ar2)]


def setdiff1d(ar1: DNDarray, ar2, assume_unique: bool = False) -> DNDarray:
    """Sorted values of ``ar1`` not in ``ar2`` (``numpy.setdiff1d``).
    ``assume_unique=True`` skips the unique pass and preserves input
    order, like numpy."""
    from . import logical, factories

    if not isinstance(ar1, DNDarray):
        ar1 = factories.array(ar1)
    u = (flatten(ar1) if assume_unique
         else unique(flatten(ar1), sorted=True))
    return u[logical.isin(u, ar2, invert=True)]


def setxor1d(ar1: DNDarray, ar2, assume_unique: bool = False) -> DNDarray:
    """Sorted symmetric difference (``numpy.setxor1d``): elements of the
    concatenated per-input uniques that appear exactly once.
    ``assume_unique=True`` skips the per-input unique passes."""
    from . import factories

    if not isinstance(ar1, DNDarray):
        ar1 = factories.array(ar1)
    if not isinstance(ar2, DNDarray):
        ar2 = factories.array(ar2, comm=ar1.comm)
    if assume_unique:
        u1, u2 = flatten(ar1), flatten(ar2)
    else:
        u1 = unique(flatten(ar1), sorted=True)
        u2 = unique(flatten(ar2), sorted=True)
    both = concatenate([u1, u2], axis=0)
    u, counts = unique(both, sorted=True, return_counts=True)
    return u[counts == 1]


def unique(a: DNDarray, sorted: bool = False, return_inverse: bool = False,
           axis: Optional[int] = None, return_counts: bool = False):
    """Unique elements (reference ``:3051``; ``return_counts`` exceeds the
    reference's signature, matching numpy's).

    Split arrays run the fully distributed pipelines
    (:mod:`heat_tpu.core._setops`: network sort → ppermute halo compare →
    psum'd unique count → network compaction; row-lexicographic variant for
    ``axis=``; ndim>1 flattens through the distributed reshape), never
    gathering the array; results are split and always sorted. Complex
    dtypes with ``axis=`` keep the logical path.
    """
    if (axis is None and a.split is not None and a.comm.size > 1
            and a.ndim == 1 and a.shape[0] > 0):
        from ._setops import distributed_unique

        return distributed_unique(a, return_inverse, return_counts)
    if (axis is None and a.split is not None
            and a.comm.size > 1 and a.ndim > 1 and a.size > 0):
        # numpy flattens for axis=None: the distributed flatten (ring
        # reshape) feeds the 1-D distributed pipeline; inverse indices ride
        # the same pipeline and reshape back to the input's shape (the
        # package's convention, matching the logical path below).
        from ._setops import distributed_unique

        res = distributed_unique(flatten(a), return_inverse, return_counts)
        if not return_inverse:
            return res
        out = list(res) if isinstance(res, tuple) else [res]
        out[1] = reshape(out[1], a.shape)
        return tuple(out)
    if (axis is not None and a.split is not None and a.comm.size > 1
            and a.size > 0
            and not jnp.issubdtype(a.larray.dtype, jnp.complexfloating)):
        ax = sanitize_axis(a.shape, axis)
        if a.ndim == 1:
            # unique(1-D, axis=0) == plain 1-D unique; use the scalar engine
            from ._setops import distributed_unique

            return distributed_unique(a, return_inverse, return_counts)
        # rows engine: move the unique axis to the front, flatten each slice
        # to a row, run the distributed lexicographic row pipeline
        # (reference ``:3051``; SURVEY.md §7 hard part 4 — closed round 4)
        from ._setops import distributed_unique_rows

        b = moveaxis(a, ax, 0) if ax != 0 else a
        if b.split != 0:
            b = b.resplit(0)
        n = b.shape[0]
        trailing = tuple(b.shape[1:])
        w = int(np.prod(trailing)) if trailing else 1
        rows = DNDarray(
            b.larray.reshape(b.larray.shape[0], w), (n, w), b.dtype, 0,
            b.device, b.comm)
        res = distributed_unique_rows(rows, return_inverse, return_counts)
        uniq = res[0]
        U = uniq.shape[0]
        out = DNDarray(
            uniq.larray.reshape((uniq.larray.shape[0],) + trailing),
            (U,) + trailing, b.dtype, 0, b.device, b.comm)
        if ax != 0:
            out = moveaxis(out, 0, ax)
        outs = [out] + list(res[1:])
        return tuple(outs) if len(outs) > 1 else out
    logical = a._logical()
    # equal_nan=False: each NaN is its own unique, matching the reference's
    # torch.unique semantics and the distributed pipeline (modern numpy
    # collapses NaNs by default)
    if return_inverse or return_counts:
        res, *rest = jnp.unique(
            logical, return_inverse=return_inverse,
            return_counts=return_counts, axis=axis, equal_nan=False)
        out = [_wrap_logical(res, None, a)]
        if return_inverse:
            inverse = rest.pop(0)
            out.append(_wrap_logical(
                inverse.reshape(logical.shape if axis is None else (-1,)), None, a))
        if return_counts:
            out.append(_wrap_logical(rest.pop(0), None, a))
        return tuple(out)
    res = jnp.unique(logical, axis=axis, equal_nan=False)
    return _wrap_logical(res, None, a)


def vsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    return split(x, indices_or_sections, axis=0)


def vstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack vertically (reference ``:3700``)."""
    prepped = [a.reshape((1, a.shape[0])) if a.ndim == 1 else a for a in arrays]
    return concatenate(prepped, axis=0)
