"""Distributed advanced indexing along the split axis (reference
``heat/core/dndarray.py:656-912`` getitem / ``:1363-1652`` setitem).

The reference translates global fancy indices to per-rank local ones and
moves rows point-to-point. The static-shape XLA rendering is a **systolic
ring**: the data (or the request/value pairs) rotate around the mesh in
``p`` ``ppermute`` steps, and each device keeps/applies the rows whose
global position falls in its range. O(chunk) memory per device, no
materialization of the logical global array — the round-1 VERDICT #5 fix
for "one fancy index = a full gather" at the 1B-point north star.

Three programs, all compiled per (shape, mesh):

- ``ring_gather_fn``  — ``x[idx]`` rows by integer array along the split
  axis (any permutation, with repeats).
- ``ring_compress_fn`` — ``x[mask]`` row compaction by a boolean mask on
  the split axis; output positions are a distributed prefix count, so each
  device's kept rows form a contiguous output range and a ``searchsorted``
  against the rotating block finds each output slot's source row.
- ``ring_scatter_fn`` — ``x[idx] = values``: (index, value-row) pairs
  rotate; each device applies the writes that target its rows with an
  out-of-bounds-drop scatter (duplicate indices resolve in rotation order,
  matching NumPy's "unspecified" contract).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from ._compat import shard_map

from ._sort import _index_dtype

__all__ = [
    "ring_gather_fn",
    "ring_compress_fn",
    "ring_scatter_fn",
    "mask_positions_fn",
]

_IDX_CACHE: dict = {}


def _row_mask(hit, row_ndim):
    return hit.reshape(hit.shape + (1,) * row_ndim)


def ring_gather_fn(phys_shape, jdt, axis: int, c_out: int, comm):
    """Jitted ``(x_physical, idx_physical) -> rows_physical``.

    ``idx_physical``: 1-D int array of physical length ``p * c_out``, split
    at 0, holding global row positions along ``axis`` (entries < 0 are
    treated as invalid and produce zero rows — callers encode padding that
    way)."""
    key = ("rgather", tuple(phys_shape), str(jdt), axis, c_out, comm.cache_key)
    fn = _IDX_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c = phys_shape[axis] // p

    def body(xb, ib):
        buf = jnp.moveaxis(xb, axis, 0)  # (c, rest...)
        me = jax.lax.axis_index(comm.axis_name)
        out = jnp.zeros((c_out,) + buf.shape[1:], buf.dtype)
        for k in range(p):
            owner = (me - k) % p  # original owner of the block in ``buf``
            rel = ib - owner * c
            hit = (rel >= 0) & (rel < c) & (ib >= 0)
            take = jnp.take(buf, jnp.clip(rel, 0, c - 1), axis=0)
            out = jnp.where(_row_mask(hit, buf.ndim - 1), take, out)
            if k < p - 1:
                buf = comm.ring_shift(buf, 1)
        return jnp.moveaxis(out, 0, axis)

    spec_x = comm.spec(len(phys_shape), axis)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=(spec_x, comm.spec(1, 0)),
                  out_specs=spec_x, check_vma=False)
    )
    _IDX_CACHE[key] = fn
    return fn


def mask_positions_fn(c: int, comm):
    """Jitted ``mask_physical -> (out_pos_physical, count)``: the output
    slot of each kept row (global prefix count over the mesh; ``-1`` where
    the mask is False), plus the global number kept."""
    key = ("mpos", c, comm.cache_key)
    fn = _IDX_CACHE.get(key)
    if fn is not None:
        return fn
    idt = _index_dtype()

    def body(mb):
        cnt = jnp.sum(mb.astype(idt))
        offs = comm.exscan(cnt)
        pos = jnp.where(mb, offs + jnp.cumsum(mb.astype(idt)) - 1,
                        jnp.asarray(-1, idt))
        total = jax.lax.psum(cnt, comm.axis_name)
        return pos, total

    spec = comm.spec(1, 0)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=spec,
                  out_specs=(spec, comm.spec(0, None)), check_vma=False)
    )
    _IDX_CACHE[key] = fn
    return fn


def ring_compress_fn(phys_shape, jdt, axis: int, m: int, c_out: int, comm):
    """Jitted ``(x_physical, out_pos_physical) -> compacted_physical``.

    ``out_pos`` (from :func:`mask_positions_fn`) holds each kept row's output
    slot and ``-1`` for dropped rows — it is NOT monotone (dropped rows are
    interleaved), so it cannot be binary-searched directly. Instead each
    step rebuilds the block's monotone inclusive prefix count
    ``s[i] = offs + #kept rows <= i`` (``offs`` = the block's first output
    slot): row ``i`` serves output slot ``q`` iff ``kept[i]`` and
    ``s[i] == q + 1``, and ``searchsorted(s, q + 1, side='left')`` lands on
    exactly that row because ``s`` first reaches ``q + 1`` where the count
    increments."""
    key = ("rcompress", tuple(phys_shape), str(jdt), axis, m, c_out,
           comm.cache_key)
    fn = _IDX_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c = phys_shape[axis] // p
    idt = _index_dtype()

    def body(xb, pb):
        buf = jnp.moveaxis(xb, axis, 0)  # (c, rest...)
        me = jax.lax.axis_index(comm.axis_name)
        qs = me * c_out + jnp.arange(c_out, dtype=idt)  # my output slots
        out = jnp.zeros((c_out,) + buf.shape[1:], buf.dtype)
        for k in range(p):
            kept = pb >= 0
            csum = jnp.cumsum(kept.astype(idt))
            # every kept row agrees on the block offset pb - (csum - 1);
            # a block with no kept rows never hits, so 0 is a safe fill
            offs = jnp.max(jnp.where(kept, pb - csum + 1, 0))
            s = offs + csum  # non-decreasing
            rel = jnp.searchsorted(s, qs + 1, side="left").astype(idt)
            relc = jnp.clip(rel, 0, c - 1)
            hit = ((rel < c) & jnp.take(kept, relc)
                   & (jnp.take(s, relc) == qs + 1) & (qs < m))
            take = jnp.take(buf, relc, axis=0)
            out = jnp.where(_row_mask(hit, buf.ndim - 1), take, out)
            if k < p - 1:
                buf = comm.ring_shift(buf, 1)
                pb = comm.ring_shift(pb, 1)
        return jnp.moveaxis(out, 0, axis)

    spec_x = comm.spec(len(phys_shape), axis)
    out_shape = list(phys_shape)
    out_shape[axis] = c_out * p
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=(spec_x, comm.spec(1, 0)),
                  out_specs=spec_x, check_vma=False)
    )
    _IDX_CACHE[key] = fn
    return fn


def ring_scatter_fn(phys_shape, jdt, axis: int, c_in: int, comm):
    """Jitted ``(x_physical, idx_physical, value_rows_physical) -> updated``.

    (index, value-row) pairs are split at 0 with chunk ``c_in`` and rotate
    around the ring; each device applies the writes landing in its row
    range via an OOB-drop scatter. Negative indices mark padding (no-op).
    """
    key = ("rscatter", tuple(phys_shape), str(jdt), axis, c_in, comm.cache_key)
    fn = _IDX_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c = phys_shape[axis] // p

    def body(xb, ib, vb):
        buf = jnp.moveaxis(xb, axis, 0)  # (c, rest...)
        me = jax.lax.axis_index(comm.axis_name)
        for k in range(p):
            rel = ib - me * c
            hit = (rel >= 0) & (rel < c) & (ib >= 0)
            # OOB-drop scatter: misses write to row index c, which is
            # outside the block and silently dropped
            tgt = jnp.where(hit, rel, c)
            buf = buf.at[tgt].set(vb, mode="drop")
            if k < p - 1:
                ib = comm.ring_shift(ib, 1)
                vb = comm.ring_shift(vb, 1)
        return jnp.moveaxis(buf, 0, axis)

    spec_x = comm.spec(len(phys_shape), axis)
    vspec = comm.spec(len(phys_shape), 0)
    fn = jax.jit(
        shard_map(body, mesh=comm.mesh,
                  in_specs=(spec_x, comm.spec(1, 0), vspec),
                  out_specs=spec_x, check_vma=False)
    )
    _IDX_CACHE[key] = fn
    return fn
