"""Arithmetic operations (reference ``heat/core/arithmetics.py:63-989``).

Every function funnels through the op engine in ``_operations.py``; local
compute is a fused XLA kernel, cross-device reduction is a GSPMD ``psum``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = [
    "add",
    "bitwise_and",
    "bitwise_not",
    "bitwise_or",
    "bitwise_xor",
    "cumprod",
    "cumproduct",
    "cumsum",
    "diff",
    "div",
    "divide",
    "ediff1d",
    "floor_divide",
    "floordiv",
    "fmod",
    "heaviside",
    "invert",
    "left_shift",
    "mod",
    "mul",
    "multiply",
    "nancumprod",
    "nancumsum",
    "neg",
    "negative",
    "pos",
    "positive",
    "pow",
    "power",
    "prod",
    "remainder",
    "right_shift",
    "sub",
    "subtract",
    "sum",
]


def add(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise addition (reference ``arithmetics.py:63``)."""
    return _operations._binary_op(jnp.add, t1, t2, out, where)


def bitwise_and(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise AND of integer/bool arrays (reference ``:121``)."""
    _check_int_args(t1, t2, "bitwise_and")
    return _operations._binary_op(jnp.bitwise_and, t1, t2, out, where)


def bitwise_or(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise OR (reference ``:175``)."""
    _check_int_args(t1, t2, "bitwise_or")
    return _operations._binary_op(jnp.bitwise_or, t1, t2, out, where)


def bitwise_xor(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise XOR (reference ``:229``)."""
    _check_int_args(t1, t2, "bitwise_xor")
    return _operations._binary_op(jnp.bitwise_xor, t1, t2, out, where)


def _check_int_args(t1, t2, name):
    for t in (t1, t2):
        if isinstance(t, DNDarray) and types.heat_type_is_inexact(t.dtype):
            raise TypeError(f"{name} is only supported for integer or boolean arrays")
        if isinstance(t, float):
            raise TypeError(f"{name} is only supported for integer or boolean operands")


def cumprod(a: DNDarray, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative product along ``axis`` (reference ``:283``)."""
    return _operations._cum_op(a, jnp.cumprod, axis, 1, out, dtype)


cumproduct = cumprod


def cumsum(a: DNDarray, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative sum along ``axis`` (reference ``:330``)."""
    return _operations._cum_op(a, jnp.cumsum, axis, 0, out, dtype)


def nancumsum(a: DNDarray, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative sum treating NaNs as zero (``numpy.nancumsum``)."""
    from . import types as _t
    from .statistics import _nan_filled

    if not _t.heat_type_is_inexact(a.dtype):
        return cumsum(a, axis, dtype=dtype, out=out)
    return cumsum(_nan_filled(a, 0.0), axis, dtype=dtype, out=out)


def nancumprod(a: DNDarray, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative product treating NaNs as one (``numpy.nancumprod``)."""
    from . import types as _t
    from .statistics import _nan_filled

    if not _t.heat_type_is_inexact(a.dtype):
        return cumprod(a, axis, dtype=dtype, out=out)
    return cumprod(_nan_filled(a, 1.0), axis, dtype=dtype, out=out)


def ediff1d(ary: DNDarray, to_end=None, to_begin=None) -> DNDarray:
    """Differences of the flattened array (``numpy.ediff1d``), with the
    optional prepend/append tails."""
    from . import manipulations, factories

    flat = manipulations.flatten(ary)
    d = diff(flat)

    def _tail(v, name):
        arr = np.ravel(np.asarray(v))
        # numpy raises for incompatible tail dtypes (same_kind rule)
        # instead of silently truncating, e.g. float tails on int input
        if not np.can_cast(arr.dtype, np.dtype(d.dtype.jax_type()),
                           casting="same_kind"):
            raise TypeError(
                f"dtype of {name} ({arr.dtype}) is not compatible with the "
                f"difference dtype ({d.dtype}) under the same_kind rule")
        return factories.array(arr, dtype=d.dtype, comm=ary.comm)

    parts = []
    if to_begin is not None:
        parts.append(_tail(to_begin, "to_begin"))
    parts.append(d)
    if to_end is not None:
        parts.append(_tail(to_end, "to_end"))
    return manipulations.concatenate(parts, axis=0) if len(parts) > 1 else d


def diff(a: DNDarray, n: int = 1, axis: int = -1, prepend=None, append=None) -> DNDarray:
    """n-th discrete difference along ``axis``, with optional values
    prepended/appended before differencing (reference ``:377``)."""
    from .dndarray import DNDarray as _D
    from .stride_tricks import sanitize_axis

    if n == 0:
        return a
    if n < 0:
        raise ValueError(f"diff requires that n be a positive number, got {n}")
    axis = sanitize_axis(a.shape, axis)
    if a.split is not None and a.comm.size > 1 and a.shape[axis] > 1:
        from . import manipulations

        # fold prepend/append into the array (distributed concat), then
        # difference gather-free
        if prepend is not None or append is not None:
            for val, at_front in ((prepend, True), (append, False)):
                if val is None:
                    continue
                # promote, never truncate (numpy: diff(int, prepend=0.5) is
                # float) — review finding
                jv = val.larray if isinstance(val, _D) else jnp.asarray(val)
                pdt = jnp.promote_types(a.larray.dtype, jv.dtype)
                if jnp.dtype(pdt) != jnp.dtype(a.larray.dtype):
                    a = a.astype(types.canonical_heat_type(pdt))
                vd = val if isinstance(val, _D) else _D.from_logical(
                    jnp.asarray(val, pdt), None, a.device, a.comm)
                if isinstance(val, _D) and \
                        jnp.dtype(vd.larray.dtype) != jnp.dtype(pdt):
                    vd = vd.astype(types.canonical_heat_type(pdt))
                if vd.ndim == 0:
                    shp = tuple(1 if i == axis else s
                                for i, s in enumerate(a.gshape))
                    vd = vd.reshape(shp)
                pair = ([vd.resplit(a.split), a] if at_front
                        else [a, vd.resplit(a.split)])
                a = manipulations.concatenate(pair, axis=axis)
            return diff(a, n=n, axis=axis)
        if axis != a.split:
            # shard-local: the differenced axis is unsharded
            res = jnp.diff(a.larray, n=n, axis=axis)
            gshape = tuple(
                s - n if i == axis else s for i, s in enumerate(a.gshape))
            if gshape[axis] <= 0:
                return diff(a.resplit(None), n=n, axis=axis)
            return _D(res, gshape, a.dtype, a.split, a.device, a.comm)
        from . import _manips

        if a.shape[axis] - n <= 0:  # numpy: repeated diffs empty out
            gshape = tuple(0 if i == axis else s
                           for i, s in enumerate(a.gshape))
            return _D.from_logical(
                jnp.zeros(gshape, a.larray.dtype), None, a.device, a.comm,
                dtype=a.dtype)
        out = a
        for _ in range(n):
            fn = _manips.split_diff_fn(
                out.larray.shape, jnp.dtype(out.larray.dtype), axis,
                out.shape[axis], out.comm)
            gshape = tuple(
                s - 1 if i == axis else s for i, s in enumerate(out.gshape))
            out = _D(fn(out.larray), gshape, out.dtype, axis, out.device,
                     out.comm)
        return out
    logical = a._logical()
    kwargs = {}
    for name, val in (("prepend", prepend), ("append", append)):
        if val is not None:
            kwargs[name] = val._logical() if isinstance(val, _D) else jnp.asarray(val)
    res = jnp.diff(logical, n=n, axis=axis, **kwargs)
    split = a.split
    if split is not None and res.shape[split] == 0:
        split = None
    return DNDarray.from_logical(res, split, a.device, a.comm)


def div(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise true division (reference ``:443``)."""
    return _operations._binary_op(jnp.true_divide, t1, t2, out, where)


divide = div


def floordiv(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise floor division (reference ``:528``)."""
    return _operations._binary_op(jnp.floor_divide, t1, t2, out, where)


floor_divide = floordiv


def fmod(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise C-style remainder (reference ``:576``)."""
    return _operations._binary_op(jnp.fmod, t1, t2, out, where)


def invert(a: DNDarray, out=None) -> DNDarray:
    """Element-wise bitwise NOT (reference ``:624``)."""
    if types.heat_type_is_inexact(a.dtype):
        raise TypeError("invert is only supported for integer or boolean arrays")
    return _operations._local_op(jnp.invert, a, out)


bitwise_not = invert


def left_shift(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise left bit-shift (reference ``:664``)."""
    _check_int_args(t1, t2, "left_shift")
    return _operations._binary_op(jnp.left_shift, t1, t2, out, where)


def mod(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise Python-style modulo (reference ``:704``)."""
    return _operations._binary_op(jnp.mod, t1, t2, out, where)


remainder = mod


def mul(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise multiplication (reference ``:746``)."""
    return _operations._binary_op(jnp.multiply, t1, t2, out, where)


multiply = mul


def neg(a: DNDarray, out=None) -> DNDarray:
    """Element-wise negation (reference ``:788``)."""
    return _operations._local_op(jnp.negative, a, out)


negative = neg


def pos(a: DNDarray, out=None) -> DNDarray:
    """Element-wise unary plus (reference ``:820``)."""
    return _operations._local_op(jnp.positive, a, out)


positive = pos


def pow(t1, t2, out=None, where=None) -> DNDarray:  # noqa: A001
    """Element-wise exponentiation (reference ``:852``)."""
    return _operations._binary_op(jnp.power, t1, t2, out, where)


power = pow


def prod(a: DNDarray, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:
    """Product reduction (reference ``:902``): local product + ``psum``-style
    all-multiply when the split axis is reduced. Records onto the fusion
    tape (no ``pprod`` primitive exists, so the flush compiles the chain
    as one GSPMD program rather than an explicit shard_map collective)."""
    if keepdim is not None:  # reference/torch keyword name
        keepdims = keepdim
    return _operations._reduce_op(a, jnp.prod, 1, axis=axis, out=out, keepdims=keepdims)


def right_shift(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise right bit-shift (reference ``:922``)."""
    _check_int_args(t1, t2, "right_shift")
    return _operations._binary_op(jnp.right_shift, t1, t2, out, where)


def sub(t1, t2, out=None, where=None) -> DNDarray:
    """Element-wise subtraction (reference ``:962``)."""
    return _operations._binary_op(jnp.subtract, t1, t2, out, where)


subtract = sub


def sum(a: DNDarray, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:  # noqa: A001
    """Sum reduction (reference ``:946``): the canonical local-reduce +
    ``Allreduce`` stack of the reference (``_operations.py:440-445``) becomes
    one XLA program with a ``psum`` over the mesh — and the whole
    elementwise chain feeding it fuses into that same program
    (:func:`heat_tpu.core.fusion.record_reduce`), independent sums sharing
    one packed all-reduce."""
    if keepdim is not None:  # reference/torch keyword name
        keepdims = keepdim
    return _operations._reduce_op(a, jnp.sum, 0, axis=axis, out=out, keepdims=keepdims)


def heaviside(x1, x2, out=None) -> DNDarray:
    """Heaviside step function (``numpy.heaviside``)."""
    return _operations._binary_op(jnp.heaviside, x1, x2, out)
