"""Relational operations (reference ``heat/core/relational.py:35-420``)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = ["eq", "equal", "ge", "greater_equal", "gt", "greater", "le", "less_equal", "lt", "less", "ne", "not_equal"]


def eq(x, y) -> DNDarray:
    """Element-wise == (reference ``relational.py:35``)."""
    return _operations._binary_op(jnp.equal, x, y)


def equal(x, y) -> bool:
    """Global three-way equality: True iff all elements equal (reference ``:85``,
    implemented there as a local test + ``Allreduce(LAND)``; here the psum is
    implicit in the global ``all``)."""
    from . import logical
    from .stride_tricks import broadcast_shape

    if not isinstance(x, DNDarray) and not isinstance(y, DNDarray):
        return bool(jnp.all(jnp.equal(jnp.asarray(x), jnp.asarray(y))))
    try:
        broadcast_shape(
            x.shape if isinstance(x, DNDarray) else jnp.shape(x),
            y.shape if isinstance(y, DNDarray) else jnp.shape(y),
        )
    except ValueError:
        return False
    result = eq(x, y)
    return bool(logical.all(result).item())


def ge(x, y) -> DNDarray:
    """Element-wise >= (reference ``:131``)."""
    return _operations._binary_op(jnp.greater_equal, x, y)


greater_equal = ge


def gt(x, y) -> DNDarray:
    """Element-wise > (reference ``:189``)."""
    return _operations._binary_op(jnp.greater, x, y)


greater = gt


def le(x, y) -> DNDarray:
    """Element-wise <= (reference ``:247``)."""
    return _operations._binary_op(jnp.less_equal, x, y)


less_equal = le


def lt(x, y) -> DNDarray:
    """Element-wise < (reference ``:305``)."""
    return _operations._binary_op(jnp.less, x, y)


less = lt


def ne(x, y) -> DNDarray:
    """Element-wise != (reference ``:363``)."""
    return _operations._binary_op(jnp.not_equal, x, y)


not_equal = ne
