"""Relational operations (reference ``heat/core/relational.py:35-420``)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = ["eq", "equal", "ge", "greater_equal", "gt", "greater", "le", "less_equal", "lt", "less", "ne", "not_equal"]


def eq(t1, t2) -> DNDarray:
    """Element-wise == (reference ``relational.py:35``)."""
    return _operations._binary_op(jnp.equal, t1, t2)


def equal(t1, t2) -> bool:
    """Global three-way equality: True iff all elements equal (reference ``:85``,
    implemented there as a local test + ``Allreduce(LAND)``; here the psum is
    implicit in the global ``all``)."""
    from . import logical
    from .stride_tricks import broadcast_shape

    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        return bool(jnp.all(jnp.equal(jnp.asarray(t1), jnp.asarray(t2))))
    try:
        broadcast_shape(
            t1.shape if isinstance(t1, DNDarray) else jnp.shape(t1),
            t2.shape if isinstance(t2, DNDarray) else jnp.shape(t2),
        )
    except ValueError:
        return False
    result = eq(t1, t2)
    return bool(logical.all(result).item())


def ge(t1, t2) -> DNDarray:
    """Element-wise >= (reference ``:131``)."""
    return _operations._binary_op(jnp.greater_equal, t1, t2)


greater_equal = ge


def gt(t1, t2) -> DNDarray:
    """Element-wise > (reference ``:189``)."""
    return _operations._binary_op(jnp.greater, t1, t2)


greater = gt


def le(t1, t2) -> DNDarray:
    """Element-wise <= (reference ``:247``)."""
    return _operations._binary_op(jnp.less_equal, t1, t2)


less_equal = le


def lt(t1, t2) -> DNDarray:
    """Element-wise < (reference ``:305``)."""
    return _operations._binary_op(jnp.less, t1, t2)


less = lt


def ne(t1, t2) -> DNDarray:
    """Element-wise != (reference ``:363``)."""
    return _operations._binary_op(jnp.not_equal, t1, t2)


not_equal = ne
