"""The distributed n-dimensional array, TPU-native.

Re-design of the reference's ``DNDarray`` (``heat/core/dndarray.py:38``): a
global array with NumPy semantics, optionally *split* along one axis across
the devices of a 1-D mesh. The reference realizes this as one process-local
``torch.Tensor`` per MPI rank; here it is **one global ``jax.Array`` with a
``NamedSharding``** over the mesh, so XLA owns layout, fusion, and collective
scheduling (GSPMD), and single-controller code sees the whole array.

Canonical layout — padded even sharding
---------------------------------------
XLA named shardings require the split dimension to be divisible by the mesh
size. The canonical physical layout therefore pads the split axis up to
``ceil(n/size) * size``; the logical global shape (``gshape``) is tracked
separately. Padding content is *don't-care*: elementwise ops may leave
garbage there, and every consumer that reads across the split axis
(reductions, scans, sorts, matmul) first overwrites the padding with the
operation's neutral element via :meth:`DNDarray.filled`. This replaces the
reference's unbalanced-chunk machinery (``lshape_map`` caching ``:573-604``,
``balance_`` ``:474``, ``redistribute_`` ``:1033-1237``) — balance is a
structural invariant here, not a runtime property.

``larray`` returns the physical ``jax.Array`` (global view — under a single
controller every shard is addressable), where the reference returns the
process-local torch shard.
"""

from __future__ import annotations

import builtins
import math
import weakref
from typing import List, Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import devices, types
from .communication import TPUCommunication, sanitize_comm
from .stride_tricks import sanitize_axis

__all__ = ["DNDarray"]

Device = devices.Device


def _reshard_physical(parray, gshape, from_split, to_split, comm):
    """Move a canonical physical array between split layouts, on device.

    Delegates to the explicit reshard planner (:mod:`.resharding`):
    split→split is ONE planned ``all_to_all`` + local reslice (the
    arXiv:2112.01075 decomposition, O(N/p) peak per device), None→split is
    a zero-collective local slice, and split→None is the only all-gather
    case — replacing both the reference's ``resplit_`` Isend/Irecv tile
    shuffle (``dndarray.py:1239-1361``) and the GSPMD-blind
    ``out_shardings`` constraint XLA could lower as an all-gather.
    """
    from . import resharding

    return resharding.reshard(parray, gshape, from_split, to_split, comm)


class LocalIndex:
    """Parity shim for the reference's ``lloc`` local-indexing helper
    (``dndarray.py:22-35``): indexes the physical array directly. Writes go
    back into the owning array (jax arrays are immutable, so the functional
    ``.at[].set()`` result must replace the owner's buffer — the reference
    mutates the local torch tensor in place).

    Semantic note: under MPI, ``lloc`` addresses the calling rank's shard;
    under the single controller it addresses the whole *physical* (padded,
    global) array — i.e. all shards at once, in canonical layout. Per-device
    blocks are ``larray.addressable_shards``."""

    def __init__(self, owner: "DNDarray"):
        self._owner = owner

    @property
    def obj(self):
        return self._owner.larray

    def __getitem__(self, key):
        return self._owner.larray[key]

    def __setitem__(self, key, value):
        self._owner.larray = self._owner.larray.at[key].set(value)


class DNDarray:
    """Distributed n-dimensional array over a TPU mesh.

    Parameters
    ----------
    array : jax.Array
        The *physical* global array (split axis padded to a multiple of the
        mesh size, sharded with ``comm.sharding(ndim, split)``).
    gshape : tuple of int
        Logical global shape.
    dtype : heat type
    split : int or None
    device : Device
    comm : TPUCommunication
    balanced : bool
        Always True under the canonical layout; kept for API parity.
    """

    def __init__(self, array, gshape, dtype, split, device, comm, balanced: bool = True):
        self._lazy_node = None  # pending fusion-tape node (core/fusion.py)
        # Certificate that the split-axis padding holds exact zeros
        # (factory/planner outputs): a reference to the EXACT physical
        # buffer the claim is true of, or None. Identity (not a bool)
        # makes the claim race-proof — a concurrent buffer swap can never
        # leave a stale True; the certificate simply stops matching.
        self._pad_zero_buf = None
        self.__parray = array
        self.__gshape = tuple(int(s) for s in gshape)
        self.__dtype = dtype
        self.__split = split
        self.__device = device
        self.__comm = comm
        self.__balanced = True
        # halo caches, populated by get_halo (reference ``dndarray.py:237-258``)
        self.halo_prev = None
        self.halo_next = None

    # ------------------------------------------------------------------ #
    # construction helpers                                               #
    # ------------------------------------------------------------------ #
    @staticmethod
    def from_logical(arr, split=None, device=None, comm=None, dtype=None):
        """Wrap a logical (unpadded) jnp array into a canonical DNDarray."""
        comm = sanitize_comm(comm)
        device = devices.sanitize_device(device)
        arr = jnp.asarray(arr)
        if dtype is not None:
            dtype = types.canonical_heat_type(dtype)
            if jnp.dtype(arr.dtype) != dtype.jax_type():
                arr = arr.astype(dtype.jax_type())
        else:
            dtype = types.canonical_heat_type(arr.dtype)
        gshape = arr.shape
        place_split = split
        if split is not None and arr.ndim > 0:
            split = sanitize_axis(gshape, split)
            place_split = split
            if gshape[split] == 0 or arr.size == 0:
                place_split = None  # zero-size axes are placed replicated
            else:
                pad = comm.padded_size(gshape[split]) - gshape[split]
                if pad:
                    cfg = [(0, pad if i == split else 0) for i in range(arr.ndim)]
                    arr = jnp.pad(arr, cfg)
        elif arr.ndim == 0:
            split = None
            place_split = None
        parray = jax.device_put(arr, comm.sharding(arr.ndim, place_split))
        out = DNDarray(parray, gshape, dtype, split, device, comm)
        out._pad_zero = True  # jnp.pad zero-fills (trivially true unpadded)
        return out

    @classmethod
    def _lazy(cls, node, gshape, dtype, split, device, comm) -> "DNDarray":
        """A deferred DNDarray owning a pending fusion-tape node; its
        physical array materializes on first ``larray`` access (the fused
        chain compiles as one program — :mod:`heat_tpu.core.fusion`)."""
        arr = cls(None, gshape, dtype, split, device, comm)
        arr._lazy_node = node
        return arr

    def _set_materialized(self, array) -> None:
        """Fusion flush write-back: install the evaluated physical array.

        Order matters for concurrent readers: ``__parray`` must be set
        BEFORE the lazy flag clears, or a racing ``larray`` getter could
        see the flag down and return a still-None physical array."""
        self.__parray = array
        self._lazy_node = None

    def _phys_or_none(self):
        """The concrete physical array, or None while a chain is pending
        (fusion reads this to build leaf handles without flushing)."""
        return None if self._lazy_node is not None else self.__parray

    def _phys_shape(self) -> Tuple[int, ...]:
        """Physical (padded) shape — metadata only, never flushes."""
        node = self._lazy_node
        if node is not None:
            return tuple(node.aval.shape)
        return tuple(self.__parray.shape)

    def _logical(self):
        """The logical (unpadded) global array. May trigger a device slice."""
        if self.pad == 0:
            return self.larray
        return self.larray[tuple(slice(0, g) for g in self.__gshape)]

    # ------------------------------------------------------------------ #
    # padding discipline                                                 #
    # ------------------------------------------------------------------ #
    @property
    def pad(self) -> int:
        """Number of padded positions along the split axis (0 if none)."""
        if self.__split is None:
            return 0
        return self._phys_shape()[self.__split] - self.__gshape[self.__split]

    @property
    def _pad_zero(self) -> builtins.bool:
        """Whether the CURRENT physical buffer is certified zero-padded.
        Setting True certifies the buffer installed at that moment (only
        do this where the buffer provably just came from a zero-padding
        producer); code that zero-filled a specific buffer should assign
        ``_pad_zero_buf`` directly so a racing install voids the claim."""
        return self.__parray is not None and \
            self._pad_zero_buf is self.__parray

    @_pad_zero.setter
    def _pad_zero(self, value: builtins.bool) -> None:
        self._pad_zero_buf = self.__parray if value else None

    @property
    def pad_is_zero(self) -> builtins.bool:
        """True when the padded positions along the split axis are known
        to hold exact zeros. Factories, ``from_logical`` and the reshard
        planner all zero-pad by construction; elementwise op results leave
        garbage there (the claim stays conservative-False). Consumers that
        would zero-fill (``matmul``'s ``_filled0``, the fusion tape's
        contract masks) skip the re-materialization when it is set.
        A PENDING tape array (``__parray`` None) never certifies —
        ``None is None`` must not read as a claim."""
        return self.pad == 0 or (self.__parray is not None
                                 and self._pad_zero_buf is self.__parray)

    def _write_back_zero_fill(self):
        """Zero-fill the split-axis padding, install the result and
        certify exactly that buffer — the pay-once masking discipline
        shared by the eager GEMM path (``linalg.basics._filled0``) and
        the fusion tape's concrete-operand masks. Ticks
        ``op_engine.zero_fills`` (counts the payers). Returns the
        zero-filled physical array."""
        from ._operations import _count_zero_fill

        _count_zero_fill()
        f = self.filled(0)
        self.larray = f  # padding is don't-care: caching the fill is free
        self._pad_zero_buf = f  # certify exactly f (racing install voids)
        return f

    def filled(self, fill_value):
        """Physical array with padding overwritten by ``fill_value``.

        The mandatory pre-step for any *eager* op that reads across the
        split axis (sort with ±inf, matmul with 0, reductions running with
        ``out=`` or under ``HEAT_TPU_FUSION_REDUCE=0``). Recorded
        reductions carry the same select as a tape **mask node** instead
        (:func:`heat_tpu.core.fusion.record_reduce`), so the fill fuses
        into the one flush program. XLA fuses the select into the
        consumer. A materialization point: any pending fused chain flushes
        here, so the neutral-element select always reads the evaluated
        physical array.
        """
        p = self.larray
        if self.pad == 0:
            return p
        try:
            # identity check against the buffer captured above: a racing
            # install between the two reads voids the claim, never lies
            if self._pad_zero_buf is p and builtins.bool(fill_value == 0):
                return p  # padding already holds the requested fill
        except Exception:
            pass  # exotic fill values take the select path
        k = self.__split
        n = self.__gshape[k]
        iota = jax.lax.broadcasted_iota(jnp.int32, p.shape, k)
        return jnp.where(iota < n, p, jnp.asarray(fill_value, p.dtype))

    def valid_mask(self):
        """Boolean physical-shaped mask, True on logical positions."""
        if self.__split is None:
            return jnp.ones(self._phys_shape(), dtype=jnp.bool_)
        k = self.__split
        iota = jax.lax.broadcasted_iota(jnp.int32, self._phys_shape(), k)
        return iota < self.__gshape[k]

    # ------------------------------------------------------------------ #
    # properties (reference ``dndarray.py:100-330``)                     #
    # ------------------------------------------------------------------ #
    @property
    def larray(self):
        """The physical backing ``jax.Array`` (global; shards addressable).

        THE materialization point: if a fused op chain is pending on this
        array, accessing ``larray`` flushes it — the whole chain compiles
        and runs as one cached XLA program (:mod:`heat_tpu.core.fusion`).
        Every consumer of physical data (reductions, resplits, indexing,
        ``numpy()``, printing, ``item()``) funnels through here."""
        if self._lazy_node is not None:
            from . import fusion

            fusion.materialize(self)
        return self.__parray

    @larray.setter
    def larray(self, array):
        if self._lazy_node is not None:
            from . import fusion

            fusion.cancel(self)
        # arbitrary writes void the zero-pad certificate (and drop its
        # strong reference to the outgoing buffer)
        self._pad_zero_buf = None
        self.__parray = array

    @property
    def balanced(self) -> bool:
        return True

    @property
    def comm(self) -> TPUCommunication:
        return self.__comm

    @comm.setter
    def comm(self, comm):
        self.__comm = sanitize_comm(comm)

    @property
    def device(self) -> Device:
        return self.__device

    @property
    def dtype(self):
        return self.__dtype

    @property
    def gshape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def ndim(self) -> int:
        return len(self.__gshape)

    @property
    def size(self) -> int:
        return int(np.prod(self.__gshape)) if self.__gshape else 1

    @property
    def gnumel(self) -> int:
        return self.size

    @property
    def gnbytes(self) -> int:
        return self.size * self.itemsize

    @property
    def nbytes(self) -> int:
        return self.size * self.itemsize

    @property
    def itemsize(self) -> int:
        return jnp.dtype(self.__dtype.jax_type()).itemsize

    @property
    def split(self) -> Optional[int]:
        return self.__split

    @property
    def lshape(self) -> Tuple[int, ...]:
        """Logical shard shape on mesh device 0.

        Semantic note (vs reference ``dndarray.py:186``): under MPI every
        rank sees *its own* local shape here; under the single-controller
        runtime there is one process, so this property reports device 0 —
        the canonical layout makes all shards the same size anyway (the last
        may be padding-short). Use :attr:`lshape_map` for the per-device
        table, or ``larray.addressable_shards`` for the raw blocks."""
        _, lshape, _ = self.__comm.chunk(self.__gshape, self.__split, rank=0)
        return lshape

    @property
    def lnbytes(self) -> int:
        return int(np.prod(self.lshape)) * self.itemsize if self.lshape else self.itemsize

    @property
    def lshape_map(self):
        """(size, ndim) per-device logical shard shapes (reference ``:573``,
        a property there too)."""
        return self.__comm.lshape_map(self.__gshape, self.__split)

    def create_lshape_map(self, force_check: bool = False):
        return self.lshape_map

    @property
    def lloc(self):
        return LocalIndex(self)

    @property
    def T(self) -> "DNDarray":
        from .linalg import transpose

        return transpose(self)

    @property
    def real(self) -> "DNDarray":
        from . import complex_math

        return complex_math.real(self)

    @property
    def imag(self) -> "DNDarray":
        from . import complex_math

        return complex_math.imag(self)

    # ------------------------------------------------------------------ #
    # distribution management                                            #
    # ------------------------------------------------------------------ #
    def is_balanced(self, force_check: bool = False) -> bool:
        return True

    def balance_(self) -> None:
        """No-op: the canonical layout is always balanced (reference ``:474``)."""
        return None

    def is_distributed(self) -> bool:
        return self.__split is not None and self.__comm.size > 1

    def resplit_(self, axis=None) -> "DNDarray":
        """In-place split-axis change (reference ``resplit_``, ``:1239-1361``).

        One jitted slice→pad→reshard XLA program; collectives ride ICI.
        On a pending fusion tape the planner's move records as a RESPLIT
        node instead of flushing (:func:`heat_tpu.core.fusion.record_resplit`)
        — this array stays lazy, already carrying the target split.
        """
        if axis is not None:
            axis = sanitize_axis(self.__gshape, axis)
        if axis == self.__split:
            return self
        if self._lazy_node is not None:
            from . import fusion

            lazy = fusion.record_resplit(self, axis)
            if lazy is not None:
                # the whole adoption runs under the flush lock: a
                # concurrent sibling flush writes back into owners under
                # that lock, and interleaving its owner-read with this
                # rebind could land the PRE-resplit buffer under the
                # post-resplit split metadata
                with fusion._FLUSH_LOCK:
                    node = lazy._lazy_node
                    # detach the pre-resplit node first: it stays
                    # evaluable as the RESPLIT node's input, but must stop
                    # writing back into this array
                    fusion.cancel(self)
                    self._lazy_node = node
                    node.owner = weakref.ref(self)
                    self.__parray = None
                    self._pad_zero_buf = None
                    self.__split = axis
                return self
        self.__parray = _reshard_physical(
            self.larray, self.__gshape, self.__split, axis, self.__comm
        )
        self.__split = axis
        self._pad_zero = True  # every reshard plan zero-pads the new axis
        return self

    def resplit(self, axis=None) -> "DNDarray":
        """Out-of-place resplit (reference ``manipulations.py:3325``).

        On a pending fusion tape the layout change records as a RESPLIT
        node — the returned array is lazy, and the eventual flush places
        the planner's collective mid-body in the one fused program."""
        if axis is not None:
            axis = sanitize_axis(self.__gshape, axis)
        if axis == self.__split:
            if self._lazy_node is not None:
                from . import fusion

                alias = fusion.alias_pending(self)
                if alias is not None:
                    return alias  # no-op resplit must not flush the tape
            out = DNDarray(
                self.larray, self.__gshape, self.__dtype, self.__split, self.__device, self.__comm
            )
            out._pad_zero = self._pad_zero  # shares the buffer verbatim
            return out
        if self._lazy_node is not None:
            from . import fusion

            lazy = fusion.record_resplit(self, axis)
            if lazy is not None:
                return lazy
        parray = _reshard_physical(self.larray, self.__gshape, self.__split, axis, self.__comm)
        out = DNDarray(parray, self.__gshape, self.__dtype, axis, self.__device, self.__comm)
        out._pad_zero = True  # every reshard plan zero-pads the new axis
        return out

    def redistribute_(self, lshape_map=None, target_map=None) -> None:
        """Reference parity (``:1033-1237``). Arbitrary target maps are not
        representable in the canonical even layout — XLA owns physical
        placement. Accepts the canonical map as a no-op; rejects others."""
        if target_map is None:
            return None
        target = np.asarray(target_map)
        if np.array_equal(target, self.lshape_map):
            return None
        raise NotImplementedError(
            "heat_tpu uses a canonical even-shard layout managed by XLA; "
            "arbitrary redistribution maps are not supported"
        )

    # ------------------------------------------------------------------ #
    # halo exchange (reference ``get_halo``/``array_with_halos``,        #
    # ``dndarray.py:332-445``) — ppermute edge exchange                  #
    # ------------------------------------------------------------------ #
    def _halo_exchange(self, halo_size: int):
        """One ``ppermute`` shift in each direction: returns the received
        edges ``(from_prev, from_next)`` as sharded arrays of global shape
        ``(size * halo_size, …)`` along the split axis, zeros on the outer
        boundary shards. ``None`` when no exchange is needed (replicated,
        ``halo_size == 0``, or a single device). The TPU-native form of the
        reference's Isend/Irecv halo exchange."""
        if not isinstance(halo_size, int) or halo_size < 0:
            raise TypeError("halo_size must be a non-negative integer")
        if self.__split is None or halo_size == 0 or self.__comm.size == 1:
            return None
        k = self.__split
        comm = self.__comm
        n = comm.size
        p = self.larray
        chunk = p.shape[k] // n
        if halo_size > chunk:
            raise ValueError(f"halo_size {halo_size} exceeds chunk size {chunk}")
        from ._compat import shard_map

        spec = comm.spec(self.ndim, k)

        def body(x):
            lo = jax.lax.slice_in_dim(x, 0, halo_size, axis=k)
            hi = jax.lax.slice_in_dim(x, chunk - halo_size, chunk, axis=k)
            nxt = [(i, i + 1) for i in range(n - 1)]
            prv = [(i + 1, i) for i in range(n - 1)]
            from_prev = jax.lax.ppermute(hi, comm.axis_name, perm=nxt)
            from_next = jax.lax.ppermute(lo, comm.axis_name, perm=prv)
            return from_prev, from_next

        fn = shard_map(body, mesh=comm.mesh, in_specs=spec,
                       out_specs=(spec, spec))
        return jax.jit(fn)(p)

    def array_with_halos(self, halo_size: int) -> jax.Array:
        """Physical array where every shard is extended by neighbor edges.

        Returns a ``jax.Array`` of global shape ``(size * (chunk + 2*halo),
        …)`` sharded along the split axis: each local block is
        ``[prev_edge; block; next_edge]`` with zeros at the outer boundaries.
        """
        parts = self._halo_exchange(halo_size)
        if parts is None:
            return self.larray
        from_prev, from_next = parts
        k = self.__split
        comm = self.__comm
        from ._compat import shard_map

        spec = comm.spec(self.ndim, k)
        fn = shard_map(
            lambda p, x, nx: jnp.concatenate([p, x, nx], axis=k),
            mesh=comm.mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return jax.jit(fn)(from_prev, self.larray, from_next)

    def get_halo(self, halo_size: int) -> None:
        """Computes and caches the per-direction halo arrays (reference
        ``get_halo``, ``dndarray.py:360-433``): ``halo_prev`` holds the edge
        received FROM the previous neighbor (the last ``halo_size`` rows of
        its shard), ``halo_next`` the edge from the next neighbor — sharded
        ``jax.Array``s of global shape ``(size * halo_size, …)`` along the
        split axis, zeros on the outer boundary shards (the reference keeps
        ``None`` there; static shapes require a uniform representation)."""
        parts = self._halo_exchange(halo_size)
        if parts is None:
            self.halo_prev = None
            self.halo_next = None
        else:
            self.halo_prev, self.halo_next = parts
        return None

    def counts_displs(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-device element counts and displacements along the split axis
        (reference ``counts_displs``, ``dndarray.py:546-571``)."""
        if self.__split is None:
            raise ValueError("Non-distributed DNDarray has no counts and displacements")
        return self.__comm.counts_displs(self.__gshape[self.__split])

    # ------------------------------------------------------------------ #
    # conversion                                                         #
    # ------------------------------------------------------------------ #
    def astype(self, dtype, copy: bool = True) -> "DNDarray":
        """Cast to ``dtype`` (reference ``:447``). The out-of-place form is
        recorded into the fusion tape (a cast is elementwise); the in-place
        form keeps the eager flush — rebinding another array's identity
        mid-tape is not worth the bookkeeping."""
        dtype = types.canonical_heat_type(dtype)
        if copy:
            from . import fusion

            lazy = fusion.record_astype(self, dtype)
            if lazy is not None:
                return lazy
        casted = self.larray.astype(dtype.jax_type())
        if copy:
            return DNDarray(
                casted, self.__gshape, dtype, self.__split, self.__device, self.__comm
            )
        # a cast preserves zero padding (0 casts to 0 in every numeric
        # dtype): carry the certificate onto the new buffer — and never
        # leave it pinning the outgoing one
        keep = self._pad_zero
        self.__parray = casted
        self._pad_zero_buf = casted if keep else None
        self.__dtype = dtype
        return self

    def numpy(self) -> np.ndarray:
        """Gather the logical global array to host NumPy (reference ``:995``)."""
        return np.asarray(self._logical())

    def __array__(self, dtype=None):
        out = self.numpy()
        return out.astype(dtype) if dtype is not None else out

    def tolist(self) -> list:
        return self.numpy().tolist()

    def item(self):
        """Scalar extraction, global sync point (reference ``:520-544``).

        The common producer is now a recorded reduction: a 0-d pending
        result flushes its whole chain here as one program (mask +
        shard-local reduce + collective included) and fetches a scalar —
        no logical-view slicing on the hot path."""
        if self.size != 1:
            raise ValueError("only one-element DNDarrays can be converted to scalars")
        if self.ndim == 0:
            return self.larray.item()  # 0-d carries no padding to strip
        return self._logical().reshape(()).item()

    def __bool__(self) -> bool:
        return bool(self.item())

    def __int__(self) -> int:
        return int(self.item())

    def __float__(self) -> float:
        return float(self.item())

    def __complex__(self) -> complex:
        return complex(self.item())

    def __index__(self) -> int:
        return int(self.item())

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.__gshape[0]

    # ------------------------------------------------------------------ #
    # indexing                                                           #
    # ------------------------------------------------------------------ #
    def __getitem__(self, key):
        from . import indexing as _indexing_mod  # noqa: F401  (keeps module import shape)

        return _getitem_impl(self, key)

    def __setitem__(self, key, value):
        _setitem_impl(self, key, value)

    # ------------------------------------------------------------------ #
    # operator protocol — delegates to the ops namespaces                #
    # ------------------------------------------------------------------ #
    def __add__(self, other):
        from . import arithmetics

        return arithmetics.add(self, other)

    __radd__ = __add__

    def __sub__(self, other):
        from . import arithmetics

        return arithmetics.sub(self, other)

    def __rsub__(self, other):
        from . import arithmetics

        return arithmetics.sub(other, self)

    def __mul__(self, other):
        from . import arithmetics

        return arithmetics.mul(self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        from . import arithmetics

        return arithmetics.div(self, other)

    def __rtruediv__(self, other):
        from . import arithmetics

        return arithmetics.div(other, self)

    def __floordiv__(self, other):
        from . import arithmetics

        return arithmetics.floordiv(self, other)

    def __rfloordiv__(self, other):
        from . import arithmetics

        return arithmetics.floordiv(other, self)

    def __mod__(self, other):
        from . import arithmetics

        return arithmetics.mod(self, other)

    def __rmod__(self, other):
        from . import arithmetics

        return arithmetics.mod(other, self)

    def __divmod__(self, other):
        from . import arithmetics

        return (arithmetics.floordiv(self, other), arithmetics.mod(self, other))

    def __rdivmod__(self, other):
        from . import arithmetics

        return (arithmetics.floordiv(other, self), arithmetics.mod(other, self))

    def __pow__(self, other):
        from . import arithmetics

        return arithmetics.pow(self, other)

    def __rpow__(self, other):
        from . import arithmetics

        return arithmetics.pow(other, self)

    def __matmul__(self, other):
        from .linalg import matmul

        return matmul(self, other)

    def __neg__(self):
        from . import arithmetics

        return arithmetics.neg(self)

    def __pos__(self):
        from . import arithmetics

        return arithmetics.pos(self)

    def __abs__(self):
        from . import rounding

        return rounding.abs(self)

    def __invert__(self):
        from . import arithmetics

        return arithmetics.invert(self)

    def __and__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_and(self, other)

    def __or__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_or(self, other)

    def __xor__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_xor(self, other)

    def __lshift__(self, other):
        from . import arithmetics

        return arithmetics.left_shift(self, other)

    def __rshift__(self, other):
        from . import arithmetics

        return arithmetics.right_shift(self, other)

    # reflected bitwise/shift operators: the reference stops at the
    # arithmetic set (``arithmetics.py:528-635`` has no __rand__/__ror__/
    # __rxor__/__rlshift__/__rrshift__, so ``6 & x`` raises there) — NumPy
    # supports them, and the ht.* surface is NumPy's
    def __rand__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_and(other, self)

    def __ror__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_or(other, self)

    def __rxor__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_xor(other, self)

    def __rlshift__(self, other):
        from . import arithmetics

        return arithmetics.left_shift(other, self)

    def __rrshift__(self, other):
        from . import arithmetics

        return arithmetics.right_shift(other, self)

    @staticmethod
    def _is_operand(other) -> builtins.bool:
        """True for types the binary-op engine can promote (DNDarray, python
        scalars, numpy/jax arrays, nested sequences). Non-operands (Ellipsis,
        None, slices, arbitrary objects) make the rich comparisons return
        ``NotImplemented`` so Python falls back to identity semantics instead
        of raising through ``_binary_op`` — e.g. ``Ellipsis in (x, ...)``."""
        return isinstance(
            other,
            (DNDarray, builtins.int, builtins.float, builtins.bool, complex,
             np.generic, np.ndarray, jnp.ndarray, list, tuple),
        )

    def __eq__(self, other):
        from . import relational

        if not self._is_operand(other):
            return NotImplemented
        return relational.eq(self, other)

    def __ne__(self, other):
        from . import relational

        if not self._is_operand(other):
            return NotImplemented
        return relational.ne(self, other)

    def __lt__(self, other):
        from . import relational

        if not self._is_operand(other):
            return NotImplemented
        return relational.lt(self, other)

    def __le__(self, other):
        from . import relational

        if not self._is_operand(other):
            return NotImplemented
        return relational.le(self, other)

    def __gt__(self, other):
        from . import relational

        if not self._is_operand(other):
            return NotImplemented
        return relational.gt(self, other)

    def __ge__(self, other):
        from . import relational

        if not self._is_operand(other):
            return NotImplemented
        return relational.ge(self, other)

    __hash__ = None

    # ------------------------------------------------------------------ #
    # method sugar over the flat namespace (subset of reference methods) #
    # ------------------------------------------------------------------ #
    def sum(self, axis=None, out=None, keepdims=False):
        from . import arithmetics

        return arithmetics.sum(self, axis=axis, out=out, keepdims=keepdims)

    def prod(self, axis=None, out=None, keepdims=False):
        from . import arithmetics

        return arithmetics.prod(self, axis=axis, out=out, keepdims=keepdims)

    def cumsum(self, axis=0):
        from . import arithmetics

        return arithmetics.cumsum(self, axis)

    def cumprod(self, axis=0):
        from . import arithmetics

        return arithmetics.cumprod(self, axis)

    def mean(self, axis=None):
        from . import statistics

        return statistics.mean(self, axis)

    def var(self, axis=None, ddof=0):
        from . import statistics

        return statistics.var(self, axis, ddof=ddof)

    def std(self, axis=None, ddof=0):
        from . import statistics

        return statistics.std(self, axis, ddof=ddof)

    def min(self, axis=None, out=None, keepdims=False):
        from . import statistics

        return statistics.min(self, axis=axis, out=out, keepdims=keepdims)

    def max(self, axis=None, out=None, keepdims=False):
        from . import statistics

        return statistics.max(self, axis=axis, out=out, keepdims=keepdims)

    def argmin(self, axis=None, out=None):
        from . import statistics

        return statistics.argmin(self, axis=axis, out=out)

    def argmax(self, axis=None, out=None):
        from . import statistics

        return statistics.argmax(self, axis=axis, out=out)

    def all(self, axis=None, out=None, keepdims=False):
        from . import logical

        return logical.all(self, axis=axis, out=out, keepdims=keepdims)

    def any(self, axis=None, out=None, keepdims=False):
        from . import logical

        return logical.any(self, axis=axis, out=out, keepdims=keepdims)

    def abs(self, out=None, dtype=None):
        from . import rounding

        return rounding.abs(self, out, dtype)

    def exp(self, out=None):
        from . import exponential

        return exponential.exp(self, out)

    def log(self, out=None):
        from . import exponential

        return exponential.log(self, out)

    def sqrt(self, out=None):
        from . import exponential

        return exponential.sqrt(self, out)

    def sin(self, out=None):
        from . import trigonometrics

        return trigonometrics.sin(self, out)

    def cos(self, out=None):
        from . import trigonometrics

        return trigonometrics.cos(self, out)

    def reshape(self, *shape, new_split=None):
        from . import manipulations

        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return manipulations.reshape(self, shape, new_split=new_split)

    def flatten(self):
        from . import manipulations

        return manipulations.flatten(self)

    def ravel(self):
        from . import manipulations

        return manipulations.ravel(self)

    def squeeze(self, axis=None):
        from . import manipulations

        return manipulations.squeeze(self, axis)

    def expand_dims(self, axis):
        from . import manipulations

        return manipulations.expand_dims(self, axis)

    def transpose(self, axes=None):
        from .linalg import transpose

        return transpose(self, axes)

    def flip(self, axis=None):
        from . import manipulations

        return manipulations.flip(self, axis)

    def nonzero(self):
        from . import indexing

        return indexing.nonzero(self)

    def unique(self, sorted=True, return_inverse=False, axis=None, return_counts=False):
        from . import manipulations

        return manipulations.unique(
            self, sorted=sorted, return_inverse=return_inverse, axis=axis,
            return_counts=return_counts)

    def clip(self, a_min, a_max, out=None):
        from . import rounding

        return rounding.clip(self, a_min, a_max, out)

    # -- reference method attachments (``DNDarray.x = ...`` throughout the
    # reference's op modules, e.g. ``rounding.py:120``, ``basics.py:2210``) --
    def absolute(self, out=None, dtype=None):
        from . import rounding

        return rounding.abs(self, out, dtype)

    def acos(self, out=None):
        from . import trigonometrics

        return trigonometrics.arccos(self, out)

    def asin(self, out=None):
        from . import trigonometrics

        return trigonometrics.arcsin(self, out)

    def atan(self, out=None):
        from . import trigonometrics

        return trigonometrics.arctan(self, out)

    def atan2(self, x2):
        from . import trigonometrics

        return trigonometrics.arctan2(self, x2)

    def allclose(self, other, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False):
        from . import logical

        return logical.allclose(self, other, rtol=rtol, atol=atol, equal_nan=equal_nan)

    def isclose(self, other, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False):
        from . import logical

        return logical.isclose(self, other, rtol=rtol, atol=atol, equal_nan=equal_nan)

    def average(self, axis=None, weights=None, returned: bool = False):
        from . import statistics

        return statistics.average(self, axis=axis, weights=weights, returned=returned)

    def ceil(self, out=None):
        from . import rounding

        return rounding.ceil(self, out)

    def floor(self, out=None):
        from . import rounding

        return rounding.floor(self, out)

    def trunc(self, out=None):
        from . import rounding

        return rounding.trunc(self, out)

    def round(self, decimals: int = 0, out=None, dtype=None):
        from . import rounding

        return rounding.round(self, decimals, out, dtype)

    def fabs(self, out=None):
        from . import rounding

        return rounding.fabs(self, out)

    def modf(self, out=None):
        from . import rounding

        return rounding.modf(self, out)

    def sign(self, out=None):
        from . import rounding

        return rounding.sign(self, out)

    def sgn(self, out=None):
        from . import rounding

        return rounding.sgn(self, out)

    def tan(self, out=None):
        from . import trigonometrics

        return trigonometrics.tan(self, out)

    def sinh(self, out=None):
        from . import trigonometrics

        return trigonometrics.sinh(self, out)

    def cosh(self, out=None):
        from . import trigonometrics

        return trigonometrics.cosh(self, out)

    def tanh(self, out=None):
        from . import trigonometrics

        return trigonometrics.tanh(self, out)

    def kurtosis(self, axis=None, unbiased: bool = True, Fischer: bool = True):
        from . import statistics

        return statistics.kurtosis(self, axis=axis, unbiased=unbiased, Fischer=Fischer)

    def skew(self, axis=None, unbiased: bool = True):
        from . import statistics

        return statistics.skew(self, axis=axis, unbiased=unbiased)

    def median(self, axis=None, keepdim: bool = False, keepdims=None):
        from . import statistics

        return statistics.median(
            self, axis=axis, keepdims=keepdim if keepdims is None else keepdims)

    def norm(self):
        from .linalg import norm as _norm

        return _norm(self)

    def qr(self, tiles_per_proc: int = 1, calc_q: bool = True, overwrite_a: bool = False):
        from .linalg import qr as _qr

        return _qr(self, tiles_per_proc=tiles_per_proc, calc_q=calc_q,
                   overwrite_a=overwrite_a)

    def trace(self, offset: int = 0, axis1: int = 0, axis2: int = 1, dtype=None, out=None):
        from .linalg import trace as _trace

        return _trace(self, offset=offset, axis1=axis1, axis2=axis2, dtype=dtype, out=out)

    def tril(self, k: int = 0):
        from .linalg import tril as _tril

        return _tril(self, k)

    def triu(self, k: int = 0):
        from .linalg import triu as _triu

        return _triu(self, k)

    def copy(self):
        from . import memory

        return memory.copy(self)

    def exp2(self, out=None):
        from . import exponential

        return exponential.exp2(self, out)

    def expm1(self, out=None):
        from . import exponential

        return exponential.expm1(self, out)

    def log2(self, out=None):
        from . import exponential

        return exponential.log2(self, out)

    def log10(self, out=None):
        from . import exponential

        return exponential.log10(self, out)

    def log1p(self, out=None):
        from . import exponential

        return exponential.log1p(self, out)

    def square(self, out=None):
        from . import exponential

        return exponential.square(self, out)

    def conj(self, out=None):
        from . import complex_math

        return complex_math.conjugate(self, out)

    def balance(self) -> "DNDarray":
        """Out-of-place balance (reference ``manipulations.py:69``): the
        canonical layout is always balanced, so this is a copy."""
        from . import memory

        return memory.copy(self)

    def redistribute(self, lshape_map=None, target_map=None) -> "DNDarray":
        from . import manipulations

        return manipulations.redistribute(self, lshape_map=lshape_map, target_map=target_map)

    def rot90(self, k: int = 1, axes=(0, 1)) -> "DNDarray":
        from . import manipulations

        return manipulations.rot90(self, k, axes)

    def swapaxes(self, axis1: int, axis2: int) -> "DNDarray":
        from . import manipulations

        return manipulations.swapaxes(self, axis1, axis2)

    def cpu(self) -> "DNDarray":
        """Parity shim (reference ``dndarray.py:520``): under a single
        controller the array is already addressable; returns self."""
        return self

    @property
    def lnumel(self) -> int:
        """Number of elements in the device-0 shard (reference ``:186``)."""
        return int(np.prod(self.lshape)) if self.lshape else 1

    def stride(self) -> Tuple[int, ...]:
        """Row-major element strides of the local shard (reference ``:272``)."""
        lshape = self.lshape
        st = []
        acc = 1
        for s in reversed(lshape):
            st.append(acc)
            acc *= max(s, 1)
        return tuple(reversed(st))

    @property
    def strides(self) -> Tuple[int, ...]:
        """NumPy-style byte strides of the local shard (reference ``:279``)."""
        return tuple(s * self.itemsize for s in self.stride())

    def save(self, path: str, *args, **kwargs) -> None:
        from . import io

        return io.save(self, path, *args, **kwargs)

    def save_hdf5(self, path: str, dataset: str = "data", **kwargs) -> None:
        from . import io

        return io.save_hdf5(self, path, dataset, **kwargs)

    def save_netcdf(self, path: str, variable: str = "data", **kwargs) -> None:
        from . import io

        return io.save_netcdf(self, path, variable, **kwargs)

    def fill_diagonal(self, value) -> "DNDarray":
        n = min(self.__gshape) if self.ndim >= 2 else 0
        if self.ndim < 2:
            raise ValueError("fill_diagonal requires at least a 2-D array")
        logical = self._logical()
        idx = jnp.arange(n)
        logical = logical.at[idx, idx].set(jnp.asarray(value, logical.dtype))
        new = DNDarray.from_logical(
            logical, self.__split, self.__device, self.__comm, dtype=self.__dtype
        )
        self.__parray = new.larray
        self._pad_zero_buf = new._pad_zero_buf  # from_logical zero-pads
        return self

    # ------------------------------------------------------------------ #
    # printing                                                           #
    # ------------------------------------------------------------------ #
    def __repr__(self) -> str:
        from . import printing

        return printing.__str__(self)

    def __str__(self) -> str:
        from . import printing

        return printing.__str__(self)


# ---------------------------------------------------------------------- #
# indexing implementation                                                #
# ---------------------------------------------------------------------- #
def _normalize_key(x, key):
    """Convert DNDarray components of an index key to jnp arrays (logical)."""
    def conv(k):
        if isinstance(k, DNDarray):
            return k._logical()
        if isinstance(k, (np.ndarray, jnp.ndarray)):
            return jnp.asarray(k)
        if isinstance(k, list):
            # NumPy semantics: a list index is an advanced (array) index;
            # an empty list selects nothing (needs an integer dtype — a bare
            # np.asarray([]) would be float64 and jax rejects float indexers)
            arr = np.asarray(k)
            if arr.size == 0:
                arr = arr.astype(np.intp)
            return jnp.asarray(arr)
        return k

    if isinstance(key, tuple):
        key = tuple(conv(k) for k in key)
    else:
        key = conv(key)
    _check_int_bounds(x, key)
    return key


def _expand_ellipsis(keys, ndim):
    """Replace a single Ellipsis with the full slices it stands for (NumPy
    arity rules via :func:`_index_axis_span`). Returns None when a second
    Ellipsis makes the key invalid for the specialized dispatchers."""
    if any(k is Ellipsis for k in keys):
        i = next(j for j, k in enumerate(keys) if k is Ellipsis)
        n_explicit = sum(_index_axis_span(k) for k in keys if k is not Ellipsis)
        keys[i:i + 1] = [slice(None)] * (ndim - n_explicit)
        if any(k is Ellipsis for k in keys):
            return None
    return keys


def _index_axis_span(k) -> builtins.int:
    """How many array axes one key element consumes (NumPy arity rules):
    a boolean mask consumes ``mask.ndim`` axes, a scalar bool / None consume
    none, everything else (int, slice, integer array) consumes one."""
    if k is None or isinstance(k, builtins.bool):
        return 0
    if isinstance(k, (np.ndarray, jnp.ndarray)) and k.dtype == np.bool_:
        return k.ndim
    return 1


def _check_int_bounds(x, key):
    """NumPy/reference semantics: out-of-range static integer indices raise
    IndexError (jax would silently clamp them). Integers are checked against
    the axis they address; dims after an Ellipsis count from the right."""
    keys = key if isinstance(key, tuple) else (key,)
    n_addr = sum(_index_axis_span(k) for k in keys if k is not Ellipsis)
    if n_addr > x.ndim:
        raise IndexError(
            f"too many indices: array is {x.ndim}-dimensional, key addresses {n_addr}"
        )
    pre, post, seen_ellipsis = [], [], False
    for k in keys:
        if k is Ellipsis:
            seen_ellipsis = True
        elif seen_ellipsis:
            post.append(k)
        else:
            pre.append(k)

    def check(segment, start_axis):
        axis = start_axis
        for k in segment:
            if isinstance(k, builtins.int) and not isinstance(k, builtins.bool):
                n = x.gshape[axis]
                if not -n <= k < n:
                    raise IndexError(
                        f"index {k} is out of bounds for axis {axis} with size {n}"
                    )
            elif (
                isinstance(k, (np.ndarray, jnp.ndarray))
                and k.size
                and k.dtype != np.bool_
                and jnp.issubdtype(k.dtype, jnp.integer)
                and axis < x.ndim
            ):
                # NumPy raises for out-of-range array indices; jax would
                # silently clamp them (two scalar fetches — the general
                # path materializes anyway)
                n = x.gshape[axis]
                lo, hi = int(k.min()), int(k.max())
                if lo < -n or hi >= n:
                    raise IndexError(
                        f"index out of bounds for axis {axis} with size {n}")
            axis += _index_axis_span(k)

    check(pre, 0)
    check(post, x.ndim - sum(_index_axis_span(k) for k in post))


def _basic_key_fast_path(x: DNDarray, key) -> bool:
    """True when the key leaves the split axis fully intact (no comm needed)."""
    if x.split is None:
        return False
    if not isinstance(key, tuple):
        key = (key,)
    if any(k is Ellipsis or k is None or not isinstance(k, (int, slice)) for k in key):
        return False
    # key addresses leading dims; the split dim must be beyond the key or
    # covered by a full slice
    dims_consumed = 0
    for k in key:
        if dims_consumed == x.split and not (isinstance(k, slice) and k == slice(None)):
            return False
        dims_consumed += 1
    return True


def _result_split_basic(x: DNDarray, key) -> Optional[int]:
    """Output split position after basic indexing that preserves the split axis."""
    if x.split is None:
        return None
    if not isinstance(key, tuple):
        key = (key,)
    key = list(key)
    # expand ellipsis (identity tests — see _match_split_axis_array_key)
    if any(k is Ellipsis for k in key):
        i = next(j for j, k in enumerate(key) if k is Ellipsis)
        n_explicit = sum(1 for k in key if k is not Ellipsis and k is not None)
        key[i : i + 1] = [slice(None)] * (x.ndim - n_explicit)
    out_pos = 0
    dim = 0
    for k in key:
        if k is None:
            out_pos += 1
            continue
        if dim == x.split:
            return out_pos if isinstance(k, slice) else None
        if isinstance(k, slice):
            out_pos += 1
        dim += 1
    if dim <= x.split:
        return out_pos + (x.split - dim)
    return None


def _match_split_axis_array_key(x: DNDarray, key):
    """Detect keys whose single non-trivial element is a 1-D integer array
    or 1-D boolean mask sitting exactly at the split axis (everything else
    full slices). These run the distributed ring-indexing programs
    (:mod:`heat_tpu.core._indexing`) instead of materializing the logical
    array. Returns ``("int"|"bool", array_like)`` or None."""
    if x.split is None or x.comm.size <= 1 or x.ndim == 0:
        return None
    keys = list(key) if isinstance(key, tuple) else [key]
    if any(k is None for k in keys):
        return None
    # identity tests only: ``in``/``index`` run ``==`` per element, which is
    # ambiguous for array-valued keys and dispatches DNDarray.__eq__
    if _expand_ellipsis(keys, x.ndim) is None:
        return None
    keys += [slice(None)] * (x.ndim - sum(_index_axis_span(k) for k in keys))
    hit = None
    axis = 0
    for k in keys:
        if isinstance(k, list):
            k = np.asarray(k)
            if k.size == 0:
                k = k.astype(np.intp)
        if isinstance(k, (DNDarray, np.ndarray, jnp.ndarray)):
            if k.ndim != 1 or axis != x.split or hit is not None:
                return None
            dt = k.larray.dtype if isinstance(k, DNDarray) else k.dtype
            if dt == np.bool_:
                if k.shape[0] != x.shape[x.split]:
                    return None
                hit = ("bool", k)
            elif jnp.issubdtype(dt, jnp.integer):
                hit = ("int", k)
            else:
                return None
            axis += 1
        elif isinstance(k, slice) and k == slice(None):
            axis += 1
        else:
            return None  # non-trivial slice/int elsewhere: fallback paths
    return hit


def _match_mixed_key(x: DNDarray, key):
    """Detect mixed advanced keys: EXACTLY ONE 1-D integer array or 1-D
    boolean mask combined with basic ints/slices (reference
    ``dndarray.py:656-912`` bread-and-butter ``x[idx, 2:5]``). Returns
    ``(keys, arr_pos, kind, arr)`` with Ellipsis expanded and the key padded
    to ``x.ndim``, or None for keys the general path must handle.

    Non-slice keys (ints + the array) must sit at consecutive axes: NumPy
    moves broadcast dims to the front when advanced indices are *separated*
    by a slice, and the per-axis layout used here would be wrong there.
    """
    if x.split is None or x.comm.size <= 1 or x.ndim == 0:
        return None
    keys = list(key) if isinstance(key, tuple) else [key]
    if any(k is None or isinstance(k, builtins.bool) for k in keys):
        return None
    if _expand_ellipsis(keys, x.ndim) is None:
        return None
    keys += [slice(None)] * (x.ndim - sum(_index_axis_span(k) for k in keys))
    if len(keys) != x.ndim:
        return None
    arr_pos = kind = arr = None
    for axis, k in enumerate(keys):
        if isinstance(k, list):
            k = np.asarray(k)
            if k.size == 0:
                k = k.astype(np.intp)
            keys[axis] = k
        if isinstance(k, (DNDarray, np.ndarray, jnp.ndarray)):
            if k.ndim != 1 or arr_pos is not None:
                return None
            dt = k.larray.dtype if isinstance(k, DNDarray) else k.dtype
            if dt == np.bool_:
                if k.shape[0] != x.gshape[axis]:
                    return None
                kind = "bool"
            elif jnp.issubdtype(dt, jnp.integer):
                kind = "int"
            else:
                return None
            arr_pos, arr = axis, k
        elif isinstance(k, slice):
            continue
        elif isinstance(k, builtins.int):
            n = x.gshape[axis]
            kk = k + n if k < 0 else k
            if not 0 <= kk < n:
                raise IndexError(
                    f"index {k} is out of bounds for axis {axis} with size {n}")
            keys[axis] = kk
        else:
            return None
    if arr_pos is None:
        return None
    adv = [i for i, k in enumerate(keys) if not isinstance(k, slice)]
    if any(b - a != 1 for a, b in zip(adv, adv[1:])):
        return None  # separated advanced indices: broadcast dims move front
    return keys, arr_pos, kind, arr


def _slice_len(sl: slice, n: int) -> builtins.int:
    return len(range(*sl.indices(n)))


def _getitem_paired_arrays(x: DNDarray, key) -> Optional[DNDarray]:
    """Paired integer-array keys over the LEADING axes (reference
    ``dndarray.py:656-912`` multi-array cases, e.g. ``x[rows, cols]``):
    the advanced group collapses to ONE flat index via ravel_multi_index,
    the leading axes merge through the distributed reshape (O(chunk) ring),
    and the flat single-array ring path finishes the job. Requires >= 2
    advanced indices (ints count), all at axes ``0..g`` with the split axis
    inside the group and only full/basic slices after — NumPy places the
    broadcast dims first there, which matches the flat result layout."""
    if x.split is None or x.comm.size <= 1 or x.ndim < 2:
        return None
    keys = list(key) if isinstance(key, tuple) else [key]
    if any(k is None or isinstance(k, builtins.bool) for k in keys):
        return None
    if _expand_ellipsis(keys, x.ndim) is None:
        return None
    keys += [slice(None)] * (x.ndim - sum(_index_axis_span(k) for k in keys))
    if len(keys) != x.ndim:
        return None

    def as_idx(k):
        if isinstance(k, builtins.int):
            return np.asarray(k)
        if isinstance(k, list):
            k = np.asarray(k)
        if isinstance(k, DNDarray):
            if not jnp.issubdtype(k.larray.dtype, jnp.integer):
                return None
            k = np.asarray(k.numpy())
        if isinstance(k, (np.ndarray, jnp.ndarray)):
            # only true integer indexers: float arrays must keep falling to
            # the general path, which rejects them like NumPy (review
            # finding: silent truncation)
            if k.ndim > 1 or not np.issubdtype(np.asarray(k).dtype, np.integer):
                return None
            return np.asarray(k, dtype=np.int64)
        return None

    adv = [i for i, k in enumerate(keys) if not isinstance(k, slice)]
    n_arrays = sum(1 for i in adv
                   if not isinstance(keys[i], builtins.int))
    if n_arrays < 2 or adv != list(range(len(adv))):
        return None  # single-array keys belong to the mixed path
    g = len(adv)
    if not (x.split < g):
        return None
    idxs = []
    for i in range(g):
        arr = as_idx(keys[i])
        if arr is None:
            return None
        n_i = x.gshape[i]
        arr = np.where(arr < 0, arr + n_i, arr)
        if arr.size and ((arr < 0).any() or (arr >= n_i).any()):
            raise IndexError(
                f"index out of bounds for axis {i} with size {n_i}")
        idxs.append(arr)
    try:
        m = np.broadcast_shapes(*[a.shape for a in idxs])
    except ValueError:
        return None
    if len(m) != 1:
        return None
    idxs = [np.broadcast_to(a, m).astype(np.int64) for a in idxs]
    combined = np.ravel_multi_index(tuple(idxs), x.gshape[:g])
    from . import manipulations

    flat_shape = (int(np.prod(x.gshape[:g], dtype=np.int64)),) + x.gshape[g:]
    xm = manipulations.reshape(x, flat_shape, new_split=0)
    rest = tuple(keys[g:])
    sub_key = (combined,) + rest if rest else combined
    return _getitem_impl(xm, sub_key)


def _getitem_mixed(x: DNDarray, keys, arr_pos, kind, arr) -> Optional[DNDarray]:
    """Execute a mixed key from :func:`_match_mixed_key` without logical
    materialization. Array at the split axis: apply the basic keys
    shard-locally (they never touch the split axis), then run the ring
    programs. Array elsewhere with the split axis untouched: the whole key
    applies shard-locally."""
    split = x.split
    if arr_pos == split:
        pre = tuple(slice(None) if i == split else k
                    for i, k in enumerate(keys))
        if all(isinstance(k, slice) and k == slice(None) for k in pre):
            sub = x
        else:
            sub_phys = x.larray[pre]
            gshape, new_split, dim = [], None, 0
            for i, k in enumerate(keys):
                if i == split:
                    new_split = dim
                    gshape.append(x.gshape[i])
                    dim += 1
                elif isinstance(k, slice):
                    gshape.append(_slice_len(k, x.gshape[i]))
                    dim += 1
                # ints drop the dim
            sub = DNDarray(sub_phys, tuple(gshape), x.dtype, new_split,
                           x.device, x.comm)
        return _getitem_split_axis_advanced(sub, kind, arr)
    # array on a non-split axis: only valid gather-free when the split axis
    # keeps its full extent
    if not (isinstance(keys[split], slice) and keys[split] == slice(None)):
        return None
    n_axis = x.gshape[arr_pos]
    if kind == "bool":
        idx_np = _mask_to_indices(arr)
    else:
        if isinstance(arr, DNDarray):
            arr = np.asarray(arr.numpy())
        idx_np = np.asarray(arr, dtype=np.int64).reshape(-1)
        idx_np = np.where(idx_np < 0, idx_np + n_axis, idx_np)
        if idx_np.size and ((idx_np < 0).any() or (idx_np >= n_axis).any()):
            raise IndexError(
                f"index out of bounds for axis {arr_pos} with size {n_axis}")
    m = idx_np.shape[0]
    key2 = tuple(jnp.asarray(idx_np) if i == arr_pos else k
                 for i, k in enumerate(keys))
    sub_phys = x.larray[key2]
    gshape, new_split, dim = [], None, 0
    for i, k in enumerate(keys):
        if i == arr_pos:
            gshape.append(m)
            dim += 1
        elif isinstance(k, slice):
            if i == split:
                new_split = dim
                gshape.append(x.gshape[i])
            else:
                gshape.append(_slice_len(k, x.gshape[i]))
            dim += 1
    return DNDarray(sub_phys, tuple(gshape), x.dtype, new_split, x.device,
                    x.comm)


def _parse_split_slice_key(x: DNDarray, key):
    """Shared matcher for the split-axis slice paths: basic int/slice keys
    (Ellipsis ok) whose split-axis element is a non-full slice or an int.
    Returns ``(keys, start, step, L, is_int)`` or None; out-of-range ints
    raise IndexError (getitem and setitem must agree on all of this)."""
    if x.split is None or x.comm.size <= 1 or x.ndim == 0:
        return None
    keys = list(key) if isinstance(key, tuple) else [key]
    for k in keys:
        if k is Ellipsis or isinstance(k, slice):
            continue
        if isinstance(k, builtins.int) and not isinstance(k, builtins.bool):
            continue
        return None
    if _expand_ellipsis(keys, x.ndim) is None:
        return None
    keys += [slice(None)] * (x.ndim - len(keys))
    if len(keys) != x.ndim:
        return None
    ks = keys[x.split]
    n = x.gshape[x.split]
    if isinstance(ks, slice):
        start, stop, step = ks.indices(n)
        if start == 0 and step == 1 and stop >= n:
            return None  # full span (any spelling): zero-comm fast path
        return keys, start, step, _slice_len(ks, n), False
    kk = ks + n if ks < 0 else ks
    if not 0 <= kk < n:
        raise IndexError(
            f"index {ks} is out of bounds for axis {x.split} with size {n}")
    return keys, kk, 1, 1, True


def _getitem_split_slice(x: DNDarray, key) -> Optional[DNDarray]:
    """Basic keys whose split-axis element is a non-trivial slice (or int):
    the selection is an AFFINE map ``src(go) = start + go*step``, so one
    scheduled window fetch re-chunks it into canonical layout — the
    reference's global slice translation (``dndarray.py:656-912``) without
    materializing the logical array. Other axes apply shard-locally."""
    parsed = _parse_split_slice_key(x, key)
    if parsed is None:
        return None
    keys, start, step, L, drop = parsed
    n = x.gshape[x.split]
    ks = keys[x.split]
    # bounds-check + normalize the other ints, then apply them shard-locally
    pre = []
    for i, k in enumerate(keys):
        if i == x.split:
            pre.append(slice(None))
        elif isinstance(k, builtins.int):
            ni = x.gshape[i]
            kkk = k + ni if k < 0 else k
            if not 0 <= kkk < ni:
                raise IndexError(
                    f"index {k} is out of bounds for axis {i} with size {ni}")
            pre.append(kkk)
        else:
            pre.append(k)
    sub_phys = x.larray[tuple(pre)]
    gshape1, new_split, dim = [], None, 0
    for i, k in enumerate(keys):
        if i == x.split:
            new_split = dim
            gshape1.append(n)
            dim += 1
        elif isinstance(k, slice):
            gshape1.append(_slice_len(k, x.gshape[i]))
            dim += 1
        # ints drop the dim
    if L == 0:
        gshape0 = tuple(0 if i == new_split else s
                        for i, s in enumerate(gshape1))
        return DNDarray.from_logical(
            jnp.zeros(gshape0, x.larray.dtype), new_split, x.device, x.comm,
            dtype=x.dtype)
    from . import _manips

    comm = x.comm
    fn = _manips.ring_slice_fn(
        sub_phys.shape, jnp.dtype(sub_phys.dtype), new_split, start, step, L,
        comm.chunk_size(L), comm)
    out_phys = fn(sub_phys)
    gshape2 = tuple(L if i == new_split else s for i, s in enumerate(gshape1))
    res = DNDarray(out_phys, gshape2, x.dtype, new_split, x.device, comm)
    if drop:
        # single split-axis element: the dim disappears, result replicated
        return DNDarray.from_logical(
            jnp.squeeze(res._logical(), axis=new_split), None, x.device,
            comm, dtype=x.dtype)
    return res


def _mask_physical(x: DNDarray, mask_like):
    """A physical split-0 bool array aligned with ``x``'s split axis chunks
    (padding positions False)."""
    comm = x.comm
    n = x.shape[x.split]
    if isinstance(mask_like, DNDarray):
        if mask_like.split == 0 and mask_like.larray.shape[0] == comm.padded_size(n):
            return jnp.where(mask_like.valid_mask(), mask_like.larray, False)
        mask_like = mask_like._logical()
    m_np = jnp.asarray(np.asarray(mask_like) if isinstance(mask_like, list)
                       else mask_like, jnp.bool_)
    pad = comm.padded_size(n) - n
    if pad:
        m_np = jnp.concatenate([m_np, jnp.zeros((pad,), jnp.bool_)])
    return jax.device_put(m_np, comm.sharding(1, 0))


def _index_physical(x: DNDarray, idx_like, m_len=None):
    """(idx_physical, m): a split-0 physical int array of global row
    positions (negatives normalized, padding = -1), bounds-checked."""
    from ._sort import _index_dtype

    comm = x.comm
    n = x.shape[x.split]
    idt = _index_dtype()
    if isinstance(idx_like, DNDarray) and idx_like.split != 0:
        # replicated (or oddly-split) index: its physical array is not in
        # the canonical padded split-0 layout the ring expects
        idx_like = np.asarray(idx_like.larray if idx_like.split is None
                              else idx_like.numpy())
    if isinstance(idx_like, DNDarray):
        m = idx_like.shape[0]
        la = idx_like.larray.astype(idt)
        la = jnp.where(la < 0, la + n, la)
        phys = jnp.where(idx_like.valid_mask(), la, jnp.asarray(-1, idt))
        if m > 0:
            lo = int(jnp.min(jnp.where(idx_like.valid_mask(), la, 0)))
            hi = int(jnp.max(jnp.where(idx_like.valid_mask(), la, 0)))
            if lo < 0 or hi >= n:
                raise IndexError(
                    f"index out of bounds for axis {x.split} with size {n}")
        return phys, m
    idx_np = np.asarray(idx_like, dtype=np.int64).reshape(-1)
    m = idx_np.shape[0]
    idx_np = np.where(idx_np < 0, idx_np + n, idx_np)
    if m and ((idx_np < 0).any() or (idx_np >= n).any()):
        raise IndexError(
            f"index out of bounds for axis {x.split} with size {n}")
    c_out = comm.chunk_size(m)
    pad = c_out * comm.size - m
    full = np.concatenate([idx_np, np.full(pad, -1, np.int64)])
    return jax.device_put(jnp.asarray(full, idt), comm.sharding(1, 0)), m


def _empty_rows(x: DNDarray, axis: int) -> DNDarray:
    gshape = tuple(0 if i == axis else s for i, s in enumerate(x.gshape))
    return DNDarray.from_logical(
        jnp.zeros(gshape, x.larray.dtype), None, x.device, x.comm,
        dtype=x.dtype)


def _getitem_split_axis_advanced(x: DNDarray, kind, arr) -> DNDarray:
    """x[idx]/x[mask] along the split axis via the ring programs — no
    logical materialization (reference translation path,
    ``dndarray.py:656-912``)."""
    from . import _indexing

    comm = x.comm
    axis = x.split
    jdt = jnp.dtype(x.larray.dtype)
    if kind == "int":
        idx_phys, m = _index_physical(x, arr)
        if m == 0:
            return _empty_rows(x, axis)
        c_out = idx_phys.shape[0] // comm.size
        fn = _indexing.ring_gather_fn(x.larray.shape, jdt, axis, c_out, comm)
        rows = fn(x.larray, idx_phys)
    else:
        mask_phys = _mask_physical(x, arr)
        c = mask_phys.shape[0] // comm.size
        pos, total = _indexing.mask_positions_fn(c, comm)(mask_phys)
        m = int(total)
        if m == 0:
            return _empty_rows(x, axis)
        c_out = comm.chunk_size(m)
        fn = _indexing.ring_compress_fn(
            x.larray.shape, jdt, axis, m, c_out, comm)
        rows = fn(x.larray, pos)
    gshape = tuple(m if i == axis else s for i, s in enumerate(x.gshape))
    return DNDarray(rows, gshape, x.dtype, axis, x.device, x.comm)


def _getitem_impl(x: DNDarray, key):
    """Global indexing (reference ``__getitem__``, ``dndarray.py:656-912``).

    Fast path: keys that leave the split axis untouched index the physical
    array directly (zero communication). Distributed path: a 1-D integer
    array or boolean mask addressing exactly the split axis runs the ring
    gather/compress programs — O(chunk) memory, no logical materialization.
    General path: index the logical global view and re-shard — correct for
    every NumPy-style key; the data motion is XLA-scheduled.
    """
    adv = _match_split_axis_array_key(x, key)
    if adv is not None:
        return _getitem_split_axis_advanced(x, *adv)
    mixed = _match_mixed_key(x, key)
    if mixed is not None:
        res = _getitem_mixed(x, *mixed)
        if res is not None:
            return res
    paired = _getitem_paired_arrays(x, key)
    if paired is not None:
        return paired
    sliced = _getitem_split_slice(x, key)
    if sliced is not None:
        return sliced
    key = _normalize_key(x, key)
    if _basic_key_fast_path(x, key):
        sub = x.larray[key]
        new_split = _result_split_basic(x, key)
        gshape = list(sub.shape)
        if new_split is not None:
            gshape[new_split] = x.gshape[x.split]
        dtype = x.dtype
        return DNDarray(sub, tuple(gshape), dtype, new_split, x.device, x.comm)
    logical = x._logical()
    sub = logical[key]
    if sub.ndim == 0:
        return DNDarray.from_logical(sub, None, x.device, x.comm, dtype=x.dtype)
    new_split = None
    if x.split is not None:
        if isinstance(key, tuple):
            basic = all(
                isinstance(k, (int, slice)) or k is None or k is Ellipsis for k in key
            )
        else:
            basic = isinstance(key, (int, slice)) or key is None or key is Ellipsis
        if basic:
            new_split = _result_split_basic(x, key)
            if new_split is not None and new_split >= sub.ndim:
                new_split = None
        else:
            # advanced (array/mask) indexing: result stays distributed along
            # the leading axis (reference ``__getitem__`` advanced cases)
            new_split = 0 if sub.ndim > 0 else None
    return DNDarray.from_logical(sub, new_split, x.device, x.comm, dtype=x.dtype)


def _setitem_split_axis_advanced(x: DNDarray, kind, arr, value) -> builtins.bool:
    """``x[idx] = v`` / ``x[mask] = v`` along the split axis without
    materializing the logical array (reference ``dndarray.py:1363-1652``):
    boolean masks with row-broadcastable values apply locally via ``where``;
    integer-array keys rotate (index, value-row) pairs around the ring
    (:func:`heat_tpu.core._indexing.ring_scatter_fn`). Returns False when the
    value shape needs the general fallback."""
    from . import _indexing

    comm = x.comm
    axis = x.split
    jdt = jnp.dtype(x.larray.dtype)
    row_shape = tuple(s for i, s in enumerate(x.gshape) if i != axis)

    val_dn = value if isinstance(value, DNDarray) else None
    if val_dn is not None and not (kind == "int" and val_dn.split == 0):
        value = val_dn._logical()
        val_dn = None

    if kind == "bool":
        val = jnp.asarray(value, jdt)
        # a masked where along the split axis is exact NumPy semantics (and
        # fully local, no ring) iff the value does not vary along that axis:
        # right-aligned against the target shape, its axis dim is 1 or absent
        j = axis - (x.ndim - val.ndim)
        if val.ndim <= x.ndim and (j < 0 or val.shape[j] == 1):
            target_one = tuple(1 if i == axis else s
                               for i, s in enumerate(x.gshape))
            try:
                np.broadcast_shapes(tuple(val.shape), target_one)
            except ValueError:
                return False
            mask_phys = _mask_physical(x, arr)
            sel = mask_phys.reshape(
                tuple(-1 if i == axis else 1 for i in range(x.ndim)))
            x.larray = jnp.where(sel, val, x.larray)
            return True
        # value varies per selected position: reduce to the integer-scatter
        # path over the kept positions
        return _setitem_split_axis_advanced(x, "int", _mask_to_indices(arr),
                                            value)

    idx_phys, m = _index_physical(x, arr)
    if m == 0:
        return True
    c_in = idx_phys.shape[0] // comm.size
    if val_dn is not None and axis == 0 and val_dn.split == 0 and \
            val_dn.gshape == (m,) + row_shape and \
            val_dn.larray.shape == (c_in * comm.size,) + row_shape:
        # split-0 value whose LOGICAL shape matches one row per index and
        # whose chunks align with the index chunks: feed the physical shards
        # straight into the ring (padding rows pair with idx -1 and drop).
        # The gshape check matters: a shorter/broadcast value can share the
        # padded physical shape and would silently write its padding rows
        # (review finding)
        val_phys = val_dn.larray.astype(jdt)
    else:
        if val_dn is not None:
            value = val_dn._logical()
        val = jnp.asarray(value, jdt)
        # NumPy target shape keeps the index dim at the axis position
        # (``x[:, idx] = v`` broadcasts v against (rows, m)); the ring wants
        # the index dim leading
        target = tuple(m if i == axis else s for i, s in enumerate(x.gshape))
        try:
            rows = jnp.moveaxis(jnp.broadcast_to(val, target), axis, 0)
        except (ValueError, TypeError):
            return False
        pad = c_in * comm.size - m
        if pad:
            rows = jnp.concatenate(
                [rows, jnp.zeros((pad,) + row_shape, jdt)])
        val_phys = jax.device_put(rows, comm.sharding(x.ndim, 0))
    fn = _indexing.ring_scatter_fn(x.larray.shape, jdt, axis, c_in, comm)
    x.larray = fn(x.larray, idx_phys, val_phys)
    return True


def _mask_to_indices(arr) -> np.ndarray:
    """Boolean mask (np/list/DNDarray) -> kept int positions (shared by the
    bool branches of getitem/setitem dispatch)."""
    if isinstance(arr, DNDarray):
        arr = np.asarray(arr.numpy())
    return np.nonzero(np.asarray(arr, bool))[0]


def _setitem_mixed(x: DNDarray, keys, arr_pos, kind, arr, value) -> builtins.bool:
    """Mixed-key assignment ``x[idx, 2:5] = v`` without materializing the
    logical array: read-modify-write through the rings — gather the
    addressed rows, apply the basic sub-key locally on the split-0 rows,
    scatter them back. (NumPy leaves duplicate-index write order
    unspecified; here duplicates resolve to the gathered-then-written row.)
    """
    if arr_pos != x.split:
        return False
    # (all-full-slice sub-keys only reach here after the direct scatter
    # already declined the value shape — the RMW below may still broadcast)
    if kind == "bool":
        arr = _mask_to_indices(arr)
        kind = "int"
    rows = _getitem_split_axis_advanced(x, kind, arr)  # m at the split pos
    if rows.ndim == 0 or rows.gshape[x.split] == 0:
        # still validate the value's shape like NumPy does for empty
        # selections (review finding: a silent no-op hides shape bugs)
        target = tuple(
            _slice_len(k, x.gshape[i]) if isinstance(k, slice)
            else (0 if i == arr_pos else None)
            for i, k in enumerate(keys))
        target = tuple(t for t in target if t is not None)
        vshape = (value.gshape if isinstance(value, DNDarray)
                  else np.shape(value))  # logical, never the padded physical
        try:
            np.broadcast_shapes(vshape, target)
        except ValueError:
            raise ValueError(
                f"could not broadcast value of shape {vshape} to indexing "
                f"result of shape {target}")
        return True
    # the basic sub-keys address the non-split dims of the gathered rows
    rows_key = tuple(slice(None) if i == arr_pos else k
                     for i, k in enumerate(keys))
    _setitem_impl(rows, rows_key, value)
    if not _setitem_split_axis_advanced(x, "int", arr, rows):
        raise AssertionError(
            "mixed-setitem scatter-back declined rows it just gathered")
    return True


def _setitem_split_slice(x: DNDarray, key, value) -> builtins.bool:
    """``x[a:b:c] = v`` (and ``x[i] = v``) along the split axis without
    materializing: the selected positions are an affine index sequence, so
    the write is an integer scatter ring; non-trivial other-axis keys go
    read-modify-write through the window-fetch getitem first."""
    parsed = _parse_split_slice_key(x, key)
    if parsed is None:
        return False
    keys, start, step, L, is_int = parsed
    ks = keys[x.split]
    if L == 0:
        # empty selection: still validate the value shape like NumPy
        target = tuple(
            0 if i == x.split else
            (_slice_len(k, x.gshape[i]) if isinstance(k, slice) else None)
            for i, k in enumerate(keys))
        target = tuple(t for t in target if t is not None)
        vshape = (value.gshape if isinstance(value, DNDarray)
                  else np.shape(value))
        try:
            np.broadcast_shapes(vshape, target)
        except ValueError:
            raise ValueError(
                f"could not broadcast value of shape {vshape} to indexing "
                f"result of shape {target}")
        return True
    idx_np = np.arange(L, dtype=np.int64) * step + start
    sub = [k for i, k in enumerate(keys) if i != x.split]
    if any(not (isinstance(k, slice) and k == slice(None)) for k in sub):
        # read-modify-write: window-gather the addressed rows, write the
        # basic sub-key locally, scatter back (same scheme as mixed keys)
        gather_ks = ks if isinstance(ks, slice) else slice(start, start + 1)
        slice_key = tuple(gather_ks if i == x.split else slice(None)
                          for i in range(x.ndim))
        rows = _getitem_impl(x, slice_key)
        rows_key = tuple(slice(None) if i == x.split else k
                         for i, k in enumerate(keys))
        _setitem_impl(rows, rows_key, value)
        value = rows
    elif is_int:
        # NumPy's target for x[i] = v drops the split dim; broadcast there
        # and re-insert the unit axis the axis-keeping scatter expects
        if isinstance(value, DNDarray):
            value = value._logical()
        row_shape = tuple(s_ for i, s_ in enumerate(x.gshape)
                          if i != x.split)
        try:
            vb = jnp.broadcast_to(
                jnp.asarray(value, x.larray.dtype), row_shape)
        except (ValueError, TypeError):
            return False  # invalid shapes raise on the general path
        value = jnp.expand_dims(vb, x.split)
    return _setitem_split_axis_advanced(x, "int", idx_np, value)


def _setitem_impl(x: DNDarray, key, value):
    """Global assignment (reference ``__setitem__``, ``dndarray.py:1363-1652``)."""
    adv = _match_split_axis_array_key(x, key)
    if adv is not None and _setitem_split_axis_advanced(x, *adv, value):
        return
    mixed = _match_mixed_key(x, key)
    if mixed is not None and _setitem_mixed(x, *mixed, value):
        return
    if _setitem_split_slice(x, key, value):
        return
    key = _normalize_key(x, key)
    if isinstance(value, DNDarray):
        value = value._logical()
    value = jnp.asarray(value, x.dtype.jax_type())
    # fast path only without padding: a logical-shaped value cannot broadcast
    # into a padded physical slice
    if x.pad == 0 and _basic_key_fast_path(x, key):
        x.larray = x.larray.at[key].set(value)
        return
    logical = x._logical()
    logical = logical.at[key].set(value)
    new = DNDarray.from_logical(logical, x.split, x.device, x.comm, dtype=x.dtype)
    x.larray = new.larray
