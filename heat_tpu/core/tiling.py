"""Tile decompositions (reference ``heat/core/tiling.py``).

``SplitTiles`` (reference ``:14-330``) tiles a DNDarray in EVERY dimension
by the process count, using the reference's MPI chunking throughout —
a metadata grid in global coordinates (the ``lshape_map`` property reports
the *physical* canonical shards, which may differ along the split axis;
the accessors all use global indexing, so the two never need to agree).
The reference uses the class to drive ``resplit_``'s Send/Irecv loops;
here resharding is a single XLA program, so the transport role is gone,
but the full tile algebra (``tile_ends_g``, ``tile_locations``,
``get_tile_size``, get/set by tile index) is kept so tile-addressed user
code ports directly.

``SquareDiagTiles`` (reference ``:331-1280``) is the diagonal-aligned 2-D
tile decomposition behind the reference's tiled CAQR. This port computes
the reference's exact tile layout — including its documented quirks (e.g.
the split=1, m<n column list extending past the array, reference
``:519-548``) — so code and tests written against the reference see
identical ``row_indices`` / ``col_indices`` / ``tile_map`` / per-process
tile counts. Layout bookkeeping that the reference realises by physically
redistributing the array (``redistribute_`` calls in ``:397``, ``:601``)
is tracked on a *virtual* lshape map instead: the TPU-side array keeps its
canonical even-shard layout (XLA owns physical placement), and the tile →
process assignment is metadata used by the accessors.

Single-controller deviations (documented, by design):

- ``get_start_stop`` returns GLOBAL index bounds (the reference returns
  bounds into the owning process's local tensor; here every accessor
  views the global array, so global bounds are the usable coordinates).
- ``__getitem__`` always returns the tile's data (the reference returns
  ``None`` on processes that do not own the tile; there is no per-rank
  view in a single-controller program). Cross-process tile spans still
  raise ``ValueError`` exactly like the reference.
- The virtual layout uses the reference's MPI chunking (remainder spread
  over the first ranks) so tile boundaries match the reference's
  bit-for-bit; the physical canonical layout may differ — accessors all
  go through global indexing, so the difference is invisible.

Our QR itself is blockwise TSQR/panel-CAQR (``linalg/qr.py``) and needs no
tile bookkeeping.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax.numpy as jnp

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


def _mpi_counts(n: int, w: int) -> np.ndarray:
    """Reference MPI chunk sizes of ``n`` items over ``w`` ranks: floor
    division with the remainder spread over the first ranks."""
    base, rem = divmod(int(n), int(w))
    out = np.full(w, base, dtype=np.int64)
    out[:rem] += 1
    return out


def _mpi_piece(n: int, w: int, rank: int) -> int:
    """Size of ``rank``'s chunk (reference ``comm.chunk`` lshape)."""
    return int(_mpi_counts(n, w)[rank])


def _starts_from_sizes(sizes) -> List[int]:
    """Reference start-index construction (``tiling.py:469-473``):
    ``[0] + sizes[:-1]`` cumulatively summed."""
    return np.concatenate([[0], np.cumsum(np.asarray(sizes, dtype=np.int64))[:-1]]).tolist()


class SplitTiles:
    """Per-process tile map in every dimension (reference ``tiling.py:14``).

    Every dimension is divided into ``comm.size`` tiles by the reference's
    MPI chunking (reference ``:85-94``) — global-coordinate metadata,
    independent of the physical canonical shards (see module docstring).
    """

    def __init__(self, arr: DNDarray):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        self.__arr = arr
        nprocs = arr.comm.size
        lshape_map = np.asarray(arr.lshape_map)
        # reference-convention (MPI-chunked) tile grid in every dimension;
        # pure metadata — the physical canonical shards may differ along
        # the split dim, and every accessor goes through global indexing
        tile_dims = np.zeros((arr.ndim, nprocs), dtype=np.int64)
        for ax in range(arr.ndim):
            tile_dims[ax] = _mpi_counts(arr.shape[ax], nprocs)
        self.__tile_dims = tile_dims
        self.__tile_ends_g = np.cumsum(tile_dims, axis=1)
        self.__lshape_map = lshape_map
        # owner of each tile: the process holding its split-dim range
        # (reference ``set_tile_locations``, ``:108``); split=None means
        # every process holds everything — single controller: process 0
        locs = np.zeros((nprocs,) * arr.ndim, dtype=np.int64)
        if arr.split is not None:
            shape = [1] * arr.ndim
            shape[arr.split] = nprocs
            locs = locs + np.arange(nprocs).reshape(shape)
        self.__tile_locations = locs

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def lshape_map(self) -> np.ndarray:
        return self.__lshape_map

    @property
    def tile_ends_g(self) -> np.ndarray:
        """(ndim, nprocs) global end index of every tile (reference ``:162``)."""
        return self.__tile_ends_g

    @property
    def tile_dimensions(self) -> np.ndarray:
        """(ndim, nprocs) size of every tile (reference ``:173``)."""
        return self.__tile_dims

    @property
    def tile_ends_per_dim(self) -> List[np.ndarray]:
        """Per-dimension global tile ends as a list (row view of
        ``tile_ends_g``; kept for callers written against round <=4)."""
        return [self.__tile_ends_g[d] for d in range(self.__arr.ndim)]

    @property
    def tile_locations(self) -> np.ndarray:
        """Owning process of each tile (reference ``:151``)."""
        return self.__tile_locations

    # ------------------------------------------------------------------ #
    def _key_to_slices(self, key) -> Tuple[slice, ...]:
        if not isinstance(key, tuple):
            key = (key,)
        for k in key:
            if not isinstance(k, (int, np.integer, slice)):
                raise TypeError(f"key type not supported: {type(k)}")
        slices = []
        for dim in range(self.__arr.ndim):
            if dim >= len(key):
                slices.append(slice(None))
                continue
            ends = self.__tile_ends_g[dim]
            starts = np.concatenate([[0], ends[:-1]])
            k = key[dim]
            if isinstance(k, (int, np.integer)):
                slices.append(slice(int(starts[k]), int(ends[k])))
            else:
                if k.step not in (None, 1):
                    raise NotImplementedError(
                        "stepped tile slices are not supported (the skipped "
                        "tiles would be silently included)")
                ks = range(*k.indices(len(ends)))
                if len(ks) == 0:
                    slices.append(slice(0, 0))
                else:
                    slices.append(slice(int(starts[ks[0]]), int(ends[ks[-1]])))
        return tuple(slices)

    def get_tile_size(self, key) -> Tuple[int, ...]:
        """Shape of the tile/s under ``key`` (reference ``:282``)."""
        return tuple(s.stop - s.start if s.stop is not None
                     else self.__arr.shape[d] - (s.start or 0)
                     for d, s in enumerate(self._key_to_slices(key)))

    def __getitem__(self, key):
        """Tile contents by tile index (reference ``:179``; the reference
        returns the owner's local view and ``None`` elsewhere — single
        controller always sees the data). O(tile), not O(array)."""
        slices = self._key_to_slices(key)
        out = self.__arr[slices]
        return out._logical() if isinstance(out, DNDarray) else jnp.asarray(out)

    def __setitem__(self, key, value) -> None:
        """Write a tile back (reference ``:299``)."""
        if not isinstance(value, (int, float, complex, np.ndarray,
                                  jnp.ndarray, DNDarray, np.number)):
            raise TypeError(f"value type not supported: {type(value)}")
        slices = self._key_to_slices(key)
        self.__arr[slices] = value


class SquareDiagTiles:
    """Diagonal-aligned 2-D tile map (reference ``tiling.py:331``).

    Reproduces the reference's tile layout exactly (see module docstring);
    the per-tile accessors work in global coordinates on the canonical
    TPU layout.
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 2):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        if isinstance(tiles_per_proc, bool) or not isinstance(
                tiles_per_proc, (int, np.integer)):
            raise TypeError(
                f"tiles_per_proc must be an int, got {type(tiles_per_proc)}")
        if tiles_per_proc < 1:
            raise ValueError(
                f"tiles_per_proc must be >= 1, got {tiles_per_proc}")
        if arr.ndim != 2:
            raise ValueError(
                f"arr must be 2-dimensional, current shape {arr.shape}")
        self.__arr = arr
        size = arr.comm.size
        split = arr.split if arr.split is not None else 0
        m, n = (int(s) for s in arr.shape)

        # virtual lshape map in the reference's MPI chunking; layout
        # bookkeeping only — the physical array keeps its canonical shards
        lshape_map = np.zeros((size, 2), dtype=np.int64)
        lshape_map[:, split] = _mpi_counts(arr.shape[split], size)
        lshape_map[:, 1 - split] = arr.shape[1 - split]

        # pre-shift so the diagonal does not end with a sliver on the next
        # process (reference ``:388-397``; the reference redistributes the
        # array, we only move the virtual boundary)
        d = 1 if tiles_per_proc <= 2 else tiles_per_proc - 1
        cums = np.cumsum(lshape_map[:, split])
        redist = np.nonzero(cums >= arr.shape[split - 1] - d)[0]
        if redist.size > 0 and m > n and redist[0] != size - 1:
            lshape_map[redist[0], split] += d
            lshape_map[redist[0] + 1, split] -= d

        row_per_proc_list = [tiles_per_proc] * size

        last_diag_pr, col_per_proc_list, col_inds, tile_columns = (
            self.__create_cols(m, n, split, lshape_map, tiles_per_proc, size))

        if split == 0 and tiles_per_proc == 1:
            # fit the full diagonal on as many processes as possible
            # (reference ``__adjust_lshape_sp0_1tile``, ``:577``)
            for cnt in col_inds[:-1]:
                for pr in range(size - 1):
                    if lshape_map[pr, 0] < cnt:
                        h = cnt - lshape_map[pr, 0]
                        lshape_map[pr, 0] += h
                        lshape_map[pr + 1, 0] -= h
            negs = np.nonzero(lshape_map[:, 0] < 0)[0]
            for neg in negs:
                lshape_map[neg - 1, 0] += lshape_map[neg, 0]
                lshape_map[neg, 0] = 0
            last_diag_pr, col_per_proc_list, col_inds, tile_columns = (
                self.__create_cols(m, n, split, lshape_map, tiles_per_proc,
                                   size))
            for e in np.nonzero(lshape_map[:, 0] == 0)[0]:
                row_per_proc_list[e] = 0

        row_inds = list(col_inds)

        if split == 0 and m < n:
            # the very last tile column covers the remainder (ref ``:429``)
            col_inds[-1] = n - sum(col_inds[:-1])

        if split == 0 and last_diag_pr < size - 1:
            # diagonal ends before the last process (ref ``:551``)
            lshape_cumsum = np.cumsum(lshape_map[:, 0])
            diff = int(lshape_cumsum[last_diag_pr]) - n
            if diff > lshape_map[last_diag_pr, 0] / 2:
                row_inds.insert(tile_columns, diff)
                row_per_proc_list[last_diag_pr] += 1
            else:
                row_inds[tile_columns - 1] += diff

        if split == 0 and m > n:
            # even tile rows below the diagonal (ref ``:678``)
            for i in range(last_diag_pr + 1, size):
                for t in range(tiles_per_proc):
                    piece = _mpi_piece(lshape_map[i, 0], tiles_per_proc, t)
                    if row_inds[-1] == 0:
                        row_inds[-1] = piece
                    else:
                        row_inds.append(piece)

        if split == 1 and m < n:
            # extend the column list past the diagonal (ref ``:519``;
            # faithfully reproduces the reference's quirk of creating
            # column boundaries beyond the array for the trailing procs)
            total_cols = sum(col_per_proc_list)
            r = last_diag_pr + 1
            for _ in range(len(col_inds), total_cols):
                col_inds.append(int(lshape_map[r, 1]))
                r += 1
            # NB: the reference computes ``col_proc_ind`` once and does NOT
            # refresh it as inserts shift later indices (``:537-548``) —
            # the layouts below depend on that, so neither do we
            col_proc_ind = np.cumsum(col_per_proc_list)
            for pr in range(size):
                lshape_cumsum = np.cumsum(lshape_map[:, 1])
                col_cumsum = np.cumsum(col_inds)
                diff = int(lshape_cumsum[pr] - col_cumsum[col_proc_ind[pr] - 1])
                if diff > 0 and pr <= last_diag_pr:
                    col_per_proc_list[pr] += 1
                    col_inds.insert(int(col_proc_ind[pr]), diff)
                if pr > last_diag_pr and diff > 0:
                    col_inds.insert(int(col_proc_ind[pr]), diff)

        if split == 1 and m > n:
            # add rows below the diagonal (ref ``:706``)
            if m - n > 10:
                num_ex_row_tiles = 1
                row_inds.append(_mpi_piece(m - n, num_ex_row_tiles, 0))
            else:
                row_inds[-1] = m - sum(row_inds[:-1])

        if m < n:
            row_inds = [r for r in row_inds if r != 0]

        # sizes -> global start indices (ref ``:465-478``)
        col_starts = _starts_from_sizes(col_inds)
        row_starts = _starts_from_sizes(row_inds)
        tile_map = np.zeros((len(row_starts), len(col_starts), 3),
                            dtype=np.int64)
        tile_map[:, :, 0] = np.asarray(row_starts)[:, None]
        tile_map[:, :, 1] = np.asarray(col_starts)[None, :]
        for i in range(size):
            st = sum(row_per_proc_list[:i])
            sp = st + row_per_proc_list[i]
            tile_map[st:sp, :, 2] = i
        tile_map[sum(row_per_proc_list[:size - 1]):, :, 2] = size - 1
        if split == 1:
            st = 0
            for pr, cols in enumerate(col_per_proc_list):
                tile_map[:, st:st + cols, 2] = pr
                st += cols

        self.__lshape_map = lshape_map
        self.__last_diag_pr = int(last_diag_pr)
        self.__tile_map = tile_map
        self.__row_inds = row_starts
        self.__col_inds = col_starts
        self.__row_per_proc_list = (
            row_per_proc_list if split == 0
            else [len(row_starts)] * len(row_per_proc_list))
        self.__col_per_proc_list = (
            col_per_proc_list if split == 1
            else [len(col_starts)] * len(col_per_proc_list))

    @staticmethod
    def __create_cols(m, n, split, lshape_map, tiles_per_proc, size):
        """Diagonal tile columns (reference ``__create_cols``, ``:608``):
        last diagonal process, per-process tile-column counts, tile-column
        sizes, and the diagonal tile-column count."""
        last_tile_cols = tiles_per_proc
        cums = np.cumsum(lshape_map[:, split])
        last_diag_pr = int(np.nonzero(cums >= min(m, n))[0][0])
        # (the reference's small-block while-loop ``:640-646`` is a no-op:
        # ``1 < floor_div < 2`` is unsatisfiable for integers; kept out)
        col_per_proc_list = [tiles_per_proc] * (last_diag_pr + 1)
        col_per_proc_list[-1] = last_tile_cols
        if last_diag_pr < size - 1 and split == 1:
            col_per_proc_list.extend([1] * (size - last_diag_pr - 1))
        tile_columns = tiles_per_proc * last_diag_pr + last_tile_cols
        diag_crossings = cums[:last_diag_pr + 1].tolist()
        diag_crossings[-1] = min(diag_crossings[-1], min(m, n))
        diag_crossings = [0] + diag_crossings
        col_inds = []
        for col in range(tile_columns):
            off = col // tiles_per_proc
            w = tiles_per_proc if off != last_diag_pr else last_tile_cols
            col_inds.append(_mpi_piece(
                diag_crossings[off + 1] - diag_crossings[off], w,
                col % tiles_per_proc))
        return last_diag_pr, col_per_proc_list, col_inds, tile_columns

    # ------------------------------------------------------------------ #
    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def col_indices(self) -> List[int]:
        """Global start index of every tile column (reference ``:732``)."""
        return list(self.__col_inds)

    @property
    def row_indices(self) -> List[int]:
        """Global start index of every tile row (reference ``:754``)."""
        return list(self.__row_inds)

    @property
    def lshape_map(self) -> np.ndarray:
        """The virtual (reference-convention) local-shape map the tile
        layout was computed from (reference ``:739``)."""
        return self.__lshape_map

    @property
    def last_diagonal_process(self) -> int:
        """Rank of the last process with diagonal elements (ref ``:747``)."""
        return self.__last_diag_pr

    @property
    def tile_columns(self) -> int:
        return len(self.__col_inds)

    @property
    def tile_rows(self) -> int:
        return len(self.__row_inds)

    @property
    def tile_columns_per_process(self) -> List[int]:
        return list(self.__col_per_proc_list)

    @property
    def tile_rows_per_process(self) -> List[int]:
        return list(self.__row_per_proc_list)

    @property
    def tile_map(self) -> np.ndarray:
        """(tile_rows, tile_columns, 3) array of (row start, col start,
        owning process) per tile (reference ``:775``)."""
        return self.__tile_map

    # ------------------------------------------------------------------ #
    def _validate_key(self, key) -> None:
        parts = key if isinstance(key, tuple) else (key,)
        if not isinstance(key, (int, np.integer, slice, tuple)):
            raise TypeError(f"key must be int, slice or tuple, got {type(key)}")
        for k in parts:
            if not isinstance(k, (int, np.integer, slice)):
                raise TypeError(f"invalid tile key element: {type(k)}")

    def _key_procs(self, key) -> np.ndarray:
        return np.unique(self.__tile_map[key][..., 2])

    def get_start_stop(self, key) -> Tuple[int, int, int, int]:
        """``(row_start, row_stop, col_start, col_stop)`` of the tile/s
        under ``key`` in GLOBAL indices (reference ``get_start_stop``,
        ``:824``, returns owner-local bounds; single controller views the
        global array, see module docstring). Raises ``ValueError`` when the
        key spans tiles on more than one process, like the reference."""
        self._validate_key(key)
        procs = self._key_procs(key)
        if procs.size > 1:
            raise ValueError(
                f"Tile/s must be located on one process, currently on: "
                f"{procs.tolist()}")
        row_inds = self.row_indices + [int(self.__arr.shape[0])]
        col_inds = self.col_indices + [int(self.__arr.shape[1])]
        key = [key] if isinstance(key, (int, np.integer)) else list(key)
        if len(key) == 1:
            key.append(slice(0, None))

        def rng(idx, inds):
            if isinstance(idx, (int, np.integer)):
                return int(inds[idx]), int(inds[idx + 1])
            start = inds[idx.start] if idx.start is not None else 0
            stop = inds[idx.stop] if idx.stop is not None else inds[-1]
            return int(start), int(stop)

        st0, sp0 = rng(key[0], row_inds)
        st1, sp1 = rng(key[1], col_inds)
        return st0, sp0, st1, sp1

    def __getitem__(self, key):
        """Tile/s contents as a jnp array (reference ``:890`` returns the
        owner's local view / ``None`` elsewhere; single controller always
        returns the data). ``ValueError`` on cross-process spans."""
        self._validate_key(key)
        procs = self._key_procs(key)
        if procs.size > 1:
            raise ValueError("Slicing across splits is not allowed")
        r0, r1, c0, c1 = self.get_start_stop(key)
        out = self.__arr[r0:r1, c0:c1]
        return out._logical() if isinstance(out, DNDarray) else jnp.asarray(out)

    def __setitem__(self, key, value) -> None:
        """Write tile/s (reference ``:1212``)."""
        self._validate_key(key)
        procs = self._key_procs(key)
        if procs.size > 1:
            raise ValueError("setting across splits is not allowed")
        r0, r1, c0, c1 = self.get_start_stop(key)
        self.__arr[r0:r1, c0:c1] = value

    def local_get(self, key):
        """Tile/s addressed in the calling process's local tile coordinates
        (reference ``:939``); single controller: process 0's block."""
        return self[self.local_to_global(key, self.__arr.comm.rank)]

    def local_set(self, key, value) -> None:
        """Write tile/s addressed in local tile coordinates (reference
        ``:959``; the reference mutates the returned torch view — jax
        arrays are immutable, so this routes through global setitem)."""
        self[self.local_to_global(key, self.__arr.comm.rank)] = value

    def local_to_global(self, key, rank: int):
        """Local tile coordinates on ``rank`` -> global tile coordinates
        (reference ``local_to_global``, ``:1022``)."""
        self._validate_key(key)
        key = ([key, slice(0, None)] if isinstance(key, (int, np.integer, slice))
               else list(key))
        split = self.__arr.split
        if split == 0:
            prev = sum(self.__row_per_proc_list[:rank])
            loc = self.__row_per_proc_list[rank]
            if isinstance(key[0], (int, np.integer)):
                key[0] = int(key[0]) + prev
            else:
                start = (key[0].start or 0) + prev
                stop = (key[0].stop + prev if key[0].stop is not None
                        else prev + loc)
                stop = stop if stop - start < loc else start + loc
                key[0] = slice(start, stop)
        if split == 1:
            prev = sum(self.__col_per_proc_list[:rank])
            loc = self.__col_per_proc_list[rank]
            if isinstance(key[1], (int, np.integer)):
                key[1] = int(key[1]) + prev
            else:
                start = (key[1].start or 0) + prev
                stop = (key[1].stop + prev if key[1].stop is not None
                        else prev + loc)
                stop = stop if stop - start < loc else start + loc
                key[1] = slice(start, stop)
        return tuple(key)

    def match_tiles(self, tiles_to_match: "SquareDiagTiles") -> None:
        """Align this map with ``tiles_to_match`` (reference ``:1084``,
        used to give Q a tile map compatible with A/R's). Metadata-only:
        where the reference physically redistributes the arrays, the
        canonical TPU layout stays put and only the virtual maps move."""
        if not isinstance(tiles_to_match, SquareDiagTiles):
            raise TypeError(
                f"tiles_to_match must be SquareDiagTiles, got "
                f"{type(tiles_to_match)}")
        base, match = self.__arr, tiles_to_match.__arr
        size = base.comm.size
        if base.split == 0 and match.split == 0:
            # rows (and cols: square logic) copied from the matched map
            self.__lshape_map = tiles_to_match.lshape_map.copy()
            self.__row_per_proc_list = list(
                tiles_to_match.__row_per_proc_list)
            self.__col_per_proc_list = (
                [tiles_to_match.tile_rows] * len(self.__row_per_proc_list))
            src = (tiles_to_match.__row_inds
                   if base.shape[0] >= base.shape[1]
                   else tiles_to_match.__col_inds)
            self.__row_inds = list(src)
            self.__col_inds = list(src)
            self.__rebuild_tile_map()
        elif base.split == 0 and match.split == 1:
            src = (tiles_to_match.__row_inds
                   if base.shape[0] <= base.shape[1]
                   else tiles_to_match.__col_inds)
            self.__row_inds = list(src)
            self.__col_inds = list(src)
            rows_per = [x for x in self.__col_inds if x < base.shape[0]]
            ldp = tiles_to_match.last_diagonal_process
            target_0 = list(tiles_to_match.lshape_map[:ldp, 1])
            end0 = base.shape[0] - sum(target_0[:ldp])
            target_0 = np.asarray(
                target_0 + [end0] + [0] * (size - 1 - ldp), dtype=np.int64)
            self.__lshape_map = self.__lshape_map.copy()
            self.__lshape_map[:, 0] = target_0
            t0c = np.cumsum(target_0)
            bounds = np.asarray(rows_per + [base.shape[0]])
            self.__row_per_proc_list = []
            st = 0
            for i in range(size):
                self.__row_per_proc_list.append(
                    int(((st < bounds) & (bounds <= t0c[i])).sum()))
                st = t0c[i]
            self.__col_per_proc_list = [self.tile_columns] * size
            self.__last_diag_pr = size - 1
            self.__rebuild_tile_map()
        else:
            raise NotImplementedError(
                "match_tiles supports split combinations (0,0) and (0,1), "
                f"got ({base.split}, {match.split}) — same as the reference "
                "(``tiling.py:1108-1210`` implements only these)")

    def __rebuild_tile_map(self) -> None:
        tile_map = np.zeros((self.tile_rows, self.tile_columns, 3),
                            dtype=np.int64)
        tile_map[:, :, 0] = np.asarray(self.__row_inds)[:, None]
        tile_map[:, :, 1] = np.asarray(self.__col_inds)[None, :]
        size = self.__arr.comm.size
        for i in range(size):
            st = sum(self.__row_per_proc_list[:i])
            sp = st + self.__row_per_proc_list[i]
            tile_map[st:sp, :, 2] = i
        tile_map[sum(self.__row_per_proc_list[:size - 1]):, :, 2] = size - 1
        self.__tile_map = tile_map
