"""Tile decompositions (reference ``heat/core/tiling.py``).

``SplitTiles`` (reference ``:14-330``) describes the per-device tiles of a
DNDarray in every dimension; the reference uses it to drive ``resplit_``'s
Send/Irecv loops. Here resharding is a single XLA program, so ``SplitTiles``
survives purely as an *introspection* utility with the same accessors.

``SquareDiagTiles`` (reference ``:331-1280``) exists to drive the tiled CAQR;
our QR is blockwise TSQR (see ``linalg/qr.py``), which needs no tile
bookkeeping — the class is provided for structural introspection only.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


class SplitTiles:
    """Per-device tile map in every dimension (reference ``tiling.py:14``)."""

    def __init__(self, arr: DNDarray):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        self.__arr = arr
        comm = arr.comm
        nprocs = comm.size
        # tile ends along each dimension: along the split axis these are the
        # canonical chunk boundaries; other axes are one tile
        ends = []
        for dim, gsize in enumerate(arr.shape):
            if dim == arr.split:
                counts, displs = comm.counts_displs(gsize)
                ends.append(np.cumsum(np.asarray(counts)))
            else:
                ends.append(np.asarray([gsize]))
        self.__tile_ends_per_dim = ends
        locs = np.zeros([len(e) for e in ends], dtype=np.int64)
        if arr.split is not None:
            shape = [1] * arr.ndim
            shape[arr.split] = nprocs
            locs = np.arange(nprocs).reshape(shape) * np.ones_like(locs)
        self.__tile_locations = locs

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_ends_per_dim(self) -> List[np.ndarray]:
        return self.__tile_ends_per_dim

    @property
    def tile_locations(self) -> np.ndarray:
        """Which device owns each tile (reference ``set_tile_locations``, ``:108``)."""
        return self.__tile_locations

    @property
    def tile_dimensions(self) -> List[np.ndarray]:
        dims = []
        for ends in self.__tile_ends_per_dim:
            starts = np.concatenate([[0], ends[:-1]])
            dims.append(ends - starts)
        return dims

    def __getitem__(self, key) -> np.ndarray:
        """Tile contents by tile index (gathered as numpy)."""
        slices = self._key_to_slices(key)
        return self.__arr.numpy()[slices]

    def _key_to_slices(self, key):
        if not isinstance(key, tuple):
            key = (key,)
        slices = []
        for dim, k in enumerate(key):
            ends = self.__tile_ends_per_dim[dim]
            starts = np.concatenate([[0], ends[:-1]])
            if isinstance(k, int):
                slices.append(slice(int(starts[k]), int(ends[k])))
            else:
                raise NotImplementedError("only integer tile indices are supported")
        return tuple(slices)


class SquareDiagTiles:
    """Diagonal-aligned 2-D tile map (reference ``tiling.py:331``).

    Introspection-only: computes the diagonal-square tile grid the reference
    uses for its tiled QR. The TSQR in ``linalg/qr.py`` replaces the tile
    algebra itself.
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 1):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        if arr.ndim != 2:
            raise ValueError("SquareDiagTiles requires a 2-D DNDarray")
        self.__arr = arr
        nprocs = arr.comm.size
        n, m = arr.shape
        # square tiles along the diagonal: tile size = chunk of the split
        # axis divided into tiles_per_proc pieces
        split = arr.split if arr.split is not None else 0
        chunk = arr.comm.chunk_size(arr.shape[split])
        tile = max(1, chunk // max(1, tiles_per_proc))
        row_ends = np.arange(tile, n + tile, tile).clip(max=n)
        col_ends = np.arange(tile, m + tile, tile).clip(max=m)
        self.__row_per_proc_list = [len(row_ends) // nprocs] * nprocs
        self.__tile_rows = len(row_ends)
        self.__tile_columns = len(col_ends)
        self.__row_ends = row_ends
        self.__col_ends = col_ends

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_rows(self) -> int:
        return self.__tile_rows

    @property
    def tile_columns(self) -> int:
        return self.__tile_columns

    @property
    def lshape_map(self):
        return self.__arr.lshape_map

    @property
    def row_indices(self) -> List[int]:
        return np.concatenate([[0], self.__row_ends[:-1]]).tolist()

    @property
    def col_indices(self) -> List[int]:
        return np.concatenate([[0], self.__col_ends[:-1]]).tolist()
