"""Tile decompositions (reference ``heat/core/tiling.py``).

``SplitTiles`` (reference ``:14-330``) describes the per-device tiles of a
DNDarray in every dimension; the reference uses it to drive ``resplit_``'s
Send/Irecv loops. Here resharding is a single XLA program, so the *transport*
role is gone — but the tile algebra itself is functional: tiles can be read
and written by tile index (``tiles[i]``, ``tiles[i] = v``), backed by the
DNDarray's global indexing.

``SquareDiagTiles`` (reference ``:331-1280``) drives the reference's tiled
CAQR. Our QR is blockwise TSQR/panel-CAQR (``linalg/qr.py``) and needs no
tile bookkeeping, but the class supports the reference's per-tile accessors
(``get_start_stop``, ``__getitem__``/``__setitem__``, ``local_get``/
``local_set``, ``match_tiles``) so tile-based user code ports directly.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax.numpy as jnp

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


def _ends_to_starts(ends: np.ndarray) -> np.ndarray:
    return np.concatenate([[0], ends[:-1]])


class SplitTiles:
    """Per-device tile map in every dimension (reference ``tiling.py:14``)."""

    def __init__(self, arr: DNDarray):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        self.__arr = arr
        comm = arr.comm
        nprocs = comm.size
        # tile ends along each dimension: along the split axis these are the
        # canonical chunk boundaries; other axes are one tile
        ends = []
        for dim, gsize in enumerate(arr.shape):
            if dim == arr.split:
                counts, displs = comm.counts_displs(gsize)
                ends.append(np.cumsum(np.asarray(counts)))
            else:
                ends.append(np.asarray([gsize]))
        self.__tile_ends_per_dim = ends
        locs = np.zeros([len(e) for e in ends], dtype=np.int64)
        if arr.split is not None:
            shape = [1] * arr.ndim
            shape[arr.split] = nprocs
            locs = np.arange(nprocs).reshape(shape) * np.ones_like(locs)
        self.__tile_locations = locs

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_ends_per_dim(self) -> List[np.ndarray]:
        return self.__tile_ends_per_dim

    @property
    def tile_locations(self) -> np.ndarray:
        """Which device owns each tile (reference ``set_tile_locations``, ``:108``)."""
        return self.__tile_locations

    @property
    def tile_dimensions(self) -> List[np.ndarray]:
        dims = []
        for ends in self.__tile_ends_per_dim:
            starts = _ends_to_starts(ends)
            dims.append(ends - starts)
        return dims

    def __getitem__(self, key):
        """Tile contents by tile index (reference returns the local torch
        tile; here the tile block as a jnp array — O(tile), not O(array))."""
        slices = self._key_to_slices(key)
        out = self.__arr[slices]
        return out._logical() if isinstance(out, DNDarray) else jnp.asarray(out)

    def __setitem__(self, key, value) -> None:
        """Write a tile back (reference ``SplitTiles.__setitem__``)."""
        slices = self._key_to_slices(key)
        self.__arr[slices] = value

    def _key_to_slices(self, key):
        if not isinstance(key, tuple):
            key = (key,)
        slices = []
        for dim, k in enumerate(key):
            ends = self.__tile_ends_per_dim[dim]
            starts = _ends_to_starts(ends)
            if isinstance(k, (int, np.integer)):
                slices.append(slice(int(starts[k]), int(ends[k])))
            elif isinstance(k, slice):
                if k.step not in (None, 1):
                    raise NotImplementedError(
                        "stepped tile slices are not supported (the skipped "
                        "tiles would be silently included)")
                ks = range(*k.indices(len(ends)))
                if len(ks) == 0:
                    slices.append(slice(0, 0))
                else:
                    slices.append(slice(int(starts[ks[0]]), int(ends[ks[-1]])))
            else:
                raise NotImplementedError(
                    "tile keys must be ints or slices of tile indices")
        return tuple(slices)


class SquareDiagTiles:
    """Diagonal-aligned 2-D tile map (reference ``tiling.py:331``).

    Computes the diagonal-square tile grid the reference uses for its tiled
    QR and supports the per-tile accessor surface; the TSQR/panel-CAQR in
    ``linalg/qr.py`` replaces the tile *algebra* (Householder merges).
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 1):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        if arr.ndim != 2:
            raise ValueError("SquareDiagTiles requires a 2-D DNDarray")
        self.__arr = arr
        nprocs = arr.comm.size
        n, m = arr.shape
        # square tiles along the diagonal: tile size = chunk of the split
        # axis divided into tiles_per_proc pieces
        split = arr.split if arr.split is not None else 0
        chunk = arr.comm.chunk_size(arr.shape[split])
        tile = max(1, chunk // max(1, tiles_per_proc))
        row_ends = np.arange(tile, n + tile, tile).clip(max=n)
        col_ends = np.arange(tile, m + tile, tile).clip(max=m)
        self.__row_per_proc_list = [len(row_ends) // nprocs] * nprocs
        self.__set_ends(row_ends, col_ends)

    def __set_ends(self, row_ends, col_ends) -> None:
        self.__row_ends = np.asarray(row_ends)
        self.__col_ends = np.asarray(col_ends)
        self.__tile_rows = len(self.__row_ends)
        self.__tile_columns = len(self.__col_ends)

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_rows(self) -> int:
        return self.__tile_rows

    @property
    def tile_columns(self) -> int:
        return self.__tile_columns

    @property
    def lshape_map(self):
        return self.__arr.lshape_map

    @property
    def row_indices(self) -> List[int]:
        return _ends_to_starts(self.__row_ends).tolist()

    @property
    def col_indices(self) -> List[int]:
        return _ends_to_starts(self.__col_ends).tolist()

    def get_start_stop(self, key) -> Tuple[int, int, int, int]:
        """(row_start, row_stop, col_start, col_stop) of tile ``key`` =
        (tile_row, tile_col) (reference ``get_start_stop``, ``:820``)."""
        tr, tc = key if isinstance(key, tuple) else (key, slice(None))
        row_starts = _ends_to_starts(self.__row_ends)
        col_starts = _ends_to_starts(self.__col_ends)

        def rng(idx, starts, ends):
            if isinstance(idx, (int, np.integer)):
                return int(starts[idx]), int(ends[idx])
            if idx.step not in (None, 1):
                raise NotImplementedError(
                    "stepped tile slices are not supported (the skipped "
                    "tiles would be silently included)")
            ks = range(*idx.indices(len(ends)))
            if len(ks) == 0:
                return 0, 0
            return int(starts[ks[0]]), int(ends[ks[-1]])

        r0, r1 = rng(tr, row_starts, self.__row_ends)
        c0, c1 = rng(tc, col_starts, self.__col_ends)
        return r0, r1, c0, c1

    def __getitem__(self, key):
        """Tile (or tile-range) contents as a jnp array (reference ``:900``:
        the local torch view)."""
        r0, r1, c0, c1 = self.get_start_stop(key)
        out = self.__arr[r0:r1, c0:c1]
        return out._logical() if isinstance(out, DNDarray) else jnp.asarray(out)

    def __setitem__(self, key, value) -> None:
        """Write a tile back (reference ``:960``)."""
        r0, r1, c0, c1 = self.get_start_stop(key)
        self.__arr[r0:r1, c0:c1] = value

    def local_get(self, key):
        """Reference ``local_get`` (``:1000``): tile addressed in *local*
        tile coordinates of one device's row block. Single-controller: local
        tile row ``i`` of device ``d`` is global tile row
        ``d * rows_per_proc + i``."""
        return self[key]

    def local_set(self, key, value) -> None:
        self[key] = value

    def match_tiles(self, other: "SquareDiagTiles") -> None:
        """Align this tile map's boundaries with ``other`` where the global
        extents coincide (reference ``match_tiles``, ``:1084``, used to give
        Q/R tile maps compatible with A's). Boundaries on an axis are adopted
        from ``other`` when that axis has the same global size; otherwise
        they are clipped to this array's extent."""
        if not isinstance(other, SquareDiagTiles):
            raise TypeError(
                f"other must be SquareDiagTiles, got {type(other)}")
        n, m = self.__arr.shape
        row_ends = (np.asarray(other.__row_ends)
                    if other.__arr.shape[0] == n
                    else np.unique(np.asarray(other.__row_ends).clip(max=n)))
        col_ends = (np.asarray(other.__col_ends)
                    if other.__arr.shape[1] == m
                    else np.unique(np.asarray(other.__col_ends).clip(max=m)))
        self.__set_ends(row_ends, col_ends)
