"""Exponential and logarithmic operations (reference ``heat/core/exponential.py:26-318``)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = [
    "exp",
    "exp2",
    "expm1",
    "i0",
    "log",
    "log10",
    "log1p",
    "log2",
    "logaddexp",
    "logaddexp2",
    "sqrt",
    "square",
]


def exp(x: DNDarray, out=None) -> DNDarray:
    """Element-wise e**x (reference ``exponential.py:26``)."""
    return _operations._local_op(jnp.exp, x, out)


def expm1(x: DNDarray, out=None) -> DNDarray:
    """Element-wise e**x - 1 (reference ``:60``)."""
    return _operations._local_op(jnp.expm1, x, out)


def exp2(x: DNDarray, out=None) -> DNDarray:
    """Element-wise 2**x (reference ``:94``)."""
    return _operations._local_op(jnp.exp2, x, out)


def log(x: DNDarray, out=None) -> DNDarray:
    """Element-wise natural log (reference ``:128``)."""
    return _operations._local_op(jnp.log, x, out)


def log2(x: DNDarray, out=None) -> DNDarray:
    """Element-wise base-2 log (reference ``:162``)."""
    return _operations._local_op(jnp.log2, x, out)


def log10(x: DNDarray, out=None) -> DNDarray:
    """Element-wise base-10 log (reference ``:196``)."""
    return _operations._local_op(jnp.log10, x, out)


def log1p(x: DNDarray, out=None) -> DNDarray:
    """Element-wise log(1+x) (reference ``:230``)."""
    return _operations._local_op(jnp.log1p, x, out)


def logaddexp(x1, x2, out=None, where=None) -> DNDarray:
    """log(exp(x1) + exp(x2)) (reference ``:250``)."""
    return _operations._binary_op(jnp.logaddexp, x1, x2, out, where)


def logaddexp2(x1, x2, out=None, where=None) -> DNDarray:
    """log2(2**x1 + 2**x2) (reference ``:270``)."""
    return _operations._binary_op(jnp.logaddexp2, x1, x2, out, where)


def sqrt(x: DNDarray, out=None) -> DNDarray:
    """Element-wise square root (reference ``:264``)."""
    return _operations._local_op(jnp.sqrt, x, out)


def square(x: DNDarray, out=None) -> DNDarray:
    """Element-wise square (reference ``:298``)."""
    return _operations._local_op(jnp.square, x, out)


def i0(x: DNDarray, out=None) -> DNDarray:
    """Modified Bessel function of order 0 (``numpy.i0``)."""
    from jax.scipy.special import i0 as _jsp_i0

    return _operations._local_op(_jsp_i0, x, out)
