"""Fused lazy op-chain engine: trace ``ht.*`` chains into one cached program.

Eagerly, every elementwise ``ht.*`` op is its own XLA dispatch: a 16-op
chain costs 16 program launches and 15 materialized intermediates with
zero cross-op fusion — exactly the op-by-op overhead the HeAT reference
accepts on MPI+torch but that XLA is built to eliminate. This module makes
the op engine *deferred* instead: ``__local_op`` / ``__binary_op`` (and
the split-preserving ``__cum_op``) record :class:`_Node` entries into a
per-array expression DAG, and the first **materialization point** flushes
the whole chain as ONE jitted program.

Materialization points (flush triggers)
---------------------------------------
Everything in the codebase reads the physical array through
``DNDarray.larray``, so the property is the single choke point: resplits
and split-changing ops, ``out=`` / ``where=`` (the op engine falls back to
eager there), ``.numpy()`` / ``__array__`` / ``item()`` / printing,
comparisons used in control flow (``__bool__``), and the tape-depth cap
(``HEAT_TPU_FUSION_MAX_OPS``, default 32). Padding discipline survives by
construction: recorded nodes never read across the split axis *blindly* —
a reduction records a neutral-element **mask node** over the canonical
padding first (the tape form of ``DNDarray.filled``), a cum over the
split axis or an alignment resplit materializes its inputs first, so
collective placement stays exactly where the explicit resharding planner
(arXiv:2112.01075) put it, and fused programs for split-preserving chains
lower with ZERO collectives (audited in ``tests/test_fusion.py``).

Reduction nodes (terminal collectives on the tape)
--------------------------------------------------
``__reduce_op`` (sum/prod/max/min/any/all and the mean/var/std/norm
family built on them) records a **reduce node** instead of forcing
``filled()``-materialization. A flush whose DAG contains a reduce node
over the split axis compiles the whole chain as ONE ``shard_map`` program
— elementwise chain on shard-local blocks, neutral-element pad masking
(global-index iota, reusing the pad bookkeeping so uneven gshapes stay
correct), shard-local reduce, then one ``lax.psum``/``pmax``/``pmin``.
Mutually independent same-kind reductions in one DAG (weighted average's
``sum(x*w)``/``sum(w)``, single-pass var's ``sum(x²)``/``sum(x)``) are
packed into ONE flattened collective per phase, so XLA emits exactly one
(tuple-fused) all-reduce and the O(n) elementwise intermediate never
exists. Heat itself merges split-axis reductions into a single MPI
Allreduce (arXiv:2007.13552); folding the combiner into the collective is
where the traffic win lives (arXiv:2004.09362). Reduce tapes the
translator cannot prove shard_map-safe (unregistered combiner such as
``prod``, exotic operand layouts) still fuse as one ``jax.jit`` program
with GSPMD-placed collectives — never eagerly. Opt-out:
``HEAT_TPU_FUSION_REDUCE=0`` restores the eager ``filled()`` flush.

Contraction nodes (planned distributed GEMM on the tape)
--------------------------------------------------------
``linalg.matmul`` (and through it ``dot``/``outer``, plus the 2-operand
``einsum``/``tensordot`` paths) records a **contract node** instead of
forcing ``filled(0)``-materialization of both operand tapes. The per-
split-case collective plan is explicit in the shard_map translation —
the by-construction discipline the reference Heat spends ~670 lines of
hand-scheduled Bcasts on (arXiv:2007.13552, ``basics.py:424-1095``):

* ``a.split=0`` (× replicated ``b``) or ``b.split=1`` (× replicated
  ``a``): local GEMM on blocks, output keeps the split, ZERO collectives;
* contracted-dim sharded (``a.split=1`` / ``b.split=0`` in any
  combination with a replicated other side): shard-local partial GEMM +
  ``lax.psum``, PACKED into the same phase-sorted flattened collective as
  any independent same-kind reductions on the tape (arXiv:2004.09362);
* mixed 2-D layouts outside the block model fall back to ONE plain-jit
  GSPMD program, exactly like non-translatable reduce tapes. Batched
  (>2-D) matmul never records — it dispatches eagerly on shard-local
  blocks in ``linalg.basics._matmul_batched``.

Zero-fill masking of contracted-axis padding rides the tape as MASK
nodes (skipped entirely when the operand's ``pad_is_zero`` bit proves
the buffer is already canonically zero-padded), so ``x @ w + b`` then an
activation then a split-axis reduction compiles as ONE cached executable
with exactly the planner's collectives. Opt-out:
``HEAT_TPU_FUSION_CONTRACT=0`` restores the eager ``_filled0`` GEMM.

Resplit nodes (the reshard planner folded into the DAG)
-------------------------------------------------------
``DNDarray.resplit``/``resplit_`` on a PENDING tape records a **RESPLIT
node** (:func:`record_resplit`) instead of flushing: the reshard
planner (:mod:`.resharding`, arXiv:2112.01075) already knows the exact
one-collective move per ``(from, to)`` pair, and ``_plan_sm`` translates
it mid-body inside the one shard_map program — local pad → ONE
``lax.all_to_all`` → local reslice for split→split, a zero-collective
local ``dynamic_slice`` for None→split, ``all_gather`` for split→None —
with per-node split state switching from the source to the target layout
downstream of the node. ``chain → resplit → chain → reduce`` therefore
compiles as ONE executable containing exactly the planner's collective
count, and the op-engine's binary-op alignment resplits plus the
manipulations family's pre-alignment resplits stop being flush barriers.
Non-translatable cases (degenerate layouts, non-canonical physicals,
foreign meshes) decline recording and take the historic
flush-then-planned-resplit path — correctness never depends on the
translation. Opt-out: ``HEAT_TPU_FUSION_RESPLIT=0``; counters
``op_engine.fusion_resplit_nodes`` / ``_fallbacks`` / ``_flushes``.

Program identity and caching
----------------------------
A flush compiles at most once per *chain signature*: a structural key over
(comm cache key, per-leaf ``(shape, dtype, weak, sharding)``, the node
list ``(op, arg slots, static kwargs)``, output slots, donation slots),
served from a generalized :class:`~heat_tpu.utils.program_cache.ProgramCache`
(``fusion.program_hits`` / ``_misses`` / ``_compiles`` counters). Python
scalars enter the program as 0-d *arguments* (weak-typed, value-cached) —
never as baked constants — so XLA cannot constant-fold them differently
from the eager dispatch (e.g. div-by-const → reciprocal-multiply), and one
program serves every scalar value.

Donation
--------
Leaves whose owning DNDarray is dead and whose buffer the tape provably
holds the only references to (exact ``sys.getrefcount`` accounting) are
donated to XLA, so ``x = ht.exp(x * 2)``-style rebinding chains reuse the
input buffer. Interior nodes never materialize at all unless another live
array shares them.

Numerics
--------
Fused results are bitwise-equal to eager for integer/bool dtypes and for
float chains without a multiply feeding directly into an add/sub. Where
such pairs fuse, XLA's backend contracts them into an FMA — a *more*
accurate single rounding that can differ from eager (and NumPy) by 1 ulp.
``tests/test_fusion.py`` pins both properties; ``doc/fusion.md`` documents
the contract.

Differentiable tapes (whole-train-step tracing)
-----------------------------------------------
:func:`trace_step` compiles an entire user train step — loss, gradients
via :func:`value_and_grad`, optimizer update — into ONE cached, donated
executable over the ``DNDarray`` leaves of its arguments: the classic JAX
one-jitted-train-step idiom the eager NumPy surface otherwise denies.
Tracing reuses the op engine itself: under a jax trace every recorded-op
entry point declines (tracers must never be captured into a cross-call
tape), so the step body dispatches through the *eager* op semantics onto
abstract leaves and the whole step lowers as one jaxpr. Gradient
all-reduces for the model-level fused steps
(:meth:`heat_tpu.nn.TransformerLM.make_train_step`,
:class:`heat_tpu.nn.DataParallel`) are PACKED by :func:`packed_psum` —
one flattened collective per dtype, the train-step form of the flush
body's phase-barrier packing (arXiv:2004.09362). Step bodies that cannot
trace (host branching on values, ``.numpy()``/``float()`` round-trips)
fall back to the eager path, counted in
``op_engine.fusion_step_fallbacks``. Opt-out: ``HEAT_TPU_FUSION_STEP=0``.

Quantized packed collectives (block-scaled wire formats)
--------------------------------------------------------
``HEAT_TPU_QUANT_COLLECTIVES`` selects an opt-in wire codec for the
packed float all-reduces this engine emits — the flush body's
:func:`packed all-reduce <_sm_body>` packing and every
:func:`packed_psum` call site (the model-level fused train steps,
``DataParallel.step``, DASO's slow-tier blending). EQuARX
(arXiv:2506.17615) shows block-scaled quantized all-reduce recovers ~2×
collective bytes at negligible accuracy cost, and the decomposition it
rides is exactly the generalized-allreduce structure
(arXiv:2004.09362) the phase scheduler already plans around:

* ``bf16`` — the payload crosses the wire as ONE bf16 all-reduce
  (encode = round-to-nearest downcast, decode = upcast): half the f32
  bytes on hardware with native bf16 reductions (TPU ICI).
* ``int8`` — block-scaled (``HEAT_TPU_QUANT_BLOCK``-element blocks,
  default 128, bf16 scales riding the payload): encode int8 → reduce-scatter-style ``all_to_all`` over the
  shard axis → exact f32 combine of the dequantized summand blocks →
  bf16 ``all_gather`` of the combined chunks → decode. The float wire
  legs travel bitcast to ``u16`` so XLA:CPU's float normalization
  cannot silently upcast them back to f32.

Integer/bool collectives, ``pmax``/``pmin``, f64, and payloads below
``HEAT_TPU_QUANT_MIN_NUMEL`` (default 256 elements) stay exact. The
codec (and floor) join the program keys, so toggling never poisons a
cached exact program; ``HEAT_TPU_QUANT_COLLECTIVES=0`` is bitwise
today's behavior. Counters: ``op_engine.quant_collectives`` /
``quant_bytes_saved`` (ring-wire model, the same formulas
``heat_tpu.utils.hlo_audit.collective_bytes`` applies to real HLO) /
``quant_fallbacks``. Error contract and the when-not-to table live in
``doc/fusion.md``.

Chunked, double-buffered packed collectives (software pipelining)
-----------------------------------------------------------------
``HEAT_TPU_FUSION_CHUNKS=N`` (default 1 = off) splits every packed
collective payload this engine emits — the flush body's phase-barrier
packing and every :func:`packed_psum` call site — into up to N contiguous
pipeline chunks, each a separate collective, chained with
``lax.optimization_barrier`` so at most TWO chunks are ever in flight
(double buffering): chunk k's reduce-scatter/all-gather legs can cross
the wire while chunk k-1's combine and consumer compute runs — the
pipelined form of the generalized-allreduce decomposition
(arXiv:2004.09362; the PR 9 int8 exchange is already structured as
RS→combine→AG legs that chunk naturally). Chunk boundaries are
block-aligned per codec (exact/bf16: the communicating group size; int8:
``primary_axis × HEAT_TPU_QUANT_BLOCK`` so no scale block ever spans a
chunk), which makes the N-chunk emission VALUE-BITWISE-equal to the
unchunked plan per codec and keeps total wire bytes identical (the
``hlo_audit.collective_bytes`` ring model sums per chunk to the
whole-payload figure — tail chunks are never double-charged for
alignment padding). Payloads below ``HEAT_TPU_FUSION_CHUNK_MIN_NUMEL``
(default 4096 elements) stay unchunked: small collectives are
latency-bound and extra legs only add dispatches. The chunk
configuration (:func:`chunk_key`) joins the flush program key and every
model-level step cache next to :func:`quant_key`, so toggling N compiles
SIBLINGS and ``HEAT_TPU_FUSION_CHUNKS=1`` is bitwise (and
program-identical to) today's behavior. Counters:
``op_engine.chunk_collectives`` / ``chunk_fallbacks``; fault site
``fusion.chunk.dispatch`` degrades to the unchunked packed collective.

Asynchronous train-step dispatch
--------------------------------
``trace_step(fn, donate_argnums, block=False)`` dispatches without the
per-step host sync: on this jax, XLA DONATION of an in-flight buffer
blocks the dispatching thread until the producer step completes, so
back-to-back donated train steps serialize the host (probed: 10 chained
donated dispatches cost the full compute wall, 10 plain ones cost
~0.2 ms). The ``block=False`` sibling program compiles WITHOUT XLA
donation and instead ``delete()``-s the donated input buffers right
after dispatch — invalidation semantics preserved (``is_deleted()``,
use-after raises) while the dispatch queue stays asynchronous, so
queued steps run back-to-back with the host free between them.
:func:`sync` blocks on the outstanding async results (or on any pytree
of arrays passed to it) — the one explicit host barrier.

Opt-out: ``HEAT_TPU_FUSION=0`` (or :func:`set_enabled` at runtime).
Counters: ``op_engine.fusion_flushes``, ``op_engine.fusion_ops`` (their
ratio is the ops-per-flush figure in ``ht.runtime_stats()``), plus the
program-cache hit/miss/compile set.
"""

from __future__ import annotations

import contextlib
import functools
import os
import sys
import threading
import weakref
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "enabled",
    "set_enabled",
    "override",
    "materialize",
    "cancel",
    "record_unary",
    "record_binary",
    "record_cum",
    "record_reduce",
    "record_contract",
    "record_contract_einsum",
    "record_resplit",
    "alias_pending",
    "register_reduce_collective",
    "program_cache",
    "stats",
    "reset",
    "capture_hlo",
    "last_hlo",
    "trace_step",
    "value_and_grad",
    "grad",
    "packed_psum",
    "step_enabled",
    "set_step_enabled",
    "step_override",
    "quant_codec",
    "set_quant_codec",
    "quant_override",
    "quant_key",
    "chunk_count",
    "set_chunk_count",
    "chunk_override",
    "chunk_key",
    "hier_enabled",
    "set_hier_enabled",
    "hier_override",
    "mesh_tiers",
    "set_mesh_tiers",
    "hier_key",
    "sync",
]


def _env_on(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default) not in ("0", "false", "False")


_ENABLED = _env_on("HEAT_TPU_FUSION")
_MAX_OPS = int(os.environ.get("HEAT_TPU_FUSION_MAX_OPS", "32"))
# chains shorter than this replay op-by-op at flush (XLA's per-op cache,
# shared across ALL chains) instead of compiling a per-signature program:
# a test-suite-shaped workload materializes thousands of DISTINCT 1-3 op
# chains once each, where per-chain executables are pure compile-time loss
_MIN_OPS = int(os.environ.get("HEAT_TPU_FUSION_MIN_OPS", "4"))
_DONATE = _env_on("HEAT_TPU_FUSION_DONATE")
# escape hatch for the reduction-node extension alone: with 0, reductions
# flush their input tape and dispatch eagerly (the pre-reduction-fusion
# behavior), while elementwise recording stays on
_REDUCE = _env_on("HEAT_TPU_FUSION_REDUCE")
# escape hatch for the contraction-node extension alone: with 0, GEMMs
# dispatch eagerly on zero-filled physical arrays (the pre-contract-fusion
# behavior), while elementwise/reduction recording stays on
_CONTRACT = _env_on("HEAT_TPU_FUSION_CONTRACT")
# escape hatch for the resplit-node extension alone: with 0, a resplit on
# a pending tape flushes it and runs the eager planned reshard (the
# pre-resplit-fusion behavior), while all other recording stays on
_RESPLIT = _env_on("HEAT_TPU_FUSION_RESPLIT")
# escape hatch for the differentiable-tape extension alone: with 0,
# trace_step-wrapped steps run their body eagerly (per-op dispatch, host
# round-trips and all) and the model-level fused steps revert to their
# historic GSPMD/check_vma train programs
_STEP = _env_on("HEAT_TPU_FUSION_STEP")
# escape hatch for the tape-compiled analytics fit steps alone: with 0,
# the estimator family (KMeans/KMedians/KMedoids Lloyd iterations, the
# Lanczos inner loop, Lasso coordinate sweeps, the KNN/GaussianNB
# predict-assign programs) runs its legacy step programs — the exact
# pre-fit-fusion dispatch, without donation, packed collectives or the
# fusion program-cache keying
_FIT = _env_on("HEAT_TPU_FUSION_FIT")


def _parse_codec(val):
    """``HEAT_TPU_QUANT_COLLECTIVES`` value -> codec name or None (exact).
    Unknown values raise immediately: a typo'd codec silently running the
    exact path would defeat the whole byte-reduction intent."""
    if val is None or val in ("", "0", "false", "False", "off", "none"):
        return None
    if val == "1":
        return "bf16"  # the conservative default codec
    if val in ("bf16", "int8"):
        return val
    raise ValueError(
        f"HEAT_TPU_QUANT_COLLECTIVES={val!r}: expected 0, 1, bf16 or int8")


# opt-in quantized wire codec for packed float all-reduces (None = exact)
_QUANT = _parse_codec(os.environ.get("HEAT_TPU_QUANT_COLLECTIVES"))
# payloads below this many elements stay exact: small collectives are
# latency-bound, and quantizing them buys nothing while still paying the
# encode/decode epilogue (it also keeps packed scalar losses exact)
_QUANT_FLOOR = int(os.environ.get("HEAT_TPU_QUANT_MIN_NUMEL", "256"))
# elements per int8 scale block (bf16 scales travel with the payload).
# 128 balances scale overhead (2 bytes per 128 payload bytes, ~1.6%)
# against within-block dynamic range: transformer grads are spiky
# (embedding rows span orders of magnitude), and 256-blocks measured at
# the edge of the documented 1e-2 rel-err contract where 128 leaves
# ~15% margin (tests/test_quant_collectives.py pins the figure)
_QUANT_BLOCK = int(os.environ.get("HEAT_TPU_QUANT_BLOCK", "128"))

# pipeline-chunk count for packed collectives (1 = off, today's emission;
# N splits every qualifying packed payload into up to N double-buffered
# chunk collectives so chunk k's wire legs overlap chunk k-1's compute)
_CHUNKS = int(os.environ.get("HEAT_TPU_FUSION_CHUNKS", "1"))
# payloads below this many elements stay unchunked: a small collective is
# latency-bound, and splitting it into N legs multiplies the latency
# while overlapping nothing worth overlapping
_CHUNK_FLOOR = int(os.environ.get("HEAT_TPU_FUSION_CHUNK_MIN_NUMEL",
                                  "4096"))


def _parse_tiers(val):
    """``HEAT_TPU_MESH_TIERS`` value -> tier declaration or None.

    Two declaration forms (arXiv:2004.09362's two-tier topology model):

    * ``"2,4"`` (integers) — a ``(dcn, ici)`` FACTORIZATION for flat 1-D
      meshes: the mesh's device order is dcn-major (``d`` hosts × ``i``
      devices per host, device ``h*i + j`` = host ``h``, local slot
      ``j``), exactly how ``jax.devices()`` orders a real multi-host pod.
      Drives the flush path's grouped hierarchical exchange and the
      default 2-D ``DataParallel`` grid.
    * ``"dcn,ici"`` (names) — the axis-NAME declaration for named grids:
      the FIRST name is the slow (DCN) tier's mesh-axis name, every other
      axis in a reduction scope is the fast (ICI) tier. ``"dcn"`` alone
      is equivalent (and is the built-in default: a grid that names an
      axis ``"dcn"`` — DASO's ``MeshGrid``, a 5-axis ``TransformerLM``
      grid — has declared its tiers by construction).

    Unknown/mixed forms raise immediately: a typo'd declaration silently
    running flat would defeat the whole DCN-byte-reduction intent."""
    if val is None or val in ("", "0", "false", "False", "off", "none"):
        return None
    parts = tuple(p.strip() for p in str(val).split(",") if p.strip())
    if not parts:
        return None
    if all(p.lstrip("-").isdigit() for p in parts):
        ints = tuple(int(p) for p in parts)
        if len(ints) != 2 or ints[0] < 1 or ints[1] < 1:
            raise ValueError(
                f"HEAT_TPU_MESH_TIERS={val!r}: factor form wants exactly "
                "two positive sizes 'dcn,ici' (e.g. 2,4)")
        return ints
    if any(p.lstrip("-").isdigit() for p in parts):
        raise ValueError(
            f"HEAT_TPU_MESH_TIERS={val!r}: mix of names and sizes "
            "(want 'dcn,ici' names or 'D,I' integer factors)")
    return parts


def _parse_ici_codec(val):
    """``HEAT_TPU_HIER_ICI_CODEC`` -> ``None`` (exact) or ``"bf16"``.
    ``int8`` is deliberately rejected for the fast tier: the ICI legs
    include a reduce-scatter (a reduction, not pure data movement), and
    EQuARX's tier-selective result is exactly that the cheap fast tier
    should stay (near-)exact while the slow tier carries the aggressive
    codec."""
    if val is None or val in ("", "0", "false", "False", "off", "none"):
        return None
    if val in ("1", "bf16"):
        return "bf16"
    raise ValueError(
        f"HEAT_TPU_HIER_ICI_CODEC={val!r}: expected 0, none or bf16 "
        "(the DCN-tier codec is HEAT_TPU_QUANT_COLLECTIVES)")


# master gate for tier-aware hierarchical packed collectives (default on;
# inert until a mesh declares tiers — a "dcn"-named grid axis or the
# HEAT_TPU_MESH_TIERS factorization — so the default is bitwise flat)
_HIER = _env_on("HEAT_TPU_HIER")
_TIERS = _parse_tiers(os.environ.get("HEAT_TPU_MESH_TIERS"))
# fast-tier (ICI) wire codec for the hierarchical exchange's RS/AG legs
# (None = exact; the slow-tier/DCN codec is the quant codec above)
_HIER_ICI = _parse_ici_codec(os.environ.get("HEAT_TPU_HIER_ICI_CODEC"))
# psum payload GROUPS below this many total elements keep the flat
# collective: the decomposition trades one collective for three, which
# only pays when the slow tier's bandwidth (not latency) dominates.
# Default 0 = decompose everything — model-step gradient payloads are
# large, and the tiny members (the packed scalar loss) ride the same
# group as the gradients rather than paying their own legs
_HIER_FLOOR = int(os.environ.get("HEAT_TPU_HIER_MIN_NUMEL", "0"))

_PROGRAMS = None  # lazy singleton (utils imports back into core)

# result-aval memo: (fn, kwargs_key, arg descriptors) -> ShapeDtypeStruct,
# or None for "declined" (non-array result, un-eval-shapeable op)
_AVAL_CACHE: Dict[Tuple, Any] = {}
_AVAL_CACHE_CAP = 8192
_UNSET = object()

# value-keyed 0-d leaves for python/numpy scalars, so repeat chains with
# the same scalar hit the same program AND the same buffer
_SCALAR_CACHE: Dict[Tuple, Any] = {}
_SCALAR_CACHE_CAP = 512

_capture_hlo = False
_last_hlo: Optional[str] = None


def program_cache():
    """The fusion :class:`~heat_tpu.utils.program_cache.ProgramCache`."""
    global _PROGRAMS
    if _PROGRAMS is None:
        from ..utils.program_cache import ProgramCache

        # fusion's key space is open (leaf shapes x chain signatures), so
        # the cache is capped — unbounded pinned executables are the exact
        # accumulated-executable pathology this engine exists to reduce
        _PROGRAMS = ProgramCache(
            name="fusion", aot=False,
            max_entries=int(os.environ.get(
                "HEAT_TPU_FUSION_MAX_PROGRAMS", "1024")))
    return _PROGRAMS


def _metrics():
    from ..utils import metrics

    return metrics


_FAULTS = None  # lazy module handle (utils imports back into core)


def _faults():
    global _FAULTS
    if _FAULTS is None:
        from ..utils import faults

        _FAULTS = faults
    return _FAULTS


# ---------------------------------------------------------------------- #
# switches                                                               #
# ---------------------------------------------------------------------- #
def enabled() -> bool:
    """Whether op recording is on (``HEAT_TPU_FUSION``, default on)."""
    return _ENABLED


def set_enabled(flag: bool) -> bool:
    """Toggle recording; returns the previous setting. Pending tapes stay
    valid — they flush on their next materialization either way."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(flag)
    return prev


@contextlib.contextmanager
def override(flag: bool):
    """Context manager form of :func:`set_enabled` (used by the eager-vs-
    fused property tests and the bench A/B)."""
    prev = set_enabled(flag)
    try:
        yield
    finally:
        set_enabled(prev)


def step_enabled() -> bool:
    """Whether trace_step tracing (and the model-level fused train steps)
    are on (``HEAT_TPU_FUSION_STEP``, default on; also requires the master
    ``HEAT_TPU_FUSION`` switch)."""
    return _ENABLED and _STEP


def set_step_enabled(flag: bool) -> bool:
    """Toggle the differentiable-tape extension alone; returns the
    previous setting."""
    global _STEP
    prev = _STEP
    _STEP = bool(flag)
    return prev


@contextlib.contextmanager
def step_override(flag: bool):
    """Context manager form of :func:`set_step_enabled` (the traced-vs-
    eager property tests and the train-step bench A/B)."""
    prev = set_step_enabled(flag)
    try:
        yield
    finally:
        set_step_enabled(prev)


def fit_enabled() -> bool:
    """Whether the analytics fit-step engine is on: estimator ``fit()``
    hot loops (and the KNN/GaussianNB predict-assign programs) dispatch
    ONE donated, packed-collective executable per iteration through
    :func:`fit_step_call` (``HEAT_TPU_FUSION_FIT``, default on; also
    requires the master ``HEAT_TPU_FUSION`` switch)."""
    return _ENABLED and _FIT


def set_fit_enabled(flag: bool) -> bool:
    """Toggle the analytics fit-step extension alone; returns the
    previous setting."""
    global _FIT
    prev = _FIT
    _FIT = bool(flag)
    return prev


@contextlib.contextmanager
def fit_override(flag: bool):
    """Context manager form of :func:`set_fit_enabled` (the fused-vs-
    legacy estimator parity tests and the analytics bench A/B)."""
    prev = set_fit_enabled(flag)
    try:
        yield
    finally:
        set_fit_enabled(prev)


def quant_codec() -> Optional[str]:
    """The active quantized-collective codec: ``None`` (exact, the
    default), ``"bf16"`` or ``"int8"`` (``HEAT_TPU_QUANT_COLLECTIVES``)."""
    return _QUANT


def set_quant_codec(codec) -> Optional[str]:
    """Select the quantized-collective codec at runtime; returns the
    previous one. Accepts the env-var spellings (``None``/``"0"``/
    ``"bf16"``/``"int8"``). Cached exact programs stay valid — the codec
    is part of every quantization-sensitive program key."""
    global _QUANT
    prev = _QUANT
    _QUANT = _parse_codec(codec)
    return prev


def quant_key() -> Tuple:
    """Hashable identity of the quantization configuration (codec, size
    floor, scale-block size) — model-level step caches (``TransformerLM``,
    ``DataParallel``, DASO) and the flush program key carry it so toggling
    any knob rebuilds instead of reusing a program with the wrong wire
    format."""
    return (_QUANT, _QUANT_FLOOR, _QUANT_BLOCK)


@contextlib.contextmanager
def quant_override(codec, min_numel: Optional[int] = None):
    """Context manager form of :func:`set_quant_codec`; ``min_numel``
    optionally overrides the size floor (the quant property sweeps use a
    low floor so small test payloads exercise the codec)."""
    global _QUANT_FLOOR
    prev = set_quant_codec(codec)
    prev_floor = _QUANT_FLOOR
    if min_numel is not None:
        _QUANT_FLOOR = int(min_numel)
    try:
        yield
    finally:
        set_quant_codec(prev)
        _QUANT_FLOOR = prev_floor


def chunk_count() -> int:
    """The configured pipeline-chunk count for packed collectives
    (``HEAT_TPU_FUSION_CHUNKS``; 1 = unchunked, today's emission)."""
    return _CHUNKS


def set_chunk_count(n) -> int:
    """Select the packed-collective pipeline-chunk count at runtime;
    returns the previous one. Cached programs stay valid — the chunk
    configuration is part of every chunk-sensitive program key, so
    toggling compiles siblings and toggling back re-hits."""
    global _CHUNKS
    prev = _CHUNKS
    n = int(n)
    if n < 1:
        raise ValueError(f"HEAT_TPU_FUSION_CHUNKS={n}: expected >= 1")
    _CHUNKS = n
    return prev


def chunk_key() -> Tuple:
    """Hashable identity of the chunking configuration ``(count,
    payload floor)`` — joins the flush program key and the model-level
    step caches next to :func:`quant_key` so a chunk-count toggle
    rebuilds instead of reusing a program with the wrong leg structure."""
    return (_CHUNKS, _CHUNK_FLOOR)


@contextlib.contextmanager
def chunk_override(n, min_numel: Optional[int] = None):
    """Context manager form of :func:`set_chunk_count`; ``min_numel``
    optionally overrides the payload floor (the chunk property sweeps use
    a low floor so small test payloads exercise the pipeline)."""
    global _CHUNK_FLOOR
    prev = set_chunk_count(n)
    prev_floor = _CHUNK_FLOOR
    if min_numel is not None:
        _CHUNK_FLOOR = int(min_numel)
    try:
        yield
    finally:
        set_chunk_count(prev)
        _CHUNK_FLOOR = prev_floor


def hier_enabled() -> bool:
    """Whether tier-aware hierarchical packed collectives are on
    (``HEAT_TPU_HIER``, default on). Inert without a tier declaration —
    a reduction scope containing a slow-named (``"dcn"``) grid axis, or
    a flat mesh with a declared ``HEAT_TPU_MESH_TIERS`` factorization."""
    return _HIER


def set_hier_enabled(flag: bool) -> bool:
    """Toggle the hierarchical-collective extension alone; returns the
    previous setting. Cached programs stay valid — :func:`hier_key` is
    part of every hierarchy-sensitive program key, so toggling compiles
    siblings and toggling back re-hits."""
    global _HIER
    prev = _HIER
    _HIER = bool(flag)
    return prev


@contextlib.contextmanager
def hier_override(flag: bool, tiers=_UNSET, ici_codec=_UNSET,
                  min_numel=None):
    """Context manager form of :func:`set_hier_enabled`; ``tiers`` /
    ``ici_codec`` / ``min_numel`` optionally override the declaration,
    the fast-tier codec and the payload floor for the block (the hier
    property sweeps pin all of them). Arguments are VALIDATED before any
    global is touched — a bad declaration raises with the configuration
    untouched, never with a half-toggled gate leaked into later code."""
    global _TIERS, _HIER_ICI
    global _HIER_FLOOR
    if tiers is not _UNSET:
        parsed_tiers = _parse_tiers(
            tiers if tiers is None or isinstance(tiers, str)
            else ",".join(str(s) for s in tiers))
    if ici_codec is not _UNSET:
        parsed_ici = _parse_ici_codec(ici_codec)
    if min_numel is not None:
        min_numel = int(min_numel)
    prev = set_hier_enabled(flag)
    prev_tiers, prev_ici, prev_floor = _TIERS, _HIER_ICI, _HIER_FLOOR
    try:
        if tiers is not _UNSET:
            _TIERS = parsed_tiers
        if ici_codec is not _UNSET:
            _HIER_ICI = parsed_ici
        if min_numel is not None:
            _HIER_FLOOR = min_numel
        yield
    finally:
        set_hier_enabled(prev)
        _TIERS, _HIER_ICI, _HIER_FLOOR = prev_tiers, prev_ici, prev_floor


def mesh_tiers():
    """The active tier declaration: ``None`` (undeclared), a ``(d, i)``
    integer factorization for flat meshes, or a name tuple whose first
    entry is the slow (DCN) axis name (``HEAT_TPU_MESH_TIERS``)."""
    return _TIERS


def set_mesh_tiers(spec):
    """Declare (or clear) the mesh tier split at runtime; returns the
    previous declaration. Accepts the env-var spellings (``None`` /
    ``"2,4"`` / ``"dcn,ici"``) or ready tuples."""
    global _TIERS
    prev = _TIERS
    if spec is None or isinstance(spec, str):
        _TIERS = _parse_tiers(spec)
    else:
        _TIERS = _parse_tiers(",".join(str(s) for s in spec))
    return prev


def hier_key() -> Tuple:
    """Hashable identity of the hierarchical-collective configuration
    ``(enabled, tier declaration, ici codec, payload floor)`` — joins
    the flush program key and every model-level step cache next to
    :func:`quant_key` / :func:`chunk_key`, so toggling the hierarchy (or
    re-declaring tiers) rebuilds siblings instead of reusing a program
    with the wrong collective structure; toggling back re-hits the
    cached sibling."""
    return (_HIER, _TIERS, _HIER_ICI, _HIER_FLOOR)


def capture_hlo(flag: bool) -> None:
    """Debug switch: compile flush programs ahead-of-time and keep the
    optimized-HLO text of the most recent compile for :func:`last_hlo`
    (the collective audit in ``tests/test_fusion.py``). Only *compiles*
    capture — reset :func:`program_cache` first to force one. Arming the
    capture clears any previous dump: a cache-hit (or compile-error) path
    must read as a loud ``last_hlo() is None``, never silently satisfy an
    audit with a stale program's HLO."""
    global _capture_hlo, _last_hlo
    _capture_hlo = bool(flag)
    if _capture_hlo:
        _last_hlo = None


def last_hlo() -> Optional[str]:
    return _last_hlo


# ---------------------------------------------------------------------- #
# the expression DAG                                                     #
# ---------------------------------------------------------------------- #
class _Leaf:
    """A concrete physical array entering a chain, plus a weakref to the
    DNDarray that owned it at record time (None for scalar constants) —
    the donation analysis input. ``split`` is the owner's split axis at
    record time (the shard_map translator's layout source of truth)."""

    __slots__ = ("array", "owner", "split")

    def __init__(self, array, owner=None, split=None):
        self.array = array
        self.owner = owner
        self.split = split


class _Node:
    """One recorded op. ``args`` are ``_Node`` / ``_Leaf`` handles;
    ``kwargs`` are static (hashability enforced at record time). ``value``
    is set once a flush evaluates the node (it then acts as a leaf for any
    later chain that still references it).

    ``kind``/``split``/``rmeta``/``cmeta``/``smeta``/``comm`` drive the
    shard_map translation of collective-carrying tapes: ``kind`` is
    ``"ew"`` (elementwise/cum/astype), ``"pad"`` (replicated-operand
    physical pad), ``"mask"`` (neutral-element padding fill),
    ``"reduce"``, ``"contract"`` (distributed GEMM/einsum), ``"resplit"``
    (the reshard planner's layout change folded into the DAG), or
    ``"crop"`` (static slice back to canonical extents — never
    blockwise); ``split`` is the physical split axis of the node's VALUE;
    ``rmeta`` holds the reduce metadata (collective kind, whether the
    split axis is reduced, the input split); ``cmeta`` the contract
    metadata (split case, collective, translatability); ``smeta`` the
    resplit metadata (source/target split); ``comm`` is set on reduce,
    contract and resplit nodes only."""

    __slots__ = ("fn", "args", "kwargs", "kwargs_key", "aval", "depth",
                 "owner", "ext_refs", "value", "kind", "split", "rmeta",
                 "cmeta", "smeta", "comm", "__weakref__")

    def __init__(self, fn, args, kwargs, kwargs_key, aval, depth):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.kwargs_key = kwargs_key
        self.aval = aval
        self.depth = depth
        self.owner = None       # weakref.ref(DNDarray) once wrapped
        self.ext_refs = 0       # times used as an argument of another node
        self.value = None       # concrete result once evaluated
        self.kind = "ew"
        self.split = None
        self.rmeta = None
        self.cmeta = None
        self.smeta = None
        self.comm = None


# partial_op -> collective kind ("psum"/"pmax"/"pmin"); a registered None
# means "no collective primitive exists" (prod): the tape still records,
# and the flush compiles ONE jax.jit program whose collective GSPMD places
_COLLECTIVE: Dict[Any, Optional[str]] = {}


def register_reduce_collective(fn, kind: Optional[str]) -> None:
    """Declare the mesh collective that combines ``fn``'s shard-local
    partials (``"psum"``/``"pmax"``/``"pmin"``, or None for ops without a
    collective primitive). Ops modules register their partial reducers at
    import (``jnp.sum`` etc. are pre-registered below)."""
    _COLLECTIVE[fn] = kind


register_reduce_collective(jnp.sum, "psum")
register_reduce_collective(jnp.max, "pmax")
register_reduce_collective(jnp.min, "pmin")
register_reduce_collective(jnp.prod, None)  # no pprod primitive: GSPMD path


def _key_val(v):
    """Type-aware hashable identity for one static kwarg value, or None to
    decline. Plain ``(k, v)`` tuples would alias values that compare equal
    across types (``0 == 0.0 == False``) and let one call's cached aval or
    compiled program serve another call's different dtype — floats key by
    ``repr`` (distinguishes ``-0.0`` and NaN, like the scalar-leaf cache)
    and everything carries its type name."""
    if isinstance(v, (np.ndarray, jnp.ndarray)):
        return None
    if isinstance(v, (list, tuple)):
        parts = tuple(_key_val(x) for x in v)
        return None if any(p is None for p in parts) else ("tuple", parts)
    if isinstance(v, (float, complex, np.floating, np.complexfloating)):
        return (type(v).__name__, repr(v))
    try:
        hash(v)
    except TypeError:
        return None
    return (type(v).__name__, v)


def _kwargs_key(kwargs: dict):
    """Hashable identity for static kwargs, or None to decline recording
    (array-valued kwargs must stay eager — baking them as constants would
    both bloat the key space and change numerics)."""
    if not kwargs:
        return ()
    items = []
    for k in sorted(kwargs):
        vk = _key_val(kwargs[k])
        if vk is None:
            return None
        items.append((k, vk))
    return tuple(items)


def _scalar_leaf(s) -> Optional[_Leaf]:
    """A 0-d leaf for a python/numpy scalar operand, value-cached.

    ``jnp.asarray`` preserves NumPy-style weak typing for python scalars,
    so passing the leaf as a program *argument* reproduces eager promotion
    exactly ((f32 array) * 0.5 stays f32). ``repr`` keys the cache so
    ``-0.0``/``0.0`` and NaN never alias."""
    key = (type(s).__name__, repr(s))
    leaf = _SCALAR_CACHE.get(key)
    if leaf is None:
        try:
            arr = jnp.asarray(s)
        except Exception:
            return None
        if isinstance(arr, jax.core.Tracer):
            # inside a jax trace (user jit / trace_step) even a python
            # constant lifts to a tracer on this jax; caching it would
            # poison every later EAGER chain that reuses the same scalar
            # (the flush reads leaf.array.sharding — tracers have none)
            return None
        if len(_SCALAR_CACHE) >= _SCALAR_CACHE_CAP:
            _SCALAR_CACHE.clear()
        leaf = _Leaf(arr, None)
        _SCALAR_CACHE[key] = leaf
    return leaf


def _handle_of(x) -> Optional[object]:
    """The symbolic handle for a DNDarray operand: its pending node, or a
    leaf over its concrete physical array. None declines recording (jax
    tracers must not be captured into a cross-turn tape)."""
    node = x._lazy_node
    if node is not None:
        if node.value is not None:
            return _Leaf(node.value, node.owner, node.split)
        return node
    arr = x._phys_or_none()
    if arr is None or isinstance(arr, jax.core.Tracer):
        return None
    return _Leaf(arr, weakref.ref(x), x.split)


def _descr(h) -> tuple:
    """Aval descriptor of a handle, for the eval-shape memo key."""
    if isinstance(h, _Node):
        return (tuple(h.aval.shape), str(h.aval.dtype), False)
    a = h.array
    return (tuple(a.shape), str(a.dtype), bool(a.aval.weak_type))


def _proxy(h):
    """What :func:`jax.eval_shape` sees for a handle: pending nodes by
    abstract aval, leaves by their concrete array (weak types ride along)."""
    if isinstance(h, _Node):
        return jax.ShapeDtypeStruct(tuple(h.aval.shape), h.aval.dtype)
    return h.array


def _result_aval(fn, kwargs, kwargs_key, handles):
    """Memoized ``eval_shape`` of one op application; None declines (op not
    abstractly traceable, or result is not a single array)."""
    key = (fn, kwargs_key, tuple(_descr(h) for h in handles))
    aval = _AVAL_CACHE.get(key, _UNSET)
    if aval is not _UNSET:
        return aval
    try:
        aval = jax.eval_shape(lambda *a: fn(*a, **kwargs),
                              *[_proxy(h) for h in handles])
        if not isinstance(aval, jax.ShapeDtypeStruct):
            aval = None
    except Exception:
        aval = None
    if len(_AVAL_CACHE) >= _AVAL_CACHE_CAP:
        _AVAL_CACHE.clear()
    _AVAL_CACHE[key] = aval
    return aval


def _depth_of(handles) -> int:
    return 1 + max((h.depth for h in handles if isinstance(h, _Node)),
                   default=0)


def _stable_fn(fn) -> bool:
    """Only module-level callables may be recorded: a lambda / closure /
    ``functools.partial`` built per call has a fresh identity every time,
    so every chain containing one would compile a brand-new executable per
    invocation and pin it forever in the program cache — unbounded
    compile-time and memory growth (the exact executable-count pathology
    this engine exists to reduce). Those ops dispatch eagerly instead."""
    if isinstance(fn, functools.partial):
        return False
    if getattr(fn, "__name__", "") == "<lambda>":
        return False
    return "<locals>" not in getattr(fn, "__qualname__", "")


def _make_node(fn, kwargs, handles, expected_shape) -> Optional[_Node]:
    """Record one op over ``handles``; enforces the tape-depth cap (flush
    the deep inputs, then record over their values) and validates the
    abstract result against the expected physical shape — any mismatch
    declines, and the caller's eager path reproduces historic behavior."""
    if not _stable_fn(fn):
        return None
    kwargs_key = _kwargs_key(kwargs)
    if kwargs_key is None:
        return None
    aval = _result_aval(fn, kwargs, kwargs_key, handles)
    if aval is None or tuple(aval.shape) != tuple(expected_shape):
        return None
    if _depth_of(handles) > _MAX_OPS:
        handles = tuple(_flushed_handle(h) for h in handles)
    node = _Node(fn, tuple(handles), dict(kwargs), kwargs_key, aval,
                 _depth_of(handles))
    with _FLUSH_LOCK:
        # ext_refs feeds the flush-time shared-node output promotion; an
        # unsynchronized += could lose an increment under concurrent
        # recording off one shared pending node and strand its value
        for h in handles:
            if isinstance(h, _Node):
                h.ext_refs += 1
    return node


def _flushed_handle(h):
    """Depth-cap helper: evaluate a pending node and hand back its value
    as a leaf (the chain splits into two programs at the cap)."""
    if isinstance(h, _Node) and h.value is None:
        _flush(h)
    if isinstance(h, _Node):
        return _Leaf(h.value, h.owner, h.split)
    return h


def _wrap(node: _Node, gshape, split, device, comm):
    """A lazy DNDarray owning ``node``."""
    from . import types
    from .dndarray import DNDarray

    arr = DNDarray._lazy(node, gshape, types.canonical_heat_type(aval_dtype(node)),
                         split, device, comm)
    node.owner = weakref.ref(arr)
    return arr


def aval_dtype(node: _Node):
    return node.aval.dtype


# ---------------------------------------------------------------------- #
# record entry points (called from the op engine)                        #
# ---------------------------------------------------------------------- #
def record_unary(operation, x, kwargs) -> Optional[object]:
    """Lazy form of ``__local_op`` (no ``out=``): shape-preserving
    elementwise op on the physical array."""
    if not _ENABLED:
        return None
    h = _handle_of(x)
    if h is None:
        return None
    node = _make_node(operation, kwargs, (h,), x._phys_shape())
    if node is None:
        return None
    node.split = x.split
    return _wrap(node, x.gshape, x.split, x.device, x.comm)


def _pad_op(a, cfg):
    """Module-level (stable identity for program keys) physical pad of a
    replicated operand onto the padded split-axis length."""
    return jnp.pad(a, list(cfg))


def record_binary(operation, t1, t2, fn_kwargs, pad1, pad2,
                  out_shape, out_split, device, comm) -> Optional[object]:
    """Lazy form of ``__binary_op``'s compute tail (no ``out=``/``where=``).

    Called AFTER distribution alignment — any needed resplit already ran
    (and materialized its operand), so both handles are layout-compatible
    and the recorded op never crosses the split axis. ``pad1``/``pad2``
    are the replicated-operand pad configs the eager path would apply;
    they become nodes of their own."""
    from .dndarray import DNDarray

    if not _ENABLED:
        return None

    def handle(t, pad_cfg):
        if isinstance(t, DNDarray):
            h = _handle_of(t)
        else:
            h = _scalar_leaf(t)
        if h is None or pad_cfg is None:
            return h
        hp = _make_node(_pad_op, {"cfg": tuple(tuple(p) for p in pad_cfg)},
                        (h,), _padded_shape(h, pad_cfg))
        if hp is not None:
            # the padded operand aligns with the split operand: to the
            # shard_map translator its value is sharded along the axis the
            # pad extended (pad-to-physical, then slice the local block)
            hp.kind = "pad"
            hp.split = next(i for i, p in enumerate(pad_cfg)
                            if tuple(p) != (0, 0))
        return hp

    h1 = handle(t1, pad1)
    h2 = handle(t2, pad2)
    if h1 is None or h2 is None:
        return None
    expected = tuple(comm.padded_size(s) if i == out_split else int(s)
                     for i, s in enumerate(out_shape))
    node = _make_node(operation, fn_kwargs, (h1, h2), expected)
    if node is None:
        return None
    node.split = out_split
    return _wrap(node, out_shape, out_split, device, comm)


def _padded_shape(h, cfg):
    base = h.aval.shape if isinstance(h, _Node) else h.array.shape
    return tuple(int(s) + int(cfg[i][0]) + int(cfg[i][1])
                 for i, s in enumerate(base))


def _astype_op(a, dtype):
    return a.astype(dtype)


def record_astype(x, heat_dtype) -> Optional[object]:
    """Lazy form of ``DNDarray.astype(copy=True)``: a dtype conversion is
    elementwise, so it records like any unary op — this keeps predicate
    chains fusible through ``ht.where``'s bool cast instead of forcing a
    flush at every ``astype`` boundary."""
    if not _ENABLED:
        return None
    h = _handle_of(x)
    if h is None:
        return None
    node = _make_node(_astype_op, {"dtype": jnp.dtype(heat_dtype.jax_type())},
                      (h,), x._phys_shape())
    if node is None:
        return None
    node.split = x.split
    return _wrap(node, x.gshape, x.split, x.device, x.comm)


def record_cum(x, partial_op, axis, dtype) -> Optional[object]:
    """Lazy form of ``__cum_op`` for scans that do NOT read across the
    split axis (``axis != split``) — the split-crossing case materializes
    first so the neutral-element padding discipline stays eager."""
    if not _ENABLED:
        return None
    if x.split is not None and axis == x.split:
        return None
    h = _handle_of(x)
    if h is None:
        return None
    node = _make_node(partial_op, {"axis": axis}, (h,), x._phys_shape())
    if node is None:
        return None
    node.split = x.split
    if dtype is not None:
        from . import types

        jdt = types.canonical_heat_type(dtype).jax_type()
        node2 = _make_node(_astype_op, {"dtype": jnp.dtype(jdt)}, (node,),
                           x._phys_shape())
        if node2 is None:
            return None
        node2.split = x.split
        node = node2
    return _wrap(node, x.gshape, x.split, x.device, x.comm)


def _mask_pad(a, axis, n, fill):
    """Module-level (stable identity) neutral-element fill of the padding
    beyond logical length ``n`` along ``axis`` — the tape form of
    ``DNDarray.filled``. Global semantics: the shard_map translator swaps
    in a per-shard version whose iota carries the block's global offset."""
    iota = jax.lax.broadcasted_iota(jnp.int32, a.shape, axis)
    return jnp.where(iota < n, a, jnp.asarray(fill, a.dtype))


def record_reduce(x, partial_op, neutral, axis, axes, keepdims,
                  touches_split, gshape, out_split, kwargs) -> Optional[object]:
    """Lazy form of ``__reduce_op`` (no ``out=``): a neutral-element mask
    node over the canonical padding (when the reduction reads across a
    padded split axis) followed by a terminal reduce node. The flush
    compiles elementwise chain → mask → shard-local reduce → one grouped
    collective as ONE program (:func:`_plan_sm`)."""
    if not _ENABLED or not _REDUCE:
        return None
    h = _handle_of(x)
    if h is None:
        return None
    phys_in = x._phys_shape()
    if touches_split and x.pad:
        try:
            hash(neutral)
        except TypeError:
            return None
        h = _mask0(h, x.split, x.gshape[x.split], phys_in, fill=neutral)
        if h is None:
            return None
    rkw = dict(kwargs)
    rkw["axis"] = None if axis is None else axes
    rkw["keepdims"] = keepdims
    if axis is None:
        expected = (1,) * len(phys_in) if keepdims else ()
    elif keepdims:
        expected = tuple(1 if i in axes else s for i, s in enumerate(phys_in))
    else:
        expected = tuple(s for i, s in enumerate(phys_in) if i not in axes)
    node = _make_node(partial_op, rkw, (h,), expected)
    if node is None:
        return None
    node.kind = "reduce"
    node.split = out_split
    node.rmeta = {"collective": _COLLECTIVE.get(partial_op),
                  "touches": bool(touches_split), "in_split": x.split}
    node.comm = x.comm
    return _wrap(node, gshape, out_split, x.device, x.comm)


def _hshape(h) -> Tuple[int, ...]:
    """Physical shape of a handle (node aval or leaf array)."""
    return tuple(h.aval.shape) if isinstance(h, _Node) else tuple(h.array.shape)


def _crop_op(a, limits):
    """Module-level (stable identity) static slice back to the canonical
    physical extents — the tape form of the eager ``res[:, :m]`` crop when
    two operand paddings cannot both stay in a contraction's output. Crop
    nodes never translate blockwise (kind ``"crop"``): their limits span
    the GLOBAL padded extent, which a shard-local block cannot satisfy."""
    return jax.lax.slice(a, (0,) * len(limits), tuple(limits))


def _einsum_op(x, y, expr):
    """Module-level (stable identity) two-operand einsum contraction."""
    return jnp.einsum(expr, x, y)


def _mask0(h, axis, n, phys, fill=0) -> Optional[_Node]:
    """Fill-mask node over the padding beyond logical length ``n`` along
    ``axis`` — the tape form of ``DNDarray.filled``. Contractions mask
    with the default zero (``linalg.basics._filled0``: padding must
    contribute nothing); reductions pass their neutral element."""
    hm = _make_node(_mask_pad, {"axis": int(axis), "n": int(n),
                                "fill": fill}, (h,), phys)
    if hm is None:
        return None
    hm.kind = "mask"
    hm.split = int(axis)
    return hm


def _masked_operand(op, axis, n) -> Optional[object]:
    """Zero-filled handle for a contraction operand whose padding holds
    garbage. A CONCRETE operand takes the eager ``_filled0`` write-back:
    the select runs ONCE per buffer (padding is don't-care), the
    ``pad_is_zero`` bit is set, and every later GEMM on the same array —
    fused or eager — skips the masking pass entirely. A pending tape
    records a MASK node instead, fusing the mask into the chain program
    (zero materialization barrier — the point of recording); its
    ``op_engine.zero_fills`` tick is per flush, honestly counting each
    fused program that carries the select."""
    from ._operations import _count_zero_fill

    if op._lazy_node is None:
        op._write_back_zero_fill()
        return _handle_of(op)
    h = _handle_of(op)
    if h is None:
        return None
    hm = _mask0(h, axis, n, op._phys_shape())
    if hm is not None:
        _count_zero_fill()
    return hm


def _zero_pad_node(h, cfg, operand_split) -> Optional[_Node]:
    """Zero-pad node aligning one operand's extents onto another's padded
    extents. A replicated operand padded along exactly one axis becomes a
    ``"pad"`` node (the translator pads then slices the local block — the
    contracted-split psum case with a replicated side); anything else
    stays an ordinary node (blockwise-safe for non-split axes, and the
    plan validator rejects the rest into the GSPMD path)."""
    hp = _make_node(_pad_op, {"cfg": tuple(tuple(p) for p in cfg)}, (h,),
                    _padded_shape(h, cfg))
    if hp is None:
        return None
    padded_axes = [i for i, p in enumerate(cfg) if tuple(p) != (0, 0)]
    if operand_split is None and len(padded_axes) == 1:
        hp.kind = "pad"
        hp.split = padded_axes[0]
    else:
        hp.split = operand_split
    return hp


def record_contract(a, b) -> Optional[object]:
    """Lazy form of the 2-D ``matmul`` compute tail: zero-fill masks for
    contracted-axis padding, the physical contracted-extent alignment, the
    GEMM itself and (when two paddings cannot coexist in the output) a
    canonical crop all become tape nodes, so ``matmul(x, w) + b`` →
    activation → reduction is ONE flush. ``cmeta["case"]`` names the
    split-combination plan the shard_map translator implements:

    ========== ============================ ======================
    case       layouts                      collectives
    ========== ============================ ======================
    local0     a.split=0, b replicated      none (output split 0)
    local1     a replicated, b.split=1      none (output split 1)
    psum       contracted dim sharded       one packed ``psum``
               (a.split=1 and/or b.split=0)
    replicated both replicated              none (local GEMM)
    gspmd      anything else                GSPMD-placed, one
                                            plain-jit program
    ========== ============================ ======================
    """
    if not _ENABLED or not _CONTRACT:
        return None
    comm = a.comm
    if b.comm is not comm or a.size == 0 or b.size == 0:
        return None
    n, k = (int(s) for s in a.gshape)
    m = int(b.gshape[1])
    sa, sb = a.split, b.split

    # zero-fill the contracted-axis padding (the tape form of ``_filled0``);
    # skipped when the buffer is already canonically zero-padded, written
    # back once for concrete operands (repeat GEMMs are then free). Masks
    # run BEFORE handle acquisition: a concrete write-back swaps the
    # operand's buffer, and an aliased sibling (``matmul(x, x)``) must see
    # the shared post-write-back buffer — and its bit — not a stale leaf
    ha = hb = None
    if sa == 1 and a.pad and not a.pad_is_zero:
        ha = _masked_operand(a, 1, k)
        if ha is None:
            return None
    if sb == 0 and b.pad and not b.pad_is_zero:
        hb = _masked_operand(b, 0, k)
        if hb is None:
            return None
    if ha is None:
        ha = _handle_of(a)
    if hb is None:
        hb = _handle_of(b)
    if ha is None or hb is None:
        return None

    # align the contracted dimension physically (zero rows/cols up to the
    # sharded side's padded extent — zeros contribute nothing to the GEMM)
    ka_phys, kb_phys = _hshape(ha)[1], _hshape(hb)[0]
    if ka_phys < kb_phys:
        ha = _zero_pad_node(ha, ((0, 0), (0, kb_phys - ka_phys)), sa)
    elif kb_phys < ka_phys:
        hb = _zero_pad_node(hb, ((0, ka_phys - kb_phys), (0, 0)), sb)
    if ha is None or hb is None:
        return None

    out_split = 0 if sa == 0 else (1 if sb == 1 else None)
    if sa == 0 and sb is None:
        case = "local0"
    elif sa is None and sb == 1:
        case = "local1"
    elif (sa == 1 or sb == 0) and sa in (1, None) and sb in (0, None):
        case = "psum"
    elif sa is None and sb is None:
        case = "replicated"
    else:
        case = "gspmd"

    raw = (_hshape(ha)[0], _hshape(hb)[1])
    node = _make_node(jnp.matmul, {}, (ha, hb), raw)
    if node is None:
        return None
    node.kind = "contract"
    node.split = out_split
    node.comm = comm
    node.cmeta = {"case": case,
                  "collective": "psum" if case == "psum" else None,
                  "translatable": case != "gspmd"}
    canonical = (comm.padded_size(n) if out_split == 0 else n,
                 comm.padded_size(m) if out_split == 1 else m)
    if raw != canonical:
        # only one axis may carry canonical padding (a.split=0 × b.split=1)
        node2 = _make_node(_crop_op, {"limits": canonical}, (node,),
                           canonical)
        if node2 is None:
            return None
        node2.kind = "crop"
        node2.split = out_split
        node = node2
    # the output's padding is never claimed zero (``_pad_zero`` stays
    # False): even zero operand padding contracted against a non-finite
    # value yields NaN padding (0 * inf), so the bit would lie for legal
    # data. Consumers pay at most one select per buffer (the write-back).
    return _wrap(node, (n, m), out_split, a.device, comm)


def record_contract_einsum(in_specs, out_part, a, b, out_split) -> Optional[object]:
    """Lazy form of the 2-operand distributed einsum (and ``tensordot``
    riding it): zero-fill masks, the label-extent normalization pads, the
    contraction and the logical-output crop all become tape nodes. The
    contraction compiles via the plain-jit GSPMD path unless both operands
    are replicated (``matmul`` owns the block-planned split cases; einsum's
    general layouts stay GSPMD-placed) — the win here is epilogue fusion
    and the removal of the ``filled(0)`` materialization barrier."""
    if not _ENABLED or not _CONTRACT:
        return None
    comm = a.comm
    if b.comm is not comm or a.size == 0 or b.size == 0:
        return None
    handles = []
    for op, spec in ((a, in_specs[0]), (b, in_specs[1])):
        if op.split is not None and op.pad and not op.pad_is_zero:
            h = _masked_operand(op, op.split, op.gshape[op.split])
        else:
            h = _handle_of(op)
        if h is None:
            return None
        handles.append(h)
    # one physical extent per label (a label can pair a padded split dim
    # with an unpadded one across operands; zero-pad the shorter dims)
    sizes: Dict[str, int] = {}
    for h, spec in zip(handles, in_specs):
        for ax, label in enumerate(spec):
            sizes[label] = max(sizes.get(label, 0), _hshape(h)[ax])
    for j, (op, spec) in enumerate(((a, in_specs[0]), (b, in_specs[1]))):
        shape = _hshape(handles[j])
        cfg = tuple((0, sizes[l] - shape[ax]) for ax, l in enumerate(spec))
        if any(w for _, w in cfg):
            handles[j] = _zero_pad_node(handles[j], cfg, op.split)
            if handles[j] is None:
                return None
    expr = ",".join(in_specs) + "->" + out_part
    raw_shape = tuple(sizes[l] for l in out_part)
    node = _make_node(_einsum_op, {"expr": expr}, tuple(handles), raw_shape)
    if node is None:
        return None
    node.kind = "contract"
    node.split = out_split
    node.comm = comm
    replicated = a.split is None and b.split is None and out_split is None
    node.cmeta = {"case": "replicated" if replicated else "gspmd",
                  "collective": None, "translatable": replicated}
    logical = []
    for label in out_part:
        for op, spec in ((a, in_specs[0]), (b, in_specs[1])):
            if label in spec:
                logical.append(int(op.gshape[spec.index(label)]))
                break
    canonical = tuple(comm.padded_size(s) if i == out_split else s
                      for i, s in enumerate(logical))
    if raw_shape != canonical:
        node2 = _make_node(_crop_op, {"limits": canonical}, (node,),
                           canonical)
        if node2 is None:
            return None
        node2.kind = "crop"
        node2.split = out_split
        node = node2
    # padding never claimed zero — zero-filled input padding contracted
    # against a non-finite value is NaN (0 * inf); see record_contract
    return _wrap(node, tuple(logical), out_split, a.device, comm)


def _resplit_op(a, gshape, pad, sharding):
    """Module-level (stable identity) GLOBAL form of a planned resplit:
    cut the source-axis tail padding, zero-pad the target axis, constrain
    the target layout. Pure value semantics — the data motion is a
    sharding change, which ``_sm_body`` renders as exactly the planner's
    collective; this global form serves the plain-jit GSPMD fallback
    (where the constraint hands XLA the intended layout) and the
    eval-shape/aval machinery. The slice/pad steps are the PLANNER'S OWN
    helpers so the fallback can never drift from the planner programs the
    audits pin against. ``_flush_inline`` never calls it: short tapes
    dispatch the eager planner program instead."""
    from . import resharding

    a = resharding._slice_logical(a, gshape)
    for ax, (_lo, w) in enumerate(pad):
        if w:
            a = resharding._pad_axis(a, ax, int(a.shape[ax]) + int(w))
    return jax.lax.with_sharding_constraint(a, sharding)


def alias_pending(x) -> Optional[object]:
    """A lazy copy-wrapper sharing ``x``'s pending node — the no-op
    (same-split) ``resplit`` case, which the eager path serves as a
    buffer-sharing wrapper and which must not flush the tape either.
    The shared node's ``ext_refs`` is bumped under the flush lock so any
    sibling flush promotes its value to a program output — the alias can
    always materialize later, even after ``x`` dies (the same
    stranded-value discipline as shared interior nodes)."""
    from .dndarray import DNDarray

    node = x._lazy_node
    if node is None:
        return None
    with _FLUSH_LOCK:
        if node.value is not None:
            return None  # evaluated already: the concrete path is exact
        node.ext_refs += 1
    return DNDarray._lazy(node, x.gshape, x.dtype, x.split, x.device,
                          x.comm)


def record_resplit(x, to_split) -> Optional[object]:
    """Lazy form of ``DNDarray.resplit``/``resplit_`` on a PENDING tape:
    the reshard planner's one-collective move (arXiv:2112.01075 — one
    all-to-all + local reslice for split→split, a zero-collective local
    slice for None→split, all-gather for split→None) records as a RESPLIT
    node instead of flushing the tape, and the flush translates it
    mid-body inside the one shard_map program, with per-node split state
    switching from the source to the target layout downstream of the
    node. Declines (→ the historic flush-then-planned-resplit path,
    counted in ``op_engine.fusion_resplit_fallbacks``) whenever the
    planner itself would fall back to GSPMD: degenerate layouts, a
    physical shape off the canonical from-layout. Concrete arrays (no
    pending tape) never record — the eager planner path is already one
    cached program, and the ``resharding.plan_*`` counters stay honest."""
    from . import resharding

    if x._lazy_node is None:
        return None  # concrete arrays keep the eager planner path
    if not _ENABLED or not _RESPLIT:
        _metrics().inc("op_engine.fusion_resplit_fallbacks")
        return None
    comm = x.comm
    gshape = tuple(int(s) for s in x.gshape)
    from_split = x.split
    if resharding.plan_kind(gshape, from_split, to_split, comm) == "gspmd":
        _metrics().inc("op_engine.fusion_resplit_fallbacks")
        return None
    # the planner programs (and the blockwise translation) assume the
    # canonical from-layout physical; anything else keeps the eager path
    expect = list(gshape)
    if from_split is not None:
        expect[from_split] = comm.padded_size(gshape[from_split])
    if tuple(x._phys_shape()) != tuple(expect):
        _metrics().inc("op_engine.fusion_resplit_fallbacks")
        return None
    h = _handle_of(x)
    if h is None:
        _metrics().inc("op_engine.fusion_resplit_fallbacks")
        return None
    out_phys = list(gshape)
    pad = [(0, 0)] * len(gshape)
    if to_split is not None:
        out_phys[to_split] = comm.padded_size(gshape[to_split])
        pad[to_split] = (0, out_phys[to_split] - gshape[to_split])
    node = _make_node(_resplit_op,
                      {"gshape": gshape, "pad": tuple(pad),
                       "sharding": comm.sharding(len(gshape), to_split)},
                      (h,), tuple(out_phys))
    if node is None:
        _metrics().inc("op_engine.fusion_resplit_fallbacks")
        return None
    node.kind = "resplit"
    node.split = to_split
    node.smeta = {"from": from_split, "to": to_split}
    node.comm = comm
    _metrics().inc("op_engine.fusion_resplit_nodes")
    return _wrap(node, gshape, to_split, x.device, comm)


# ---------------------------------------------------------------------- #
# flush                                                                  #
# ---------------------------------------------------------------------- #
# Serializes flush against flush: two threads materializing overlapping
# DAGs would otherwise race plan construction against the post-run
# ``node.args = ()`` release (the eager engine's immutable __parray reads
# had no such hazard). Flushes are host-side bookkeeping around one
# program call, so serializing them costs nothing on the XLA:CPU backend
# (dispatch is serialized there anyway) and little elsewhere. RLock:
# a depth-cap flush can nest inside a record that nested inside a flush-
# adjacent path.
_FLUSH_LOCK = threading.RLock()


def materialize(arr) -> None:
    """Evaluate ``arr``'s pending chain (the ``DNDarray.larray`` hook)."""
    node = arr._lazy_node
    if node is None:
        return
    with _FLUSH_LOCK:
        if node.value is None:
            _flush(node)
        arr._set_materialized(node.value)


def cancel(arr) -> None:
    """Detach ``arr`` from its pending node (its ``larray`` is being
    overwritten): the node stays evaluable for any chain that references
    it, but no longer writes back into ``arr``."""
    node = arr._lazy_node
    if node is not None:
        node.owner = None
        arr._lazy_node = None


def _topo(root: _Node):
    """Iterative post-order over the pending sub-DAG reachable from
    ``root`` (evaluated nodes act as leaves). Returns the node list and a
    per-node in-DAG parent-reference count."""
    order = []
    state: Dict[int, int] = {}  # id -> 0 visiting / 1 done
    in_refs: Dict[int, int] = {}
    stack = [(root, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            state[id(node)] = 1
            order.append(node)
            continue
        if state.get(id(node)) is not None:
            continue
        state[id(node)] = 0
        stack.append((node, True))
        for h in node.args:
            if isinstance(h, _Node) and h.value is None:
                in_refs[id(h)] = in_refs.get(id(h), 0) + 1
                if state.get(id(h)) is None:
                    stack.append((h, False))
    return order, in_refs


def _donatable(leaves, occurs) -> Tuple[int, ...]:
    """Leaf slots whose buffer the tape provably holds the only remaining
    references to: the owning DNDarray is gone and ``sys.getrefcount``
    matches the tape's own reference bookkeeping exactly (list entry +
    loop variable + getrefcount argument + in-tape ``_Leaf`` holders).
    Anything else — a live owner, another pending tape, a user variable —
    shows up as an extra reference and vetoes donation."""
    if not _DONATE:
        return ()
    out = []
    for j, a in enumerate(leaves):
        if a.ndim == 0:
            continue  # cached scalar leaves are shared by design
        if sys.getrefcount(a) == occurs[j] + 3:
            out.append(j)
    return tuple(out)


def _flush(root: _Node) -> None:
    """Compile-and-run the pending chain under ``root`` as ONE program.

    Outputs are the root plus every interior node some live DNDarray or
    other pending chain still needs; everything else stays a fused
    temporary inside XLA. The program is cached by structural signature;
    donation slots are part of the key.

    Chains below ``HEAT_TPU_FUSION_MIN_OPS`` replay inline instead (eager
    per-op dispatch through XLA's shared op cache): compiling one
    executable per 1-3-op signature costs more than it saves, and the
    inline path is bitwise-eager by construction. ``capture_hlo`` forces
    compilation so audits can look at short chains too."""
    with _FLUSH_LOCK:
        _flush_locked(root)


def _flush_locked(root: _Node) -> None:
    order, in_refs = _topo(root)
    has_reduce = any(n.kind == "reduce" for n in order)
    has_contract = any(n.kind == "contract" for n in order)
    has_resplit = any(n.kind == "resplit" for n in order)

    if len(order) < _MIN_OPS and not _capture_hlo:
        _flush_inline(order, has_reduce, has_contract, has_resplit)
        return

    leaves = []        # unique concrete arrays, first-encounter order
    leaf_slot = {}     # id(array) -> slot
    leaf_splits = []   # recorded split axis per slot (shard_map in_specs)
    leaf_occurs = []   # in-tape _Leaf/value holders per slot
    leaf_owner_dead = []
    plan = []          # (fn, codes, kwargs) per node
    sig_nodes = []
    index = {}

    for pos, node in enumerate(order):
        index[id(node)] = pos
        codes = []
        for h in node.args:
            if isinstance(h, _Node) and h.value is None:
                codes.append((0, index[id(h)]))
                continue
            if isinstance(h, _Node):
                arr, owner, split, from_node = h.value, h.owner, h.split, True
            else:
                arr, owner, split, from_node = h.array, h.owner, h.split, False
            slot = leaf_slot.get(id(arr))
            if slot is None:
                slot = len(leaves)
                leaf_slot[id(arr)] = slot
                leaves.append(arr)
                leaf_splits.append(split)
                leaf_occurs.append(0)
                leaf_owner_dead.append(True)
            leaf_occurs[slot] += 1
            # a value still pinned inside a node may be referenced by other
            # pending chains through that node — never donate those
            if from_node or owner is None or owner() is not None:
                leaf_owner_dead[slot] = False
            codes.append((1, slot))
        plan.append((node.fn, tuple(codes), node.kwargs))
        sig_nodes.append((node.fn, tuple(codes), node.kwargs_key))

    out_idx = []
    root_pos = index[id(root)]
    for pos, node in enumerate(order):
        live_owner = node.owner is not None and node.owner() is not None
        shared = node.ext_refs > in_refs.get(id(node), 0)
        if pos == root_pos or live_owner or shared:
            out_idx.append(pos)
    out_idx = tuple(out_idx)

    touching = [n for n in order
                if (n.kind == "reduce" and n.rmeta["touches"])
                or (n.kind == "contract" and n.cmeta["case"] != "replicated")
                or n.kind == "resplit"]
    comm = touching[0].comm if touching else None
    sm = None
    if touching and all(n.cmeta["translatable"] for n in order
                        if n.kind == "contract"):
        # a gspmd-case contract anywhere on the tape dooms the plan at
        # that node — skip the O(tape) walk and go straight to plain-jit
        sm = _plan_sm(order, plan, leaves, leaf_splits, out_idx, comm)
    if has_reduce or has_contract or has_resplit:
        # reduce-, contract- and resplit-carrying tapes compile without
        # donation (documented contract, doc/fusion.md): the program is
        # shard_map-shaped or collective-carrying, so buffer reuse buys
        # little — and donated inputs would complicate the
        # packed-collective body for zero win
        donate = ()
    else:
        donate = tuple(j for j in _donatable(leaves, leaf_occurs)
                       if leaf_owner_dead[j])

    # mesh identity rides in through the per-leaf sharding strings (axis
    # layout + device kind); ``jax.jit`` itself re-lowers per concrete
    # input sharding, so a signature collision across distinct device sets
    # degrades to an internal recompile, never a wrong program. The
    # recorded split axes join the key because they pick the shard_map
    # in_specs; the reduce mode and comm identity key the collective form.
    # tier-aware hierarchical decomposition (HEAT_TPU_HIER + declared
    # HEAT_TPU_MESH_TIERS factorization): planned FIRST — the quant byte
    # model follows the tiered legs — and captured like the quant/chunk
    # keys below; a gate-off/undeclared/fault decision keys as None and
    # HITS any cached flat program
    hplan = _hier_flush_plan(order, sm, comm) if sm is not None else None
    hcfg = hplan[0] if hplan is not None else None
    # quantized-collective selection (HEAT_TPU_QUANT_COLLECTIVES): static
    # per-flush, so the decision, the program key and the traced body all
    # agree; a fault/floor/codec-off decision keys as None and therefore
    # HITS any cached exact program instead of compiling a duplicate
    qplan = (_quant_flush_plan(order, sm, comm, hcfg=hcfg)
             if sm is not None else None)
    # codec/block from the PLAN's captured key, never re-read from the
    # globals: a concurrent set_quant_codec between planning and build
    # (or the deferred jit trace) must not trace a body whose wire format
    # mismatches the selection or the program key
    qcfg = qplan[3] if qplan is not None else (None, 0, 0)
    qsel = qplan[0] if qplan is not None else frozenset()
    # chunk selection under the same captured-key discipline: the plan
    # fires the fault site, keys the program, and its (count, floor) is
    # what the traced body reads — never the live globals
    cplan = (_chunk_flush_plan(order, sm, comm, qsel, qcfg, hcfg=hcfg)
             if sm is not None else None)
    ccfg = cplan[0] if cplan is not None else (1, 0)

    leaf_descrs = tuple(
        (tuple(a.shape), str(a.dtype), bool(a.aval.weak_type),
         str(a.sharding), leaf_splits[j])
        for j, a in enumerate(leaves))
    key = (leaf_descrs, tuple(sig_nodes), out_idx, donate)
    if touching:
        qtag = qplan[3] if qplan is not None else None
        ctag = cplan[0] if cplan is not None else None
        htag = hplan[1] if hplan is not None else None
        key = key + (("sm" if sm is not None else "gspmd"), comm.cache_key,
                     qtag, ctag, htag)

    def build():
        _faults().check("fusion.flush.compile")
        if sm is not None:
            replay = _sm_body(plan, sm, out_idx, comm, qsel, qcfg, ccfg,
                              hcfg)
            from ._compat import shard_map

            sched, instrs, phases, in_specs, out_specs = sm
            fn = shard_map(replay, mesh=comm.mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
            jitted = jax.jit(fn)
        else:
            def replay(*leaf_vals):
                vals = []
                for fn, codes, kwargs in plan:
                    args = [vals[i] if tag == 0 else leaf_vals[i]
                            for tag, i in codes]
                    vals.append(fn(*args, **kwargs))
                return tuple(vals[i] for i in out_idx)

            jitted = jax.jit(replay, donate_argnums=donate)
        if _capture_hlo:
            global _last_hlo
            try:
                compiled = jitted.lower(*leaves).compile()
                _last_hlo = compiled.as_text()
                return compiled
            except Exception:
                pass
        return jitted

    try:
        program = program_cache().get_custom(key, build)
        _faults().check("fusion.flush.dispatch")
        results = program(*leaves)
    except Exception:
        # HARDENED FAILURE DOMAIN (doc/robustness.md): a failed fused
        # compile or dispatch must not strand the tape. No node has been
        # mutated yet (values land only below), so the whole chain
        # replays inline through the eager per-op path — bitwise the
        # pre-fusion semantics — and the tape ends exactly as consistent
        # as a successful flush (values set, owners written back, args
        # released). A stale captured HLO from an earlier compile must
        # not satisfy a later audit either: the dump is cleared before
        # the fallback (same trap PR 6 fixed for reset(), now for the
        # error path). A genuinely-broken op raises again from the
        # inline replay and surfaces to the caller as eager dispatch
        # would have. The one unreplayable case: a DONATING program that
        # failed mid-dispatch may already have invalidated its input
        # buffers — then the original error re-raises (replaying from
        # deleted buffers would surface a misleading "Array deleted").
        if any(getattr(a, "is_deleted", lambda: False)() for a in leaves):
            raise
        global _last_hlo
        _last_hlo = None
        _metrics().inc("op_engine.fusion_flush_fallbacks")
        _flush_inline(order, has_reduce, has_contract, has_resplit,
                      is_fallback=True)
        return

    m = _metrics()
    m.inc("op_engine.fusion_flushes")
    m.inc("op_engine.fusion_ops", len(order))
    if has_reduce:
        m.inc("op_engine.fusion_reduce_flushes")
    if has_contract:
        m.inc("op_engine.fusion_contract_flushes")
    if has_resplit:
        m.inc("op_engine.fusion_resplit_flushes")
    if qplan is not None:
        # per DISPATCH (cache hits included): the counters mirror what
        # this program's collectives moved, not what compiling cost
        m.inc("op_engine.quant_collectives", qplan[1])
        m.inc("op_engine.quant_bytes_saved", qplan[2])
    if cplan is not None:
        m.inc("op_engine.chunk_collectives", cplan[1])
    if hplan is not None:
        m.inc("op_engine.hier_collectives", hplan[2])

    for pos, res in zip(out_idx, results):
        node = order[pos]
        node.value = res
        owner = node.owner() if node.owner is not None else None
        if owner is not None:
            owner._set_materialized(res)
            if node.kind == "resplit":
                # the translation zero-pads the target axis (shard_map
                # body and GSPMD fallback alike) — certify exactly this
                # buffer, matching the eager planner's _pad_zero claim
                owner._pad_zero_buf = res
    # evaluated interior nodes can never be demanded again (every external
    # holder was promoted to an output) — release their inputs promptly
    for node in order:
        node.args = ()
        node.kwargs = {}


# collective kind -> jax.lax combiner over the mesh axis
_COLL_FNS = {"psum": jax.lax.psum, "pmax": jax.lax.pmax,
             "pmin": jax.lax.pmin}


# ---------------------------------------------------------------------- #
# quantized packed collectives (HEAT_TPU_QUANT_COLLECTIVES)              #
# ---------------------------------------------------------------------- #
def _numel(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _quant_dtype_ok(dt, codec) -> bool:
    """Whether a psum payload of ``dt`` is quantizable under ``codec``.
    Only additive float reductions quantize (pmax/pmin and integer/bool
    payloads must stay exact); f64 is excluded (a user reaching for f64
    asked for the precision); bf16/f16 payloads only gain under ``int8``
    (the bf16 codec would be a no-op re-encode)."""
    if codec == "int8":
        return dt in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16),
                      jnp.dtype(jnp.float16))
    return dt == jnp.dtype(jnp.float32)


def _quant_payload_numel(numels, codec, block) -> int:
    """Wire-payload element count for a group of summands: the int8 codec
    BLOCK-ALIGNS every part (a scale block must never span two packed
    values — one spiky leaf's amax would crush a small-magnitude
    neighbor's elements sharing its block), so each part pads to a block
    multiple; bf16 packs raw."""
    if codec != "int8":
        return sum(numels)
    return sum(n + ((-n) % block) for n in numels)


def _quant_wire_bytes(numels, itemsize: int, codec: str,
                      sizes, block: int) -> Tuple[int, int]:
    """(exact, quantized) modeled ring-wire bytes for one float all-reduce
    of the ``numels``-element summands over mesh axes of the given
    ``sizes`` — the same per-kind formulas
    :func:`heat_tpu.utils.hlo_audit.collective_bytes` applies to real HLO
    dumps, so the ``quant_bytes_saved`` counter and the audit agree by
    construction (up to the exchange's device-chunk tail padding, which
    the model ignores). The EXACT baseline carries the raw concatenated
    payload; only the int8 leg pays the per-part block alignment
    (:func:`_quant_payload_numel`). Exact all-reduce rides reduce-scatter
    + all-gather (2 passes of the payload) over the FULL group; the int8
    codec's a2a/gather legs run over the LARGEST axis only (matching
    :func:`_quant_allreduce_parts`'s primary-axis choice), plus the exact
    f32 psum of the combined chunk over the remaining axes. NOTE for the
    bf16 codec: the model reflects the INTENDED wire dtype — on backends
    whose float normalization upcasts bf16 collectives back to f32
    (XLA:CPU), the real wire saves nothing while the counter still ticks;
    doc/fusion.md documents the caveat (the int8 legs are bitcast-guarded
    precisely to avoid it)."""
    group = 1
    for s in sizes:
        group *= s
    raw = sum(numels)
    exact = 2 * raw * itemsize * (group - 1) // group
    if codec == "bf16":
        quant = 2 * raw * 2 * (group - 1) // group
    else:  # int8
        padded = _quant_payload_numel(numels, codec, block)
        p = max(sizes)           # the primary-axis size (a2a/gather legs)
        r = group // p           # remaining-axes scope (exact chunk psum)
        nblocks = -(-padded // block)
        quant = ((padded + 2 * nblocks) * (p - 1) // p  # a2a s8+u16 scales
                 + 2 * padded * (p - 1) // p)           # u16 gather
        if r > 1:
            # f32 psum of the 1/p-size combined chunk over the rest axes
            quant += 2 * (padded * 4 // p) * (r - 1) // r
    return exact, quant


# ---------------------------------------------------------------------- #
# chunked, double-buffered packed collectives (HEAT_TPU_FUSION_CHUNKS)   #
# ---------------------------------------------------------------------- #
def _chunk_bounds(total: int, n: int, align: int):
    """``[(start, stop), ...]`` contiguous pieces of a ``total``-element
    flat payload: up to ``n`` pieces, every boundary a multiple of
    ``align`` (the tail piece carries any sub-``align`` remainder), sizes
    as even as the alignment admits. ``None`` when fewer than two aligned
    pieces exist — the caller emits the unchunked collective.

    The alignment is what makes chunking VALUE- and BYTE-exact: with
    boundaries on multiples of the communicating group size the per-chunk
    ring-model wire bytes sum to exactly the whole-payload figure
    (``floor((M·g + t)·c/g) == M·c + floor(t·c/g)``), and with the int8
    codec's ``group × block`` alignment every scale block and device
    chunk of each piece coincides with the unchunked exchange's — the
    tail piece pays exactly the padding the unchunked payload would, so
    the audit never double-charges it."""
    if n <= 1 or align < 1:
        return None
    units = total // align
    n = min(int(n), units)
    if n <= 1:
        return None
    base, extra = divmod(units, n)
    bounds, off = [], 0
    for i in range(n):
        stop = off + (base + (1 if i < extra else 0)) * align
        if i == n - 1:
            stop = total  # the tail carries the sub-align remainder
        bounds.append((off, stop))
        off = stop
    return bounds


def _pipe_gate(piece, prev_out):
    """Double-buffer gate: make chunk k's input depend on chunk k-2's
    combined output via ``lax.optimization_barrier`` (values untouched),
    so the scheduler can hold at most TWO chunk collectives in flight —
    chunk k issues while chunk k-1 crosses the wire and chunk k-2's
    consumers compute. Without the gate XLA is free to launch all N legs
    at once, which buys no overlap and N× the in-flight buffer peak."""
    barrier = getattr(jax.lax, "optimization_barrier", None)
    if barrier is None:  # ancient jax: ungated legs are still correct
        return piece
    return barrier((piece, prev_out))[0]


def _chunked_exact(flat, axes, coll, bounds):
    """The exact (or bf16-wire) packed collective over ``flat``, emitted
    as one collective per ``bounds`` piece, double-buffered. Elementwise
    reductions make each piece bitwise the matching slice of the
    unchunked result."""
    outs = []
    for i, (a, b) in enumerate(bounds):
        piece = jax.lax.slice_in_dim(flat, a, b, axis=0)
        if i >= 2:
            piece = _pipe_gate(piece, outs[i - 2])
        outs.append(coll(piece, axes))
    return jnp.concatenate(outs)


def _quant_chunk_bounds(numels, sizes, codec, block, nchunks):
    """Chunk boundaries for one quantized payload group (or ``None``):
    the bf16 codec chunks the raw concatenated payload on group-size
    boundaries like the exact path; the int8 codec chunks the
    block-ALIGNED payload (:func:`_quant_payload_numel`) on
    ``primary_axis_size × block`` boundaries, so every piece's device
    chunks and scale blocks coincide with the unchunked exchange's."""
    if nchunks <= 1:
        return None
    group = 1
    for s in sizes:
        group *= s
    if codec == "int8":
        total = _quant_payload_numel(numels, codec, block)
        align = max(sizes) * block
    else:
        total = sum(numels)
        align = group
    return _chunk_bounds(total, nchunks, align)


def _wire_u16(x):
    """bf16 -> u16 bitcast for float wire legs: XLA:CPU's float
    normalization upcasts bf16 collectives back to f32 (probed on this
    jax — the convert folds THROUGH the collective), which would silently
    un-save the bytes; integer collectives are left alone on every
    backend. Bitwise free both ways."""
    return jax.lax.bitcast_convert_type(x, jnp.uint16)


def _unwire_u16(x):
    return jax.lax.bitcast_convert_type(x, jnp.bfloat16)


def _quant_bf16_allreduce(flat, axes):
    """The bf16 codec: ONE all-reduce with the payload rounded to bf16 —
    EQuARX's BF16 AR. The reduction itself runs at wire precision; the
    downcast saturates (``_sat_bf16``) so a just-above-bf16-max payload
    enters the wire at ±bf16max instead of inf."""
    return jax.lax.psum(_sat_bf16(flat), axes).astype(flat.dtype)


# largest finite bf16 value: the int8 codec's scales and combined chunks
# travel bf16, and every downcast SATURATES into this range instead of
# rounding to inf — a finite f32 sum just above bf16 max must round-trip
# as the saturated value (0.3% off, inside the 1e-2 contract), never as
# inf, and an inf block amax must not poison its scale into inf (whose
# decode is 0*inf = NaN — the PR 10 drive gotcha, regression-pinned in
# tests/test_quant_collectives.py)
_BF16_MAX = 3.3895313892515355e38


def _sat_bf16(x):
    """Saturating f32 -> bf16 downcast (clip into finite bf16 range).
    Identity for in-range values — the clip changes nothing below
    ``_BF16_MAX`` — so in-range payloads stay bitwise the unclipped
    cast. NaN propagates (clip keeps NaN): a NaN payload is the caller's
    bug either way; only the overflow-to-inf poisoning is removed."""
    return jnp.clip(x, -_BF16_MAX, _BF16_MAX).astype(jnp.bfloat16)


def _quant_int8_allreduce(flat, primary, size, rest, block, groups=None,
                          rest_size=1):
    """The int8 block-scaled codec over mesh axis ``primary`` (static size
    ``size``; any ``rest`` axes combine the dequantized chunks exactly):

    encode     per-(device-chunk, ``_QUANT_BLOCK``-block) bf16 scale =
               amax/127, SATURATED into finite bf16 range,
               payload rounded to s8;
    exchange   reduce-scatter as ONE tiled ``all_to_all`` of the s8
               payload (+ scales bitcast u16) — device i receives every
               peer's i-th chunk;
    combine    dequantize + sum in f32 (exact given s8 inputs; the
               summands are pre-scaled down by a power of two so a
               transient partial overflow cannot turn a finite total
               into inf — the shift is exponent-exact, bitwise-neutral
               for in-range payloads);
    return     bf16 ``all_gather`` (bitcast u16 on the wire) of the
               combined chunks — saturating downcast — decoded back to
               the payload dtype.

    This is the arXiv:2004.09362 generalized-allreduce decomposition with
    quantized phases (EQuARX, arXiv:2506.17615). Wire bytes: ~3/8 of the
    exact f32 all-reduce (1 byte down + 2 bytes back vs 4 bytes each
    way). Values combine and return within bf16's finite range: payloads
    whose true sum exceeds it SATURATE at ±bf16max (they no longer
    round-trip as inf/NaN — doc/fusion.md when-not-to). ``groups``
    optionally restricts the exchange to ``axis_index_groups`` subsets of
    ``primary`` — the hierarchical decomposition's DCN leg on a flat
    mesh, where ``size`` is the per-group participant count.
    ``rest_size`` is the product of the ``rest`` axes' sizes: the
    downscale covers the WHOLE summation scope (local combine and the
    rest-axes psum), so the shift back to true magnitude happens only
    after every addition has run."""
    dt = flat.dtype
    f = flat.astype(jnp.float32)
    n = f.shape[0]
    chunk = -(-n // size)
    chunk = -(-chunk // block) * block
    total = chunk * size
    if total != n:
        f = jnp.pad(f, (0, total - n))
    m = f.reshape(size, chunk // block, block)
    amax = jnp.max(jnp.abs(m), axis=-1, keepdims=True)
    # the scale is rounded to bf16 BEFORE the encode divide, so encode and
    # decode use the identical value — no scale-rounding skew. Saturated:
    # an inf amax (non-finite payload block) must yield a finite scale,
    # or the decode's 0 * inf poisons the whole block as NaN
    scale = _sat_bf16(jnp.where(amax > 0, amax, 1.0) * (1.0 / 127.0))
    q = jnp.clip(jnp.round(m / scale.astype(jnp.float32)),
                 -127, 127).astype(jnp.int8)
    q = jax.lax.all_to_all(q, primary, split_axis=0, concat_axis=0,
                           tiled=True, axis_index_groups=groups)
    s = jax.lax.all_to_all(_wire_u16(scale), primary, split_axis=0,
                           concat_axis=0, tiled=True,
                           axis_index_groups=groups)
    s = _unwire_u16(s).astype(jnp.float32)
    # combine with power-of-two downscaled summands: partial sums of
    # `size * rest_size` terms each bounded by amax can transiently pass
    # f32 max even when the total is representable (±1e38-magnitude
    # gradients) — dividing the SCALES by 2^ceil(log2(scope)) bounds
    # every partial (including the rest-axes psum's) by max|amax|, and
    # the final shift back is exact (exponent arithmetic)
    k = float(1 << max(0, (size * max(1, int(rest_size)) - 1)
                       .bit_length()))
    part = jnp.sum(q.astype(jnp.float32) * (s * (1.0 / k)), axis=0)
    if rest:
        part = jax.lax.psum(part, rest)
    part = part * k
    g = jax.lax.all_gather(_wire_u16(_sat_bf16(part)), primary, axis=0,
                           tiled=True, axis_index_groups=groups)
    out = _unwire_u16(g).astype(jnp.float32).reshape(-1)
    if total != n:
        out = out[:n]
    return out.astype(dt)


def _quant_allreduce_parts(parts, axes, sizes, codec, block, bounds=None):
    """Quantized all-reduce of mutually independent same-dtype shard-local
    summands: flatten-concat (the int8 codec block-ALIGNS each part —
    see :func:`_quant_payload_numel`), one quantized exchange, unpack.
    The int8 exchange runs over the LARGEST axis (best chunking) with any
    remaining axes combined exactly on the already-reduced chunks.
    ``bounds`` (:func:`_quant_chunk_bounds`) splits the exchange into
    double-buffered pipeline chunks — per-codec block alignment makes the
    chunked exchange bitwise the unchunked one."""
    if codec == "int8":
        flats = []
        for p in parts:
            v = p.reshape(-1)
            pad = (-_numel(p.shape)) % block
            flats.append(jnp.pad(v, (0, pad)) if pad else v)
        flat = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
        k, rest, rest_size = _slow_primary(axes, sizes)
        if bounds is None:
            comb = _quant_int8_allreduce(flat, axes[k], sizes[k], rest,
                                         block, rest_size=rest_size)
        else:
            def int8_leg(piece, _axes):
                return _quant_int8_allreduce(piece, axes[k], sizes[k],
                                             rest, block,
                                             rest_size=rest_size)

            comb = _chunked_exact(flat, None, int8_leg, bounds)
        stride = block
    else:
        flat = parts[0].reshape(-1) if len(parts) == 1 else \
            jnp.concatenate([p.reshape(-1) for p in parts])
        if bounds is None:
            comb = _quant_bf16_allreduce(flat, tuple(axes))
        else:
            comb = _chunked_exact(flat, tuple(axes), _quant_bf16_allreduce,
                                  bounds)
        stride = 1
    out, off = [], 0
    for p in parts:
        n = _numel(p.shape)
        out.append(comb[off:off + n].reshape(p.shape))
        off += n + ((-n) % stride)
    return out


# ---------------------------------------------------------------------- #
# tier-aware hierarchical packed collectives (HEAT_TPU_HIER)             #
# ---------------------------------------------------------------------- #
def _slow_axis_name(hk) -> str:
    """The slow (DCN) tier's mesh-axis name under declaration ``hk[1]``:
    the first name of a name-form declaration, else the built-in
    ``"dcn"`` (a grid that names an axis ``"dcn"`` has declared it)."""
    t = hk[1]
    if isinstance(t, tuple) and t and isinstance(t[0], str):
        return t[0]
    return "dcn"


def _hier_factor(size, hk):
    """The declared ``(d, i)`` factorization when it exactly factors a
    flat ``size``-device scope into d>1 hosts × i>1 devices, else None."""
    t = hk[1]
    if not (isinstance(t, tuple) and len(t) == 2
            and all(isinstance(v, int) for v in t)):
        return None
    d, i = t
    if d > 1 and i > 1 and d * i == int(size):
        return (d, i)
    return None


def _hier_dtype_ok(dt) -> bool:
    """bool payloads keep the flat collective (a reduce-scattered pred
    reduction is not portably expressible); every other dtype decomposes
    exactly (sum reassociation: bitwise for ints, few-ulp for floats)."""
    return dt != jnp.dtype(jnp.bool_)


def _hier_subgroups(members, qset, numel_of, dt, dcn_codec, ici_codec,
                    ici_floor):
    """The qm/im/rest tier-subgroup split — ONE source for the
    predicates the plan/key/body-agreement argument depends on, shared
    by the flush body (``_sm_body.emit_all``), :func:`packed_psum` and
    :func:`_chunk_flush_plan`: quant-selected members (``qset``) carry
    the DCN codec plus the ICI codec on the fast legs; with the ICI
    codec armed but no DCN selection, floor-qualifying f32 members still
    ride the bf16 fast legs; everything else goes exact. Returns
    ``((qm, dcn_codec, ici), (im, None, ici), (rest, None, None))``."""
    qm = [m for m in members if m in qset]
    im = []
    if ici_codec == "bf16" and dt == jnp.dtype(jnp.float32):
        im = [m for m in members if m not in qset
              and numel_of(m) >= ici_floor]
    taken = set(qm) | set(im)
    rest = [m for m in members if m not in taken]
    return ((qm, dcn_codec, ici_codec), (im, None, ici_codec),
            (rest, None, None))


def _slow_primary(axes, sizes):
    """``(primary index, rest axis names, rest size product)`` — the
    largest-axis primary selection of the int8 exchange, shared by
    :func:`_quant_allreduce_parts` and ``_TierComm.slow_allreduce`` so
    the axis the a2a/gather legs ride (and the overflow downscale's
    scope) can never drift between the flat and tiered paths."""
    k = max(range(len(axes)), key=lambda j: sizes[j])
    rest = tuple(a for j, a in enumerate(axes)
                 if j != k and sizes[j] > 1)
    rest_size = 1
    for j, s in enumerate(sizes):
        if j != k and s > 1:
            rest_size *= s
    return k, rest, rest_size


class _TierComm:
    """Static leg descriptor for ONE hierarchical packed exchange: how to
    reduce-scatter / all-gather over the fast (ICI) tier and all-reduce
    over the slow (DCN) tier. Two forms share the interface:

    * **named** — the scope's mesh axes split by name into fast/slow
      tiers (a ``MeshGrid`` with a ``"dcn"`` axis: the 5-axis
      ``TransformerLM`` grid, ``DataParallel``'s 2-D tier grid, DASO);
    * **flat** — a single mesh axis with a declared ``(d, i)``
      factorization, tiers expressed as ``axis_index_groups`` (the flush
      path's 1-D communicator; device order is dcn-major, matching
      ``jax.devices()`` on a real pod).

    ``replicated=True`` marks values already replicated over the fast
    tier (DASO's slow-tier capture): the reduce-scatter degenerates to a
    zero-collective static slice of each device's own tile."""

    __slots__ = ("pf", "ps", "fast_axes", "fast_sizes", "slow_axes",
                 "slow_sizes", "axn", "fast_groups", "slow_groups",
                 "replicated")

    def __init__(self):
        self.axn = None
        self.fast_groups = self.slow_groups = None
        self.replicated = False

    @classmethod
    def named(cls, fast_axes, fast_sizes, slow_axes, slow_sizes,
              replicated=False):
        tc = cls()
        tc.fast_axes = tuple(fast_axes)
        tc.fast_sizes = tuple(int(s) for s in fast_sizes)
        tc.slow_axes = tuple(slow_axes)
        tc.slow_sizes = tuple(int(s) for s in slow_sizes)
        tc.pf = 1
        for s in tc.fast_sizes:
            tc.pf *= s
        tc.ps = 1
        for s in tc.slow_sizes:
            tc.ps *= s
        tc.replicated = bool(replicated)
        return tc

    @classmethod
    def flat(cls, axn, d, i):
        tc = cls()
        tc.axn = axn
        tc.pf, tc.ps = int(i), int(d)
        tc.fast_sizes, tc.slow_sizes = (int(i),), (int(d),)
        tc.fast_axes = tc.slow_axes = ()
        # dcn-major device order: device h*i + j = host h, local slot j
        tc.fast_groups = tuple(tuple(h * i + j for j in range(i))
                               for h in range(d))
        tc.slow_groups = tuple(tuple(h * i + j for h in range(d))
                               for j in range(i))
        return tc

    # -- fast (ICI) tier legs ----------------------------------------- #
    def rs(self, x):
        """Tiled reduce-scatter of a flat payload over the fast tier."""
        if self.axn is not None:
            return jax.lax.psum_scatter(
                x, self.axn, scatter_dimension=0, tiled=True,
                axis_index_groups=self.fast_groups)
        return jax.lax.psum_scatter(x, self.fast_axes,
                                    scatter_dimension=0, tiled=True)

    def ag(self, x):
        """Tiled all-gather of the combined shard over the fast tier."""
        if self.axn is not None:
            return jax.lax.all_gather(x, self.axn, axis=0, tiled=True,
                                      axis_index_groups=self.fast_groups)
        return jax.lax.all_gather(x, self.fast_axes, axis=0, tiled=True)

    def fast_index(self):
        """This device's flattened index along the fast tier (the tile
        the replicated form slices in place of the reduce-scatter)."""
        if self.axn is not None:
            return jax.lax.axis_index(self.axn) % self.pf
        idx = None
        for a, s in zip(self.fast_axes, self.fast_sizes):
            ai = jax.lax.axis_index(a)
            idx = ai if idx is None else idx * s + ai
        return idx

    # -- slow (DCN) tier leg ------------------------------------------ #
    def _slow_psum(self, x):
        if self.axn is not None:
            return jax.lax.psum(x, self.axn,
                                axis_index_groups=self.slow_groups)
        return jax.lax.psum(x, self.slow_axes)

    def slow_allreduce(self, x, codec, block, bounds=None):
        """All-reduce of the 1/pf shard across the slow tier with the
        DCN wire codec; ``bounds`` pipelines this leg into
        double-buffered chunks (the PR 10 chunking composed onto the
        slow tier — the legs worth overlapping are the slow ones)."""
        if codec == "int8":
            if self.axn is not None:
                def leg(piece, _axes):
                    return _quant_int8_allreduce(
                        piece, self.axn, self.ps, (), block,
                        groups=self.slow_groups)
            else:
                k, rest, rest_size = _slow_primary(self.slow_axes,
                                                   self.slow_sizes)

                def leg(piece, _axes):
                    return _quant_int8_allreduce(
                        piece, self.slow_axes[k], self.slow_sizes[k],
                        rest, block, rest_size=rest_size)
        elif codec == "bf16":
            def leg(piece, _axes):
                return self._slow_psum(_sat_bf16(piece)).astype(piece.dtype)
        else:
            def leg(piece, _axes):
                return self._slow_psum(piece)
        if bounds is None:
            return leg(x, None)
        return _chunked_exact(x, None, leg, bounds)


def _tier_scope(axes, sizes, hk, replicated=()):
    """A :class:`_TierComm` for a ``packed_psum`` reduction scope, or
    None when no hierarchy applies: the REPLICATED form when the caller
    declares fast axes its values are replicated over (DASO), the named
    split when the scope contains the slow-named axis plus fast axes
    (tiered model grids), or the flat ``(d, i)`` factorization when the
    scope is one axis of exactly that size."""
    if replicated:
        rep = tuple(replicated)
        rsizes = tuple(int(jax.lax.psum(1, a)) for a in rep)
        pf = 1
        for s in rsizes:
            pf *= s
        if pf > 1:
            return _TierComm.named(rep, rsizes, axes, sizes,
                                   replicated=True)
        return None
    slow_name = _slow_axis_name(hk)
    slow = tuple(j for j, a in enumerate(axes)
                 if a == slow_name and sizes[j] > 1)
    fast = tuple(j for j, a in enumerate(axes)
                 if a != slow_name and sizes[j] > 1)
    if slow and fast:
        return _TierComm.named(
            tuple(axes[j] for j in fast), tuple(sizes[j] for j in fast),
            tuple(axes[j] for j in slow), tuple(sizes[j] for j in slow))
    if len(axes) == 1:
        f = _hier_factor(sizes[0], hk)
        if f is not None:
            return _TierComm.flat(axes[0], f[0], f[1])
    return None


def _hier_leg_bounds(numels, codec, block, pf, ps, cn):
    """Pipeline-chunk bounds for the DCN leg of one hierarchical payload
    group (PR 10 chunking composed onto the slow tier), or None: the
    1/pf shard splits on ps-aligned (int8: ps×block-aligned) boundaries
    so every piece's device chunks and scale blocks coincide with the
    unchunked slow exchange's — value- and byte-exact per the
    ``_chunk_bounds`` lemma."""
    stride = pf * (block if codec == "int8" else 1)
    shard_total = sum(n + ((-n) % stride) for n in numels) // pf
    align = ps * (block if codec == "int8" else 1)
    return _chunk_bounds(shard_total, cn, align)


def _hier_allreduce_parts(parts, tc, dcn_codec, block, ici_codec,
                          bounds=None):
    """Hierarchical all-reduce of mutually independent same-dtype
    shard-local summands: flatten-concat (each part padded so every
    fast-tier tile boundary — and, under the int8 DCN codec, every scale
    block — stays within one part), reduce-scatter over the fast (ICI)
    tier, all-reduce of the 1/pf shard over the slow (DCN) tier with the
    DCN wire codec (``bounds`` pipelines THIS leg), then all-gather back
    over the fast tier — the generalized-allreduce decomposition
    (arXiv:2004.09362) with EQuARX's tier-selective codecs
    (arXiv:2506.17615): full-precision bytes cross the fast wire, only
    the 1/pf shard (optionally block-scaled int8) crosses the slow one.

    ``ici_codec="bf16"`` rounds the payload to bf16 for the fast legs
    (native on TPU ICI; the all-gather travels bitcast u16 so XLA:CPU
    float normalization cannot upcast it — the reduce-scatter is a
    reduction and keeps the usual bf16-collective CPU caveat). With
    ``tc.replicated`` the reduce-scatter degenerates to each device's
    zero-collective static slice of its own tile (values already agree
    across the fast tier — DASO's capture).

    Value contract: the decomposition re-associates the flat psum —
    bitwise for integer payloads, few-ulp for floats (the documented
    psum-reassociation freedom); tier codecs add their documented error
    on top, on their tier only."""
    dt = parts[0].dtype
    pf = tc.pf
    stride = pf * (block if dcn_codec == "int8" else 1)
    flats = []
    for p in parts:
        v = p.reshape(-1)
        pad = (-v.shape[0]) % stride
        flats.append(jnp.pad(v, (0, pad)) if pad else v)
    flat = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
    wire_bf16 = ici_codec == "bf16" and flat.dtype == jnp.dtype(jnp.float32)
    if tc.replicated:
        chunkn = flat.shape[0] // pf
        shard = jax.lax.dynamic_slice_in_dim(
            flat, tc.fast_index() * chunkn, chunkn, axis=0)
        if wire_bf16:
            shard = _sat_bf16(shard).astype(flat.dtype)
    elif wire_bf16:
        shard = tc.rs(_sat_bf16(flat)).astype(flat.dtype)
    else:
        shard = tc.rs(flat)
    comb = tc.slow_allreduce(shard, dcn_codec, block, bounds=bounds)
    if wire_bf16:
        out_flat = _unwire_u16(tc.ag(_wire_u16(_sat_bf16(comb)))).astype(dt)
    else:
        out_flat = tc.ag(comb)
    out, off = [], 0
    for p in parts:
        n = _numel(p.shape)
        out.append(out_flat[off:off + n].reshape(p.shape))
        off += n + ((-n) % stride)
    return out


def _hier_wire_bytes(numels, itemsize: int, dcn_codec, ici_codec,
                     pf: int, ps: int, block: int) -> Tuple[int, int]:
    """(flat exact, hierarchical) modeled ring-wire bytes for one psum
    payload group under the tier decomposition — the same per-kind
    formulas :func:`heat_tpu.utils.hlo_audit.collective_bytes` applies
    to real HLO (AR = 2R(g-1)/g, RS = R_out(g-1), AG = R_out(g-1)/g),
    so the counters and the audit agree by construction. The exact
    baseline is the flat full-mesh all-reduce of the raw payload; the
    hierarchical figure sums the fast RS+AG legs (bf16-halved under the
    ICI codec) and the slow leg at 1/pf payload with the DCN codec."""
    g = pf * ps
    raw = sum(numels)
    exact = 2 * raw * itemsize * (g - 1) // g
    if dcn_codec == "int8":
        padded = sum(n + ((-n) % (pf * block)) for n in numels)
    else:
        padded = sum(n + ((-n) % pf) for n in numels)
    item_fast = 2 if ici_codec == "bf16" else itemsize
    hier = 2 * padded * item_fast * (pf - 1) // pf  # RS + AG over ici
    shard = padded // pf
    if dcn_codec == "int8":
        nblocks = -(-shard // block)
        hier += ((shard + 2 * nblocks) * (ps - 1) // ps  # a2a s8 + scales
                 + 2 * shard * (ps - 1) // ps)           # u16 gather
    elif dcn_codec == "bf16":
        hier += 2 * shard * 2 * (ps - 1) // ps
    else:
        hier += 2 * shard * itemsize * (ps - 1) // ps
    return exact, hier


def _hier_flush_plan(order, sm, comm):
    """Static hierarchical-decomposition selection for one shard_map
    flush: ``(hcfg, htag, n_groups)`` — the ``(d, i, ici_codec,
    ici_floor)`` leg configuration captured AT PLANNING TIME (a
    concurrent ``set_mesh_tiers``/``set_hier_enabled``/floor change
    between planning and the deferred jit trace must not change the
    collective structure out from under the program key; the floor
    selects which payloads ride the bf16 fast legs when no quant codec
    is armed), the tag that keys the program, and the number of psum
    payload groups the body decomposes (ticked per dispatch as
    ``op_engine.hier_collectives``) — or None when the hierarchy does
    not apply (gate off, no/mismatched factorization for this flat
    communicator, no qualifying psum group). The ``fusion.hier.exchange``
    fault site fires here: a fault degrades the WHOLE flush to the flat
    packed emission — keyed as such, so it HITS any cached flat program
    — counted in ``op_engine.hier_fallbacks``."""
    hkey = hier_key()
    if not hkey[0]:
        return None
    f = _hier_factor(comm.size, hkey)
    if f is None:
        return None
    sched, instrs, phases, _, _ = sm
    totals: Dict[Tuple, int] = {}
    for pos in sched:
        ins = instrs[pos]
        if ins[0] in ("reduce", "contract") and ins[1] == "psum" \
                and _hier_dtype_ok(jnp.dtype(order[pos].aval.dtype)):
            key = (phases[pos], str(jnp.dtype(order[pos].aval.dtype)))
            totals[key] = totals.get(key, 0) + _numel(order[pos].aval.shape)
    # the hier payload floor gates per GROUP total (hkey[3], captured):
    # latency-bound tiny groups keep the flat collective
    n = sum(1 for v in totals.values() if v >= hkey[3])
    if not n:
        return None
    try:
        _faults().check("fusion.hier.exchange")
    except Exception:
        _metrics().inc("op_engine.hier_fallbacks")
        return None
    floor = _QUANT_FLOOR
    return (f[0], f[1], hkey[2], floor, hkey[3]), (hkey, floor), n


def reset_qinfo(qinfo: dict) -> None:
    """Reset a ``packed_psum`` accounting dict at the START of a traced
    body — runs once per trace, so the dict is stable (and idempotent
    across retraces) by the time any dispatch completes."""
    qinfo["collectives"] = 0
    qinfo["bytes_saved"] = 0
    qinfo["chunk_collectives"] = 0
    qinfo["hier_collectives"] = 0


def tick_quant(qinfo: dict) -> None:
    """Tick ``op_engine.quant_collectives`` / ``quant_bytes_saved`` (and
    ``op_engine.chunk_collectives`` for chunk-pipelined payload groups)
    from a trace-time ``packed_psum`` accounting dict — call once per
    DISPATCH of the program whose body filled it (the model-level step
    wrappers and DASO's capture do; the flush path ticks from its static
    plan)."""
    if qinfo.get("collectives"):
        m = _metrics()
        m.inc("op_engine.quant_collectives", qinfo["collectives"])
        m.inc("op_engine.quant_bytes_saved", qinfo["bytes_saved"])
    if qinfo.get("chunk_collectives"):
        _metrics().inc("op_engine.chunk_collectives",
                       qinfo["chunk_collectives"])
    if qinfo.get("hier_collectives"):
        _metrics().inc("op_engine.hier_collectives",
                       qinfo["hier_collectives"])


def _quant_flush_plan(order, sm, comm, hcfg=None):
    """Static quant selection for one shard_map flush: ``(qsel, n,
    bytes_saved, qkey)`` — the pending-psum node positions routed through
    the quantized exchange, the rewritten-collective count, the modeled
    wire bytes saved (both ticked per dispatch by ``_flush_locked``) and
    the :func:`quant_key` captured AT PLANNING TIME (a concurrent
    ``set_quant_codec`` between planning and build must not key or trace
    the program with a different codec than the one the selection is
    valid for) — or None when nothing qualifies. Mirrors ``emit_all``'s
    phase grouping exactly (same (phase, kind, dtype) keys), so the
    selection, the program key and the body agree by construction. The
    ``fusion.quant.encode`` fault site fires here: a fault falls back to
    the exact collectives (and, via the key, to any cached exact
    program), counted in ``op_engine.quant_fallbacks``."""
    qkey = quant_key()  # one coherent read of the codec configuration
    codec, floor, block = qkey
    if codec is None or comm.size < 2:
        return None
    sched, instrs, phases, _, _ = sm
    groups: Dict[Tuple, list] = {}
    for pos in sched:
        ins = instrs[pos]
        if ins[0] not in ("reduce", "contract") or ins[1] != "psum":
            continue
        dt = jnp.dtype(order[pos].aval.dtype)
        groups.setdefault((phases[pos], str(dt)), []).append(pos)
    sel, n, saved = set(), 0, 0
    for (_ph, _dt), members in groups.items():
        dt = jnp.dtype(_dt)
        if not _quant_dtype_ok(dt, codec):
            continue
        mq = [p for p in members
              if _numel(order[p].aval.shape) >= floor]
        if not mq:
            continue
        numels = [_numel(order[p].aval.shape) for p in mq]
        if hcfg is not None:
            # hierarchical flush: the byte model follows the tiered legs
            # (pf = hcfg[1] ici, ps = hcfg[0] dcn, ici codec hcfg[2]),
            # not the flat exchange the body no longer emits
            e, q = _hier_wire_bytes(numels, dt.itemsize, codec, hcfg[2],
                                    hcfg[1], hcfg[0], block)
        else:
            e, q = _quant_wire_bytes(numels, dt.itemsize, codec,
                                     (comm.size,), block)
        sel.update(mq)
        n += 1
        saved += max(0, e - q)
    if not sel:
        return None
    try:
        _faults().check("fusion.quant.encode")
    except Exception:
        _metrics().inc("op_engine.quant_fallbacks")
        return None
    return frozenset(sel), n, saved, qkey


def _chunk_flush_plan(order, sm, comm, qsel, qcfg, hcfg=None):
    """Static chunk selection for one shard_map flush: ``(ckey,
    n_groups)`` — the :func:`chunk_key` captured AT PLANNING TIME (a
    concurrent ``set_chunk_count`` between planning and the deferred jit
    trace must not change the leg structure out from under the program
    key) and the number of packed payload groups the body will emit
    chunked (ticked per dispatch as ``op_engine.chunk_collectives``) —
    or None when nothing qualifies. Mirrors ``emit_all``'s grouping and
    its quant split exactly (same (phase, kind, dtype) keys, same
    payload-floor and alignment predicates over the same static shapes),
    so the selection, the program key and the traced body agree by
    construction. The ``fusion.chunk.dispatch`` fault site fires here,
    once per intended chunk leg: a fault degrades the WHOLE flush to the
    unchunked packed emission — keyed as such, so it HITS any cached
    unchunked program — counted in ``op_engine.chunk_fallbacks``."""
    ckey = chunk_key()  # one coherent read of the chunk configuration
    cn, cfloor = ckey
    if cn <= 1 or comm.size < 2:
        return None
    sched, instrs, phases, _, _ = sm
    groups: Dict[Tuple, list] = {}
    for pos in sched:
        ins = instrs[pos]
        if ins[0] not in ("reduce", "contract") or ins[1] is None:
            continue
        dt = jnp.dtype(order[pos].aval.dtype)
        groups.setdefault((phases[pos], ins[1], str(dt)), []).append(pos)
    chunked = 0
    for (_ph, _kind, _dt), members in groups.items():
        numel_of = lambda p: _numel(order[p].aval.shape)  # noqa: E731
        hier_grp = (hcfg is not None and _kind == "psum"
                    and _hier_dtype_ok(jnp.dtype(_dt))
                    and sum(numel_of(p) for p in members) >= hcfg[4])
        if hier_grp:
            # hierarchical group: chunking rides the DCN leg of each
            # subgroup — the SAME shared split + bounds predicates the
            # body applies (_hier_subgroups / _hier_leg_bounds)
            for sub, sub_codec, _si in _hier_subgroups(
                    members, qsel, numel_of, jnp.dtype(_dt), qcfg[0],
                    hcfg[2], hcfg[3]):
                if not sub:
                    continue
                numels = [numel_of(p) for p in sub]
                if sum(numels) >= cfloor and _hier_leg_bounds(
                        numels, sub_codec, qcfg[2], hcfg[1], hcfg[0],
                        cn) is not None:
                    chunked += 1
            continue
        qm = [p for p in members if p in qsel]
        rest = [p for p in members if p not in qsel]
        if qm:
            numels = [numel_of(p) for p in qm]
            if sum(numels) >= cfloor and _quant_chunk_bounds(
                    numels, (comm.size,), qcfg[0], qcfg[2],
                    cn) is not None:
                chunked += 1
        if rest:
            total = sum(numel_of(p) for p in rest)
            if total >= cfloor and _chunk_bounds(
                    total, cn, comm.size) is not None:
                chunked += 1
    if not chunked:
        return None
    try:
        for _ in range(cn):  # the site fires per intended chunk leg
            _faults().check("fusion.chunk.dispatch")
    except Exception:
        _metrics().inc("op_engine.chunk_fallbacks")
        return None
    return ckey, chunked


def _plan_sm(order, plan, leaves, leaf_splits, out_idx, comm):
    """Translate a reduce-carrying tape into a shard_map execution plan, or
    None when the tape is not provably block-safe (the caller then
    compiles the global replay under plain ``jax.jit`` and GSPMD places
    the collectives — still one program, just not hand-placed).

    The plan tracks each value's layout state (split axis or replicated),
    schedules nodes into **phases** so that mutually independent split-axis
    reductions land in the same phase (one packed collective per
    ``(phase, kind, dtype)`` — the fused tuple all-reduce), and notes where
    a replicated operand must be sliced to the local block.

    Returns ``(sched, instrs, phases, in_specs, out_specs)``.
    """
    size = comm.size
    states = []   # split axis of each produced value (None = replicated)
    instrs = []   # per node: ("ew", blocks) | ("pad", ax) | ("mask",)
                  #           | ("reduce", collective-or-None)
    phases = []   # emission phase per node (barrier between phases)

    def state_of(tag, i):
        return states[i] if tag == 0 else leaf_splits[i]

    def shape_of(tag, i):
        return (tuple(order[i].aval.shape) if tag == 0
                else tuple(leaves[i].shape))

    for pos, node in enumerate(order):
        _, codes, kwargs = plan[pos]
        phase = 0
        for tag, i in codes:
            if tag == 0:
                p = phases[i]
                inner = order[i]
                if (inner.kind == "reduce" and inner.rmeta["touches"]) or \
                        (inner.kind == "contract"
                         and inner.cmeta["collective"] is not None):
                    p += 1  # consumes a combined value: next phase
                phase = max(phase, p)
        if node.kind == "reduce":
            m = node.rmeta
            (tag, i), = codes
            if m["touches"]:
                if m["collective"] is None or node.comm is not comm:
                    return None
                if state_of(tag, i) != m["in_split"]:
                    return None
            elif state_of(tag, i) != m["in_split"]:
                return None
            instrs.append(("reduce", m["collective"] if m["touches"] else None))
        elif node.kind == "contract":
            cm = node.cmeta
            if not cm["translatable"] or node.comm is not comm:
                return None
            (ta, ia), (tb, ib) = codes
            sa, sb = state_of(ta, ia), state_of(tb, ib)
            blocks = ()
            if cm["case"] == "psum":
                # partial GEMM + psum. A replicated side (even contracted
                # extent — no alignment pad node carried it to block
                # state) is dynamic-sliced to its contracted-axis block
                # in the body, like replicated "ew" operands; extents are
                # aligned by construction (record_contract pads), checked
                # here so a mismatch falls back instead of miscomputing
                ok = (sa in (1, None) and sb in (0, None)
                      and (sa, sb) != (None, None))
                ka = shape_of(ta, ia)[1]
                sl = []
                if ok and sa is None:
                    ok = ka == shape_of(tb, ib)[0] and ka % size == 0
                    sl.append((0, 1))
                if ok and sb is None:
                    kb = shape_of(tb, ib)[0]
                    ok = kb == ka and kb % size == 0
                    sl.append((1, 0))
                blocks = tuple(sl)
            else:
                ok = {"local0": sa == 0 and sb is None,  # block GEMM, out 0
                      "local1": sa is None and sb == 1,  # block GEMM, out 1
                      "replicated": sa is None and sb is None,
                      }.get(cm["case"], False)
            if not ok:
                return None
            instrs.append(("contract", cm["collective"], blocks))
        elif node.kind == "resplit":
            # the planner's move mid-body: the collective sits between the
            # upstream and downstream block computations, and the value's
            # layout state switches from the source to the target split
            if node.comm is not comm:
                return None
            (tag, i), = codes
            j, k = node.smeta["from"], node.smeta["to"]
            if state_of(tag, i) != j:
                return None
            gs = kwargs["gshape"]
            expect = list(gs)
            if j is not None:
                expect[j] = comm.padded_size(gs[j])
            if tuple(shape_of(tag, i)) != tuple(expect):
                return None  # off-canonical value: let GSPMD sort it out
            instrs.append(("resplit", j, k))
        elif node.kind == "crop":
            # a crop's limits span the GLOBAL padded extent — no blockwise
            # form exists (it only ever follows a gspmd-case contract)
            return None
        elif node.kind == "mask":
            (tag, i), = codes
            if state_of(tag, i) != kwargs["axis"] or node.split != kwargs["axis"]:
                return None
            instrs.append(("mask",))
        elif node.kind == "pad":
            (tag, i), = codes
            if state_of(tag, i) is not None or node.split is None:
                return None
            instrs.append(("pad", node.split))
        else:
            k = node.split
            nshape = tuple(node.aval.shape)
            blocks = []
            for ci, (tag, i) in enumerate(codes):
                s = state_of(tag, i)
                oshape = shape_of(tag, i)
                offset = len(nshape) - len(oshape)
                if s is None:
                    if k is not None:
                        ax = k - offset
                        if ax >= 0 and oshape[ax] == nshape[k] \
                                and nshape[k] != 1:
                            blocks.append((ci, ax))
                elif k is None or s + offset != k or oshape[s] != nshape[k]:
                    return None  # layout the block model cannot express
            instrs.append(("ew", tuple(blocks)))
        states.append(node.split)
        phases.append(phase)

    for a, s in zip(leaves, leaf_splits):
        if s is None:
            continue
        if a.ndim <= s or a.shape[s] == 0 or a.shape[s] % size != 0:
            return None
        if getattr(getattr(a, "sharding", None), "mesh", None) != comm.mesh:
            return None  # foreign-mesh leaf: let GSPMD sort the layout out

    # stable phase-major topological schedule: same-phase touching reduces
    # become one packed collective at the phase barrier
    sched = sorted(range(len(order)), key=lambda p: (phases[p], p))
    in_specs = tuple(comm.spec(a.ndim, s)
                     for a, s in zip(leaves, leaf_splits))
    out_specs = tuple(comm.spec(len(order[p].aval.shape), states[p])
                      for p in out_idx)
    return sched, instrs, phases, in_specs, out_specs


def _sm_body(plan, sm, out_idx, comm, qsel=frozenset(),
             qcfg=(None, 0, 0), ccfg=(1, 0), hcfg=None):
    """The shard_map replay body for a :func:`_plan_sm` plan: every value
    is a shard-local block (replicated values are full arrays), reduce
    partials accumulate per phase and combine in ONE flattened collective
    per ``(kind, dtype)`` at each phase barrier. Positions in ``qsel``
    (:func:`_quant_flush_plan`) route through the quantized exchange for
    the CAPTURED ``qcfg = (codec, floor, block)`` instead (never the live
    globals — the trace may run after a toggle); sub-floor members of the
    same group keep the exact flattened psum alongside. ``ccfg = (count,
    floor)`` (:func:`_chunk_flush_plan`'s captured :func:`chunk_key`)
    splits qualifying payload groups into double-buffered pipeline chunk
    collectives — same floor/alignment predicates as the plan, so the
    body emits exactly the leg structure the plan counted and keyed.
    ``hcfg = (d, i, ici_codec)`` (:func:`_hier_flush_plan`'s captured
    tier factorization) routes every psum payload group through the
    hierarchical decomposition instead — reduce-scatter inside each
    i-device ICI group, all-reduce of the 1/i shard across the d DCN
    peers (quant members with the DCN codec, chunk bounds on this leg),
    all-gather back — so full-precision bytes never cross the slow tier
    whole. pmax/pmin (and bool) groups keep the flat collective."""
    sched, instrs, phases, _, _ = sm
    axn = comm.axis_name
    size = comm.size
    cn, cfloor = ccfg
    tc = _TierComm.flat(axn, hcfg[0], hcfg[1]) if hcfg is not None else None
    hier_ici = hcfg[2] if hcfg is not None else None
    # lazy (utils/core cycle): the resplit branch reuses the planner's
    # pad helper so the blockwise translation shares its one source
    from . import resharding

    def body(*leaf_vals):
        vals = [None] * len(plan)
        pend = {}  # pos -> collective kind (partials awaiting combine)

        def emit_all():
            groups: Dict[Tuple, list] = {}
            for pos2, kind in pend.items():
                groups.setdefault((kind, jnp.dtype(vals[pos2].dtype)),
                                  []).append(pos2)
            pend.clear()
            for (kind, _dt), members in groups.items():
                coll = _COLL_FNS[kind]
                if tc is not None and kind == "psum" \
                        and _hier_dtype_ok(_dt) \
                        and sum(_numel(vals[p2].shape)
                                for p2 in members) >= hcfg[4]:
                    # hierarchical decomposition (group total at/above
                    # the captured hier floor): the shared subgroup
                    # split — qsel members carry the DCN codec (and the
                    # ICI codec on the fast legs); with no quant codec
                    # armed the ICI codec still applies to the
                    # floor-qualifying f32 payloads (the plan's
                    # CAPTURED floor, mirroring packed_psum); the rest
                    # ride exact tiered legs. PR 10 chunk bounds
                    # pipeline each DCN sub-leg
                    for sub, sub_codec, sub_ici in _hier_subgroups(
                            members, qsel,
                            lambda p2: _numel(vals[p2].shape), _dt,
                            qcfg[0], hier_ici, hcfg[3]):
                        if not sub:
                            continue
                        numels = [_numel(vals[p2].shape) for p2 in sub]
                        bounds = None
                        if cn > 1 and sum(numels) >= cfloor:
                            bounds = _hier_leg_bounds(
                                numels, sub_codec, qcfg[2], tc.pf,
                                tc.ps, cn)
                        for p2, v in zip(sub, _hier_allreduce_parts(
                                [vals[p2] for p2 in sub], tc, sub_codec,
                                qcfg[2], sub_ici, bounds=bounds)):
                            vals[p2] = v
                    continue
                if qsel:
                    qm = [p2 for p2 in members if p2 in qsel]
                    if qm:
                        numels = [_numel(vals[p2].shape) for p2 in qm]
                        bounds = None
                        if cn > 1 and sum(numels) >= cfloor:
                            bounds = _quant_chunk_bounds(
                                numels, (size,), qcfg[0], qcfg[2], cn)
                        for p2, v in zip(qm, _quant_allreduce_parts(
                                [vals[p2] for p2 in qm], (axn,), (size,),
                                qcfg[0], qcfg[2], bounds=bounds)):
                            vals[p2] = v
                        members = [p2 for p2 in members if p2 not in qsel]
                        if not members:
                            continue
                total = sum(_numel(vals[p2].shape) for p2 in members)
                bounds = (_chunk_bounds(total, cn, size)
                          if cn > 1 and total >= cfloor else None)
                if bounds is None and len(members) == 1:
                    p2 = members[0]
                    vals[p2] = coll(vals[p2], axn)
                    continue
                packed = jnp.concatenate([vals[p2].reshape(-1)
                                          for p2 in members])
                combined = (coll(packed, axn) if bounds is None
                            else _chunked_exact(packed, axn, coll, bounds))
                off = 0
                for p2 in members:
                    shp = vals[p2].shape
                    n = 1
                    for s in shp:
                        n *= s
                    vals[p2] = combined[off:off + n].reshape(shp)
                    off += n

        def block(a, ax):
            chunk = a.shape[ax] // size
            return jax.lax.dynamic_slice_in_dim(
                a, jax.lax.axis_index(axn) * chunk, chunk, axis=ax)

        cur = 0
        for pos in sched:
            if phases[pos] != cur:
                emit_all()
                cur = phases[pos]
            fn, codes, kwargs = plan[pos]
            args = [vals[i] if tag == 0 else leaf_vals[i]
                    for tag, i in codes]
            ins = instrs[pos]
            op = ins[0]
            if op == "ew":
                for ci, ax in ins[1]:
                    args[ci] = block(args[ci], ax)
                vals[pos] = fn(*args, **kwargs)
            elif op == "pad":
                vals[pos] = block(fn(*args, **kwargs), ins[1])
            elif op == "mask":
                a = args[0]
                kax = kwargs["axis"]
                start = jax.lax.axis_index(axn) * a.shape[kax]
                iota = jax.lax.broadcasted_iota(jnp.int32, a.shape, kax) \
                    + start
                vals[pos] = jnp.where(iota < kwargs["n"], a,
                                      jnp.asarray(kwargs["fill"], a.dtype))
            elif op == "resplit":
                # the reshard planner's per-(from, to) move on the local
                # block (core/resharding.py, arXiv:2112.01075) — the
                # collective placed mid-body, not at a flush barrier
                a = args[0]
                j, k = ins[1], ins[2]
                gs = kwargs["gshape"]
                if k is None:
                    # split j → None: gathering IS the semantics here
                    a = jax.lax.all_gather(a, axn, axis=j, tiled=True)
                    if a.shape[j] != gs[j]:
                        a = jax.lax.slice_in_dim(a, 0, gs[j], axis=j)
                else:
                    pad = kwargs["pad"]
                    if pad[k][1]:
                        # local zero-pad of axis k so the tile split (or
                        # the canonical chunking) divides evenly — the
                        # planner's own helper (core/resharding.py)
                        a = resharding._pad_axis(
                            a, k, a.shape[k] + pad[k][1])
                    if j is None:
                        # None → k: every device slices its own canonical
                        # chunk out of the replicated value; ZERO
                        # collectives
                        ck = a.shape[k] // size
                        a = jax.lax.dynamic_slice_in_dim(
                            a, jax.lax.axis_index(axn) * ck, ck, axis=k)
                    else:
                        # j → k: ONE all_to_all (split_axis=k,
                        # concat_axis=j) then cut axis j's tail padding
                        a = jax.lax.all_to_all(
                            a, axn, split_axis=k, concat_axis=j, tiled=True)
                        if a.shape[j] != gs[j]:
                            a = jax.lax.slice_in_dim(a, 0, gs[j], axis=j)
                vals[pos] = a
            else:  # reduce/contract: shard-local partial (or local GEMM on
                # blocks), combined at the phase barrier when a collective
                # kind is attached
                if op == "contract":
                    for ci, ax in ins[2]:
                        args[ci] = block(args[ci], ax)
                vals[pos] = fn(*args, **kwargs)
                if ins[1] is not None:
                    pend[pos] = ins[1]
        emit_all()
        return tuple(vals[i] for i in out_idx)

    return body


def _flush_inline(order, has_reduce: bool = False,
                  has_contract: bool = False,
                  has_resplit: bool = False,
                  is_fallback: bool = False) -> None:
    """Evaluate a short chain op-by-op (children first — ``order`` is
    post-order): each dispatch reuses XLA's per-op executable cache, which
    every other chain in the process shares. Values land on every node, so
    later chains referencing them see leaves. Reduce and mask nodes carry
    global semantics, so the eager dispatch (GSPMD collective placement)
    is exactly the pre-recording behavior; a resplit node dispatches the
    eager PLANNER program (:func:`heat_tpu.core.resharding.reshard` —
    plan-cache counters tick, like pre-recording)."""
    for node in order:
        args = [h.value if isinstance(h, _Node) else h.array
                for h in node.args]
        if node.kind == "resplit":
            from . import resharding

            node.value = resharding.reshard(
                args[0], node.kwargs["gshape"], node.smeta["from"],
                node.smeta["to"], node.comm)
        else:
            node.value = node.fn(*args, **node.kwargs)
        owner = node.owner() if node.owner is not None else None
        if owner is not None:
            owner._set_materialized(node.value)
            if node.kind == "resplit":
                owner._pad_zero_buf = node.value  # planner zero-pads
    m = _metrics()
    m.inc("op_engine.fusion_flushes")
    m.inc("op_engine.fusion_ops", len(order))
    if not is_fallback:
        # error-path fallbacks are counted in fusion_flush_fallbacks;
        # inline_flushes keeps its documented meaning (short chains)
        m.inc("op_engine.fusion_inline_flushes")
    if has_reduce:
        m.inc("op_engine.fusion_reduce_flushes")
    if has_contract:
        m.inc("op_engine.fusion_contract_flushes")
    if has_resplit:
        m.inc("op_engine.fusion_resplit_flushes")
    for node in order:
        node.args = ()
        node.kwargs = {}


# ---------------------------------------------------------------------- #
# differentiable tapes: grads + whole-train-step tracing                 #
# ---------------------------------------------------------------------- #
class _Untraceable(Exception):
    """A step argument/structure trace_step cannot key or trace."""


def _isdnd(x) -> bool:
    from .dndarray import DNDarray

    return isinstance(x, DNDarray)


def _is_arr(x) -> bool:
    return isinstance(x, (jnp.ndarray, np.ndarray, np.generic, float,
                          complex))


def packed_psum(values, axes, qinfo: Optional[dict] = None,
                quant: Optional[Tuple] = None,
                chunks: Optional[Tuple] = None,
                hier: Optional[Tuple] = None,
                replicated: Tuple = ()):
    """ONE flattened all-reduce per dtype over mesh ``axes`` for a list of
    mutually independent shard-local partials — the train-step form of the
    flush body's phase-barrier packing (``_sm_body.emit_all``; the
    generalized-allreduce flattening of arXiv:2004.09362). Call inside a
    ``shard_map`` body; returns the combined values in order. ``axes``
    empty (all trivial mesh axes) returns the inputs untouched — no
    collective is emitted for a 1-device reduction scope. Flatten-concat-
    psum is bitwise-equal to per-value solo psums (probed in PR 4: XLA
    neither tuple-fuses grouped psums itself nor re-associates the
    concatenated reduce), so packing never moves the numerics.

    Under ``HEAT_TPU_QUANT_COLLECTIVES`` the qualifying float payloads
    (additive, at/above the size floor) ride the quantized exchange
    instead — sub-floor values (e.g. the packed scalar loss), integer
    payloads and every value under a fault-injected encode keep the exact
    flattened psum. ``qinfo`` (a dict the caller resets at body start)
    accumulates ``collectives``/``bytes_saved`` at trace time so step
    wrappers can tick the ``op_engine.quant_*`` counters per dispatch.
    ``quant`` pins the configuration to a :func:`quant_key` tuple captured
    when the caller BUILT (and cache-keyed) its program — jax traces
    lazily at first dispatch, and a codec toggle in between must not
    produce a program whose wire format contradicts its cache key; when
    None (direct in-body use) the live configuration is read at trace
    time. ``chunks`` pins the :func:`chunk_key` tuple the same way: under
    ``HEAT_TPU_FUSION_CHUNKS=N`` every payload group at/above the chunk
    floor splits into up to N double-buffered pipeline chunk collectives
    (per-codec block-aligned boundaries — bitwise the unchunked packing);
    the ``fusion.chunk.dispatch`` fault site degrades the call to the
    unchunked emission, counted in ``op_engine.chunk_fallbacks``.

    Under ``HEAT_TPU_HIER`` with declared tiers, every psum payload
    group whose reduction scope splits into a slow (DCN) and a fast
    (ICI) tier — the scope contains the slow-named axis plus fast axes,
    or is one flat axis with the declared ``(d, i)`` factorization —
    rides the HIERARCHICAL exchange instead
    (:func:`_hier_allreduce_parts`): reduce-scatter over the fast tier,
    all-reduce of the 1/pf shard over the slow tier with the DCN codec
    (the quant codec above; chunk bounds pipeline this leg), all-gather
    back with the ICI codec on the fast legs. ``hier`` pins the
    :func:`hier_key` tuple the way ``quant``/``chunks`` do;
    ``replicated`` names fast axes the values are already replicated
    over (DASO's slow-tier capture) — the reduce-scatter then
    degenerates to each device's zero-collective slice of its own tile,
    so only 1/pf of the payload ever crosses the slow tier per device.
    The ``fusion.hier.exchange`` fault site degrades the call to the
    flat emission, counted in ``op_engine.hier_fallbacks``."""
    values = list(values)
    if not axes:
        return values
    axes = tuple(axes)
    groups: Dict[Any, list] = {}
    for i, v in enumerate(values):
        groups.setdefault(jnp.dtype(v.dtype), []).append(i)
    out = list(values)
    codec, floor, block = quant if quant is not None else quant_key()
    cn, cfloor = chunks if chunks is not None else chunk_key()
    hk = hier if hier is not None else hier_key()
    sizes, group_size = (), 1
    quant_ok = codec is not None
    if quant_ok or cn > 1 or hk[0]:
        # lax.psum of a python int is STATIC (the axis-size idiom):
        # sizes are concrete here, usable for the int8/pipeline chunking
        # and the tier split. Only computed when a codec, chunking or
        # the hierarchy is armed — the exact flat path is untouched
        sizes = tuple(jax.lax.psum(1, a) for a in axes)
        for s in sizes:
            group_size *= s
        quant_ok = quant_ok and group_size > 1
    tc = None
    if hk[0] and group_size > 1:
        tc = _tier_scope(axes, sizes, hk, replicated)
    if quant_ok:
        try:
            _faults().check("fusion.quant.encode")
        except Exception:
            _metrics().inc("op_engine.quant_fallbacks")
            quant_ok = False
    chunk_state = {"ok": cn > 1 and group_size > 1, "checked": False}
    hier_state = {"ok": tc is not None, "checked": False}

    def hier_gate():
        """Arm the ``fusion.hier.exchange`` site on the FIRST payload
        group that would actually decompose (matching
        ``_hier_flush_plan``): a call with no qualifying group neither
        fires the site nor ticks the fallback counter. A raise degrades
        the WHOLE call to the flat packed emission."""
        if not hier_state["ok"]:
            return None
        if not hier_state["checked"]:
            hier_state["checked"] = True
            try:
                _faults().check("fusion.hier.exchange")
            except Exception:
                _metrics().inc("op_engine.hier_fallbacks")
                hier_state["ok"] = False
                return None
        return tc

    def chunk_gate(bounds):
        """Arm the ``fusion.chunk.dispatch`` site on the FIRST payload
        group that actually qualifies (once per intended chunk leg,
        matching ``_chunk_flush_plan``): a call whose payloads all stay
        unchunked never fires the site nor ticks the fallback counter.
        A raise degrades the WHOLE call to the unchunked emission."""
        if bounds is None or not chunk_state["ok"]:
            return None
        if not chunk_state["checked"]:
            chunk_state["checked"] = True
            try:
                for _ in range(cn):
                    _faults().check("fusion.chunk.dispatch")
            except Exception:
                _metrics().inc("op_engine.chunk_fallbacks")
                chunk_state["ok"] = False
                return None
        return bounds

    for _dt, members in groups.items():
        dt = jnp.dtype(_dt)
        tcg = None
        if _hier_dtype_ok(dt) and sum(
                _numel(values[i].shape) for i in members) >= hk[3]:
            tcg = hier_gate()
        if tcg is not None:
            # hierarchical decomposition for this payload group (total
            # at/above the hier floor): the SHARED subgroup split —
            # codec-qualifying members carry the DCN codec on the slow
            # leg (and the ICI codec on the fast legs), floor-qualifying
            # f32 members ride bf16 fast legs when only the ICI codec is
            # armed, the rest go exact; PR 10 chunk bounds pipeline the
            # DCN leg of each subgroup
            qset = set()
            if quant_ok and _quant_dtype_ok(dt, codec):
                qset = {i for i in members
                        if _numel(values[i].shape) >= floor}
            nhier = 0
            for sub, sub_codec, sub_ici in _hier_subgroups(
                    members, qset,
                    lambda i: _numel(values[i].shape), dt,
                    codec if qset else None, hk[2], floor):
                if not sub:
                    continue
                numels = [_numel(values[i].shape) for i in sub]
                bounds = None
                if chunk_state["ok"] and sum(numels) >= cfloor:
                    bounds = chunk_gate(_hier_leg_bounds(
                        numels, sub_codec, block, tcg.pf, tcg.ps, cn))
                for i, v in zip(sub, _hier_allreduce_parts(
                        [values[i] for i in sub], tcg, sub_codec, block,
                        sub_ici, bounds=bounds)):
                    out[i] = v
                nhier += 1
                if qinfo is not None:
                    if sub_codec is not None:
                        # only DCN-codec rewrites tick the quant
                        # counters: ici-bf16-only savings belong to the
                        # hier feature, not the quant one (stats
                        # attribution — a dashboard reading
                        # quant_collectives with quant_codec None would
                        # otherwise see phantom rewrites)
                        e, q = _hier_wire_bytes(
                            numels, dt.itemsize, sub_codec, sub_ici,
                            tcg.pf, tcg.ps, block)
                        qinfo["collectives"] = \
                            qinfo.get("collectives", 0) + 1
                        qinfo["bytes_saved"] = (qinfo.get("bytes_saved", 0)
                                                + max(0, e - q))
                    if bounds is not None:
                        qinfo["chunk_collectives"] = \
                            qinfo.get("chunk_collectives", 0) + 1
            if qinfo is not None and nhier:
                qinfo["hier_collectives"] = \
                    qinfo.get("hier_collectives", 0) + 1
            continue
        qm = []
        if quant_ok and _quant_dtype_ok(dt, codec):
            qm = [i for i in members
                  if _numel(values[i].shape) >= floor]
        if qm:
            numels = [_numel(values[i].shape) for i in qm]
            bounds = None
            if chunk_state["ok"] and sum(numels) >= cfloor:
                bounds = chunk_gate(_quant_chunk_bounds(
                    numels, sizes, codec, block, cn))
            for i, v in zip(qm, _quant_allreduce_parts(
                    [values[i] for i in qm], axes, sizes, codec, block,
                    bounds=bounds)):
                out[i] = v
            if qinfo is not None:
                e, q = _quant_wire_bytes(numels, dt.itemsize,
                                         codec, sizes, block)
                qinfo["collectives"] = qinfo.get("collectives", 0) + 1
                qinfo["bytes_saved"] = (qinfo.get("bytes_saved", 0)
                                        + max(0, e - q))
                if bounds is not None:
                    qinfo["chunk_collectives"] = \
                        qinfo.get("chunk_collectives", 0) + 1
            qset = set(qm)
            members = [i for i in members if i not in qset]
            if not members:
                continue
        total = sum(_numel(values[i].shape) for i in members)
        bounds = (chunk_gate(_chunk_bounds(total, cn, group_size))
                  if chunk_state["ok"] and total >= cfloor else None)
        if bounds is None and len(members) == 1:
            i = members[0]
            out[i] = jax.lax.psum(values[i], axes)
            continue
        packed = jnp.concatenate([values[i].reshape(-1) for i in members])
        combined = (jax.lax.psum(packed, axes) if bounds is None
                    else _chunked_exact(packed, axes, jax.lax.psum,
                                        bounds))
        if bounds is not None and qinfo is not None:
            qinfo["chunk_collectives"] = \
                qinfo.get("chunk_collectives", 0) + 1
        off = 0
        for i in members:
            n = 1
            for s in values[i].shape:
                n *= s
            out[i] = combined[off:off + n].reshape(values[i].shape)
            off += n
    return out


def _dnd_meta(x):
    """(rebuild metadata, signature entry) for one DNDarray leaf. The
    signature entry is hashable and pins everything program identity
    depends on; the metadata carries the live python objects (heat dtype,
    device, comm) the rebuild needs."""
    meta = ("dnd", x.gshape, x.dtype, x.split, x.device, x.comm)
    sig = ("dnd", tuple(x.gshape), str(jnp.dtype(x.dtype.jax_type())),
           x.split, x.comm.cache_key, str(x.device))
    return meta, sig


def _rebuild_dnd(meta, array):
    from .dndarray import DNDarray

    _, gshape, dtype, split, device, comm = meta
    return DNDarray(array, gshape, dtype, split, device, comm)


def value_and_grad(fun, argnums=0, has_aux=False):
    """``jax.value_and_grad`` over functions of ``DNDarray`` pytrees — the
    tape's grad-capable form.

    ``fun`` must return a scalar (0-d ``DNDarray`` or jax scalar; with
    ``has_aux`` a ``(scalar, aux)`` pair). The wrapper rebuilds the
    differentiated arguments' ``DNDarray`` leaves around jax's abstract
    leaves and traces ``fun`` through the op engine's EAGER semantics
    (recording declines on tracers by design, so the traced jaxpr is
    exactly the eager dispatch sequence); gradients come back as
    ``DNDarray`` leaves mirroring each parameter's layout. No loss
    cotangent ever flows into split-axis padding (every padding-crossing
    read is masked by the op engine's neutral-element discipline), so
    padded grad positions are don't-care — exact zeros for canonically
    zero-padded parameters (factories, planner outputs); grads are NOT
    certified ``pad_is_zero``, so consumers mask as usual.

    Called EAGERLY this traces per invocation (the torch-autograd cost
    shape); inside :func:`trace_step` the whole thing lowers into the one
    cached step executable — that composition is the supported hot path.
    The loss is returned as a 0-d ``DNDarray``; ``aux`` may contain
    ``DNDarray`` leaves (rebuilt on the way out).
    """
    multi = isinstance(argnums, (tuple, list))
    idxs = tuple(argnums) if multi else (int(argnums),)

    def wrapped(*args, **kwargs):
        from . import types
        from .communication import sanitize_comm
        from .dndarray import DNDarray

        per_arg = [jax.tree_util.tree_flatten(args[i], is_leaf=_isdnd)
                   for i in idxs]
        metas, phys, spans = [], [], []
        for leaves, _td in per_arg:
            start = len(phys)
            for leaf in leaves:
                if _isdnd(leaf):
                    m, _s = _dnd_meta(leaf)
                    metas.append(m)
                    phys.append(leaf.larray)
                else:
                    metas.append(("raw",))
                    phys.append(jnp.asarray(leaf))
            spans.append((start, len(phys)))
        aux_meta = []

        def pure(*leaf_arrays):
            rebuilt = [_rebuild_dnd(m, a) if m[0] == "dnd" else a
                       for m, a in zip(metas, leaf_arrays)]
            args2 = list(args)
            for j, i in enumerate(idxs):
                lo, hi = spans[j]
                args2[i] = jax.tree_util.tree_unflatten(
                    per_arg[j][1], rebuilt[lo:hi])
            out = fun(*args2, **kwargs)
            if has_aux:
                out, aux = out
                aflat, atree = jax.tree_util.tree_flatten(aux,
                                                          is_leaf=_isdnd)
                del aux_meta[:]
                aux_meta.append(atree)
                aux_arrs = []
                for a in aflat:
                    if _isdnd(a):
                        aux_meta.append(_dnd_meta(a)[0])
                        aux_arrs.append(a.larray)
                    else:
                        aux_meta.append(("raw",))
                        aux_arrs.append(a)
            val = out.larray if _isdnd(out) else jnp.asarray(out)
            val = val.reshape(())
            return (val, tuple(aux_arrs)) if has_aux else val

        vg = jax.value_and_grad(pure, argnums=tuple(range(len(phys))),
                                has_aux=has_aux)
        if has_aux:
            (val, aux_arrs), gphys = vg(*phys)
        else:
            val, gphys = vg(*phys)
        gleaves = [_rebuild_dnd(m, g) if m[0] == "dnd" else g
                   for m, g in zip(metas, gphys)]
        grads = tuple(
            jax.tree_util.tree_unflatten(per_arg[j][1],
                                         gleaves[spans[j][0]:spans[j][1]])
            for j in range(len(idxs)))
        if not multi:
            grads = grads[0]
        first_dnd = next((m for m in metas if m[0] == "dnd"), None)
        comm = first_dnd[5] if first_dnd is not None else sanitize_comm(None)
        device = first_dnd[4] if first_dnd is not None else None
        from .devices import sanitize_device

        vout = DNDarray(val, (), types.canonical_heat_type(val.dtype),
                        None, sanitize_device(device), comm)
        if has_aux:
            atree, ams = aux_meta[0], aux_meta[1:]
            aleaves = [_rebuild_dnd(m, a) if m[0] == "dnd" else a
                       for m, a in zip(ams, aux_arrs)]
            return (vout, jax.tree_util.tree_unflatten(atree, aleaves)), \
                grads
        return vout, grads

    return wrapped


def grad(fun, argnums=0, has_aux=False):
    """:func:`value_and_grad` without the value."""
    vg = value_and_grad(fun, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        out, grads = vg(*args, **kwargs)
        return (grads, out[1]) if has_aux else grads

    return wrapped


class _StepRecord:
    """One compiled traced step: the jitted pure function plus the output
    rebuild metadata captured during its first trace. ``delete_slots``
    (async siblings only) are the dynamic-argument slots whose buffers
    the wrapper invalidates by hand after each dispatch — the
    donation-semantics half of the ``block=False`` contract."""

    __slots__ = ("jitted", "out_meta", "delete_slots")

    def __init__(self, jitted, delete_slots=()):
        self.jitted = jitted
        self.out_meta = None
        self.delete_slots = tuple(delete_slots)


# outstanding async trace_step results, for the no-argument sync():
# device execution is FIFO per dispatch order, so a bounded recent window
# is enough — blocking the newest results implies the older ones
# finished. The window is deliberately SMALL: each entry pins its step's
# output buffers (a full parameter tree for a train step) until sync()
# or eviction, and 8 steps of lookback already covers every in-flight
# execution a double-buffered device queue can hold
_ASYNC_LOCK = threading.Lock()
_ASYNC_PENDING: list = []
_ASYNC_PENDING_CAP = 8


def _note_async(results) -> None:
    with _ASYNC_LOCK:
        _ASYNC_PENDING.append(tuple(results))
        if len(_ASYNC_PENDING) > _ASYNC_PENDING_CAP:
            del _ASYNC_PENDING[:-_ASYNC_PENDING_CAP]


def sync(*trees) -> None:
    """The explicit host barrier of the async-dispatch path. With
    arguments, block until every ``DNDarray`` / jax-array leaf of the
    given pytrees is computed; with none, block on all outstanding
    ``block=False`` :func:`trace_step` results (then forget them). Call
    it before reading wall-clock time, checkpointing to host, or exiting
    a training loop that queued steps asynchronously."""
    if trees:
        for t in trees:
            for leaf in jax.tree_util.tree_leaves(t, is_leaf=_isdnd):
                if _isdnd(leaf):
                    jax.block_until_ready(leaf.larray)
                elif isinstance(leaf, jnp.ndarray):
                    jax.block_until_ready(leaf)
        return
    with _ASYNC_LOCK:
        pending = list(_ASYNC_PENDING)
        del _ASYNC_PENDING[:]
    for res in pending:
        for a in res:
            if not getattr(a, "is_deleted", lambda: False)():
                jax.block_until_ready(a)


class _TracedStep:
    """The callable :func:`trace_step` returns. Caches one compiled
    program per structural signature of the arguments in the fusion
    :func:`program_cache` (steady-state repeat calls are a key lookup and
    one donated program dispatch — zero host round-trips)."""

    def __init__(self, fn, donate_argnums=(), block=True):
        self.fn = fn
        self.donate_argnums = tuple(sorted(set(int(i)
                                               for i in donate_argnums)))
        # block=False: the async-dispatch sibling. XLA donation of an
        # in-flight buffer BLOCKS the dispatching thread until the
        # producer completes (probed on this jax — chained donated
        # dispatches serialize the host), so the async program compiles
        # WITHOUT donate_argnums and the wrapper delete()s the donated
        # input buffers after dispatch instead: invalidation semantics
        # preserved, dispatch queue asynchronous. fusion.sync() is the
        # explicit barrier.
        self.block = bool(block)
        # signatures whose first call failed to trace/compile: those
        # stay eager. PER-SIGNATURE, not per-fn — one oversized batch
        # failing to compile must not un-fuse the signatures already
        # running fused (each new signature pays at most one failed
        # trace before settling eager)
        self._eager_keys = set()

    def __call__(self, *args, **kwargs):
        if not (_ENABLED and _STEP):
            return self.fn(*args, **kwargs)
        try:
            flat, treedef = jax.tree_util.tree_flatten((args, kwargs),
                                                       is_leaf=_isdnd)
            metas, sig, phys = self._classify(flat)
        except _Untraceable:
            _metrics().inc("op_engine.fusion_step_fallbacks")
            return self.fn(*args, **kwargs)
        # quant/chunk/hier keys ride along: a step body may call
        # packed_psum directly (trace-time config read), and a config
        # toggle must compile a SIBLING instead of reusing a program
        # traced under the other wire format / leg structure — the same
        # discipline as the flush key's qtag/ctag/htag
        key = ("step", self.fn, treedef, tuple(sig), self.donate_argnums,
               self.block, quant_key(), chunk_key(), hier_key())
        if key in self._eager_keys:
            _metrics().inc("op_engine.fusion_step_fallbacks")
            return self.fn(*args, **kwargs)
        record = program_cache().get_custom(
            key, lambda: self._build(args, treedef, metas))
        primed = record.out_meta is not None  # this program ran before
        try:
            _faults().check("fusion.step.dispatch" if primed
                            else "fusion.step.trace")
            results = record.jitted(*phys)
        except Exception:
            if primed:
                # a previously-successful program failed at DISPATCH
                # (donated tree reused, device error): that is a real
                # runtime error — surface it, don't silently degrade
                # every later step to the eager path
                raise
            # first-call trace/compile failure: the body is not
            # traceable at this signature. It may have half-run with
            # tracers — step bodies must be functional (the standard jax
            # contract) — so the eager re-run below is exact; this
            # signature stays eager
            self._eager_keys.add(key)
            _metrics().inc("op_engine.fusion_step_fallbacks")
            return self.fn(*args, **kwargs)
        if not self.block:
            # the async sibling's manual donation: invalidate the donated
            # input buffers now that the (non-donating) dispatch holds its
            # own references — use-after raises exactly like XLA donation.
            # Passthrough outputs are fresh buffers on this backend
            # (probed), but an identity guard keeps a future aliasing
            # backend from deleting its own result
            out_ids = {id(r) for r in results}
            for slot in record.delete_slots:
                a = phys[slot]
                if id(a) not in out_ids and not a.is_deleted():
                    a.delete()
            _note_async(results)
        _metrics().inc("op_engine.fusion_step_flushes")
        # out_meta is always set by the time jitted() returns: compiling
        # needs the jaxpr, the jaxpr needs pure() to complete, and pure()
        # writes the metadata before returning — in every thread
        ometa, otree = record.out_meta
        it = iter(results)
        oleaves = []
        for m in ometa:
            if m[0] == "static":
                oleaves.append(m[1])
            elif m[0] == "dnd":
                oleaves.append(_rebuild_dnd(m, next(it)))
            else:
                oleaves.append(next(it))
        return jax.tree_util.tree_unflatten(otree, oleaves)

    # -------------------------------------------------------------- #
    def _classify(self, flat):
        """Per-leaf (rebuild meta, hashable signature entry, program
        argument). DNDarray leaves flush any pending tape here (the step
        boundary) and enter as their physical arrays; raw arrays and
        python floats enter as (weak-typed) arguments so one program
        serves every value; ints/bools/strings are STATIC — they key the
        program (shape-like and control-flow-like roles)."""
        metas, sig, phys = [], [], []
        for leaf in flat:
            if _isdnd(leaf):
                m, s = _dnd_meta(leaf)
                metas.append(m)
                sig.append(s)
                phys.append(leaf.larray)
            elif isinstance(leaf, jax.core.Tracer):
                raise _Untraceable("tracer argument")  # nested-trace call
            elif _is_arr(leaf):
                a = jnp.asarray(leaf)
                metas.append(("raw",))
                sig.append(("arr", tuple(a.shape), str(a.dtype),
                            bool(a.aval.weak_type)))
                phys.append(a)
            else:
                k = _key_val(leaf)
                if k is None:
                    raise _Untraceable("unhashable static argument")
                metas.append(("static", leaf))
                sig.append(("static", k))
        return metas, tuple(sig), phys

    def _build(self, args, treedef, metas):
        record = [None]  # box: pure() runs inside the jit trace

        def pure(*leaf_arrays):
            it = iter(leaf_arrays)
            rebuilt = []
            for m in metas:
                if m[0] == "static":
                    rebuilt.append(m[1])
                elif m[0] == "dnd":
                    rebuilt.append(_rebuild_dnd(m, next(it)))
                else:
                    rebuilt.append(next(it))
            args2, kwargs2 = jax.tree_util.tree_unflatten(treedef, rebuilt)
            out = self.fn(*args2, **kwargs2)
            oflat, otree = jax.tree_util.tree_flatten(out, is_leaf=_isdnd)
            ometa, oarrs = [], []
            for o in oflat:
                if _isdnd(o):
                    ometa.append(_dnd_meta(o)[0])
                    oarrs.append(o.larray)
                elif isinstance(o, (jnp.ndarray, np.ndarray, np.generic,
                                    jax.core.Tracer)):
                    ometa.append(("raw",))
                    oarrs.append(jnp.asarray(o))
                else:
                    # host-static output (int epoch counters, flags):
                    # baked into the record; data-dependent host values
                    # cannot reach here (float(tracer) raises upstream)
                    ometa.append(("static", o))
            record[0].out_meta = (tuple(ometa), otree)
            return tuple(oarrs)

        donate = self._donate_slots(args, metas)
        if self.block:
            record[0] = _StepRecord(jax.jit(pure, donate_argnums=donate))
        else:
            # async sibling: no XLA donation (donating an in-flight
            # buffer blocks the dispatching thread on this jax) — the
            # wrapper invalidates these slots by hand after dispatch
            record[0] = _StepRecord(jax.jit(pure), delete_slots=donate)
        return record[0]

    def _donate_slots(self, args, metas):
        """Flat dynamic-argument slots of the donated step arguments.
        Donated ``DNDarray`` buffers are INVALIDATED by the call — the
        functional-update idiom (``params, ... = step(params, ...)``)
        rebinds them anyway, and XLA reuses the memory in place."""
        if not self.donate_argnums:
            return ()
        spans, pos = [], 0
        for a in args:
            n = len(jax.tree_util.tree_flatten(a, is_leaf=_isdnd)[0])
            spans.append((pos, pos + n))
            pos += n
        wanted = set()
        for i in self.donate_argnums:
            if i < len(spans):
                wanted.update(range(*spans[i]))
        out, dyn = [], 0
        for slot, m in enumerate(metas):
            if m[0] == "static":
                continue
            if slot in wanted:
                out.append(dyn)
            dyn += 1
        return tuple(out)


def trace_step(fn, donate_argnums=(), block=True):
    """Compile a whole (functional) train step over ``DNDarray`` / jax
    pytrees as ONE cached executable — loss, backward and optimizer
    update in a single program with donated state.

    ``block=False`` selects ASYNC dispatch: repeat calls return
    device-resident results without a host sync, so back-to-back train
    steps queue on the device and the host never sits between steps (XLA
    donation of an in-flight buffer blocks the dispatching thread on
    this jax — the async sibling program skips XLA donation and
    invalidates the donated input buffers by hand instead, preserving
    the use-after-donation contract). Read results through
    :func:`sync` (or any materialization) when you actually need the
    values; queued steps are bitwise the synchronous ones.

    ``fn`` must be functional: pytrees in, pytrees out, no host-side
    value inspection (``float()``, ``.numpy()``, value-dependent
    branches). The first call per argument signature traces ``fn`` on
    abstract leaves — recorded ops decline tracers, so the body runs the
    op engine's eager semantics symbolically — and compiles the jaxpr
    once; repeat calls are a cache hit plus one program dispatch with
    zero host round-trips (``op_engine.fusion_step_flushes`` counts
    them). Non-traceable bodies fall back to the eager path — per
    argument signature, so one failing signature never un-fuses the
    others (``op_engine.fusion_step_fallbacks``; the semantics are
    identical, the fusion is lost). ``donate_argnums`` marks positional
    arguments
    (params, optimizer state) whose buffers XLA may update in place —
    their input ``DNDarray``\\ s are invalidated by the call.

    Escape hatch: ``HEAT_TPU_FUSION_STEP=0`` (or
    :func:`step_override`) runs every wrapped step eagerly.
    """
    return _TracedStep(fn, donate_argnums, block=block)


# ---------------------------------------------------------------------- #
# tape-compiled analytics fit steps                                      #
# ---------------------------------------------------------------------- #
def fit_step_call(key, build, args, eager):
    """Dispatch ONE compiled analytics fit/predict step through the
    fusion program cache — the estimator-family sibling of
    :func:`trace_step` (KMeans/KMedians/KMedoids Lloyd iterations, the
    Lanczos inner loop, Lasso coordinate sweeps, the KNN ring and
    GaussianNB likelihood programs ride this).

    ``key`` is the caller's structural signature (shapes, dtypes, the
    communicator cache key); the full program key appends the captured
    :func:`quant_key`/:func:`chunk_key`/:func:`hier_key` tuples, so a
    wire-codec toggle compiles a SIBLING program instead of reusing one
    traced under the other wire format (the PR 9 deferred-trace
    discipline). ``build(qk, ck, hk)`` returns the compiled callable and
    must PIN the captured tuples into any :func:`packed_psum` it traces.
    ``eager`` replays the same mathematics per-op (unjitted, GSPMD
    collectives) — the degrade path of the ``fit.step.dispatch`` fault
    site and of real compile/dispatch failures, counted in
    ``op_engine.fit_step_fallbacks``; a failure after a donated input
    buffer was already invalidated re-raises (replaying from dead
    buffers would be the PR 8 flush-fallback hazard). Successful
    dispatches count ``op_engine.fit_step_flushes``.

    With the engine off (``HEAT_TPU_FUSION_FIT=0`` or the master
    switch), callers run their legacy step programs and never reach
    here — see :func:`fit_enabled`.
    """
    qk, ck, hk = quant_key(), chunk_key(), hier_key()
    full_key = ("fit",) + tuple(key) + (qk, ck, hk)
    try:
        prog = program_cache().get_custom(
            full_key, lambda: build(qk, ck, hk))
        _faults().check("fit.step.dispatch")
        out = prog(*args)
    except Exception:
        for a in args:
            if getattr(a, "is_deleted", lambda: False)():
                raise  # donated buffer already invalidated — no replay
        _metrics().inc("op_engine.fit_step_fallbacks")
        return eager(*args)
    _metrics().inc("op_engine.fit_step_flushes")
    return out


# ---------------------------------------------------------------------- #
# observability                                                          #
# ---------------------------------------------------------------------- #
def stats() -> dict:
    """Fusion engine snapshot (folded into ``ht.runtime_stats()``)."""
    c = _metrics().counters()
    flushes = int(c.get("op_engine.fusion_flushes", 0))
    ops = int(c.get("op_engine.fusion_ops", 0))
    return {
        "enabled": _ENABLED,
        "reduce_enabled": _REDUCE,
        "contract_enabled": _CONTRACT,
        "resplit_enabled": _RESPLIT,
        "step_enabled": _STEP,
        "step_flushes": int(c.get("op_engine.fusion_step_flushes", 0)),
        "step_fallbacks": int(c.get("op_engine.fusion_step_fallbacks", 0)),
        "fit_enabled": _FIT,
        "fit_step_flushes": int(c.get("op_engine.fit_step_flushes", 0)),
        "fit_step_fallbacks": int(
            c.get("op_engine.fit_step_fallbacks", 0)),
        "flushes": flushes,
        "flush_fallbacks": int(
            c.get("op_engine.fusion_flush_fallbacks", 0)),
        "inline_flushes": int(c.get("op_engine.fusion_inline_flushes", 0)),
        "reduce_flushes": int(c.get("op_engine.fusion_reduce_flushes", 0)),
        "contract_flushes": int(
            c.get("op_engine.fusion_contract_flushes", 0)),
        "resplit_flushes": int(
            c.get("op_engine.fusion_resplit_flushes", 0)),
        "resplit_nodes": int(c.get("op_engine.fusion_resplit_nodes", 0)),
        "resplit_fallbacks": int(
            c.get("op_engine.fusion_resplit_fallbacks", 0)),
        "fused_ops": ops,
        "ops_per_flush": round(ops / flushes, 3) if flushes else 0.0,
        "max_ops": _MAX_OPS,
        "min_ops": _MIN_OPS,
        "quant_codec": _QUANT,
        "quant_min_numel": _QUANT_FLOOR,
        "quant_collectives": int(c.get("op_engine.quant_collectives", 0)),
        "quant_bytes_saved": int(c.get("op_engine.quant_bytes_saved", 0)),
        "quant_fallbacks": int(c.get("op_engine.quant_fallbacks", 0)),
        "chunk_count": _CHUNKS,
        "chunk_min_numel": _CHUNK_FLOOR,
        "chunk_collectives": int(c.get("op_engine.chunk_collectives", 0)),
        "chunk_fallbacks": int(c.get("op_engine.chunk_fallbacks", 0)),
        "hier_enabled": _HIER,
        "mesh_tiers": list(_TIERS) if _TIERS is not None else None,
        "hier_ici_codec": _HIER_ICI,
        "hier_collectives": int(c.get("op_engine.hier_collectives", 0)),
        "hier_fallbacks": int(c.get("op_engine.hier_fallbacks", 0)),
        "program_cache": program_cache().stats(),
    }


def reset() -> None:
    """Drop cached programs, memoized avals and the captured HLO (tests)."""
    global _last_hlo
    program_cache().reset()
    _AVAL_CACHE.clear()
    _SCALAR_CACHE.clear()
    _last_hlo = None
