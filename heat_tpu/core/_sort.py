"""Distributed sort over the device mesh: a block merge-split network.

TPU-native counterpart of the reference's parallel sample-sort
(``heat/core/manipulations.py:2263``: local sort → pivot exchange →
Alltoallv rebucket → local merge). A literal sample-sort cannot compile
under XLA: the Alltoallv bucket sizes are data-dependent, and XLA requires
static shapes. The static-shape equivalent is a **block merge-split
network**: every device keeps exactly ``c`` elements at every step, and a
comparator ``(i, j)`` of a sorting network becomes "merge the two sorted
blocks; ``i`` keeps the lower half, ``j`` the upper". By the 0-1 principle
this turns ANY sorting network on ``p`` inputs into a sorter of ``p``
pre-sorted blocks (Knuth TAOCP 5.3.4). We use Batcher's odd-even mergesort
network: ``O(log^2 p)`` rounds, each a disjoint set of pairwise
``ppermute`` exchanges riding ICI — **no all-gather of the sort axis
anywhere**, O(c) memory per device.

Arbitrary (non-power-of-two) ``p``: the network is built for the next power
of two and comparators touching indices ``>= p`` are dropped. Every Batcher
odd-even comparator is ascending (min to the lower index), so virtual
blocks — conceptually filled with the ascending sentinel — never leave the
top positions and every dropped comparator is a no-op on real data (the
mirror argument covers descending).

Sentinel discipline: ascending sorts fill the canonical layout's padding
with the dtype's maximum, so after the global sort all padding lands in the
trailing physical positions — exactly the canonical padded layout again.
Descending mirrors with the minimum.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from ._compat import shard_map

__all__ = ["batcher_rounds", "distributed_sort_fn", "distributed_flat_sort_fn"]

# jitted sort programs keyed by (shape, dtype, axis, n, descending, mesh)
_SORT_CACHE: dict = {}


def batcher_rounds(p: int) -> List[List[Tuple[int, int]]]:
    """Rounds of disjoint ascending comparator pairs ``(low, high)`` of
    Batcher's odd-even mergesort on ``p`` inputs.

    Built for the next power of two ``P >= p``; comparators touching an
    index ``>= p`` are dropped (no-ops on virtual sentinel blocks, see
    module docstring). Pairs within one round are disjoint, so each round
    is a single ``ppermute``.
    """
    P = 1
    while P < p:
        P *= 2
    rounds: List[List[Tuple[int, int]]] = []
    ph = 1
    while ph < P:
        k = ph
        while k >= 1:
            pairs = []
            j = k % ph
            while j + k < P:
                for i in range(k):
                    a, b = i + j, i + j + k
                    if b < P and (a // (ph * 2)) == (b // (ph * 2)) and b < p:
                        pairs.append((a, b))
                j += 2 * k
            if pairs:
                rounds.append(pairs)
            k //= 2
        ph *= 2
    return rounds


def _sentinel(jdt, descending: bool):
    """Value that sorts to the END of the requested order for dtype ``jdt``."""
    if jnp.issubdtype(jdt, jnp.floating):
        return jnp.asarray(-jnp.inf if descending else jnp.inf, jdt)
    if jdt == jnp.bool_:
        return jnp.asarray(not descending, jdt)
    info = jnp.iinfo(jdt)
    return jnp.asarray(info.min if descending else info.max, jdt)


def _float_key_dtype(jdt):
    return jnp.int64 if jnp.dtype(jdt).itemsize == 8 else jnp.int32


def _float_sort_key(x):
    """Monotone integer encoding of a float array's total order.

    Needed because value sentinels cannot bound NaN: under jax's sort NaNs
    order after +inf, so an inf-filled padding would slip *inside* the valid
    region whenever the data contains NaNs (round-2 review finding —
    fabricated infs, dropped NaNs, out-of-range indices). The IEEE bit trick
    gives a total order ``-inf < … < +inf < NaN`` (NaNs canonicalized to the
    positive quiet pattern first), all strictly below the integer maximum —
    which is therefore a safe padding key."""
    if x.dtype in (jnp.bfloat16, jnp.float16):
        x = x.astype(jnp.float32)  # exact, monotone; bitcast needs 32 bits
    idt = _float_key_dtype(x.dtype)
    x = jnp.where(jnp.isnan(x), jnp.asarray(jnp.nan, x.dtype), x)
    b = jax.lax.bitcast_convert_type(x, idt)
    imax = jnp.asarray(jnp.iinfo(idt).max, idt)
    # b >= 0 (positive floats incl. canonical NaN): key = b, ascending.
    # b < 0 (negative floats): imax - b wraps to a strictly increasing map
    # onto [imin, -1], so every negative float keys below every positive one.
    return jnp.where(b >= 0, b, imax - b)


def _index_dtype():
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


def _merge_split_network(key_block, payload_blocks, rounds, role_tables, c,
                         axis_name, merge, block_axis):
    """Shared Batcher merge-split round loop, inside shard_map.

    ``merge(key, payloads) -> (key, payloads)`` locally sorts one (possibly
    doubled) block along ``block_axis`` (-1 for scalar-key sorts, 0 for row
    sorts). Each round ppermutes blocks between comparator pairs, merges,
    and keeps the low/high half by role. Both sides of a pair MUST merge the
    identical sequence (low-index block first): under tied keys a stable
    sort of [own, recv] and [recv, own] disagree, and the kept halves would
    no longer be complementary — tied payloads would be duplicated/dropped.
    """
    def halves(x):
        if block_axis == 0:
            return x[:c], x[c:]
        return x[..., :c], x[..., c:]

    xl, pls = merge(key_block, tuple(payload_blocks))
    me = jax.lax.axis_index(axis_name)
    for pairs, role in zip(rounds, role_tables):
        perm = [(a, b) for a, b in pairs] + [(b, a) for a, b in pairs]
        rx = jax.lax.ppermute(xl, axis_name, perm=perm)
        rpls = tuple(jax.lax.ppermute(pl, axis_name, perm=perm) for pl in pls)
        myrole = jnp.asarray(role)[me]

        def ordered_concat(own, recv):
            first = jnp.where(myrole == 2, recv, own)
            second = jnp.where(myrole == 2, own, recv)
            return jnp.concatenate([first, second], axis=block_axis)

        both_v, both_p = merge(
            ordered_concat(xl, rx),
            tuple(ordered_concat(pl, rpl) for pl, rpl in zip(pls, rpls)),
        )

        def pick(low, high, keep):
            return jnp.where(myrole == 1, low,
                             jnp.where(myrole == 2, high, keep))

        xl = pick(*halves(both_v), xl)
        pls = tuple(pick(*halves(bp), pl) for bp, pl in zip(both_p, pls))
    return xl, pls


def _network_sort(key_block, payload_blocks, rounds, role_tables, c, descending,
                  axis_name, tie_block=None):
    """Merge-split network sort on per-device blocks, inside shard_map.

    ``key_block``: (..., c) sort keys, last axis is the (local chunk of the)
    sort axis. ``payload_blocks``: tuple of same-shaped arrays co-sorted with
    the keys. ``tie_block``: optional secondary key sorted ASCENDING within
    equal primary keys — used by exact dtypes to keep padding rows (tie=1)
    after real rows (tie=0) when the data itself contains the sentinel value,
    so returned indices never point at padding. Returns (sorted key block,
    tuple of sorted payload blocks).
    """
    has_tie = tie_block is not None

    def _merge(vals, payloads):
        if has_tie:
            # lexicographic (primary, tie): stable-sort by the tie first,
            # then stable-sort by the primary, and compose the permutations
            o2 = jnp.argsort(payloads[0], axis=-1, stable=True)
            v2 = jnp.take_along_axis(vals, o2, axis=-1)
            o1 = jnp.argsort(v2, axis=-1, descending=descending, stable=True)
            order = jnp.take_along_axis(o2, o1, axis=-1)
        else:
            order = jnp.argsort(vals, axis=-1, descending=descending, stable=True)
        return (
            jnp.take_along_axis(vals, order, axis=-1),
            tuple(jnp.take_along_axis(pl, order, axis=-1) for pl in payloads),
        )

    payload_blocks = ((tie_block,) if has_tie else ()) + tuple(payload_blocks)
    xl, pls = _merge_split_network(
        key_block, payload_blocks, rounds, role_tables, c, axis_name, _merge,
        block_axis=-1)
    return xl, (pls[1:] if has_tie else pls)


def _role_tables(rounds, p):
    """Per-round device roles: 0 = bystander, 1 = keeps low, 2 = keeps high."""
    tables = []
    for pairs in rounds:
        role = np.zeros(p, np.int32)
        for a, b in pairs:
            role[a], role[b] = 1, 2
        tables.append(role)
    return tables


def distributed_sort_fn(phys_shape, jdt, axis: int, n: int, descending: bool, comm):
    """Jitted ``physical -> (sorted_physical, global_indices_physical)``.

    ``physical`` is the canonical padded global array split at ``axis``
    (padding content is ignored: sentinels are applied inside). The returned
    values land back in canonical layout (valid data first, padding last);
    indices are global positions along ``axis`` into the original array.
    """
    key = ("dsort", tuple(phys_shape), str(jdt), axis, n, descending, comm.cache_key)
    fn = _SORT_CACHE.get(key)
    if fn is not None:
        return fn

    p = comm.size
    c = phys_shape[axis] // p
    rounds = batcher_rounds(p)
    roles = _role_tables(rounds, p)
    spec = comm.spec(len(phys_shape), axis)
    idt = _index_dtype()
    floating = jnp.issubdtype(jdt, jnp.floating)

    def body(x):
        me = jax.lax.axis_index(comm.axis_name)
        xl = jnp.moveaxis(x, axis, -1)
        gpos = me * c + jnp.arange(c, dtype=idt)  # global positions, this block
        if floating:
            # NaN-safe total order: sort integer keys carrying the values as
            # payload; the padding key strictly bounds every data key
            kdt = _float_key_dtype(jnp.float32 if jnp.dtype(jdt).itemsize < 4
                                   else jdt)
            info = jnp.iinfo(kdt)
            pad_key = jnp.asarray(info.min if descending else info.max, kdt)
            keys = jnp.where(gpos < n, _float_sort_key(xl), pad_key)
            _, (xl, gi) = _network_sort(
                keys, (xl, jnp.broadcast_to(gpos, xl.shape)), rounds, roles,
                c, descending, comm.axis_name)
        else:
            # the sentinel is a representable value for exact dtypes, so a
            # padding tie-break key keeps real sentinel-valued rows (tie=0)
            # ahead of padding rows (tie=1) — indices stay < n (round-2
            # advisor finding)
            xl = jnp.where(gpos < n, xl, _sentinel(jdt, descending))
            tie = jnp.broadcast_to((gpos >= n).astype(jnp.int8), xl.shape)
            xl, (gi,) = _network_sort(
                xl, (jnp.broadcast_to(gpos, xl.shape),), rounds, roles, c,
                descending, comm.axis_name, tie_block=tie)
        return jnp.moveaxis(xl, -1, axis), jnp.moveaxis(gi, -1, axis)

    fn = jax.jit(
        shard_map(body, mesh=comm.mesh, in_specs=spec, out_specs=(spec, spec),
                  check_vma=False)
    )
    _SORT_CACHE[key] = fn
    return fn


def distributed_flat_sort_fn(phys_shape, jdt, split: int, comm):
    """Jitted flatten-and-sort of a sharded N-D array as a 1-D bag.

    Each device flattens its own shard locally (row-major shard order, NOT
    the global logical order — callers must only rely on the sorted
    multiset, i.e. order statistics) and the network sorts the resulting
    ``p * prod(shard_shape)`` 1-D array. Validity is the caller's job:
    pre-fill padding with a sentinel (``DNDarray.filled``), after which the
    valid elements occupy the first ``n`` global positions of the result.
    """
    key = ("dflat", tuple(phys_shape), str(jdt), split, comm.cache_key)
    fn = _SORT_CACHE.get(key)
    if fn is not None:
        return fn

    p = comm.size
    rounds = batcher_rounds(p)
    roles = _role_tables(rounds, p)
    local = int(np.prod([s // p if i == split else s
                         for i, s in enumerate(phys_shape)], dtype=np.int64))

    def body(xs):
        flat = xs.reshape(-1)
        out, _ = _network_sort(flat, (), rounds, roles, local, False,
                               comm.axis_name)
        return out

    fn = jax.jit(
        shard_map(body, mesh=comm.mesh,
                  in_specs=comm.spec(len(phys_shape), split),
                  out_specs=comm.spec(1, 0), check_vma=False)
    )
    _SORT_CACHE[key] = fn
    return fn


