"""Trigonometric and hyperbolic operations (reference ``heat/core/trigonometrics.py:46-500``)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = [
    "acos",
    "acosh",
    "arccos",
    "arccosh",
    "arcsin",
    "arcsinh",
    "arctan",
    "arctan2",
    "arctanh",
    "asin",
    "asinh",
    "atan",
    "atan2",
    "atanh",
    "cos",
    "cosh",
    "deg2rad",
    "degrees",
    "hypot",
    "rad2deg",
    "radians",
    "sin",
    "sinc",
    "sinh",
    "tan",
    "tanh",
]


def arccos(x: DNDarray, out=None) -> DNDarray:
    """Element-wise inverse cosine (reference ``trigonometrics.py:46``)."""
    return _operations._local_op(jnp.arccos, x, out)


acos = arccos


def arccosh(x: DNDarray, out=None) -> DNDarray:
    """Element-wise inverse hyperbolic cosine (reference ``trigonometrics.py:78``)."""
    return _operations._local_op(jnp.arccosh, x, out)


acosh = arccosh


def arcsin(x: DNDarray, out=None) -> DNDarray:
    """Element-wise inverse sine (reference ``trigonometrics.py:104``)."""
    return _operations._local_op(jnp.arcsin, x, out)


asin = arcsin


def arcsinh(x: DNDarray, out=None) -> DNDarray:
    """Element-wise inverse hyperbolic sine (reference ``trigonometrics.py:136``)."""
    return _operations._local_op(jnp.arcsinh, x, out)


asinh = arcsinh


def arctan(x: DNDarray, out=None) -> DNDarray:
    """Element-wise inverse tangent (reference ``trigonometrics.py:162``)."""
    return _operations._local_op(jnp.arctan, x, out)


atan = arctan


def arctanh(x: DNDarray, out=None) -> DNDarray:
    """Element-wise inverse hyperbolic tangent (reference ``trigonometrics.py:230``)."""
    return _operations._local_op(jnp.arctanh, x, out)


atanh = arctanh


def hypot(t1, t2) -> DNDarray:
    """Element-wise ``sqrt(t1**2 + t2**2)`` (NumPy-parity extra; the
    reference has no hypot)."""
    return _operations._binary_op(jnp.hypot, t1, t2)


def arctan2(x1, x2) -> DNDarray:
    """Element-wise two-argument arctangent (reference ``:200``)."""
    return _operations._binary_op(jnp.arctan2, x1, x2)


atan2 = arctan2


def cos(x: DNDarray, out=None) -> DNDarray:
    """Element-wise cosine (reference ``trigonometrics.py:256``)."""
    return _operations._local_op(jnp.cos, x, out)


def cosh(x: DNDarray, out=None) -> DNDarray:
    """Element-wise hyperbolic cosine (reference ``trigonometrics.py:282``)."""
    return _operations._local_op(jnp.cosh, x, out)


def deg2rad(x: DNDarray, out=None) -> DNDarray:
    """Element-wise degrees to radians (reference ``trigonometrics.py:310``)."""
    return _operations._local_op(jnp.deg2rad, x, out)


radians = deg2rad


def rad2deg(x: DNDarray, out=None) -> DNDarray:
    """Element-wise radians to degrees (reference ``trigonometrics.py:350``)."""
    return _operations._local_op(jnp.rad2deg, x, out)


degrees = rad2deg


def sin(x: DNDarray, out=None) -> DNDarray:
    """Element-wise sine (reference ``trigonometrics.py:390``)."""
    return _operations._local_op(jnp.sin, x, out)


def sinh(x: DNDarray, out=None) -> DNDarray:
    """Element-wise hyperbolic sine (reference ``trigonometrics.py:418``)."""
    return _operations._local_op(jnp.sinh, x, out)


def tan(x: DNDarray, out=None) -> DNDarray:
    """Element-wise tangent (reference ``trigonometrics.py:446``)."""
    return _operations._local_op(jnp.tan, x, out)


def tanh(x: DNDarray, out=None) -> DNDarray:
    """Element-wise hyperbolic tangent (reference ``trigonometrics.py:475``)."""
    return _operations._local_op(jnp.tanh, x, out)


def sinc(x: DNDarray, out=None) -> DNDarray:
    """Normalized sinc (``numpy.sinc``)."""
    return _operations._local_op(jnp.sinc, x, out)
