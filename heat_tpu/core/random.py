"""Parallel pseudo-random number generation (reference ``heat/core/random.py``).

The reference hand-implements a counter-based Threefry-2x32/2x64 generator in
torch integer ops (``random.py:55-200, 868-1040``) so that results are
identical regardless of process count. JAX's native PRNG *is* counter-based
Threefry — this is the one subsystem that maps more naturally to the TPU
stack than to the reference's (SURVEY.md §5). The global state here is a
``(seed, counter)`` pair mirroring the reference's
``seed``/``get_state``/``set_state`` API; every draw derives an independent
key via ``fold_in`` and generates the **global logical array** (sharded
directly on device via a jitted creator), so results are independent of the
mesh size — the same process-count invariance the reference engineers by
hand.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import devices, types
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "get_state",
    "normal",
    "permutation",
    "rand",
    "randint",
    "randn",
    "random",
    "random_integer",
    "random_sample",
    "randperm",
    "ranf",
    "sample",
    "seed",
    "set_state",
    "standard_normal",
    "uniform",
]

# global (seed, counter) state — parity with the reference's __seed/__counter
__seed: int = None  # type: ignore[assignment]
__counter: int = 0

# cache of jitted sharded generators keyed by (kind, shape, dtype, split, mesh, extras)
_GEN_CACHE: dict = {}


def seed(seed: Optional[int] = None) -> None:
    """Reset the RNG state (reference ``random.py:764``)."""
    global __seed, __counter
    if seed is None:
        seed = int(time.time() * 256) % (2**63)
    __seed = int(seed)
    __counter = 0


def get_state() -> Tuple[str, int, int, int, float]:
    """Return the RNG state tuple (reference ``random.py:203``)."""
    return ("Threefry", __seed, __counter, 0, 0.0)


def set_state(state: Tuple) -> None:
    """Restore an RNG state tuple (reference ``random.py:782``)."""
    global __seed, __counter
    if not isinstance(state, tuple) or len(state) not in (3, 5):
        raise ValueError("state needs to be a tuple with 3 or 5 entries")
    if state[0] != "Threefry":
        raise ValueError(f"algorithm must be 'Threefry', got {state[0]}")
    __seed = int(state[1])
    __counter = int(state[2])


def _next_key():
    """Derive the key for the next draw and advance the counter."""
    global __counter
    key_id = __counter
    __counter += 1
    return __seed, key_id


def _generate(kind, gshape, jdtype, split, comm, make, extras=()):
    """jit-compiled sharded generation: the global logical array is produced
    directly with the target sharding (no host materialization), padded to
    the canonical layout."""
    gshape = tuple(int(s) for s in gshape)
    cache_key = (kind, gshape, str(jdtype), split, comm.cache_key, extras)
    fn = _GEN_CACHE.get(cache_key)
    if fn is None:
        sharding = comm.sharding(len(gshape), split)

        def _go(seed_, fold):
            key = jax.random.fold_in(jax.random.key(seed_), fold)
            arr = make(key)
            if split is not None and len(gshape):
                padn = comm.padded_size(gshape[split]) - gshape[split]
                if padn:
                    cfg = [(0, padn if i == split else 0) for i in range(len(gshape))]
                    arr = jnp.pad(arr, cfg)
            return arr

        fn = jax.jit(_go, out_shardings=sharding)
        _GEN_CACHE[cache_key] = fn
    s, c = _next_key()
    return fn(s, c)


def _ensure_seeded():
    if __seed is None:
        seed()


def rand(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples (reference ``random.py:358``)."""
    _ensure_seeded()
    if len(d) == 1 and isinstance(d[0], (tuple, list)):
        d = tuple(d[0])
    gshape = sanitize_shape(d if d else (1,))
    if not d:
        gshape = ()
    dtype = types.canonical_heat_type(dtype)
    jdtype = dtype.jax_type()
    device = devices.sanitize_device(device)
    comm = sanitize_comm(comm)
    if split is not None and gshape:
        split = sanitize_axis(gshape, split)
    parray = _generate(
        "rand", gshape, jdtype, split, comm, lambda key: jax.random.uniform(key, gshape, jdtype)
    )
    return DNDarray(parray, gshape, dtype, split if gshape else None, device, comm)


def random_sample(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0,1) over a shape tuple (reference ``random.py:640``)."""
    if shape is None:
        shape = (1,)
    return rand(*sanitize_shape(shape), dtype=dtype, split=split, device=device, comm=comm)


random = random_sample
ranf = random_sample
sample = random_sample


def randint(
    low, high=None, size=None, dtype=None, split=None, device=None, comm=None
) -> DNDarray:
    """Uniform random integers in [low, high) (reference ``random.py:473``)."""
    _ensure_seeded()
    if high is None:
        low, high = 0, low
    if size is None:
        size = ()
    if isinstance(size, (int, np.integer)):
        size = (int(size),)
    size = sanitize_shape(size)
    if dtype is None:
        dtype = types.int64 if jax.config.jax_enable_x64 else types.int32
    dtype = types.canonical_heat_type(dtype)
    if not issubclass(dtype, types.integer):
        raise ValueError(f"Unsupported dtype for randint: {dtype}")
    jdtype = dtype.jax_type()
    if low >= high:
        raise ValueError(f"low >= high: {low}, {high}")
    device = devices.sanitize_device(device)
    comm = sanitize_comm(comm)
    if split is not None and size:
        split = sanitize_axis(size, split)
    parray = _generate(
        ("randint", int(low), int(high)),
        size,
        jdtype,
        split if size else None,
        comm,
        lambda key: jax.random.randint(key, size, int(low), int(high), jdtype),
    )
    return DNDarray(parray, size, dtype, split if size else None, device, comm)


random_integer = randint


def randn(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard-normal samples (reference ``random.py:557``; the reference
    converts uniforms with the Kundu transform ``:248`` — JAX draws normals
    natively)."""
    _ensure_seeded()
    if len(d) == 1 and isinstance(d[0], (tuple, list)):
        d = tuple(d[0])
    gshape = sanitize_shape(d if d else (1,))
    dtype = types.canonical_heat_type(dtype)
    jdtype = dtype.jax_type()
    device = devices.sanitize_device(device)
    comm = sanitize_comm(comm)
    if split is not None:
        split = sanitize_axis(gshape, split)
    parray = _generate(
        "randn", gshape, jdtype, split, comm, lambda key: jax.random.normal(key, gshape, jdtype)
    )
    return DNDarray(parray, gshape, dtype, split, device, comm)


def standard_normal(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard normal over a shape tuple (reference ``random.py:700``)."""
    if shape is None:
        shape = (1,)
    return randn(*sanitize_shape(shape), dtype=dtype, split=split, device=device, comm=comm)


def normal(mean=0.0, std=1.0, shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Normal(mean, std) samples (reference ``random.py:290``)."""
    x = standard_normal(shape, dtype=dtype, split=split, device=device, comm=comm)
    from . import arithmetics

    return arithmetics.add(arithmetics.mul(x, std), mean)


def uniform(low=0.0, high=1.0, size=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [low, high) samples (reference ``random.py:820``)."""
    if size is None:
        size = (1,)
    x = random_sample(size, dtype=dtype, split=split, device=device, comm=comm)
    from . import arithmetics

    return arithmetics.add(arithmetics.mul(x, high - low), low)


def randperm(n: int, dtype=types.int64, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation of ``arange(n)`` (reference ``random.py:744``)."""
    _ensure_seeded()
    dtype = types.canonical_heat_type(dtype)
    jdtype = dtype.jax_type()
    device = devices.sanitize_device(device)
    comm = sanitize_comm(comm)
    gshape = (int(n),)
    if split is not None:
        split = sanitize_axis(gshape, split)
    parray = _generate(
        "randperm",
        gshape,
        jdtype,
        split,
        comm,
        lambda key: jax.random.permutation(key, int(n)).astype(jdtype),
    )
    return DNDarray(parray, gshape, dtype, split, device, comm)


def permutation(x, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation of an int range or shuffle of an array's first axis
    (reference ``random.py:203``)."""
    _ensure_seeded()
    if isinstance(x, (int, np.integer)):
        return randperm(int(x), split=split, device=device, comm=comm)
    if not isinstance(x, DNDarray):
        from . import factories

        x = factories.array(x, split=split, device=device, comm=comm)
    n = x.shape[0]
    perm = randperm(n, split=None, comm=x.comm)
    if x.split is not None and x.comm.size > 1 and n > 0:
        # same permutation stream, gather-free application: split-0 rows go
        # through the ring-gather getitem; other splits row-select locally
        idx = np.asarray(perm.larray)
        return x[idx]
    logical = x._logical()[perm._logical()]
    return DNDarray.from_logical(logical, x.split, x.device, x.comm, dtype=x.dtype)
